#!/usr/bin/env bash
# CI entry point: one command a reviewer can run.  Mirrors the
# reference's workflow scope (fmt/test matrix, .github/workflows/ci.yml
# there) with this repo's equivalents: the full pytest suite (hermetic,
# virtual 8-device CPU mesh), the native tier built and self-checked
# under ASan and TSan, a bounded CPU bench smoke, and config lint over
# the in-repo configs.
set -euo pipefail
cd "$(dirname "$0")"

echo "== flowcheck (static analysis: trace-safety, thread discipline, =="
echo "==   byte-identity, exceptions, keys, metrics, locks, events,   =="
echo "==            fault-site coverage, thread/fd lifecycle)         =="
# pure-ast, no JAX import: fails on any non-baselined FC01-FC10
# finding.  --expect-rules pins the registry size (a rule that fails
# to register would otherwise pass as "no findings"); --check fails on
# stale baseline tombstones.  Wall time is printed on stderr; the
# full-tree scan is bounded at 15s (it measures ~5s here) so the gate
# can never quietly eat the CI budget.
timeout 15 python -m flowgger_tpu.analysis --format text --check --expect-rules 10 .

# SARIF surface: emit the same run as SARIF and shape-check it, then
# prove --validate-sarif fast-fails (exit 2) on a malformed document.
python -m flowgger_tpu.analysis --format text --sarif-out /tmp/flowcheck.sarif . >/dev/null
python -m flowgger_tpu.analysis --validate-sarif /tmp/flowcheck.sarif
echo '{"version": "9.9.9", "runs": []}' > /tmp/flowcheck-bad.sarif
if python -m flowgger_tpu.analysis --validate-sarif /tmp/flowcheck-bad.sarif 2>/dev/null; then
  echo "flowcheck: --validate-sarif accepted a malformed SARIF doc" >&2; exit 1
else
  rc=$?; [ "$rc" -eq 2 ] || { echo "flowcheck: expected exit 2 on malformed SARIF, got $rc" >&2; exit 1; }
fi
rm -f /tmp/flowcheck.sarif /tmp/flowcheck-bad.sarif

echo "== BENCH series trajectory check (tools/bench_trend.py) =="
# every BENCH_r*.json must parse into the trajectory table (the r06
# metadata stub is allowed); a malformed new BENCH entry fails fast
python tools/bench_trend.py --check

echo "== overlap-executor + fused-route + zero-JIT-boot smoke (<630s) =="
# asserts the in-flight submit/fetch window sustains >= the serial e2e,
# 2-lane dispatch sustains >= 0.92x the 1-lane executor (jitter
# tolerance for small hosts; the ratio itself is in the JSON line),
# the jsonl/dns block routes are byte-identical to the scalar pipeline
# at or above the backend-tiered throughput floor (new_formats line),
# the fused decode→encode routes emit byte-identical output with
# fetched bytes/row under emitted on every route (fused_routes line),
# AND an artifact-booted cold subprocess performs zero fresh kernel
# compiles with scalar-oracle-identical bytes per framing while the
# TPU fused-route export round-trips build-only (aot_smoke line),
# AND the device-resident framing tier emits byte-identical output on
# line/nul/syslen with span-metadata fetch bytes/row under emitted
# (framing_smoke line; throughput gate backend-tiered),
# AND the Pallas tier passes its three gates: stage-1 [N,L] pass count
# reduced >=5x vs the jnp screen, interpret span kernels byte-identical
# to the host scans, and the AOT pallas family round-tripping cpu+tpu
# with an aot_hits dispatch (pallas_smoke line, backend cpu-interpret)
JAX_PLATFORMS=cpu timeout 900 python bench.py --smoke

echo "== python test suite (virtual 8-device CPU mesh) =="
# slow-marked tests are excluded here (pytest.ini tier-1 contract);
# all of them still run in CI via dedicated capped steps below: the
# lanes cold-process cache test in the 2-device step, the device
# encode-output differentials in their own step, and the fused deep
# fuzz in its step (running the in-suite wrapper here would execute
# the same ~10-minute fuzz twice per CI pass)
python -m pytest tests/ -q -m "not faults and not slow"

echo "== lane-dispatch suite (forced 2-device CPU) =="
# real multi-lane placement/ordering for tests/test_lanes.py only; the
# rest of the suite keeps its usual device setup so timings stay stable
XLA_FLAGS=--xla_force_host_platform_device_count=2 JAX_PLATFORMS=cpu \
  python -m pytest tests/test_lanes.py -q -m "not faults"

echo "== zero-JIT boot: AOT cold-boot zero-compile acceptance (slow) =="
# builds + warms a CPU-platform artifact set, then boots a COLD
# subprocess against input.tpu_aot_dir: compile_cache_misses must be 0
# with aot_hits > 0 and output byte-identical to a JIT-booted process.
# TPU-platform export is build-only on this host (no TPU to execute
# it); its acceptance — serialize + deserialize + manifest-validation
# round trip for all four fused routes — runs in the main suite
# (test_aot.py::test_tpu_fused_routes_serialize_and_roundtrip).
# outer cap must dominate the test's own 600s-per-subprocess budgets
# (3 subprocesses) so a slow run fails inside pytest with diagnostics
# instead of a bare SIGKILL; measured ~20s on the 2-core container
JAX_PLATFORMS=cpu timeout 1900 python -m pytest tests/test_aot.py -q -m "slow"

echo "== fleet federation: multi-process acceptance (slow) =="
# a real 2-host localhost fleet (jax.distributed + fleet heartbeats):
# the harness SIGKILLs host 1 mid-stream (host_kill fault site) and the
# survivor must emit byte-identical output while the victim walks
# suspect -> draining -> departed, observable via the health endpoint.
# subprocess budgets dominate the cap (PR 8 lesson): 2 workers with
# 240s communicate timeouts inside; measured ~25s on the 2-core
# container
JAX_PLATFORMS=cpu timeout 600 python -m pytest tests/test_fleet_acceptance.py -q -m "slow"

echo "== self-healing fleet: chaos drills + failover acceptance (slow) =="
# (1) the slow-marked pytest half: the 3-process chaos acceptance
# (coordinator SIGKILL mid-stream; survivors byte-identical, fallback
# rendezvous agreed within the ladder bound, new joiner admitted) —
# the non-slow failover/roster/rebalance tests already ran in the main
# suite step.  (2) a bounded tools/chaos.py loop on a 2-process
# localhost fleet cycling every fault site (coordinator_kill,
# host_kill, peer_partition, roster_corrupt); the harness asserts
# reconvergence + clean-prefix outputs after every drill.  measured
# ~20s total on the 2-core container
JAX_PLATFORMS=cpu timeout 600 python -m pytest tests/test_fleet_failover.py -q -m "slow"
timeout 600 python tools/chaos.py --hosts 2 --events 4 --window 60

echo "== zero-loss ingestion: WAL spill chaos drill (kill mid-spill) =="
# (1) the slow-marked pytest half: kill-mid-spill acceptance through
# the drill harness; (2) the drill itself — SIGKILL a spilling worker
# mid-record, SIGKILL a replaying worker mid-replay, then replay to
# completion: every WAL-owed line delivered (clean-prefix accounting),
# nothing foreign, no line more than twice (at-least-once across
# process restarts).  measured ~10s per run on the 2-core container
JAX_PLATFORMS=cpu timeout 600 python -m pytest tests/test_durability.py -q -m "slow"
timeout 300 python tools/chaos.py --durability --json

echo "== control loop: burn-driven admission, share feedback, autoscale =="
# (1) the unit suite: AIMD hysteresis/clamps (fake clock), in-place
# bucket re-rating, frozen-at-last-applied (stop + control_freeze),
# weight emitter renders/runtime pushes, steering-proxy byte identity
# per framing, /fleetz control section, and the disarmed-inertness
# contract (no [control] table -> no threads, no hot-path cost);
# (2) the closed-loop drills: a flooding tenant burn-tightened within
# the reaction bound while a calm tenant stays byte-identical with a
# green SLO, and a degrading host's advertised share decaying at its
# peers BEFORE its decode breaker trips.  measured ~8s total
JAX_PLATFORMS=cpu timeout 600 python -m pytest tests/test_control.py -q -m "not faults"
timeout 300 python tools/chaos.py --control --json

echo "== multi-tenant serving suite (admission, fair queue, templates) =="
JAX_PLATFORMS=cpu python -m pytest tests/test_tenancy.py -q -m "not faults"

echo "== observability suite (spans, event journal, exposition) =="
# flight recorder: strict Prometheus exposition-format parse of
# GET /metrics, one typed journal event per degradation rung, trace
# ring -> Chrome trace JSON (tools/trace_dump.py), the reporter/
# final_flush write-race fix, and the SIGUSR2 / POST /profile toggle
JAX_PLATFORMS=cpu timeout 600 python -m pytest tests/test_obs.py tests/test_metrics.py -q -m "not faults"

echo "== observability plane: SLO engine + fleet aggregation (obs-fleet) =="
# SLO unit suite (multi-window burn rates, burn/recover events, sink
# rotation, BENCH-seeded regression sentinel) + the multi-host /fleetz
# tests: merged counters/histograms (pooled-sample quantiles), the
# rank-tagged event union, dead-host staleness marking, fleetctl top
# exit codes, and trace_dump --fleet process lanes.  The host_kill
# staleness drill (faults-marked, subprocess) runs in the
# fault-injection step below
JAX_PLATFORMS=cpu timeout 600 python -m pytest tests/test_slo.py tests/test_fleetz.py -q -m "not faults"

echo "== new-format decode subsystems (jsonl_tpu / dns_tpu, slow half) =="
# the non-slow differential/framing/auto-leg/AOT tests already ran in
# the main suite step above — this step adds ONLY their slow-marked
# half (1/2-lane identity, rescue tier, and the filtered deep fuzz
# over both new routes: randomized lanes × framings vs the oracles)
JAX_PLATFORMS=cpu timeout 1200 python -m pytest tests/test_tpu_jsonl.py tests/test_tpu_dns.py tests/test_cross_route_fuzz.py -q -m "slow and not faults"

echo "== device-resident framing (differential vs host splitters) =="
# span kernels + raw-session ingest vs the host splitters across
# line/nul/syslen x adversarial chunk boundaries x 1/2 lanes, the
# decline/breaker ladder, and the AOT framing family round trip
JAX_PLATFORMS=cpu timeout 600 python -m pytest tests/test_framing.py -q -m "not faults"

echo "== framing deep fuzz (random chunk splits vs host splitters) =="
# random chunk sizes that split records mid-byte (incl. mid-syslen-
# prefix and delimiters exactly on chunk edges): device spans == host
# splitter output, e2e bytes identical across 1/2 lanes
timeout 900 python tools/deep_fuzz.py --routes framing 1 4

echo "== Pallas kernels (interpret-mode differentials, slow half) =="
# the non-slow Pallas half (span kernels vs host scans, the
# decline/hysteresis ladders, config validation) already ran in the
# main suite step — this step adds the slow-marked half: the
# compiled-NFA classifier and decode differentials vs the jnp screen,
# raw-ingest byte identity, the fused framing→decode entries vs the
# split path, the line/nul/syslen × rfc5424/jsonl × 1/2-lane e2e
# matrix, and the AOT pallas-family round trip with aot_hits asserted.
# Interpret-mode compiles dominate the wall time (each geometry
# compiles once, then differentials are cheap)
JAX_PLATFORMS=cpu timeout 1800 python -m pytest tests/test_pallas_kernels.py -q -m "slow and not faults"

echo "== Pallas deep fuzz (interpret kernels vs host scans + jnp screen) =="
# randomized regions (partial tails, bad prefixes) vs the host scalar
# scans, randomized JSON rows (escape runs straddling ESC_RUN_CAP) vs
# the jnp lax/sum screen, and e2e chunk plans splitting records
# mid-byte and mid-syslen-prefix with tpu_pallas on vs the all-host
# pipeline; the larger-budget version is
# `python tools/deep_fuzz.py --routes pallas <seed> <trials>`
timeout 900 python tools/deep_fuzz.py --routes pallas 1 2

echo "== fault-injection suite (robustness degradation paths) =="
JAX_PLATFORMS=cpu python -m pytest tests/ -q -m "faults and not slow"

echo "== device encode outputs (rfc5424/ltsv/capnp legs, differential) =="
# the PR 19 N×M output legs: split kernels (device_rfc5424_out /
# device_ltsv_out / device_capnp) and their fused registrations vs the
# scalar oracles across line/nul/syslen, fallback splicing, per-route
# gauge denominators, and 1/2-lane BatchHandler byte identity.  The
# file is slow-marked (excluded from the tier-1 pytest step above) so
# its eager differentials don't double the main suite's wall time;
# measured ~2min on the 2-core container
JAX_PLATFORMS=cpu timeout 600 python -m pytest tests/test_device_encode_out.py -q -m "not faults"

echo "== fused-route deep fuzz (slow: eager route matrix vs scalar oracle) =="
# the fused route matrix — every decode leg -> GELF plus the PR 19
# output legs (rfc5424->rfc5424/ltsv/capnp, rfc3164->rfc5424) — over
# randomized framing vs its scalar oracle, run eagerly so it holds
# even where this host's XLA cannot compile the fused programs; the
# larger-budget version is
# `python tools/deep_fuzz.py --routes fused <seed> <trials>`
JAX_PLATFORMS=cpu timeout 900 python tools/deep_fuzz.py --routes fused 1 2

echo "== native build =="
make -C native -s

echo "== native sanitizer self-checks =="
make -C native -s asan-check
make -C native -s tsan-check

echo "== config lint =="
python -m flowgger_tpu --check flowgger.toml
python -m flowgger_tpu --check examples/multihost-dp.toml
python -m flowgger_tpu --check examples/tenants.toml
python -m flowgger_tpu --check examples/jsonl.toml

echo "== bench smoke (CPU backend, bounded) =="
JAX_PLATFORMS=cpu FLOWGGER_BENCH_SMOKE=1 timeout 600 python bench.py

echo "CI OK"
