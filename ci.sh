#!/usr/bin/env bash
# CI entry point: one command a reviewer can run.  Mirrors the
# reference's workflow scope (fmt/test matrix, .github/workflows/ci.yml
# there) with this repo's equivalents: the full pytest suite (hermetic,
# virtual 8-device CPU mesh), the native tier built and self-checked
# under ASan and TSan, a bounded CPU bench smoke, and config lint over
# the in-repo configs.
set -euo pipefail
cd "$(dirname "$0")"

echo "== flowcheck (static analysis: trace-safety, thread discipline, =="
echo "==            byte-identity contracts, exception hygiene, keys) =="
# pure-ast, no JAX import: fails on any non-baselined FC01-FC05 finding
python -m flowgger_tpu.analysis --format text .

echo "== overlap-executor smoke (forced 4-device CPU, <120s) =="
# asserts the in-flight submit/fetch window sustains >= the serial e2e
# AND 2-lane dispatch sustains >= 0.92x the 1-lane executor (jitter
# tolerance for small hosts; the ratio itself is in the JSON line)
JAX_PLATFORMS=cpu timeout 240 python bench.py --smoke

echo "== python test suite (virtual 8-device CPU mesh) =="
python -m pytest tests/ -q -m "not faults"

echo "== lane-dispatch suite (forced 2-device CPU) =="
# real multi-lane placement/ordering for tests/test_lanes.py only; the
# rest of the suite keeps its usual device setup so timings stay stable
XLA_FLAGS=--xla_force_host_platform_device_count=2 JAX_PLATFORMS=cpu \
  python -m pytest tests/test_lanes.py -q -m "not faults"

echo "== multi-tenant serving suite (admission, fair queue, templates) =="
JAX_PLATFORMS=cpu python -m pytest tests/test_tenancy.py -q -m "not faults"

echo "== fault-injection suite (robustness degradation paths) =="
JAX_PLATFORMS=cpu python -m pytest tests/ -q -m "faults and not slow"

echo "== native build =="
make -C native -s

echo "== native sanitizer self-checks =="
make -C native -s asan-check
make -C native -s tsan-check

echo "== config lint =="
python -m flowgger_tpu --check flowgger.toml
python -m flowgger_tpu --check examples/multihost-dp.toml
python -m flowgger_tpu --check examples/tenants.toml

echo "== bench smoke (CPU backend, bounded) =="
JAX_PLATFORMS=cpu FLOWGGER_BENCH_SMOKE=1 timeout 600 python bench.py

echo "CI OK"
