// Native host tier: the hot host-side paths of the batched pipeline.
//
// The reference's host runtime is native (Rust) end to end; here the
// host-side work that sits on the TPU ingest path — newline framing of
// raw chunks and packing framed lines into the dense [N, max_len] batch
// the kernels consume — is C++ with simple pthread fan-out, exposed via
// a C ABI for ctypes (flowgger_tpu/native.py).  Python/numpy remains the
// fallback when the library isn't built.
//
// Parity notes: split semantics match BufRead::lines (line_splitter.rs:
// 17 — \n framing, one trailing \r stripped); the packer implements the
// same clip-and-zero-pad contract as tpu/pack.py pack_lines_2d.

#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <algorithm>
#include <functional>
#include <thread>
#include <vector>

extern "C" {

// Scan a raw chunk for newline-framed records.
// Writes line start offsets and (CR-stripped) lengths; returns the number
// of complete lines.  *carry_start receives the offset of the trailing
// partial line (== size when the chunk ends exactly on a newline).
int64_t fg_split_lines(const uint8_t* buf, int64_t size,
                       int32_t* starts, int32_t* lens, int64_t cap,
                       int strip_cr, int64_t* carry_start) {
    int64_t n = 0;
    int64_t pos = 0;
    while (pos < size && n < cap) {
        const void* nl = memchr(buf + pos, '\n', (size_t)(size - pos));
        if (nl == nullptr) break;
        int64_t end = (const uint8_t*)nl - buf;
        int64_t len = end - pos;
        if (strip_cr && len > 0 && buf[end - 1] == '\r') len -= 1;
        starts[n] = (int32_t)pos;
        lens[n] = (int32_t)len;
        n += 1;
        pos = end + 1;
    }
    *carry_start = pos;
    return n;
}

// Scan a buffered stream region for RFC5425-style octet-counted frames:
// ASCII decimal length, one space, then exactly that many bytes
// (syslen_splitter.rs:10-69 semantics, batched).  Returns the number of
// complete frames; *consumed receives the offset just past the last
// complete frame (the caller keeps the remainder as carry); *err is set
// to 1 when a malformed length prefix is found (non-digit before the
// space) — framing past that point is undefined, matching the
// reference's "Can't read message's length" abort.
int64_t fg_split_syslen(const uint8_t* buf, int64_t size,
                        int32_t* starts, int32_t* lens, int64_t cap,
                        int64_t* consumed, int* err) {
    int64_t n = 0;
    int64_t pos = 0;
    *err = 0;
    while (pos < size && n < cap) {
        int64_t p = pos;
        int64_t val = 0;
        int digits = 0;
        while (p < size && buf[p] >= '0' && buf[p] <= '9') {
            val = val * 10 + (buf[p] - '0');
            if (val > INT32_MAX) { *err = 1; goto done; }
            p++; digits++;
        }
        if (p >= size) break;              // prefix may continue next read
        if (buf[p] != ' ' || digits == 0) { *err = 1; break; }
        p++;
        if (p + val > size) break;         // frame incomplete: carry
        starts[n] = (int32_t)p;
        lens[n] = (int32_t)val;
        n++;
        pos = p + val;
    }
done:
    *consumed = pos;
    return n;
}

// Pack n lines (described by starts/lens into chunk) into a dense
// row-major [n_rows, max_len] uint8 batch, zero-padded; lens_out receives
// the clipped lengths.  Rows beyond n are left untouched (caller zeroes).
void fg_pack_lines(const uint8_t* chunk, int64_t chunk_size,
                   const int32_t* starts, const int32_t* lens, int64_t n,
                   int32_t max_len, uint8_t* out, int32_t* lens_out,
                   int n_threads) {
    if (n_threads < 1) n_threads = 1;
    auto work = [&](int64_t lo, int64_t hi) {
        for (int64_t i = lo; i < hi; i++) {
            uint8_t* row = out + (size_t)i * (size_t)max_len;
            int64_t start = starts[i];
            int64_t len = lens[i];
            if (len > max_len) len = max_len;
            if (start < 0 || start + len > chunk_size) len = 0;
            if (len > 0) memcpy(row, chunk + start, (size_t)len);
            if (len < max_len) memset(row + len, 0, (size_t)(max_len - len));
            lens_out[i] = (int32_t)len;
        }
    };
    if (n_threads == 1 || n < 4096) {
        work(0, n);
        return;
    }
    std::vector<std::thread> threads;
    int64_t per = (n + n_threads - 1) / n_threads;
    for (int t = 0; t < n_threads; t++) {
        int64_t lo = t * per;
        int64_t hi = std::min<int64_t>(lo + per, n);
        if (lo >= hi) break;
        threads.emplace_back(work, lo, hi);
    }
    for (auto& th : threads) th.join();
}

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli) — required by the Kafka record-batch v2 format.
// Table-driven, slicing-by-4.
// ---------------------------------------------------------------------------

namespace {

struct Crc32cTables {
    uint32_t t[4][256];
    Crc32cTables() {
        const uint32_t poly = 0x82F63B78u;  // reflected 0x1EDC6F41
        for (uint32_t i = 0; i < 256; i++) {
            uint32_t c = i;
            for (int k = 0; k < 8; k++)
                c = (c & 1) ? (c >> 1) ^ poly : c >> 1;
            t[0][i] = c;
        }
        for (uint32_t i = 0; i < 256; i++) {
            t[1][i] = (t[0][i] >> 8) ^ t[0][t[0][i] & 0xFF];
            t[2][i] = (t[1][i] >> 8) ^ t[0][t[1][i] & 0xFF];
            t[3][i] = (t[2][i] >> 8) ^ t[0][t[2][i] & 0xFF];
        }
    }
};
const Crc32cTables kCrc;

}  // namespace

extern "C" {

uint32_t fg_crc32c(const uint8_t* data, int64_t len, uint32_t init) {
    uint32_t c = ~init;
    int64_t i = 0;
    for (; i + 4 <= len; i += 4) {
        c ^= (uint32_t)data[i] | ((uint32_t)data[i + 1] << 8)
             | ((uint32_t)data[i + 2] << 16) | ((uint32_t)data[i + 3] << 24);
        c = kCrc.t[3][c & 0xFF] ^ kCrc.t[2][(c >> 8) & 0xFF]
            ^ kCrc.t[1][(c >> 16) & 0xFF] ^ kCrc.t[0][c >> 24];
    }
    for (; i < len; i++)
        c = (c >> 8) ^ kCrc.t[0][(c ^ data[i]) & 0xFF];
    return ~c;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Snappy block format (raw, no framing) — the compression codec Kafka
// record batches use for attributes=2.  Greedy 64KB-block hash matching
// per the public format description; decompressor handles every element
// type.
// ---------------------------------------------------------------------------

namespace {

inline int put_varint(uint8_t* dst, uint64_t v) {
    int n = 0;
    while (v >= 0x80) {
        dst[n++] = (uint8_t)(v | 0x80);
        v >>= 7;
    }
    dst[n++] = (uint8_t)v;
    return n;
}

inline uint8_t* emit_literal(uint8_t* op, const uint8_t* s, int64_t len) {
    int64_t n = len - 1;
    if (n < 60) {
        *op++ = (uint8_t)(n << 2);
    } else if (n < 256) {
        *op++ = (uint8_t)(60 << 2);
        *op++ = (uint8_t)n;
    } else if (n < 65536) {
        *op++ = (uint8_t)(61 << 2);
        *op++ = (uint8_t)n;
        *op++ = (uint8_t)(n >> 8);
    } else if (n < (1 << 24)) {
        *op++ = (uint8_t)(62 << 2);
        *op++ = (uint8_t)n;
        *op++ = (uint8_t)(n >> 8);
        *op++ = (uint8_t)(n >> 16);
    } else {
        *op++ = (uint8_t)(63 << 2);
        *op++ = (uint8_t)n;
        *op++ = (uint8_t)(n >> 8);
        *op++ = (uint8_t)(n >> 16);
        *op++ = (uint8_t)(n >> 24);
    }
    memcpy(op, s, (size_t)len);
    return op + len;
}

inline uint8_t* emit_copy(uint8_t* op, int64_t offset, int64_t len) {
    // len 4..11 with offset < 2048: 1-byte-offset form
    while (len >= 68) {
        *op++ = (uint8_t)((63 << 2) | 2);  // copy-2, len 64
        *op++ = (uint8_t)offset;
        *op++ = (uint8_t)(offset >> 8);
        len -= 64;
    }
    if (len > 64) {
        *op++ = (uint8_t)((59 << 2) | 2);  // len 60
        *op++ = (uint8_t)offset;
        *op++ = (uint8_t)(offset >> 8);
        len -= 60;
    }
    if (len >= 12 || offset >= 2048) {
        *op++ = (uint8_t)(((len - 1) << 2) | 2);
        *op++ = (uint8_t)offset;
        *op++ = (uint8_t)(offset >> 8);
    } else {
        *op++ = (uint8_t)(((offset >> 8) << 5) | ((len - 4) << 2) | 1);
        *op++ = (uint8_t)offset;
    }
    return op;
}

inline uint32_t snappy_hash(uint32_t v) { return (v * 0x1E35A7BDu) >> 18; }

}  // namespace

extern "C" {

int64_t fg_snappy_max_compressed(int64_t n) {
    return 32 + n + n / 6;
}

// Compress src into dst (sized >= fg_snappy_max_compressed); returns the
// compressed size.
int64_t fg_snappy_compress(const uint8_t* src, int64_t n, uint8_t* dst) {
    uint8_t* op = dst;
    op += put_varint(op, (uint64_t)n);
    const int64_t kBlock = 1 << 16;
    std::vector<uint16_t> table(1 << 14);
    for (int64_t base = 0; base < n; base += kBlock) {
        int64_t blen = std::min(kBlock, n - base);
        const uint8_t* p = src + base;
        std::fill(table.begin(), table.end(), 0);
        int64_t ip = 0;
        int64_t lit_start = 0;
        while (ip + 4 <= blen) {
            uint32_t v;
            memcpy(&v, p + ip, 4);
            uint32_t h = snappy_hash(v);
            int64_t cand = table[h];
            table[h] = (uint16_t)ip;
            uint32_t cv;
            memcpy(&cv, p + cand, 4);
            if (cand < ip && cv == v) {
                // extend the match
                int64_t len = 4;
                while (ip + len < blen && p[cand + len] == p[ip + len]
                       && len < (int64_t)0xFFFF)
                    len++;
                if (ip > lit_start)
                    op = emit_literal(op, p + lit_start, ip - lit_start);
                op = emit_copy(op, ip - cand, len);
                ip += len;
                lit_start = ip;
            } else {
                ip++;
            }
        }
        if (blen > lit_start)
            op = emit_literal(op, p + lit_start, blen - lit_start);
    }
    return op - dst;
}

// Decompress src into dst (sized to the preamble's uncompressed length).
// Returns the decompressed size, or -1 on malformed input.
int64_t fg_snappy_decompress(const uint8_t* src, int64_t n,
                             uint8_t* dst, int64_t dst_cap) {
    int64_t ip = 0;
    uint64_t ulen = 0;
    int shift = 0;
    while (ip < n) {
        uint8_t b = src[ip++];
        ulen |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) break;
        shift += 7;
        if (shift > 35) return -1;
    }
    if ((int64_t)ulen > dst_cap) return -1;
    int64_t op = 0;
    while (ip < n) {
        uint8_t tag = src[ip++];
        int type = tag & 3;
        if (type == 0) {  // literal
            int64_t len = (tag >> 2) + 1;
            if (len > 60) {
                int nb = (int)len - 60;
                if (ip + nb > n) return -1;
                len = 0;
                for (int k = 0; k < nb; k++)
                    len |= (int64_t)src[ip + k] << (8 * k);
                len += 1;
                ip += nb;
            }
            if (ip + len > n || op + len > (int64_t)ulen) return -1;
            memcpy(dst + op, src + ip, (size_t)len);
            ip += len;
            op += len;
            continue;
        }
        int64_t len, offset;
        if (type == 1) {
            if (ip >= n) return -1;
            len = ((tag >> 2) & 7) + 4;
            offset = ((int64_t)(tag >> 5) << 8) | src[ip++];
        } else if (type == 2) {
            if (ip + 2 > n) return -1;
            len = (tag >> 2) + 1;
            offset = (int64_t)src[ip] | ((int64_t)src[ip + 1] << 8);
            ip += 2;
        } else {
            if (ip + 4 > n) return -1;
            len = (tag >> 2) + 1;
            offset = (int64_t)src[ip] | ((int64_t)src[ip + 1] << 8)
                     | ((int64_t)src[ip + 2] << 16)
                     | ((int64_t)src[ip + 3] << 24);
            ip += 4;
        }
        if (offset == 0 || offset > op || op + len > (int64_t)ulen) return -1;
        // overlapping copies are byte-serial by definition
        for (int64_t k = 0; k < len; k++) {
            dst[op + k] = dst[op + k - offset];
        }
        op += len;
    }
    return op == (int64_t)ulen ? op : -1;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Columnar RFC5424 -> GELF row assembly (the encode hot loop of
// gelf_encoder.rs:51-116, batched): given the decode kernel's span
// tables, emit each row's GELF JSON bytes directly from the chunk.
// Two phases — fg_gelf_lens_v2 measures exact output lengths, the
// caller prefix-sums them, fg_gelf_write_v2 fills the buffer in
// parallel.  (v2: the escaped-SD-value flags changed the signature; the
// suffix keeps a stale prebuilt .so from being called with a shifted
// argument layout — loaders feature-test the symbol name.)
// JSON escaping matches json.encoder.encode_basestring (backslash,
// quote, \b \t \n \f \r shortcuts, \u00XX for other control bytes);
// differential tests in tests/test_encode_gelf_block.py pin the bytes
// against the scalar encoder.
// ---------------------------------------------------------------------------

namespace {

// rowmeta columns (int32, row-major [R, 17]); span offsets row-relative
enum {
    M_START = 0, M_HOST_S, M_HOST_E, M_APP_S, M_APP_E, M_PROC_S, M_PROC_E,
    M_MSG_A, M_TRIM_E, M_FULL_S, M_SEV, M_NSD, M_SID_S, M_SID_E,
    M_TS_OFF, M_TS_LEN, M_NPAIR, M_NCOL
};

struct EscTables {
    uint8_t width[256];
    char seq[256][8];
    EscTables() {
        for (int b = 0; b < 256; b++) {
            width[b] = 1;
            seq[b][0] = (char)b;
        }
        auto two = [&](int b, char c) {
            width[b] = 2; seq[b][0] = '\\'; seq[b][1] = c;
        };
        for (int b = 0; b < 0x20; b++) {
            width[b] = 6;
            snprintf(seq[b], 8, "\\u%04x", b);
        }
        two('\b', 'b'); two('\t', 't'); two('\n', 'n');
        two('\f', 'f'); two('\r', 'r'); two('"', '"'); two('\\', '\\');
    }
};
const EscTables kEsc;

inline int64_t esc_len(const uint8_t* s, int64_t len) {
    int64_t out = 0;
    for (int64_t i = 0; i < len; i++) out += kEsc.width[s[i]];
    return out;
}

inline uint8_t* esc_write(uint8_t* dst, const uint8_t* s, int64_t len) {
    for (int64_t i = 0; i < len; i++) {
        uint8_t w = kEsc.width[s[i]];
        if (w == 1) {
            *dst++ = s[i];
        } else {
            memcpy(dst, kEsc.seq[s[i]], w);
            dst += w;
        }
    }
    return dst;
}

// SD-escaped values: RFC5424 unescape (backslash before '"' '\\' ']'
// collapses; any other backslash is literal — rfc5424_decoder.rs:105-125
// semantics) composed with the JSON escape, in one walk.
inline int64_t esc_len_sd(const uint8_t* s, int64_t len) {
    int64_t out = 0;
    int64_t i = 0;
    while (i < len) {
        uint8_t b = s[i];
        if (b == '\\' && i + 1 < len) {
            uint8_t c = s[i + 1];
            if (c == '"' || c == '\\' || c == ']')
                out += kEsc.width[c];
            else
                out += kEsc.width[(uint8_t)'\\'] + kEsc.width[c];
            i += 2;
        } else {
            out += kEsc.width[b];
            i += 1;
        }
    }
    return out;
}

inline uint8_t* esc_write_sd(uint8_t* dst, const uint8_t* s, int64_t len) {
    auto put1 = [&](uint8_t b) {
        uint8_t w = kEsc.width[b];
        if (w == 1) {
            *dst++ = b;
        } else {
            memcpy(dst, kEsc.seq[b], w);
            dst += w;
        }
    };
    int64_t i = 0;
    while (i < len) {
        uint8_t b = s[i];
        if (b == '\\' && i + 1 < len) {
            uint8_t c = s[i + 1];
            if (!(c == '"' || c == '\\' || c == ']'))
                put1('\\');
            put1(c);
            i += 2;
        } else {
            put1(b);
            i += 1;
        }
    }
    return dst;
}

inline uint8_t* put(uint8_t* dst, const char* s, size_t len) {
    memcpy(dst, s, len);
    return dst + len;
}

#define LIT(dst, s) put(dst, s, sizeof(s) - 1)

const int kMaxPairs = 64;

// sorted pair order with exact dict semantics: stable sort by name
// bytes, then among equal names only the last (original order) survives
// (Python dict last-wins + sorted(keys)).  Returns count of emitted
// pairs; idx_out holds their original indices in emit order.
inline int sort_pairs(const uint8_t* chunk, int64_t base,
                      const int32_t* ns, const int32_t* ne, int p,
                      int* idx_out) {
    int idx[kMaxPairs];
    for (int i = 0; i < p; i++) idx[i] = i;
    // insertion sort (p is small), stable
    for (int i = 1; i < p; i++) {
        int cur = idx[i];
        const uint8_t* cs = chunk + base + ns[cur];
        int cl = ne[cur] - ns[cur];
        int j = i - 1;
        while (j >= 0) {
            const uint8_t* js = chunk + base + ns[idx[j]];
            int jl = ne[idx[j]] - ns[idx[j]];
            int c = memcmp(js, cs, (size_t)std::min(jl, cl));
            if (c < 0 || (c == 0 && jl <= cl)) break;
            idx[j + 1] = idx[j];
            j--;
        }
        idx[j + 1] = cur;
    }
    int out = 0;
    for (int i = 0; i < p; i++) {
        if (i + 1 < p) {  // name equal to the next entry? skip — the
            // sort is stable, so the run's last element carries the
            // last original occurrence (dict last-wins)
            int a = idx[i], b = idx[i + 1];
            int al = ne[a] - ns[a], bl = ne[b] - ns[b];
            if (al == bl &&
                memcmp(chunk + base + ns[a], chunk + base + ns[b],
                       (size_t)al) == 0)
                continue;
        }
        idx_out[out++] = idx[i];
    }
    return out;
}

inline int dec_digits(int64_t v) {
    int d = 1;
    while (v >= 10) { v /= 10; d++; }
    return d;
}

// syslen framing prefix "{body} ": the caller only knows the total
// framed length, so recover body = framed - digits(body) - 1 by
// scanning digit counts (unique fixpoint, dec_digits is monotonic)
inline uint8_t* put_syslen_prefix(uint8_t* dst, int64_t framed_len) {
    int64_t body = framed_len;
    for (int d = 1; d <= 10; d++) {
        int64_t cand = framed_len - d - 1;
        if (dec_digits(cand) == d) { body = cand; break; }
    }
    char buf[16];
    int nb = snprintf(buf, sizeof buf, "%lld ", (long long)body);
    return put(dst, buf, (size_t)nb);
}

struct GelfArgs {
    const uint8_t* chunk;
    const int32_t* meta;      // [R, M_NCOL]
    int64_t R;
    const int32_t* pns;       // [R, P] name/val spans, row-relative
    const int32_t* pne;
    const int32_t* pvs;
    const int32_t* pve;
    const int32_t* pesc;      // [R, P] value-needs-SD-unescape flags
    int32_t P;
    const uint8_t* ts_scratch;
    const uint8_t* suffix;
    int32_t suffix_len;
    int32_t syslen;
};

int64_t gelf_row_len(const GelfArgs& a, int64_t r) {
    const int32_t* m = a.meta + r * M_NCOL;
    const uint8_t* chunk = a.chunk;
    int64_t base = m[M_START];
    int64_t len = 0;
    int p = m[M_NPAIR];
    if (p > 0) {
        const int32_t* ns = a.pns + r * a.P;
        const int32_t* ne = a.pne + r * a.P;
        const int32_t* vs = a.pvs + r * a.P;
        const int32_t* ve = a.pve + r * a.P;
        const int32_t* pe = a.pesc + r * a.P;
        int order[kMaxPairs];
        int cnt = sort_pairs(chunk, base, ns, ne, p, order);
        for (int k = 0; k < cnt; k++) {
            int i = order[k];
            len += 2 + 3 + 2;  // "_  ":"  ",
            len += esc_len(chunk + base + ns[i], ne[i] - ns[i]);
            len += pe[i]
                ? esc_len_sd(chunk + base + vs[i], ve[i] - vs[i])
                : esc_len(chunk + base + vs[i], ve[i] - vs[i]);
        }
    }
    len += 1;                                   // {
    len += sizeof("\"application_name\":\"") - 1;
    len += esc_len(chunk + base + m[M_APP_S], m[M_APP_E] - m[M_APP_S]);
    len += sizeof("\",\"full_message\":\"") - 1;
    len += esc_len(chunk + base + m[M_FULL_S], m[M_TRIM_E] - m[M_FULL_S]);
    len += sizeof("\",\"host\":\"") - 1;
    int64_t hl = m[M_HOST_E] - m[M_HOST_S];
    len += hl ? esc_len(chunk + base + m[M_HOST_S], hl)
              : (int64_t)(sizeof("unknown") - 1);
    len += sizeof("\",\"level\":") - 1 + 1;     // single severity digit
    len += sizeof(",\"process_id\":\"") - 1;
    len += esc_len(chunk + base + m[M_PROC_S], m[M_PROC_E] - m[M_PROC_S]);
    if (m[M_NSD]) {
        len += sizeof("\",\"sd_id\":\"") - 1;
        len += esc_len(chunk + base + m[M_SID_S], m[M_SID_E] - m[M_SID_S]);
    }
    len += sizeof("\",\"short_message\":\"") - 1;
    int64_t ml = m[M_TRIM_E] - m[M_MSG_A];
    len += ml > 0 ? esc_len(chunk + base + m[M_MSG_A], ml) : 1;  // "-"
    len += sizeof("\",\"timestamp\":") - 1;
    len += m[M_TS_LEN];
    len += sizeof(",\"version\":\"1.1\"}") - 1;
    len += a.suffix_len;
    if (a.syslen) len += dec_digits(len) + 1;   // "NNN " prefix
    return len;
}

uint8_t* gelf_row_write(const GelfArgs& a, int64_t r, uint8_t* dst,
                        int64_t framed_len) {
    const int32_t* m = a.meta + r * M_NCOL;
    const uint8_t* chunk = a.chunk;
    int64_t base = m[M_START];
    if (a.syslen) dst = put_syslen_prefix(dst, framed_len);
    *dst++ = '{';
    int p = m[M_NPAIR];
    if (p > 0) {
        const int32_t* ns = a.pns + r * a.P;
        const int32_t* ne = a.pne + r * a.P;
        const int32_t* vs = a.pvs + r * a.P;
        const int32_t* ve = a.pve + r * a.P;
        const int32_t* pe = a.pesc + r * a.P;
        int order[kMaxPairs];
        int cnt = sort_pairs(chunk, base, ns, ne, p, order);
        for (int k = 0; k < cnt; k++) {
            int i = order[k];
            dst = LIT(dst, "\"_");
            dst = esc_write(dst, chunk + base + ns[i], ne[i] - ns[i]);
            dst = LIT(dst, "\":\"");
            dst = pe[i]
                ? esc_write_sd(dst, chunk + base + vs[i], ve[i] - vs[i])
                : esc_write(dst, chunk + base + vs[i], ve[i] - vs[i]);
            dst = LIT(dst, "\",");
        }
    }
    dst = LIT(dst, "\"application_name\":\"");
    dst = esc_write(dst, chunk + base + m[M_APP_S], m[M_APP_E] - m[M_APP_S]);
    dst = LIT(dst, "\",\"full_message\":\"");
    dst = esc_write(dst, chunk + base + m[M_FULL_S], m[M_TRIM_E] - m[M_FULL_S]);
    dst = LIT(dst, "\",\"host\":\"");
    int64_t hl = m[M_HOST_E] - m[M_HOST_S];
    if (hl) dst = esc_write(dst, chunk + base + m[M_HOST_S], hl);
    else dst = LIT(dst, "unknown");
    dst = LIT(dst, "\",\"level\":");
    *dst++ = (uint8_t)('0' + m[M_SEV]);
    dst = LIT(dst, ",\"process_id\":\"");
    dst = esc_write(dst, chunk + base + m[M_PROC_S], m[M_PROC_E] - m[M_PROC_S]);
    if (m[M_NSD]) {
        dst = LIT(dst, "\",\"sd_id\":\"");
        dst = esc_write(dst, chunk + base + m[M_SID_S], m[M_SID_E] - m[M_SID_S]);
    }
    dst = LIT(dst, "\",\"short_message\":\"");
    int64_t ml = m[M_TRIM_E] - m[M_MSG_A];
    if (ml > 0) dst = esc_write(dst, chunk + base + m[M_MSG_A], ml);
    else *dst++ = '-';
    dst = LIT(dst, "\",\"timestamp\":");
    dst = put(dst, (const char*)a.ts_scratch + m[M_TS_OFF],
              (size_t)m[M_TS_LEN]);
    dst = LIT(dst, ",\"version\":\"1.1\"}");
    if (a.suffix_len)
        dst = put(dst, (const char*)a.suffix, (size_t)a.suffix_len);
    return dst;
}

void run_threaded(int64_t n, int n_threads,
                  const std::function<void(int64_t, int64_t)>& work,
                  int64_t min_n = 4096) {
    if (n_threads < 1) n_threads = 1;
    if (n_threads == 1 || n < min_n) {
        work(0, n);
        return;
    }
    std::vector<std::thread> threads;
    int64_t per = (n + n_threads - 1) / n_threads;
    for (int t = 0; t < n_threads; t++) {
        int64_t lo = t * per;
        int64_t hi = std::min<int64_t>(lo + per, n);
        if (lo >= hi) break;
        threads.emplace_back(work, lo, hi);
    }
    for (auto& th : threads) th.join();
}

}  // namespace

extern "C" {

void fg_gelf_lens_v2(const uint8_t* chunk, const int32_t* meta, int64_t R,
                  const int32_t* pns, const int32_t* pne,
                  const int32_t* pvs, const int32_t* pve,
                  const int32_t* pesc, int32_t P,
                  const uint8_t* ts_scratch,
                  const uint8_t* suffix, int32_t suffix_len, int32_t syslen,
                  int64_t* out_lens, int n_threads) {
    GelfArgs a{chunk, meta, R, pns, pne, pvs, pve, pesc, P,
               ts_scratch, suffix, suffix_len, syslen};
    run_threaded(R, n_threads, [&](int64_t lo, int64_t hi) {
        for (int64_t r = lo; r < hi; r++) out_lens[r] = gelf_row_len(a, r);
    });
}

void fg_gelf_write_v2(const uint8_t* chunk, const int32_t* meta, int64_t R,
                   const int32_t* pns, const int32_t* pne,
                   const int32_t* pvs, const int32_t* pve,
                   const int32_t* pesc, int32_t P,
                   const uint8_t* ts_scratch,
                   const uint8_t* suffix, int32_t suffix_len, int32_t syslen,
                   const int64_t* out_off, uint8_t* dst, int n_threads) {
    GelfArgs a{chunk, meta, R, pns, pne, pvs, pve, pesc, P,
               ts_scratch, suffix, suffix_len, syslen};
    run_threaded(R, n_threads, [&](int64_t lo, int64_t hi) {
        for (int64_t r = lo; r < hi; r++)
            gelf_row_write(a, r, dst + out_off[r], out_off[r + 1] - out_off[r]);
    });
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Columnar RFC5424 -> RFC5424 re-encode row assembly
// (rfc5424_encoder.rs:28-93 semantics, batched): "<pri>1 ts host app
// proc msgid sd msg" from raw spans — no escaping, no sorting (SD
// blocks and pairs re-emit in original order, values verbatim per the
// reference's Display).  Same two-phase contract as the GELF assembler.
// rowmeta columns (int32, [R, R5_NCOL]); spans row-relative:
// ---------------------------------------------------------------------------

namespace {

enum {
    R5_START = 0, R5_PRI, R5_HOST_S, R5_HOST_E, R5_APP_S, R5_APP_E,
    R5_PROC_S, R5_PROC_E, R5_MSGID_S, R5_MSGID_E, R5_MSG_A, R5_TRIM_E,
    R5_NSD, R5_NPAIR, R5_TS_OFF, R5_TS_LEN, R5_NCOL
};

struct R5Args {
    const uint8_t* chunk;
    const int32_t* meta;
    int64_t R;
    const int32_t* sid_s;   // [R, SD]
    const int32_t* sid_e;
    int32_t SD;
    const int32_t* pns;     // [R, P]
    const int32_t* pne;
    const int32_t* pvs;
    const int32_t* pve;
    const int32_t* psd;     // pair -> block ordinal
    int32_t P;
    const uint8_t* ts_scratch;
    const uint8_t* suffix;
    int32_t suffix_len;
    int32_t syslen;
};

int64_t r5_row_len(const R5Args& a, int64_t r) {
    const int32_t* m = a.meta + r * R5_NCOL;
    int64_t len = 1 + dec_digits(m[R5_PRI]) + 2;     // '<' pri '>' '1'
    len += 1 + m[R5_TS_LEN];                         // ' ' ts
    len += 1 + (m[R5_HOST_E] - m[R5_HOST_S]);
    len += 1 + (m[R5_APP_E] - m[R5_APP_S]);
    len += 1 + (m[R5_PROC_E] - m[R5_PROC_S]);
    len += 1 + (m[R5_MSGID_E] - m[R5_MSGID_S]);
    len += 1;                                        // ' ' before sd
    int nsd = m[R5_NSD];
    if (nsd == 0) {
        len += 1;                                    // '-'
    } else {
        const int32_t* ss = a.sid_s + r * a.SD;
        const int32_t* se = a.sid_e + r * a.SD;
        for (int k = 0; k < nsd; k++)
            len += 2 + (se[k] - ss[k]);              // '[' sid ']'
        const int32_t* ns = a.pns + r * a.P;
        const int32_t* ne = a.pne + r * a.P;
        const int32_t* vs = a.pvs + r * a.P;
        const int32_t* ve = a.pve + r * a.P;
        for (int j = 0; j < m[R5_NPAIR]; j++)
            len += 1 + (ne[j] - ns[j]) + 2 + (ve[j] - vs[j]) + 1;
    }
    len += 1 + (m[R5_TRIM_E] - m[R5_MSG_A]);         // ' ' msg
    len += a.suffix_len;
    if (a.syslen) len += dec_digits(len) + 1;
    return len;
}

uint8_t* r5_row_write(const R5Args& a, int64_t r, uint8_t* dst,
                      int64_t framed_len) {
    const int32_t* m = a.meta + r * R5_NCOL;
    const uint8_t* chunk = a.chunk;
    int64_t base = m[R5_START];
    if (a.syslen) dst = put_syslen_prefix(dst, framed_len);
    *dst++ = '<';
    {
        char buf[8];
        int nb = snprintf(buf, sizeof buf, "%d", m[R5_PRI]);
        dst = put(dst, buf, (size_t)nb);
    }
    dst = LIT(dst, ">1 ");
    dst = put(dst, (const char*)a.ts_scratch + m[R5_TS_OFF],
              (size_t)m[R5_TS_LEN]);
    *dst++ = ' ';
    dst = put(dst, (const char*)chunk + base + m[R5_HOST_S],
              (size_t)(m[R5_HOST_E] - m[R5_HOST_S]));
    *dst++ = ' ';
    dst = put(dst, (const char*)chunk + base + m[R5_APP_S],
              (size_t)(m[R5_APP_E] - m[R5_APP_S]));
    *dst++ = ' ';
    dst = put(dst, (const char*)chunk + base + m[R5_PROC_S],
              (size_t)(m[R5_PROC_E] - m[R5_PROC_S]));
    *dst++ = ' ';
    dst = put(dst, (const char*)chunk + base + m[R5_MSGID_S],
              (size_t)(m[R5_MSGID_E] - m[R5_MSGID_S]));
    *dst++ = ' ';
    int nsd = m[R5_NSD];
    if (nsd == 0) {
        *dst++ = '-';
    } else {
        const int32_t* ss = a.sid_s + r * a.SD;
        const int32_t* se = a.sid_e + r * a.SD;
        const int32_t* ns = a.pns + r * a.P;
        const int32_t* ne = a.pne + r * a.P;
        const int32_t* vs = a.pvs + r * a.P;
        const int32_t* ve = a.pve + r * a.P;
        const int32_t* psd = a.psd + r * a.P;
        int npair = m[R5_NPAIR];
        int j = 0;
        for (int k = 0; k < nsd; k++) {
            *dst++ = '[';
            dst = put(dst, (const char*)chunk + base + ss[k],
                      (size_t)(se[k] - ss[k]));
            for (; j < npair && psd[j] == k; j++) {
                *dst++ = ' ';
                dst = put(dst, (const char*)chunk + base + ns[j],
                          (size_t)(ne[j] - ns[j]));
                dst = LIT(dst, "=\"");
                dst = put(dst, (const char*)chunk + base + vs[j],
                          (size_t)(ve[j] - vs[j]));
                *dst++ = '"';
            }
            *dst++ = ']';
        }
    }
    *dst++ = ' ';
    dst = put(dst, (const char*)chunk + base + m[R5_MSG_A],
              (size_t)(m[R5_TRIM_E] - m[R5_MSG_A]));
    if (a.suffix_len)
        dst = put(dst, (const char*)a.suffix, (size_t)a.suffix_len);
    return dst;
}

}  // namespace

extern "C" {

void fg_r5_lens(const uint8_t* chunk, const int32_t* meta, int64_t R,
                const int32_t* sid_s, const int32_t* sid_e, int32_t SD,
                const int32_t* pns, const int32_t* pne,
                const int32_t* pvs, const int32_t* pve,
                const int32_t* psd, int32_t P,
                const uint8_t* ts_scratch,
                const uint8_t* suffix, int32_t suffix_len, int32_t syslen,
                int64_t* out_lens, int n_threads) {
    R5Args a{chunk, meta, R, sid_s, sid_e, SD, pns, pne, pvs, pve, psd,
             P, ts_scratch, suffix, suffix_len, syslen};
    run_threaded(R, n_threads, [&](int64_t lo, int64_t hi) {
        for (int64_t r = lo; r < hi; r++) out_lens[r] = r5_row_len(a, r);
    });
}

void fg_r5_write(const uint8_t* chunk, const int32_t* meta, int64_t R,
                 const int32_t* sid_s, const int32_t* sid_e, int32_t SD,
                 const int32_t* pns, const int32_t* pne,
                 const int32_t* pvs, const int32_t* pve,
                 const int32_t* psd, int32_t P,
                 const uint8_t* ts_scratch,
                 const uint8_t* suffix, int32_t suffix_len, int32_t syslen,
                 const int64_t* out_off, uint8_t* dst, int n_threads) {
    R5Args a{chunk, meta, R, sid_s, sid_e, SD, pns, pne, pvs, pve, psd,
             P, ts_scratch, suffix, suffix_len, syslen};
    run_threaded(R, n_threads, [&](int64_t lo, int64_t hi) {
        for (int64_t r = lo; r < hi; r++)
            r5_row_write(a, r, dst + out_off[r],
                         out_off[r + 1] - out_off[r]);
    });
}

}  // extern "C"

// Concatenate segments of src into dst: segment i copies
// src[seg_src[i] .. seg_src[i]+seg_len[i]) to dst[dst_off[i]).
// dst_off is the exclusive prefix sum of seg_len (computed by the
// caller, which lets worker threads start mid-stream).  This is the
// byte-assembly engine of the columnar encode path
// (flowgger_tpu/tpu/assemble.py).
void fg_concat_segments(const uint8_t* src,
                        const int64_t* seg_src, const int64_t* seg_len,
                        const int64_t* dst_off, int64_t nseg,
                        uint8_t* dst, int n_threads) {
    run_threaded(nseg, n_threads, [&](int64_t lo, int64_t hi) {
        for (int64_t i = lo; i < hi; i++) {
            int64_t len = seg_len[i];
            if (len > 0)
                memcpy(dst + dst_off[i], src + seg_src[i], (size_t)len);
        }
    }, 8192);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// serde_json-style f64 formatting (utils/rustfmt.py json_f64 semantics):
// shortest round-trip digits via std::to_chars, re-rendered with the
// CPython-repr notation rule (fixed for 10^-4 <= |v| < 10^16, keeping
// ".0" on integral values; otherwise "dE" exponent form without '+' or
// leading exponent zeros; non-finite -> "null").  Differentially fuzz-
// tested against the Python oracle in tests/test_native_and_chunks.py.
// ---------------------------------------------------------------------------

namespace {

int json_f64_render(double v, char* out) {
    if (std::isnan(v) || std::isinf(v)) {
        memcpy(out, "null", 4);
        return 4;
    }
    char buf[40];
    auto r = std::to_chars(buf, buf + sizeof(buf), v,
                           std::chars_format::scientific);
    const char* p = buf;
    char* o = out;
    if (*p == '-') { *o++ = '-'; p++; }
    char digits[24];
    int nd = 0;
    while (p < r.ptr && *p != 'e') {
        if (*p != '.') digits[nd++] = *p;
        p++;
    }
    p++;  // 'e'
    int esign = 1;
    if (p < r.ptr && *p == '+') p++;
    else if (p < r.ptr && *p == '-') { esign = -1; p++; }
    int E = 0;
    while (p < r.ptr) E = E * 10 + (*p++ - '0');
    E *= esign;
    if (E >= -4 && E < 16) {
        if (E >= 0) {
            int i = 0;
            for (; i <= E; i++) *o++ = i < nd ? digits[i] : '0';
            *o++ = '.';
            if (i < nd) { for (; i < nd; i++) *o++ = digits[i]; }
            else *o++ = '0';
        } else {
            *o++ = '0';
            *o++ = '.';
            for (int z = 0; z < -E - 1; z++) *o++ = '0';
            for (int i = 0; i < nd; i++) *o++ = digits[i];
        }
    } else {
        *o++ = digits[0];
        if (nd > 1) {
            *o++ = '.';
            for (int i = 1; i < nd; i++) *o++ = digits[i];
        }
        *o++ = 'e';
        if (E < 0) { *o++ = '-'; E = -E; }
        char eb[8];
        int ne = 0;
        do { eb[ne++] = (char)('0' + E % 10); E /= 10; } while (E);
        while (ne) *o++ = eb[--ne];
    }
    return (int)(o - out);
}

}  // namespace

extern "C" {

// Format n doubles into a dense [n, width] byte matrix (rows zero-
// padded) + per-row byte lengths.  Rows whose rendering would exceed
// `width` get length 0 (callers treat that as "fall back this row");
// json_f64 output is at most 24 bytes so any width >= 24 never clips.
void fg_format_f64_json(const double* vals, int64_t n, uint8_t* out,
                        int32_t width, int32_t* out_len, int n_threads) {
    run_threaded(n, n_threads, [&](int64_t lo, int64_t hi) {
        char buf[48];
        for (int64_t i = lo; i < hi; i++) {
            int len = json_f64_render(vals[i], buf);
            uint8_t* row = out + (size_t)i * (size_t)width;
            if (len > width) {
                memset(row, 0, (size_t)width);
                out_len[i] = 0;
                continue;
            }
            memcpy(row, buf, (size_t)len);
            if (len < width) memset(row + len, 0, (size_t)(width - len));
            out_len[i] = (int32_t)len;
        }
    }, 16384);
}

}  // extern "C"
