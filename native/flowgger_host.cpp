// Native host tier: the hot host-side paths of the batched pipeline.
//
// The reference's host runtime is native (Rust) end to end; here the
// host-side work that sits on the TPU ingest path — newline framing of
// raw chunks and packing framed lines into the dense [N, max_len] batch
// the kernels consume — is C++ with simple pthread fan-out, exposed via
// a C ABI for ctypes (flowgger_tpu/native.py).  Python/numpy remains the
// fallback when the library isn't built.
//
// Parity notes: split semantics match BufRead::lines (line_splitter.rs:
// 17 — \n framing, one trailing \r stripped); the packer implements the
// same clip-and-zero-pad contract as tpu/pack.py pack_lines_2d.

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <thread>
#include <vector>

extern "C" {

// Scan a raw chunk for newline-framed records.
// Writes line start offsets and (CR-stripped) lengths; returns the number
// of complete lines.  *carry_start receives the offset of the trailing
// partial line (== size when the chunk ends exactly on a newline).
int64_t fg_split_lines(const uint8_t* buf, int64_t size,
                       int32_t* starts, int32_t* lens, int64_t cap,
                       int strip_cr, int64_t* carry_start) {
    int64_t n = 0;
    int64_t pos = 0;
    while (pos < size && n < cap) {
        const void* nl = memchr(buf + pos, '\n', (size_t)(size - pos));
        if (nl == nullptr) break;
        int64_t end = (const uint8_t*)nl - buf;
        int64_t len = end - pos;
        if (strip_cr && len > 0 && buf[end - 1] == '\r') len -= 1;
        starts[n] = (int32_t)pos;
        lens[n] = (int32_t)len;
        n += 1;
        pos = end + 1;
    }
    *carry_start = pos;
    return n;
}

// Pack n lines (described by starts/lens into chunk) into a dense
// row-major [n_rows, max_len] uint8 batch, zero-padded; lens_out receives
// the clipped lengths.  Rows beyond n are left untouched (caller zeroes).
void fg_pack_lines(const uint8_t* chunk, int64_t chunk_size,
                   const int32_t* starts, const int32_t* lens, int64_t n,
                   int32_t max_len, uint8_t* out, int32_t* lens_out,
                   int n_threads) {
    if (n_threads < 1) n_threads = 1;
    auto work = [&](int64_t lo, int64_t hi) {
        for (int64_t i = lo; i < hi; i++) {
            uint8_t* row = out + (size_t)i * (size_t)max_len;
            int64_t start = starts[i];
            int64_t len = lens[i];
            if (len > max_len) len = max_len;
            if (start < 0 || start + len > chunk_size) len = 0;
            if (len > 0) memcpy(row, chunk + start, (size_t)len);
            if (len < max_len) memset(row + len, 0, (size_t)(max_len - len));
            lens_out[i] = (int32_t)len;
        }
    };
    if (n_threads == 1 || n < 4096) {
        work(0, n);
        return;
    }
    std::vector<std::thread> threads;
    int64_t per = (n + n_threads - 1) / n_threads;
    for (int t = 0; t < n_threads; t++) {
        int64_t lo = t * per;
        int64_t hi = std::min<int64_t>(lo + per, n);
        if (lo >= hi) break;
        threads.emplace_back(work, lo, hi);
    }
    for (auto& th : threads) th.join();
}

}  // extern "C"
