// Self-test driver for the native host tier, built and run under
// ASan/TSan by `make asan-check` / `make tsan-check` — sanitizers need a
// runnable binary, not a shared library loaded into an unsanitized
// python (which ASan refuses outright).

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

extern "C" {
int64_t fg_split_lines(const uint8_t*, int64_t, int32_t*, int32_t*, int64_t,
                       int, int64_t*);
int64_t fg_split_syslen(const uint8_t*, int64_t, int32_t*, int32_t*, int64_t,
                        int64_t*, int*);
void fg_pack_lines(const uint8_t*, int64_t, const int32_t*, const int32_t*,
                   int64_t, int32_t, uint8_t*, int32_t*, int);
void fg_concat_segments(const uint8_t*, const int64_t*, const int64_t*,
                        const int64_t*, int64_t, uint8_t*, int);
uint32_t fg_crc32c(const uint8_t*, int64_t, uint32_t);
int64_t fg_snappy_max_compressed(int64_t);
int64_t fg_snappy_compress(const uint8_t*, int64_t, uint8_t*);
int64_t fg_snappy_decompress(const uint8_t*, int64_t, uint8_t*, int64_t);
void fg_format_f64_json(const double*, int64_t, uint8_t*, int32_t,
                        int32_t*, int);
}

int main() {
    // build a chunk of 10000 framed lines (CRLF every third line)
    std::string chunk;
    for (int i = 0; i < 10000; i++) {
        chunk += "line number " + std::to_string(i);
        chunk += (i % 3 == 0) ? "\r\n" : "\n";
    }
    chunk += "partial tail";
    std::vector<int32_t> starts(20000), lens(20000);
    int64_t carry = 0;
    int64_t n = fg_split_lines((const uint8_t*)chunk.data(), (int64_t)chunk.size(),
                               starts.data(), lens.data(), 20000, 1, &carry);
    assert(n == 10000);
    assert(chunk.substr((size_t)carry) == "partial tail");
    for (int i = 0; i < n; i++) {
        std::string expect = "line number " + std::to_string(i);
        assert(std::string(chunk, starts[i], lens[i]) == expect);
    }

    // threaded pack: exercises the pthread fan-out under TSan
    const int32_t max_len = 32;
    std::vector<uint8_t> out((size_t)n * max_len, 0xFF);
    std::vector<int32_t> lens_out(n);
    fg_pack_lines((const uint8_t*)chunk.data(), (int64_t)chunk.size(),
                  starts.data(), lens.data(), n, max_len, out.data(),
                  lens_out.data(), 8);
    for (int i = 0; i < n; i++) {
        std::string expect = "line number " + std::to_string(i);
        assert(lens_out[i] == (int32_t)expect.size());
        assert(memcmp(out.data() + (size_t)i * max_len, expect.data(),
                      expect.size()) == 0);
        for (int j = lens_out[i]; j < max_len; j++)
            assert(out[(size_t)i * max_len + j] == 0);
    }
    // threaded segment concat: interleave two sources of the chunk
    {
        int64_t nseg = 2 * n;
        std::vector<int64_t> seg_src(nseg), seg_len(nseg), dst_off(nseg + 1);
        int64_t pos = 0;
        for (int64_t i = 0; i < n; i++) {
            seg_src[2 * i] = starts[i];
            seg_len[2 * i] = lens[i];
            seg_src[2 * i + 1] = starts[0];
            seg_len[2 * i + 1] = 4;  // "line"
        }
        for (int64_t i = 0; i < nseg; i++) {
            dst_off[i] = pos;
            pos += seg_len[i];
        }
        dst_off[nseg] = pos;
        std::vector<uint8_t> cat(pos);
        fg_concat_segments((const uint8_t*)chunk.data(), seg_src.data(),
                           seg_len.data(), dst_off.data(), nseg, cat.data(), 8);
        assert(memcmp(cat.data() + dst_off[1], "line", 4) == 0);
        assert(memcmp(cat.data(), chunk.data(), (size_t)lens[0]) == 0);
    }

    // syslen scanner
    {
        std::string s = "5 hello0 12 hello world!9 partial";
        std::vector<int32_t> st(8), ln(8);
        int64_t consumed = 0;
        int err = 0;
        int64_t m = fg_split_syslen((const uint8_t*)s.data(), (int64_t)s.size(),
                                    st.data(), ln.data(), 8, &consumed, &err);
        assert(m == 3 && !err);
        assert(std::string(s, st[0], ln[0]) == "hello");
        assert(std::string(s, st[1], ln[1]) == "");
        assert(std::string(s, st[2], ln[2]) == "hello world!");
        assert(std::string(s, (size_t)consumed) == "9 partial");
    }

    // crc32c vector + snappy round-trip (threads not involved, but the
    // sanitizers watch the buffer math)
    {
        assert(fg_crc32c((const uint8_t*)"123456789", 9, 0) == 0xE3069283u);
        std::string data;
        for (int i = 0; i < 5000; i++)
            data += "repetitive payload chunk " + std::to_string(i % 17);
        std::vector<uint8_t> comp(fg_snappy_max_compressed((int64_t)data.size()));
        int64_t clen = fg_snappy_compress((const uint8_t*)data.data(),
                                          (int64_t)data.size(), comp.data());
        assert(clen > 0 && clen < (int64_t)data.size());
        std::vector<uint8_t> round(data.size());
        int64_t dlen = fg_snappy_decompress(comp.data(), clen, round.data(),
                                            (int64_t)round.size());
        assert(dlen == (int64_t)data.size());
        assert(memcmp(round.data(), data.data(), data.size()) == 0);
    }

    // threaded f64 JSON formatter (shortest round-trip, json_f64
    // notation): spot values + a threaded batch under the sanitizers
    {
        std::vector<double> vals = {1438790025.637824, 0.0, -0.0, 1e16,
                                    0.0001, 1e-5, 5e-324,
                                    1.7976931348623157e308};
        for (int i = 0; i < 40000; i++)
            vals.push_back(1.0e9 + i * 0.001 + i);
        int64_t nv = (int64_t)vals.size();
        std::vector<uint8_t> txt((size_t)nv * 32);
        std::vector<int32_t> tlen(nv);
        fg_format_f64_json(vals.data(), nv, txt.data(), 32, tlen.data(), 4);
        auto row = [&](int64_t i) {
            return std::string((const char*)txt.data() + i * 32,
                               (size_t)tlen[i]);
        };
        assert(row(0) == "1438790025.637824");
        assert(row(1) == "0.0");
        assert(row(2) == "-0.0");
        assert(row(3) == "1e16");
        assert(row(4) == "0.0001");
        assert(row(5) == "1e-5");
        assert(row(6) == "5e-324");
        for (int64_t i = 0; i < nv; i++) {
            assert(tlen[i] >= 1 && tlen[i] <= 32);
            double back = strtod(row(i).c_str(), nullptr);
            assert(back == vals[i] || (vals[i] != vals[i]));
        }
    }

    printf("native self-test ok: %lld lines\n", (long long)n);
    return 0;
}
