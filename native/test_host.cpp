// Self-test driver for the native host tier, built and run under
// ASan/TSan by `make asan-check` / `make tsan-check` — sanitizers need a
// runnable binary, not a shared library loaded into an unsanitized
// python (which ASan refuses outright).

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

extern "C" {
int64_t fg_split_lines(const uint8_t*, int64_t, int32_t*, int32_t*, int64_t,
                       int, int64_t*);
void fg_pack_lines(const uint8_t*, int64_t, const int32_t*, const int32_t*,
                   int64_t, int32_t, uint8_t*, int32_t*, int);
}

int main() {
    // build a chunk of 10000 framed lines (CRLF every third line)
    std::string chunk;
    for (int i = 0; i < 10000; i++) {
        chunk += "line number " + std::to_string(i);
        chunk += (i % 3 == 0) ? "\r\n" : "\n";
    }
    chunk += "partial tail";
    std::vector<int32_t> starts(20000), lens(20000);
    int64_t carry = 0;
    int64_t n = fg_split_lines((const uint8_t*)chunk.data(), (int64_t)chunk.size(),
                               starts.data(), lens.data(), 20000, 1, &carry);
    assert(n == 10000);
    assert(chunk.substr((size_t)carry) == "partial tail");
    for (int i = 0; i < n; i++) {
        std::string expect = "line number " + std::to_string(i);
        assert(std::string(chunk, starts[i], lens[i]) == expect);
    }

    // threaded pack: exercises the pthread fan-out under TSan
    const int32_t max_len = 32;
    std::vector<uint8_t> out((size_t)n * max_len, 0xFF);
    std::vector<int32_t> lens_out(n);
    fg_pack_lines((const uint8_t*)chunk.data(), (int64_t)chunk.size(),
                  starts.data(), lens.data(), n, max_len, out.data(),
                  lens_out.data(), 8);
    for (int i = 0; i < n; i++) {
        std::string expect = "line number " + std::to_string(i);
        assert(lens_out[i] == (int32_t)expect.size());
        assert(memcmp(out.data() + (size_t)i * max_len, expect.data(),
                      expect.size()) == 0);
        for (int j = lens_out[i]; j < max_len; j++)
            assert(out[(size_t)i * max_len + j] == 0);
    }
    printf("native self-test ok: %lld lines\n", (long long)n);
    return 0;
}
