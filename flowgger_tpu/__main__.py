"""CLI entry: ``python -m flowgger_tpu [config.toml]``.

Parity model: /root/reference/src/main.rs:9-26 (single positional config
path, default ``flowgger.toml``).
"""

from __future__ import annotations

import argparse

from . import __version__, start

DEFAULT_CONFIG_FILE = "flowgger.toml"


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="flowgger-tpu",
        description="A TPU-native data collector (flowgger-compatible)",
    )
    parser.add_argument("config_file", nargs="?", default=DEFAULT_CONFIG_FILE,
                        help="Configuration file (default: flowgger.toml)")
    parser.add_argument("--check", action="store_true",
                        help="Lint the config against the known key "
                             "namespace and exit")
    parser.add_argument("--version", action="version", version=__version__)
    args = parser.parse_args(argv)
    if args.check:
        from .lint import check_file

        raise SystemExit(check_file(args.config_file))
    print(f"Flowgger-TPU {__version__}")
    start(args.config_file)


if __name__ == "__main__":
    main()
