"""The control plane: one ticker, three feedback loops.

``ControlPlane.from_config`` is the enablement switch (the
TenantRegistry/Fleet/DurabilityManager idiom): no ``[control]`` table
→ ``None`` → the pipeline builds nothing and the hot path is
untouched.  Armed, a single daemon ticker evaluates the loops every
``control.interval_s`` seconds against signals other subsystems
already compute — the SLO engine's per-objective burn state, the
breaker gauge, the durability backlog, the fleet roster — so the
controller itself adds no hot-path instrumentation at all.

Loop 1 — burn-driven admission.  Every *tenant-dimensioned* objective
feeds that tenant's :class:`~.aimd.AimdLimiter`; the limiter's factor
is applied through ``TenantState.set_rate_factor`` (the token buckets
re-rate in place, bursts untouched).  Tighten/relax transitions
journal ``admission_tighten``/``admission_relax`` with the applied
lines/sec rate as cost.  Only rate-limited tenants are governed — an
unlimited tenant has no rate to multiply (the ``tenant_flood``
convention).

Loop 2 — share feedback.  Host-level pressure is any of: a burning
*non-tenant* objective (tenant objectives are loop 1's job — one
noisy tenant must not cost the whole host its share), the decode
breaker away from CLOSED, or a nonzero spill backlog / pinned replay
cursor.  Pressure decays the advertised ``tpu_fleet_capacity`` weight
through ``Membership.set_local_capacity``; the decayed weight rides
the next heartbeat doc, so every peer's ``fleet.shares`` — and
through the weight emitter / steering proxy, actual traffic — shifts
away from the degrading host *before* its breaker trips.

Loop 3 — autoscale signal.  :func:`desired_hosts` derives a desired
routable-host count from fleet burn, queue occupancy against the
per-host target, and the replay backlog; the result is the
``fleet_desired_hosts`` gauge and the ``/fleetz`` ``control`` section.
The signal is advisory by design — *this* process can tighten tenants
and shed share, but only an external compose/k8s layer can buy
hardware.

Failure philosophy: frozen-at-last-applied.  ``stop()`` (and the
``control_freeze`` drill site, which makes a tick deterministically
skip) leaves every applied factor exactly where the last live tick
put it — a dead controller must not un-throttle a flood.  Nothing
here ever *widens* an operator limit: factors are clamped to
``[floor, 1.0]`` of configured values.
"""

from __future__ import annotations

import math
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from ..utils import faultinject as _faults
from ..utils.metrics import registry as _metrics
from .aimd import AimdLimiter
from .emitter import WeightEmitter
from .spec import ControlSpec, control_spec

ROUTABLE_STATES = ("joining", "active")


def desired_hosts(routable: int, burning: bool, max_fast_burn: float,
                  fill_fraction: float, target_fill: float,
                  replay_lag: int, lag_per_host: int,
                  min_hosts: int, max_hosts: int) -> int:
    """The autoscale signal, as a pure function.

    Scale-up pressure is the max of two ratios — queue occupancy over
    the per-host target, and the fast-window burn rate (capped at 8x
    so one pathological window cannot demand an absurd fleet) — scaled
    onto the current routable count, plus one extra host per
    ``lag_per_host`` records of replay backlog.  Scale-down is
    deliberately conservative: only when nothing burns, the backlog is
    clear, and occupancy sits under half the target does the signal
    step down, and then by exactly one host — the same
    remove-slowly/add-quickly asymmetry as the AIMD loops.
    """
    routable = max(1, routable)
    need = float(routable)
    if target_fill > 0 and fill_fraction > target_fill:
        need = max(need, routable * fill_fraction / target_fill)
    if burning:
        need = max(need, routable * max(1.0, min(max_fast_burn, 8.0)))
    desired = math.ceil(need - 1e-9)
    if lag_per_host > 0 and replay_lag > 0:
        desired += math.ceil(replay_lag / lag_per_host)
    if (desired <= routable and not burning and replay_lag <= 0
            and fill_fraction < target_fill / 2):
        desired = routable - 1
    return max(min_hosts, min(max_hosts, desired))


class ControlPlane:
    """Owns the limiters, the ticker, the emitter, and (when
    configured) the steering proxy's lifecycle."""

    def __init__(self, spec: ControlSpec, tenants=None, fleet=None,
                 tx=None, durability=None,
                 burn_source: Optional[Callable[[], List[dict]]] = None,
                 registry=None, clock=time.monotonic):
        self.spec = spec
        self.tenants = tenants
        self.fleet = fleet
        self.tx = tx
        self.durability = durability
        self._clock = clock
        self._metrics = registry if registry is not None else _metrics
        if burn_source is None:
            from ..obs import slo as _slo

            burn_source = _slo.engine.burn_states
        self._burn_source = burn_source
        self._limiters: Dict[str, AimdLimiter] = {}
        self._share = AimdLimiter(
            backoff=spec.share_backoff,
            recover_step=spec.share_recover_pct / 100.0,
            floor=spec.share_floor_pct / 100.0)
        self._emitter: Optional[WeightEmitter] = None
        if spec.emits_weights:
            self._emitter = WeightEmitter(
                path=spec.weights_path, fmt=spec.weights_format,
                backend=spec.backend, ingest_port=spec.ingest_port,
                haproxy_socket=spec.haproxy_socket)
        self.proxy = None            # fleet/proxy.SteeringProxy (start())
        self.desired = 0             # last autoscale signal
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- construction ------------------------------------------------------
    @classmethod
    def from_config(cls, config, tenants=None, fleet=None, tx=None,
                    durability=None) -> Optional["ControlPlane"]:
        """The enablement switch: None when ``[control]`` is absent."""
        spec = control_spec(config)
        if spec is None:
            return None
        return cls(spec, tenants=tenants, fleet=fleet, tx=tx,
                   durability=durability)

    def _tenant_limiter(self, name: str) -> AimdLimiter:
        lim = self._limiters.get(name)
        if lim is None:
            lim = AimdLimiter(
                backoff=self.spec.admission_backoff,
                recover_step=self.spec.admission_recover_pct / 100.0,
                floor=self.spec.admission_floor_pct / 100.0)
            self._limiters[name] = lim
        return lim

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Arm the loops: ticker (``interval_s > 0`` and at least one
        loop on) and the steering proxy.  Call after ``fleet.start()``
        — the proxy routes off the live roster."""
        if self.spec.proxy and self.proxy is None:
            from ..fleet.proxy import SteeringProxy

            self.proxy = SteeringProxy(
                bind=self.spec.proxy_bind, port=self.spec.proxy_port,
                roster_fn=self._roster, ingest_port=self.spec.ingest_port)
            self.proxy.start()
            print(f"control: steering proxy on {self.proxy.addr} -> "
                  f"ingest port {self.spec.ingest_port}",
                  file=sys.stderr)
        if self.spec.interval_s > 0 and self.spec.any_loop \
                and self._thread is None:
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="control-plane")
            self._thread.start()
            armed = [n for n, on in (
                ("admission", self.spec.admission),
                ("share", self.spec.share),
                ("autoscale", self.spec.autoscale),
                ("weights", self.spec.emits_weights)) if on]
            print(f"control: loop(s) armed every "
                  f"{self.spec.interval_s:g}s: {', '.join(armed)}",
                  file=sys.stderr)

    def stop(self) -> None:
        """Frozen-at-last-applied: stops the ticker and the proxy but
        deliberately leaves every applied factor in place — a dying
        controller must never reset a throttled flood to open."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        if self.proxy is not None:
            self.proxy.stop()

    def _run(self) -> None:
        while not self._stop.wait(self.spec.interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 - the controller must never die silently mid-soak
                print(f"control: tick failed: {e}", file=sys.stderr)

    def _roster(self) -> List[dict]:
        fleet = self.fleet
        membership = getattr(fleet, "membership", None) if fleet else None
        return membership.roster() if membership is not None else []

    # -- the tick ----------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> bool:
        """One controller pass (the ticker calls this; tests and the
        chaos drill call it directly).  Returns True when any loop
        applied a change."""
        if _faults.enabled() and _faults.fire("control_freeze"):
            # the controller-death drill: this tick never happened —
            # whatever the last live tick applied stays applied
            self._metrics.inc("control_freezes")
            from ..obs import events as _events

            _events.emit(
                "control", "control_freeze",
                msg="control: tick skipped (control_freeze); tenant "
                    "rates and capacity weight stay frozen at "
                    "last-applied")
            return False
        self._metrics.inc("control_ticks")
        burns = self._burn_source()
        applied = False
        if self.spec.admission and self.tenants is not None:
            applied |= self._tick_admission(burns)
        if self.spec.share and self.fleet is not None:
            applied |= self._tick_share(burns)
        if self.spec.autoscale:
            self._tick_autoscale(burns)
        if self._emitter is not None:
            roster = self._roster()
            if roster:
                applied |= self._emitter.update(roster)
        if applied:
            self._metrics.inc("control_applies")
        return applied

    def _tick_admission(self, burns: List[dict]) -> bool:
        from ..obs import events as _events

        # combine a tenant's objectives: tighten if ANY is burning
        # (the engine's burning flag IS the both-windows hysteresis),
        # relax only when ALL are clear
        per_tenant: Dict[str, bool] = {}
        for b in burns:
            tenant = b.get("tenant")
            if not tenant:
                continue
            per_tenant[tenant] = per_tenant.get(tenant, False) \
                or bool(b.get("burning"))
        changed = False
        for tenant, burning in per_tenant.items():
            state = self.tenants.state(tenant)
            if not state.spec.limited or state.name != tenant:
                # unlimited (ungovernable) or an unknown name that
                # resolved to the default state — never punish the
                # default lane for a typo'd objective dimension
                continue
            lim = self._tenant_limiter(tenant)
            action = lim.step(burning, not burning)
            if action is None:
                continue
            changed = True
            rate = state.set_rate_factor(lim.factor)
            reason = ("admission_tighten" if action == "tighten"
                      else "admission_relax")
            _events.emit(
                "control", reason, tenant=tenant,
                detail=state.admission_detail(),
                cost=rate, cost_unit="lines_per_sec",
                msg=(f"control: tenant [{tenant}] {action}ed to "
                     f"{lim.factor:.0%} of configured rate "
                     f"({rate:g} lines/s)"))
        return changed

    def _host_pressure(self, burns: List[dict]) -> Optional[str]:
        """The share loop's input: a human-readable pressure cause, or
        None when the host is healthy."""
        for b in burns:
            if b.get("burning") and not b.get("tenant"):
                return f"slo burn ({b.get('name')})"
        if self._metrics.get_gauge("device_breaker_state", 0) >= 1:
            return "decode breaker away from CLOSED"
        if self.durability is not None:
            if self.durability.backlog() > 0:
                return "spill backlog"
        elif (self._metrics.get_gauge("spill_segments", 0) > 0
                or self._metrics.get_gauge("replay_cursor_lag", 0) > 0):
            return "spill backlog"
        return None

    def _tick_share(self, burns: List[dict]) -> bool:
        from ..obs import events as _events

        membership = getattr(self.fleet, "membership", None)
        if membership is None:
            return False
        cause = self._host_pressure(burns)
        action = self._share.step(cause is not None, cause is None)
        if action is None:
            return False
        base = self.fleet.capacity or 1.0
        capacity = base * self._share.factor
        if not membership.set_local_capacity(capacity):
            return False
        self._metrics.set_gauge("control_capacity_factor",
                                round(self._share.factor, 4))
        reason = "share_decay" if action == "tighten" else "share_restore"
        verb = "decayed" if action == "tighten" else "restored"
        _events.emit(
            "control", reason,
            detail=(f"advertised capacity {capacity:g} of configured "
                    f"{base:g}"
                    + (f"; pressure: {cause}" if cause else "")),
            cost=capacity, cost_unit="capacity",
            msg=(f"control: {verb} advertised capacity to "
                 f"{self._share.factor:.0%} of configured"
                 + (f" ({cause})" if cause else "")))
        return True

    def _tick_autoscale(self, burns: List[dict]) -> None:
        routable = 1
        membership = getattr(self.fleet, "membership", None) \
            if self.fleet else None
        if membership is not None:
            counts = membership.counts()
            routable = sum(counts.get(s, 0) for s in ROUTABLE_STATES)
        burning = any(b.get("burning") for b in burns)
        max_fast = max((float(b.get("fast_burn", 0.0)) for b in burns),
                       default=0.0)
        fill = self.tx.fill_fraction() if self.tx is not None else 0.0
        lag = (self.durability.backlog() if self.durability is not None
               else int(self._metrics.get_gauge("replay_cursor_lag", 0)))
        self.desired = desired_hosts(
            routable, burning, max_fast, fill,
            self.spec.autoscale_target_fill, lag,
            self.spec.autoscale_lag_per_host,
            self.spec.autoscale_min_hosts, self.spec.autoscale_max_hosts)
        self._metrics.set_gauge("fleet_desired_hosts", self.desired)

    # -- export ------------------------------------------------------------
    @property
    def ticks(self) -> int:
        """Live ticks completed (the control_ticks counter — frozen
        ticks count control_freezes instead)."""
        return self._metrics.get("control_ticks")

    def fleetz_section(self) -> dict:
        """The ``control`` section of the ``/fleetz`` document."""
        return {
            "enabled": True,
            "desired_hosts": int(self.desired),
            "capacity_factor": round(self._share.factor, 4),
            "tenants": {name: round(lim.factor, 4)
                        for name, lim in self._limiters.items()},
        }
