"""``[control]`` table parsing — the enablement switch for the whole
feedback layer.

``control_spec(config)`` returns ``None`` when no ``[control]`` table
exists (the pipeline then builds nothing: zero threads, zero hot-path
cost), and a validated :class:`ControlSpec` otherwise.  Every loop is
additionally gated by its own boolean, all defaulting off, so an
operator arms exactly the loops they trust::

    [control]
    interval_s = 1.0              # controller tick; 0 = manual (tests)

    admission = true              # loop 1: burn-driven tenant AIMD
    admission_backoff = 0.5       # multiplicative tighten per tick
    admission_recover_pct = 10    # additive recovery, % of configured
    admission_floor_pct = 10      # tighten clamp, % of configured

    share = true                  # loop 2: capacity-weight feedback
    share_backoff = 0.7
    share_recover_pct = 10
    share_floor_pct = 20

    autoscale = true              # loop 3: desired-host-count signal
    autoscale_min_hosts = 1
    autoscale_max_hosts = 16
    autoscale_target_fill = 0.5   # queue occupancy a host should hold
    autoscale_lag_per_host = 100000  # replay backlog one host absorbs

    # share *enforcement* (either/both; shares stay advisory without)
    proxy = true                  # built-in TCP steering proxy
    proxy_bind = "0.0.0.0"
    proxy_port = 5514
    ingest_port = 514             # maps a peer's fleet addr -> ingest
    weights_path = "/run/flowgger/weights.map"   # rendered on change
    weights_format = "haproxy"    # or "nginx"
    haproxy_socket = "/var/run/haproxy.sock"     # live runtime pushes
    backend = "flowgger"          # LB backend/upstream name
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..config import Config, ConfigError

DEFAULT_INTERVAL_S = 1.0
WEIGHT_FORMATS = ("haproxy", "nginx")

_KNOWN_KEYS = frozenset((
    "interval_s",
    "admission", "admission_backoff", "admission_recover_pct",
    "admission_floor_pct",
    "share", "share_backoff", "share_recover_pct", "share_floor_pct",
    "autoscale", "autoscale_min_hosts", "autoscale_max_hosts",
    "autoscale_target_fill", "autoscale_lag_per_host",
    "proxy", "proxy_bind", "proxy_port", "ingest_port",
    "weights_path", "weights_format", "haproxy_socket", "backend",
))


@dataclass
class ControlSpec:
    """One validated ``[control]`` table."""

    interval_s: float = DEFAULT_INTERVAL_S
    admission: bool = False
    admission_backoff: float = 0.5
    admission_recover_pct: float = 10.0
    admission_floor_pct: float = 10.0
    share: bool = False
    share_backoff: float = 0.7
    share_recover_pct: float = 10.0
    share_floor_pct: float = 20.0
    autoscale: bool = False
    autoscale_min_hosts: int = 1
    autoscale_max_hosts: int = 16
    autoscale_target_fill: float = 0.5
    autoscale_lag_per_host: int = 100_000
    proxy: bool = False
    proxy_bind: str = "0.0.0.0"
    proxy_port: int = 0
    ingest_port: int = 0
    weights_path: Optional[str] = None
    weights_format: str = "haproxy"
    haproxy_socket: Optional[str] = None
    backend: str = "flowgger"

    @property
    def any_loop(self) -> bool:
        """Anything for the ticker to do?"""
        return (self.admission or self.share or self.autoscale
                or self.emits_weights)

    @property
    def emits_weights(self) -> bool:
        return self.weights_path is not None or self.haproxy_socket is not None


def _pct(value: float, key: str) -> float:
    if not (0.0 < value <= 100.0):
        raise ConfigError(f"control.{key} must be in (0, 100]")
    return value


def control_spec(config: Config) -> Optional[ControlSpec]:
    """Parse ``[control]``; None = the feedback layer stays unbuilt."""
    table = config.lookup_table(
        "control", "[control] must be a table (the feedback-loop "
        "configuration)")
    if table is None:
        return None
    unknown = set(table) - _KNOWN_KEYS
    if unknown:
        raise ConfigError(
            f"unknown [control] key(s): {', '.join(sorted(unknown))} "
            f"(known: {', '.join(sorted(_KNOWN_KEYS))})")
    spec = ControlSpec()
    interval = config.lookup_float(
        "control.interval_s",
        "control.interval_s must be a number (seconds between "
        "controller ticks; 0 = manual tick, tests only)",
        DEFAULT_INTERVAL_S)
    if interval < 0:
        raise ConfigError("control.interval_s must be >= 0")
    spec.interval_s = interval

    spec.admission = config.lookup_bool(
        "control.admission",
        "control.admission must be a boolean (arm the burn-driven "
        "tenant AIMD loop)", False)
    spec.admission_backoff = config.lookup_float(
        "control.admission_backoff",
        "control.admission_backoff must be a number in (0, 1) "
        "(multiplicative tighten per burning tick)", 0.5)
    if not 0.0 < spec.admission_backoff < 1.0:
        raise ConfigError("control.admission_backoff must be in (0, 1)")
    spec.admission_recover_pct = _pct(config.lookup_float(
        "control.admission_recover_pct",
        "control.admission_recover_pct must be a number in (0, 100] "
        "(additive recovery per clear tick, % of the configured rate)",
        10.0), "admission_recover_pct")
    spec.admission_floor_pct = _pct(config.lookup_float(
        "control.admission_floor_pct",
        "control.admission_floor_pct must be a number in (0, 100] "
        "(tighten clamp, % of the configured rate — a governed tenant "
        "keeps a trickle, never a blackhole)", 10.0),
        "admission_floor_pct")

    spec.share = config.lookup_bool(
        "control.share",
        "control.share must be a boolean (arm the capacity-weight "
        "feedback loop)", False)
    spec.share_backoff = config.lookup_float(
        "control.share_backoff",
        "control.share_backoff must be a number in (0, 1) "
        "(multiplicative capacity decay per pressured tick)", 0.7)
    if not 0.0 < spec.share_backoff < 1.0:
        raise ConfigError("control.share_backoff must be in (0, 1)")
    spec.share_recover_pct = _pct(config.lookup_float(
        "control.share_recover_pct",
        "control.share_recover_pct must be a number in (0, 100] "
        "(additive capacity recovery per clear tick)", 10.0),
        "share_recover_pct")
    spec.share_floor_pct = _pct(config.lookup_float(
        "control.share_floor_pct",
        "control.share_floor_pct must be a number in (0, 100] "
        "(capacity decay clamp — a pressured host keeps a floor share "
        "so it stays routable while it recovers)", 20.0),
        "share_floor_pct")

    spec.autoscale = config.lookup_bool(
        "control.autoscale",
        "control.autoscale must be a boolean (export the "
        "fleet_desired_hosts signal)", False)
    spec.autoscale_min_hosts = config.lookup_int(
        "control.autoscale_min_hosts",
        "control.autoscale_min_hosts must be an integer >= 1", 1)
    spec.autoscale_max_hosts = config.lookup_int(
        "control.autoscale_max_hosts",
        "control.autoscale_max_hosts must be an integer >= min_hosts",
        16)
    if spec.autoscale_min_hosts < 1:
        raise ConfigError("control.autoscale_min_hosts must be >= 1")
    if spec.autoscale_max_hosts < spec.autoscale_min_hosts:
        raise ConfigError("control.autoscale_max_hosts must be >= "
                          "control.autoscale_min_hosts")
    spec.autoscale_target_fill = config.lookup_float(
        "control.autoscale_target_fill",
        "control.autoscale_target_fill must be a number in (0, 1] "
        "(queue occupancy one host should run at)", 0.5)
    if not 0.0 < spec.autoscale_target_fill <= 1.0:
        raise ConfigError(
            "control.autoscale_target_fill must be in (0, 1]")
    spec.autoscale_lag_per_host = config.lookup_int(
        "control.autoscale_lag_per_host",
        "control.autoscale_lag_per_host must be an integer >= 1 "
        "(spilled-but-unacked records one extra host absorbs)",
        100_000)
    if spec.autoscale_lag_per_host < 1:
        raise ConfigError(
            "control.autoscale_lag_per_host must be >= 1")

    spec.proxy = config.lookup_bool(
        "control.proxy",
        "control.proxy must be a boolean (start the built-in TCP "
        "steering proxy)", False)
    spec.proxy_bind = config.lookup_str(
        "control.proxy_bind",
        "control.proxy_bind must be a string (proxy listen address)",
        "0.0.0.0")
    spec.proxy_port = config.lookup_int(
        "control.proxy_port",
        "control.proxy_port must be an integer (proxy listen port; "
        "0 = ephemeral, tests only)", 0)
    spec.ingest_port = config.lookup_int(
        "control.ingest_port",
        "control.ingest_port must be an integer (the port senders "
        "reach each host's ingest listener on — maps a peer's fleet "
        "address to its ingest address)", 0)
    if spec.proxy and spec.ingest_port <= 0:
        raise ConfigError(
            "control.proxy requires control.ingest_port (the proxy "
            "routes connections to each routable host's ingest port)")

    spec.weights_path = config.lookup_str(
        "control.weights_path",
        "control.weights_path must be a string (file the weight "
        "emitter atomically rewrites on share change)")
    spec.weights_format = config.lookup_str(
        "control.weights_format",
        'control.weights_format must be "haproxy" or "nginx"',
        "haproxy")
    if spec.weights_format not in WEIGHT_FORMATS:
        raise ConfigError(
            'control.weights_format must be "haproxy" or "nginx"')
    spec.haproxy_socket = config.lookup_str(
        "control.haproxy_socket",
        "control.haproxy_socket must be a string (haproxy runtime-API "
        "stats socket for live set-weight pushes)")
    spec.backend = config.lookup_str(
        "control.backend",
        "control.backend must be a string (LB backend/upstream name "
        "the rendered weights address)", "flowgger")
    return spec
