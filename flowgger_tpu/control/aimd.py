"""AIMD rate governor: the pure, clockless unit under the admission
and share-feedback loops.

The limiter tracks one scalar ``factor`` in ``[floor, 1.0]`` — the
fraction of the *configured* rate (or capacity weight) currently
applied.  Each controller tick feeds it the same two-window burn
signals the SLO engine computes, and the decision rule deliberately
mirrors the engine's hysteresis (obs/slo.py):

- **tighten** (multiplicative, ``factor *= backoff``) only when BOTH
  the fast and the slow window are at/over the burn threshold — the
  fast window confirms the problem is current, the slow window that it
  is significant, so a single-window blip can never oscillate the
  factor;
- **relax** (additive, ``factor += recover_step``) only when the fast
  window is clear AND the factor is below 1.0 — the same fast-window
  condition that flips the engine's ``burning`` flag off;
- anything else **holds** (notably fast-hot/slow-cold: neither rule
  fires, the factor sits still).

Tightening clamps at ``floor`` (a governed tenant keeps a trickle —
admission must stay distinguishable from a blackhole) and relaxing
clamps at 1.0 (the configured rate is the ceiling; the controller only
ever *removes* headroom, never grants more than the operator did).

The unit is step-based and owns no clock or thread: determinism under
a fake clock is the caller's trivially-held property, and the tests
drive it as a value → value function.
"""

from __future__ import annotations

from typing import Optional

DEFAULT_BACKOFF = 0.5       # multiplicative tighten per burning tick
DEFAULT_RECOVER_STEP = 0.1  # additive recovery per clear tick
DEFAULT_FLOOR = 0.1         # tighten clamp (fraction of configured)

TIGHTEN = "tighten"
RELAX = "relax"


class AimdLimiter:
    """One governed scalar: multiplicative decrease, additive
    increase, both-windows hysteresis."""

    def __init__(self, backoff: float = DEFAULT_BACKOFF,
                 recover_step: float = DEFAULT_RECOVER_STEP,
                 floor: float = DEFAULT_FLOOR):
        if not 0.0 < backoff < 1.0:
            raise ValueError("backoff must be in (0, 1) "
                             "(multiplicative decrease)")
        if recover_step <= 0.0:
            raise ValueError("recover_step must be > 0 "
                             "(additive increase)")
        if not 0.0 < floor <= 1.0:
            raise ValueError("floor must be in (0, 1]")
        self.backoff = float(backoff)
        self.recover_step = float(recover_step)
        self.floor = float(floor)
        self.factor = 1.0

    def update(self, fast_burn: float, slow_burn: float,
               threshold: float = 1.0) -> Optional[str]:
        """One tick from raw window burns: applies the both-windows
        rule above and returns ``"tighten"``/``"relax"`` when the
        factor moved, None on hold (including hold-at-floor and
        hold-at-ceiling — a clamped no-move emits no action, so a
        pinned limiter does not journal every tick)."""
        tighten = fast_burn >= threshold and slow_burn >= threshold
        relax = fast_burn < threshold
        return self.step(tighten, relax)

    def step(self, tighten: bool, relax: bool) -> Optional[str]:
        """The decision half, pre-digested signals (the control plane
        combines several objectives into one tighten/relax pair before
        stepping).  ``tighten`` wins when both are set."""
        if tighten:
            new = max(self.floor, self.factor * self.backoff)
            if new < self.factor:
                self.factor = new
                return TIGHTEN
            return None
        if relax and self.factor < 1.0:
            self.factor = min(1.0, self.factor + self.recover_step)
            return RELAX
        return None
