"""Weight emitter: renders ``fleet.shares`` into load-balancer
configuration, turning advisory shares into enforced routing.

Until this module the shares were hand-templated into
``examples/lb-healthz.conf`` and went stale the moment a host joined,
drained, or decayed its capacity.  The emitter closes that gap two
ways, both driven from the control plane's tick off the live roster:

- **file render** (``control.weights_path``): the current weights are
  rendered (haproxy ``server`` stanzas or an nginx ``upstream`` block)
  and atomically rewritten (tmp + rename, the roster-journal idiom)
  whenever they change.  Pair with the LB's config-reload hook, or
  pull one-shot renders from a bastion with
  ``tools/fleetctl.py weights <host> --render haproxy|nginx``.
- **haproxy runtime API** (``control.haproxy_socket``): ``set weight
  <backend>/r<rank> <w>`` commands are pushed over the stats socket on
  every change — live rebalancing with no reload at all.

Weight mapping: a routable (joining/active — the healthz-200 set)
host's share is scaled to an integer weight in [1, 256] (haproxy's
native range; nginx treats it as a plain ratio).  Non-routable hosts
render at weight 0 (haproxy: the slot stays addressable for runtime
updates) or ``down`` (nginx) so the 200/503 routability contract and
the rendered config never disagree.

Failures are contained: an unwritable path or a dead socket counts
``control_emit_errors``-adjacent stderr noise but never raises into
the control tick — the LB keeps its last applied weights, the same
frozen-at-last-applied philosophy the controller itself follows.
"""

from __future__ import annotations

import os
import socket
import sys
import tempfile
from typing import Dict, List, Optional

ROUTABLE_STATES = ("joining", "active")
MAX_WEIGHT = 256


def scaled_weights(roster: List[dict]) -> Dict[int, int]:
    """rank -> integer LB weight.  Routable hosts get their share
    scaled into [1, 256]; everyone else gets 0."""
    routable = [p for p in roster if p.get("state") in ROUTABLE_STATES]
    top = max((float(p.get("share", 0.0)) for p in routable),
              default=0.0)
    out: Dict[int, int] = {}
    for p in roster:
        rank = int(p["rank"])
        if p.get("state") not in ROUTABLE_STATES or top <= 0:
            out[rank] = 0
            continue
        share = float(p.get("share", 0.0))
        out[rank] = max(1, min(MAX_WEIGHT,
                               round(share / top * MAX_WEIGHT)))
    return out


def ingest_addr(fleet_addr: str, ingest_port: int) -> str:
    """Map a peer's fleet (health) address to its ingest listener —
    same host, the configured ingest port.  With ``ingest_port = 0``
    the fleet address is used as-is (tests that point the roster
    straight at listeners)."""
    host = fleet_addr.rsplit(":", 1)[0] if ":" in fleet_addr else fleet_addr
    return f"{host}:{ingest_port}" if ingest_port > 0 else fleet_addr


def render_haproxy(roster: List[dict], backend: str = "flowgger",
                   ingest_port: int = 0) -> str:
    """haproxy ``server`` stanzas (drop into the backend, or reload a
    mapped file).  Weight 0 keeps a non-routable host's slot present
    so runtime-API pushes address a stable name set."""
    weights = scaled_weights(roster)
    lines = [f"# backend {backend} — rendered from fleet.shares; do "
             "not hand-edit"]
    for p in sorted(roster, key=lambda p: int(p["rank"])):
        rank = int(p["rank"])
        addr = ingest_addr(str(p["addr"]), ingest_port)
        lines.append(f"server r{rank} {addr} weight {weights[rank]} "
                     f"check  # state={p.get('state')}")
    return "\n".join(lines) + "\n"


def render_nginx(roster: List[dict], backend: str = "flowgger",
                 ingest_port: int = 0) -> str:
    """An nginx ``upstream`` block (stream or http context)."""
    weights = scaled_weights(roster)
    lines = [f"upstream {backend} {{",
             "    # rendered from fleet.shares; do not hand-edit"]
    for p in sorted(roster, key=lambda p: int(p["rank"])):
        rank = int(p["rank"])
        addr = ingest_addr(str(p["addr"]), ingest_port)
        if weights[rank] > 0:
            lines.append(f"    server {addr} "
                         f"weight={weights[rank]};  # r{rank} "
                         f"{p.get('state')}")
        else:
            lines.append(f"    server {addr} down;  # r{rank} "
                         f"{p.get('state')}")
    lines.append("}")
    return "\n".join(lines) + "\n"


def render(roster: List[dict], fmt: str, backend: str = "flowgger",
           ingest_port: int = 0) -> str:
    if fmt == "nginx":
        return render_nginx(roster, backend, ingest_port)
    return render_haproxy(roster, backend, ingest_port)


def runtime_commands(roster: List[dict], backend: str = "flowgger"
                     ) -> List[str]:
    """haproxy runtime-API command per host (stats socket)."""
    weights = scaled_weights(roster)
    return [f"set weight {backend}/r{rank} {weights[rank]}"
            for rank in sorted(weights)]


class WeightEmitter:
    """Change-driven emitter the control plane ticks: renders to the
    weights file and/or pushes runtime commands when (and only when)
    the rendered weights differ from the last applied set."""

    def __init__(self, path: Optional[str] = None,
                 fmt: str = "haproxy", backend: str = "flowgger",
                 ingest_port: int = 0,
                 haproxy_socket: Optional[str] = None):
        self.path = path
        self.fmt = fmt
        self.backend = backend
        self.ingest_port = ingest_port
        self.haproxy_socket = haproxy_socket
        self._last: Optional[Dict[int, int]] = None
        self.renders = 0
        self.pushes = 0

    def update(self, roster: List[dict]) -> bool:
        """Apply the roster's weights if they changed.  Returns True
        when something was rendered/pushed."""
        weights = scaled_weights(roster)
        if weights == self._last:
            return False
        if self.path is not None:
            try:
                self._write_atomic(
                    render(roster, self.fmt, self.backend,
                           self.ingest_port))
                self.renders += 1
            except OSError as e:
                print(f"control: weights render to {self.path} failed "
                      f"({e}); LB keeps its last applied weights",
                      file=sys.stderr)
                return False
        if self.haproxy_socket is not None:
            if not self._push_runtime(runtime_commands(roster,
                                                       self.backend)):
                return False
            self.pushes += 1
        self._last = weights
        return True

    def _write_atomic(self, text: str) -> None:
        dirname = os.path.dirname(os.path.abspath(self.path))
        fd, tmp = tempfile.mkstemp(prefix=".weights-", dir=dirname)
        try:
            with os.fdopen(fd, "w") as f:
                f.write(text)
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def _push_runtime(self, commands: List[str]) -> bool:
        try:
            with socket.socket(socket.AF_UNIX,
                               socket.SOCK_STREAM) as sock:
                sock.settimeout(2.0)
                sock.connect(self.haproxy_socket)
                sock.sendall(("; ".join(commands) + "\n").encode())
                sock.recv(4096)  # drain the reply, errors included
            return True
        except OSError as e:
            print(f"control: haproxy runtime push to "
                  f"{self.haproxy_socket} failed ({e}); LB keeps its "
                  "last applied weights", file=sys.stderr)
            return False
