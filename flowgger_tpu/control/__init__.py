"""Feedback control: the loop-closing layer over the observability
plane.

PR 14-16 made the fleet *observable* — capacity-weighted shares,
per-tenant/per-route SLO burn rates, spill backlog — but nothing
*acted* on those signals: shares were advisory LB hints and a burning
SLO only journaled while the flooder kept flooding.  This package
turns the signals into enforcement, three loops, each individually
gated under the ``[control]`` config table and **off by default**:

1. **Burn-driven admission** (:mod:`.aimd`, :mod:`.plane`): sustained
   per-tenant ``slo_burn`` multiplicatively tightens that tenant's
   token-bucket rates at the existing admission layer; recovery is
   additive once the burn clears (AIMD, the TCP congestion-control
   shape).  A misbehaving tenant is throttled at its own bucket before
   the weighted-fair queue has to shed fleet-wide.
2. **Share feedback**: sustained host-level burn (or breaker-open /
   spill-backlog pressure) decays the host's advertised
   ``tpu_fleet_capacity`` weight, so a degrading host gives up traffic
   *before* it trips breakers.  The decayed weight rides the existing
   heartbeat doc, so every peer's ``fleet.shares`` reflects it with no
   added protocol — and the shares become *enforced* through the
   weight emitter (:mod:`.emitter`: haproxy runtime-API / nginx
   upstream renders) or the built-in steering proxy
   (``fleet/proxy.py``) for deployments with no external LB.
3. **Autoscale signal**: a desired-routable-host count derived from
   fleet burn + queue headroom + spill backlog, exported as the
   ``fleet_desired_hosts`` gauge and the ``/fleetz`` ``control``
   section for compose/k8s layers to consume.

Failure philosophy: **frozen-at-last-applied**.  A dead controller
(crash, ``control_freeze`` drill, plain ``stop()``) leaves tightened
rates and a decayed capacity weight exactly where the last live tick
put them — never reset-to-open, because a controller that fails open
un-throttles a flood at the worst possible moment.  Recovery resumes
when ticks resume.

With no ``[control]`` table the package is inert by construction:
``ControlPlane.from_config`` returns ``None``, the pipeline keeps its
pre-control objects, zero threads start, and the admission hot path is
byte-for-byte the PR 13 code path.
"""

from .aimd import AimdLimiter                      # noqa: F401
from .plane import ControlPlane, desired_hosts     # noqa: F401
from .spec import ControlSpec, control_spec        # noqa: F401
