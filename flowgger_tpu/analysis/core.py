"""flowcheck engine: file discovery, suppressions, rule registry, runner.

Everything here is pure ``ast`` + stdlib so the checker can run in CI
environments (and pre-commit hooks) without the JAX toolchain — the same
Python 3.10/tomli floor the pipeline itself supports.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# directories never scanned (tooling, build output, reference corpora);
# tests/ is excluded from the *per-file* rule scan — it is the oracle
# layer the invariants are checked against, and FC03 reads it separately
# through Project.test_files
EXCLUDED_DIRS = {
    ".git", "__pycache__", ".pytest_cache", ".mypy_cache", ".ruff_cache",
    "node_modules", "native", "tools", "examples",
}
EXCLUDED_FILES = {"bench.py"}

_SUPPRESS_RE = re.compile(
    r"#\s*flowcheck:\s*disable=([A-Za-z0-9_,\s]+?)"
    r"(?:\s*--\s*(?P<reason>.*))?\s*$")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a location.

    Baseline identity is ``(rule, path, message)`` — line numbers drift
    with unrelated edits, so they are reported but not matched on.
    """

    rule: str
    path: str          # posix-style path relative to the scan root
    line: int
    col: int
    message: str

    @property
    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


class Suppressions:
    """Per-line ``# flowcheck: disable=RULE[,RULE] [-- reason]`` map.

    A trailing comment covers its own line; a comment alone on a line
    covers the next line holding code (so a suppression can sit above a
    long statement without breaking line length).
    """

    def __init__(self, source: str):
        self._rules_by_line: Dict[int, Set[str]] = {}
        lines = source.splitlines()
        for idx, text in enumerate(lines):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = {r.strip().upper() for r in m.group(1).split(",")
                     if r.strip()}
            lineno = idx + 1
            self._rules_by_line.setdefault(lineno, set()).update(rules)
            if text.lstrip().startswith("#"):
                # standalone comment: also covers the next code line
                for j in range(idx + 1, len(lines)):
                    nxt = lines[j].strip()
                    if nxt and not nxt.startswith("#"):
                        self._rules_by_line.setdefault(j + 1, set()).update(
                            rules)
                        break

    def covers(self, line: int, rule: str) -> bool:
        rules = self._rules_by_line.get(line)
        return rules is not None and (rule in rules or "ALL" in rules)


@dataclass
class Module:
    """One parsed source file under the scan root."""

    path: str                    # absolute
    rel: str                     # posix relpath from the scan root
    source: str
    tree: ast.Module
    suppressions: Suppressions

    @property
    def name(self) -> str:
        return os.path.splitext(os.path.basename(self.rel))[0]


@dataclass
class Project:
    """The scan root plus every parsed module and the test tree."""

    root: str
    modules: List[Module] = field(default_factory=list)
    test_files: List[str] = field(default_factory=list)  # rel posix paths
    _parse_cache: Dict[str, Optional[ast.Module]] = field(
        default_factory=dict, repr=False)

    def parse(self, rel: str) -> Optional[ast.Module]:
        """AST of any file under the root (cached); None if unreadable."""
        if rel not in self._parse_cache:
            try:
                with open(os.path.join(self.root, rel), "r",
                          encoding="utf-8") as fd:
                    self._parse_cache[rel] = ast.parse(fd.read())
            except (OSError, SyntaxError, ValueError):
                self._parse_cache[rel] = None
        return self._parse_cache[rel]

    def exists(self, rel: str) -> bool:
        return os.path.exists(os.path.join(self.root, rel))


class Rule:
    """Base class for flowcheck rules.

    Subclasses register with ``@register`` and implement ``check``
    (per-module) and/or ``check_project`` (whole-tree rules like FC03 /
    FC05).  ``scope`` filters which files a per-module rule sees.
    """

    id: str = "FC00"
    title: str = ""

    def scope(self, rel: str) -> bool:
        return True

    def check(self, module: Module, project: Project) -> Iterable[Finding]:
        return ()

    def check_project(self, project: Project) -> Iterable[Finding]:
        return ()


_REGISTRY: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and register a Rule by its id."""
    rule = cls()
    _REGISTRY[rule.id] = rule
    return cls


def all_rules() -> Dict[str, Rule]:
    _load_rules()
    return dict(sorted(_REGISTRY.items()))


def _load_rules() -> None:
    # import-for-effect: each rule module registers itself
    from .rules import (  # noqa: F401
        fc01_trace,
        fc02_threads,
        fc03_oracle,
        fc04_exceptions,
        fc05_configkeys,
        fc06_metrics,
        fc07_lockdiscipline,
        fc08_events,
        fc09_faultsites,
        fc10_lifecycle,
    )


# -- discovery ---------------------------------------------------------------

def _iter_py_files(root: str) -> Iterable[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames
            if d not in EXCLUDED_DIRS and not d.startswith("."))
        for fn in sorted(filenames):
            if fn.endswith(".py") and fn not in EXCLUDED_FILES:
                yield os.path.join(dirpath, fn)


def _relposix(path: str, root: str) -> str:
    return os.path.relpath(path, root).replace(os.sep, "/")


def load_project(root: str) -> Project:
    """Parse every scannable file under ``root`` into a Project.

    ``tests/`` (outside ``tests/fixtures``) is catalogued for the
    cross-reference rules but excluded from the per-file scan; files
    that fail to parse are skipped (a syntax error is the compiler's
    finding, not ours).
    """
    root = os.path.abspath(root)
    project = Project(root=root)
    for path in _iter_py_files(root):
        rel = _relposix(path, root)
        parts = rel.split("/")
        if "tests" in parts:
            if "fixtures" not in parts:
                project.test_files.append(rel)
            continue
        try:
            with open(path, "r", encoding="utf-8") as fd:
                source = fd.read()
            tree = ast.parse(source)
        except (OSError, SyntaxError, ValueError):
            continue
        project.modules.append(Module(
            path=path, rel=rel, source=source, tree=tree,
            suppressions=Suppressions(source)))
    return project


# -- runner ------------------------------------------------------------------

@dataclass
class CheckResult:
    findings: List[Finding]          # active (non-suppressed, non-baselined)
    baselined: List[Finding]
    suppressed_count: int
    project: Project
    # baseline entries (key -> leftover count) no visible finding consumed.
    # Meaningful only on a FULL run (all rules, no path filter) — a subset
    # run cannot tell "fixed" from "not checked"; run_check leaves this
    # empty for partial runs.
    stale_baseline: Dict[Tuple[str, str, str], int] = field(
        default_factory=dict)


def run_check(root: str, rule_ids: Optional[Sequence[str]] = None,
              baseline_keys: Optional[Dict[Tuple[str, str, str], int]] = None,
              only_paths: Optional[Set[str]] = None,
              ) -> CheckResult:
    """Run the (selected) rules over ``root`` and partition the findings
    into active / baselined, dropping suppressed ones.

    ``only_paths`` (rel posix paths) is the incremental pre-commit mode:
    per-module rules run only on those files, and cross-module rules
    still see the whole tree (their invariants are global) but report
    only findings landing in the filtered set.  Stale-baseline detection
    is skipped for any partial run — a rule subset or path filter cannot
    distinguish a fixed finding from an unchecked one.
    """
    rules = all_rules()
    if rule_ids is not None:
        unknown = [r for r in rule_ids if r not in rules]
        if unknown:
            raise KeyError(f"unknown rule id(s): {', '.join(unknown)}")
        rules = {rid: rules[rid] for rid in rule_ids}
    project = load_project(root)
    raw: List[Finding] = []
    suppress_map = {m.rel: m.suppressions for m in project.modules}
    for rule in rules.values():
        for module in project.modules:
            if only_paths is not None and module.rel not in only_paths:
                continue
            if rule.scope(module.rel):
                raw.extend(rule.check(module, project))
        raw.extend(rule.check_project(project))
    if only_paths is not None:
        raw = [f for f in raw if f.path in only_paths]

    suppressed = 0
    visible: List[Finding] = []
    for f in raw:
        sup = suppress_map.get(f.path)
        if sup is not None and sup.covers(f.line, f.rule):
            suppressed += 1
        else:
            visible.append(f)
    visible.sort(key=lambda f: (f.path, f.line, f.rule, f.message))

    remaining = dict(baseline_keys or {})
    active: List[Finding] = []
    baselined: List[Finding] = []
    for f in visible:
        if remaining.get(f.key, 0) > 0:
            remaining[f.key] -= 1
            baselined.append(f)
        else:
            active.append(f)
    full_run = rule_ids is None and only_paths is None
    stale = {k: n for k, n in remaining.items() if n > 0} if full_run else {}
    return CheckResult(findings=active, baselined=baselined,
                       suppressed_count=suppressed, project=project,
                       stale_baseline=stale)


# -- shared AST helpers ------------------------------------------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
