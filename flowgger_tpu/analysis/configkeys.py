"""Derive the config-key namespace from ``config.lookup*`` call sites.

``lint.py`` used to hand-maintain ``KNOWN_KEYS`` — a list that drifted
the moment anyone added a lookup without updating it (``metrics.jsonl``
sat in it for two PRs; it was never a key, it was the *example value* of
``metrics.path``).  This module walks the package source with ``ast``
and derives the namespace from what the code actually reads:

- a literal first argument to ``.lookup`` / ``.lookup_str`` /
  ``.lookup_int`` / ``.lookup_float`` / ``.lookup_bool`` is a known key;
- a literal first argument to ``.lookup_table`` is a free-form table
  (user-defined sub-keys: ltsv_schema, *_extra, faults);
- calls through registered *forwarders* — helpers that build key paths
  from a literal prefix argument — expand to the keys the helper reads
  (``retry_config_kwargs(config, "output.kafka")`` reads the three
  ``output.kafka_retry_*`` keys; a ``tcp_config_parse(config)`` call
  reads its default ``threads_key``, ``input.tcp_threads``, and a
  literal ``threads_key=`` argument would be picked up the same way).

Any other non-literal lookup path is *underivable*; flowcheck FC05
flags it so the namespace stays machine-checkable.  ``lint.py`` imports
``derived_namespace`` instead of a hand-written set, which makes this
class of drift structurally impossible.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .core import dotted_name, literal_str

LOOKUP_METHODS = {
    "lookup", "lookup_str", "lookup_int", "lookup_float", "lookup_bool",
}
TABLE_METHODS = {"lookup_table"}

# helpers whose non-literal lookup paths are derived from their call
# sites instead: name -> (prefix argument index, suffixes added to the
# literal prefix; None = the prefix IS the key)
RETRY_SUFFIXES = ("_retry_init", "_retry_max", "_retry_attempts")
FORWARDERS: Dict[str, Tuple[int, Optional[Tuple[str, ...]]]] = {
    "retry_config_kwargs": (1, RETRY_SUFFIXES),
    "policy_from_config": (1, RETRY_SUFFIXES),
    "tcp_config_parse": (1, None),
}
# keyword spelling of each forwarder's prefix argument
_FORWARDER_KW = {"retry_config_kwargs": "prefix", "policy_from_config": "prefix",
                 "tcp_config_parse": "threads_key"}
# a forwarder called without its prefix argument uses its default
_FORWARDER_DEFAULT = {"tcp_config_parse": "input.tcp_threads"}


@dataclass
class DerivedNamespace:
    keys: Set[str] = field(default_factory=set)
    free_tables: Set[str] = field(default_factory=set)
    # (rel, line, enclosing function name) of lookups whose path is not
    # a string literal and whose enclosing function is not a forwarder
    dynamic_sites: List[Tuple[str, int, str]] = field(default_factory=list)
    # key -> first (rel, line) that reads it, for FC05 diagnostics
    read_sites: Dict[str, Tuple[str, int]] = field(default_factory=dict)


def _forwarder_prefix(call: ast.Call, name: str) -> Optional[str]:
    idx, _ = FORWARDERS[name]
    if len(call.args) > idx:
        return literal_str(call.args[idx])
    kw_name = _FORWARDER_KW[name]
    for kw in call.keywords:
        if kw.arg == kw_name:
            return literal_str(kw.value)
    return _FORWARDER_DEFAULT.get(name)


def scan_tree(tree: ast.Module, rel: str, ns: DerivedNamespace) -> None:
    """Accumulate one file's lookup/forwarder sites into ``ns``."""
    # enclosing-function names, for the forwarder exemption
    func_of: Dict[ast.AST, str] = {}

    def annotate(node: ast.AST, fname: str) -> None:
        for child in ast.iter_child_nodes(node):
            inner = fname
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                inner = child.name
            func_of[child] = inner
            annotate(child, inner)

    annotate(tree, "<module>")

    def record(key: str, line: int) -> None:
        ns.keys.add(key)
        ns.read_sites.setdefault(key, (rel, line))

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        # config.lookup*("dotted.key", ...)
        if isinstance(func, ast.Attribute) and (
                func.attr in LOOKUP_METHODS or func.attr in TABLE_METHODS):
            if not node.args:
                continue
            key = literal_str(node.args[0])
            if key is None:
                fname = func_of.get(node, "<module>")
                if (fname not in FORWARDERS
                        and fname not in LOOKUP_METHODS
                        and fname not in TABLE_METHODS):
                    # the Config.lookup_* wrappers themselves and
                    # registered forwarders are the two places a
                    # variable path is expected
                    ns.dynamic_sites.append((rel, node.lineno, fname))
                continue
            if func.attr in TABLE_METHODS:
                ns.free_tables.add(key)
                ns.read_sites.setdefault(key, (rel, node.lineno))
            else:
                record(key, node.lineno)
            continue
        # forwarder(config, "literal.prefix", ...)
        callee = dotted_name(func)
        short = callee.rsplit(".", 1)[-1] if callee else None
        if short in FORWARDERS:
            prefix = _forwarder_prefix(node, short)
            if prefix is None:
                # a forwarder delegating to another forwarder with its
                # own (variable) prefix resolves at ITS call sites
                fname = func_of.get(node, "<module>")
                if fname not in FORWARDERS:
                    ns.dynamic_sites.append((rel, node.lineno, fname))
                continue
            _, suffixes = FORWARDERS[short]
            if suffixes is None:
                record(prefix, node.lineno)
            else:
                for suffix in suffixes:
                    record(prefix + suffix, node.lineno)


def namespace_from_sources(files: List[Tuple[str, ast.Module]]
                           ) -> DerivedNamespace:
    ns = DerivedNamespace()
    for rel, tree in files:
        scan_tree(tree, rel, ns)
    return ns


_CACHE: Dict[str, DerivedNamespace] = {}


def derived_namespace(package_root: Optional[str] = None) -> DerivedNamespace:
    """Namespace read from the ``flowgger_tpu`` package source (cached).

    Default root: the installed package directory itself — ``lint.py``
    calls this with no argument, so ``--check`` validates configs
    against whatever keys *this* build of the code actually reads.
    """
    if package_root is None:
        package_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    package_root = os.path.abspath(package_root)
    if package_root in _CACHE:
        return _CACHE[package_root]
    files: List[Tuple[str, ast.Module]] = []
    for dirpath, dirnames, filenames in os.walk(package_root):
        dirnames[:] = sorted(d for d in dirnames
                             if d != "__pycache__" and d != "analysis"
                             and not d.startswith("."))
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            try:
                with open(path, "r", encoding="utf-8") as fd:
                    tree = ast.parse(fd.read())
            except (OSError, SyntaxError, ValueError):
                continue
            files.append((os.path.relpath(path, package_root), tree))
    ns = namespace_from_sources(files)
    _CACHE[package_root] = ns
    return ns
