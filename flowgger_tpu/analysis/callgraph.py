"""Shared module-local call-graph machinery for the cross-module
contract rules (FC07–FC10).

The concurrency/degradation rules all reason the same way: "from this
site, following calls that resolve *module-locally* (a bare ``name(...)``
or ``self.method(...)`` / ``obj.method(...)`` whose method name a
function in the same file defines), what is reachable?"  That closure is
deliberately not a real type analysis — it is the same first-definition-
wins name resolution FC02 uses, which matches this tree's convention of
unique helper names per module and keeps the checker pure ``ast``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Optional, Set

from .core import dotted_name


def callable_name(node: ast.AST) -> Optional[str]:
    """The local function name a callable expression refers to: a bare
    Name, or the method name of ``self.method`` / ``obj.method``."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def receiver_terminal(func: ast.Attribute) -> Optional[str]:
    """Terminal name of a call receiver: ``_events.emit`` → ``_events``;
    ``self._sink.write`` → ``_sink``; ``mod.journal.emit`` →
    ``journal``."""
    value = func.value
    if isinstance(value, ast.Name):
        return value.id
    if isinstance(value, ast.Attribute):
        return value.attr
    return None


def build_parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    """child → parent map for walking up from a found node."""
    parents: Dict[ast.AST, ast.AST] = {}
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            parents[child] = parent
    return parents


class FunctionIndex:
    """Functions/methods of one module by name (first definition wins,
    the FC02 convention) plus closure computation over them."""

    def __init__(self, tree: ast.Module):
        self.functions: Dict[str, ast.FunctionDef] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.setdefault(node.name, node)

    def resolve(self, call: ast.Call) -> Optional[str]:
        """Module-local callee name of a call, or None."""
        name = callable_name(call.func)
        return name if name in self.functions else None

    def closure(self, roots: Iterable[str]) -> Set[str]:
        """Transitive module-local call closure over function names."""
        seen: Set[str] = set()
        queue = [r for r in roots if r in self.functions]
        while queue:
            name = queue.pop()
            if name in seen:
                continue
            seen.add(name)
            fn = self.functions.get(name)
            if fn is None:
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    callee = self.resolve(node)
                    if callee is not None and callee not in seen:
                        queue.append(callee)
        return seen

    def calls_in(self, names: Iterable[str]) -> Iterable[ast.Call]:
        """Every Call node in the bodies of the named functions."""
        for name in names:
            fn = self.functions.get(name)
            if fn is None:
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    yield node


def walk_pruned(node: ast.AST) -> Iterable[ast.AST]:
    """``ast.walk`` that does not descend into nested function/lambda
    bodies — they run later, on some other thread's clock."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        yield child
        stack.extend(ast.iter_child_nodes(child))


def stmt_calls(stmts: Iterable[ast.stmt]) -> Iterable[ast.Call]:
    """Call nodes in a statement list, nested defs excluded."""
    for stmt in stmts:
        if isinstance(stmt, ast.Call):
            yield stmt
        for node in walk_pruned(stmt):
            if isinstance(node, ast.Call):
                yield node


def literal_strings(tree: ast.AST) -> Set[str]:
    """Every string constant anywhere in a tree (docstrings included)."""
    return {n.value for n in ast.walk(tree)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)}
