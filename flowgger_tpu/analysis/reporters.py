"""flowcheck output formats: human text, machine JSON, and SARIF 2.1.0.

SARIF is the lingua franca of code-scanning UIs (GitHub code scanning
ingests it directly); JSON is the stable shape scripts and the test
suite consume; text is for humans and CI logs.
"""

from __future__ import annotations

import json
from typing import List

from .core import CheckResult, Finding, all_rules

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def render_text(result: CheckResult) -> str:
    lines: List[str] = [f.render() for f in result.findings]
    lines.append(
        f"flowcheck: {len(result.findings)} finding(s), "
        f"{len(result.baselined)} baselined, "
        f"{result.suppressed_count} suppressed, "
        f"{len(result.project.modules)} file(s) scanned")
    return "\n".join(lines)


def _finding_dict(f: Finding) -> dict:
    return {"rule": f.rule, "path": f.path, "line": f.line, "col": f.col,
            "message": f.message}


def render_json(result: CheckResult) -> str:
    payload = {
        "tool": "flowcheck",
        "root": result.project.root,
        "findings": [_finding_dict(f) for f in result.findings],
        "baselined": [_finding_dict(f) for f in result.baselined],
        "counts": {
            "findings": len(result.findings),
            "baselined": len(result.baselined),
            "suppressed": result.suppressed_count,
            "files_scanned": len(result.project.modules),
        },
    }
    return json.dumps(payload, indent=2, sort_keys=True)


def render_sarif(result: CheckResult) -> str:
    rules = [{
        "id": rule.id,
        "name": type(rule).__name__,
        "shortDescription": {"text": rule.title},
    } for rule in all_rules().values()]
    results = [{
        "ruleId": f.rule,
        "level": "error",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path},
                "region": {"startLine": f.line,
                           "startColumn": max(1, f.col + 1)},
            },
        }],
    } for f in result.findings]
    payload = {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "flowcheck",
                "informationUri":
                    "https://github.com/awslabs/flowgger",
                "rules": rules,
            }},
            "results": results,
        }],
    }
    return json.dumps(payload, indent=2, sort_keys=True)


def validate_sarif(text: str) -> List[str]:
    """Shape-check a SARIF document; returns the list of problems
    (empty = valid).  Not a full JSON-Schema validation — it asserts the
    subset GitHub code scanning (and our own tests) depend on, so ci.sh
    can fast-fail with exit 2 on a malformed upload instead of letting
    the ingester reject it minutes later."""
    problems: List[str] = []
    try:
        doc = json.loads(text)
    except ValueError as e:
        return [f"not valid JSON: {e}"]
    if not isinstance(doc, dict):
        return ["top level must be a JSON object"]
    if doc.get("version") != SARIF_VERSION:
        problems.append(f"version must be {SARIF_VERSION!r}, "
                        f"got {doc.get('version')!r}")
    if not isinstance(doc.get("$schema"), str):
        problems.append("$schema must be a string URI")
    runs = doc.get("runs")
    if not isinstance(runs, list) or not runs:
        return problems + ["runs must be a non-empty list"]
    for ri, run in enumerate(runs):
        driver = run.get("tool", {}).get("driver", {}) \
            if isinstance(run, dict) else {}
        if not isinstance(driver, dict) \
                or not isinstance(driver.get("name"), str):
            problems.append(f"runs[{ri}].tool.driver.name must be a string")
            continue
        rules = driver.get("rules", [])
        if not isinstance(rules, list) or any(
                not isinstance(r, dict) or not isinstance(r.get("id"), str)
                for r in rules):
            problems.append(f"runs[{ri}] rules must each carry a string id")
        known = {r.get("id") for r in rules if isinstance(r, dict)}
        results = run.get("results")
        if not isinstance(results, list):
            problems.append(f"runs[{ri}].results must be a list")
            continue
        for i, res in enumerate(results):
            where = f"runs[{ri}].results[{i}]"
            if not isinstance(res, dict):
                problems.append(f"{where} must be an object")
                continue
            if not isinstance(res.get("ruleId"), str):
                problems.append(f"{where}.ruleId must be a string")
            elif known and res["ruleId"] not in known:
                problems.append(f"{where}.ruleId {res['ruleId']!r} is not "
                                f"declared in the driver rules")
            msg = res.get("message")
            if not isinstance(msg, dict) \
                    or not isinstance(msg.get("text"), str):
                problems.append(f"{where}.message.text must be a string")
            locs = res.get("locations")
            if not isinstance(locs, list) or not locs:
                problems.append(f"{where}.locations must be non-empty")
                continue
            for li, loc in enumerate(locs):
                phys = loc.get("physicalLocation", {}) \
                    if isinstance(loc, dict) else {}
                art = phys.get("artifactLocation", {}) \
                    if isinstance(phys, dict) else {}
                region = phys.get("region", {}) \
                    if isinstance(phys, dict) else {}
                if not isinstance(art, dict) \
                        or not isinstance(art.get("uri"), str):
                    problems.append(f"{where}.locations[{li}] needs an "
                                    f"artifactLocation.uri string")
                start = region.get("startLine") \
                    if isinstance(region, dict) else None
                if not isinstance(start, int) or start < 1:
                    problems.append(f"{where}.locations[{li}] needs a "
                                    f"positive integer region.startLine")
    return problems


RENDERERS = {"text": render_text, "json": render_json, "sarif": render_sarif}
