"""flowcheck output formats: human text, machine JSON, and SARIF 2.1.0.

SARIF is the lingua franca of code-scanning UIs (GitHub code scanning
ingests it directly); JSON is the stable shape scripts and the test
suite consume; text is for humans and CI logs.
"""

from __future__ import annotations

import json
from typing import List

from .core import CheckResult, Finding, all_rules

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def render_text(result: CheckResult) -> str:
    lines: List[str] = [f.render() for f in result.findings]
    lines.append(
        f"flowcheck: {len(result.findings)} finding(s), "
        f"{len(result.baselined)} baselined, "
        f"{result.suppressed_count} suppressed, "
        f"{len(result.project.modules)} file(s) scanned")
    return "\n".join(lines)


def _finding_dict(f: Finding) -> dict:
    return {"rule": f.rule, "path": f.path, "line": f.line, "col": f.col,
            "message": f.message}


def render_json(result: CheckResult) -> str:
    payload = {
        "tool": "flowcheck",
        "root": result.project.root,
        "findings": [_finding_dict(f) for f in result.findings],
        "baselined": [_finding_dict(f) for f in result.baselined],
        "counts": {
            "findings": len(result.findings),
            "baselined": len(result.baselined),
            "suppressed": result.suppressed_count,
            "files_scanned": len(result.project.modules),
        },
    }
    return json.dumps(payload, indent=2, sort_keys=True)


def render_sarif(result: CheckResult) -> str:
    rules = [{
        "id": rule.id,
        "name": type(rule).__name__,
        "shortDescription": {"text": rule.title},
    } for rule in all_rules().values()]
    results = [{
        "ruleId": f.rule,
        "level": "error",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path},
                "region": {"startLine": f.line,
                           "startColumn": max(1, f.col + 1)},
            },
        }],
    } for f in result.findings]
    payload = {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "flowcheck",
                "informationUri":
                    "https://github.com/awslabs/flowgger",
                "rules": rules,
            }},
            "results": results,
        }],
    }
    return json.dumps(payload, indent=2, sort_keys=True)


RENDERERS = {"text": render_text, "json": render_json, "sarif": render_sarif}
