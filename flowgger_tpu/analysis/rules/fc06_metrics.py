"""FC06 — metric-name discipline.

``Registry.inc``/``set_gauge``/``add_seconds``/``observe`` mint
counters on first use: a typo'd name silently creates a dead series
and the real one stays flat — the class of bug no test notices until a
graph is empty mid-incident.  This rule resolves **every literal name
passed to a registry call** against the namespace the metrics module
declares:

- the declared literal tuples in any scanned ``metrics.py`` defining
  ``_COUNTERS``: ``_COUNTERS``, ``_SECONDS_NAMES``, ``_GAUGE_NAMES``,
  ``_HISTOGRAM_NAMES``;
- the registered family patterns (``_FAMILY_PATTERNS``), where each
  ``{placeholder}`` matches one ``[A-Za-z0-9_]+`` segment — so the
  literal ``"aot_rejects_missing_route"`` resolves via
  ``"aot_rejects_{reason}"``;
- dynamic families a module declares in its **docstring** as a
  backticked ``name_{var}``-shaped token (the escape hatch for
  families minted far from metrics.py).

Call sites are recognized by method name (``inc``, ``set_gauge``,
``init_gauge``, ``add_seconds``, ``observe``, ``get``, ``get_gauge``)
AND receiver spelling (``_metrics``/``registry``/``reg``/… — the
conventional registry aliases), so ``dict.get("key")`` or an
economics tracker's ``observe("framing", …)`` never false-positive.
Non-literal names (f-strings, variables) are out of scope here: they
are the families the patterns declare.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional, Set, Tuple

from ..core import Finding, Module, Project, Rule, register

# methods of utils.metrics.Registry that take a metric name first
_METHODS = frozenset({"inc", "set_gauge", "init_gauge", "add_seconds",
                      "observe", "get", "get_gauge"})

# receiver spellings that mean "the metrics registry" across the tree
_RECEIVERS = frozenset({"_metrics", "metrics", "registry", "reg",
                        "_reg", "_global_registry", "_registry"})

_DECL_TUPLES = ("_COUNTERS", "_SECONDS_NAMES", "_GAUGE_NAMES",
                "_HISTOGRAM_NAMES")

_PLACEHOLDER = re.compile(r"\{[A-Za-z0-9_]+\}")
_DOC_PATTERN = re.compile(r"``([a-z0-9_]*\{[a-z0-9_]+\}[a-z0-9_{}]*)``")


def _literal_str_tuple(tree: ast.Module, name: str) -> Optional[Set[str]]:
    """String elements of a module-level ``NAME = (...)`` tuple/list/
    set assignment; None when absent."""
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == name
                   for t in node.targets):
            continue
        if isinstance(node.value, (ast.Tuple, ast.List, ast.Set)):
            return {el.value for el in node.value.elts
                    if isinstance(el, ast.Constant)
                    and isinstance(el.value, str)}
        return None
    return None


def _pattern_regex(pattern: str):
    """``lane{i}_route_{path}_spr`` → compiled fullmatch regex with one
    ``[A-Za-z0-9_]+`` segment per placeholder."""
    out, pos = [], 0
    for m in _PLACEHOLDER.finditer(pattern):
        out.append(re.escape(pattern[pos:m.start()]))
        out.append(r"[A-Za-z0-9_]+")
        pos = m.end()
    out.append(re.escape(pattern[pos:]))
    return re.compile("".join(out) + r"\Z")


def _receiver_name(func: ast.Attribute) -> Optional[str]:
    """Terminal name of the call receiver: ``_metrics.inc`` →
    ``_metrics``; ``self._registry.inc`` → ``_registry``;
    ``mod.registry.inc`` → ``registry``."""
    value = func.value
    if isinstance(value, ast.Name):
        return value.id
    if isinstance(value, ast.Attribute):
        return value.attr
    if isinstance(value, ast.Call):
        # reg = _metrics() pattern inlined: _metrics().inc(...)
        f = value.func
        if isinstance(f, ast.Name):
            return f.id
        if isinstance(f, ast.Attribute):
            return f.attr
    return None


def _docstring_patterns(tree: ast.Module) -> List[str]:
    doc = ast.get_docstring(tree) or ""
    return _DOC_PATTERN.findall(doc)


@register
class MetricNameDiscipline(Rule):
    id = "FC06"
    title = "metric-name discipline (literal registry names must be declared)"

    def check_project(self, project: Project) -> Iterable[Finding]:
        declared, patterns = self._namespace(project)
        if declared is None:
            # no metrics declaration module under this root: nothing
            # to resolve against (fixture projects without metrics.py)
            return []
        for module in project.modules:
            patterns = patterns + [
                _pattern_regex(p) for p in _docstring_patterns(module.tree)]
        findings: List[Finding] = []
        for module in project.modules:
            for name, line, col in self._literal_sites(module.tree):
                if name in declared:
                    continue
                if any(rx.match(name) for rx in patterns):
                    continue
                findings.append(Finding(
                    self.id, module.rel, line, col,
                    f"metric name '{name}' resolves against neither the "
                    f"declared tuples (_COUNTERS/_SECONDS_NAMES/"
                    f"_GAUGE_NAMES/_HISTOGRAM_NAMES) nor a registered "
                    f"family pattern — a typo here mints a silent dead "
                    f"series; declare it in utils/metrics.py or fix the "
                    f"spelling"))
        return findings

    def _namespace(self, project: Project
                   ) -> Tuple[Optional[Set[str]], list]:
        """(declared literal names, compiled family regexes) from the
        scanned metrics declaration module (a ``metrics.py`` defining
        ``_COUNTERS``)."""
        for module in project.modules:
            if module.rel.rsplit("/", 1)[-1] != "metrics.py":
                continue
            counters = _literal_str_tuple(module.tree, "_COUNTERS")
            if counters is None:
                continue
            declared = set(counters)
            for tup in _DECL_TUPLES[1:]:
                declared |= _literal_str_tuple(module.tree, tup) or set()
            fams = _literal_str_tuple(module.tree, "_FAMILY_PATTERNS") \
                or set()
            return declared, [_pattern_regex(p) for p in sorted(fams)]
        return None, []

    def _literal_sites(self, tree: ast.Module):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute) \
                    or func.attr not in _METHODS:
                continue
            if _receiver_name(func) not in _RECEIVERS:
                continue
            if not node.args:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) \
                    and isinstance(first.value, str):
                yield first.value, node.lineno, node.col_offset
