"""FC01 — trace-safety of jit/Pallas kernel entry points.

A jitted function is traced once per input signature; anything
impure that runs during tracing is baked in (wall clocks, RNG draws) or
forces a host round-trip (``.item()``, ``.tolist()``), and a Python
branch on a *traced* value either crashes or — worse — silently
retraces per value, which is exactly the recompile cliff that drops the
decode path off the >=50M lines/sec target (cf. simdjson's branch-free
hot-path discipline).

The rule finds jit roots in a module (``@jax.jit`` /
``@partial(jax.jit, static_argnames=...)`` decorators, ``f =
jax.jit(g)`` assignments, kernels handed to ``pl.pallas_call``),
computes the module-local call-graph closure under them, and flags:

- wall-clock reads (``time.time/monotonic/perf_counter/...``) and
  ``time.sleep``;
- Python/numpy RNG (``random.*``, ``np.random.*``) — device RNG via
  ``jax.random`` keys is fine;
- host synchronization: ``.item()``, ``.tolist()``,
  ``.block_until_ready()``;
- I/O: ``open()``, ``print()``, ``input()``;
- tracer-dependent branching: an ``if``/``while``/``assert`` in a jit
  root whose test reads a parameter not listed in ``static_argnames``
  (``x.shape``/``x.ndim``/``x.dtype``, ``len(x)``, ``x is None`` and
  ``isinstance`` checks are static and exempt).

Reachability is module-local by construction: kernels in this tree are
self-contained per module (device_*/encode_* import only jnp/lax), so
cross-module reachability would add noise, not coverage.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import Finding, Module, Project, Rule, dotted_name, register

_CLOCK_CALLS = {
    "time.time", "time.monotonic", "time.perf_counter", "time.time_ns",
    "time.monotonic_ns", "time.perf_counter_ns", "time.process_time",
    "time.sleep", "datetime.datetime.now", "datetime.datetime.utcnow",
}
_RNG_PREFIXES = ("random.", "np.random.", "numpy.random.")
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_IO_CALLS = {"open", "print", "input"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}


def _jit_target(call: ast.Call) -> bool:
    """Is this call expression ``jax.jit(...)`` / ``jit(...)`` or a
    ``partial(jax.jit, ...)`` wrapping?"""
    name = dotted_name(call.func)
    if name in ("jax.jit", "jit"):
        return True
    if name in ("partial", "functools.partial") and call.args:
        inner = dotted_name(call.args[0])
        return inner in ("jax.jit", "jit")
    return False


def _static_argnames(call: ast.Call) -> Set[str]:
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg in ("static_argnames", "static_argnums"):
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    names.add(el.value)
    return names


class _ModuleIndex:
    """Module-level functions, jit roots, and the call-graph closure."""

    def __init__(self, tree: ast.Module):
        self.functions: Dict[str, ast.FunctionDef] = {}
        self.roots: Dict[str, Set[str]] = {}  # func name -> static args
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
                for deco in node.decorator_list:
                    if isinstance(deco, ast.Call) and _jit_target(deco):
                        self.roots[node.name] = _static_argnames(deco)
                    elif dotted_name(deco) in ("jax.jit", "jit"):
                        self.roots[node.name] = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name in ("jax.jit", "jit") and node.args:
                target = dotted_name(node.args[0])
                if target in self.functions:
                    self.roots.setdefault(target, _static_argnames(node))
            elif name in ("pl.pallas_call", "pallas_call") and node.args:
                target = dotted_name(node.args[0])
                if target in self.functions:
                    self.roots.setdefault(target, set())

    def reachable(self) -> Dict[str, Tuple[str, Optional[Set[str]]]]:
        """name -> (root it is reachable from, static args if it IS a
        root).  BFS over module-local ``Name`` references (covers plain
        calls and functions passed to ``lax.scan``/``while_loop``)."""
        out: Dict[str, Tuple[str, Optional[Set[str]]]] = {}
        queue = [(name, name) for name in self.roots]
        while queue:
            name, root = queue.pop()
            if name in out:
                continue
            out[name] = (root, self.roots.get(name))
            fn = self.functions.get(name)
            if fn is None:
                continue
            for node in ast.walk(fn):
                if (isinstance(node, ast.Name)
                        and isinstance(node.ctx, ast.Load)
                        and node.id in self.functions
                        and node.id not in out):
                    queue.append((node.id, root))
        return out


def _param_names(fn: ast.FunctionDef) -> Set[str]:
    args = fn.args
    names = {a.arg for a in args.posonlyargs + args.args + args.kwonlyargs}
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    return names


def _traced_names_in_test(test: ast.AST, traced: Set[str]) -> Set[str]:
    """Parameter names the test actually *reads as values* — skipping
    static accessors (``.shape``/``.ndim``/``.dtype``/``len``),
    identity-vs-None checks, and ``isinstance``."""
    if isinstance(test, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
        return set()
    hits: Set[str] = set()
    skip: Set[int] = set()
    for node in ast.walk(test):
        if id(node) in skip:
            continue
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            for sub in ast.walk(node.value):
                skip.add(id(sub))
        elif isinstance(node, ast.Call):
            callee = dotted_name(node.func)
            if callee in ("len", "isinstance", "getattr", "hasattr"):
                for sub in ast.walk(node):
                    skip.add(id(sub))
    for node in ast.walk(test):
        if (id(node) not in skip and isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load) and node.id in traced):
            hits.add(node.id)
    return hits


@register
class TraceSafety(Rule):
    id = "FC01"
    title = "trace-safety of jit/Pallas entry points"

    def check(self, module: Module, project: Project) -> Iterable[Finding]:
        index = _ModuleIndex(module.tree)
        if not index.roots:
            return []
        findings: List[Finding] = []

        def flag(node: ast.AST, root: str, what: str) -> None:
            findings.append(Finding(
                self.id, module.rel, node.lineno, node.col_offset,
                f"{what} inside code reachable from jit entry point "
                f"'{root}'"))

        for name, (root, statics) in index.reachable().items():
            fn = index.functions.get(name)
            if fn is None:
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    callee = dotted_name(node.func)
                    if callee in _CLOCK_CALLS:
                        flag(node, root, f"wall-clock call {callee}()")
                    elif callee and callee.startswith(_RNG_PREFIXES):
                        flag(node, root, f"host RNG call {callee}()")
                    elif callee in _IO_CALLS:
                        flag(node, root, f"I/O call {callee}()")
                    elif (isinstance(node.func, ast.Attribute)
                          and node.func.attr in _SYNC_METHODS
                          and not node.args):
                        flag(node, root,
                             f"host sync .{node.func.attr}()")
            if statics is None:
                continue  # helper: branch tests use its own locals
            traced = _param_names(fn) - statics
            for node in ast.walk(fn):
                if isinstance(node, (ast.If, ast.While)):
                    test = node.test
                elif isinstance(node, ast.Assert):
                    test = node.test
                elif isinstance(node, ast.IfExp):
                    test = node.test
                else:
                    continue
                hit = _traced_names_in_test(test, traced)
                if hit:
                    kind = type(node).__name__.lower()
                    findings.append(Finding(
                        self.id, module.rel, node.lineno, node.col_offset,
                        f"Python {kind} on traced value(s) "
                        f"{', '.join(sorted(hit))} in jit entry point "
                        f"'{name}' (make it static_argnames or use "
                        f"jnp.where/lax.cond)"))
        return findings
