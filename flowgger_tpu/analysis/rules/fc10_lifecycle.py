"""FC10 — thread & resource lifecycle.

The PR 6 bug class: a thread (or fd/socket) created and *dropped* — no
handle, no join, no close — so drain can't wait for it and nothing
bounds how many pile up.  Two contracts, both resolved with the same
parent-chain classification:

1. **Threads.**  Every ``threading.Thread(...)`` construction and every
   ``*.spawn(...)`` start site must leave a reachable stop/join path:

   - stored as instance state (``self._thread = ...``): some code in
     the module must ``join`` that attribute — the stop/drain method
     owns the lifecycle;
   - bound to a local: the local must be *used* beyond starting it
     (returned to a caller who owns it, joined, stored in a container
     or attribute, passed along) — ``t.start()`` alone is
     fire-and-forget with extra steps;
   - returned or passed as an argument directly: the receiver owns it —
     covered;
   - ``threading.Thread(...).start()`` as a bare statement: no handle
     exists, nothing can ever join it — flagged.

2. **Resources.**  Every ``open()`` / ``socket.socket()`` /
   ``socket.create_connection()`` / ``socket.create_server()`` result
   stored as instance state must have a ``close`` on that attribute
   somewhere in the module (or be managed by a ``with``) — an fd held
   on ``self`` with no close path leaks one descriptor per object for
   the life of the process.

Deliberately fire-and-forget threads (a drain-announce wave that must
not block an HTTP reply, a compile worker that must outlive its caller)
carry reasoned inline suppressions — the rule makes the *decision*
visible, not impossible.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

from ..callgraph import build_parents, receiver_terminal
from ..core import Finding, Module, Project, Rule, dotted_name, register

# parent nodes the classification sees through: a thread inside a list/
# tuple/comprehension/conditional is still the same thread
_TRANSPARENT = (ast.List, ast.Tuple, ast.Set, ast.ListComp, ast.SetComp,
                ast.GeneratorExp, ast.IfExp, ast.Starred, ast.Await,
                ast.NamedExpr)

# loads of a thread local that do NOT count as lifecycle ownership
_NEUTRAL_ATTRS = frozenset({"start", "is_alive", "daemon", "name",
                            "ident", "setDaemon", "setName"})

_RESOURCE_DOTTED = frozenset({"socket.socket", "socket.create_connection",
                              "socket.create_server"})


def _is_thread_ctor(call: ast.Call) -> bool:
    callee = dotted_name(call.func)
    return callee is not None and callee.split(".")[-1] == "Thread"


def _is_spawn(call: ast.Call) -> bool:
    return isinstance(call.func, ast.Attribute) and call.func.attr == "spawn"


def _is_resource_ctor(call: ast.Call) -> bool:
    func = call.func
    if isinstance(func, ast.Name) and func.id == "open":
        return True
    callee = dotted_name(func)
    return callee in _RESOURCE_DOTTED


def _self_attr(target: ast.AST) -> Optional[str]:
    """``self.A`` / ``cls.A`` target → ``A``."""
    if isinstance(target, ast.Attribute) \
            and isinstance(target.value, ast.Name) \
            and target.value.id in ("self", "cls"):
        return target.attr
    return None


@register
class ThreadResourceLifecycle(Rule):
    id = "FC10"
    title = ("thread/resource lifecycle (every thread start has a join "
             "path, every instance-state fd has a close path)")

    def check(self, module: Module, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        parents = build_parents(module.tree)
        joined = self._attrs_with(module.tree, "join")
        closed = self._attrs_with(module.tree, "close") \
            | self._with_managed(module.tree)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_thread_ctor(node) or _is_spawn(node):
                self._check_thread(node, parents, joined, module, findings)
            elif _is_resource_ctor(node):
                self._check_resource(node, parents, closed, module,
                                     findings)
        return findings

    # -- evidence ----------------------------------------------------------
    @staticmethod
    def _attrs_with(tree: ast.Module, method: str) -> Set[str]:
        """Attribute names X for which ``<...>.X.<method>(...)`` (or a
        bare ``X.<method>(...)``) appears anywhere in the module."""
        out: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == method:
                recv = receiver_terminal(node.func)
                if recv is not None:
                    out.add(recv)
        return out

    @staticmethod
    def _with_managed(tree: ast.Module) -> Set[str]:
        """Attribute names used as a ``with`` context — the runtime
        closes those."""
        out: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    name = dotted_name(item.context_expr)
                    if name is not None:
                        out.add(name.split(".")[-1])
        return out

    # -- threads -----------------------------------------------------------
    def _check_thread(self, call: ast.Call, parents, joined: Set[str],
                      module: Module, findings: List[Finding]) -> None:
        node: ast.AST = call
        parent = parents.get(node)
        while isinstance(parent, _TRANSPARENT):
            node, parent = parent, parents.get(parent)
        if isinstance(parent, (ast.Return, ast.Yield, ast.YieldFrom)):
            return  # the caller owns the handle
        if isinstance(parent, ast.Call) and node is not parent.func:
            return  # passed as an argument: the callee owns it
        if isinstance(parent, (ast.Assign, ast.AnnAssign)):
            targets = parent.targets if isinstance(parent, ast.Assign) \
                else [parent.target]
            for target in targets:
                attr = _self_attr(target)
                if attr is not None:
                    if attr not in joined:
                        findings.append(Finding(
                            self.id, module.rel, call.lineno,
                            call.col_offset,
                            f"thread stored as 'self.{attr}' is never "
                            f"joined anywhere in this module — the "
                            f"stop/drain path cannot wait for it; join "
                            f"it in stop()"))
                    return
                if isinstance(target, ast.Name):
                    if not self._local_owned(target.id, parent, parents):
                        findings.append(Finding(
                            self.id, module.rel, call.lineno,
                            call.col_offset,
                            f"thread local '{target.id}' is only "
                            f"started, never joined/stored/returned — "
                            f"fire-and-forget with a handle nobody "
                            f"keeps; tie it to a join path or drop the "
                            f"variable deliberately"))
                    return
                # subscript / tuple-unpack target: stored in a
                # container the enclosing code tracks — covered
                return
            return
        if isinstance(parent, ast.Attribute) and parent.attr == "start":
            grand = parents.get(parents.get(parent))
            if isinstance(grand, ast.Expr):
                findings.append(Finding(
                    self.id, module.rel, call.lineno, call.col_offset,
                    "thread is constructed and started with no handle "
                    "kept — nothing can ever join it on the drain "
                    "path; keep the handle (and reap finished ones) or "
                    "suppress with the reason it may outlive drain"))
            return
        if isinstance(parent, ast.Expr):
            findings.append(Finding(
                self.id, module.rel, call.lineno, call.col_offset,
                "thread is constructed and discarded — it is never "
                "even started; dead code or a missing .start()"))

    def _local_owned(self, name: str, assign: ast.AST, parents) -> bool:
        """Is a thread-holding local used beyond lifecycle-neutral
        calls inside its enclosing function?"""
        fn = assign
        while fn is not None and not isinstance(
                fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
            fn = parents.get(fn)
        if fn is None:
            return True  # can't scope it: stay silent
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Name) and node.id == name
                    and isinstance(node.ctx, ast.Load)):
                continue
            parent = parents.get(node)
            if isinstance(parent, ast.Attribute) \
                    and parent.attr in _NEUTRAL_ATTRS:
                continue
            return True  # returned, joined, stored, passed along…
        return False

    # -- resources ---------------------------------------------------------
    def _check_resource(self, call: ast.Call, parents, closed: Set[str],
                        module: Module, findings: List[Finding]) -> None:
        node: ast.AST = call
        parent = parents.get(node)
        while isinstance(parent, _TRANSPARENT):
            node, parent = parent, parents.get(parent)
        if not isinstance(parent, (ast.Assign, ast.AnnAssign)):
            return  # locals and with-statements are FC02/CPython's turf
        targets = parent.targets if isinstance(parent, ast.Assign) \
            else [parent.target]
        for target in targets:
            attr = _self_attr(target)
            if attr is not None and attr not in closed:
                findings.append(Finding(
                    self.id, module.rel, call.lineno, call.col_offset,
                    f"fd/socket stored as 'self.{attr}' has no close "
                    f"anywhere in this module — one descriptor leaks "
                    f"per object for the life of the process; close it "
                    f"on the drain/stop path"))
