"""FC05 — config-key drift between ``lint.py`` and the code.

``--check`` validates user configs against a known-key namespace; that
namespace is only worth anything if it matches the keys the code
actually reads.  This rule derives the read-namespace from every
``config.lookup*`` call site (``analysis.configkeys``) and checks it
against the declaration module (any scanned ``lint.py``):

- a **literal** ``KNOWN_KEYS`` set (the pre-reconcile shape) is diffed
  both ways: keys read but undeclared, and keys declared but never
  read, are findings;
- a literal ``DECLARED_ONLY`` set (the post-reconcile escape hatch for
  keys read through paths the AST cannot see) must not contain keys
  that ARE derivable — a redundant entry is drift waiting to happen;
- every lookup whose key path is not a string literal must sit inside
  a registered forwarder (``configkeys.FORWARDERS``); anything else
  makes the namespace underivable and is flagged at the call site.

``lint.py`` importing ``derived_namespace()`` (instead of hand-writing
the set) is what makes the drift structurally impossible; this rule is
the CI tripwire for the parts that stay hand-written.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from ..configkeys import DerivedNamespace, namespace_from_sources
from ..core import Finding, Module, Project, Rule, register


def _literal_str_set(tree: ast.Module, name: str) -> Optional[Set[str]]:
    """The literal string elements of ``NAME = {...}`` / frozenset({...})
    at module level, or None when no such assignment exists."""
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == name
                   for t in node.targets):
            continue
        value = node.value
        if (isinstance(value, ast.Call)
                and getattr(value.func, "id", None) == "frozenset"):
            value = value.args[0] if value.args else ast.Set(elts=[])
        if isinstance(value, (ast.Set, ast.Tuple, ast.List)):
            out = set()
            for el in value.elts:
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    out.add(el.value)
            return out
        # computed (e.g. derived_namespace() union) — not a literal set,
        # so there is nothing to diff against
        return None
    return None


@register
class ConfigKeyDrift(Rule):
    id = "FC05"
    title = "config-key drift (lint.py namespace vs lookup call sites)"

    def check_project(self, project: Project) -> Iterable[Finding]:
        lint_mod = None
        for module in project.modules:
            if module.rel.rsplit("/", 1)[-1] == "lint.py":
                lint_mod = module
                break
        sources = [(m.rel, m.tree) for m in project.modules
                   if "analysis" not in m.rel.split("/")]
        ns = namespace_from_sources(sources)
        findings: List[Finding] = []
        findings.extend(self._dynamic_site_findings(ns))
        if lint_mod is not None:
            findings.extend(self._lint_findings(lint_mod, ns))
        return findings

    def _dynamic_site_findings(self, ns: DerivedNamespace) -> List[Finding]:
        out = []
        for rel, line, fname in ns.dynamic_sites:
            out.append(Finding(
                self.id, rel, line, 0,
                f"config lookup with a non-literal key path in "
                f"'{fname}' — use a literal, or register the helper in "
                f"analysis.configkeys.FORWARDERS so the namespace stays "
                f"derivable"))
        return out

    def _lint_findings(self, lint_mod: Module,
                       ns: DerivedNamespace) -> List[Finding]:
        findings: List[Finding] = []
        known = _literal_str_set(lint_mod.tree, "KNOWN_KEYS")
        free = _literal_str_set(lint_mod.tree, "FREE_TABLES") or set()
        declared_only = _literal_str_set(lint_mod.tree, "DECLARED_ONLY")
        if known is not None:
            # pre-reconcile shape: hand-maintained set, diff both ways
            for key in sorted(ns.keys - known):
                rel, line = ns.read_sites.get(key, (lint_mod.rel, 1))
                findings.append(Finding(
                    self.id, rel, line, 0,
                    f"config key '{key}' is read here but not declared "
                    f"in lint.py KNOWN_KEYS"))
            for key in sorted(known - ns.keys):
                findings.append(Finding(
                    self.id, lint_mod.rel, 1, 0,
                    f"config key '{key}' is declared in KNOWN_KEYS but "
                    f"never read by any lookup site (dead key?)"))
            for table in sorted(ns.free_tables - free - known):
                findings.append(Finding(
                    self.id, lint_mod.rel, 1, 0,
                    f"free-form table '{table}' is read via lookup_table "
                    f"but not declared in FREE_TABLES"))
        if declared_only:
            for key in sorted(declared_only & ns.keys):
                findings.append(Finding(
                    self.id, lint_mod.rel, 1, 0,
                    f"DECLARED_ONLY entry '{key}' is derivable from the "
                    f"lookup sites — remove the redundant declaration"))
        return findings
