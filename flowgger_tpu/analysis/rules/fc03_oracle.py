"""FC03 — the byte-identity contract of device/columnar encode routes.

Every accelerated route in this tree is only allowed to exist because a
scalar oracle produces the *same bytes* at lower throughput (BASELINE.md
seals the format surface; the breaker and every degradation path rely on
the swap being invisible).  That contract has two halves, and both must
be declared where the kernel lives so the checker — and the next reader
— can verify them:

- ``SCALAR_ORACLE = "flowgger_tpu.encoders.gelf:GelfEncoder"`` — the
  scalar counterpart this module must stay byte-identical to.  The
  module path must exist in the tree and export the named attribute.
- ``DIFF_TEST = "tests/test_x.py::test_fn"`` (a string or tuple of
  strings) — the differential test(s) that enforce the contract.  The
  file must exist and define the named test function.

Applies to ``tpu/device_*.py``, ``tpu/encode_*_block.py``,
``tpu/fused_*.py`` (the fused decode→encode route tier carries the
same byte-identity obligation as the split kernels it composes),
``tpu/aot.py`` (an AOT-loaded exported program replaces a jit compile
at dispatch — the swap must be byte-invisible, so the loader carries
the contract too), and ``tpu/framing.py`` (device-resident framing
replaces the host splitters — its oracle is the host split/scan
itself).  ``device_common.py`` is shared kernel
infrastructure (segment engine, compile watchdog) with no route of
its own and is exempt.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Iterable, List, Optional, Tuple

from ..core import Finding, Module, Project, Rule, register

_PATTERNS = ("*tpu/device_*.py", "*tpu/encode_*_block.py",
             "*tpu/fused_*.py", "*tpu/aot.py", "*tpu/framing.py",
             "*tpu/pallas_kernels.py",
             "tpu/device_*.py", "tpu/encode_*_block.py",
             "tpu/fused_*.py", "tpu/aot.py", "tpu/framing.py",
             "tpu/pallas_kernels.py")
_EXEMPT_BASENAMES = {"device_common.py"}


def _in_scope(rel: str) -> bool:
    base = rel.rsplit("/", 1)[-1]
    if base in _EXEMPT_BASENAMES:
        return False
    return any(fnmatch.fnmatch(rel, pat) for pat in _PATTERNS)


def _module_const(tree: ast.Module, name: str) -> Optional[ast.AST]:
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Name) and target.id == name:
                    return node.value
        elif isinstance(node, ast.AnnAssign):
            if (isinstance(node.target, ast.Name)
                    and node.target.id == name):
                return node.value
    return None


def _str_values(node: Optional[ast.AST]) -> List[str]:
    if node is None:
        return []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.append(el.value)
        return out
    return []


def _defines(tree: ast.Module, attr: str) -> bool:
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)) and node.name == attr:
            return True
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Name) and target.id == attr:
                    return True
    return False


@register
class ByteIdentityContract(Rule):
    id = "FC03"
    title = "byte-identity contract (scalar oracle + differential test)"

    def check_project(self, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        for module in project.modules:
            if _in_scope(module.rel):
                findings.extend(self._check_module(module, project))
        return findings

    def _check_module(self, module: Module,
                      project: Project) -> List[Finding]:
        findings: List[Finding] = []

        def flag(message: str, line: int = 1) -> None:
            findings.append(Finding(self.id, module.rel, line, 0, message))

        oracle = _module_const(module.tree, "SCALAR_ORACLE")
        oracle_strs = _str_values(oracle)
        if not oracle_strs:
            flag("device/block-encode module does not register its "
                 "scalar oracle (add SCALAR_ORACLE = "
                 '"pkg.module:Attr")')
        else:
            self._check_oracle(oracle_strs[0], module, project, flag)

        tests = _str_values(_module_const(module.tree, "DIFF_TEST"))
        if not tests:
            flag("device/block-encode module does not register a "
                 "differential test (add DIFF_TEST = "
                 '"tests/test_x.py::test_fn")')
        for ref in tests:
            self._check_test_ref(ref, project, flag)
        return findings

    def _check_oracle(self, spec: str, module: Module, project: Project,
                      flag) -> None:
        mod_path, _, attr = spec.partition(":")
        rel = mod_path.replace(".", "/") + ".py"
        if not project.exists(rel):
            flag(f"SCALAR_ORACLE module '{mod_path}' does not resolve to "
                 f"a file in the tree ({rel})")
            return
        if attr:
            tree = project.parse(rel)
            if tree is not None and not _defines(tree, attr):
                flag(f"SCALAR_ORACLE '{spec}': module '{mod_path}' does "
                     f"not define '{attr}'")

    def _check_test_ref(self, ref: str, project: Project, flag) -> None:
        path, _, func = ref.partition("::")
        if not project.exists(path):
            flag(f"DIFF_TEST '{ref}': test file '{path}' does not exist")
            return
        if not func:
            flag(f"DIFF_TEST '{ref}' must name a test function "
                 f"(file.py::test_fn)")
            return
        tree = project.parse(path)
        if tree is None:
            flag(f"DIFF_TEST '{ref}': test file '{path}' is unparseable")
            return
        names = {n.name for n in ast.walk(tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        if func not in names:
            flag(f"DIFF_TEST '{ref}': '{path}' does not define "
                 f"'{func}'")
