"""FC02 — thread discipline in the supervised pipeline.

The supervisor/breaker/queue layer (PR 2) runs a dozen threads over
shared mutable state.  Two invariants keep that sane, and both are
checkable from the AST:

1. **Guarded read-modify-write.**  An augmented assignment to an
   attribute (``self.count += 1`` and friends) from a function that
   runs on its own thread — a ``threading.Thread``/``Timer`` target, a
   ``Supervisor.spawn``/``spawn_worker`` worker, or anything those call
   module-locally — must sit inside a ``with <...lock...>:`` block.
   Unshared counters belong in locals; shared ones belong behind a lock
   or in ``utils.metrics`` (whose registry takes its own lock).
   Plain stores are deliberately not flagged: a GIL-atomic flag write
   (``self.open_failed = True``) is a legitimate publication idiom, the
   lost-update hazard is specific to read-modify-write.

2. **No blocking call while holding a lock.**  Inside any ``with``
   whose context expression names a lock, calls that can block
   indefinitely (queue ``get``/``put``, socket ``recv``/``accept``/
   ``connect``/``send*``, ``time.sleep``, ``Thread.join``,
   ``subprocess.run``) turn every other thread contending on that lock
   into a convoy — the exact wedge class the bounded-queue layer
   exists to avoid.  ``Condition.wait`` is exempt (it releases the
   lock); so is ``dict.get(key)`` (only zero-argument ``.get()`` —
   the queue signature — is considered blocking).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from ..core import Finding, Module, Project, Rule, dotted_name, register

_BLOCKING_ATTRS = {
    "join", "recv", "recvfrom", "recv_into", "accept", "connect",
    "sendall", "send", "put",
}
_BLOCKING_CALLS = {
    "time.sleep", "subprocess.run", "subprocess.check_call",
    "subprocess.check_output", "select.select",
}
_SPAWN_FUNCS = {"spawn_worker"}
_SPAWN_METHODS = {"spawn"}


def _lockish(expr: ast.AST) -> bool:
    name = dotted_name(expr) or ""
    if "lock" in name.lower():
        return True
    # threading.Lock()/RLock() constructed inline
    if isinstance(expr, ast.Call):
        inner = dotted_name(expr.func) or ""
        return inner.split(".")[-1] in ("Lock", "RLock")
    return False


def _callable_name(node: ast.AST) -> Optional[str]:
    """Local function name a callable expression refers to: a bare
    Name, ``self.method``, ``obj.method`` (method name), or the
    function(s) a ``lambda`` body calls."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


class _Index(ast.NodeVisitor):
    """Functions/methods by name plus the set of thread-target names."""

    def __init__(self, tree: ast.Module):
        self.functions: Dict[str, ast.FunctionDef] = {}
        self.targets: Set[str] = set()
        self._collect_defs(tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                self._scan_call(node)

    def _collect_defs(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # first definition wins; names are unique enough per module
                self.functions.setdefault(node.name, node)

    def _add_target(self, expr: ast.AST) -> None:
        if isinstance(expr, ast.Lambda):
            for sub in ast.walk(expr.body):
                if isinstance(sub, ast.Call):
                    name = _callable_name(sub.func)
                    if name in self.functions:
                        self.targets.add(name)
            return
        name = _callable_name(expr)
        if name in self.functions:
            self.targets.add(name)

    def _scan_call(self, call: ast.Call) -> None:
        callee = dotted_name(call.func) or ""
        short = callee.split(".")[-1]
        if short == "Thread":
            for kw in call.keywords:
                if kw.arg == "target":
                    self._add_target(kw.value)
        elif short == "Timer":
            if len(call.args) >= 2:
                self._add_target(call.args[1])
        elif short in _SPAWN_FUNCS and call.args:
            self._add_target(call.args[0])
        elif (isinstance(call.func, ast.Attribute)
              and call.func.attr in _SPAWN_METHODS and call.args):
            self._add_target(call.args[0])

    def thread_reachable(self) -> Set[str]:
        """Module-local call-graph closure under the thread targets."""
        seen: Set[str] = set()
        queue = list(self.targets)
        while queue:
            name = queue.pop()
            if name in seen:
                continue
            seen.add(name)
            fn = self.functions.get(name)
            if fn is None:
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    callee = _callable_name(node.func)
                    if callee in self.functions and callee not in seen:
                        queue.append(callee)
        return seen


def _with_lock_lines(fn: ast.FunctionDef) -> Set[int]:
    """Line numbers covered by a lock-guarded ``with`` inside ``fn``
    (nested function bodies excluded — they run later, elsewhere)."""
    lines: Set[int] = set()

    def visit(node: ast.AST, in_nested: bool) -> None:
        for child in ast.iter_child_nodes(node):
            nested = in_nested or isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda))
            if (not in_nested and isinstance(child, ast.With)
                    and any(_lockish(item.context_expr)
                            for item in child.items)):
                end = getattr(child, "end_lineno", child.lineno)
                lines.update(range(child.lineno, end + 1))
            visit(child, nested)

    visit(fn, False)
    return lines


@register
class ThreadDiscipline(Rule):
    id = "FC02"
    title = "thread discipline (guarded counters, no blocking under locks)"

    def check(self, module: Module, project: Project) -> Iterable[Finding]:
        index = _Index(module.tree)
        findings: List[Finding] = []

        # (1) unguarded attribute read-modify-write on thread paths
        for name in index.thread_reachable():
            fn = index.functions.get(name)
            if fn is None:
                continue
            guarded = _with_lock_lines(fn)
            for node in ast.walk(fn):
                if (isinstance(node, ast.AugAssign)
                        and isinstance(node.target, ast.Attribute)
                        and node.lineno not in guarded):
                    target = dotted_name(node.target) or node.target.attr
                    findings.append(Finding(
                        self.id, module.rel, node.lineno, node.col_offset,
                        f"unguarded read-modify-write of shared attribute "
                        f"'{target}' in thread-target '{name}' (guard with "
                        f"a lock or use utils.metrics counters)"))

        # (2) blocking calls while holding a lock — any function
        for fn in index.functions.values():
            self._check_lock_bodies(fn, module, findings)
        return findings

    def _check_lock_bodies(self, fn: ast.FunctionDef, module: Module,
                           findings: List[Finding]) -> None:
        def visit(node: ast.AST, holding: bool) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child,
                              (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
                    continue  # separate execution context
                hold = holding
                if isinstance(child, ast.With) and any(
                        _lockish(item.context_expr)
                        for item in child.items):
                    hold = True
                if holding and isinstance(child, ast.Call):
                    self._flag_blocking(child, fn, module, findings)
                visit(child, hold)

        visit(fn, False)

    def _flag_blocking(self, call: ast.Call, fn: ast.FunctionDef,
                       module: Module, findings: List[Finding]) -> None:
        callee = dotted_name(call.func)
        blocked = None
        if callee in _BLOCKING_CALLS:
            blocked = f"{callee}()"
        elif isinstance(call.func, ast.Attribute):
            attr = call.func.attr
            if attr in _BLOCKING_ATTRS:
                blocked = f".{attr}()"
            elif attr == "get" and not call.args:
                # zero-arg .get() is the queue signature; dict.get(key)
                # always has arguments
                blocked = ".get()"
        if blocked:
            findings.append(Finding(
                self.id, module.rel, call.lineno, call.col_offset,
                f"blocking call {blocked} while holding a lock in "
                f"'{fn.name}'"))
