"""FC09 — fault-site & chaos coverage.

The robustness family's whole value is that every choke point the
pipeline can fail at is *drilled*: a `faultinject` site nobody arms is a
decline rung that has never fired outside production.  Three one-way
doors this rule closes, resolved against ``utils/faultinject.py`` the
way FC03 resolves oracles:

1. **Used ⇒ registered.**  Every literal site passed to
   ``faultinject.fire`` / ``maybe_raise`` / ``set_site`` in source must
   be a member of ``KNOWN_SITES`` — ``configure_from`` hard-errors on
   unknown sites at boot, so a typo'd check site silently never fires
   and a "robustness" test passes without injecting anything.
2. **Registered ⇒ used.**  A ``KNOWN_SITES`` entry no source file ever
   checks is a dead drill — the catalog promises a choke point that no
   longer exists.
3. **Registered ⇒ documented & drilled.**  Every site must appear in
   the ``flowgger.toml`` fault catalog (the operator-facing `[faults]`
   reference) and be referenced by at least one test under ``tests/``
   or a ``tools/chaos.py`` drill — a site with no drill is untested
   failure handling.

The doc/drill halves scan raw text (a site name inside an env-style
``"spill_io=once:1"`` literal or a TOML comment both count): the
contract is *referenced somewhere an operator or CI will exercise it*,
not a specific call shape.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..callgraph import receiver_terminal
from ..core import (Finding, Module, Project, Rule, literal_str,
                    register)

_FIRE_FUNCS = frozenset({"fire", "maybe_raise", "set_site"})
_FIRE_RECEIVERS = frozenset({"faultinject", "_faults", "faults",
                             "_faultinject", "fi"})


def _site_literal(call: ast.Call) -> Optional[str]:
    """Literal site name of a fault-check call, else None."""
    func = call.func
    if isinstance(func, ast.Attribute):
        if func.attr not in _FIRE_FUNCS \
                or receiver_terminal(func) not in _FIRE_RECEIVERS:
            return None
    elif isinstance(func, ast.Name):
        if func.id not in _FIRE_FUNCS:
            return None
    else:
        return None
    if call.args:
        return literal_str(call.args[0])
    return None


def _known_sites(module: Module) -> Optional[Tuple[int, List[str]]]:
    """(lineno, sites) of the KNOWN_SITES tuple, else None."""
    for node in module.tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "KNOWN_SITES"
                for t in node.targets):
            if isinstance(node.value, (ast.Tuple, ast.List)):
                sites = [el.value for el in node.value.elts
                         if isinstance(el, ast.Constant)
                         and isinstance(el.value, str)]
                return node.lineno, sites
    return None


@register
class FaultSiteCoverage(Rule):
    id = "FC09"
    title = ("fault-site coverage (sites registered, documented in the "
             "toml catalog, and drilled by a test or chaos run)")

    def check_project(self, project: Project) -> Iterable[Finding]:
        registry = None
        for module in project.modules:
            if module.rel.endswith("faultinject.py"):
                found = _known_sites(module)
                if found is not None:
                    registry = (module, *found)
                    break
        if registry is None:
            return []
        reg_module, reg_line, sites = registry
        known = set(sites)
        findings: List[Finding] = []

        used: Set[str] = set()
        for module in project.modules:
            if module is reg_module:
                continue
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                site = _site_literal(node)
                if site is None:
                    continue
                used.add(site)
                if site not in known:
                    findings.append(Finding(
                        self.id, module.rel, node.lineno, node.col_offset,
                        f"fault site '{site}' is not registered in "
                        f"faultinject.KNOWN_SITES — configure_from "
                        f"rejects it, so no plan can ever arm this "
                        f"check; register it or fix the spelling"))

        toml_text = self._read(project, "flowgger.toml")
        drill_text = self._drill_text(project)
        for site in sites:
            if site not in used:
                findings.append(Finding(
                    self.id, reg_module.rel, reg_line, 0,
                    f"registered fault site '{site}' is never checked "
                    f"by any source file — dead drill; drop it from "
                    f"KNOWN_SITES or wire the choke point"))
                continue
            if toml_text is not None and site not in toml_text:
                findings.append(Finding(
                    self.id, reg_module.rel, reg_line, 0,
                    f"fault site '{site}' is missing from the "
                    f"flowgger.toml fault catalog — operators cannot "
                    f"discover the drill; document it under [faults]"))
            if drill_text and site not in drill_text:
                findings.append(Finding(
                    self.id, reg_module.rel, reg_line, 0,
                    f"fault site '{site}' is referenced by no test and "
                    f"no tools/chaos.py drill — untested failure "
                    f"handling; add a [faults]-armed test or chaos "
                    f"drill"))
        return findings

    @staticmethod
    def _read(project: Project, rel: str) -> Optional[str]:
        try:
            with open(os.path.join(project.root, rel), "r",
                      encoding="utf-8") as fd:
                return fd.read()
        except OSError:
            return None

    def _drill_text(self, project: Project) -> str:
        """Concatenated text of every test file plus the chaos tool.
        Empty string when the project has neither (fixture projects) —
        the drill check is then skipped rather than all-failing."""
        parts: List[str] = []
        for rel in project.test_files:
            text = self._read(project, rel)
            if text is not None:
                parts.append(text)
        chaos = self._read(project, "tools/chaos.py")
        if chaos is not None:
            parts.append(chaos)
        return "\n".join(parts)
