"""FC07 — lock discipline: no journal/sink/file I/O under a lock, and
no lock-ordering cycles.

The hardest review-round bugs of the obs/fleet/control PRs were all the
same two shapes, hand-fixed case by case:

1. **I/O while holding a lock.**  The degradation journal's ``emit``
   may write a JSONL sink (disk), and every ``open``/``fsync``/
   ``os.replace``/``print`` is I/O that can stall arbitrarily — doing
   any of it inside a ``with <lock>:`` region (or between
   ``lock.acquire()`` and ``lock.release()``) serializes every thread
   contending on that lock behind the disk, exactly when overload makes
   those events fire fastest.  The sanctioned pattern is
   **stage-under-lock, emit-after-release** (``fairqueue._drain_events``,
   ``federation._fleet_watch``); this rule makes it mechanical.  Helper
   calls that resolve module-locally are followed (the
   ``maybe_save → _save_locked`` shape hides the I/O one hop away), so
   the check sees through the ``*_locked`` helper convention.  FC02
   keeps ownership of queue/socket blocking calls; FC07 owns the
   journal/sink/file-I/O class.

2. **Lock-ordering cycles.**  Per module, every ``with A: ... with B:``
   nesting (direct, or through a module-locally resolved helper that
   acquires) contributes an edge A→B to the lock-acquisition graph; a
   cycle means two threads can deadlock by acquiring the same pair in
   opposite orders.  Taking a lock again while it is already held is
   the one-node cycle (flagged unless the module constructs it as an
   ``RLock``).

Lock spelling: a context expression whose terminal name contains
``lock``/``mutex``/``cond`` or is one of the ``queue.Queue`` condition
names (``not_empty``/``not_full``/``all_tasks_done`` — they wrap the
queue mutex) counts as a lock, as does an inline
``threading.Lock()``/``RLock()`` construction.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..callgraph import FunctionIndex, receiver_terminal, stmt_calls
from ..core import Finding, Module, Project, Rule, dotted_name, register

_LOCK_HINTS = ("lock", "mutex", "cond")
_LOCK_EXACT = frozenset({"not_empty", "not_full", "all_tasks_done"})

# receivers that mean "the degradation journal" / "a JSONL sink"
_EMIT_RECEIVERS = frozenset({"events", "_events", "journal", "_journal"})
_SINK_RECEIVERS = frozenset({"sink", "_sink"})

# direct file I/O: anything here under a lock convoys every contending
# thread behind the disk.  ``print`` is deliberately NOT in the set —
# stderr diagnostics on cold decline paths are pervasive and cheap; the
# contract this rule enforces is about the journal/sink/disk class.
_IO_NAME_CALLS = frozenset({"open"})
_IO_DOTTED_CALLS = frozenset({"os.fsync", "os.replace", "os.rename"})


def _lock_name(expr: ast.AST) -> Optional[str]:
    """Normalized lock identity of a with/acquire context, or None."""
    name = dotted_name(expr)
    if name is not None:
        terminal = name.split(".")[-1]
        low = terminal.lower()
        if terminal in _LOCK_EXACT or any(h in low for h in _LOCK_HINTS):
            # strip a leading self./cls. so `self._lock` and `_lock`
            # are one node in the acquisition graph
            parts = name.split(".")
            if parts[0] in ("self", "cls"):
                parts = parts[1:]
            return ".".join(parts) or terminal
    if isinstance(expr, ast.Call):
        inner = dotted_name(expr.func) or ""
        if inner.split(".")[-1] in ("Lock", "RLock"):
            return "<inline-lock>"
    return None


def _module_rlocks(tree: ast.Module) -> Set[str]:
    """Attribute/variable names assigned a ``threading.RLock()`` —
    re-acquiring those while held is legal by construction."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            callee = dotted_name(node.value.func) or ""
            if callee.split(".")[-1] == "RLock":
                for target in node.targets:
                    name = dotted_name(target)
                    if name:
                        parts = name.split(".")
                        if parts[0] in ("self", "cls"):
                            parts = parts[1:]
                        out.add(".".join(parts))
    return out


def _classify_io(call: ast.Call) -> Optional[str]:
    """Human label of a journal/sink/file I/O call, or None."""
    func = call.func
    if isinstance(func, ast.Attribute):
        recv = receiver_terminal(func)
        if func.attr == "emit" and recv in _EMIT_RECEIVERS:
            return "journal emit"
        if func.attr == "write" and recv in _SINK_RECEIVERS:
            return "sink write"
    callee = dotted_name(func)
    if callee in _IO_DOTTED_CALLS:
        return f"{callee}() file I/O"
    if isinstance(func, ast.Name) and func.id in _IO_NAME_CALLS:
        return f"{func.id}() I/O"
    return None


@register
class LockDiscipline(Rule):
    id = "FC07"
    title = ("lock discipline (no journal/sink/file I/O under locks; "
             "acyclic lock order)")

    def check(self, module: Module, project: Project) -> Iterable[Finding]:
        index = FunctionIndex(module.tree)
        findings: List[Finding] = []
        reported: Set[Tuple[int, str]] = set()
        edges: Dict[Tuple[str, str], int] = {}
        rlocks = _module_rlocks(module.tree)
        for fn in index.functions.values():
            self._walk_stmts(fn.body, (), fn.name, index, module,
                             findings, reported, edges, set())
        self._check_order(edges, rlocks, module, findings)
        return findings

    # -- lock-region walk --------------------------------------------------
    def _walk_stmts(self, stmts, held: Tuple[str, ...], holder: str,
                    index: FunctionIndex, module: Module,
                    findings: List[Finding], reported: Set,
                    edges: Dict, visiting: Set[str]) -> None:
        for stmt in stmts:
            # explicit acquire(): the held set grows for the rest of
            # this statement list (release() shrinks it)
            acq = self._acquire_name(stmt)
            if acq is not None:
                if held:
                    edges.setdefault((held[-1], acq), stmt.lineno)
                held = held + (acq,)
                continue
            rel = self._release_name(stmt)
            if rel is not None:
                held = tuple(h for h in held if h != rel)
                continue
            self._visit_stmt(stmt, held, holder, index, module,
                             findings, reported, edges, visiting)

    def _visit_stmt(self, stmt, held, holder, index, module,
                    findings, reported, edges, visiting) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # separate execution context
        if isinstance(stmt, ast.With):
            locks = [n for n in (_lock_name(item.context_expr)
                                 for item in stmt.items) if n is not None]
            new_held = held
            for lock in locks:
                if new_held:
                    edges.setdefault((new_held[-1], lock), stmt.lineno)
                new_held = new_held + (lock,)
            self._walk_stmts(stmt.body, new_held, holder, index, module,
                             findings, reported, edges, visiting)
            return
        if isinstance(stmt, (ast.If, ast.While, ast.For, ast.AsyncFor,
                             ast.Try, ast.ClassDef)):
            # compound statement: its header expression (test/iter)
            # runs under the current held set too, then each body
            # recurses with the same held set
            header = [stmt.test] if isinstance(stmt, (ast.If, ast.While)) \
                else [stmt.iter] if isinstance(stmt, (ast.For,
                                                      ast.AsyncFor)) else []
            if held:
                for call in stmt_calls(header):
                    self._check_call(call, held, holder, index, module,
                                     findings, reported, edges, visiting)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub:
                    self._walk_stmts(sub, held, holder, index, module,
                                     findings, reported, edges, visiting)
            for handler in getattr(stmt, "handlers", ()) or ():
                self._walk_stmts(handler.body, held, holder, index,
                                 module, findings, reported, edges,
                                 visiting)
            return
        if not held:
            return
        # a leaf statement under a lock: classify its calls, following
        # module-local helpers (the *_locked convention)
        for call in stmt_calls([stmt]):
            self._check_call(call, held, holder, index, module,
                             findings, reported, edges, visiting)

    def _check_call(self, call, held, holder, index, module,
                    findings, reported, edges, visiting) -> None:
        label = _classify_io(call)
        if label is not None:
            key = (call.lineno, label)
            if key not in reported:
                reported.add(key)
                findings.append(Finding(
                    self.id, module.rel, call.lineno, call.col_offset,
                    f"{label} while holding lock '{held[-1]}' in "
                    f"'{holder}' — stage under the lock, emit/write "
                    f"after release"))
            return
        callee = self._resolve_strict(call, index)
        if callee is not None and callee not in visiting:
            fn = index.functions[callee]
            self._scan_helper(fn, held, f"{holder} -> {callee}", index,
                              module, findings, reported, edges,
                              visiting | {callee})

    @staticmethod
    def _resolve_strict(call: ast.Call,
                        index: FunctionIndex) -> Optional[str]:
        """Module-local callee, restricted to the shapes that really
        mean "this file's function": a bare name or ``self.method`` /
        ``cls.method``.  Resolving ``obj.method`` by name alone would
        conflate ``self._fd.write`` with a ``write`` method defined
        here."""
        func = call.func
        if isinstance(func, ast.Name):
            name = func.id
        elif isinstance(func, ast.Attribute) \
                and isinstance(func.value, ast.Name) \
                and func.value.id in ("self", "cls"):
            name = func.attr
        else:
            return None
        return name if name in index.functions else None

    def _scan_helper(self, fn, held, holder, index, module,
                     findings, reported, edges, visiting) -> None:
        """The caller holds ``held`` while this helper runs: every I/O
        op and lock acquisition inside counts against the caller's
        lock."""
        self._walk_stmts(fn.body, held, holder, index, module,
                         findings, reported, edges, visiting)

    # -- acquire()/release() statements ------------------------------------
    def _acquire_name(self, stmt) -> Optional[str]:
        call = self._bare_call(stmt)
        if call is not None and isinstance(call.func, ast.Attribute) \
                and call.func.attr == "acquire":
            return _lock_name(call.func.value)
        return None

    def _release_name(self, stmt) -> Optional[str]:
        call = self._bare_call(stmt)
        if call is not None and isinstance(call.func, ast.Attribute) \
                and call.func.attr == "release":
            return _lock_name(call.func.value)
        return None

    @staticmethod
    def _bare_call(stmt) -> Optional[ast.Call]:
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            return stmt.value
        return None

    # -- ordering graph ----------------------------------------------------
    def _check_order(self, edges: Dict[Tuple[str, str], int],
                     rlocks: Set[str], module: Module,
                     findings: List[Finding]) -> None:
        adj: Dict[str, List[str]] = {}
        for (a, b), _line in edges.items():
            if a == "<inline-lock>" or b == "<inline-lock>":
                continue
            adj.setdefault(a, []).append(b)
        for (a, b), line in sorted(edges.items(), key=lambda kv: kv[1]):
            if a == b:
                if a not in rlocks and a != "<inline-lock>":
                    findings.append(Finding(
                        self.id, module.rel, line, 0,
                        f"lock '{a}' is acquired while already held "
                        f"(self-deadlock unless it is an RLock)"))
                continue
            # is there a path b ~> a?  then a->b closes a cycle
            if self._reaches(adj, b, a):
                findings.append(Finding(
                    self.id, module.rel, line, 0,
                    f"lock-ordering cycle: '{a}' -> '{b}' here, but "
                    f"'{b}' -> '{a}' elsewhere in this module — two "
                    f"threads taking these in opposite orders deadlock"))

    @staticmethod
    def _reaches(adj: Dict[str, List[str]], src: str, dst: str) -> bool:
        seen: Set[str] = set()
        queue = [src]
        while queue:
            node = queue.pop()
            if node == dst:
                return True
            if node in seen:
                continue
            seen.add(node)
            queue.extend(adj.get(node, ()))
        return False
