"""FC04 — exception hygiene in supervised threads, sinks, transports.

A degraded path that swallows its trigger is invisible: the stream
keeps flowing, the operator sees nothing, and the next symptom is data
loss.  The robustness layer's contract (README "Robustness and
degradation") is that every degradation is *observable* — it counts a
metric, logs to stderr, or re-raises into the supervisor.

Flagged, within the supervised/sink/transport scope (``outputs/``,
``inputs/``, ``utils/``, ``supervise.py``, ``pipeline.py``,
``tpu/breaker.py``):

- bare ``except:`` — always (it eats ``KeyboardInterrupt``/
  ``SystemExit``; catch ``Exception`` and let the supervisor see the
  rest);
- ``except BaseException`` without an unconditional re-raise;
- *silent* handlers: a body that is only ``pass``/``continue``/
  ``return``/constant assignments, with no call (metric, log, recovery)
  and no ``raise``.

Deliberate swallows (closing an fd that already failed, best-effort
teardown) stay allowed via an inline suppression **with a reason**::

    except OSError:  # flowcheck: disable=FC04 -- fd already dead; close is best-effort
        pass

Parse-layer code (decoders/encoders/materializers) is out of scope:
its ``except DecodeError: return error-value`` shape is the per-line
error contract, not a swallow.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..core import Finding, Module, Project, Rule, register

_SCOPE_DIRS = {"outputs", "inputs", "utils"}
_SCOPE_FILES = {"supervise.py", "pipeline.py", "breaker.py"}


def _has_unconditional_raise(body: List[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Raise):
            return True
    return False


def _is_silent(body: List[ast.stmt]) -> bool:
    """True when the handler body cannot possibly observe the error:
    no call, no raise — only pass/continue/break/return/assignments of
    call-free expressions."""
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Raise, ast.Call)):
                return False
    return True


@register
class ExceptionHygiene(Rule):
    id = "FC04"
    title = "exception hygiene (no swallowed errors in supervised code)"

    def scope(self, rel: str) -> bool:
        parts = rel.split("/")
        if parts[-1] in _SCOPE_FILES:
            return True
        return any(p in _SCOPE_DIRS for p in parts[:-1])

    def check(self, module: Module, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                findings.append(Finding(
                    self.id, module.rel, node.lineno, node.col_offset,
                    "bare 'except:' swallows KeyboardInterrupt/SystemExit; "
                    "catch Exception (or narrower) instead"))
                continue
            caught = [n.id for n in ast.walk(node.type)
                      if isinstance(n, ast.Name)]
            if ("BaseException" in caught
                    and not _has_unconditional_raise(node.body)):
                findings.append(Finding(
                    self.id, module.rel, node.lineno, node.col_offset,
                    "'except BaseException' without re-raise hides "
                    "interpreter shutdown; re-raise or catch Exception"))
                continue
            if _is_silent(node.body):
                exc = ast.unparse(node.type)
                findings.append(Finding(
                    self.id, module.rel, node.lineno, node.col_offset,
                    f"silent 'except {exc}' — degraded paths must count "
                    f"a metric, log, or re-raise (suppress with a reason "
                    f"if deliberate)"))
        return findings
