"""flowcheck rule plug-ins.

Each module defines one rule class decorated with ``@core.register``;
``core.all_rules()`` imports this package for effect.  Adding a rule =
adding a module here and importing it from ``core._load_rules``.
"""
