"""FC08 — degradation-event completeness.

The decline ladder's observability contract (PR 13): **every**
decline/trip/shed control-flow site journals a typed event through
``obs/events.py`` with a reason from the registered ``REASONS``
vocabulary.  An unjournaled decline is a rung an operator cannot see
fire; an unregistered reason literal would be a runtime ``ValueError``
at the worst possible moment (``emit`` rejects unknown reasons).  This
rule resolves both halves against the events module's AST, the way FC03
resolves scalar oracles:

1. **Reason vocabulary.**  Every literal reason passed to an emit call
   (``events.emit`` / ``_events.emit`` / ``journal.emit``) must be a
   member of the ``REASONS`` tuple.  A variable reason is resolved
   through literal assignments to that name in the enclosing function
   (the ``reason = "a" if cond else "b"`` idiom); literals that cannot
   be resolved are out of scope.

2. **Dead vocabulary.**  A ``REASONS`` entry no source file ever
   references is a row in the operator-facing table that can never
   fire — registered-but-unused is the same drift class as FC05's
   declared-but-never-read config keys.

3. **Decline-path completeness.**  Three mechanical site classes must
   reach an emit:

   - ``raise *Declined(...)`` / ``raise DurabilityError(...)``: the
     innermost block holding the raise must emit (directly or through a
     module-local helper), or some ``except`` handler for that
     exception type anywhere in the tree must emit — a decline that
     propagates to a journaling boundary is covered.
   - a ``_count_drop*`` / ``_count_shed*`` helper must either emit in
     its closure or **stage** into an attribute that an emitting
     function of the same module drains (the WFQ
     ``_event_buf``/``_drain_events`` stage-then-emit pattern).
   - a degradation counter bump (``inc`` of a ``*_freezes`` /
     ``*_trips`` / ``*_declines`` counter on a metrics registry) must
     have an emit on its path — in its innermost block, through the
     enclosing function's stage-then-drain buffer (the breaker holds
     its lock across ``_transition``, so it stages and a drain
     function emits after release), or, for a bump inside a
     ``_count*`` helper, at every module-local call site (the helper
     centralizes the counter; the callers own the emit).  The counter
     says *how often*, the event says *when and why*.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..callgraph import (FunctionIndex, literal_strings,
                         receiver_terminal, stmt_calls)
from ..core import Finding, Module, Project, Rule, literal_str, register

_EMIT_RECEIVERS = frozenset({"events", "_events", "journal", "_journal"})
_METRIC_RECEIVERS = frozenset({"_metrics", "metrics", "registry", "reg",
                               "_reg", "_global_registry", "_registry"})
_COUNTER_PATTERNS = ("*_freezes", "*_trips", "*_declines")
_RAISE_NAMES_EXACT = frozenset({"DurabilityError"})
_RAISE_SUFFIX = "Declined"
_COUNT_PREFIXES = ("_count_drop", "_count_shed")


def _is_emit(call: ast.Call) -> bool:
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr == "emit"
            and receiver_terminal(call.func) in _EMIT_RECEIVERS)


def _reason_node(call: ast.Call) -> Optional[ast.AST]:
    if len(call.args) >= 2:
        return call.args[1]
    for kw in call.keywords:
        if kw.arg == "reason":
            return kw.value
    return None


def _raise_name(stmt: ast.Raise) -> Optional[str]:
    exc = stmt.exc
    if isinstance(exc, ast.Call):
        exc = exc.func
    name = None
    if isinstance(exc, ast.Name):
        name = exc.id
    elif isinstance(exc, ast.Attribute):
        name = exc.attr
    if name and (name in _RAISE_NAMES_EXACT
                 or name.endswith(_RAISE_SUFFIX)):
        return name
    return None


def _degradation_counter(call: ast.Call) -> Optional[str]:
    if not (isinstance(call.func, ast.Attribute)
            and call.func.attr == "inc"
            and receiver_terminal(call.func) in _METRIC_RECEIVERS
            and call.args):
        return None
    name = literal_str(call.args[0])
    if name and any(fnmatch.fnmatch(name, p) for p in _COUNTER_PATTERNS):
        return name
    return None


@register
class DegradationEventCompleteness(Rule):
    id = "FC08"
    title = ("degradation-event completeness (every decline site "
             "journals a registered reason)")

    def check_project(self, project: Project) -> Iterable[Finding]:
        vocab = self._vocabulary(project)
        if vocab is None:
            return []
        vocab_module, vocab_line, reasons = vocab
        findings: List[Finding] = []
        used: Set[str] = set()
        emitting_handlers = self._covered_exception_names(project, reasons)
        for module in project.modules:
            if module is vocab_module:
                continue
            used |= literal_strings(module.tree) & reasons
            index = FunctionIndex(module.tree)
            self._check_vocab(module, index, reasons, findings)
            self._check_sites(module, index, reasons, emitting_handlers,
                              findings)
        for reason in sorted(reasons - used):
            findings.append(Finding(
                self.id, vocab_module.rel, vocab_line, 0,
                f"registered reason '{reason}' is never emitted by any "
                f"source file — dead vocabulary (drop it from REASONS "
                f"or wire the decline site)"))
        return findings

    # -- vocabulary --------------------------------------------------------
    def _vocabulary(self, project: Project
                    ) -> Optional[Tuple[Module, int, Set[str]]]:
        for module in project.modules:
            if not module.rel.endswith("events.py"):
                continue
            for node in module.tree.body:
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == "REASONS"
                        for t in node.targets):
                    if isinstance(node.value, (ast.Tuple, ast.List)):
                        reasons = {el.value for el in node.value.elts
                                   if isinstance(el, ast.Constant)
                                   and isinstance(el.value, str)}
                        return module, node.lineno, reasons
        return None

    def _check_vocab(self, module: Module, index: FunctionIndex,
                     reasons: Set[str], findings: List[Finding]) -> None:
        for fn in index.functions.values():
            assigns = self._literal_assigns(fn)
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call) and _is_emit(node)):
                    continue
                rnode = _reason_node(node)
                self._check_reason_node(rnode, assigns, reasons, module,
                                        node, findings)
        # module-level emits (rare, but cheap to cover)
        for stmt in module.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for call in stmt_calls([stmt]):
                if _is_emit(call):
                    self._check_reason_node(_reason_node(call), {},
                                            reasons, module, call,
                                            findings)

    def _check_reason_node(self, rnode, assigns, reasons, module, call,
                           findings) -> None:
        lit = literal_str(rnode) if rnode is not None else None
        if lit is not None:
            if lit not in reasons:
                findings.append(Finding(
                    self.id, module.rel, call.lineno, call.col_offset,
                    f"emit reason '{lit}' is not registered in the "
                    f"events REASONS vocabulary — emit() raises "
                    f"ValueError at runtime; register it (and document "
                    f"it) or fix the spelling"))
            return
        if isinstance(rnode, ast.Name):
            for value, line in assigns.get(rnode.id, ()):
                if value not in reasons:
                    findings.append(Finding(
                        self.id, module.rel, line, 0,
                        f"emit reason '{value}' (assigned to "
                        f"'{rnode.id}') is not registered in the events "
                        f"REASONS vocabulary"))

    @staticmethod
    def _literal_assigns(fn) -> Dict[str, List[Tuple[str, int]]]:
        """name → [(literal, line)] for every literal (or conditional-
        literal) assignment in the function, tuple unpacking included
        (the ``for st, reason in transitions`` idiom stays out of
        scope — those literals are checked as plain string usage)."""
        out: Dict[str, List[Tuple[str, int]]] = {}

        def note(target, value_node):
            if not isinstance(target, ast.Name):
                return
            values: List[ast.AST] = [value_node]
            if isinstance(value_node, ast.IfExp):
                values = [value_node.body, value_node.orelse]
            for v in values:
                lit = literal_str(v)
                if lit is not None:
                    out.setdefault(target.id, []).append((lit, v.lineno))

        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    note(target, node.value)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                note(node.target, node.value)
        return out

    # -- decline-path completeness ----------------------------------------
    def _covered_exception_names(self, project: Project,
                                 reasons: Set[str]) -> Set[str]:
        """Exception names some handler catches AND journals: a raise
        of one of these reaches a typed emit at the catching boundary."""
        covered: Set[str] = set()
        for module in project.modules:
            index = FunctionIndex(module.tree)
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.ExceptHandler) \
                        or node.type is None:
                    continue
                names = []
                types = node.type.elts if isinstance(
                    node.type, ast.Tuple) else [node.type]
                for t in types:
                    if isinstance(t, ast.Name):
                        names.append(t.id)
                    elif isinstance(t, ast.Attribute):
                        names.append(t.attr)
                interesting = [n for n in names
                               if n in _RAISE_NAMES_EXACT
                               or n.endswith(_RAISE_SUFFIX)]
                if not interesting:
                    continue
                if self._block_emits(node.body, index, reasons):
                    covered.update(interesting)
        return covered

    def _block_emits(self, stmts, index: FunctionIndex,
                     reasons: Set[str]) -> bool:
        """Does this statement list (following module-local helper
        calls) contain an emit with a registered — or at least
        plausible — reason?"""
        for call in stmt_calls(stmts):
            if _is_emit(call):
                return True
            callee = index.resolve(call)
            if callee is not None:
                for sub in index.calls_in(index.closure([callee])):
                    if _is_emit(sub):
                        return True
        return False

    def _check_sites(self, module: Module, index: FunctionIndex,
                     reasons: Set[str], emitting_handlers: Set[str],
                     findings: List[Finding]) -> None:
        for fn in index.functions.values():
            name = fn.name
            if any(name.startswith(p) for p in _COUNT_PREFIXES):
                if not self._counts_covered(fn, index, module):
                    findings.append(Finding(
                        self.id, module.rel, fn.lineno, fn.col_offset,
                        f"shed/drop counter helper '{name}' neither "
                        f"emits a degradation event nor stages into a "
                        f"buffer an emitting function drains — this "
                        f"decline path is invisible to the journal"))
            self._check_blocks(fn.body, fn, index, reasons,
                               emitting_handlers, module, findings)

    def _check_blocks(self, stmts, fn, index, reasons, emitting_handlers,
                      module, findings) -> None:
        block_covered: Optional[bool] = None  # lazy per statement list

        def covered() -> bool:
            nonlocal block_covered
            if block_covered is None:
                block_covered = self._block_emits(stmts, index, reasons)
            return block_covered

        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(stmt, ast.Raise):
                rname = _raise_name(stmt)
                if rname is not None and rname not in emitting_handlers \
                        and not covered():
                    findings.append(Finding(
                        self.id, module.rel, stmt.lineno, stmt.col_offset,
                        f"decline raise '{rname}' has no degradation "
                        f"event on its path: neither this block nor any "
                        f"'except {rname}' handler in the tree emits a "
                        f"typed journal event"))
            for call in stmt_calls([stmt]) \
                    if not self._is_compound(stmt) else ():
                cname = _degradation_counter(call)
                if cname is not None and not covered() \
                        and not self._bump_covered_indirectly(
                            fn, index, module, reasons):
                    findings.append(Finding(
                        self.id, module.rel, call.lineno, call.col_offset,
                        f"degradation counter '{cname}' is bumped "
                        f"without a typed journal event on its path — "
                        f"the counter says how often, the event must "
                        f"say when and why"))
            # recurse into nested blocks
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub:
                    self._check_blocks(sub, fn, index, reasons,
                                       emitting_handlers, module,
                                       findings)
            for handler in getattr(stmt, "handlers", ()) or ():
                self._check_blocks(handler.body, fn, index, reasons,
                                   emitting_handlers, module, findings)

    @staticmethod
    def _is_compound(stmt) -> bool:
        return bool(getattr(stmt, "body", None))

    def _bump_covered_indirectly(self, fn, index: FunctionIndex,
                                 module: Module, reasons: Set[str]) -> bool:
        """A counter bump with no emit in its own block is still covered
        when the *enclosing function* stages into a drained buffer (the
        breaker ``_transition`` runs under the state lock and stages
        into ``_event_buf``; ``_drain_events`` emits after release), or
        when the bump lives in a ``_count*`` helper whose every
        module-local call site emits (the helper centralizes the
        counter; the emit belongs to the caller's context)."""
        if fn is None:
            return False
        if self._counts_covered(fn, index, module):
            return True
        name = getattr(fn, "name", "")
        if name.startswith("_count"):
            return self._call_sites_emit(name, index, reasons)
        return False

    def _call_sites_emit(self, fn_name: str, index: FunctionIndex,
                         reasons: Set[str]) -> bool:
        """True iff the module calls ``fn_name`` at least once and every
        call site's innermost block emits (module-local closure)."""
        found = False
        all_covered = True

        def calls_target(call: ast.Call) -> bool:
            f = call.func
            if isinstance(f, ast.Name):
                return f.id == fn_name
            return isinstance(f, ast.Attribute) and f.attr == fn_name

        def scan(stmts) -> None:
            nonlocal found, all_covered
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue
                if not self._is_compound(stmt) and any(
                        calls_target(c) for c in stmt_calls([stmt])):
                    found = True
                    if not self._block_emits(stmts, index, reasons):
                        all_covered = False
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field, None)
                    if sub:
                        scan(sub)
                for handler in getattr(stmt, "handlers", ()) or ():
                    scan(handler.body)

        for other in index.functions.values():
            if other.name == fn_name:
                continue
            scan(other.body)
        return found and all_covered

    def _counts_covered(self, fn, index: FunctionIndex,
                        module: Module) -> bool:
        closure = index.closure([fn.name])
        for call in index.calls_in(closure):
            if _is_emit(call):
                return True
        # staging pattern: fn appends to self.<A>; an emitting function
        # of the module references <A>
        staged: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("append", "extend", "add"):
                recv = receiver_terminal(node.func)
                if recv is not None:
                    staged.add(recv)
        if not staged:
            return False
        for other in index.functions.values():
            if other.name == fn.name:
                continue
            emits = any(_is_emit(c)
                        for c in index.calls_in(index.closure([other.name])))
            if not emits:
                continue
            for node in ast.walk(other):
                if isinstance(node, ast.Attribute) and node.attr in staged:
                    return True
                if isinstance(node, ast.Name) and node.id in staged:
                    return True
        return False
