"""flowcheck: AST-based invariant checker for this repo's own contracts.

Generic linters cannot see the invariants this pipeline's correctness
actually rests on: jitted kernels must stay trace-pure or they silently
recompile off the >=50M lines/sec target, the supervisor/breaker/queue
layer shares mutable state across a dozen threads, and every device
decode/encode route is only *allowed* to exist because a scalar oracle
reproduces its bytes exactly (BASELINE.json / PAPER section 1).
``flowcheck`` encodes those invariants as a rule set over the repo's own
Python AST — the Python tier's counterpart to the ASan/TSan self-checks
the native tier already gets in ci.sh.

Rules (see ``flowcheck --list-rules`` / README "Static analysis"):

- **FC01 trace-safety** — no wall clocks, Python RNG, I/O, host syncs,
  or tracer-dependent Python branching in code reachable from a
  ``jax.jit`` / Pallas kernel entry point;
- **FC02 thread discipline** — counters mutated from thread targets are
  lock-guarded (or routed through ``utils.metrics``), and no blocking
  call is made while holding a lock;
- **FC03 byte-identity contract** — every ``tpu/device_*`` /
  ``encode_*_block`` module registers its scalar oracle
  (``SCALAR_ORACLE``) and a differential test (``DIFF_TEST``), both
  verified against the tree;
- **FC04 exception hygiene** — no bare/swallowing ``except`` in
  supervised threads, sinks, transports, or the breaker;
- **FC05 config-key drift** — the ``lint.py`` known-key namespace must
  match the ``config.lookup*`` call sites the code actually reads;
- **FC06 metric-name discipline** — every counter/gauge/histogram name
  resolves against the ``utils/metrics.py`` declarations (no typo'd
  silently-dead series);
- **FC07 lock discipline** — no journal emit / sink write / file I/O
  while holding a lock (stage-under-lock, emit-after-release), and the
  per-module lock-acquisition graph stays acyclic;
- **FC08 degradation-event completeness** — every decline/trip/shed
  site reaches a typed ``obs/events.py`` emit with a reason registered
  in the ``REASONS`` vocabulary (and no dead vocabulary);
- **FC09 fault-site coverage** — every ``utils/faultinject.py`` site is
  registered in ``KNOWN_SITES``, documented in the ``flowgger.toml``
  fault catalog, and drilled by a test or ``tools/chaos.py``;
- **FC10 thread/resource lifecycle** — every thread start leaves a
  reachable join path for drain, every instance-state fd/socket has a
  close path.

The package is deliberately dependency-free (``ast`` + stdlib only; no
JAX, no numpy) so ``python -m flowgger_tpu.analysis`` runs in seconds on
any Python >= 3.10 — CI gates on it before the test suite even starts.

Per-line suppressions: ``# flowcheck: disable=FC04 -- reason`` on the
finding's line (or alone on the line above).  Pre-existing findings can
be frozen in a committed baseline (``.flowcheck-baseline.json``,
``--write-baseline``); CI fails only on non-baselined findings.
"""

from .core import Finding, Project, Rule, all_rules, run_check  # noqa: F401

__all__ = ["Finding", "Project", "Rule", "all_rules", "run_check"]
