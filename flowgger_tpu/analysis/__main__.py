"""CLI: ``python -m flowgger_tpu.analysis [root] [options]``.

Exit codes: 0 = clean (no non-baselined findings), 1 = findings,
2 = usage/internal error (unknown rule, malformed baseline, bad root).
Pure ``ast`` + stdlib — no JAX import, so this runs in seconds and
gates CI before the test suite starts.
"""

from __future__ import annotations

import argparse
import os
import sys

from . import baseline as baseline_mod
from .core import all_rules, run_check
from .reporters import RENDERERS


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="flowcheck",
        description="AST-based invariant checker for flowgger-tpu "
                    "(trace-safety, thread discipline, byte-identity "
                    "contracts, exception hygiene, config-key drift)")
    parser.add_argument("root", nargs="?", default=".",
                        help="scan root (default: current directory)")
    parser.add_argument("--format", choices=sorted(RENDERERS),
                        default="text", help="report format")
    parser.add_argument("--rules", metavar="FC01,FC02,...",
                        help="comma-separated subset of rules to run")
    parser.add_argument("--baseline", metavar="FILE",
                        help="baseline file (default: "
                             f"<root>/{baseline_mod.DEFAULT_BASELINE} "
                             "when present)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore any baseline file")
    parser.add_argument("--write-baseline", action="store_true",
                        help="freeze current findings into the baseline "
                             "file and exit 0")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in all_rules().values():
            print(f"{rule.id}  {rule.title}")
        return 0

    root = os.path.abspath(args.root)
    if not os.path.isdir(root):
        print(f"flowcheck: scan root {args.root!r} is not a directory",
              file=sys.stderr)
        return 2

    rule_ids = None
    if args.rules:
        rule_ids = [r.strip().upper() for r in args.rules.split(",")
                    if r.strip()]

    baseline_path = args.baseline or os.path.join(
        root, baseline_mod.DEFAULT_BASELINE)
    baseline_keys = None
    if not args.no_baseline and not args.write_baseline:
        if os.path.exists(baseline_path):
            try:
                baseline_keys = baseline_mod.load(baseline_path)
            except baseline_mod.BaselineError as e:
                print(f"flowcheck: {e}", file=sys.stderr)
                return 2
        elif args.baseline:
            print(f"flowcheck: baseline {args.baseline!r} not found",
                  file=sys.stderr)
            return 2

    try:
        result = run_check(root, rule_ids=rule_ids,
                           baseline_keys=baseline_keys)
    except KeyError as e:
        print(f"flowcheck: {e.args[0]}", file=sys.stderr)
        return 2

    if args.write_baseline:
        baseline_mod.write(baseline_path, result.findings)
        print(f"flowcheck: wrote {len(result.findings)} finding(s) to "
              f"{baseline_path}")
        return 0

    print(RENDERERS[args.format](result))
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())
