"""CLI: ``python -m flowgger_tpu.analysis [root] [options]``.

Exit codes: 0 = clean (no non-baselined findings), 1 = findings (or a
stale baseline under ``--check``), 2 = usage/internal error (unknown
rule, malformed baseline, bad root, rule-count mismatch, malformed
SARIF).  Pure ``ast`` + stdlib — no JAX import, so this runs in seconds
and gates CI before the test suite starts.

Modes:

- full run (default) — every rule over the whole tree; the ci.sh gate.
  ``--check`` additionally fails on stale baseline entries: a baseline
  row no current finding consumes is a fixed finding whose tombstone
  must be deleted (zero unexplained baseline growth AND shrinkage).
- ``--changed REF`` — the pre-commit path: per-module rules run only on
  files changed vs ``REF`` (plus untracked files); cross-module rules
  still see the whole tree but report only into the changed set.
  Stale-baseline enforcement is skipped — a partial run cannot tell
  "fixed" from "not checked".
- ``--validate-sarif FILE`` — standalone shape-check of a SARIF
  document (exit 0 valid / 2 malformed), the ci.sh fast-fail before an
  upload step.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

from . import baseline as baseline_mod
from .core import all_rules, run_check
from .reporters import RENDERERS, render_sarif, validate_sarif


def _changed_paths(root: str, ref: str):
    """Rel posix paths of ``*.py`` files changed vs ``ref`` (committed,
    staged, or working-tree changes) plus untracked files.  Returns None
    when git cannot answer (not a repo, bad ref) — the caller exits 2."""
    out = []
    for cmd in (["git", "diff", "--name-only", ref, "--"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(  # noqa: S603 - fixed argv, no shell
                cmd, cwd=root, capture_output=True, text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired) as e:
            print(f"flowcheck: cannot run {' '.join(cmd)}: {e}",
                  file=sys.stderr)
            return None
        if proc.returncode != 0:
            print(f"flowcheck: {' '.join(cmd)} failed: "
                  f"{proc.stderr.strip()}", file=sys.stderr)
            return None
        out.extend(line.strip() for line in proc.stdout.splitlines())
    return {p.replace(os.sep, "/") for p in out if p.endswith(".py")}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="flowcheck",
        description="AST-based invariant checker for flowgger-tpu "
                    "(trace-safety, thread discipline, byte-identity "
                    "contracts, exception hygiene, config-key drift, "
                    "lock discipline, degradation-event completeness, "
                    "fault-site coverage, thread/resource lifecycle)")
    parser.add_argument("root", nargs="?", default=".",
                        help="scan root (default: current directory)")
    parser.add_argument("--format", choices=sorted(RENDERERS),
                        default="text", help="report format")
    parser.add_argument("--rules", metavar="FC01,FC02,...",
                        help="comma-separated subset of rules to run")
    parser.add_argument("--baseline", metavar="FILE",
                        help="baseline file (default: "
                             f"<root>/{baseline_mod.DEFAULT_BASELINE} "
                             "when present)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore any baseline file")
    parser.add_argument("--write-baseline", action="store_true",
                        help="freeze current findings into the baseline "
                             "file and exit 0")
    parser.add_argument("--check", action="store_true",
                        help="strict CI mode: a stale baseline entry "
                             "(no longer produced by a full run) is a "
                             "failure — delete the tombstone")
    parser.add_argument("--changed", metavar="REF",
                        help="incremental mode: scan only *.py files "
                             "changed vs the given git ref (plus "
                             "untracked files)")
    parser.add_argument("--expect-rules", type=int, metavar="N",
                        help="exit 2 unless exactly N rules are "
                             "registered (CI guard against a rule "
                             "module silently failing to load)")
    parser.add_argument("--sarif-out", metavar="FILE",
                        help="additionally write the SARIF report to "
                             "FILE (independent of --format)")
    parser.add_argument("--validate-sarif", metavar="FILE",
                        help="validate a SARIF file's shape and exit "
                             "(0 = valid, 2 = malformed); no scan runs")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    args = parser.parse_args(argv)

    if args.validate_sarif:
        try:
            with open(args.validate_sarif, "r", encoding="utf-8") as fd:
                text = fd.read()
        except OSError as e:
            print(f"flowcheck: cannot read {args.validate_sarif!r}: {e}",
                  file=sys.stderr)
            return 2
        problems = validate_sarif(text)
        if problems:
            for p in problems:
                print(f"flowcheck: sarif: {p}", file=sys.stderr)
            print(f"flowcheck: {args.validate_sarif} is malformed SARIF "
                  f"({len(problems)} problem(s))", file=sys.stderr)
            return 2
        print(f"flowcheck: {args.validate_sarif} is well-formed SARIF "
              f"{'2.1.0'}")
        return 0

    if args.list_rules:
        for rule in all_rules().values():
            print(f"{rule.id}  {rule.title}")
        return 0

    if args.expect_rules is not None:
        have = len(all_rules())
        if have != args.expect_rules:
            print(f"flowcheck: expected {args.expect_rules} registered "
                  f"rule(s), found {have} — a rule module failed to "
                  f"load or the gate is out of date", file=sys.stderr)
            return 2

    root = os.path.abspath(args.root)
    if not os.path.isdir(root):
        print(f"flowcheck: scan root {args.root!r} is not a directory",
              file=sys.stderr)
        return 2

    rule_ids = None
    if args.rules:
        rule_ids = [r.strip().upper() for r in args.rules.split(",")
                    if r.strip()]

    only_paths = None
    if args.changed:
        only_paths = _changed_paths(root, args.changed)
        if only_paths is None:
            return 2
        if not only_paths:
            print("flowcheck: no python files changed vs "
                  f"{args.changed} — nothing to scan")
            return 0

    baseline_path = args.baseline or os.path.join(
        root, baseline_mod.DEFAULT_BASELINE)
    baseline_keys = None
    if not args.no_baseline and not args.write_baseline:
        if os.path.exists(baseline_path):
            try:
                baseline_keys = baseline_mod.load(baseline_path)
            except baseline_mod.BaselineError as e:
                print(f"flowcheck: {e}", file=sys.stderr)
                return 2
        elif args.baseline:
            print(f"flowcheck: baseline {args.baseline!r} not found",
                  file=sys.stderr)
            return 2

    t0 = time.perf_counter()
    try:
        result = run_check(root, rule_ids=rule_ids,
                           baseline_keys=baseline_keys,
                           only_paths=only_paths)
    except KeyError as e:
        print(f"flowcheck: {e.args[0]}", file=sys.stderr)
        return 2
    wall = time.perf_counter() - t0

    if args.write_baseline:
        baseline_mod.write(baseline_path, result.findings)
        print(f"flowcheck: wrote {len(result.findings)} finding(s) to "
              f"{baseline_path}")
        return 0

    if args.sarif_out:
        try:
            with open(args.sarif_out, "w", encoding="utf-8") as fd:
                fd.write(render_sarif(result))
                fd.write("\n")
        except OSError as e:
            print(f"flowcheck: cannot write {args.sarif_out!r}: {e}",
                  file=sys.stderr)
            return 2

    print(RENDERERS[args.format](result))
    # wall time on stderr so json/sarif stdout stays machine-parseable
    print(f"flowcheck: scanned {len(result.project.modules)} file(s) in "
          f"{wall:.2f}s", file=sys.stderr)

    stale_failed = False
    if args.check and result.stale_baseline:
        for (rule, path, message), count in sorted(
                result.stale_baseline.items()):
            print(f"flowcheck: stale baseline entry ({count} leftover): "
                  f"{rule} {path}: {message} — the finding is gone; "
                  f"delete the tombstone from the baseline",
                  file=sys.stderr)
        stale_failed = True
    return 1 if (result.findings or stale_failed) else 0


if __name__ == "__main__":
    sys.exit(main())
