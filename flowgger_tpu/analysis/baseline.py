"""flowcheck baseline: freeze pre-existing findings so CI gates on new
ones only.

The baseline is a committed JSON file (default
``.flowcheck-baseline.json`` at the scan root) listing findings by
``(rule, path, message)`` — line numbers drift with unrelated edits and
are deliberately not part of the identity.  Each entry carries a
``reason`` so a frozen finding documents *why* it is allowed to exist;
entries are consumed as a multiset (``count``), so two identical
swallows in one file need a baseline count of 2.

Workflow: ``python -m flowgger_tpu.analysis --write-baseline`` freezes
the current findings (reasons default to "baselined"; edit them), and a
later clean run means every entry can be deleted — the file shrinking
to ``[]`` is the goal state, enforced by review rather than tooling.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Tuple

from .core import Finding

DEFAULT_BASELINE = ".flowcheck-baseline.json"

Key = Tuple[str, str, str]


class BaselineError(Exception):
    """Unreadable or malformed baseline file."""


def load(path: str) -> Dict[Key, int]:
    """Baseline file -> multiset of finding keys."""
    try:
        with open(path, "r", encoding="utf-8") as fd:
            entries = json.load(fd)
    except OSError as e:
        raise BaselineError(f"cannot read baseline {path}: {e}")
    except ValueError as e:
        raise BaselineError(f"baseline {path} is not valid JSON: {e}")
    if not isinstance(entries, list):
        raise BaselineError(f"baseline {path} must be a JSON list")
    keys: Dict[Key, int] = {}
    for i, entry in enumerate(entries):
        if not isinstance(entry, dict) or not all(
                isinstance(entry.get(k), str)
                for k in ("rule", "path", "message")):
            raise BaselineError(
                f"baseline {path} entry {i} needs string rule/path/message")
        key = (entry["rule"], entry["path"], entry["message"])
        count = entry.get("count", 1)
        if not isinstance(count, int) or count < 1:
            raise BaselineError(
                f"baseline {path} entry {i}: count must be a positive int")
        keys[key] = keys.get(key, 0) + count
    return keys


def write(path: str, findings: List[Finding]) -> None:
    """Freeze ``findings`` (the active, non-baselined ones) to ``path``.

    Regeneration is non-destructive: an entry already present in the
    old baseline keeps its hand-edited ``reason``; only genuinely new
    entries get the placeholder.
    """
    old_reasons: Dict[Key, str] = {}
    if os.path.exists(path):
        try:
            with open(path, "r", encoding="utf-8") as fd:
                for entry in json.load(fd):
                    key = (entry["rule"], entry["path"], entry["message"])
                    reason = entry.get("reason")
                    if isinstance(reason, str):
                        old_reasons.setdefault(key, reason)
        except (OSError, ValueError, KeyError, TypeError):
            pass  # unreadable old baseline: fall through to placeholders
    counted: Dict[Key, int] = {}
    for f in findings:
        counted[f.key] = counted.get(f.key, 0) + 1
    placeholder = "baselined — replace with why this finding is deliberate"
    entries = [{
        "rule": rule, "path": rel, "message": message, "count": count,
        "reason": old_reasons.get((rule, rel, message), placeholder),
    } for (rule, rel, message), count in sorted(counted.items())]
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fd:
        json.dump(entries, fd, indent=2, sort_keys=True)
        fd.write("\n")
    os.replace(tmp, path)
