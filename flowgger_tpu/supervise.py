"""Thread supervision: crashed pipeline threads restart instead of
silently dying.

The reference delegates recovery to an external supervisor — several
components exit the whole process on error (kafka_output.rs,
redis_input.rs) and a panicked output thread simply stops consuming,
wedging the bounded queue.  This module gives the pipeline an in-process
supervisor: input-accept and output-consumer threads run inside a
restart loop with the shared ``RetryPolicy`` backoff, crashes and
restarts are counted (``thread_crashes`` / ``thread_restarts``), and a
thread that exhausts its restart budget logs loudly instead of wedging
silently.  The overlap executor's per-lane fetcher threads
(tpu/overlap.py ``LaneSet`` → ``InflightWindow._run``) and the startup
kernel-prewarm worker (tpu/device_common.py) spawn through ``spawn``
too, so a crashed lane restarts with backoff instead of wedging its
share of the in-flight window.

The same ladder maps onto *hosts* at fleet granularity: a host whose
heartbeats vanish walks missed-heartbeat → suspect → evicted in its
peers' membership views (fleet/membership.py), and a host that
discovers its own eviction rejoins through ``fleet_policy()`` — the
fleet-level restart policy this module owns — with backoff and a
bounded budget, exactly like a crashed thread.

Config (all optional)::

    [supervisor]
    max_restarts = 16     # per thread between stable runs; absent = unlimited
    backoff_init = 100    # ms
    backoff_max = 30000   # ms
    fleet_max_rejoins = 8 # host rejoins after eviction; absent = unlimited

A supervised target that *returns* is treated as a clean exit (output
workers return on the SHUTDOWN sentinel); only exceptions trigger a
restart.  A run that stays up longer than ``backoff_max`` resets the
thread's restart budget, so a daemon that crashes once a day never
exhausts it.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Optional

from .utils.metrics import registry as _metrics
from .utils.retry import RetryPolicy

DEFAULT_BACKOFF_INIT_MS = 100
DEFAULT_BACKOFF_MAX_MS = 30_000


class Supervisor:
    def __init__(self, config=None):
        if config is not None:
            self.max_restarts: Optional[int] = config.lookup_int(
                "supervisor.max_restarts",
                "supervisor.max_restarts must be an integer", None)
            self.backoff_init = config.lookup_int(
                "supervisor.backoff_init",
                "supervisor.backoff_init must be an integer (ms)",
                DEFAULT_BACKOFF_INIT_MS)
            self.backoff_max = config.lookup_int(
                "supervisor.backoff_max",
                "supervisor.backoff_max must be an integer (ms)",
                DEFAULT_BACKOFF_MAX_MS)
            self.fleet_max_rejoins: Optional[int] = config.lookup_int(
                "supervisor.fleet_max_rejoins",
                "supervisor.fleet_max_rejoins must be an integer", None)
        else:
            self.max_restarts = None
            self.backoff_init = DEFAULT_BACKOFF_INIT_MS
            self.backoff_max = DEFAULT_BACKOFF_MAX_MS
            self.fleet_max_rejoins = None

    def _policy(self) -> RetryPolicy:
        return RetryPolicy(init_ms=self.backoff_init, max_ms=self.backoff_max,
                           max_attempts=self.max_restarts,
                           metric="thread_restarts")

    def fleet_policy(self, init_ms: Optional[int] = None) -> RetryPolicy:
        """The restart ladder at fleet granularity: backoff between a
        host's rejoin attempts after the fleet evicted it (missed
        heartbeats), bounded by ``supervisor.fleet_max_rejoins``.  Each
        backoff counts ``fleet_rejoins`` — the host-level analog of
        ``thread_restarts``."""
        return RetryPolicy(
            init_ms=self.backoff_init if init_ms is None else init_ms,
            max_ms=max(self.backoff_max,
                       init_ms if init_ms is not None else 0),
            max_attempts=self.fleet_max_rejoins,
            metric="fleet_rejoins")

    def run(self, target, name: str, args: tuple = (),
            exhausted: str = "return") -> None:
        """Run ``target(*args)`` in the calling thread under supervision:
        restart on crash with backoff until it returns normally or the
        restart budget is spent.

        ``exhausted`` controls budget exhaustion: ``"return"`` (input
        loops — the pipeline then drains and exits gracefully) or
        ``"exit"`` (queue consumers — a dead sole consumer would wedge
        every producer on the bounded queue forever, so honor the
        reference's exit-1 external-supervisor contract instead)."""
        policy = self._policy()
        while True:
            started = time.monotonic()
            try:
                target(*args)
                return
            except SystemExit:
                raise
            # flowcheck: disable=FC04 -- supervision boundary: the crash is counted, logged, and restarted (SystemExit re-raised above)
            except BaseException:  # noqa: BLE001 - supervision boundary
                _metrics.inc("thread_crashes")
                print(f"supervised thread [{name}] crashed:",
                      file=sys.stderr)
                traceback.print_exc()
                policy.note_run(started)  # long runs earn a fresh budget
                if policy.backoff() is None:
                    print(
                        f"supervised thread [{name}] exceeded its restart "
                        f"budget ({policy.attempts} restarts), giving up",
                        file=sys.stderr)
                    if exhausted == "exit":
                        import os

                        os._exit(1)
                    return
                print(f"restarting [{name}] "
                      f"(restart #{policy.attempts})", file=sys.stderr)

    def spawn(self, target, name: str, args: tuple = (),
              exhausted: str = "exit") -> threading.Thread:
        """Start a daemon thread running ``target`` under supervision.
        Spawned threads default to ``exhausted="exit"`` — they are queue
        consumers whose silent death would wedge the pipeline."""
        t = threading.Thread(target=self.run, args=(target, name, args,
                                                    exhausted),
                             daemon=True, name=name)
        t.start()
        return t
