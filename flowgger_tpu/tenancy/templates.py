"""Online log structuration: an evolving template tree over decoded
message columns (USTEP style, arxiv 2304.12331).

The miner clusters each message into a *template* — its token sequence
with variable positions wildcarded — using a fixed-depth search tree:
level 0 groups by token count, levels 1..depth by leading token
(numeric-looking tokens descend the wildcard child, so ``pid=4137``
and ``pid=9001`` share a path), and each leaf holds the templates of
its group.  A message joins the best-matching template when the exact-
token similarity clears ``tenant.template_sim`` (mismatched positions
degrade to ``<*>``), else it seeds a new one.  Insertion order fully
determines the result: two runs over the same corpus produce the same
template set and the same IDs.

This is the first stage that *consumes* the TPU-decoded columns: on
the columnar block route the per-row message spans come straight from
the kernel's output channels (``extract_block``), with zero
re-parsing on the host — the host path is pinned while mining so the
span channels are actually fetched.  On the Record route the miner
observes ``record.msg``.

Everything is off unless ``tenant.templates = "on"``: ``from_config``
returns None and no handler holds a miner (the smoke bench asserts
the off-path structurally).

Metrics: ``template_hits`` (rows mined), per-tenant
``tenant_{t}_template_{id}`` counters (IDs above ``_COUNTER_ID_CAP``
fold into ``tenant_{t}_template_overflow`` so the registry stays
bounded), the ``tenant_templates_distinct`` gauge (all tenants) and
per-tenant ``tenant_{t}_templates_distinct``.

The optional ``tenant.template_enrich`` flag additionally stamps each
GELF record with a ``_template_id`` field — that rides the Record
route (see tpu/batch.py route gating).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

from ..config import Config, ConfigError
from ..utils.metrics import registry as _metrics
from . import DEFAULT_TENANT

WILDCARD = "<*>"
# template IDs beyond this report into ..._template_overflow instead of
# minting one counter per id (bounds the metrics registry)
_COUNTER_ID_CAP = 128
_MAX_TOKENS = 48          # tokens considered per message
_MAX_MSG_BYTES = 512      # mining window into very long messages

DEFAULT_DEPTH = 4
DEFAULT_SIM = 0.5
DEFAULT_MAX_CHILDREN = 32
DEFAULT_MAX_TEMPLATES = 1024


def _looks_variable(token: str) -> bool:
    """Numeric-bearing tokens descend the wildcard branch so runs of
    ids/timestamps don't fan the tree out."""
    return any(c.isdigit() for c in token)


class TemplateMiner:
    """One tenant's evolving template tree.  Thread-safe; observation
    order determines IDs, so callers that need cross-run stability must
    observe in a deterministic order (the block route does: taps run
    under the lane sequencer, in batch order)."""

    def __init__(self, depth: int = DEFAULT_DEPTH, sim: float = DEFAULT_SIM,
                 max_children: int = DEFAULT_MAX_CHILDREN,
                 max_templates: int = DEFAULT_MAX_TEMPLATES):
        self.depth = max(1, depth)
        self.sim = sim
        self.max_children = max(2, max_children)
        self.max_templates = max_templates
        self._root: Dict = {}
        self._templates: Dict[int, List[str]] = {}
        self._next_id = 1
        self._lock = threading.Lock()

    def observe(self, msg) -> int:
        """Cluster one message; returns its template ID (0 = unmined:
        empty message or tenant at its template cap)."""
        if isinstance(msg, (bytes, bytearray, memoryview)):
            msg = bytes(msg[:_MAX_MSG_BYTES]).decode("utf-8", "replace")
        else:
            msg = (msg or "")[:_MAX_MSG_BYTES]
        tokens = msg.split()
        if not tokens:
            return 0
        tokens = tokens[:_MAX_TOKENS]
        with self._lock:
            return self._observe_locked(tokens)

    def _observe_locked(self, tokens: List[str]) -> int:
        # level 0: token count; levels 1..depth: leading tokens
        node = self._root.setdefault(len(tokens), {})
        for tok in tokens[: self.depth]:
            key = WILDCARD if _looks_variable(tok) else tok
            children = node.setdefault("c", {})
            child = children.get(key)
            if child is None:
                if key != WILDCARD and len(children) >= self.max_children:
                    key = WILDCARD  # full fan-out: overflow branch
                    child = children.get(key)
                if child is None:
                    child = children[key] = {}
            node = child
        leaf = node.setdefault("t", [])
        # best exact-token similarity among the leaf's templates
        best, best_sim = None, -1.0
        for entry in leaf:
            tmpl = entry[0]
            same = sum(1 for a, b in zip(tmpl, tokens) if a == b)
            s = same / len(tokens)
            if s > best_sim:
                best, best_sim = entry, s
        if best is not None and best_sim >= self.sim:
            tmpl = best[0]
            for i, tok in enumerate(tokens):
                if tmpl[i] != tok:
                    tmpl[i] = WILDCARD
            return best[1]
        if len(self._templates) >= self.max_templates:
            return 0
        tid = self._next_id
        self._next_id += 1
        tmpl = [WILDCARD if _looks_variable(t) else t for t in tokens]
        leaf.append((tmpl, tid))
        self._templates[tid] = tmpl
        return tid

    def distinct(self) -> int:
        with self._lock:
            return len(self._templates)

    def template(self, tid: int) -> Optional[str]:
        with self._lock:
            tmpl = self._templates.get(tid)
        return " ".join(tmpl) if tmpl is not None else None

    def templates(self) -> Dict[int, str]:
        with self._lock:
            items = [(tid, list(t)) for tid, t in self._templates.items()]
        return {tid: " ".join(t) for tid, t in items}


# per-format block-route message span channels: (start key, end key);
# an end key of None means "to the end of the (clipped) line"
_BLOCK_SPANS = {
    "rfc5424": ("msg_trim_start", "trim_end"),
    "rfc3164": ("msg_start", None),
    "ltsv": ("msg_start", "msg_end"),
    "dns": ("qname_start", "qname_end"),
}


def _extract_jsonl(packed, host_out) -> list:
    """JSON-lines block tap: the ``message`` key has no dedicated
    kernel channel — scan each ok row's key spans for it (field counts
    are small and mining already pins the host path)."""
    chunk, starts, orig_lens = packed[2], packed[3], packed[4]
    n_real = int(packed[5])
    max_len = int(packed[0].shape[1])
    ok = host_out["ok"]
    n_fields = host_out["n_fields"]
    key_s, key_e = host_out["key_start"], host_out["key_end"]
    val_s, val_e = host_out["val_start"], host_out["val_end"]
    val_t = host_out["val_type"]
    msgs: list = []
    for i in range(n_real):
        if not bool(ok[i]):
            msgs.append(None)
            continue
        s = int(starts[i])
        ln = min(int(orig_lens[i]), max_len)
        msg = b""
        for f in range(int(n_fields[i])):
            a, b = int(key_s[i][f]), int(key_e[i][f])
            if chunk[s + a:s + b] == b"message" \
                    and int(val_t[i][f]) == 0:  # VT_STRING
                lo = min(int(val_s[i][f]), ln)
                hi = min(int(val_e[i][f]), ln)
                msg = chunk[s + lo:s + hi] if hi > lo else b""
                break
        msgs.append(msg)
    return msgs


class TemplateMinerSet:
    """Per-tenant miners plus the metric plumbing shared by the block
    tap and the Record-route hook."""

    def __init__(self, depth: int = DEFAULT_DEPTH, sim: float = DEFAULT_SIM,
                 max_children: int = DEFAULT_MAX_CHILDREN,
                 max_templates: int = DEFAULT_MAX_TEMPLATES,
                 enrich: bool = False, opted_out=()):
        self.depth = depth
        self.sim = sim
        self.max_children = max_children
        self.max_templates = max_templates
        self.enrich = enrich
        # tenants whose [tenants.<name>] spec set templates = false:
        # their rows are never mined (observe returns 0, no counters)
        self.opted_out = frozenset(opted_out)
        self._miners: Dict[str, TemplateMiner] = {}
        self._lock = threading.Lock()
        # last distinct count pushed per tenant gauge: the gauges (and
        # the all-tenants sum) refresh only when a tenant's template
        # set actually grew, not once per observed line
        self._pushed: Dict[str, int] = {}

    @classmethod
    def from_config(cls, config: Config) -> Optional["TemplateMinerSet"]:
        mode = config.lookup_str(
            "tenant.templates",
            'tenant.templates must be "on" or "off"', "off")
        if mode not in ("on", "off"):
            raise ConfigError('tenant.templates must be "on" or "off"')
        enrich = config.lookup_bool(
            "tenant.template_enrich",
            "tenant.template_enrich must be a boolean", False)
        if mode != "on":
            if enrich:
                raise ConfigError(
                    'tenant.template_enrich needs tenant.templates = "on"')
            return None
        depth = config.lookup_int(
            "tenant.template_depth",
            "tenant.template_depth must be an integer", DEFAULT_DEPTH)
        sim = config.lookup_float(
            "tenant.template_sim",
            "tenant.template_sim must be a number in (0, 1]", DEFAULT_SIM)
        max_children = config.lookup_int(
            "tenant.template_max_children",
            "tenant.template_max_children must be an integer",
            DEFAULT_MAX_CHILDREN)
        max_templates = config.lookup_int(
            "tenant.template_max_templates",
            "tenant.template_max_templates must be an integer",
            DEFAULT_MAX_TEMPLATES)
        if not (0.0 < sim <= 1.0):
            raise ConfigError("tenant.template_sim must be in (0, 1]")
        if depth < 1 or max_children < 2 or max_templates < 1:
            raise ConfigError(
                "tenant.template_depth/max_children/max_templates must be "
                "positive (max_children >= 2)")
        tenants = config.lookup_table(
            "tenants", "[tenants] must be a table of tenant tables")
        opted_out = tuple(
            name for name, sub in (tenants or {}).items()
            if isinstance(sub, dict) and sub.get("templates") is False)
        return cls(depth=depth, sim=sim, max_children=max_children,
                   max_templates=max_templates, enrich=enrich,
                   opted_out=opted_out)

    def miner(self, tenant: str) -> TemplateMiner:
        with self._lock:
            m = self._miners.get(tenant)
            if m is None:
                m = self._miners[tenant] = TemplateMiner(
                    depth=self.depth, sim=self.sim,
                    max_children=self.max_children,
                    max_templates=self.max_templates)
            return m

    # -- observation -------------------------------------------------------
    def observe_msg(self, tenant: str, msg) -> int:
        """Mine one message for one tenant, with metrics (0 = unmined:
        empty message, tenant at its cap, or tenant opted out)."""
        if tenant in self.opted_out:
            return 0
        tid = self.miner(tenant).observe(msg)
        self._count(tenant, {tid: 1})
        return tid

    def _count(self, tenant: str, hits: Dict[int, int]) -> None:
        total = sum(hits.values())
        _metrics.inc("template_hits", total)
        for tid, n in hits.items():
            if tid <= 0 or tid > _COUNTER_ID_CAP:
                _metrics.inc(f"tenant_{tenant}_template_overflow", n)
            else:
                _metrics.inc(f"tenant_{tenant}_template_{tid}", n)
        distinct = self.miner(tenant).distinct()
        if self._pushed.get(tenant) != distinct:
            self._pushed[tenant] = distinct
            _metrics.set_gauge(f"tenant_{tenant}_templates_distinct",
                               distinct)
            _metrics.set_gauge("tenant_templates_distinct",
                               self.distinct_total())

    def distinct_total(self) -> int:
        with self._lock:
            miners = list(self._miners.values())
        return sum(m.distinct() for m in miners)

    # -- block-route tap ---------------------------------------------------
    def extract_block(self, fmt: str, packed, host_out) -> Optional[list]:
        """Pull per-row message bytes out of one fetched kernel output
        (pure extraction — safe on a concurrent lane fetcher thread;
        observation happens later, in sequenced batch order).  Returns
        None when the format has no mined span channels (gelf/auto)."""
        if fmt == "jsonl":
            if host_out.get("ok") is None:
                return None
            return _extract_jsonl(packed, host_out)
        spans = _BLOCK_SPANS.get(fmt)
        if spans is None:
            return None
        start_key, end_key = spans
        a = host_out.get(start_key)
        ok = host_out.get("ok")
        if a is None or ok is None:
            return None
        chunk, starts, orig_lens = packed[2], packed[3], packed[4]
        n_real = int(packed[5])
        max_len = int(packed[0].shape[1])
        b = host_out.get(end_key) if end_key is not None else None
        msgs: list = []
        for i in range(n_real):
            if not bool(ok[i]):
                msgs.append(None)  # undecodable row: nothing to mine
                continue
            s = int(starts[i])
            ln = min(int(orig_lens[i]), max_len)
            lo = min(int(a[i]), ln)
            hi = min(int(b[i]), ln) if b is not None else ln
            msgs.append(bytes(chunk[s + lo:s + hi]) if hi > lo else b"")
        return msgs

    def observe_rows(self, msgs: Sequence, runs: Optional[List[Tuple[str, int]]]) -> None:
        """Mine one batch's extracted messages, attributed to tenants by
        the ingest-order runs (None, or a count mismatch — e.g. rows the
        pack split differently — attributes the batch to ``default``)."""
        if not msgs:
            return
        if runs is None or sum(n for _, n in runs) != len(msgs):
            runs = [(DEFAULT_TENANT, len(msgs))]
        row = 0
        for tenant, n in runs:
            if n <= 0:
                continue
            if tenant in self.opted_out:
                row += n
                continue
            miner = self.miner(tenant)
            hits: Dict[int, int] = {}
            for msg in msgs[row:row + n]:
                if msg is None:
                    continue
                tid = miner.observe(msg)
                hits[tid] = hits.get(tid, 0) + 1
            row += n
            if hits:
                self._count(tenant, hits)


def make_gelf_enricher(miners: TemplateMinerSet):
    """Record hook for the GELF Record route: mines ``record.msg`` and
    stamps the template ID as a ``_template_id`` field (flattened to a
    top-level GELF key by the encoder's SD handling).  ``tenant`` is
    the row's attributed tenant when the caller knows it (the batch
    Record route passes its ingest runs); single-arg callers (the
    per-connection scalar path) fall back to the calling thread's
    tenant tag — the connection's own tenant there."""
    from ..record import SDValue, StructuredData
    from . import current_or_default

    def enrich(record, tenant: Optional[str] = None) -> None:
        tid = miners.observe_msg(tenant or current_or_default(),
                                 record.msg or "")
        sd = StructuredData(None)
        sd.pairs = [("_template_id", SDValue(SDValue.U64, tid))]
        if record.sd is None:
            record.sd = [sd]
        else:
            record.sd = list(record.sd) + [sd]

    return enrich
