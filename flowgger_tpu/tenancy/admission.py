"""Per-tenant token-bucket admission, applied at input-accept.

``AdmissionHandler`` wraps the pipeline's per-connection handler: every
framed region is charged against the connection's tenant buckets
(lines/sec and bytes/sec, with burst) *before* it reaches the batch
arena or the queue.  A tenant over its rate is shed right here — the
flood never consumes pack/decode/queue capacity, so well-behaved
tenants keep their exact bytes and ordering (the hard bar: admission
only ever removes a misbehaving tenant's own input, it never touches
anyone else's stream or reorders what it admits).

Admission granularity is the splitter's delivery unit: per line on the
scalar path, per complete-line region on the chunked fast path, per
span set on the syslen path — all-or-nothing per call, so the decision
costs one bucket check regardless of region size and can never split a
region (which would re-frame another tenant's carry).  Size bursts
accordingly (a region is at most one socket read, <= 64 KiB).

The ``tenant_flood`` fault site makes admission checks of *rate-limited*
tenants deterministically deny (unlimited tenants never check the site,
so a chaos plan targets exactly the tenants a test marks with a finite
rate).

Metrics per tenant: ``tenant_{name}_lines`` / ``_bytes`` (admitted),
``_drops`` (admission denials, lines), and the ``tenant_{name}_state``
gauge (0 admitting, 1 throttled, 2 queue-shed) — plus the aggregate
``tenant_lines/bytes/drops`` counters.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..splitters import Handler
from ..utils import faultinject as _faults
from ..utils.metrics import registry as _metrics
from . import set_current
from .registry import TenantSpec

# tenant_state gauge values
STATE_OK = 0
STATE_THROTTLED = 1
STATE_SHED = 2


class TokenBucket:
    """Monotonic-clock token bucket; ``rate <= 0`` = unlimited."""

    def __init__(self, rate: float, burst: float, clock=None):
        self.rate = float(rate)
        self.burst = float(burst) if burst > 0 else float(rate)
        self._clock = clock or time.monotonic
        self._tokens = self.burst
        self._last = self._clock()
        self._lock = threading.Lock()

    def try_take(self, n: float) -> bool:
        if self.rate <= 0:
            return True
        with self._lock:
            now = self._clock()
            if now > self._last:
                self._tokens = min(self.burst,
                                   self._tokens + (now - self._last) * self.rate)
                self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def set_rate(self, rate: float) -> None:
        """Retune the refill rate in place (control plane).  Tokens
        accrued so far refill at the *old* rate up to now, then the new
        rate applies — no retroactive grant or confiscation.  Burst
        capacity is unchanged: tightening bounds the sustained rate,
        not the configured headroom for a one-off spike."""
        with self._lock:
            now = self._clock()
            if now > self._last and self.rate > 0:
                self._tokens = min(self.burst,
                                   self._tokens + (now - self._last) * self.rate)
            self._last = now
            self.rate = float(rate)


class TenantState:
    """One tenant's shared admission/QoS state: every connection of the
    tenant charges the same bucket pair; the fair queue reads the spec's
    weight/policy through here too."""

    def __init__(self, spec: TenantSpec, clock=None):
        self.spec = spec
        self.name = spec.name
        self.lines_bucket = TokenBucket(spec.rate, spec.burst, clock)
        self.bytes_bucket = TokenBucket(spec.byte_rate, spec.byte_burst, clock)
        self._m_lines = f"tenant_{spec.name}_lines"
        self._m_bytes = f"tenant_{spec.name}_bytes"
        self._m_drops = f"tenant_{spec.name}_drops"
        self._m_shed = f"tenant_{spec.name}_shed"
        self._m_state = f"tenant_{spec.name}_state"
        self._last_notice = 0.0
        self._gauge_state = STATE_OK
        # controller-applied rate factor (control/plane.py AIMD loop):
        # 1.0 = configured rates; < 1.0 = tightened.  Written only from
        # the controller tick, read on the denial path — never on the
        # admit hot path.
        self.rate_factor = 1.0
        _metrics.init_gauge(self._m_state, STATE_OK)

    def set_rate_factor(self, factor: float) -> float:
        """Scale the tenant's admitted rates to ``factor`` of the
        configured spec (burn-driven admission).  Only rate-limited
        tenants are governable — an unlimited tenant has no rate to
        multiply (the same convention the ``tenant_flood`` fault site
        uses).  Returns the effective lines/sec rate now applied."""
        factor = min(1.0, max(0.0, float(factor)))
        if not self.spec.limited or factor == self.rate_factor:
            return self.effective_rate()
        self.rate_factor = factor
        if self.spec.rate > 0:
            self.lines_bucket.set_rate(self.spec.rate * factor)
        if self.spec.byte_rate > 0:
            self.bytes_bucket.set_rate(self.spec.byte_rate * factor)
        _metrics.set_gauge(f"tenant_{self.name}_rate_factor", factor)
        return self.effective_rate()

    def effective_rate(self) -> float:
        """The lines/sec rate currently enforced (configured rate x
        controller factor); 0 = unlimited."""
        return self.lines_bucket.rate

    def admission_detail(self) -> str:
        """Denial-path annotation: the effective bucket rate, flagged
        when the controller (not the operator's config) set it — lets
        ``fleetctl top`` distinguish "over configured rate" from
        "throttled by controller".  Built only when an event fires."""
        if self.rate_factor < 1.0:
            return (f"effective_rate={self.lines_bucket.rate:g}/s "
                    f"(configured {self.spec.rate:g}/s, controller "
                    f"factor {self.rate_factor:.2f})")
        return f"effective_rate={self.lines_bucket.rate:g}/s"

    def admit(self, lines: int, nbytes: int) -> bool:
        """Charge one delivery unit; False = shed it (already counted)."""
        denied = (self.spec.limited and _faults.enabled()
                  and _faults.fire("tenant_flood"))
        if not denied:
            # charge lines first: a lines-denied unit must not drain the
            # byte bucket (and vice versa matters less — byte flood with
            # few lines is the rarer shape; one-sided drain is bounded)
            if not self.lines_bucket.try_take(lines):
                denied = True
            elif not self.bytes_bucket.try_take(nbytes):
                denied = True
        if not denied:
            _metrics.inc(self._m_lines, lines)
            _metrics.inc(self._m_bytes, nbytes)
            _metrics.inc("tenant_lines", lines)
            _metrics.inc("tenant_bytes", nbytes)
            self._set_state(STATE_OK)
            return True
        _metrics.inc(self._m_drops, lines)
        _metrics.inc("tenant_drops", lines)
        self._set_state(STATE_THROTTLED)
        now = time.monotonic()
        msg = None
        if now - self._last_notice >= 5.0:
            # rate-limited notice: a sustained flood must not turn
            # stderr into a second flood (the journal event still fires
            # per denied delivery unit — the ring is bounded)
            self._last_notice = now
            msg = (f"tenant [{self.name}] over admission rate; shedding "
                   f"(tenant_{self.name}_drops counts lines)")
        from ..obs import events as _events

        _events.emit("admission", "tenant_shed", tenant=self.name,
                     detail=self.admission_detail(),
                     cost=lines, cost_unit="lines", msg=msg)
        return False

    def _set_state(self, state: int) -> None:
        # gauge write only on transitions: the steady state costs one
        # attribute compare per delivery unit, not a registry lock
        if self._gauge_state != state:
            self._gauge_state = state
            _metrics.set_gauge(self._m_state, state)

    def count_shed(self, lines: int = 1) -> None:
        """A queued item of this tenant was load-shed under global
        pressure (fairqueue calls this)."""
        _metrics.inc(self._m_shed, lines)
        _metrics.inc("tenant_shed", lines)
        self._set_state(STATE_SHED)


class RawCharge:
    """Record-aligned admission hook a raw (device-framed) session
    carries: the batch handler calls ``admit_region`` once per *framed*
    region — after the boundary scan, before dispatch — with the exact
    (records, bytes) the host splitter would have charged for the same
    stream.  All-or-nothing per region, so a denial sheds whole records
    (never a mid-record splice) and the tenant counters stay identical
    to the host-framing baseline.  The carry tail (a record split
    across chunks) is charged when it finally frames, or as one record
    at EOF — again mirroring the host splitters' delivery units."""

    __slots__ = ("state",)

    def __init__(self, state: TenantState):
        self.state = state

    def admit_region(self, lines: int, nbytes: int) -> bool:
        return self.state.admit(lines, nbytes)


class AdmissionHandler(Handler):
    """Per-connection wrapper: tags the connection thread with its
    tenant, charges admission, forwards admitted input to the shared
    inner handler.  Exposes ``ingest_chunk``/``ingest_spans`` only when
    the inner handler does, so splitter fast-path dispatch (hasattr
    checks) is unchanged.

    Device-resident framing forwards too (``wants_raw``/``open_raw``):
    the raw session carries a :class:`RawCharge` that the batch handler
    invokes on each *framed* region, so admission stays record-aligned
    (a raw chunk can end mid-record; charging at frame time means a
    denial can never splice the surrounding records together) while
    tenancy-admitted connections keep the device framing tier."""

    def __init__(self, inner: Handler, tenant: TenantState):
        self._inner = inner
        self._tenant = tenant
        if hasattr(inner, "ingest_chunk"):
            self.ingest_chunk = self._ingest_chunk
        if hasattr(inner, "ingest_spans"):
            self.ingest_spans = self._ingest_spans

    # splitters configure these ON the handler they receive; forward to
    # the shared inner handler where the batch/error paths read them
    @property
    def quiet_empty(self):
        return self._inner.quiet_empty

    @quiet_empty.setter
    def quiet_empty(self, v):
        self._inner.quiet_empty = v

    @property
    def bare_errors(self):
        return self._inner.bare_errors

    @bare_errors.setter
    def bare_errors(self, v):
        self._inner.bare_errors = v

    @property
    def ingest_sep(self):
        return self._inner.ingest_sep

    @ingest_sep.setter
    def ingest_sep(self, v):
        self._inner.ingest_sep = v

    @property
    def ingest_strip_cr(self):
        return self._inner.ingest_strip_cr

    @ingest_strip_cr.setter
    def ingest_strip_cr(self, v):
        self._inner.ingest_strip_cr = v

    def wants_raw(self, framing: str) -> bool:
        return self._inner.wants_raw(framing)

    def open_raw(self, framing: str):
        # the session is charged at frame time (RawCharge), not here:
        # raw chunks are admitted unconditionally into the session
        # buffer and pay admission once record boundaries are known
        set_current(self._tenant.name)
        sess = self._inner.open_raw(framing)
        sess.charge = RawCharge(self._tenant)
        return sess

    def handle_bytes(self, raw: bytes) -> None:
        if self._tenant.admit(1, len(raw)):
            set_current(self._tenant.name)
            self._inner.handle_bytes(raw)

    def _ingest_chunk(self, region: bytes) -> None:
        n = region.count(self._inner.ingest_sep)
        if self._tenant.admit(n, len(region)):
            set_current(self._tenant.name)
            self._inner.ingest_chunk(region)

    def _ingest_spans(self, chunk: bytes, starts, lens) -> None:
        if self._tenant.admit(len(starts), int(lens.sum())):
            set_current(self._tenant.name)
            self._inner.ingest_spans(chunk, starts, lens)

    def handle_record(self, record) -> None:
        if self._tenant.admit(1, 0):
            set_current(self._tenant.name)
            self._inner.handle_record(record)

    def flush(self) -> None:
        self._inner.flush()

    def close(self) -> None:
        close = getattr(self._inner, "close", None)
        if close is not None:
            close()
