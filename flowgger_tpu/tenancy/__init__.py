"""Multi-tenant serving: per-tenant admission, weighted-fair QoS, and
online template mining over the decoded columns.

A collector fronting many sources cannot let one flooding listener
degrade everyone: with a single bounded queue, the drop policy sheds
victims indiscriminately.  This package adds the tenancy layer:

- ``registry``  — tenant specs keyed by source listener/peer (the
  ``[tenants]`` config table plus ``tenant.default_*`` keys);
- ``admission`` — per-tenant token-bucket admission (lines/sec and
  bytes/sec with burst) applied at input-accept, *before* the queue;
- ``fairqueue`` — per-tenant sub-queues with deficit-round-robin
  dequeue and noisiest-tenant-first load shedding under global
  pressure (SHUTDOWN stays unsheddable);
- ``templates`` — an optional USTEP-style evolving template tree
  (arxiv 2304.12331) mining message templates from the TPU-decoded
  columnar batches — the first stage that *consumes* the decoded
  columns instead of re-serializing them.

Everything here is opt-in: with no ``[tenants]`` table and
``tenant.templates`` off, the pipeline builds the exact same objects
it did before this package existed (PolicyQueue, bare handlers) and
pays zero overhead.

This module itself stays import-light (no config/metrics/JAX): the hot
path (``tpu/batch.py`` ingest) only needs the thread-local tenant tag
set by the admission wrapper on each connection thread.
"""

from __future__ import annotations

import threading
from typing import Optional

DEFAULT_TENANT = "default"

_tls = threading.local()


def set_current(name: Optional[str]) -> None:
    """Tag the calling thread with the tenant whose traffic it is
    carrying (admission wrapper; one connection thread serves one
    tenant).  ``None`` clears the tag."""
    _tls.tenant = name


def current_name() -> Optional[str]:
    """The calling thread's tenant tag, or None off a tagged thread
    (batch fetcher threads, timers, tests)."""
    return getattr(_tls, "tenant", None)


def current_or_default() -> str:
    name = current_name()
    return DEFAULT_TENANT if name is None else name
