"""Weighted-fair multi-queue: per-tenant FIFO lanes, DRR dequeue,
noisiest-tenant-first load shedding.

Drop-in for ``utils.bounded_queue.PolicyQueue`` (the ``queue.Queue``
surface the sinks use: put/get/get_nowait/empty/qsize/task_done/join),
engaged by the pipeline only when a ``TenantRegistry`` is configured.

Structure:

- one FIFO lane per tenant, created on first put;
- a separate control lane for the SHUTDOWN sentinel (``None``): never
  counted against capacity, never shed, and delivered only once every
  data lane is empty — so graceful drain keeps its "flush, then
  sentinel, then join" contract even though dequeue is no longer
  globally FIFO;
- deficit-round-robin dequeue: each lane accumulates quantum
  proportional to its weight and serves whole items against it, so a
  tenant's long-run share of dequeued *bytes* tracks its weight while
  each lane stays strictly FIFO;
- global-pressure shedding: when the queue is full (or the
  ``queue_pressure`` fault site fires), the *noisiest* sheddable lane —
  largest queued cost per unit weight, ``queue_policy != "block"`` —
  loses its oldest item first.  Only when no lane is sheddable does the
  producer's own policy apply (block = reference backpressure).

Item → lane attribution: per-message items take the producing thread's
tenant tag (set by the admission wrapper for connection threads; batch
Record-route emits re-tag per row from their ingest runs — see
tpu/batch.py ``_emit`` — so a mixed-tenant batch never lands wholesale
on the flusher's lane).  ``EncodedBlock`` items — the batched block
route's output — always ride the ``default`` lane: the batch arena
aggregates every tenant upstream of the queue, so block-route isolation
is enforced at admission instead (see tenancy/__init__ docstring).

Shed metrics: ``queue_dropped`` (aggregate, unchanged meaning), the
per-cause ``queue_dropped_{policy}`` labels, per-tenant
``tenant_{name}_shed``, and ``queue_shed_during_drain`` once the
pipeline has entered its drain phase.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from collections import deque
from typing import Dict, Optional

from ..utils import faultinject
from ..utils.bounded_queue import QUEUE_WAIT_SAMPLE
from ..utils.metrics import registry as _metrics
from . import DEFAULT_TENANT, current_name
from .registry import TenantRegistry

# deficit added per DRR visit, scaled by the lane's weight.  Smaller
# than a typical block (MBs) — lanes with huge items accumulate over
# visits, which is exactly DRR's longest-item fairness behavior.
BASE_QUANTUM = 16384


class _Lane:
    __slots__ = ("name", "q", "cost", "deficit", "weight", "policy", "state")

    def __init__(self, name: str, weight: int, policy: str, state):
        self.name = name
        self.q: deque = deque()  # (item, cost, lines, enqueue perf_counter)
        self.cost = 0            # queued bytes (DRR + noisiest metric)
        self.deficit = 0.0
        self.weight = max(1, weight)
        self.policy = policy
        self.state = state       # admission.TenantState (shed counters)


def _item_cost(item):
    """(cost bytes, line count) of one queued item."""
    data = getattr(item, "data", None)
    if data is not None:  # EncodedBlock: data bytes, __len__ = messages
        return len(data), len(item)
    try:
        return len(item), 1
    except TypeError:
        return 1, 1


class WeightedFairQueue:
    def __init__(self, maxsize: int = 0, registry: Optional[TenantRegistry] = None):
        self.maxsize = maxsize
        self.registry = registry
        self.mutex = threading.Lock()
        self.not_empty = threading.Condition(self.mutex)
        self.not_full = threading.Condition(self.mutex)
        self.all_tasks_done = threading.Condition(self.mutex)
        self.unfinished_tasks = 0
        self._lanes: Dict[str, _Lane] = {}
        self._order: list = []     # lane names, DRR rotation order
        self._cursor = 0           # rotation position of the last serve
        self._control: deque = deque()
        self._total = 0            # queued data items (maxsize domain)
        self.draining = False
        self._wait_n = 0           # queue_wait_seconds sample counter
        # shed events staged under the mutex, emitted after release:
        # the journal's optional JSONL sink is disk I/O, and per-drop
        # I/O inside the queue lock would serialize every producer
        # behind the disk exactly when overload sheds fire
        self._event_buf: list = []

    def _sample_wait_locked(self, ts: float, lane: str) -> None:
        """Sampled sojourn time of dequeued items (PolicyQueue parity:
        one queue_wait_seconds sample per QUEUE_WAIT_SAMPLE gets).
        Samples also land the per-tenant ``queue_wait_seconds_{tenant}``
        family so a tenant-scoped latency SLO (obs/slo.py) can tell a
        starved lane from global pressure."""
        self._wait_n += 1
        if self._wait_n % QUEUE_WAIT_SAMPLE == 0:
            wait = time.perf_counter() - ts
            _metrics.observe("queue_wait_seconds", wait)
            _metrics.observe(f"queue_wait_seconds_{lane}", wait)

    # -- introspection (PolicyQueue/queue.Queue parity) --------------------
    def qsize(self) -> int:
        with self.mutex:
            return self._total + len(self._control)

    def empty(self) -> bool:
        return self.qsize() == 0

    def lane_depths(self) -> Dict[str, int]:
        with self.mutex:
            return {name: len(lane.q) for name, lane in self._lanes.items()}

    def mark_draining(self) -> None:
        """Pipeline drain entered: sheds from here on additionally count
        ``queue_shed_during_drain`` so a SIGTERM test can tell shed
        lines from delivered lines."""
        with self.mutex:
            self.draining = True

    def fill_fraction(self) -> float:
        """Data-item occupancy in [0, 1] (PolicyQueue parity — the
        durability watermark signal; the control lane is capacity-
        exempt and does not count)."""
        with self.mutex:
            return self._total / self.maxsize if self.maxsize > 0 else 0.0

    # -- producers ---------------------------------------------------------
    def _lane_for(self, name: str) -> _Lane:
        lane = self._lanes.get(name)
        if lane is None:
            if self.registry is not None:
                spec = self.registry.spec(name)
                state = self.registry.state(name)
                lane = _Lane(name, spec.weight, spec.queue_policy, state)
            else:
                lane = _Lane(name, 1, "block", None)
            self._lanes[name] = lane
            self._order.append(name)
        return lane

    def _shed_head_locked(self, lane: _Lane, cause: str) -> None:
        _item, cost, lines, _ts = lane.q.popleft()
        lane.cost -= cost
        self._total -= 1
        self._count_shed_locked(lane, cause, lines)
        # the shed item's put was counted as an unfinished task
        self._task_done_locked()

    def _count_shed_locked(self, lane: Optional[_Lane], cause: str,
                           lines: int) -> None:
        # queue_dropped family counts ITEMS (PolicyQueue parity: one
        # shed EncodedBlock = one drop, exactly as on the tenancy-off
        # queue); the per-tenant tenant_{name}_shed counts LINES, the
        # unit admission drops are counted in
        _metrics.inc("queue_dropped")
        _metrics.inc(f"queue_dropped_{cause}")
        if self.draining:
            _metrics.inc("queue_shed_during_drain")
        if lane is not None and lane.state is not None:
            lane.state.count_shed(lines)
        # staged, not emitted: put() drains the buffer after the mutex
        self._event_buf.append(
            (cause, lane.name if lane is not None else None, lines,
             lane.state if lane is not None else None))

    def _noisiest_sheddable_locked(self) -> Optional[_Lane]:
        best, best_score = None, -1.0
        for lane in self._lanes.values():
            if not lane.q or lane.policy == "block":
                continue
            score = lane.cost / lane.weight
            if score > best_score:
                best, best_score = lane, score
        return best

    def _drain_events(self) -> None:
        """Emit staged shed events outside the mutex (journal I/O must
        never run under the queue lock)."""
        with self.mutex:
            if not self._event_buf:
                return
            buf, self._event_buf = self._event_buf, []
        from ..obs import events as _events

        for cause, tenant, lines, state in buf:
            # annotate with the tenant's *effective* admitted rate so
            # fleetctl top can tell "over configured rate" from
            # "tightened by the controller" (string built out here —
            # never under the queue mutex)
            detail = (f"{cause} {state.admission_detail()}"
                      if state is not None else cause)
            _events.emit("queue", "queue_drop", detail=detail,
                         tenant=tenant, cost=lines, cost_unit="lines")

    def put(self, item, block: bool = True, timeout=None) -> None:
        try:
            self._put_inner(item, block, timeout)
        finally:
            self._drain_events()

    def _put_inner(self, item, block: bool = True, timeout=None) -> None:
        if item is None:
            # SHUTDOWN sentinel: unsheddable, capacity-exempt, delivered
            # by get() only after the data lanes drain
            with self.not_empty:
                self._control.append(item)
                self.unfinished_tasks += 1
                self.not_empty.notify()
            return
        name = current_name()
        cost, lines = _item_cost(item)
        if getattr(item, "data", None) is not None or name is None:
            name = DEFAULT_TENANT  # block-route items: see module doc
        deadline = (time.monotonic() + timeout) if (block and timeout
                                                    is not None) else None
        with self.mutex:
            lane = self._lane_for(name)
            pressured = faultinject.enabled() and faultinject.fire(
                "queue_pressure")
            while True:
                full = 0 < self.maxsize <= self._total
                if not (full or pressured):
                    break
                synthetic = pressured and not full
                pressured = False
                victim = self._noisiest_sheddable_locked()
                if victim is lane and lane.policy == "drop_newest":
                    # own lane is the noisiest: honor its flavor — shed
                    # the incoming item (never queued, no task to balance)
                    self._count_shed_locked(lane, "drop_newest", lines)
                    return
                if victim is not None:
                    self._shed_head_locked(
                        victim, "drop_oldest" if victim is lane
                        else "shed_noisiest")
                    continue
                # nothing sheddable queued anywhere
                if lane.policy == "block":
                    if synthetic:
                        # PolicyQueue parity: under block policy the
                        # pressure site only counts — never deadlock a
                        # producer on a queue that has room
                        break
                    # queue.Queue put() parity for the backpressure wait
                    if not block:
                        raise _queue.Full
                    if deadline is None:
                        self.not_full.wait()
                    else:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            raise _queue.Full
                        self.not_full.wait(remaining)
                    continue
                # the incoming item is discarded either way; label it
                # with the lane's configured policy, not a fixed cause
                self._count_shed_locked(lane, lane.policy, lines)
                return
            lane.q.append((item, cost, lines, time.perf_counter()))
            lane.cost += cost
            self._total += 1
            self.unfinished_tasks += 1
            self.not_empty.notify()

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    # -- consumers ---------------------------------------------------------
    def _dequeue_locked(self):
        # data lanes first; the control lane (SHUTDOWN) only when empty
        active = [n for n in self._order if self._lanes[n].q]
        if not active:
            item = self._control.popleft()
            return item
        if len(active) == 1:
            lane = self._lanes[active[0]]
            item, cost, _lines, ts = lane.q.popleft()
            lane.cost -= cost
            if not lane.q:
                lane.deficit = 0.0
            self._total -= 1
            self._sample_wait_locked(ts, lane.name)
            return item
        # DRR: resume the rotation after the last-served lane; refill
        # every active lane's deficit until one can afford its head
        start = self._cursor
        while True:
            for off in range(len(active)):
                idx = (start + off) % len(active)
                lane = self._lanes[active[idx]]
                head_cost = lane.q[0][1]
                if lane.deficit >= head_cost:
                    item, cost, _lines, ts = lane.q.popleft()
                    lane.cost -= cost
                    lane.deficit -= cost
                    if not lane.q:
                        lane.deficit = 0.0
                    self._total -= 1
                    self._cursor = idx
                    self._sample_wait_locked(ts, lane.name)
                    return item
            for n in active:
                lane = self._lanes[n]
                lane.deficit += BASE_QUANTUM * lane.weight

    def get(self, block: bool = True, timeout=None):
        with self.not_empty:
            if not block:
                if not (self._total or self._control):
                    raise _queue.Empty
            elif timeout is None:
                while not (self._total or self._control):
                    self.not_empty.wait()
            else:
                deadline = time.monotonic() + timeout
                while not (self._total or self._control):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise _queue.Empty
                    self.not_empty.wait(remaining)
            item = self._dequeue_locked()
            self.not_full.notify()
            return item

    def get_nowait(self):
        return self.get(block=False)

    # -- task accounting (queue.Queue parity) ------------------------------
    def _task_done_locked(self) -> None:
        unfinished = self.unfinished_tasks - 1
        if unfinished < 0:
            raise ValueError("task_done() called too many times")
        self.unfinished_tasks = unfinished
        if unfinished == 0:
            self.all_tasks_done.notify_all()

    def task_done(self) -> None:
        with self.all_tasks_done:
            self._task_done_locked()

    def join(self) -> None:
        with self.all_tasks_done:
            while self.unfinished_tasks:
                self.all_tasks_done.wait()
