"""Tenant registry: specs from config, peer → tenant resolution.

Tenants are declared as ``[tenants.<name>]`` tables; the ``[tenant]``
table holds the defaults every spec inherits (and the catch-all
``default`` tenant uses):

    [tenant]
    default_rate = 0            # lines/sec admitted; 0 = unlimited
    default_byte_rate = 0       # bytes/sec admitted; 0 = unlimited
    default_burst = 0           # bucket depth, lines; 0 = 2x rate
    default_byte_burst = 0      # bucket depth, bytes; 0 = 2x byte rate
    default_weight = 1          # weighted-fair dequeue share
    default_queue_policy = "block"   # per-tenant overflow policy

    [tenants.alpha]
    peers = ["10.0.0.0/8", "192.0.2.7"]   # CIDR, exact IP, or exact
                                          # source label (file path)
    rate = 50000
    weight = 4
    queue_policy = "drop_oldest"

Resolution is first-match in declaration order; unmatched peers (and
peerless inputs: stdin, redis) land on the ``default`` tenant.  A
``[tenants.default]`` entry customizes the catch-all itself.

The registry is the enablement switch for the whole tenancy layer:
``from_config`` returns None when no ``[tenants]`` table and no
``tenant.default_*`` rate key is present, and the pipeline then builds
the exact pre-tenancy objects (no admission wrapper, PolicyQueue).
"""

from __future__ import annotations

import ipaddress
from typing import Dict, List, Optional, Tuple

from ..config import Config, ConfigError
from ..utils.bounded_queue import POLICIES
from . import DEFAULT_TENANT

_SPEC_KEYS = frozenset((
    "peers", "rate", "byte_rate", "burst", "byte_burst", "weight",
    "queue_policy", "templates",
))


class TenantSpec:
    __slots__ = ("name", "peers", "rate", "byte_rate", "burst",
                 "byte_burst", "weight", "queue_policy", "templates")

    def __init__(self, name: str, peers: List[str], rate: int,
                 byte_rate: int, burst: int, byte_burst: int, weight: int,
                 queue_policy: str, templates: bool):
        self.name = name
        self.peers = peers
        self.rate = rate
        self.byte_rate = byte_rate
        # bucket depth defaults to two seconds of the sustained rate so
        # a fresh connection can burst without tripping admission
        self.burst = burst if burst > 0 else 2 * rate
        self.byte_burst = byte_burst if byte_burst > 0 else 2 * byte_rate
        self.weight = weight
        self.queue_policy = queue_policy
        self.templates = templates

    @property
    def limited(self) -> bool:
        return self.rate > 0 or self.byte_rate > 0


def _spec_int(table: dict, name: str, key: str, default: int) -> int:
    v = table.get(key, default)
    if isinstance(v, bool) or not isinstance(v, int) or v < 0:
        raise ConfigError(
            f"[tenants.{name}] {key} must be a non-negative integer")
    return v


class TenantRegistry:
    """Parsed tenant specs plus the peer matchers.

    Admission state (token buckets, per-tenant counters) lives in
    ``admission.TenantState`` objects built once per tenant here, so
    every connection of one tenant shares one pair of buckets.
    """

    def __init__(self, specs: "Dict[str, TenantSpec]", default: TenantSpec,
                 clock=None):
        from .admission import TenantState

        self.specs = specs
        self.default = default
        # ordered matchers — resolution is first match in declaration
        # order, so a broad CIDR declared before an exact IP wins for
        # that IP (the docstring's contract).  _exact is a fast path
        # used only when no CIDR/"*" entry exists.
        self._matchers: List[Tuple[str, object, str]] = []
        self._exact: Dict[str, str] = {}
        for name, spec in specs.items():
            for peer in spec.peers:
                if peer == "*":
                    self._matchers.append(("star", None, name))
                    continue
                try:
                    net = ipaddress.ip_network(peer, strict=False)
                except ValueError:
                    # not an address: exact source label (file path,
                    # unix peer name)
                    self._matchers.append(("label", peer, name))
                    self._exact.setdefault(peer, name)
                    continue
                if net.num_addresses == 1:
                    addr = str(net.network_address)
                    self._matchers.append(("label", addr, name))
                    self._exact.setdefault(addr, name)
                else:
                    self._matchers.append(("net", net, name))
        self._exact_only = all(k == "label" for k, _, _ in self._matchers)
        self._states: Dict[str, TenantState] = {
            name: TenantState(spec, clock=clock)
            for name, spec in specs.items()
        }
        if DEFAULT_TENANT not in self._states:
            self._states[DEFAULT_TENANT] = TenantState(default, clock=clock)

    # -- config ------------------------------------------------------------
    @classmethod
    def from_config(cls, config: Config,
                    fallback_policy: str = "block",
                    clock=None) -> Optional["TenantRegistry"]:
        table = config.lookup_table(
            "tenants", "[tenants] must be a table of tenant tables")
        d_rate = config.lookup_int(
            "tenant.default_rate",
            "tenant.default_rate must be an integer (lines/sec)", 0)
        d_byte_rate = config.lookup_int(
            "tenant.default_byte_rate",
            "tenant.default_byte_rate must be an integer (bytes/sec)", 0)
        d_burst = config.lookup_int(
            "tenant.default_burst",
            "tenant.default_burst must be an integer (lines)", 0)
        d_byte_burst = config.lookup_int(
            "tenant.default_byte_burst",
            "tenant.default_byte_burst must be an integer (bytes)", 0)
        d_weight = config.lookup_int(
            "tenant.default_weight",
            "tenant.default_weight must be a positive integer", 1)
        d_policy = config.lookup_str(
            "tenant.default_queue_policy",
            'tenant.default_queue_policy must be "block", "drop_newest" '
            'or "drop_oldest"', fallback_policy)
        if table is None and not (d_rate or d_byte_rate):
            # tenancy off: the pipeline keeps its pre-tenancy objects
            return None
        if d_weight < 1:
            raise ConfigError("tenant.default_weight must be >= 1")
        if d_policy not in POLICIES:
            raise ConfigError(
                'tenant.default_queue_policy must be "block", '
                '"drop_newest" or "drop_oldest"')
        if any(v < 0 for v in (d_rate, d_byte_rate, d_burst, d_byte_burst)):
            raise ConfigError("tenant.default_* rates must be >= 0")

        def build(name: str, sub: dict) -> TenantSpec:
            unknown = set(sub) - _SPEC_KEYS
            if unknown:
                raise ConfigError(
                    f"[tenants.{name}] unknown key(s): "
                    f"{', '.join(sorted(unknown))} "
                    f"(known: {', '.join(sorted(_SPEC_KEYS))})")
            peers = sub.get("peers", [])
            if (not isinstance(peers, list)
                    or any(not isinstance(p, str) for p in peers)):
                raise ConfigError(
                    f"[tenants.{name}] peers must be a list of strings")
            policy = sub.get("queue_policy", d_policy)
            if policy not in POLICIES:
                raise ConfigError(
                    f'[tenants.{name}] queue_policy must be "block", '
                    '"drop_newest" or "drop_oldest"')
            templates = sub.get("templates", True)
            if not isinstance(templates, bool):
                raise ConfigError(
                    f"[tenants.{name}] templates must be a boolean")
            weight = _spec_int(sub, name, "weight", d_weight)
            if weight < 1:
                raise ConfigError(f"[tenants.{name}] weight must be >= 1")
            return TenantSpec(
                name, peers,
                rate=_spec_int(sub, name, "rate", d_rate),
                byte_rate=_spec_int(sub, name, "byte_rate", d_byte_rate),
                burst=_spec_int(sub, name, "burst", d_burst),
                byte_burst=_spec_int(sub, name, "byte_burst", d_byte_burst),
                weight=weight, queue_policy=policy, templates=templates)

        specs: Dict[str, TenantSpec] = {}
        for name, sub in (table or {}).items():
            if not isinstance(sub, dict):
                raise ConfigError(
                    f"[tenants.{name}] must be a table")
            specs[name] = build(name, sub)
        default = specs.get(DEFAULT_TENANT) or TenantSpec(
            DEFAULT_TENANT, [], rate=d_rate, byte_rate=d_byte_rate,
            burst=d_burst, byte_burst=d_byte_burst, weight=d_weight,
            queue_policy=d_policy, templates=True)
        return cls(specs, default, clock=clock)

    # -- resolution --------------------------------------------------------
    def resolve_name(self, peer: Optional[str]) -> str:
        """Tenant name for a source peer (IP, file path, or None for
        peerless inputs): first match in declaration order."""
        if peer is None:
            return DEFAULT_TENANT
        if self._exact_only:
            return self._exact.get(peer, DEFAULT_TENANT)
        try:
            addr = ipaddress.ip_address(peer)
        except ValueError:
            addr = None
        for kind, value, name in self._matchers:
            if kind == "star":
                return name
            if kind == "label":
                if peer == value:
                    return name
            elif addr is not None and addr in value:
                return name
        return DEFAULT_TENANT

    def resolve(self, peer: Optional[str]):
        return self._states[self.resolve_name(peer)]

    def state(self, name: str):
        """Admission/QoS state for a tenant name (the default tenant's
        state for unknown names, so queue attribution can never miss)."""
        return self._states.get(name) or self._states[DEFAULT_TENANT]

    def states(self):
        return self._states.values()

    def spec(self, name: str) -> TenantSpec:
        return self.specs.get(name, self.default)
