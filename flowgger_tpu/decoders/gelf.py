"""Scalar GELF JSON decoder.

Parity model: /root/reference/src/flowgger/decoder/gelf_decoder.rs:34-125.
Known keys: timestamp (f64), host, short_message, full_message, version
(must be 1.0/1.1), level (u64 ≤ 7); every other key becomes an SD pair
(``_``-prefixed if not already).  Keys are processed in *sorted* order —
serde_json 0.8's object is a BTreeMap — which fixes both SD pair order
and which error fires first on multi-error input.  A parse failure from a
raw newline inside a string retries with ``\\n`` escaped
(gelf_decoder.rs:42-48).
"""

from __future__ import annotations

import json

from . import DecodeError, Decoder
from ..record import Record, SDValue, SEVERITY_MAX, StructuredData
from ..utils.timeparse import now_precise

_U64_MAX = (1 << 64) - 1
_I64_MIN = -(1 << 63)


def _as_f64(v):
    if isinstance(v, bool):
        return None
    if isinstance(v, (int, float)):
        return float(v)
    return None


def _as_u64(v):
    if isinstance(v, bool):
        return None
    if isinstance(v, int) and 0 <= v <= _U64_MAX:
        return v
    return None


class GelfDecoder(Decoder):
    def __init__(self, config=None):
        pass

    def decode(self, line: str) -> Record:
        try:
            obj = json.loads(line)
        except json.JSONDecodeError as e:
            if e.msg.startswith("Invalid control character"):
                try:
                    obj = json.loads(line.replace("\n", "\\n"))
                except json.JSONDecodeError:
                    raise DecodeError(
                        "Invalid GELF input, unable to parse as a JSON object"
                    )
            else:
                raise DecodeError("Invalid GELF input, unable to parse as a JSON object")
        if not isinstance(obj, dict):
            raise DecodeError("Empty GELF input")

        sd = StructuredData(None)
        ts = None
        hostname = None
        msg = None
        full_msg = None
        severity = None
        for key in sorted(obj.keys()):
            value = obj[key]
            if key == "timestamp":
                ts = _as_f64(value)
                if ts is None:
                    raise DecodeError("Invalid GELF timestamp")
            elif key == "host":
                if not isinstance(value, str):
                    raise DecodeError("GELF host name must be a string")
                hostname = value
            elif key == "short_message":
                if not isinstance(value, str):
                    raise DecodeError("GELF short message must be a string")
                msg = value
            elif key == "full_message":
                if not isinstance(value, str):
                    raise DecodeError("GELF full message must be a string")
                full_msg = value
            elif key == "version":
                if not isinstance(value, str):
                    raise DecodeError("GELF version must be a string")
                if value not in ("1.0", "1.1"):
                    raise DecodeError("Unsupported GELF version")
            elif key == "level":
                sev = _as_u64(value)
                if sev is None:
                    raise DecodeError("Invalid severity level")
                if sev > SEVERITY_MAX:
                    raise DecodeError("Invalid severity level (too high)")
                severity = sev
            else:
                if isinstance(value, str):
                    sval = SDValue.string(value)
                elif isinstance(value, bool):
                    sval = SDValue.bool_(value)
                elif isinstance(value, float):
                    sval = SDValue.f64(value)
                elif isinstance(value, int):
                    if 0 <= value <= _U64_MAX:
                        sval = SDValue.u64(value)
                    elif _I64_MIN <= value < 0:
                        sval = SDValue.i64(value)
                    else:
                        raise DecodeError("Invalid value type in structured data")
                elif value is None:
                    sval = SDValue.null()
                else:
                    raise DecodeError("Invalid value type in structured data")
                name = key if key.startswith("_") else f"_{key}"
                sd.pairs.append((name, sval))
        if hostname is None:
            raise DecodeError("Missing hostname")
        return Record(
            ts=ts if ts is not None else now_precise(),
            hostname=hostname,
            severity=severity,
            msg=msg,
            full_msg=full_msg,
            sd=[sd] if sd.pairs else None,
        )
