r"""Scalar RFC5424 decoder.

Parity model: /root/reference/src/flowgger/decoder/rfc5424_decoder.rs:17-242.
Line shape: ``<PRI>1 TS HOST APP PROCID MSGID SD [msg]`` where SD is ``-``
or one or more ``[id k="v" ...]`` blocks.  Semantics preserved exactly:

- optional UTF-8 BOM before ``<`` (rs:57-72); otherwise the line must
  start with ``<``;
- the header is split on the first six spaces (``splitn(7, ' ')``), so
  empty fields between doubled spaces are possible and faithful;
- PRI is a u8 (0..=255), version must be the literal ``1``;
- SD pair names gain a ``_`` prefix; values unescape ``\"``, ``\\`` and
  ``\]`` only, any other ``\x`` stays verbatim (rs:105-125);
- ``msg`` is the whitespace-trimmed remainder, None when empty;
- ``full_msg`` is the whole line (after BOM strip) with trailing
  whitespace removed.

This scalar form doubles as the specification for the columnar kernel in
flowgger_tpu/tpu/rfc5424.py; the differential test in
tests/test_tpu_rfc5424.py holds the two paths byte-identical.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from . import DecodeError, Decoder
from ..record import Record, SDValue, StructuredData
from ..utils.timeparse import rfc3339_to_unix

_SD_NAME_EXCLUDED = {" ", '"', "=", "]"}


def _is_sd_name_char(c: str) -> bool:
    o = ord(c)
    return 33 <= o <= 126 and c not in _SD_NAME_EXCLUDED


def _unescape_sd_value(value: str) -> str:
    if "\\" not in value:
        return value
    out = []
    esc = False
    for c in value:
        if esc:
            if c in ('"', "\\", "]"):
                out.append(c)
            else:
                out.append("\\")
                out.append(c)
            esc = False
        elif c == "\\":
            esc = True
        else:
            out.append(c)
    if esc:
        out.append("\\")  # unreachable for well-formed values (closing quote)
    return "".join(out)


def _parse_pri_version(field: str) -> Tuple[int, int]:
    if not field.startswith("<"):
        raise DecodeError("The priority should be inside brackets")
    end = field.find(">", 1)
    if end < 0:
        raise DecodeError("Missing version")
    pri_s = field[1:end]
    if not pri_s.isdigit() or not pri_s.isascii():
        raise DecodeError("Invalid priority")
    pri = int(pri_s)
    if pri > 255:
        raise DecodeError("Invalid priority")
    if field[end + 1:] != "1":
        raise DecodeError("Unsupported version")
    return pri >> 3, pri & 7


def _parse_msg(line: str, offset: int) -> Optional[str]:
    if offset > len(line):
        return None
    m = line[offset:].strip()
    return m if m else None


def _parse_sd_block(sd: str) -> Tuple[Optional[int], List[Tuple[str, SDValue]]]:
    """Parse the interior of one SD element after its id, i.e. the text
    following ``[id ``; returns (index just past the closing ``]`` or None
    if unterminated, pairs).  State machine equivalent to rs:174-242
    including the tolerated bogus extra-quote case."""
    in_name = False
    in_value = False
    esc = False
    name_start = 0
    value_start = 0
    name: Optional[str] = None
    res_pairs: List[Tuple[str, SDValue]] = []
    after: Optional[int] = None

    for i, c in enumerate(sd):
        if in_value:
            if esc:
                esc = False
            elif c == "\\":
                esc = True
            elif c == '"':
                in_value = False
                assert name is not None
                res_pairs.append(
                    ("_" + name, SDValue.string(_unescape_sd_value(sd[value_start:i])))
                )
                name = None
        elif in_name:
            if c == "=":
                name = sd[name_start:i]
                in_name = False
            elif _is_sd_name_char(c):
                pass
            else:
                raise DecodeError("Format error in the structured data")
        elif name is not None:
            # between '=' and the opening quote only '"' is legal
            if c == '"':
                in_value = True
                value_start = i + 1
            else:
                raise DecodeError("Format error in the structured data")
        else:
            if c == " ":
                continue
            if c == "]":
                after = i + 1
                break
            if c == '"':
                continue  # tolerate bogus entries with an extra quote
            if _is_sd_name_char(c):
                in_name = True
                name_start = i
            else:
                raise DecodeError("Format error in the structured data")
    return after, res_pairs


def _parse_sd_data(line: str, offset: int) -> Tuple[StructuredData, str, int]:
    rest = line[offset:]
    sp = rest.find(" ")
    if sp < 0:
        raise DecodeError("Missing structured data")
    sd_id, sd = rest[:sp], rest[sp + 1:]
    after, pairs = _parse_sd_block(sd)
    if after is None:
        raise DecodeError("Missing ] after structured data")
    elem = StructuredData(sd_id)
    elem.pairs = pairs
    return elem, sd, after


def _parse_data(line: str) -> Tuple[List[StructuredData], Optional[str]]:
    if not line:
        raise DecodeError("Missing log message")
    sd_vec: List[StructuredData] = []
    c0 = line[0]
    if c0 == "-":
        return sd_vec, _parse_msg(line, 1)
    if c0 != "[":
        raise DecodeError("Malformated RFC5424 message")
    leftover, offset = line, 0
    while True:
        sd, leftover, offset = _parse_sd_data(leftover, offset + 1)
        sd_vec.append(sd)
        if offset >= len(leftover):
            raise DecodeError("Missing log message")
        nxt = leftover[offset]
        if nxt == "[":
            continue
        if nxt == " ":
            return sd_vec, _parse_msg(leftover, offset)
        raise DecodeError("Malformated RFC5424 message")


class RFC5424Decoder(Decoder):
    def __init__(self, config=None):
        pass

    def decode(self, line: str) -> Record:
        if line.startswith("\ufeff"):
            line = line[1:]
        elif not line.startswith("<"):
            raise DecodeError("Unsupported BOM")
        parts = line.split(" ", 6)
        if len(parts) < 7:
            needed = ("Missing priority and version", "Missing timestamp",
                      "Missing hostname", "Missing application name",
                      "Missing process id", "Missing message id",
                      "Missing message data")
            raise DecodeError(needed[len(parts)])
        facility, severity = _parse_pri_version(parts[0])
        try:
            ts = rfc3339_to_unix(parts[1])
        except ValueError:
            raise DecodeError(
                "Unable to parse the date from RFC3339 to Unix time in RFC5424 decoder"
            )
        hostname, appname, procid, msgid = parts[2], parts[3], parts[4], parts[5]
        sd_vec, msg = _parse_data(parts[6])
        return Record(
            ts=ts,
            hostname=hostname,
            facility=facility,
            severity=severity,
            appname=appname,
            procid=procid,
            msgid=msgid,
            msg=msg,
            full_msg=line.rstrip(),
            sd=sd_vec if sd_vec else None,
        )
