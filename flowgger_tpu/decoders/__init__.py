"""Scalar (per-line) decoders: the exactness oracle and small-batch
fallback for the batched TPU decode tier.

Parity model: /root/reference/src/flowgger/decoder/ — trait
``Decoder { decode(line: &str) -> Result<Record> }`` (decoder/mod.rs:44-46).
Decode errors are raised as ``DecodeError(str)``; the pipeline treats them
as per-message and non-fatal, matching the reference's stderr-and-drop
behavior (splitter/line_splitter.rs:37-39).
"""

from __future__ import annotations

from ..record import Record


class DecodeError(Exception):
    """Per-message decode failure; message text mirrors the reference's
    ``&'static str`` errors."""


class Decoder:
    def decode(self, line: str) -> Record:
        raise NotImplementedError


class InvalidDecoder(Decoder):
    """Placeholder paired with the capnp splitter, which never calls the
    decoder (decoder/invalid_decoder.rs:14-18, mod.rs:413-416)."""

    def __init__(self, config=None):
        pass

    def decode(self, line: str) -> Record:
        raise RuntimeError("The capnp decoder cannot be used for this input format")


from .rfc5424 import RFC5424Decoder  # noqa: E402
from .rfc3164 import RFC3164Decoder  # noqa: E402
from .gelf import GelfDecoder  # noqa: E402
from .ltsv import LTSVDecoder  # noqa: E402
from .jsonl import JSONLDecoder  # noqa: E402
from .dns import DNSDecoder  # noqa: E402

__all__ = [
    "Decoder",
    "DecodeError",
    "InvalidDecoder",
    "RFC5424Decoder",
    "RFC3164Decoder",
    "GelfDecoder",
    "LTSVDecoder",
    "JSONLDecoder",
    "DNSDecoder",
]
