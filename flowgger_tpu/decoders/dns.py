"""Scalar DNS query-log decoder — the byte-identity oracle for the
fixed-grammar columnar path (flowgger_tpu/tpu/dns.py).

Dnstap-style text/TSV query logs (one query/response event per line),
the high-volume format arxiv 2411.12035 parses at millions of
records/sec with the same fixed-grammar columnar tricks this repo's
syslog kernels use.  Line shape — exactly six tab-separated fields:

    <ts> \\t <client> \\t <qname> \\t <qtype> \\t <rcode> \\t <latency_us>

- ``ts``: unix epoch seconds, ``digits[.digits]`` (no sign/exponent);
- ``client``: the resolver client address (→ hostname), non-empty;
- ``qname``: the query name (→ msg), non-empty;
- ``qtype``/``rcode``: mnemonic or numeric text, kept verbatim as
  string SD pairs (``_qtype``/``_rcode``);
- ``latency_us``: response latency in microseconds, decimal u64
  (→ ``_latency_us`` pair).

The ``_``-prefixed pair names follow the GELF additional-field
convention (GELF output keeps them; LTSV strips the prefix).
"""

from __future__ import annotations

from . import DecodeError, Decoder
from ..record import Record, SDValue, StructuredData

_U64_MAX = (1 << 64) - 1

PARTS_ERR = "Invalid DNS record: expected 6 tab-separated fields"
TS_ERR = "Invalid DNS record timestamp"
CLIENT_ERR = "Missing DNS client address"
QNAME_ERR = "Missing DNS query name"
LATENCY_ERR = "Invalid DNS record latency"


def _ts_valid(s: str) -> bool:
    """``digits[.digits]`` — the grammar the columnar kernel fast-paths
    (and ``float()`` parses identically for)."""
    if not s:
        return False
    head, dot, tail = s.partition(".")
    if not head.isascii() or not head.isdigit():
        return False
    if dot and (not tail or not tail.isascii() or not tail.isdigit()):
        return False
    return True


class DNSDecoder(Decoder):
    def __init__(self, config=None):
        pass

    def decode(self, line: str) -> Record:
        parts = line.split("\t")
        if len(parts) != 6:
            raise DecodeError(PARTS_ERR)
        ts_s, client, qname, qtype, rcode, lat_s = parts
        if not _ts_valid(ts_s):
            raise DecodeError(TS_ERR)
        if not client:
            raise DecodeError(CLIENT_ERR)
        if not qname:
            raise DecodeError(QNAME_ERR)
        if not (lat_s.isascii() and lat_s.isdigit()):
            raise DecodeError(LATENCY_ERR)
        latency = int(lat_s)
        if latency > _U64_MAX:
            raise DecodeError(LATENCY_ERR)
        sd = StructuredData(None)
        sd.pairs.append(("_latency_us", SDValue.u64(latency)))
        sd.pairs.append(("_qtype", SDValue.string(qtype)))
        sd.pairs.append(("_rcode", SDValue.string(rcode)))
        return Record(
            ts=float(ts_s),
            hostname=client,
            msg=qname,
            sd=[sd],
        )
