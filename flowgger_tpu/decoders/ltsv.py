"""Scalar LTSV decoder.

Parity model: /root/reference/src/flowgger/decoder/ltsv_decoder.rs:23-267.
Tab-separated ``key:value`` pairs; special keys time/host/message/level;
optional typed schema ``[input.ltsv_schema]`` (string/bool/f64/i64/u64)
and per-type key suffixes ``[input.ltsv_suffixes]`` appended to names not
already carrying them.  ``time`` accepts a unix float, RFC3339, or the
apache-english form (optionally wrapped in ``[...]``).
"""

from __future__ import annotations

from typing import Dict, Optional

from . import DecodeError, Decoder
from ..config import Config, ConfigError
from ..record import Record, SDValue, StructuredData
from ..utils.timeparse import parse_english_time, rfc3339_to_unix

_TYPES = ("string", "bool", "f64", "i64", "u64")
_U64_MAX = (1 << 64) - 1
_I64_MIN, _I64_MAX = -(1 << 63), (1 << 63) - 1


def _parse_unix_strtime(s: str) -> float:
    # Rust f64::from_str: no underscores, no surrounding whitespace;
    # accepts inf/NaN/exponents.
    if not s or s != s.strip() or "_" in s:
        raise ValueError("bad float")
    return float(s)


def _parse_ts(s: str) -> float:
    try:
        return _parse_unix_strtime(s)
    except ValueError:
        pass
    try:
        return rfc3339_to_unix(s)
    except ValueError:
        pass
    try:
        return parse_english_time(s)
    except ValueError:
        raise DecodeError("Unable to parse the English to Unix timestamp in LTSV decoder")


class LTSVDecoder(Decoder):
    def __init__(self, config: Optional[Config] = None):
        self.schema: Optional[Dict[str, str]] = None
        self.suffixes: Dict[str, Optional[str]] = {t: None for t in _TYPES}
        if config is None:
            return
        schema_tbl = config.lookup_table(
            "input.ltsv_schema", "input.ltsv_schema must be a list of key/type pairs"
        )
        if schema_tbl is not None:
            self.schema = {}
            for name, sdtype in schema_tbl.items():
                if not isinstance(sdtype, str):
                    raise ConfigError("input.ltsv_schema types must be strings")
                t = sdtype.lower()
                if t not in _TYPES:
                    raise ConfigError(
                        f"Unsupported type in input.ltsv_schema for name [{name}]"
                    )
                self.schema[name] = t
        suffix_tbl = config.lookup_table(
            "input.ltsv_suffixes", "input.ltsv_suffixes must be a list of type/suffixes pairs"
        )
        if suffix_tbl is not None:
            for sdtype, suffix in suffix_tbl.items():
                if not isinstance(suffix, str):
                    raise ConfigError("input.ltsv_suffixes suffixes must be strings")
                t = sdtype.lower()
                if t == "string":
                    raise ConfigError("Strings cannot be suffixed")
                if t not in _TYPES:
                    raise ConfigError(
                        f"Unsupported type in input.ltsv_suffixes for type [{sdtype}]"
                    )
                self.suffixes[t] = suffix

    def _typed_pair(self, name: str, value: str):
        sdtype = self.schema.get(name) if self.schema is not None else None
        if sdtype is None or sdtype == "string":
            return f"_{name}", SDValue.string(value)
        suffix = self.suffixes.get(sdtype)
        if suffix is not None and not name.endswith(suffix):
            final_name = f"_{name}{suffix}"
        else:
            final_name = f"_{name}"
        if sdtype == "bool":
            if value == "true":
                return final_name, SDValue.bool_(True)
            if value == "false":
                return final_name, SDValue.bool_(False)
            raise DecodeError("Type error; boolean was expected")
        if sdtype == "f64":
            try:
                return final_name, SDValue.f64(_parse_unix_strtime(value))
            except ValueError:
                raise DecodeError("Type error; f64 was expected")
        if sdtype == "i64":
            v = _parse_int_strict(value)
            if v is None or not (_I64_MIN <= v <= _I64_MAX):
                raise DecodeError("Type error; i64 was expected")
            return final_name, SDValue.i64(v)
        # u64
        v = _parse_int_strict(value)
        if v is None or not (0 <= v <= _U64_MAX) or value.startswith("-"):
            raise DecodeError("Type error; u64 was expected")
        return final_name, SDValue.u64(v)

    def decode(self, line: str) -> Record:
        sd = StructuredData(None)
        ts = None
        hostname = None
        msg = None
        severity = None
        for part in line.split("\t"):
            k, sep, v = part.partition(":")
            if not sep:
                print(f"Missing value for name '{k}'")
                continue
            if k == "time":
                ts_s = v[1:-1] if v.startswith("[") and v.endswith("]") else v
                ts = _parse_ts(ts_s)
            elif k == "host":
                hostname = v
            elif k == "message":
                msg = v
            elif k == "level":
                sev = _parse_int_strict(v)
                if sev is None or not (0 <= sev <= 255):
                    raise DecodeError("Invalid severity level")
                if sev > 7:
                    raise DecodeError("Severity level should be <= 7")
                severity = sev
            else:
                sd.pairs.append(self._typed_pair(k, v))
        if ts is None:
            raise DecodeError("Missing timestamp")
        if hostname is None:
            raise DecodeError("Missing hostname")
        return Record(
            ts=ts,
            hostname=hostname,
            severity=severity,
            msg=msg,
            full_msg=line,
            sd=[sd] if sd.pairs else None,
        )


def _parse_int_strict(s: str) -> Optional[int]:
    """Rust integer FromStr: optional sign then ASCII digits only."""
    if not s:
        return None
    body = s[1:] if s[0] in "+-" else s
    if not body or not (body.isdigit() and body.isascii()):
        return None
    return int(s)
