"""Scalar RFC3164 (legacy syslog) decoder.

Parity model: /root/reference/src/flowgger/decoder/rfc3164_decoder.rs:31-213.
Tries the standard layout ``[<pri>]DATE HOST MSG`` first, then the custom
``[<pri>]HOST: DATE: MSG`` layout; both failures log the line to stderr
and surface the custom layout's error.  Dates are ``Mon d hh:mm:ss`` with
the current UTC year assumed, or ``yyyy Mon d hh:mm:ss``; a following
token naming an IANA timezone shifts the result.
"""

from __future__ import annotations

import sys

from . import DecodeError, Decoder
from ..record import Record
from ..utils.timeparse import parse_rfc3164_ts


def _parse_strip_pri(event: str):
    if event.startswith("<"):
        end = event.find(">")
        if end < 0:
            raise DecodeError("Malformed RFC3164 event: Invalid priority")
        pri_s = event[:end + 1].lstrip("<").rstrip(">")
        if not (pri_s.isdigit() and pri_s.isascii()) or int(pri_s) > 255:
            raise DecodeError("Invalid priority")
        npri = int(pri_s)
        return (npri >> 3, npri & 7), event[end + 1:]
    return (None, None), event


def _parse_date_token(tokens):
    if len(tokens) < 3:
        raise DecodeError("Invalid time format")
    try:
        ts, consumed = parse_rfc3164_ts(tokens, has_year=False)
    except ValueError:
        try:
            ts, consumed = parse_rfc3164_ts(tokens, has_year=True)
        except ValueError:
            raise DecodeError("Unable to parse the date in RFC3164 decoder")
    return ts, tokens[consumed:]


def _decode_standard(pri, msg: str, line: str) -> Record:
    tokens = msg.split()
    if len(tokens) <= 3:
        raise DecodeError("Malformed RFC3164 standard event: Invalid timestamp or hostname")
    ts, log_tokens = _parse_date_token(tokens)
    if not log_tokens:
        raise DecodeError("Malformed RFC3164 standard event: Invalid timestamp or hostname")
    hostname = log_tokens[0]
    message = " ".join(log_tokens[1:])
    return Record(
        ts=ts,
        hostname=hostname,
        facility=pri[0],
        severity=pri[1],
        msg=message,
        full_msg=line.rstrip(),
    )


def _decode_custom(pri, msg: str, line: str) -> Record:
    tokens = msg.split(": ")
    if len(tokens) <= 2:
        raise DecodeError("Malformed RFC3164 event: Invalid timestamp or hostname")
    hostname = tokens[0]
    ts, _ = _parse_date_token(tokens[1].split())
    message = ": ".join(tokens[2:])
    return Record(
        ts=ts,
        hostname=hostname,
        facility=pri[0],
        severity=pri[1],
        msg=message,
        full_msg=line.rstrip(),
    )


class RFC3164Decoder(Decoder):
    def __init__(self, config=None):
        pass

    def decode(self, line: str) -> Record:
        pri, msg = _parse_strip_pri(line)
        try:
            return _decode_standard(pri, msg, line)
        except DecodeError:
            pass
        try:
            return _decode_custom(pri, msg, line)
        except DecodeError as err:
            print(f"Unable to parse the rfc3164 input: '{line}'", file=sys.stderr)
            raise err
