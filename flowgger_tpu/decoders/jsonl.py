"""Scalar JSON-lines decoder — the byte-identity oracle for the
TPU-vectorized structural-index path (flowgger_tpu/tpu/jsonl.py).

Generic JSON-lines (one JSON object per line, e.g. application logs,
CloudTrail-style event streams).  Unlike GELF there is no version
handshake and every key is optional; the dialect is:

- ``timestamp`` (number) → ``Record.ts`` (absent → receive time);
- ``host`` (string) → hostname (absent → empty, rendered per encoder);
- ``message`` (string) → msg;
- ``level`` (integer 0..7) → severity;
- every other key becomes a typed SD pair, ``_``-prefixed when not
  already (the GELF additional-field convention, so GELF output needs
  no renaming and LTSV output strips the prefix back off);
- nested objects/arrays become STRING pairs holding their compact JSON
  re-serialization (``json.dumps(v, separators=(",", ":"))``) — the
  columnar path materializes the same value from the container's span.

Keys are processed in *sorted* order like the GELF decoder (which pins
both SD pair order and which error fires first on multi-error input).
"""

from __future__ import annotations

import json

from . import DecodeError, Decoder
from ..record import Record, SDValue, SEVERITY_MAX, StructuredData
from ..utils.timeparse import now_precise

_U64_MAX = (1 << 64) - 1
_I64_MIN = -(1 << 63)

PARSE_ERR = "Invalid JSON-lines input, unable to parse as a JSON object"


def nested_json(value) -> str:
    """THE compact re-serialization of a nested container value —
    single-sourced so the oracle and the columnar materializer
    (tpu/materialize_jsonl.py) cannot drift."""
    return json.dumps(value, separators=(",", ":"))


def route_obj(obj: dict) -> Record:
    """THE sorted-key routing/validation of one parsed object into a
    Record — single-sourced so the oracle and the columnar
    materializer (tpu/materialize_jsonl.py builds the same dict from
    token spans) cannot drift on rule changes.  Raises DecodeError."""
    sd = StructuredData(None)
    ts = None
    hostname = None
    msg = None
    severity = None
    for key in sorted(obj.keys()):
        value = obj[key]
        if key == "timestamp":
            if isinstance(value, bool) or not isinstance(
                    value, (int, float)):
                raise DecodeError("Invalid JSON-lines timestamp")
            ts = float(value)
        elif key == "host":
            if not isinstance(value, str):
                raise DecodeError("JSON-lines host must be a string")
            hostname = value
        elif key == "message":
            if not isinstance(value, str):
                raise DecodeError("JSON-lines message must be a string")
            msg = value
        elif key == "level":
            if isinstance(value, bool) or not isinstance(value, int) \
                    or value < 0:
                raise DecodeError("Invalid severity level")
            if value > SEVERITY_MAX:
                raise DecodeError("Invalid severity level (too high)")
            severity = value
        else:
            if isinstance(value, str):
                sval = SDValue.string(value)
            elif isinstance(value, bool):
                sval = SDValue.bool_(value)
            elif isinstance(value, float):
                sval = SDValue.f64(value)
            elif isinstance(value, int):
                if 0 <= value <= _U64_MAX:
                    sval = SDValue.u64(value)
                elif _I64_MIN <= value < 0:
                    sval = SDValue.i64(value)
                else:
                    raise DecodeError(
                        "Invalid value type in structured data")
            elif value is None:
                sval = SDValue.null()
            elif isinstance(value, (dict, list)):
                sval = SDValue.string(nested_json(value))
            else:
                raise DecodeError(
                    "Invalid value type in structured data")
            name = key if key.startswith("_") else f"_{key}"
            sd.pairs.append((name, sval))
    return Record(
        ts=ts if ts is not None else now_precise(),
        hostname=hostname if hostname is not None else "",
        severity=severity,
        msg=msg,
        sd=[sd] if sd.pairs else None,
    )


class JSONLDecoder(Decoder):
    def __init__(self, config=None):
        pass

    def decode(self, line: str) -> Record:
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            raise DecodeError(PARSE_ERR)
        if not isinstance(obj, dict):
            raise DecodeError("JSON-lines record must be an object")
        return route_obj(obj)
