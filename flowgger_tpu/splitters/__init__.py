"""Splitters: turn a byte stream into framed messages and push them
through a Handler (decode → encode → enqueue).

Parity model: /root/reference/src/flowgger/splitter/ — trait
``Splitter<T> { run(BufReader<T>, tx, decoder, encoder) }``
(splitter/mod.rs:18-26).  Redesign for the batched TPU path: instead of
baking ``decode→encode→send`` into each splitter (the reference's
``handle_line``, line_splitter.rs:44-54), splitters feed a *Handler*.
``ScalarHandler`` reproduces the reference's per-line semantics exactly;
``flowgger_tpu.tpu.batch.BatchHandler`` accumulates lines into a packed
byte tensor and decodes them on the TPU in bulk.  Handlers receive raw
``bytes`` so the hot path never materializes per-line ``str`` objects.

Stream contract: a binary file-like with ``read(n)`` returning ``b""`` on
EOF; idle timeouts surface as ``TimeoutError`` and are treated like the
reference's ``WouldBlock`` (close the idle connection).
"""

from __future__ import annotations

import struct as _struct
import sys
from typing import Optional

from .. import capnp_wire
from ..decoders import DecodeError
from ..encoders import EncodeError
from ..record import FACILITY_MAX, Record, SEVERITY_MAX, StructuredData
from ..utils.metrics import registry as _metrics

_CHUNK = 1 << 16


class Handler:
    """Sink for framed messages coming out of a splitter."""

    quiet_empty = False  # NulSplitter sets this: suppress empty-frame errors
    bare_errors = False  # UdpInput sets this: errors print without the line
                         # (udp_input.rs:84-86 vs line_splitter.rs:38)
    ingest_sep = b"\n"   # set by the splitter when a chunk-capable handler
    ingest_strip_cr = True  # receives regions framed on another separator

    def handle_bytes(self, raw: bytes) -> None:
        raise NotImplementedError

    def handle_record(self, record: Record) -> None:
        """Used by the capnp splitter, which bypasses the decoder."""
        raise NotImplementedError

    def flush(self) -> None:
        """Called at end-of-stream (and by batching handlers on timers)."""

    def wants_raw(self, framing: str) -> bool:
        """Device-resident framing (input.tpu_framing): a handler that
        returns True gets *raw* transport chunks via a per-connection
        session (``open_raw``) and finds record boundaries itself — the
        splitter does zero scanning.  Default: host framing as ever."""
        return False


class ScalarHandler(Handler):
    """Reference-exact per-line path: utf-8 validate → decode → encode →
    enqueue; errors go to stderr and drop the message
    (line_splitter.rs:17-54)."""

    # applied to every decoded Record before encode (tenancy template
    # enrichment keeps the degraded scalar path byte-identical to the
    # Record route it falls back from); None = zero-cost no-op
    record_hook = None

    def __init__(self, tx, decoder, encoder):
        self.tx = tx
        self.decoder = decoder
        self.encoder = encoder
        # set by NulSplitter.run: suppress error reports for empty frames
        self.quiet_empty = False

    def handle_bytes(self, raw: bytes) -> None:
        try:
            line = raw.decode("utf-8")
        except UnicodeDecodeError:
            _metrics.inc("invalid_utf8")
            print("Invalid UTF-8 input", file=sys.stderr)
            return
        self.handle_line(line)

    def handle_line(self, line: str) -> None:
        _metrics.inc("input_lines")
        try:
            record = self.decoder.decode(line)
            if self.record_hook is not None:
                self.record_hook(record)
            encoded = self.encoder.encode(record)
        except DecodeError as e:
            _metrics.inc("decode_errors")
            self._report_error(e, line)
            return
        except EncodeError as e:
            _metrics.inc("encode_errors")
            self._report_error(e, line)
            return
        _metrics.inc("decoded_records")
        _metrics.inc("enqueued")
        self.tx.put(encoded)

    def _report_error(self, e, line: str) -> None:
        if self.bare_errors:
            print(e, file=sys.stderr)
            return
        stripped = line.strip()
        if not (self.quiet_empty and not stripped):
            print(f"{e}: [{stripped}]", file=sys.stderr)

    def handle_record(self, record: Record) -> None:
        try:
            if self.record_hook is not None:
                self.record_hook(record)
            encoded = self.encoder.encode(record)
        except EncodeError as e:
            print(e, file=sys.stderr)
            return
        self.tx.put(encoded)


class Splitter:
    def run(self, stream, handler: Handler) -> None:
        raise NotImplementedError


class LineAssembler:
    """Carry-over framing: split incoming chunks on a separator, holding
    the partial tail until the next chunk — the same carry the TPU
    batcher keeps between batches (SURVEY.md §5 long-context note).
    Shared by the stream splitters and the file tailer."""

    def __init__(self, handler: Handler, sep: bytes = b"\n", strip_cr: bool = True):
        self.handler = handler
        self.sep = sep
        self.strip_cr = strip_cr
        self.carry = b""

    def push(self, chunk: bytes) -> None:
        parts = (self.carry + chunk).split(self.sep)
        self.carry = parts.pop()
        for part in parts:
            if self.strip_cr and part.endswith(b"\r"):
                part = part[:-1]
            self.handler.handle_bytes(part)

    def finish(self) -> None:
        """Emit the trailing partial line (BufRead::lines yields it too)."""
        if self.carry:
            part = self.carry
            self.carry = b""
            if self.strip_cr and part.endswith(b"\r"):
                part = part[:-1]
            self.handler.handle_bytes(part)


def _read_stream(stream):
    """Yield chunks until EOF; idle timeouts print the reference's
    WouldBlock close notice (line_splitter.rs:26-33) and end the stream."""
    from ..utils import faultinject as _faults

    while True:
        try:
            if _faults.enabled():
                # chaos site: a reset here closes this connection like a
                # real peer reset; the accept loop keeps serving
                _faults.maybe_raise("input_socket", ConnectionResetError)
            chunk = stream.read(_CHUNK)
        except TimeoutError:
            print(
                "Client hasn't sent any data for a while - Closing idle connection",
                file=sys.stderr,
            )
            return
        except OSError:
            return
        if not chunk:
            return
        yield chunk


def _run_raw_sep(stream, handler: Handler, framing: str) -> None:
    """Device-framing fast path for line/nul: hand every raw chunk to
    the handler's per-connection session untouched (record boundaries —
    including the carry for records split across chunk edges — resolve
    on device, or on the handler's host fallback).  EOF semantics match
    the host path: the session's ``finish`` emits a trailing partial
    frame exactly like ``_run_chunked``."""
    sess = handler.open_raw(framing)
    for chunk in _read_stream(stream):
        if not sess.push(chunk):
            break
    sess.finish()
    handler.flush()


def _run_raw_syslen(stream, handler: Handler) -> None:
    """Device-framing fast path for syslen framing: raw chunks to the
    session; the octet-count scan happens on device (host scan on
    decline).  Stderr parity with ``SyslenSplitter._run_spans``: idle
    and EOF leftovers print the same messages (ordering may differ by
    one flush — the messages come from the session, which owns the
    carry)."""
    sess = handler.open_raw("syslen")
    while True:
        try:
            chunk = stream.read(_CHUNK)
        except TimeoutError:
            sess.finish(idle=True)
            return
        except OSError:
            chunk = b""
        if not chunk:
            break
        if not sess.push(chunk):
            # mid-stream framing error: the session printed the host
            # scan's message and went dead — close like the host path.
            # finish() still runs so the dead session unregisters from
            # the handler (it prints nothing more); without it every
            # errored connection would leak one session entry.
            sess.finish()
            handler.flush()
            return
    sess.finish()
    handler.flush()


def _read_chunks_split(stream, handler: Handler, sep: bytes, strip_cr: bool):
    """Shared chunked scan for line/nul framing: bulk ``bytes.split`` per
    chunk (C speed) instead of the reference's per-byte BufRead loop."""
    asm = LineAssembler(handler, sep, strip_cr)
    for chunk in _read_stream(stream):
        asm.push(chunk)
    asm.finish()
    handler.flush()


class LineSplitter(Splitter):
    """``\\n`` framing with trailing-``\\r`` strip (line_splitter.rs:9-41).

    Handlers exposing ``ingest_chunk`` (the TPU BatchHandler) get whole
    complete-line regions instead of per-line bytes: the splitter only
    finds the last newline per read — framing happens columnar/native
    downstream, so the per-message Python cost on the hot path is zero.
    """

    def run(self, stream, handler: Handler) -> None:
        if handler.wants_raw("line"):
            _run_raw_sep(stream, handler, "line")
        elif hasattr(handler, "ingest_chunk"):
            self._run_chunked(stream, handler)
        else:
            _read_chunks_split(stream, handler, b"\n", strip_cr=True)

    @staticmethod
    def _run_chunked(stream, handler: Handler, sep: bytes = b"\n",
                     strip_cr: bool = True) -> None:
        handler.ingest_sep = sep
        handler.ingest_strip_cr = strip_cr
        carry = b""
        for chunk in _read_stream(stream):
            data = carry + chunk if carry else chunk
            cut = data.rfind(sep)
            if cut < 0:
                carry = data
                continue
            handler.ingest_chunk(data[:cut + 1])
            carry = data[cut + 1:]
        if carry:
            if strip_cr and carry.endswith(b"\r"):
                carry = carry[:-1]
            handler.handle_bytes(carry)
        handler.flush()


class NulSplitter(Splitter):
    """NUL framing; errors on all-whitespace frames are suppressed
    (nul_splitter.rs:10-49).  Chunk-capable handlers (the TPU
    BatchHandler) get whole NUL-terminated regions, same zero-per-
    message contract as LineSplitter."""

    def run(self, stream, handler: Handler) -> None:
        handler.quiet_empty = True
        if handler.wants_raw("nul"):
            _run_raw_sep(stream, handler, "nul")
        elif hasattr(handler, "ingest_chunk"):
            LineSplitter._run_chunked(stream, handler, b"\0", strip_cr=False)
        else:
            _read_chunks_split(stream, handler, b"\0", strip_cr=False)


def _scan_syslen_region(chunk: bytes):
    """(starts, lens, n, consumed, bad_prefix): batched octet-count scan
    — native memchr loop with a Python fallback."""
    from .. import native

    res = native.split_syslen_native(chunk)
    if res is not None:
        return res
    import numpy as np

    starts, lens = [], []
    pos = 0
    err = False
    size = len(chunk)
    while pos < size:
        sp = chunk.find(b" ", pos)
        if sp < 0:
            break
        len_s = chunk[pos:sp]
        if not len_s.isdigit():
            err = True
            break
        val = int(len_s)
        if val > 2**31 - 1:
            # same guard as the native scan: int32 span arrays cannot
            # describe such frames, and buffering one unboundedly would
            # never complete anyway
            err = True
            break
        if sp + 1 + val > size:
            break
        starts.append(sp + 1)
        lens.append(val)
        pos = sp + 1 + val
    return (np.array(starts, np.int32), np.array(lens, np.int32),
            len(starts), pos, err)


class SyslenSplitter(Splitter):
    """RFC5425-style octet counting: ASCII decimal length, one space, then
    exactly that many bytes (syslen_splitter.rs:10-69).

    Span-capable handlers (the TPU BatchHandler) get whole regions with
    pre-computed frame offset/length arrays from one native scan, so the
    reference's ``framed=true`` production mode is zero-per-message too.
    """

    def run(self, stream, handler: Handler) -> None:
        if handler.wants_raw("syslen"):
            _run_raw_syslen(stream, handler)
            return
        if hasattr(handler, "ingest_spans"):
            self._run_spans(stream, handler)
            return
        self._run_scalar(stream, handler)

    @staticmethod
    def _mid_body(buf: bytes) -> bool:
        """True when the carry holds a valid length prefix awaiting its
        body — the scalar loop would be in its read-body phase."""
        sp = buf.find(b" ")
        return sp > 0 and buf[:sp].isdigit()

    @staticmethod
    def _run_spans(stream, handler: Handler) -> None:
        buf = b""
        while True:
            try:
                chunk = stream.read(_CHUNK)
            except TimeoutError:
                # stderr parity with _run_scalar: idle in the prefix
                # phase closes quietly; idle mid-body is a short read
                if SyslenSplitter._mid_body(buf):
                    print("failed to fill whole buffer", file=sys.stderr)
                else:
                    print(
                        "Client hasn't sent any data for a while - "
                        "Closing idle connection",
                        file=sys.stderr,
                    )
                handler.flush()
                return
            except OSError:
                chunk = b""
            if not chunk:
                break
            buf = buf + chunk if buf else chunk
            starts, lens, n, consumed, err = _scan_syslen_region(buf)
            if n:
                handler.ingest_spans(buf[:consumed], starts, lens)
            if err:
                print("Can't read message's length", file=sys.stderr)
                handler.flush()
                return
            buf = buf[consumed:]
        if buf:
            # EOF mid-frame: incomplete body vs bad/absent length prefix
            if SyslenSplitter._mid_body(buf):
                print("failed to fill whole buffer", file=sys.stderr)
            else:
                print("Can't read message's length", file=sys.stderr)
        handler.flush()

    @staticmethod
    def _run_scalar(stream, handler: Handler) -> None:
        buf = b""
        while True:
            # read length prefix up to the space
            sp = buf.find(b" ")
            while sp < 0:
                try:
                    chunk = stream.read(_CHUNK)
                except TimeoutError:
                    print(
                        "Client hasn't sent any data for a while - Closing idle connection",
                        file=sys.stderr,
                    )
                    handler.flush()
                    return
                except OSError:
                    chunk = b""
                if not chunk:
                    if buf:
                        print("Can't read message's length", file=sys.stderr)
                    handler.flush()
                    return
                buf += chunk
                sp = buf.find(b" ")
            len_s = buf[:sp]
            if not len_s.isdigit():
                print("Can't read message's length", file=sys.stderr)
                handler.flush()
                return
            size = int(len_s)
            buf = buf[sp + 1:]
            while len(buf) < size:
                try:
                    chunk = stream.read(_CHUNK)
                except (TimeoutError, OSError):
                    chunk = b""
                if not chunk:
                    print("failed to fill whole buffer", file=sys.stderr)
                    handler.flush()
                    return
                buf += chunk
            msg, buf = buf[:size], buf[size:]
            handler.handle_bytes(msg)


class CapnpSplitter(Splitter):
    """Binary Cap'n Proto stream; builds Records directly from the wire
    (bypassing the decoder) and hands them to the handler
    (capnp_splitter.rs:15-167)."""

    def run(self, stream, handler: Handler) -> None:
        buf = b""

        def read_exact(n: int) -> Optional[bytes]:
            nonlocal buf
            while len(buf) < n:
                try:
                    chunk = stream.read(_CHUNK)
                except TimeoutError:
                    print(
                        "Client hasn't sent any data for a while - Closing idle connection",
                        file=sys.stderr,
                    )
                    return None
                except OSError:
                    return None
                if not chunk:
                    return None
                buf += chunk
            out, buf = buf[:n], buf[n:]
            return out

        while True:
            head = read_exact(4)
            if head is None:
                break
            nseg = _struct.unpack("<I", head)[0] + 1
            table_rest = read_exact(4 * nseg + (4 * nseg + 4) % 8)
            if table_rest is None:
                print("Capnp decoding error: truncated segment table", file=sys.stderr)
                break
            sizes = _struct.unpack_from(f"<{nseg}I", table_rest, 0)
            body = read_exact(8 * sum(sizes))
            if body is None:
                print("Capnp decoding error: truncated message", file=sys.stderr)
                break
            try:
                reader = capnp_wire.parse_message(head + table_rest + body)
                record = _record_from_capnp(reader)
            except _MessageError as e:
                print(e, file=sys.stderr)
                continue
            except (capnp_wire.CapnpDecodeError, _struct.error, IndexError,
                    ValueError, UnicodeDecodeError) as e:
                # malformed wire data must not crash the input loop — the
                # reference logs and closes (capnp_splitter.rs:27-31)
                print(f"Capnp decoding error: {e}", file=sys.stderr)
                break
            handler.handle_record(record)
        handler.flush()


class _MessageError(Exception):
    pass


def _record_from_capnp(reader: "capnp_wire.RecordReader") -> Record:
    """handle_message + get_sd + get_pairs (capnp_splitter.rs:65-167):
    nan/non-positive ts rejected; facility/severity above their max read
    as missing; pairs get the ``_`` prefix; extra pairs only keep string
    values; sd is always present (capnp null text reads as "")."""
    ts = reader.get_ts()
    if ts != ts or ts <= 0.0:
        raise _MessageError("Missing timestamp")
    facility = reader.get_facility()
    severity = reader.get_severity()
    pairs = []
    for name, value in reader.get_pairs():
        if not name.startswith("_"):
            name = f"_{name}"
        pairs.append((name, value))
    for name, value in reader.get_extra():
        if value.kind == value.STRING:
            pairs.append((name, value))
    sd = StructuredData(reader.get_sd_id())
    sd.pairs = pairs
    return Record(
        ts=ts,
        hostname=reader.get_hostname(),
        facility=facility if facility <= FACILITY_MAX else None,
        severity=severity if severity <= SEVERITY_MAX else None,
        appname=reader.get_appname(),
        procid=reader.get_procid(),
        msgid=reader.get_msgid(),
        msg=reader.get_msg(),
        full_msg=reader.get_full_msg(),
        sd=[sd],
    )


def get_splitter(framing: str) -> Splitter:
    """Framing-name → splitter (stdin_input.rs:56-63 match arms)."""
    if framing == "capnp":
        return CapnpSplitter()
    if framing == "line":
        return LineSplitter()
    if framing == "syslen":
        return SyslenSplitter()
    if framing == "nul":
        return NulSplitter()
    from ..config import ConfigError

    raise ConfigError("Unsupported framing scheme")
