"""Device-mesh sharding for the columnar decoders."""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..tpu import rfc5424


def make_decode_mesh(devices: Optional[Sequence] = None,
                     sp: int = 1) -> Mesh:
    """Mesh over ``devices`` with axes (dp, sp).  ``sp`` > 1 enables
    sequence-parallel decode of the packed byte axis."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if n % sp != 0:
        raise ValueError(f"device count {n} not divisible by sp={sp}")
    arr = np.asarray(devices).reshape(n // sp, sp)
    return Mesh(arr, axis_names=("dp", "sp"))


def make_sharded_decode_fn(mesh: Mesh, max_sd: int = rfc5424.DEFAULT_MAX_SD,
                           max_pairs: int = rfc5424.DEFAULT_MAX_PAIRS):
    """jit the columnar decoder over the mesh: rows over dp, bytes over
    sp.  Outputs are row-sharded over dp (replicated over sp), ready for
    a sharded columnar encode stage or host gather."""
    batch_sharding = NamedSharding(mesh, P("dp", "sp"))
    lens_sharding = NamedSharding(mesh, P("dp"))
    out_sharding = NamedSharding(mesh, P("dp"))

    @functools.partial(
        jax.jit,
        in_shardings=(batch_sharding, lens_sharding),
        out_shardings=out_sharding,
    )
    def fn(batch, lens):
        return rfc5424.decode_rfc5424(batch, lens, max_sd=max_sd,
                                      max_pairs=max_pairs)

    return fn


def decode_sharded(mesh: Mesh, batch, lens):
    """One-shot helper: shard inputs onto the mesh and decode."""
    fn = make_sharded_decode_fn(mesh)
    return fn(batch, lens)
