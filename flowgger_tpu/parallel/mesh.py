"""Device-mesh sharding for the columnar decoders.

Log decode is embarrassingly parallel over records (SURVEY.md §2.8), so
the mesh carries two axes: ``dp`` shards batch rows across chips (ICI or
DCN — no cross-record collectives exist on this path) and ``sp`` shards
the packed byte axis of very long records inside a host.  Every format
kernel (rfc5424 / ltsv / gelf / rfc3164 / the auto-detect classifier)
shards the same way; ``ShardedDecode`` wraps the jitted sharded kernel
together with the input placement (pad rows to a dp multiple, then
``jax.device_put`` with the batch sharding) so the production
BatchHandler can swap it in for the single-chip submit path.

Mesh vs lane dispatch (tpu/overlap.py LaneSet): the mesh shards ONE
batch across every chip (lowest latency per batch, one compiled
program, cross-chip synchronization per dispatch); lane dispatch gives
each chip its OWN whole batches (highest throughput, zero cross-chip
traffic, per-chip degradation).  The production BatchHandler defaults
to lanes on multi-chip hosts and disables the mesh when more than one
lane resolves — ``input.tpu_mesh = "on"`` pins the mesh instead (and is
a config error combined with ``input.tpu_lanes > 1``).  Multi-host
deployments compose identically either way: each host lane-dispatches
(or meshes) only its own ingest stream over its own chips, with the
process group joined by ``parallel/distributed.py``'s
``tpu_coordinator*`` keys.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..tpu import rfc5424


def make_decode_mesh(devices: Optional[Sequence] = None,
                     sp: int = 1) -> Mesh:
    """Mesh over ``devices`` with axes (dp, sp).  ``sp`` > 1 enables
    sequence-parallel decode of the packed byte axis."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if n % sp != 0:
        raise ValueError(f"device count {n} not divisible by sp={sp}")
    arr = np.asarray(devices).reshape(n // sp, sp)
    return Mesh(arr, axis_names=("dp", "sp"))


def _decode_body(fmt: str, **kw):
    """The un-jitted decode body for one format, normalized to
    ``fn(batch, lens, *extra)``."""
    if fmt == "rfc5424":
        return lambda b, ln: rfc5424.decode_rfc5424(
            b, ln, max_sd=kw.get("max_sd", rfc5424.DEFAULT_MAX_SD),
            max_pairs=kw.get("max_pairs", rfc5424.DEFAULT_MAX_PAIRS),
            extract_impl=kw.get("extract_impl", "sum"))
    if fmt == "ltsv":
        from ..tpu import ltsv

        return lambda b, ln: ltsv.decode_ltsv(
            b, ln, max_parts=kw.get("max_parts", ltsv.DEFAULT_MAX_PARTS))
    if fmt == "gelf":
        from ..tpu import gelf

        return lambda b, ln: gelf.decode_gelf(
            b, ln, max_fields=kw.get("max_fields",
                                     gelf.DEFAULT_MAX_FIELDS))
    if fmt == "rfc3164":
        from ..tpu import rfc3164

        return lambda b, ln, year: rfc3164.decode_rfc3164(b, ln, year)
    if fmt == "classify":
        from ..tpu import autodetect

        return autodetect.classify_device
    raise ValueError(f"no sharded decode for format {fmt}")


def make_sharded_decode_fn(mesh: Mesh, fmt: str = "rfc5424", **kw):
    """jit one format's columnar decoder over the mesh: rows over dp,
    bytes over sp.  Outputs are row-sharded over dp (replicated over
    sp), ready for a sharded device-encode stage or host gather.
    rfc3164's trailing ``year`` argument rides replicated."""
    batch_sharding = NamedSharding(mesh, P("dp", "sp"))
    lens_sharding = NamedSharding(mesh, P("dp"))
    out_sharding = NamedSharding(mesh, P("dp"))
    body = _decode_body(fmt, **kw)
    extra = (NamedSharding(mesh, P()),) if fmt == "rfc3164" else ()

    return jax.jit(
        body,
        in_shardings=(batch_sharding, lens_sharding) + extra,
        out_shardings=out_sharding,
    )


class ShardedDecode:
    """A jitted sharded decode plus its input placement, pluggable into
    the per-format ``decode_*_submit`` functions."""

    def __init__(self, mesh: Mesh, fmt: str, **kw):
        self.mesh = mesh
        self.fmt = fmt
        # the kernel parameters actually baked into the jitted fn —
        # submit paths must record these in their handles, not their
        # own arguments (rescue/encode stages trust the handle)
        self.kw = dict(kw)
        self.fn = make_sharded_decode_fn(mesh, fmt, **kw)
        self.batch_sharding = NamedSharding(mesh, P("dp", "sp"))
        self.lens_sharding = NamedSharding(mesh, P("dp"))
        self.dp = mesh.shape["dp"]
        self.sp = mesh.shape["sp"]
        self._put_cache = None  # one-slot: (batch_obj, lens_obj, placed)
        self._frozen = []       # arrays we set read-only for the cache entry

    def put(self, batch, lens):
        """Pad rows to a dp multiple (padding rows have len 0 and fall
        outside ``n_real``) and place both arrays on the mesh.  Repeat
        calls with the *same* host arrays (dryrun, rescue paths) reuse
        the first placement instead of re-padding + re-uploading.

        Contract: the cache keys on object identity, so callers must
        treat a batch passed to put() as frozen — mutating it in place
        and re-putting would decode the stale device copy.  Every
        packer allocates fresh arrays per batch; a future pooled-buffer
        packer must copy (or bypass the sharded path) instead of
        rewriting a previously-put array."""
        if self._put_cache is not None:
            cb, cl, placed = self._put_cache
            if cb is batch and cl is lens:
                return placed
        orig = (batch, lens)
        batch = np.asarray(batch)
        lens = np.asarray(lens)
        n, L = batch.shape
        if L % self.sp:
            raise ValueError(
                f"packed width {L} not divisible by sp={self.sp}")
        pad = (-n) % self.dp
        if pad:
            batch = np.pad(batch, ((0, pad), (0, 0)))
            lens = np.pad(lens, (0, pad))
        placed = (jax.device_put(batch, self.batch_sharding),
                  jax.device_put(lens, self.lens_sharding))
        # enforce the freeze contract: a cached numpy batch is made
        # read-only so an in-place mutation + re-put raises instead of
        # silently decoding the stale device copy (ADVICE r4).  The
        # freeze is scoped to the cache entry's lifetime: arrays WE
        # froze thaw on eviction, so refilling a buffer after a later
        # put displaced it stays legal.
        for a in self._frozen:
            a.flags.writeable = True
        self._frozen = []
        for a in orig:
            if isinstance(a, np.ndarray) and a.flags.writeable:
                a.flags.writeable = False
                self._frozen.append(a)
        # hold the original objects so their ids can't be recycled
        self._put_cache = (orig[0], orig[1], placed)
        return placed


def decode_sharded(mesh: Mesh, batch, lens):
    """One-shot helper: shard inputs onto the mesh and decode."""
    fn = make_sharded_decode_fn(mesh)
    return fn(batch, lens)
