"""Multi-chip parallelism for the batched decode tier.

The reference's parallelism is thread-per-connection on one host
(SURVEY.md §2.8); log decode has no cross-record dependencies, so the
TPU-native scale-out is sharding the batch over a device mesh:

- ``dp`` (data parallel): rows (= log lines) split across chips; zero
  communication — the embarrassingly-parallel axis.
- ``sp`` (sequence parallel): the byte axis of the packed ``[N, L]``
  tensor split across chips, for very long records (the analogue of the
  reference's records-spanning-buffer-boundaries concern, SURVEY.md §5).
  The kernel's cumulative scans and top_k reductions then span shards;
  XLA inserts the ICI collectives (the "pick a mesh, annotate shardings,
  let XLA insert collectives" recipe).

Multi-host: the same mesh spans hosts (jax.distributed), dp traffic
rides DCN trivially since there is none; sp stays intra-host by
construction when ``sp`` ≤ chips-per-host.
"""

from .mesh import decode_sharded, make_decode_mesh, make_sharded_decode_fn

__all__ = ["make_decode_mesh", "make_sharded_decode_fn", "decode_sharded"]
