"""Multi-host deployment: jax.distributed initialization driven by the
TOML config, and the global decode mesh that spans all hosts.

Log decode is embarrassingly parallel over records (SURVEY.md §2.8: the
reference has no cross-record communication to preserve), so the
multi-host story is data parallelism over DCN: every host runs its own
transport/ingest stack, hosts join one JAX process group, and the decode
mesh's ``dp`` axis spans all chips — each host feeds its addressable
shard, no collectives cross hosts on the decode path.  ICI still
carries the (dp, sp) sharding inside each host.

Config keys (all under ``[input]``, alongside the other tpu_* keys):

    tpu_coordinator = "10.0.0.1:8476"   # coordinator address
    tpu_num_processes = 4               # total hosts
    tpu_process_id = 0                  # this host's rank

See ``examples/multihost-dp.toml`` for a complete dp-over-DCN config.

The JAX process group is only half the multi-host story: membership,
per-host health export, and drain-on-departure live in
``flowgger_tpu/fleet`` (``input.tpu_fleet_*`` keys, which default their
rank/size from the spec above) — the heartbeat layer deliberately runs
beside, not through, JAX so a dead peer never blocks decode.
"""

from __future__ import annotations

from typing import Optional

from ..config import Config, ConfigError


def distributed_spec(config: Config):
    """(coordinator, num_processes, process_id) or None when the config
    doesn't request multi-host operation.  Validation panics with the
    key name, matching the reference's config error style."""
    coord = config.lookup_str(
        "input.tpu_coordinator", "input.tpu_coordinator must be a string")
    if coord is None:
        return None
    nproc = config.lookup_int(
        "input.tpu_num_processes",
        "input.tpu_num_processes must be an integer")
    pid = config.lookup_int(
        "input.tpu_process_id", "input.tpu_process_id must be an integer")
    if nproc is None or pid is None:
        raise ConfigError(
            "input.tpu_coordinator requires tpu_num_processes and "
            "tpu_process_id")
    if not (0 <= pid < nproc):
        raise ConfigError(
            "input.tpu_process_id must be in [0, tpu_num_processes)")
    return coord, int(nproc), int(pid)


def init_distributed(config: Config) -> bool:
    """Join the JAX process group when the config asks for it.  Returns
    True when distributed mode was initialized.  Safe to call once at
    pipeline construction; all hosts must call it before any device op.
    """
    spec = distributed_spec(config)
    if spec is None:
        return False
    coord, nproc, pid = spec
    import jax

    jax.distributed.initialize(
        coordinator_address=coord, num_processes=nproc, process_id=pid)
    return True


def make_global_decode_mesh(config: Optional[Config] = None, sp: int = 1):
    """Mesh over every device in the process group (all hosts): rows
    over ``dp`` (spanning DCN — embarrassingly parallel, no cross-host
    collectives on the decode path), bytes over ``sp`` (inside a host).
    Call after ``init_distributed``.

    Since PR 5, lane dispatch supersedes the sharded mesh whenever more
    than one lane resolves — each chip gets its *own* batches, and a
    global mesh would be built and never consulted.  Passing the
    ``config`` makes that conflict a ``ConfigError`` at config time
    (the fleet path always does) instead of silently constructing dead
    weight: callers that genuinely want the sharded-mesh path must pin
    ``tpu_mesh = "on"`` and leave ``tpu_lanes`` at 1/absent."""
    if config is not None:
        mesh_mode = config.lookup_str(
            "input.tpu_mesh", "input.tpu_mesh must be a string", "auto")
        if mesh_mode == "off":
            raise ConfigError(
                'input.tpu_mesh = "off": refusing to build a global '
                "decode mesh this config will never consult")
        lanes = config.lookup_int(
            "input.tpu_lanes",
            "input.tpu_lanes must be an integer (device lanes)", None)
        if lanes is not None and lanes > 1:
            raise ConfigError(
                "input.tpu_lanes > 1: lane dispatch supersedes the "
                "sharded decode mesh (each chip gets its own batches), "
                "so a global decode mesh would be dead weight — drop "
                'tpu_lanes or set tpu_mesh = "on" with tpu_lanes = 1')
        sp = config.lookup_int(
            "input.tpu_sp", "input.tpu_sp must be an integer", sp)
    from .mesh import make_decode_mesh
    import jax

    return make_decode_mesh(jax.devices(), sp=sp)
