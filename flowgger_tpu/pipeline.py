"""Orchestrator: config → component factories → queue wiring → run.

Parity model: /root/reference/src/flowgger/mod.rs:95-472 — defaults,
factory match arms, output-framing inference table, bounded queue, output
consumer startup, blocking input loop.

TPU extension: ``input.format`` values suffixed ``_tpu`` (rfc5424_tpu,
gelf_tpu, ltsv_tpu, auto_tpu) select the batched columnar decode path
(flowgger_tpu.tpu): the scalar decoder for that format is still
constructed as the per-line fallback oracle, and the handler factory
returns a BatchHandler instead of a ScalarHandler.
"""

from __future__ import annotations

import queue
from typing import Optional

from .config import Config, ConfigError
from .decoders import (
    GelfDecoder,
    InvalidDecoder,
    LTSVDecoder,
    RFC3164Decoder,
    RFC5424Decoder,
)
from .encoders import (
    CapnpEncoder,
    GelfEncoder,
    LTSVEncoder,
    PassthroughEncoder,
    RFC3164Encoder,
    RFC5424Encoder,
)
from .mergers import LineMerger, NulMerger, SyslenMerger
from .splitters import ScalarHandler

# mod.rs:101-109
DEFAULT_INPUT_FORMAT = "rfc5424"
DEFAULT_INPUT_TYPE = "syslog-tls"
DEFAULT_OUTPUT_FORMAT = "gelf"
DEFAULT_OUTPUT_FRAMING = "noop"
DEFAULT_OUTPUT_TYPE = "kafka"
DEFAULT_QUEUE_SIZE = 10_000_000


def get_input(input_type: str, config: Config):
    """Input factory (mod.rs:181-193)."""
    if input_type == "redis":
        from .inputs.redis_input import RedisInput

        return RedisInput(config)
    if input_type == "stdin":
        from .inputs import StdinInput

        return StdinInput(config)
    if input_type in ("tcp", "syslog-tcp"):
        from .inputs.tcp_input import TcpInput

        return TcpInput(config)
    if input_type in ("tcp_co", "tcpco", "syslog-tcp_co", "syslog-tcpco"):
        from .inputs.tcp_input import TcpCoInput

        return TcpCoInput(config)
    if input_type in ("tls", "syslog-tls"):
        from .inputs.tls_input import TlsInput

        return TlsInput(config)
    if input_type in ("tls_co", "tlsco", "syslog-tls_co", "syslog-tlsco"):
        from .inputs.tls_input import TlsCoInput

        return TlsCoInput(config)
    if input_type == "udp":
        from .inputs.udp_input import UdpInput

        return UdpInput(config)
    if input_type == "file":
        from .inputs.file_input import FileInput

        return FileInput(config)
    raise ConfigError(f"Invalid input type: {input_type}")


def get_output(output_type: str, config: Config):
    """Output factory (mod.rs:235-243)."""
    from .outputs import DebugOutput, FileOutput, KafkaOutput, TlsOutput

    if output_type == "stdout":
        return DebugOutput(config)
    if output_type == "kafka":
        return KafkaOutput(config)
    if output_type in ("tls", "syslog-tls"):
        return TlsOutput(config)
    if output_type == "debug":
        return DebugOutput(config)
    if output_type == "file":
        return FileOutput(config)
    raise ConfigError(f"Invalid output type: {output_type}")


_TPU_FORMATS = {
    "rfc5424_tpu": "rfc5424",
    "gelf_tpu": "gelf",
    "ltsv_tpu": "ltsv",
    "rfc3164_tpu": "rfc3164",
    "jsonl_tpu": "jsonl",
    "dns_tpu": "dns",
    "auto_tpu": "auto",
}


def get_decoder(input_format: str, config: Config):
    """Decoder factory (mod.rs:413-422), extended with the *_tpu formats."""
    base = _TPU_FORMATS.get(input_format, input_format)
    if input_format == "capnp":
        return InvalidDecoder(config)
    if base == "gelf":
        return GelfDecoder(config)
    if base == "ltsv":
        return LTSVDecoder(config)
    if base == "jsonl":
        from .decoders import JSONLDecoder

        return JSONLDecoder(config)
    if base == "dns":
        from .decoders import DNSDecoder

        return DNSDecoder(config)
    if base in ("rfc5424", "auto"):
        return RFC5424Decoder(config)
    if base == "rfc3164":
        return RFC3164Decoder(config)
    raise ConfigError(f"Unknown input format: {input_format}")


def get_encoder(output_format: str, config: Config):
    """Encoder factory (mod.rs:429-437)."""
    if output_format == "capnp":
        return CapnpEncoder(config)
    if output_format in ("gelf", "json"):
        return GelfEncoder(config)
    if output_format == "ltsv":
        return LTSVEncoder(config)
    if output_format == "rfc3164":
        return RFC3164Encoder(config)
    if output_format == "rfc5424":
        return RFC5424Encoder(config)
    if output_format == "passthrough":
        return PassthroughEncoder(config)
    raise ConfigError(f"Unknown output format: {output_format}")


def get_merger(output_framing: str, config: Config):
    """Framing-name → merger (mod.rs:453-460)."""
    if output_framing in ("noop", "nop", "none", "capnp"):
        return None
    if output_framing == "line":
        return LineMerger(config)
    if output_framing == "nul":
        return NulMerger(config)
    if output_framing == "syslen":
        return SyslenMerger(config)
    raise ConfigError(f"Invalid framing type: {output_framing}")


def infer_output_framing(output_format: str, output_type: str) -> str:
    """Framing inference when output.framing is absent (mod.rs:444-452)."""
    if output_format == "capnp" or output_type == "kafka":
        return "noop"
    if output_type == "debug" or output_format == "ltsv":
        return "line"
    if output_format == "gelf":
        return "nul"
    return DEFAULT_OUTPUT_FRAMING


class Pipeline:
    """Wired-but-not-yet-running pipeline; ``run()`` blocks on the input.

    Splitting construction from running keeps the pieces testable the way
    the reference's tests poke at components with an in-memory channel
    (udp_input.rs:182-233)."""

    def __init__(self, config: Config):
        input_format = config.lookup_str(
            "input.format", "input.format must be a string", DEFAULT_INPUT_FORMAT
        )
        input_type = config.lookup_str(
            "input.type", "input.type must be a string", DEFAULT_INPUT_TYPE
        )
        self.input = get_input(input_type, config)
        self.decoder = get_decoder(input_format, config)
        output_format = config.lookup_str(
            "output.format", "output.format must be a string", DEFAULT_OUTPUT_FORMAT
        )
        self.encoder = get_encoder(output_format, config)
        output_type = config.lookup_str(
            "output.type", "output.type must be a string", DEFAULT_OUTPUT_TYPE
        )
        self.output = get_output(output_type, config)
        output_framing = config.lookup_str(
            "output.framing", "output.framing must be a string"
        )
        if output_framing is None:
            output_framing = infer_output_framing(output_format, output_type)
        self.merger = get_merger(output_framing, config)
        queue_size = config.lookup_int(
            "input.queuesize", "input.queuesize must be a size integer", DEFAULT_QUEUE_SIZE
        )
        queue_policy = config.lookup_str(
            "input.queue_policy",
            'input.queue_policy must be "block", "drop_newest" or "drop_oldest"',
            "block")
        from .utils.bounded_queue import POLICIES, PolicyQueue

        if queue_policy not in POLICIES:
            raise ConfigError(
                'input.queue_policy must be "block", "drop_newest" or '
                '"drop_oldest"')
        # multi-tenant serving: a configured [tenants] table (or a
        # tenant.default_* rate) builds the tenant registry, swaps the
        # single bounded queue for the weighted-fair multi-queue, and
        # makes handler_factory wrap every connection in token-bucket
        # admission.  Unconfigured -> None, and the pipeline builds the
        # exact pre-tenancy objects below (zero added overhead)
        from .tenancy.registry import TenantRegistry

        self.tenants = TenantRegistry.from_config(
            config, fallback_policy=queue_policy)
        if self.tenants is not None:
            from .tenancy.fairqueue import WeightedFairQueue

            self.tx: "queue.Queue[Optional[bytes]]" = WeightedFairQueue(
                maxsize=queue_size, registry=self.tenants)
        else:
            self.tx = PolicyQueue(maxsize=queue_size, policy=queue_policy)
        # zero-loss ingestion ([durability]): the WAL spill tier arms
        # only on the *_tpu formats — the spill record is the packed-
        # region shape (chunk + span vectors) only the batch handler
        # produces.  A scalar pipeline asking for it gets a warning,
        # not silent false durability.
        from .durability.manager import DurabilityManager

        self.durability = None
        if input_format in _TPU_FORMATS:
            self.durability = DurabilityManager.from_config(config)
            if self.durability is not None:
                self.durability.attach_queue(self.tx)
        else:
            _dmode = config.lookup_str(
                "durability.mode",
                'durability.mode must be "off", "spill" or "require"',
                "off")
            if _dmode != "off":
                import sys

                _dmsg = (f'durability.mode = "{_dmode}" requires a '
                         f"*_tpu input format (got '{input_format}')")
                if _dmode == "require":
                    # "require" promised no silent loss: refusing to
                    # start beats booting a lossy pipeline quietly
                    raise ConfigError(_dmsg)
                print(f"{_dmsg}; the spill tier is disabled",
                      file=sys.stderr)
        self.input_format = input_format
        self.config = config
        # template mining for scalar pipelines (the batch handler owns
        # its own miner set; building both would double-count)
        self._scalar_miners = None
        if input_format not in _TPU_FORMATS:
            from .tenancy.templates import TemplateMinerSet

            self._scalar_miners = TemplateMinerSet.from_config(config)
        self._handlers: list = []
        import threading

        self._handler_lock = threading.Lock()
        from .supervise import Supervisor
        from .utils import faultinject as _faultinject
        from .utils import metrics as _metrics_mod

        _metrics_mod.configure_from(config)
        _faultinject.configure_from(config)
        self.supervisor = Supervisor(config)
        # fleet federation (input.tpu_fleet = true): membership +
        # health export + drain-on-departure for multi-host lane
        # scale-out.  Construction is cheap and socket-free; run()
        # starts the listener/ticker.  Unconfigured -> None, zero
        # added overhead (fleet/federation.py)
        from .fleet import Fleet

        self.fleet = Fleet.from_config(
            config, supervisor=self.supervisor,
            on_drain=self._fleet_drain_signal)
        # standalone observability listener ([metrics] prom_port):
        # fleet-off deployments scrape GET /metrics (and /trace, POST
        # /profile) without joining a fleet — with fleet on, the fleet
        # health server carries the same legs and this stays None.
        # Started in run() beside the fleet agent, stopped at drain.
        self._obs_server = None
        # feedback control ([control]): burn-driven admission, share
        # feedback, autoscale signal.  Unconfigured -> None — zero
        # threads, zero hot-path cost (control/plane.py).  Started in
        # run() after the fleet (the proxy routes off the live
        # roster); stopped at drain frozen-at-last-applied.
        from .control import ControlPlane

        self.control = ControlPlane.from_config(
            config, tenants=self.tenants, fleet=self.fleet,
            tx=self.tx, durability=self.durability)
        if self.control is not None and self.fleet is not None:
            self.fleet.set_control_source(self.control.fleetz_section)
        if input_format in _TPU_FORMATS:
            # multi-host: join the JAX process group before any device
            # op so the decode mesh's dp axis can span every host's
            # chips (no-op without the tpu_coordinator keys)
            from .parallel.distributed import init_distributed

            init_distributed(config)
            # zero-JIT boot: load the AOT artifact store first
            # (input.tpu_aot_dir; no key = no-op) — when it carries a
            # warmed xla-cache and no explicit cache dir is configured,
            # it points JAX's persistent cache inside the artifact dir
            from .tpu.aot import setup_aot

            setup_aot(config)
            # persistent XLA compile cache (input.tpu_compile_cache_dir)
            # must be wired before the first kernel dispatch so every
            # compile this process pays — including the handler's
            # startup prewarm — lands in it (no key = no-op)
            from .tpu.device_common import setup_compile_cache

            setup_compile_cache(config)
            if self.fleet is not None:
                # advertised fleet capacity defaults to the resolved
                # lane count: a 4-chip host should absorb 4x a 1-chip
                # host's traffic share unless input.tpu_fleet_capacity
                # pins something else (fleet/membership.py shares())
                from .tpu.overlap import resolve_lanes

                lanes, _ = resolve_lanes(config)
                self.fleet.set_default_capacity(float(lanes))

    def handler_factory(self, peer=None):
        """Per-connection handler.  ``peer`` is the transport's source
        identity (peer IP for tcp/tls, the path for file inputs, None
        for peerless transports) — with tenancy configured it selects
        the tenant whose admission buckets the connection charges."""
        handler = self._base_handler()
        if self.tenants is not None:
            from .tenancy.admission import AdmissionHandler

            return AdmissionHandler(handler, self.tenants.resolve(peer))
        return handler

    def _base_handler(self):
        if self.input_format in _TPU_FORMATS:
            # ONE batch handler shared by every connection thread: the
            # reference's per-connection decode state is per-line and
            # stateless, but batches fragment per connection — sharing
            # aggregates all connections into full batches (the handler
            # is internally locked; message interleaving across
            # connections is unspecified in the reference too, mod.rs
            # queue semantics).  Per-connection framing attributes are
            # identical for every connection of one input by
            # construction (single input.framing config).
            with self._handler_lock:
                if self._handlers:
                    return self._handlers[0]
                from .tpu.batch import BatchHandler

                # the handler's in-flight fetcher thread spawns through
                # the supervisor: a crashed fetcher restarts (with
                # backoff + metrics) instead of wedging the window
                handler = BatchHandler(
                    self.tx, self.decoder, self.encoder, self.config,
                    fmt=_TPU_FORMATS[self.input_format], merger=self.merger,
                    supervisor=self.supervisor,
                )
                handler.durability = self.durability
                self._handlers.append(handler)
                return handler
        # ScalarHandlers are stateless (no buffered batch, flush is a
        # no-op) so they are NOT tracked for drain — tracking every
        # per-connection (and, for UDP tenancy, per-source) handler
        # would grow _handlers unboundedly in a long-lived process
        handler = ScalarHandler(self.tx, self.decoder, self.encoder)
        handler.record_hook = self._scalar_record_hook()
        return handler

    def _scalar_record_hook(self):
        """Template mining/enrichment for scalar (non-*_tpu) pipelines:
        the batch handler wires its own miners (tpu/batch.py); without
        this, ``tenant.templates = "on"`` on a scalar pipeline would
        silently mine nothing."""
        if self._scalar_miners is None:
            return None
        from .encoders import GelfEncoder
        from .tenancy.templates import make_gelf_enricher

        if self._scalar_miners.enrich and type(self.encoder) is GelfEncoder:
            return make_gelf_enricher(self._scalar_miners)
        from .tenancy import current_or_default

        miners = self._scalar_miners

        def mine(record, tenant=None):
            miners.observe_msg(tenant or current_or_default(),
                               record.msg or "")

        return mine

    def start_output(self):
        # sinks spawn their consumer threads through the supervisor so a
        # crashed worker restarts (with backoff + metrics) instead of
        # silently wedging the bounded queue
        self.output.supervisor = self.supervisor
        return self.output.start(self.tx, self.merger)

    def _drain(self, threads):
        """Flush pending batches and drain the queue through the sinks —
        the reference loses in-flight queue contents on shutdown
        (SURVEY.md §5 checkpoint/resume); we flush instead.  For batch
        handlers ``flush()`` also fences **every** dispatch lane of the
        in-flight submit/fetch executor (tpu/overlap.py LaneSet), so
        every batch any lane still holds reaches the queue — in batch
        order — before SHUTDOWN is enqueued."""
        # drain-on-departure, phase 1: stop being routable and announce
        # `draining` to fleet peers BEFORE the flush, so a load
        # balancer stops sending new traffic while in-flight batches
        # emit byte-identically through the fence-all path below
        if self.fleet is not None:
            self.fleet.enter_draining()
        # from here on, queue sheds also count queue_shed_during_drain:
        # a drain test can tell shed lines from delivered lines
        mark = getattr(self.tx, "mark_draining", None)
        if mark is not None:
            mark()
        # bounded-wait for in-flight connection handler threads (tcp/tls
        # thread-per-connection inputs) so their last lines land before
        # the flush/queue barrier below; stragglers stay daemonized and
        # are counted, same contract as the output-thread stragglers
        join_handlers = getattr(self.input, "join_handlers", None)
        if join_handlers is not None:
            still_alive = join_handlers(timeout=2.0)
            if still_alive:
                from .utils.metrics import registry as _metrics

                _metrics.inc("drain_stragglers", still_alive)
        for handler in self._handlers:
            try:
                handler.flush()
                close = getattr(handler, "close", None)
                if close is not None:
                    close()
            except Exception:  # noqa: BLE001 - best-effort during shutdown
                # the batch is lost either way, but losing it silently
                # would make a truncated output file look like an input
                # problem: say so and count it
                import sys
                import traceback

                from .utils.metrics import registry as _metrics

                _metrics.inc("drain_flush_errors")
                print("drain: final flush failed, batch lost:",
                      file=sys.stderr)
                traceback.print_exc()
        if self.durability is not None:
            # replay-on-drain: spilled batches re-enter through the
            # (already flushed and fenced) handlers so nothing rides
            # out the process on disk unnecessarily.  The replay
            # happens BEFORE the queue drain barrier below, so
            # replayed blocks and the live tail both clear the sinks
            # before any SHUTDOWN is enqueued — replay can never
            # interleave with sink teardown.
            for handler in self._handlers:
                replay = getattr(handler, "replay_spilled", None)
                if replay is None:
                    continue
                try:
                    replay()
                except Exception:  # noqa: BLE001 - best-effort during shutdown
                    import sys
                    import traceback

                    from .utils.metrics import registry as _metrics

                    _metrics.inc("drain_flush_errors")
                    print("drain: spill replay failed; the WAL keeps "
                          "the records for the next boot:",
                          file=sys.stderr)
                    traceback.print_exc()
        # drain barrier: every enqueued item must be consumed AND
        # task_done'd by a sink before SHUTDOWN goes in.  The WFQ
        # already delivers its control lane last, but the barrier makes
        # the ordering explicit for every queue type — and sink acks
        # fire before task_done, so replay cursors are settled here too
        self._await_queue_drain()
        if self.durability is not None:
            self.durability.stop()
        from .outputs import SHUTDOWN

        for _ in threads:
            self.tx.put(SHUTDOWN)
        for t in threads:
            t.join(timeout=30)
        import sys

        from .utils import metrics as _metrics_mod

        stragglers = [t for t in threads if t.is_alive()]
        if stragglers:
            # a sink that ignored SHUTDOWN for 30s is abandoned, not
            # silently forgotten: name it and count it
            _metrics_mod.registry.inc("drain_stragglers", len(stragglers))
            names = ", ".join(t.name for t in stragglers)
            print(f"drain: {len(stragglers)} output thread(s) still alive "
                  f"after 30s, abandoning: [{names}]", file=sys.stderr)
        _metrics_mod.registry.final_flush()
        _metrics_mod.stop_jax_profiler()
        # the control plane stops frozen-at-last-applied: tightened
        # tenant rates and a decayed capacity weight stay exactly
        # where the last tick put them (never reset-to-open), the
        # ticker and steering proxy just stop
        if self.control is not None:
            self.control.stop()
        # the SLO engine's evaluator (and the sentinel riding its
        # ticker) stops with the pipeline — a drained process must not
        # keep journaling slo_burn events off a frozen traffic rate
        from .obs import slo as _slo

        _slo.engine.stop()
        # drain-on-departure, phase 2: every queued batch reached the
        # sinks — announce `departed` and stop the fleet threads
        if self.fleet is not None:
            self.fleet.shutdown()
        if self._obs_server is not None:
            self._obs_server.stop()
            self._obs_server = None

    def _await_queue_drain(self, deadline_s: float = 30.0) -> None:
        """Block until the sinks have consumed and ``task_done``'d every
        enqueued item (outputs ack before task_done, so durability
        replay cursors are settled when this returns).  A sink that
        cannot drain within ``deadline_s`` is surfaced, not waited on
        forever — counted in ``drain_barrier_timeouts``."""
        import sys
        import time

        if getattr(self.tx, "unfinished_tasks", None) is None:
            return
        t0 = time.monotonic()
        while time.monotonic() - t0 < deadline_s:
            if self.tx.unfinished_tasks == 0:
                return
            time.sleep(0.01)
        from .utils.metrics import registry as _metrics

        _metrics.inc("drain_barrier_timeouts")
        print(f"drain: queue barrier timed out after {deadline_s:.0f}s "
              f"({self.tx.unfinished_tasks} item(s) still in flight)",
              file=sys.stderr)

    def _install_signal_handlers(self, threads):
        import os
        import signal
        import threading as _threading

        if _threading.current_thread() is not _threading.main_thread():
            return

        def handle(signum, frame):
            print(f"Received signal {signum}, draining and exiting",
                  file=__import__("sys").stderr)
            self._drain(threads)
            os._exit(0)

        signal.signal(signal.SIGTERM, handle)
        signal.signal(signal.SIGINT, handle)

        def profile_toggle(signum, frame):
            # on-demand xprof capture for soak runs: SIGUSR2 starts a
            # trace into metrics.jax_profile_dir (or a per-pid default)
            # and a second SIGUSR2 stops it — no restart, no config
            # edit (the health server's POST /profile is the same flip)
            from .utils import metrics as _m

            _m.toggle_jax_profiler()

        if hasattr(signal, "SIGUSR2"):
            signal.signal(signal.SIGUSR2, profile_toggle)

    def _fleet_drain_signal(self):
        """`POST /drain` on the health endpoint (fleetctl drain): route
        through the SIGTERM path so a remote drain and a local one are
        the same code — fence lanes, flush, drain the queue, exit."""
        import os
        import signal

        os.kill(os.getpid(), signal.SIGTERM)

    def run(self):
        threads = self.start_output()
        if not isinstance(threads, list):
            threads = [threads]
        self._install_signal_handlers(threads)
        # fleet membership goes live only once the pipeline can serve:
        # sinks are up, signal handlers (the drain path peers rely on)
        # are installed
        if self.fleet is not None:
            self.fleet.start()
        else:
            from .obs import prom as _prom

            self._obs_server = _prom.maybe_start_from(
                self.config, supervisor=self.supervisor)
        if self.control is not None:
            # after fleet.start(): the controller's steering proxy and
            # share loop read the live membership roster
            self.control.start()
        if self.durability is not None and self.durability.backlog():
            # crash recovery: a previous life left unacked records in
            # the WAL — replay them through the sinks BEFORE fresh
            # ingest is admitted, so restart ordering is replay-then-
            # live and the at-least-once window closes at boot
            import sys

            handler = self._base_handler()
            replayed = handler.replay_spilled()
            if replayed:
                print(f"durability: replayed {replayed} spilled line(s) "
                      f"from {self.durability.dir}", file=sys.stderr)
        # the accept loop runs supervised: a crash in the transport
        # restarts it (bounded by [supervisor] config) instead of
        # killing the daemon while consumers still hold the queue
        self.supervisor.run(self.input.accept, "input-accept",
                            (self.handler_factory,))
        # Input ended (EOF on stdin, etc.): drain before exiting rather
        # than killing the daemon consumers mid-write.
        self._drain(threads)


def start(config_file: str):
    """Library entry point (lib.rs:18-20, mod.rs:395-472): blocks forever."""
    try:
        config = Config.from_path(config_file)
    except OSError as e:
        raise ConfigError(f"Unable to read the config file [{config_file}]: {e}")
    Pipeline(config).run()
