"""Config lint (``--check``): validate a TOML config against the known
key namespace and report likely typos.

The reference silently ignores unknown keys (config.rs lookup simply
returns None), and this pipeline matches that at runtime — the lint
flag is the cheap insurance layer on top: it walks every leaf key in
the file, flags anything outside the known namespace, and suggests the
nearest known key.  Table-valued free-form namespaces
(ltsv_schema/ltsv_suffixes/*_extra) accept arbitrary sub-keys.

The namespace is **derived from the code**, not hand-maintained: the
``analysis.configkeys`` AST pass collects every literal
``config.lookup*`` path in the package (plus forwarder expansions like
the ``*_retry_*`` families), so a key is "known" exactly when some
code path reads it.  The previous hand-written set had drifted four
keys deep — ``metrics.jsonl`` (a config *value* mistaken for a key),
``input.tls_threads``, and the output-side TLS
``compatibility_level``/``compression`` pair, none of which any code
read — and flowcheck FC05 now fails CI if the derivation ever stops
covering a read or a ``DECLARED_ONLY`` entry goes stale.
"""

from __future__ import annotations

import difflib
from typing import List

from .analysis.configkeys import derived_namespace
from .config import Config

# Keys that are legitimately configurable but read through paths the
# AST derivation cannot see.  Empty by design — add a key here ONLY if
# a new dynamic lookup pattern cannot be expressed as a
# configkeys.FORWARDERS entry, and leave a comment saying where it is
# read.  flowcheck FC05 flags entries that are in fact derivable.
DECLARED_ONLY = frozenset()

_NAMESPACE = derived_namespace()

KNOWN_KEYS = frozenset(_NAMESPACE.keys) | DECLARED_ONLY

# tables whose sub-keys are user-defined (every lookup_table site:
# ltsv_schema/ltsv_suffixes, the *_extra tables, and [faults])
FREE_TABLES = frozenset(_NAMESPACE.free_tables)


def _walk(table, prefix: str, out: List[str]):
    for key, value in table.items():
        path = f"{prefix}.{key}" if prefix else key
        if path in FREE_TABLES:
            continue
        if isinstance(value, dict):
            _walk(value, path, out)
        else:
            out.append(path)


def lint_config(config: Config) -> List[str]:
    """Warnings for unknown keys, with nearest-known suggestions."""
    leaves: List[str] = []
    _walk(config._table, "", leaves)
    warnings = []
    for path in leaves:
        if path in KNOWN_KEYS:
            continue
        near = difflib.get_close_matches(path, KNOWN_KEYS, n=1, cutoff=0.6)
        hint = f" (did you mean {near[0]!r}?)" if near else ""
        warnings.append(f"unknown config key {path!r}{hint}")
    return warnings


def check_file(config_file: str) -> int:
    """CLI ``--check`` entry: parse + lint.

    Exit-code contract (tested by tests/test_lint.py): 0 = clean,
    1 = unknown keys, 2 = the file is unreadable or not valid TOML —
    scripts gating a deploy on ``--check`` can tell "typo in a key"
    from "config missing entirely".
    """
    import sys

    from .config import ConfigError

    try:
        config = Config.from_path(config_file)
    except (OSError, ConfigError) as e:
        print(f"error: {config_file}: {e}", file=sys.stderr)
        return 2
    warnings = lint_config(config)
    for w in warnings:
        print(f"warning: {w}")
    if warnings:
        print(f"{config_file}: {len(warnings)} warning(s)")
        return 1
    print(f"{config_file}: OK")
    return 0
