"""Config lint (``--check``): validate a TOML config against the known
key namespace and report likely typos.

The reference silently ignores unknown keys (config.rs lookup simply
returns None), and this pipeline matches that at runtime — the lint
flag is the cheap insurance layer on top: it walks every leaf key in
the file, flags anything outside the known namespace, and suggests the
nearest known key.  Table-valued free-form namespaces
(ltsv_schema/ltsv_suffixes/*_extra) accept arbitrary sub-keys.
"""

from __future__ import annotations

import difflib
from typing import List

from .config import Config

KNOWN_KEYS = {
    # [input] — mod.rs:101-109 + per-input config_parse sites
    "input.type", "input.format", "input.framing", "input.framed",
    "input.listen", "input.timeout", "input.queuesize", "input.src",
    "input.tcp_threads", "input.tls_threads",
    "input.tls_cert", "input.tls_key", "input.tls_ciphers",
    "input.tls_compatibility_level", "input.tls_compression",
    "input.tls_verify_peer", "input.tls_ca_file",
    "input.redis_connect", "input.redis_queue_key", "input.redis_threads",
    # TPU extensions
    "input.tpu_batch_size", "input.tpu_flush_ms", "input.tpu_max_line_len",
    "input.tpu_coordinator", "input.tpu_num_processes",
    "input.tpu_process_id", "input.tpu_mesh", "input.tpu_sp",
    # robustness layer
    "input.queue_policy",
    "input.tpu_breaker", "input.tpu_breaker_failures",
    "input.tpu_breaker_cooldown_ms", "input.tpu_breaker_window",
    "input.tpu_breaker_fallback_ratio",
    "input.redis_retry_init", "input.redis_retry_max",
    "input.redis_retry_attempts",
    # [output] — per-output config sites
    "output.type", "output.format", "output.framing", "output.connect",
    "output.timeout", "output.file_path", "output.file_buffer_size",
    "output.file_rotation_size", "output.file_rotation_time",
    "output.file_rotation_maxfiles", "output.file_rotation_timeformat",
    "output.kafka_brokers", "output.kafka_topic", "output.kafka_acks",
    "output.kafka_timeout", "output.kafka_threads", "output.kafka_coalesce",
    "output.kafka_compression",
    "output.tls_cert", "output.tls_key", "output.tls_ciphers",
    "output.tls_compatibility_level", "output.tls_compression",
    "output.tls_verify_peer", "output.tls_ca_file", "output.tls_threads",
    "output.tls_async", "output.tls_recovery_delay_init",
    "output.tls_recovery_delay_max", "output.tls_recovery_probe_time",
    "output.syslog_prepend_timestamp",
    "output.kafka_retry_init", "output.kafka_retry_max",
    "output.kafka_retry_attempts",
    # [metrics] — observability extension
    "metrics.interval", "metrics.path", "metrics.jsonl",
    "metrics.jax_profile_dir",
    # [supervisor] — thread crash/restart policy
    "supervisor.max_restarts", "supervisor.backoff_init",
    "supervisor.backoff_max",
}

# tables whose sub-keys are user-defined
FREE_TABLES = {
    "input.ltsv_schema", "input.ltsv_suffixes",
    "output.gelf_extra", "output.ltsv_extra", "output.capnp_extra",
    # fault-injection sites (validated by utils.faultinject at boot)
    "faults",
}


def _walk(table, prefix: str, out: List[str]):
    for key, value in table.items():
        path = f"{prefix}.{key}" if prefix else key
        if path in FREE_TABLES:
            continue
        if isinstance(value, dict):
            _walk(value, path, out)
        else:
            out.append(path)


def lint_config(config: Config) -> List[str]:
    """Warnings for unknown keys, with nearest-known suggestions."""
    leaves: List[str] = []
    _walk(config._table, "", leaves)
    warnings = []
    for path in leaves:
        if path in KNOWN_KEYS:
            continue
        near = difflib.get_close_matches(path, KNOWN_KEYS, n=1, cutoff=0.6)
        hint = f" (did you mean {near[0]!r}?)" if near else ""
        warnings.append(f"unknown config key {path!r}{hint}")
    return warnings


def check_file(config_file: str) -> int:
    """CLI ``--check`` entry: parse + lint; returns the exit code."""
    config = Config.from_path(config_file)
    warnings = lint_config(config)
    for w in warnings:
        print(f"warning: {w}")
    if warnings:
        print(f"{config_file}: {len(warnings)} warning(s)")
        return 1
    print(f"{config_file}: OK")
    return 0
