"""Mergers: output framing applied by the sink consumer.

Parity model: /root/reference/src/flowgger/merger/ — trait
``Merger { frame(&self, bytes: &mut Vec<u8>) }`` (merger/mod.rs:30-32).
Python bytes are immutable so ``frame`` returns the framed value; the
reference's in-place unsafe shift (syslen_merger.rs:20-28) is just a
concatenation here.
"""

from __future__ import annotations


class Merger:
    def frame(self, data: bytes) -> bytes:
        raise NotImplementedError


class LineMerger(Merger):
    """Append ``\\n`` (line_merger.rs:13-17)."""

    def __init__(self, config=None):
        pass

    def frame(self, data: bytes) -> bytes:
        return data + b"\n"


class NulMerger(Merger):
    """Append ``\\0`` (nul_merger.rs:13-17)."""

    def __init__(self, config=None):
        pass

    def frame(self, data: bytes) -> bytes:
        return data + b"\0"


class SyslenMerger(Merger):
    """Prepend ``"{len} "`` and append ``\\n``; the length counts the
    payload plus the trailing newline (syslen_merger.rs:14-31)."""

    def __init__(self, config=None):
        pass

    def frame(self, data: bytes) -> bytes:
        return f"{len(data) + 1} ".encode("ascii") + data + b"\n"
