"""Deterministic fault injection for the robustness test family.

Faults are declared per *site* — a named choke point the pipeline checks
as it runs — with a deterministic trigger spec, so a test (or a chaos
run) can make the Nth device decode explode, reset an input socket
mid-stream, or fail a sink write, and assert the degradation path
recovers without losing the stream.

Configuration, either source merges into one plan (env wins):

    [faults]                       # TOML table, values are spec strings
    device_decode = "every:3"      # fire on every 3rd check
    input_socket = "once:5"        # fire on the 5th check only
    sink_write = "first:2"         # fire on checks 1..2
    queue_pressure = "after:10"    # fire on every check past the 10th

    FLOWGGER_FAULTS="device_decode=every:3,sink_write=once:2"

Sites wired in (each names the exception type it surfaces):

- ``device_decode``  — raised inside BatchHandler's device dispatch/fetch
  (``InjectedFault``), exercising the decode circuit breaker;
- ``input_socket``   — ``ConnectionResetError`` from input socket reads;
- ``sink_write``     — ``OSError`` from sink write paths (tls/file);
- ``queue_pressure`` — makes the bounded queue report Full to producers;
- ``tenant_flood``   — makes admission checks of *rate-limited* tenants
  deny as if their token bucket were empty (unlimited tenants never
  check the site, so a plan targets exactly the tenants a test marks
  with a finite rate — see tenancy/admission.py);
- ``peer_partition`` — the fleet heartbeat layer drops exchanges in
  BOTH directions at the armed host: outbound sends are suppressed,
  inbound exchanges are refused as if the network ate them (the
  sender sees a failed delivery), and stray replies are discarded.
  Checked per send target and per inbound heartbeat; set
  ``FLOWGGER_PARTITION_PEER=<rank>`` to partition only the named peer
  (absent = every peer) — see fleet/federation.py;
- ``host_kill``      — the fleet ticker SIGKILLs its own process on the
  firing tick: a deterministic hard host loss (no drain, no goodbye)
  for the multi-process acceptance tests.  ``once:N`` kills on the Nth
  tick, i.e. ~N x tpu_fleet_heartbeat_ms after fleet start;
- ``coordinator_kill`` — like ``host_kill`` but self-selecting: only
  checked while this host *is* the fleet's agreed rendezvous (lowest
  active rank), so arming it fleet-wide kills exactly the coordinator —
  the rendezvous-failover drill (see fleet/federation.py);
- ``roster_corrupt`` — the next durable-roster journal write
  (fleet/roster.py) writes a deliberately truncated file instead: the
  corrupt-journal → clean-re-rendezvous path, end to end.
- ``route_throttle`` — injects a 50 ms delay into each firing batch's
  finish path (tpu/batch.py ``_finish_batch``): an artificial
  route-throughput collapse with no byte-level change, the drill the
  regression sentinel (obs/sentinel.py) must flag as
  ``perf_regression`` within its window.
- ``spill_io``       — the durability tier's segment append
  (durability/segments.py) writes a deliberately TORN record fragment
  and then raises ``OSError``: with ``durability.mode = "spill"`` the
  batch declines to shed (it continues down the normal lossy dispatch
  path), with ``mode = "require"`` the append failure is a hard
  ``DurabilityError`` — and the next boot's segment scan must recover
  the valid prefix ahead of the torn tail;
- ``sink_ack_loss``  — a sink's durability acknowledgment never
  arrives (``outputs.ack_item`` suppresses the callback): the WAL
  replay cursor pins, ``replay_cursor_lag`` stays nonzero, and the
  stall watchdog journals ``replay_stall`` — the stuck-replay drill.
- ``control_freeze`` — the control plane's ticker (control/plane.py)
  skips the firing tick entirely: the controller-death drill.  The
  failure philosophy is frozen-at-last-applied — tightened tenant
  rates and a decayed capacity weight stay exactly where the last
  live tick left them, never reset to open — and this site proves it
  deterministically.

Runtime arming: beyond the boot-time plan below, ``set_site`` merges
one site into the active plan while the process runs — the fleet
health endpoint's ``POST /fault`` leg (``input.tpu_fleet_chaos = true``
only) exposes it so ``tools/chaos.py`` can drive fault drills against
long-running hosts.

Counters are per-site, process-wide, and thread-safe; numbering is
1-based (``once:1`` fires on the first check).  The module is inert —
one dict lookup per check — unless a plan is configured.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, Optional, Tuple

ENV_VAR = "FLOWGGER_FAULTS"

KNOWN_SITES = ("device_decode", "input_socket", "sink_write",
               "queue_pressure", "tenant_flood", "peer_partition",
               "host_kill", "coordinator_kill", "roster_corrupt",
               "route_throttle", "spill_io", "sink_ack_loss",
               "control_freeze")


class InjectedFault(Exception):
    """The device_decode site's synthetic failure."""


class FaultInjectError(Exception):
    """Bad fault spec at configure time."""


def _parse_spec(site: str, spec: str) -> Optional[Tuple[str, int]]:
    spec = spec.strip().lower()
    if spec in ("off", "none", ""):
        return None
    kind, _, arg = spec.partition(":")
    if kind not in ("every", "once", "after", "first") or not arg.isdigit():
        raise FaultInjectError(
            f"fault spec for [{site}] must be off|every:N|once:N|after:N|"
            f"first:N, got [{spec}]")
    n = int(arg)
    if n < 1:
        raise FaultInjectError(f"fault spec for [{site}]: N must be >= 1")
    return kind, n


class FaultPlan:
    def __init__(self, specs: Dict[str, str]):
        self._rules: Dict[str, Tuple[str, int]] = {}
        self._counts: Dict[str, int] = {}
        self._specs = dict(specs)  # raw specs, so set_site can merge
        self._lock = threading.Lock()
        for site, spec in specs.items():
            parsed = _parse_spec(site, spec)
            if parsed is not None:
                self._rules[site] = parsed
                self._counts[site] = 0

    def fire(self, site: str) -> bool:
        """Count one check of ``site``; True when the fault triggers."""
        rule = self._rules.get(site)
        if rule is None:
            return False
        with self._lock:
            self._counts[site] += 1
            n = self._counts[site]
        kind, arg = rule
        if kind == "every":
            return n % arg == 0
        if kind == "once":
            return n == arg
        if kind == "after":
            return n > arg
        return n <= arg  # first:N

    def count(self, site: str) -> int:
        with self._lock:
            return self._counts.get(site, 0)


_plan: Optional[FaultPlan] = None


def enabled() -> bool:
    return _plan is not None


def fire(site: str) -> bool:
    """One deterministic check of a fault site (1-based numbering)."""
    return _plan is not None and _plan.fire(site)


def maybe_raise(site: str, exc_type: type = InjectedFault) -> None:
    if _plan is not None and _plan.fire(site):
        raise exc_type(f"injected fault at site [{site}]")


def reset() -> None:
    """Drop the active plan (tests)."""
    global _plan
    _plan = None


def configure(specs: Dict[str, str]) -> None:
    """Install a plan directly (tests / programmatic chaos runs)."""
    global _plan
    _plan = FaultPlan(specs) if specs else None


def set_site(site: str, spec: str) -> None:
    """Runtime (chaos) arming: merge ONE site into the active plan —
    other sites keep their specs but every counter restarts, so each
    arm is a fresh deterministic drill (``once:1`` = the next check).
    ``spec = "off"`` disarms the site.  Raises ``FaultInjectError`` on
    an unknown site or malformed spec, exactly like configure_from."""
    if site not in KNOWN_SITES:
        raise FaultInjectError(
            f"unknown fault site [{site}] (known: "
            f"{', '.join(KNOWN_SITES)})")
    _parse_spec(site, spec)  # validate before touching the plan
    specs = dict(_plan._specs) if _plan is not None else {}
    if spec.strip().lower() in ("off", "none", ""):
        specs.pop(site, None)
    else:
        specs[site] = spec
    configure(specs)


def configure_from(config) -> None:
    """Pipeline boot: merge the ``[faults]`` config table with the
    ``FLOWGGER_FAULTS`` env (env wins per site).  No sources → inert."""
    specs: Dict[str, str] = {}
    table = config.lookup_table("faults", "[faults] must be a table")
    if table:
        for site, spec in table.items():
            if not isinstance(spec, str):
                raise FaultInjectError(
                    f"[faults] {site} must be a spec string")
            specs[site] = spec
    env = os.environ.get(ENV_VAR, "")
    for part in filter(None, (p.strip() for p in env.split(","))):
        site, eq, spec = part.partition("=")
        if not eq:
            raise FaultInjectError(
                f"{ENV_VAR} entries must look like site=spec, got [{part}]")
        specs[site.strip()] = spec
    for site in specs:
        if site not in KNOWN_SITES:
            # hard error: a typo'd site would silently inject nothing
            # and let a fault-free run pass as a robustness validation
            raise FaultInjectError(
                f"unknown fault site [{site}] (known: "
                f"{', '.join(KNOWN_SITES)})")
    configure(specs)
    if specs:
        print(f"faultinject: active plan {specs}", file=sys.stderr)
