"""Minimal inotify binding via ctypes (no external deps).

The reference's file input reacts to filesystem events through the
notify crate (input/file/discovery.rs:44-87, worker.rs:37-78); this is
the equivalent capability on raw libc: ``inotify_init1`` /
``inotify_add_watch`` plus ``os.read`` of the event stream, with
``select`` supplying bounded waits so callers stay responsive to stop
flags.  ``available()`` is False off Linux (or in sandboxes rejecting
the syscalls) and callers fall back to polling.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import os
import select
import struct
import sys
import threading
from typing import List, Optional, Tuple

IN_ACCESS = 0x001
IN_MODIFY = 0x002
IN_ATTRIB = 0x004
IN_CLOSE_WRITE = 0x008
IN_MOVED_FROM = 0x040
IN_MOVED_TO = 0x080
IN_CREATE = 0x100
IN_DELETE = 0x200
IN_DELETE_SELF = 0x400
IN_MOVE_SELF = 0x800
IN_IGNORED = 0x8000
IN_ISDIR = 0x40000000

_EVENT_HEAD = struct.Struct("iIII")

_libc = None
_libc_lock = threading.Lock()


def _get_libc():
    global _libc
    with _libc_lock:
        if _libc is None:
            try:
                _libc = ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6",
                                    use_errno=True)
                _libc.inotify_init1
                _libc.inotify_add_watch
            except (OSError, AttributeError):  # flowcheck: disable=FC04 -- availability probe; caller falls back to polling
                _libc = False
        return _libc


def available() -> bool:
    if not sys.platform.startswith("linux"):
        return False
    libc = _get_libc()
    if not libc:
        return False
    # some sandboxes stub the symbol but fail the syscall: probe once
    fd = libc.inotify_init1(os.O_CLOEXEC)
    if fd < 0:
        return False
    os.close(fd)
    return True


class Inotify:
    """One inotify instance; thread-safe adds, single reader."""

    def __init__(self):
        libc = _get_libc()
        if not libc:
            raise OSError("inotify unavailable")
        self._libc = libc
        self.fd = libc.inotify_init1(os.O_CLOEXEC)
        if self.fd < 0:
            raise OSError(ctypes.get_errno(), "inotify_init1 failed")
        self._closed = False

    def add_watch(self, path: str, mask: int) -> int:
        wd = self._libc.inotify_add_watch(
            self.fd, os.fsencode(path), ctypes.c_uint32(mask))
        if wd < 0:
            raise OSError(ctypes.get_errno(),
                          f"inotify_add_watch failed for {path}")
        return wd

    def read(self, timeout_s: Optional[float] = None
             ) -> List[Tuple[int, int, int, str]]:
        """Blocking (bounded by ``timeout_s``) read of pending events:
        [(wd, mask, cookie, name)], empty list on timeout/close."""
        if self._closed:
            return []
        try:
            r, _, _ = select.select([self.fd], [], [], timeout_s)
        except (OSError, ValueError):  # flowcheck: disable=FC04 -- fd closed mid-select; caller treats [] as quiet
            return []
        if not r:
            return []
        try:
            buf = os.read(self.fd, 65536)
        except OSError:  # flowcheck: disable=FC04 -- watch fd gone; caller treats [] as quiet
            return []
        events = []
        pos = 0
        while pos + _EVENT_HEAD.size <= len(buf):
            wd, mask, cookie, nlen = _EVENT_HEAD.unpack_from(buf, pos)
            pos += _EVENT_HEAD.size
            name = buf[pos:pos + nlen].split(b"\0", 1)[0].decode(
                "utf-8", "surrogateescape")
            pos += nlen
            events.append((wd, mask, cookie, name))
        return events

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                os.close(self.fd)
            except OSError:  # flowcheck: disable=FC04 -- fd already dead; close is best-effort
                pass
