"""Per-stage counters and latency histograms.

The reference has no observability at all — diagnostics are bare stderr
writes and its declared ``log`` dependency is never used (SURVEY.md §5).
This registry gives every pipeline stage cheap thread-safe counters and
the batched decode path latency histograms, reported as one JSON line
on a configurable interval:

    [metrics]
    interval = 10            # seconds; 0/absent = disabled
    path = "metrics.jsonl"   # default: stderr

Counter names: input_lines, decoded_records, decode_errors,
encode_errors, invalid_utf8, enqueued, output_written, output_errors,
batches, batch_lines, fallback_rows.  ``batch_seconds`` is a histogram
(count/sum/min/max/p50/p99 over a sliding window); the named histogram
family (``observe(name, value)``) adds ``queue_wait_seconds`` (sampled
sojourn time of queued items, bounded_queue/fairqueue) and
``e2e_batch_seconds`` (flush→emit wall per dispatched batch,
tpu/batch.py) so latency, not just throughput, is measurable.

Overlap executor stages report as cumulative seconds
(``dispatch_seconds`` submit-side pack+dispatch, ``fetch_seconds``
fetch-behind wall, ``overlap_stall_seconds`` window backpressure) plus
the ``inflight_depth`` gauge — see tpu/overlap.py.

Lane dispatch / compile stability (tpu/overlap.py LaneSet,
tpu/device_common.py cache+prewarm, tpu/pack.py bucketing):
``lane_depth`` (deepest lane) and per-lane ``lane{i}_depth`` gauges,
``lane{i}_rows`` counters, per-lane ``lane{i}_route_{path}_spr``
EWMA gauges, ``distinct_compiled_shapes`` gauge (every (rows, max_len)
shape packed so far), and the ``compile_cache_hits`` /
``compile_cache_misses`` / ``prewarmed_shapes`` counters — a second
cold process of an identical config with ``input.tpu_compile_cache_dir``
set should report zero misses.

Fused decode→encode routes (tpu/fused_routes.py): ``fused_rows`` (rows
emitted through a fused single-program route, plus the per-route
``fused_rows_{route}`` family), ``fused_fallbacks`` (batches that
declined from the fused tier to the split path, plus
``fused_fallbacks_{route}``), and the per-route
``fetch_bytes_per_row_{route}`` / ``emit_bytes_per_row_{route}`` gauges
— the fused acceptance is fetch under emit on every route.  Fused
compile-watchdog declines fold into the shared
``device_encode_compile_declines`` counter; per-lane fused-vs-split
economics export as ``lane{i}_route_fused_spr`` alongside the
device/host gauges.

Multi-tenant serving (tenancy/): per-tenant ``tenant_{name}_lines`` /
``tenant_{name}_bytes`` (admitted), ``tenant_{name}_drops`` (admission
denials), ``tenant_{name}_shed`` (queue-pressure sheds) counters and
the ``tenant_{name}_state`` gauge (0 admitting / 1 throttled /
2 shed), plus the aggregate ``tenant_lines/bytes/drops/shed``.  Queue
sheds carry per-cause labels: ``queue_dropped_{policy}`` alongside the
aggregate ``queue_dropped``, and ``queue_shed_during_drain`` after the
pipeline enters its drain phase.  Template mining reports
``template_hits``, the ``tenant_templates_distinct`` gauge (and its
per-tenant ``tenant_{name}_templates_distinct`` form), and the
per-template ``tenant_{name}_template_{id}`` counter family (capped;
overflow ids fold into ``tenant_{name}_template_overflow``).

Fleet federation (fleet/): ``fleet_hosts_{state}`` gauges (the local
host counts toward its own state), per-peer ``fleet_peer{rank}_state``
(0..4 in ladder order), ``fleet_peer{rank}_hb_age_ms`` and
``fleet_peer{rank}_share`` (capacity-weighted traffic share) gauges,
the ``fleet_rendezvous_rank`` gauge (the elected rendezvous; -1 while
none), plus the ``fleet_evictions`` / ``fleet_rejoins`` /
``fleet_hb_send_errors`` / ``fleet_hb_retries`` /
``fleet_roster_saves`` / ``fleet_roster_load_errors`` counters.  The whole ``snapshot()`` is what each host's HTTP health
endpoint serves under ``metrics`` (fleet/health.py) — it is JSON-safe
by construction (counters and gauges are numbers, histograms flat
dicts), so the health document needs no second serialization layer.

Observability layer (obs/): degradation rungs journal through
``obs.events`` and mirror here as the ``degradation_events`` aggregate
plus the per-reason ``events_{reason}`` counter family; the whole
registry renders in the Prometheus text exposition format via
``obs.prom.render`` (``GET /metrics``).

SLO plane (obs/slo.py + obs/sentinel.py): per-batch emits land the
``route_rows_{route}`` counter family and the per-route
``e2e_batch_seconds_{route}`` histogram family (tpu/batch.py
``_finish_batch``); the weighted-fair queue lands per-tenant sojourn
samples as ``queue_wait_seconds_{tenant}``.  The SLO engine exports
``slo_{name}_burn_rate`` / ``slo_{name}_budget_remaining`` gauges per
configured objective, and the regression sentinel exports
``sentinel_{route}_ratio`` / ``sentinel_{route}_baseline`` gauges.
Histograms additionally support *observe taps*
(:meth:`Registry.add_observe_tap`) — the SLO engine's per-sample
threshold accounting rides the existing ``observe()`` call with one
dict lookup when no tap is registered.

The declaration tuples below
(``_COUNTERS``/``_SECONDS_NAMES``/``_GAUGE_NAMES``/
``_HISTOGRAM_NAMES``/``_FAMILY_PATTERNS``) are the metric-name
namespace flowcheck rule FC06 resolves every literal call-site name
against — a typo'd counter is a CI finding, not a silent new series.
"""

from __future__ import annotations

import itertools
import json
import sys
import threading
import time
from typing import Dict, Optional, Tuple

_COUNTERS = (
    "input_lines", "decoded_records", "decode_errors", "encode_errors",
    "invalid_utf8", "enqueued", "output_written", "output_errors",
    "batches", "batch_lines", "fallback_rows",
    # robustness / supervision layer
    "queue_dropped", "drain_stragglers", "drain_flush_errors",
    "sink_reconnects", "sink_failovers",
    "thread_crashes", "thread_restarts", "input_reconnects",
    "device_decode_errors", "breaker_trips", "breaker_recoveries",
    # overlap executor (tpu/overlap.py): D2H bytes the compaction +
    # constant-elision path avoided, and encode-route economics picks
    "fetch_bytes_saved", "encode_route_device", "encode_route_host",
    "encode_route_fused",
    # compile stability (tpu/device_common.py): persistent-cache
    # traffic, startup kernel prewarm progress, and the compile
    # watchdog's decline/health accounting
    "compile_cache_hits", "compile_cache_misses", "prewarmed_shapes",
    "prewarm_aot_skips", "device_encode_compile_declines",
    # device-encode tier accounting (tpu/device_common.py driver)
    "device_encode_declined", "device_encode_rows",
    "device_encode_scalar_rows", "device_encode_fetch_bytes",
    "device_encode_out_bytes", "device_encode_wide_batches",
    # multi-chip mesh + fused routes + device framing
    "sharded_kernels", "fused_rows", "fused_fallbacks",
    "framing_rows", "framing_declines", "framing_span_fetch_bytes",
    # Pallas structural-pass tier (tpu/pallas_kernels.py): rows that
    # went through a Pallas kernel, and declines back to the jnp tier
    "pallas_rows", "pallas_declines",
    # zero-JIT boot (tpu/aot.py): artifact-store traffic; per-reason
    # rejects ride the aot_rejects_{reason} family
    "aot_hits", "aot_misses", "aot_rejects",
    # multi-tenant serving (tenancy/): aggregate admission and shed
    # counters — the per-tenant family (tenant_{name}_lines/_bytes/
    # _drops/_shed, tenant_{name}_state gauge) materializes on first
    # use, keyed by tenant name
    "tenant_lines", "tenant_bytes", "tenant_drops", "tenant_shed",
    # queue sheds that happened after the pipeline entered its drain
    # phase (bounded_queue.mark_draining): lets a SIGTERM test tell
    # shed lines from delivered lines
    "queue_shed_during_drain",
    # online template mining (tenancy/templates.py): rows mined; the
    # per-template family is tenant_{name}_template_{id} (+ _overflow)
    "template_hits", "template_tap_errors",
    # fleet federation (fleet/): peers evicted by the missed-heartbeat
    # ladder, local rejoins after a discovered self-eviction, and
    # heartbeat deliveries that failed in transit (partition/churn —
    # normal life at fleet scale, counted not logged).  The state
    # gauges (fleet_hosts_{state}, fleet_peer{rank}_state,
    # fleet_peer{rank}_hb_age_ms) materialize when membership starts
    "fleet_evictions", "fleet_rejoins", "fleet_hb_send_errors",
    # self-healing fleet (PR 14): heartbeat-POST retries before a send
    # is declared failed (utils/retry.py full jitter), durable-roster
    # journal writes, and corrupt/unreadable journal loads (each load
    # error is a clean re-rendezvous, not a crash — fleet/roster.py)
    "fleet_hb_retries", "fleet_roster_saves", "fleet_roster_load_errors",
    # degradation journal (obs/events.py): aggregate event count; the
    # per-reason family is events_{reason}
    "degradation_events",
    # zero-loss ingestion (durability/): WAL spill/replay traffic,
    # unreadable segment/cursor loads (each one degrades — recovered
    # prefix, widened at-least-once window — never a crash), failed
    # fsynced appends, sink acks fired/contained, and output drain
    # barriers that expired before the queue fully drained
    "spill_records", "replayed_lines", "spill_load_errors",
    "spill_io_errors", "sink_acks", "sink_ack_errors",
    "drain_barrier_timeouts",
    # control plane (control/plane.py + fleet/proxy.py): controller
    # ticks that applied a change, ticks skipped by the control_freeze
    # drill site, steering-proxy connections routed / bytes pumped /
    # routing failures (no routable host, dial error)
    "control_applies", "control_freezes", "control_ticks",
    "proxy_connections", "proxy_bytes", "proxy_route_errors",
)

# cumulative per-stage wall-clock accumulators (add_seconds)
_SECONDS_NAMES = (
    "dispatch_seconds", "fetch_seconds", "overlap_stall_seconds",
    "device_fetch_seconds", "encode_seconds",
    "device_encode_declined_seconds",
    "pack_stage_seconds", "pack_slice_seconds", "pack_copy_seconds",
)

# point-in-time gauges with literal names (set_gauge/init_gauge)
_GAUGE_NAMES = (
    "device_breaker_state", "inflight_depth", "lane_depth",
    "distinct_compiled_shapes", "framing_carry_bytes",
    "tenant_templates_distinct", "fleet_rendezvous_rank",
    # durability tier backlog (durability/manager.py): on-disk WAL
    # bytes/segments and the spilled-but-unacked record count the
    # replay-stall watchdog and fleetctl's spill line key on
    "spill_bytes", "spill_segments", "replay_cursor_lag",
    # control plane (control/plane.py): the autoscale signal (desired
    # routable host count) and this host's applied capacity factor
    # (1.0 = configured weight, < 1.0 = share-feedback decay)
    "fleet_desired_hosts", "control_capacity_factor",
)

# sliding-window histogram family (observe)
_HISTOGRAM_NAMES = (
    "batch_seconds", "queue_wait_seconds", "e2e_batch_seconds",
)

# dynamic name families: ``{placeholder}`` stands for one
# ``[A-Za-z0-9_]+`` segment.  FC06 resolves literal call-site names
# against these too (e.g. the literal "aot_rejects_missing_route"
# resolves via "aot_rejects_{reason}"); f-string call sites are by
# construction members of exactly one family here
_FAMILY_PATTERNS = (
    "lane{i}_depth", "lane{i}_rows", "lane{i}_route_{path}_spr",
    "queue_dropped_{policy}",
    "tenant_{name}_lines", "tenant_{name}_bytes", "tenant_{name}_drops",
    "tenant_{name}_shed", "tenant_{name}_state",
    "tenant_{name}_rate_factor",
    "tenant_{name}_templates_distinct",
    "tenant_{name}_template_{id}", "tenant_{name}_template_overflow",
    "fleet_hosts_{state}", "fleet_peer{rank}_state",
    "fleet_peer{rank}_hb_age_ms", "fleet_peer{rank}_share",
    "aot_rejects_{reason}",
    "fused_rows_{route}", "fused_fallbacks_{route}",
    "fetch_bytes_per_row_{route}", "emit_bytes_per_row_{route}",
    "framing_{path}_spr",
    "events_{reason}",
    # SLO / observability plane (obs/slo.py, obs/sentinel.py,
    # tpu/batch.py _finish_batch, tenancy/fairqueue.py)
    "route_rows_{route}",
    "e2e_batch_seconds_{route}", "queue_wait_seconds_{tenant}",
    "slo_{name}_burn_rate", "slo_{name}_budget_remaining",
    "sentinel_{route}_ratio", "sentinel_{route}_baseline",
)


# kind of each dynamic family in _FAMILY_PATTERNS — the fleet-level
# merge (fleet/federation.merge_metric_snapshots) must sum counters
# and pool histograms while leaving point-in-time gauges per-host, and
# a flat snapshot alone cannot tell them apart
_FAMILY_KINDS = (
    ("lane{i}_depth", "gauge"),
    ("lane{i}_rows", "counter"),
    ("lane{i}_route_{path}_spr", "gauge"),
    ("queue_dropped_{policy}", "counter"),
    ("tenant_{name}_state", "gauge"),
    ("tenant_{name}_rate_factor", "gauge"),
    ("tenant_{name}_templates_distinct", "gauge"),
    ("tenant_{name}_template_overflow", "counter"),
    ("tenant_{name}_template_{id}", "counter"),
    ("tenant_{name}_lines", "counter"),
    ("tenant_{name}_bytes", "counter"),
    ("tenant_{name}_drops", "counter"),
    ("tenant_{name}_shed", "counter"),
    ("fleet_hosts_{state}", "gauge"),
    ("fleet_peer{rank}_state", "gauge"),
    ("fleet_peer{rank}_hb_age_ms", "gauge"),
    ("fleet_peer{rank}_share", "gauge"),
    ("aot_rejects_{reason}", "counter"),
    ("fused_rows_{route}", "counter"),
    ("fused_fallbacks_{route}", "counter"),
    ("fetch_bytes_per_row_{route}", "gauge"),
    ("emit_bytes_per_row_{route}", "gauge"),
    ("framing_{path}_spr", "gauge"),
    ("events_{reason}", "counter"),
    ("route_rows_{route}", "counter"),
    ("e2e_batch_seconds_{route}", "histogram"),
    ("queue_wait_seconds_{tenant}", "histogram"),
    ("slo_{name}_burn_rate", "gauge"),
    ("slo_{name}_budget_remaining", "gauge"),
    ("sentinel_{route}_ratio", "gauge"),
    ("sentinel_{route}_baseline", "gauge"),
)

_classify_cache: Dict[str, Optional[str]] = {}
_CLASSIFY_CACHE_MAX = 4096  # /fleetz feeds REMOTE snapshot keys here:
#                             a skewed peer's churning names must not
#                             grow a process-global cache forever
_family_kind_rx = None


def classify_metric(name: str) -> Optional[str]:
    """``"counter" | "seconds" | "gauge" | "histogram" | None`` for a
    metric name, resolving the declared tuples first and then the
    family patterns above (first match wins — patterns are ordered
    most-specific-first where prefixes overlap)."""
    global _family_kind_rx
    cached = _classify_cache.get(name)
    if cached is not None or name in _classify_cache:
        return cached
    if _family_kind_rx is None:
        import re as _re

        def rx(pattern):
            out, pos = [], 0
            for m in _re.finditer(r"\{[A-Za-z0-9_]+\}", pattern):
                out.append(_re.escape(pattern[pos:m.start()]))
                out.append(r"[A-Za-z0-9_]+")
                pos = m.end()
            out.append(_re.escape(pattern[pos:]))
            return _re.compile("".join(out) + r"\Z")

        _family_kind_rx = [(rx(p), kind) for p, kind in _FAMILY_KINDS]
    kind: Optional[str] = None
    if name in _COUNTERS:
        kind = "counter"
    elif name in _SECONDS_NAMES:
        kind = "seconds"
    elif name in _GAUGE_NAMES:
        kind = "gauge"
    elif name in _HISTOGRAM_NAMES:
        kind = "histogram"
    else:
        for pattern, fam_kind in _family_kind_rx:
            if pattern.match(name):
                kind = fam_kind
                break
    if len(_classify_cache) < _CLASSIFY_CACHE_MAX:
        _classify_cache[name] = kind
    return kind


def window_quantiles(sorted_samples) -> Dict[str, float]:
    """p50/p99 over an already-sorted sample list — the ONE definition
    of this registry's summary quantiles.  Histogram.snapshot() and the
    fleet merge (fleet/federation.merge_metric_snapshots) both call it,
    so a per-host quantile change cannot drift from the fleet view."""
    if not sorted_samples:
        return {}
    n = len(sorted_samples)
    return {"p50": sorted_samples[n // 2],
            "p99": sorted_samples[min(n - 1, int(n * 0.99))]}


class Histogram:
    """Sliding-window latency histogram (last ``window`` samples)."""

    def __init__(self, window: int = 1024):
        self.window = window
        self._samples = []
        self._idx = itertools.count()
        self.count = 0
        self.sum = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float):
        with self._lock:
            self.count += 1
            self.sum += value
            if len(self._samples) < self.window:
                self._samples.append(value)
            else:
                self._samples[next(self._idx) % self.window] = value

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            samples = sorted(self._samples)
            count, total = self.count, self.sum
        if not samples:
            return {"count": 0, "sample_count": 0}
        return {
            "count": count,
            "sum": round(total, 6),
            "min": samples[0],
            **window_quantiles(samples),
            "max": samples[-1],
            # how many window samples back the quantiles above: the
            # window is bounded, so a scraper (and the fleet merge)
            # can judge quantile confidence instead of trusting a p99
            # computed from 3 samples
            "sample_count": len(samples),
        }

    def samples(self, cap: int = 128) -> list:
        """Up to ``cap`` evenly-strided window samples (sorted) — the
        raw material the fleet-level histogram merge pools so merged
        quantiles come from data, not from averaging per-host p99s."""
        with self._lock:
            samples = sorted(self._samples)
        if len(samples) <= cap:
            return [round(s, 6) for s in samples]
        stride = len(samples) / cap
        return [round(samples[int(i * stride)], 6) for i in range(cap)]


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {name: 0 for name in _COUNTERS}
        self._seconds: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        # named histogram family; batch_seconds keeps its attribute
        # alias (it predates the family and call sites/tests use it)
        self.batch_seconds = Histogram()
        self._hists: Dict[str, Histogram] = {
            "batch_seconds": self.batch_seconds}
        self._reporter: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # reporter sink shared between the interval thread and
        # final_flush: both write through ONE handle under ONE lock, so
        # a drain-time flush can never interleave bytes mid-line with a
        # reporter tick (the two used to open the append path
        # independently)
        self._out_lock = threading.Lock()
        self._out = None
        self._path: Optional[str] = None
        # observe taps: name -> (fn, ...) called after the histogram
        # records a sample (obs/slo.py threshold accounting).  Replaced
        # wholesale under _lock, read without it on the observe path —
        # an observe racing a reconfigure sees either tuple, both valid
        self._observe_taps: Dict[str, tuple] = {}

    def inc(self, name: str, value: int = 1):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def set_gauge(self, name: str, value: float):
        """Point-in-time values (e.g. device_breaker_state: 0 closed,
        1 open, 2 half-open) — reported alongside counters."""
        with self._lock:
            self._gauges[name] = value

    def init_gauge(self, name: str, value: float):
        """Make a gauge visible in reports without clobbering a live
        value (e.g. a second BatchHandler must not mask an open
        breaker's state with a fresh 0)."""
        with self._lock:
            self._gauges.setdefault(name, value)

    def get_gauge(self, name: str, default: float = 0):
        with self._lock:
            return self._gauges.get(name, default)

    def add_seconds(self, name: str, value: float):
        """Accumulate a per-stage wall-clock share (pipeline stage
        timings: device_fetch_seconds, encode_seconds, ...)."""
        with self._lock:
            self._seconds[name] = self._seconds.get(name, 0.0) + value

    def observe(self, name: str, value: float):
        """One sample into the named histogram family (created on
        first use): queue_wait_seconds, e2e_batch_seconds, ..."""
        h = self._hists.get(name)
        if h is None:
            with self._lock:
                h = self._hists.setdefault(name, Histogram())
        h.observe(value)
        taps = self._observe_taps.get(name)
        if taps:
            for tap in taps:
                tap(value)

    def add_observe_tap(self, name: str, fn) -> None:
        """Register ``fn(value)`` to run after every ``observe(name,
        ...)`` sample — the SLO engine's per-objective good/bad
        accounting.  Taps must be cheap and never raise."""
        with self._lock:
            self._observe_taps[name] = self._observe_taps.get(name, ()) \
                + (fn,)

    def clear_observe_taps(self) -> None:
        with self._lock:
            self._observe_taps = {}

    def histogram(self, name: str) -> Histogram:
        h = self._hists.get(name)
        if h is None:
            with self._lock:
                h = self._hists.setdefault(name, Histogram())
        return h

    def get(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def snapshot(self, include_hist_samples: bool = False
                 ) -> Dict[str, object]:
        """Flat JSON-safe snapshot.  ``include_hist_samples`` adds each
        histogram's bounded sample ring (the fleet /fleetz merge pools
        them for honest merged quantiles); the periodic JSONL reporter
        leaves it off so report lines stay one-screen."""
        with self._lock:
            counters = dict(self._counters)
            seconds = {k: round(v, 6) for k, v in self._seconds.items()}
            gauges = dict(self._gauges)
            hists = dict(self._hists)
        snap: Dict[str, object] = {"ts": round(time.time(), 3)}
        snap.update(counters)
        snap.update(seconds)
        snap.update(gauges)
        for name, h in hists.items():
            hsnap = h.snapshot()
            if include_hist_samples:
                hsnap["samples"] = h.samples()
            snap[name] = hsnap
        return snap

    def export(self) -> Dict[str, dict]:
        """Typed snapshot for renderers that need counter/gauge/
        histogram kinds kept apart (obs/prom.py — Prometheus TYPE
        lines)."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "seconds": dict(self._seconds),
                "gauges": dict(self._gauges),
                "histograms": {k: h.snapshot()
                               for k, h in self._hists.items()},
            }

    def reset(self):
        with self._lock:
            for k in self._counters:
                self._counters[k] = 0
            self._seconds.clear()
            self._gauges.clear()
            self.batch_seconds = Histogram()
            self._hists = {"batch_seconds": self.batch_seconds}
            self._observe_taps = {}

    # -- periodic reporter -------------------------------------------------
    def start_reporter(self, interval: float, path: Optional[str] = None):
        if interval <= 0 or self._reporter is not None:
            return
        self._path = path
        if path:
            try:
                self._out = open(path, "a")
            except OSError as e:
                print(f"metrics: cannot open {path} ({e}); reporting "
                      "to stderr", file=sys.stderr)
                self._path = None
                self._out = None

        def run():
            while not self._stop.wait(interval):
                self._write_snapshot()

        self._reporter = threading.Thread(target=run, daemon=True,
                                          name="metrics-reporter")
        self._reporter.start()

    def _write_snapshot(self) -> None:
        line = json.dumps(self.snapshot())
        with self._out_lock:
            out = self._out if self._out is not None else sys.stderr
            print(line, file=out, flush=True)

    def stop_reporter(self):
        self._stop.set()
        if self._reporter is not None:
            self._reporter.join(timeout=2)
            self._reporter = None
        self._stop = threading.Event()
        # release the sink and clear the stale path: a final_flush
        # after stop must not re-open a file the reporter no longer
        # owns (the old code left _path behind forever)
        with self._out_lock:
            if self._out is not None:
                self._out.close()
                self._out = None
            self._path = None

    def final_flush(self):
        """One last snapshot at shutdown — short-lived runs would
        otherwise exit between reporter ticks.  Writes through the
        reporter's own handle under its lock (never a second
        independent open of the same append path — the interleaved-
        bytes race the old implementation had)."""
        if self._reporter is None:
            return
        self._write_snapshot()


# process-wide registry; pipeline stages import and increment this
registry = Registry()


def configure_from(config) -> None:
    """Start the reporter (and optional XLA profiler trace) if [metrics]
    is configured (pipeline boot).  Also wires the observability layer:
    span tracing (obs/trace.py) and the degradation-event journal
    (obs/events.py) read their ``[metrics]`` keys here."""
    interval = config.lookup_int(
        "metrics.interval", "metrics.interval must be an integer", 0)
    path = config.lookup_str("metrics.path", "metrics.path must be a string")
    if interval and interval > 0:
        registry.start_reporter(float(interval), path)
    profile_dir = config.lookup_str(
        "metrics.jax_profile_dir", "metrics.jax_profile_dir must be a string")
    if profile_dir:
        global _profile_dir
        _profile_dir = profile_dir
        start_jax_profiler(profile_dir)
    from ..obs import events as _events
    from ..obs import slo as _slo
    from ..obs import trace as _trace

    _trace.configure_from(config)
    _events.configure_from(config)
    _slo.configure_from(config)


_profiling = False
# the directory on-demand profiling (SIGUSR2 / POST /profile) captures
# into: metrics.jax_profile_dir when configured, else a per-pid default
_profile_dir: Optional[str] = None


def _default_profile_dir() -> str:
    import os
    import tempfile

    return f"{tempfile.gettempdir()}/flowgger-xprof-{os.getpid()}"


def start_jax_profiler(log_dir: str) -> None:
    """Capture an XLA device trace of the batched decode path (viewable
    with tensorboard/xprof).  Stopped by stop_jax_profiler at drain."""
    global _profiling
    if _profiling:
        return
    try:
        import jax

        jax.profiler.start_trace(log_dir)
        _profiling = True
        print(f"jax profiler tracing to {log_dir}", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 - profiling must never kill ingest
        print(f"jax profiler unavailable: {e}", file=sys.stderr)


def stop_jax_profiler() -> None:
    global _profiling
    if not _profiling:
        return
    try:
        import jax

        jax.profiler.stop_trace()
    except Exception:  # noqa: BLE001  # flowcheck: disable=FC04 -- shutdown best-effort; profiling must never block drain
        pass
    _profiling = False


def toggle_jax_profiler() -> Tuple[bool, str]:
    """On-demand profiling flip (SIGUSR2 handler and the health
    server's ``POST /profile`` both land here): start a trace into the
    configured — or default per-pid — directory when idle, stop the
    running one otherwise.  Returns (now profiling?, log dir) so a
    soak-run operator can capture an xprof trace without a restart."""
    log_dir = _profile_dir or _default_profile_dir()
    if _profiling:
        stop_jax_profiler()
        print(f"jax profiler stopped (trace in {log_dir})",
              file=sys.stderr)
    else:
        start_jax_profiler(log_dir)
    return _profiling, log_dir
