"""Per-stage counters and latency histograms.

The reference has no observability at all — diagnostics are bare stderr
writes and its declared ``log`` dependency is never used (SURVEY.md §5).
This registry gives every pipeline stage cheap thread-safe counters and
the batched decode path a latency histogram, reported as one JSON line
on a configurable interval:

    [metrics]
    interval = 10            # seconds; 0/absent = disabled
    path = "metrics.jsonl"   # default: stderr

Counter names: input_lines, decoded_records, decode_errors,
encode_errors, invalid_utf8, enqueued, output_written, output_errors,
batches, batch_lines, fallback_rows.  ``batch_seconds`` is a histogram
(count/sum/min/max/p50/p99 over a sliding window).

Overlap executor stages report as cumulative seconds
(``dispatch_seconds`` submit-side pack+dispatch, ``fetch_seconds``
fetch-behind wall, ``overlap_stall_seconds`` window backpressure) plus
the ``inflight_depth`` gauge — see tpu/overlap.py.

Lane dispatch / compile stability (tpu/overlap.py LaneSet,
tpu/device_common.py cache+prewarm, tpu/pack.py bucketing):
``lane_depth`` (deepest lane) and per-lane ``lane{i}_depth`` gauges,
``lane{i}_rows`` counters, per-lane ``lane{i}_route_{device,host}_spr``
EWMA gauges, ``distinct_compiled_shapes`` gauge (every (rows, max_len)
shape packed so far), and the ``compile_cache_hits`` /
``compile_cache_misses`` / ``prewarmed_shapes`` counters — a second
cold process of an identical config with ``input.tpu_compile_cache_dir``
set should report zero misses.

Fused decode→encode routes (tpu/fused_routes.py): ``fused_rows`` (rows
emitted through a fused single-program route, plus the per-route
``fused_rows_{route}`` family), ``fused_fallbacks`` (batches that
declined from the fused tier to the split path, plus
``fused_fallbacks_{route}``), and the per-route
``fetch_bytes_per_row_{route}`` / ``emit_bytes_per_row_{route}`` gauges
— the fused acceptance is fetch under emit on every route.  Fused
compile-watchdog declines fold into the shared
``device_encode_compile_declines`` counter; per-lane fused-vs-split
economics export as ``lane{i}_route_fused_spr`` alongside the
device/host gauges.

Multi-tenant serving (tenancy/): per-tenant ``tenant_{name}_lines`` /
``_bytes`` (admitted), ``_drops`` (admission denials), ``_shed``
(queue-pressure sheds) counters and the ``tenant_{name}_state`` gauge
(0 admitting / 1 throttled / 2 shed), plus the aggregate
``tenant_lines/bytes/drops/shed``.  Queue sheds carry per-cause labels:
``queue_dropped_{drop_newest,drop_oldest,shed_noisiest}`` alongside the
aggregate ``queue_dropped``, and ``queue_shed_during_drain`` after the
pipeline enters its drain phase.  Template mining reports
``template_hits``, the ``tenant_templates_distinct`` gauge (and its
per-tenant form), and the per-template ``tenant_{name}_template_{id}``
counter family (capped; overflow ids fold into
``tenant_{name}_template_overflow``).

Fleet federation (fleet/): ``fleet_hosts_{joining,active,suspect,
draining,departed}`` gauges (the local host counts toward its own
state), per-peer ``fleet_peer{rank}_state`` (0..4 in ladder order) and
``fleet_peer{rank}_hb_age_ms`` gauges, plus the ``fleet_evictions`` /
``fleet_rejoins`` / ``fleet_hb_send_errors`` counters.  The whole
``snapshot()`` is what each host's HTTP health endpoint serves under
``metrics`` (fleet/health.py) — it is JSON-safe by construction
(counters and gauges are numbers, ``batch_seconds`` a flat dict), so
the health document needs no second serialization layer.
"""

from __future__ import annotations

import itertools
import json
import sys
import threading
import time
from typing import Dict, Optional

_COUNTERS = (
    "input_lines", "decoded_records", "decode_errors", "encode_errors",
    "invalid_utf8", "enqueued", "output_written", "output_errors",
    "batches", "batch_lines", "fallback_rows",
    # robustness / supervision layer
    "queue_dropped", "drain_stragglers", "drain_flush_errors",
    "sink_reconnects", "sink_failovers",
    "thread_crashes", "thread_restarts", "input_reconnects",
    "device_decode_errors", "breaker_trips", "breaker_recoveries",
    # overlap executor (tpu/overlap.py): D2H bytes the compaction +
    # constant-elision path avoided, and encode-route economics picks
    "fetch_bytes_saved", "encode_route_device", "encode_route_host",
    # compile stability (tpu/device_common.py): persistent-cache
    # traffic and startup kernel prewarm progress
    "compile_cache_hits", "compile_cache_misses", "prewarmed_shapes",
    # multi-tenant serving (tenancy/): aggregate admission and shed
    # counters — the per-tenant family (tenant_{name}_lines/_bytes/
    # _drops/_shed, tenant_{name}_state gauge) materializes on first
    # use, keyed by tenant name
    "tenant_lines", "tenant_bytes", "tenant_drops", "tenant_shed",
    # queue sheds that happened after the pipeline entered its drain
    # phase (bounded_queue.mark_draining): lets a SIGTERM test tell
    # shed lines from delivered lines
    "queue_shed_during_drain",
    # online template mining (tenancy/templates.py): rows mined; the
    # per-template family is tenant_{name}_template_{id} (+ _overflow)
    "template_hits",
    # fleet federation (fleet/): peers evicted by the missed-heartbeat
    # ladder, local rejoins after a discovered self-eviction, and
    # heartbeat deliveries that failed in transit (partition/churn —
    # normal life at fleet scale, counted not logged).  The state
    # gauges (fleet_hosts_{joining,active,suspect,draining,departed},
    # fleet_peer{rank}_state, fleet_peer{rank}_hb_age_ms) materialize
    # when membership starts
    "fleet_evictions", "fleet_rejoins", "fleet_hb_send_errors",
)


class Histogram:
    """Sliding-window latency histogram (last ``window`` samples)."""

    def __init__(self, window: int = 1024):
        self.window = window
        self._samples = []
        self._idx = itertools.count()
        self.count = 0
        self.sum = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float):
        with self._lock:
            self.count += 1
            self.sum += value
            if len(self._samples) < self.window:
                self._samples.append(value)
            else:
                self._samples[next(self._idx) % self.window] = value

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            samples = sorted(self._samples)
            count, total = self.count, self.sum
        if not samples:
            return {"count": 0}
        return {
            "count": count,
            "sum": round(total, 6),
            "min": samples[0],
            "p50": samples[len(samples) // 2],
            "p99": samples[min(len(samples) - 1, int(len(samples) * 0.99))],
            "max": samples[-1],
        }


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {name: 0 for name in _COUNTERS}
        self._seconds: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self.batch_seconds = Histogram()
        self._reporter: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def inc(self, name: str, value: int = 1):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def set_gauge(self, name: str, value: float):
        """Point-in-time values (e.g. device_breaker_state: 0 closed,
        1 open, 2 half-open) — reported alongside counters."""
        with self._lock:
            self._gauges[name] = value

    def init_gauge(self, name: str, value: float):
        """Make a gauge visible in reports without clobbering a live
        value (e.g. a second BatchHandler must not mask an open
        breaker's state with a fresh 0)."""
        with self._lock:
            self._gauges.setdefault(name, value)

    def get_gauge(self, name: str, default: float = 0):
        with self._lock:
            return self._gauges.get(name, default)

    def add_seconds(self, name: str, value: float):
        """Accumulate a per-stage wall-clock share (pipeline stage
        timings: device_fetch_seconds, encode_seconds, ...)."""
        with self._lock:
            self._seconds[name] = self._seconds.get(name, 0.0) + value

    def get(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            counters = dict(self._counters)
            seconds = {k: round(v, 6) for k, v in self._seconds.items()}
            gauges = dict(self._gauges)
        snap: Dict[str, object] = {"ts": round(time.time(), 3)}
        snap.update(counters)
        snap.update(seconds)
        snap.update(gauges)
        snap["batch_seconds"] = self.batch_seconds.snapshot()
        return snap

    def reset(self):
        with self._lock:
            for k in self._counters:
                self._counters[k] = 0
            self._seconds.clear()
            self._gauges.clear()
        self.batch_seconds = Histogram()

    # -- periodic reporter -------------------------------------------------
    def start_reporter(self, interval: float, path: Optional[str] = None):
        if interval <= 0 or self._reporter is not None:
            return
        self._path = path

        def run():
            out = open(path, "a") if path else sys.stderr
            try:
                while not self._stop.wait(interval):
                    print(json.dumps(self.snapshot()), file=out, flush=True)
            finally:
                if path:
                    out.close()

        self._reporter = threading.Thread(target=run, daemon=True,
                                          name="metrics-reporter")
        self._reporter.start()

    def stop_reporter(self):
        self._stop.set()
        if self._reporter is not None:
            self._reporter.join(timeout=2)
            self._reporter = None
        self._stop = threading.Event()

    def final_flush(self):
        """One last snapshot at shutdown — short-lived runs would
        otherwise exit between reporter ticks."""
        if self._reporter is None:
            return
        path = getattr(self, "_path", None)
        if path:
            with open(path, "a") as out:
                print(json.dumps(self.snapshot()), file=out, flush=True)
        else:
            print(json.dumps(self.snapshot()), file=sys.stderr, flush=True)


# process-wide registry; pipeline stages import and increment this
registry = Registry()


def configure_from(config) -> None:
    """Start the reporter (and optional XLA profiler trace) if [metrics]
    is configured (pipeline boot)."""
    interval = config.lookup_int(
        "metrics.interval", "metrics.interval must be an integer", 0)
    path = config.lookup_str("metrics.path", "metrics.path must be a string")
    if interval and interval > 0:
        registry.start_reporter(float(interval), path)
    profile_dir = config.lookup_str(
        "metrics.jax_profile_dir", "metrics.jax_profile_dir must be a string")
    if profile_dir:
        start_jax_profiler(profile_dir)


_profiling = False


def start_jax_profiler(log_dir: str) -> None:
    """Capture an XLA device trace of the batched decode path (viewable
    with tensorboard/xprof).  Stopped by stop_jax_profiler at drain."""
    global _profiling
    if _profiling:
        return
    try:
        import jax

        jax.profiler.start_trace(log_dir)
        _profiling = True
        print(f"jax profiler tracing to {log_dir}", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 - profiling must never kill ingest
        print(f"jax profiler unavailable: {e}", file=sys.stderr)


def stop_jax_profiler() -> None:
    global _profiling
    if not _profiling:
        return
    try:
        import jax

        jax.profiler.stop_trace()
    except Exception:  # noqa: BLE001  # flowcheck: disable=FC04 -- shutdown best-effort; profiling must never block drain
        pass
    _profiling = False
