"""Shared utilities: precise timestamps, Rust-compatible formatting,
calendar math, rotating files."""

from .rustfmt import display_f64, display_i64, json_f64
from .timeparse import (
    civil_from_days,
    days_from_civil,
    format_rfc3164_header_ts,
    format_time_description,
    now_precise,
    parse_english_time,
    parse_rfc3164_ts,
    rfc3339_to_unix,
    unix_to_rfc3339_ms,
)

__all__ = [
    "display_f64",
    "display_i64",
    "json_f64",
    "civil_from_days",
    "days_from_civil",
    "format_rfc3164_header_ts",
    "format_time_description",
    "now_precise",
    "parse_english_time",
    "parse_rfc3164_ts",
    "rfc3339_to_unix",
    "unix_to_rfc3339_ms",
]
