"""Minimal RESP (Redis serialization protocol) client over a socket.

Dependency-free replacement for the redis crate subset the reference
uses (redis_input.rs: RPOPLPUSH, BRPOPLPUSH, LREM; plus LPUSH/DEL for
tests).  RESP2 only — ample for these list commands.
"""

from __future__ import annotations

import socket
from typing import List, Optional, Union


class RespError(Exception):
    pass


class RespClient:
    def __init__(self, host: str, port: int = 6379, timeout: Optional[float] = None):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self._buf = b""

    @classmethod
    def from_connect_string(cls, connect: str, timeout: Optional[float] = None):
        if ":" in connect:
            host, _, port = connect.rpartition(":")
            return cls(host, int(port), timeout)
        return cls(connect, 6379, timeout)

    def close(self):
        try:
            self.sock.close()
        except OSError:  # flowcheck: disable=FC04 -- fd already dead; close is best-effort
            pass

    # -- wire --------------------------------------------------------------
    def _send(self, *parts: Union[str, bytes, int]):
        out = [f"*{len(parts)}\r\n".encode()]
        for p in parts:
            if isinstance(p, int):
                p = str(p)
            if isinstance(p, str):
                p = p.encode("utf-8")
            out.append(f"${len(p)}\r\n".encode() + p + b"\r\n")
        self.sock.sendall(b"".join(out))

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self.sock.recv(4096)
            if not chunk:
                raise RespError("connection closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self.sock.recv(4096)
            if not chunk:
                raise RespError("connection closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _read_reply(self):
        line = self._read_line()
        t, body = line[:1], line[1:]
        if t == b"+":
            return body.decode()
        if t == b"-":
            raise RespError(body.decode())
        if t == b":":
            return int(body)
        if t == b"$":
            n = int(body)
            if n == -1:
                return None
            data = self._read_exact(n)
            self._read_exact(2)  # trailing CRLF
            return data
        if t == b"*":
            n = int(body)
            if n == -1:
                return None
            return [self._read_reply() for _ in range(n)]
        raise RespError(f"unexpected reply type: {line!r}")

    def command(self, *parts):
        self._send(*parts)
        return self._read_reply()

    # -- the commands the pipeline needs ----------------------------------
    def rpoplpush(self, src: str, dst: str) -> Optional[bytes]:
        return self.command("RPOPLPUSH", src, dst)

    def brpoplpush(self, src: str, dst: str, timeout: int = 0) -> Optional[bytes]:
        return self.command("BRPOPLPUSH", src, dst, timeout)

    def lrem(self, key: str, count: int, value: bytes) -> int:
        return self.command("LREM", key, count, value)

    def lpush(self, key: str, value: bytes) -> int:
        return self.command("LPUSH", key, value)

    def lrange(self, key: str, start: int, stop: int) -> List[bytes]:
        return self.command("LRANGE", key, start, stop)

    def delete(self, key: str) -> int:
        return self.command("DEL", key)
