"""Minimal Kafka wire-protocol producer (dependency-free).

Implements what the Kafka output needs, against both broker
generations — the same capability set the reference gets from the
`kafka` crate (kafka_output.rs: required-acks -1/0/1, ack timeout,
gzip/snappy compression):

- **ApiVersions negotiation** on connect picks the protocol per broker:
  modern brokers (Kafka >= 0.11, including 4.x where KIP-896 removed
  the legacy versions) get Metadata v4 + Produce v3 with **record
  batches v2** (varint records, CRC32C, per-batch compression); legacy
  brokers that reject or don't answer ApiVersions get Metadata v0 +
  Produce v0 with the classic message-set format (magic 0, CRC32).
- gzip on both generations; snappy (raw block format,
  utils/snappy.py) on record batches v2.

Messages are round-robined across the topic's led partitions.

Protocol notes: every request is ``[i32 size][i16 api_key][i16 api_ver]
[i32 correlation][str client_id]body``; strings are i16-length-prefixed,
bytes i32-length-prefixed (-1 = null).
"""

from __future__ import annotations

import gzip as _gzip
import socket
import struct
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

_API_PRODUCE = 0
_API_METADATA = 3
_API_VERSIONS = 18
_CLIENT_ID = b"flowgger-tpu"


class KafkaError(Exception):
    pass


def _str(s: bytes) -> bytes:
    return struct.pack(">h", len(s)) + s


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


def _covers(rng: Optional[Tuple[int, int]], ver: int) -> bool:
    return rng is not None and rng[0] <= ver <= rng[1]


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.off = 0

    def i8(self) -> int:
        v = struct.unpack_from(">b", self.data, self.off)[0]
        self.off += 1
        return v

    def i16(self) -> int:
        v = struct.unpack_from(">h", self.data, self.off)[0]
        self.off += 2
        return v

    def i32(self) -> int:
        v = struct.unpack_from(">i", self.data, self.off)[0]
        self.off += 4
        return v

    def i64(self) -> int:
        v = struct.unpack_from(">q", self.data, self.off)[0]
        self.off += 8
        return v

    def string(self) -> Optional[str]:
        n = self.i16()
        if n == -1:
            return None
        s = self.data[self.off:self.off + n]
        self.off += n
        return s.decode("utf-8")


def _message(value: bytes, compression: int = 0) -> bytes:
    # magic 0: crc over [magic][attrs][key][value]
    body = struct.pack(">bb", 0, compression) + _bytes(None) + _bytes(value)
    crc = zlib.crc32(body) & 0xFFFFFFFF
    return struct.pack(">I", crc) + body


def _message_set(values: List[bytes], compression: str) -> bytes:
    msgs = b"".join(
        struct.pack(">q", 0) + struct.pack(">i", len(m)) + m
        for m in (_message(v) for v in values)
    )
    if compression == "gzip":
        wrapped = _message(_gzip.compress(msgs), compression=1)
        return struct.pack(">q", 0) + struct.pack(">i", len(wrapped)) + wrapped
    return msgs


# -- record batch v2 (message format v2, magic 2) ---------------------------

def _zigzag(v: int) -> int:
    return (v << 1) ^ (v >> 63)


def _varint(v: int) -> bytes:
    v = _zigzag(v) & 0xFFFFFFFFFFFFFFFF
    out = bytearray()
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


_COMPRESSION_ATTR = {"none": 0, "gzip": 1, "snappy": 2}


def _record(value: bytes, offset_delta: int) -> bytes:
    body = (b"\x00"                       # record attributes
            + _varint(0)                  # timestamp delta
            + _varint(offset_delta)
            + _varint(-1)                 # null key
            + _varint(len(value)) + value
            + _varint(0))                 # no headers
    return _varint(len(body)) + body


def _record_batch(values: List[bytes], compression: str,
                  now_ms: Optional[int] = None) -> bytes:
    """One record batch v2: varint records, CRC32C over the post-crc
    region, whole-payload compression per ``attributes``."""
    from .. import native

    if now_ms is None:
        now_ms = int(time.time() * 1000)
    records = b"".join(_record(v, i) for i, v in enumerate(values))
    attrs = _COMPRESSION_ATTR[compression]
    if compression == "gzip":
        records = _gzip.compress(records)
    elif compression == "snappy":
        from . import snappy as _snappy

        records = _snappy.compress(records)
    post_crc = (
        struct.pack(">hiqqqhii", attrs, len(values) - 1, now_ms, now_ms,
                    -1, -1, -1, len(values))
        + records
    )
    crc = native.crc32c(post_crc)
    head = struct.pack(">qi", 0, 4 + 1 + 4 + len(post_crc))  # offset, length
    return head + struct.pack(">ib", -1, 2) + struct.pack(">I", crc) + post_crc


class KafkaProducer:
    """Synchronous producer: one connection per partition leader."""

    def __init__(self, brokers: List[str], required_acks: int, timeout_ms: int,
                 compression: str = "none", socket_timeout: float = 30.0):
        if compression not in ("none", "gzip", "snappy"):
            raise KafkaError(f"Unsupported compression method: {compression}")
        self.brokers = brokers
        self.required_acks = required_acks
        self.timeout_ms = timeout_ms
        self.compression = compression
        self.socket_timeout = socket_timeout
        self._corr = 0
        self._lock = threading.Lock()
        self._conns: Dict[Tuple[str, int], socket.socket] = {}
        self._leaders: Dict[str, List[Tuple[int, Tuple[str, int]]]] = {}
        # per-broker negotiated (produce_version, metadata_version)
        self._versions: Dict[Tuple[str, int], Tuple[int, int]] = {}
        self._rr = 0

    # -- plumbing ----------------------------------------------------------
    def _connect(self, addr: Tuple[str, int]) -> socket.socket:
        sock = self._conns.get(addr)
        if sock is not None:
            return sock
        sock = socket.create_connection(addr, timeout=self.socket_timeout)
        self._conns[addr] = sock
        if addr not in self._versions:
            versions, cacheable = self._negotiate(addr, sock)
            if cacheable:
                # an explicit broker answer (modern ranges, or an error
                # code from a pre-ApiVersions broker) is authoritative;
                # a transport failure is NOT cached so the next
                # connection re-negotiates instead of pinning a modern
                # broker to legacy v0 after one network blip
                self._versions[addr] = versions
            if addr not in self._conns:
                # negotiation closed the socket (a pre-ApiVersions
                # broker dropping the unknown request, or a blip):
                # reconnect so the caller gets a usable connection for
                # its legacy-versioned attempt
                sock = socket.create_connection(
                    addr, timeout=self.socket_timeout)
                self._conns[addr] = sock
        return sock

    def _negotiate(self, addr, sock) -> Tuple[Tuple[int, int], bool]:
        """ApiVersions v0 → ((produce_version, metadata_version),
        cacheable).  A broker that answers with an error, or ignores /
        closes on the request, is treated as legacy v0; only transport
        failures are marked non-cacheable."""
        self._corr += 1
        header = (struct.pack(">hhi", _API_VERSIONS, 0, self._corr)
                  + _str(_CLIENT_ID))
        old_timeout = sock.gettimeout()
        try:
            sock.settimeout(5.0)
            sock.sendall(struct.pack(">i", len(header)) + header)
            raw = b""
            while len(raw) < 4:
                chunk = sock.recv(4 - len(raw))
                if not chunk:
                    raise OSError("closed")
                raw += chunk
            size = struct.unpack(">i", raw)[0]
            data = b""
            while len(data) < size:
                chunk = sock.recv(size - len(data))
                if not chunk:
                    raise OSError("closed")
                data += chunk
        except (OSError, TimeoutError):
            # could be a pre-ApiVersions broker ignoring the request OR
            # a transient network failure on a modern one: use legacy
            # for this attempt but renegotiate on the next connection
            self._conns.pop(addr, None)
            try:
                sock.close()
            except OSError:  # flowcheck: disable=FC04 -- fd already dead; close is best-effort
                pass
            return (0, 0), False
        finally:
            try:
                sock.settimeout(old_timeout)
            except OSError:  # flowcheck: disable=FC04 -- socket died during negotiation; the caller reconnects
                pass
        rd = _Reader(data)
        rd.i32()  # correlation
        if rd.i16() != 0:
            return (0, 0), True
        ranges = {}
        for _ in range(rd.i32()):
            api = rd.i16()
            lo, hi = rd.i16(), rd.i16()
            ranges[api] = (lo, hi)
        produce = 3 if _covers(ranges.get(_API_PRODUCE), 3) else 0
        metadata = 4 if _covers(ranges.get(_API_METADATA), 4) else 0
        return (produce, metadata), True

    def _roundtrip(self, addr, api_key: int, body: bytes,
                   expect_response: bool = True,
                   api_ver: int = 0) -> Optional[_Reader]:
        sock = self._connect(addr)
        self._corr += 1
        header = (struct.pack(">hhi", api_key, api_ver, self._corr)
                  + _str(_CLIENT_ID))
        payload = header + body
        try:
            sock.sendall(struct.pack(">i", len(payload)) + payload)
            if not expect_response:
                return None
            raw = b""
            while len(raw) < 4:
                chunk = sock.recv(4 - len(raw))
                if not chunk:
                    raise KafkaError("connection closed")
                raw += chunk
            size = struct.unpack(">i", raw)[0]
            data = b""
            while len(data) < size:
                chunk = sock.recv(size - len(data))
                if not chunk:
                    raise KafkaError("connection closed")
                data += chunk
        except OSError as e:
            self._conns.pop(addr, None)
            try:
                sock.close()
            except OSError:  # flowcheck: disable=FC04 -- fd already dead; close is best-effort
                pass
            raise KafkaError(str(e))
        rd = _Reader(data)
        rd.i32()  # correlation id
        return rd

    @staticmethod
    def _parse_broker_addr(broker: str) -> Tuple[str, int]:
        host, sep, port = broker.rpartition(":")
        if not sep:
            return broker, 9092
        if not port.isdigit():
            raise KafkaError(f"invalid broker address: {broker!r}")
        return host, int(port)

    # -- metadata ----------------------------------------------------------
    def refresh_metadata(self, topic: str):
        last_err = None
        for broker in self.brokers:
            addr = self._parse_broker_addr(broker)
            try:
                self._connect(addr)  # negotiate before picking the body
                mver = self._versions.get(addr, (0, 0))[1]
                body = struct.pack(">i", 1) + _str(topic.encode())
                if mver >= 4:
                    body += struct.pack(">b", 1)  # allow_auto_topic_creation
                rd = self._roundtrip(addr, _API_METADATA, body, api_ver=mver)
            except (KafkaError, OSError) as e:
                last_err = KafkaError(str(e))
                continue
            if mver >= 4:
                rd.i32()  # throttle_time_ms
            nodes = {}
            for _ in range(rd.i32()):
                node_id = rd.i32()
                host = rd.string()
                port = rd.i32()
                if mver >= 4:
                    rd.string()  # rack
                nodes[node_id] = (host, port)
            if mver >= 4:
                rd.string()  # cluster_id
                rd.i32()     # controller_id
            parts = []
            for _ in range(rd.i32()):
                rd.i16()  # topic error code
                tname = rd.string()
                if mver >= 4:
                    rd.i8()  # is_internal
                for _ in range(rd.i32()):
                    perr = rd.i16()
                    pid = rd.i32()
                    leader = rd.i32()
                    for _ in range(rd.i32()):
                        rd.i32()  # replicas
                    for _ in range(rd.i32()):
                        rd.i32()  # isr
                    if tname == topic and perr in (0, 9) and leader in nodes:
                        parts.append((pid, nodes[leader]))
            if parts:
                self._leaders[topic] = sorted(parts)
                return
            last_err = KafkaError(f"no leaders found for topic {topic}")
        raise KafkaError(f"metadata refresh failed: {last_err}")

    # -- produce -----------------------------------------------------------
    def send_all(self, topic: str, values: List[bytes]):
        if not values:
            return
        with self._lock:
            if topic not in self._leaders:
                self.refresh_metadata(topic)
            parts = self._leaders[topic]
            self._rr = (self._rr + 1) % len(parts)
            pid, addr = parts[self._rr]
            try:
                self._connect(addr)
            except OSError as e:
                self._leaders.pop(topic, None)
                raise KafkaError(str(e))
            pver = self._versions.get(addr, (0, 0))[0]
            if pver >= 3:
                mset = _record_batch(values, self.compression)
                body = (
                    struct.pack(">h", -1)  # null transactional_id
                    + struct.pack(">hi", self.required_acks, self.timeout_ms)
                    + struct.pack(">i", 1) + _str(topic.encode())
                    + struct.pack(">i", 1) + struct.pack(">i", pid)
                    + struct.pack(">i", len(mset)) + mset
                )
            else:
                if self.compression == "snappy":
                    raise KafkaError(
                        "snappy compression requires a broker supporting "
                        "record batches v2 (Kafka >= 0.11)")
                mset = _message_set(values, self.compression)
                body = (
                    struct.pack(">hi", self.required_acks, self.timeout_ms)
                    + struct.pack(">i", 1) + _str(topic.encode())
                    + struct.pack(">i", 1) + struct.pack(">i", pid)
                    + struct.pack(">i", len(mset)) + mset
                )
            try:
                rd = self._roundtrip(addr, _API_PRODUCE, body,
                                     expect_response=self.required_acks != 0,
                                     api_ver=pver)
            except KafkaError:
                self._leaders.pop(topic, None)
                raise
            if rd is not None:
                for _ in range(rd.i32()):
                    rd.string()
                    for _ in range(rd.i32()):
                        rd.i32()  # partition
                        err = rd.i16()
                        rd.i64()  # offset
                        if pver >= 3:
                            rd.i64()  # log_append_time
                        if err != 0:
                            self._leaders.pop(topic, None)
                            raise KafkaError(f"produce error code {err}")

    def send(self, topic: str, value: bytes):
        self.send_all(topic, [value])

    def close(self):
        for sock in self._conns.values():
            try:
                sock.close()
            except OSError:  # flowcheck: disable=FC04 -- fd already dead; close is best-effort
                pass
        self._conns.clear()
