"""Minimal Kafka wire-protocol producer (dependency-free).

Implements just what the Kafka output needs: Metadata v0 to find topic
partition leaders and Produce v0 with the classic message-set format
(magic 0, CRC32), optional gzip-wrapped compressed sets — the same
capability set the reference gets from the `kafka` crate
(kafka_output.rs: required-acks -1/0/1, ack timeout, gzip compression).
Messages are round-robined across the topic's led partitions.

Protocol notes: every request is ``[i32 size][i16 api_key][i16 api_ver]
[i32 correlation][str client_id]body``; strings are i16-length-prefixed,
bytes i32-length-prefixed (-1 = null).
"""

from __future__ import annotations

import gzip as _gzip
import socket
import struct
import threading
import zlib
from typing import Dict, List, Optional, Tuple

_API_PRODUCE = 0
_API_METADATA = 3
_CLIENT_ID = b"flowgger-tpu"


class KafkaError(Exception):
    pass


def _str(s: bytes) -> bytes:
    return struct.pack(">h", len(s)) + s


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.off = 0

    def i16(self) -> int:
        v = struct.unpack_from(">h", self.data, self.off)[0]
        self.off += 2
        return v

    def i32(self) -> int:
        v = struct.unpack_from(">i", self.data, self.off)[0]
        self.off += 4
        return v

    def i64(self) -> int:
        v = struct.unpack_from(">q", self.data, self.off)[0]
        self.off += 8
        return v

    def string(self) -> Optional[str]:
        n = self.i16()
        if n == -1:
            return None
        s = self.data[self.off:self.off + n]
        self.off += n
        return s.decode("utf-8")


def _message(value: bytes, compression: int = 0) -> bytes:
    # magic 0: crc over [magic][attrs][key][value]
    body = struct.pack(">bb", 0, compression) + _bytes(None) + _bytes(value)
    crc = zlib.crc32(body) & 0xFFFFFFFF
    return struct.pack(">I", crc) + body


def _message_set(values: List[bytes], compression: str) -> bytes:
    msgs = b"".join(
        struct.pack(">q", 0) + struct.pack(">i", len(m)) + m
        for m in (_message(v) for v in values)
    )
    if compression == "gzip":
        wrapped = _message(_gzip.compress(msgs), compression=1)
        return struct.pack(">q", 0) + struct.pack(">i", len(wrapped)) + wrapped
    return msgs


class KafkaProducer:
    """Synchronous producer: one connection per partition leader."""

    def __init__(self, brokers: List[str], required_acks: int, timeout_ms: int,
                 compression: str = "none", socket_timeout: float = 30.0):
        if compression not in ("none", "gzip"):
            raise KafkaError(f"Unsupported compression method: {compression}")
        self.brokers = brokers
        self.required_acks = required_acks
        self.timeout_ms = timeout_ms
        self.compression = compression
        self.socket_timeout = socket_timeout
        self._corr = 0
        self._lock = threading.Lock()
        self._conns: Dict[Tuple[str, int], socket.socket] = {}
        self._leaders: Dict[str, List[Tuple[int, Tuple[str, int]]]] = {}
        self._rr = 0

    # -- plumbing ----------------------------------------------------------
    def _connect(self, addr: Tuple[str, int]) -> socket.socket:
        sock = self._conns.get(addr)
        if sock is not None:
            return sock
        sock = socket.create_connection(addr, timeout=self.socket_timeout)
        self._conns[addr] = sock
        return sock

    def _roundtrip(self, addr, api_key: int, body: bytes,
                   expect_response: bool = True) -> Optional[_Reader]:
        sock = self._connect(addr)
        self._corr += 1
        header = struct.pack(">hhi", api_key, 0, self._corr) + _str(_CLIENT_ID)
        payload = header + body
        try:
            sock.sendall(struct.pack(">i", len(payload)) + payload)
            if not expect_response:
                return None
            raw = b""
            while len(raw) < 4:
                chunk = sock.recv(4 - len(raw))
                if not chunk:
                    raise KafkaError("connection closed")
                raw += chunk
            size = struct.unpack(">i", raw)[0]
            data = b""
            while len(data) < size:
                chunk = sock.recv(size - len(data))
                if not chunk:
                    raise KafkaError("connection closed")
                data += chunk
        except OSError as e:
            self._conns.pop(addr, None)
            try:
                sock.close()
            except OSError:
                pass
            raise KafkaError(str(e))
        rd = _Reader(data)
        rd.i32()  # correlation id
        return rd

    @staticmethod
    def _parse_broker_addr(broker: str) -> Tuple[str, int]:
        host, sep, port = broker.rpartition(":")
        if not sep:
            return broker, 9092
        if not port.isdigit():
            raise KafkaError(f"invalid broker address: {broker!r}")
        return host, int(port)

    # -- metadata ----------------------------------------------------------
    def refresh_metadata(self, topic: str):
        last_err = None
        for broker in self.brokers:
            try:
                rd = self._roundtrip(
                    self._parse_broker_addr(broker), _API_METADATA,
                    struct.pack(">i", 1) + _str(topic.encode()))
            except KafkaError as e:
                last_err = e
                continue
            nodes = {}
            for _ in range(rd.i32()):
                node_id = rd.i32()
                host = rd.string()
                port = rd.i32()
                nodes[node_id] = (host, port)
            parts = []
            for _ in range(rd.i32()):
                rd.i16()  # topic error code
                tname = rd.string()
                for _ in range(rd.i32()):
                    perr = rd.i16()
                    pid = rd.i32()
                    leader = rd.i32()
                    for _ in range(rd.i32()):
                        rd.i32()  # replicas
                    for _ in range(rd.i32()):
                        rd.i32()  # isr
                    if tname == topic and perr in (0, 9) and leader in nodes:
                        parts.append((pid, nodes[leader]))
            if parts:
                self._leaders[topic] = sorted(parts)
                return
            last_err = KafkaError(f"no leaders found for topic {topic}")
        raise KafkaError(f"metadata refresh failed: {last_err}")

    # -- produce -----------------------------------------------------------
    def send_all(self, topic: str, values: List[bytes]):
        if not values:
            return
        with self._lock:
            if topic not in self._leaders:
                self.refresh_metadata(topic)
            parts = self._leaders[topic]
            self._rr = (self._rr + 1) % len(parts)
            pid, addr = parts[self._rr]
            mset = _message_set(values, self.compression)
            body = (
                struct.pack(">hi", self.required_acks, self.timeout_ms)
                + struct.pack(">i", 1) + _str(topic.encode())
                + struct.pack(">i", 1) + struct.pack(">i", pid)
                + struct.pack(">i", len(mset)) + mset
            )
            try:
                rd = self._roundtrip(addr, _API_PRODUCE, body,
                                     expect_response=self.required_acks != 0)
            except KafkaError:
                self._leaders.pop(topic, None)
                raise
            if rd is not None:
                for _ in range(rd.i32()):
                    rd.string()
                    for _ in range(rd.i32()):
                        rd.i32()  # partition
                        err = rd.i16()
                        rd.i64()  # offset
                        if err != 0:
                            self._leaders.pop(topic, None)
                            raise KafkaError(f"produce error code {err}")

    def send(self, topic: str, value: bytes):
        self.send_all(topic, [value])

    def close(self):
        for sock in self._conns.values():
            try:
                sock.close()
            except OSError:
                pass
        self._conns.clear()
