"""Calendar math and timestamp parsing/formatting.

Behavioral model: the reference's use of the ``time`` crate —
- RFC3339 → unix f64 with nanosecond precision (rfc5424_decoder.rs:94-103,
  ``PreciseTimestamp::from_offset_datetime`` utils/mod.rs:23-27: integer
  nanos divided by 1e9 as f64);
- RFC3339 formatting with trailing-zero-trimmed subseconds and ``Z`` for
  UTC (rfc5424_encoder.rs:43-54 golden tests);
- the RFC3164 ``"[year] [month repr:short] [day] [hh]:[mm]:[ss]"`` form with
  optional IANA timezone (rfc3164_decoder.rs:153-213);
- the LTSV "english"/apache form ``d/Mon/yyyy:hh:mm:ss[.frac] ±zzzz``
  (ltsv_decoder.rs:224-253).

Everything integer-sized here is kept as exact int math until the single
final float division so results are bit-identical with the reference, and
so the same arithmetic can run columnar (int32 components) on TPU — see
flowgger_tpu/tpu/rfc5424.py which emits the same (days, secs, nanos)
decomposition.
"""

from __future__ import annotations

import time as _time
from typing import Optional, Tuple

MONTH_ABBR = ("Jan", "Feb", "Mar", "Apr", "May", "Jun",
              "Jul", "Aug", "Sep", "Oct", "Nov", "Dec")
_MONTH_IDX = {m: i + 1 for i, m in enumerate(MONTH_ABBR)}

_DAYS_IN_MONTH = (31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31)


def is_leap(year: int) -> bool:
    return year % 4 == 0 and (year % 100 != 0 or year % 400 == 0)


def days_in_month(year: int, month: int) -> int:
    if month == 2 and is_leap(year):
        return 29
    return _DAYS_IN_MONTH[month - 1]


def days_from_civil(y: int, m: int, d: int) -> int:
    """Days since 1970-01-01 (Howard Hinnant's civil-days algorithm —
    branch-free, so the TPU kernel runs the identical formula in int32)."""
    y -= m <= 2
    era = (y if y >= 0 else y - 399) // 400
    yoe = y - era * 400
    doy = (153 * (m + (-3 if m > 2 else 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def civil_from_days(z: int) -> Tuple[int, int, int]:
    z += 719468
    era = (z if z >= 0 else z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + (3 if mp < 10 else -9)
    return y + (m <= 2), m, d


def _ascii_digits(s: str) -> bool:
    """Rust-style digit check: ASCII 0-9 only (str.isdigit alone accepts
    Unicode digits the reference rejects)."""
    return bool(s) and s.isascii() and s.isdigit()


def _parse_fixed_digits(s: str, start: int, n: int) -> int:
    chunk = s[start:start + n]
    if len(chunk) != n or not _ascii_digits(chunk):
        raise ValueError(f"expected {n} digits at {start}")
    return int(chunk)


def rfc3339_to_unix(s: str) -> float:
    """Parse an RFC3339 timestamp into unix seconds as f64.

    Matches ``OffsetDateTime::parse(s, &Rfc3339)`` followed by
    ``unix_timestamp_nanos() as f64 / 1e9``: date components validated,
    subseconds capped at 9 digits, offset ``Z``/``z`` or ``±hh:mm``.
    Raises ValueError on any malformation.
    """
    n = len(s)
    if n < 20:
        raise ValueError("too short")
    year = _parse_fixed_digits(s, 0, 4)
    if s[4] != "-":
        raise ValueError("bad date separator")
    month = _parse_fixed_digits(s, 5, 2)
    if s[7] != "-":
        raise ValueError("bad date separator")
    day = _parse_fixed_digits(s, 8, 2)
    if s[10] not in "Tt":
        raise ValueError("bad time separator")
    hour = _parse_fixed_digits(s, 11, 2)
    if s[13] != ":":
        raise ValueError("bad time separator")
    minute = _parse_fixed_digits(s, 14, 2)
    if s[16] != ":":
        raise ValueError("bad time separator")
    sec = _parse_fixed_digits(s, 17, 2)
    if not (1 <= month <= 12 and 1 <= day <= days_in_month(year, month)):
        raise ValueError("bad date")
    if not (hour <= 23 and minute <= 59 and sec <= 59):
        raise ValueError("bad time")
    pos = 19
    nanos = 0
    if pos < n and s[pos] == ".":
        pos += 1
        frac_start = pos
        while pos < n and "0" <= s[pos] <= "9":
            pos += 1
        ndigits = pos - frac_start
        if ndigits == 0 or ndigits > 9:
            raise ValueError("bad subsecond")
        nanos = int(s[frac_start:pos]) * 10 ** (9 - ndigits)
    if pos >= n:
        raise ValueError("missing offset")
    offset_secs = 0
    c = s[pos]
    if c in "Zz":
        if pos + 1 != n:
            raise ValueError("trailing data")
    elif c in "+-":
        if pos + 6 != n or s[pos + 3] != ":":
            raise ValueError("bad offset")
        oh = _parse_fixed_digits(s, pos + 1, 2)
        om = _parse_fixed_digits(s, pos + 4, 2)
        if oh > 23 or om > 59:
            raise ValueError("bad offset")
        offset_secs = oh * 3600 + om * 60
        if c == "-":
            offset_secs = -offset_secs
    else:
        raise ValueError("bad offset")
    days = days_from_civil(year, month, day)
    total = days * 86400 + hour * 3600 + minute * 60 + sec - offset_secs
    return (total * 1_000_000_000 + nanos) / 1e9


def unix_to_rfc3339_ms(ts: float) -> str:
    """Format unix seconds as RFC3339 after millisecond truncation —
    ``((ts*1000.) as i128)*1_000_000`` then time-crate Rfc3339 formatting
    (rfc5424_encoder.rs:43-55): subsecond printed as 9 digits with trailing
    zeros trimmed, omitted entirely when zero, UTC rendered as ``Z``.
    """
    total_ns = int(ts * 1000.0) * 1_000_000
    secs, nanos = divmod(total_ns, 1_000_000_000)
    y, m, d = civil_from_days(secs // 86400)
    sod = secs % 86400
    hh, rem = divmod(sod, 3600)
    mm, ss = divmod(rem, 60)
    out = f"{y:04d}-{m:02d}-{d:02d}T{hh:02d}:{mm:02d}:{ss:02d}"
    if nanos:
        frac = f"{nanos:09d}".rstrip("0")
        out += f".{frac}"
    return out + "Z"


def now_precise() -> float:
    """PreciseTimestamp::now (utils/mod.rs:14-21): secs + nanos/1e9."""
    ns = _time.time_ns()
    return (ns // 1_000_000_000) + (ns % 1_000_000_000) / 1e9


def current_year_utc() -> int:
    return _time.gmtime().tm_year


def _tz_offset_nanos(tzname: str, year: int, month: int, day: int,
                     hour: int, minute: int, sec: int) -> Optional[int]:
    """UTC offset (seconds) for an IANA zone at the given *local* wall time,
    or None if the zone name is unknown.  Mirrors time-tz
    ``assume_timezone`` (rfc3164_decoder.rs:190-209)."""
    try:
        from zoneinfo import ZoneInfo
        import datetime as _dt

        tz = ZoneInfo(tzname)
    except Exception:  # flowcheck: disable=FC04 -- parse contract: None means "no zoneinfo"; caller logs once
        return None
    local = _dt.datetime(year, month, day, hour, minute, sec, tzinfo=tz)
    off = local.utcoffset()
    if off is None:
        return None
    return int(off.total_seconds())


def parse_rfc3164_ts(tokens, has_year: bool) -> Tuple[float, int]:
    """Parse ``[Mon] [day] [hh:mm:ss]`` (+optional leading year token when
    ``has_year``) followed by an optional IANA timezone token.

    Returns (unix_ts_f64, tokens_consumed).  Matches
    rfc3164_decoder.rs:162-213: without a year the *current UTC year* is
    assumed; a following token naming a known timezone shifts the result,
    otherwise the wall time is taken as UTC.
    """
    idx = 0
    if has_year:
        if len(tokens) < 4:
            raise ValueError("not enough tokens")
        year_s, mon_s, day_s, time_s = tokens[0], tokens[1], tokens[2], tokens[3]
        if not _ascii_digits(year_s):
            raise ValueError("bad year")
        year = int(year_s)
        idx = 4
    else:
        if len(tokens) < 3:
            raise ValueError("not enough tokens")
        year = current_year_utc()
        mon_s, day_s, time_s = tokens[0], tokens[1], tokens[2]
        idx = 3
    month = _MONTH_IDX.get(mon_s)
    if month is None:
        raise ValueError("bad month")
    if not _ascii_digits(day_s):
        raise ValueError("bad day")
    day = int(day_s)
    parts = time_s.split(":")
    if len(parts) != 3 or not all(_ascii_digits(p) for p in parts):
        raise ValueError("bad time")
    hour, minute, sec = (int(p) for p in parts)
    if not (len(parts[0]) == 2 and len(parts[1]) == 2 and len(parts[2]) == 2):
        raise ValueError("bad time field width")
    if not (1 <= day <= days_in_month(year, month)
            and hour <= 23 and minute <= 59 and sec <= 59):
        raise ValueError("bad date/time")

    days = days_from_civil(year, month, day)
    total = days * 86400 + hour * 3600 + minute * 60 + sec

    # Optional timezone token
    if idx < len(tokens):
        off = _tz_offset_nanos(tokens[idx], year, month, day, hour, minute, sec)
        if off is not None:
            return float((total - off) * 1_000_000_000 / 1e9), idx + 1
    return float(total * 1_000_000_000 / 1e9), idx


def parse_english_time(s: str) -> float:
    """Apache-style ``d/Mon/yyyy:hh:mm:ss[.frac] ±zzzz`` → unix f64
    (ltsv_decoder.rs:224-253; day has no padding, offset is mandatory
    with sign, 4-digit ``hhmm``)."""
    # split date part and offset part on the single space
    sp = s.find(" ")
    if sp < 0:
        raise ValueError("missing offset")
    dt_part, off_part = s[:sp], s[sp + 1:]
    if len(off_part) != 5 or off_part[0] not in "+-":
        raise ValueError("bad offset")
    if not _ascii_digits(off_part[1:]):
        raise ValueError("bad offset")
    oh, om = int(off_part[1:3]), int(off_part[3:5])
    offset = oh * 3600 + om * 60
    if off_part[0] == "-":
        offset = -offset

    comps = dt_part.split(":")
    if len(comps) != 4:
        raise ValueError("bad datetime")
    date_s, hh_s, mm_s, ss_s = comps
    dmy = date_s.split("/")
    if len(dmy) != 3:
        raise ValueError("bad date")
    day_s, mon_s, year_s = dmy
    if not (_ascii_digits(day_s) and _ascii_digits(year_s)):
        raise ValueError("bad date")
    month = _MONTH_IDX.get(mon_s)
    if month is None:
        raise ValueError("bad month")
    day, year = int(day_s), int(year_s)
    nanos = 0
    if "." in ss_s:
        sec_s, frac_s = ss_s.split(".", 1)
        if not (_ascii_digits(frac_s) and 1 <= len(frac_s) <= 9):
            raise ValueError("bad subsecond")
        nanos = int(frac_s) * 10 ** (9 - len(frac_s))
    else:
        sec_s = ss_s
    if not (_ascii_digits(hh_s) and _ascii_digits(mm_s) and _ascii_digits(sec_s)):
        raise ValueError("bad time")
    hour, minute, sec = int(hh_s), int(mm_s), int(sec_s)
    if not (1 <= month <= 12 and 1 <= day <= days_in_month(year, month)
            and hour <= 23 and minute <= 59 and sec <= 59):
        raise ValueError("bad date/time")
    days = days_from_civil(year, month, day)
    total = days * 86400 + hour * 3600 + minute * 60 + sec - offset
    return (total * 1_000_000_000 + nanos) / 1e9


def format_time_description(fmt: str, ts: Optional[float] = None) -> str:
    """Render a (subset of the) time-crate format-description string —
    the config surface for ``output.syslog_prepend_timestamp`` and
    ``file_rotation_timeformat`` (encoder/mod.rs:31, file_output.rs).

    Supported components: [year] [month] [month repr:short] [day]
    [day padding:none] [hour] [minute] [second]; literal text passes
    through.  Raises ValueError on an unknown component.
    """
    if ts is None:
        ts = now_precise()
    secs = int(ts)
    y, m, d = civil_from_days(secs // 86400)
    sod = secs % 86400
    hh, rem = divmod(sod, 3600)
    mm, ss = divmod(rem, 60)
    out = []
    i = 0
    while i < len(fmt):
        c = fmt[i]
        if c != "[":
            out.append(c)
            i += 1
            continue
        j = fmt.find("]", i)
        if j < 0:
            raise ValueError("unterminated format component")
        comp = fmt[i + 1:j].strip()
        if comp == "year":
            out.append(f"{y:04d}")
        elif comp == "month":
            out.append(f"{m:02d}")
        elif comp == "month repr:short":
            out.append(MONTH_ABBR[m - 1])
        elif comp == "day":
            out.append(f"{d:02d}")
        elif comp == "day padding:none":
            out.append(str(d))
        elif comp == "hour":
            out.append(f"{hh:02d}")
        elif comp == "minute":
            out.append(f"{mm:02d}")
        elif comp == "second":
            out.append(f"{ss:02d}")
        else:
            raise ValueError(f"unsupported format component: [{comp}]")
        i = j + 1
    return "".join(out)


def format_rfc3164_header_ts(ts: float) -> str:
    """``[month repr:short]  [day padding:none] [hh]:[mm]:[ss] `` — note the
    double space before the unpadded day (rfc3164_encoder.rs:55-58)."""
    secs = int(ts)
    y, m, d = civil_from_days(secs // 86400)
    sod = secs % 86400
    hh, rem = divmod(sod, 3600)
    mm, ss = divmod(rem, 60)
    return f"{MONTH_ABBR[m - 1]}  {d} {hh:02d}:{mm:02d}:{ss:02d} "
