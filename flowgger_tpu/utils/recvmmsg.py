"""Batched UDP receive via the Linux ``recvmmsg(2)`` syscall (ctypes,
no external deps).

The reference's UDP input performs one ``recv_from`` syscall per
datagram (udp_input.rs:78-82).  For the batched TPU pipeline that loop
is the ingest bottleneck, so this binding pulls up to ``vlen`` datagrams
per syscall into one resident buffer and hands back (offsets, lengths)
arrays that flow straight into the span-ingest path — no per-datagram
Python objects for well-formed traffic.  ``available()`` is False off
Linux and callers keep the portable loop.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import socket
from typing import Optional, Tuple

import numpy as np

from ..inputs.udp_input import MAX_UDP_PACKET_SIZE


class _IoVec(ctypes.Structure):
    _fields_ = [("iov_base", ctypes.c_void_p),
                ("iov_len", ctypes.c_size_t)]


class _MsgHdr(ctypes.Structure):
    _fields_ = [("msg_name", ctypes.c_void_p),
                ("msg_namelen", ctypes.c_uint32),
                ("msg_iov", ctypes.POINTER(_IoVec)),
                ("msg_iovlen", ctypes.c_size_t),
                ("msg_control", ctypes.c_void_p),
                ("msg_controllen", ctypes.c_size_t),
                ("msg_flags", ctypes.c_int)]


class _MMsgHdr(ctypes.Structure):
    _fields_ = [("msg_hdr", _MsgHdr),
                ("msg_len", ctypes.c_uint32)]


_libc = None


def _get_libc():
    global _libc
    if _libc is None:
        try:
            lib = ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6",
                              use_errno=True)
            lib.recvmmsg
            _libc = lib
        except (OSError, AttributeError):  # flowcheck: disable=FC04 -- availability probe; caller falls back to recvfrom
            _libc = False
    return _libc


def available() -> bool:
    import sys

    return bool(sys.platform.startswith("linux") and _get_libc())


class BatchReceiver:
    """Reusable recvmmsg state for one socket: ``vlen`` iovecs of
    ``MAX_UDP_PACKET_SIZE`` bytes over one resident buffer."""

    def __init__(self, sock: socket.socket, vlen: int = 64):
        self._libc = _get_libc()
        if not self._libc:
            raise OSError("recvmmsg unavailable")
        self.sock = sock
        self.vlen = vlen
        self._buf = np.empty(vlen * MAX_UDP_PACKET_SIZE, dtype=np.uint8)
        base = self._buf.ctypes.data
        self._iovecs = (_IoVec * vlen)()
        self._hdrs = (_MMsgHdr * vlen)()
        for i in range(vlen):
            self._iovecs[i].iov_base = base + i * MAX_UDP_PACKET_SIZE
            self._iovecs[i].iov_len = MAX_UDP_PACKET_SIZE
            h = self._hdrs[i].msg_hdr
            h.msg_name = None
            h.msg_namelen = 0
            h.msg_iov = ctypes.pointer(self._iovecs[i])
            h.msg_iovlen = 1
            h.msg_control = None
            h.msg_controllen = 0
            h.msg_flags = 0
        self._starts = (np.arange(vlen, dtype=np.int64)
                        * MAX_UDP_PACKET_SIZE)

    def recv_batch(self) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Block for at least one datagram, then drain whatever else is
        already queued (MSG_WAITFORONE).  Returns (buffer view, starts,
        lens) for n >= 1 datagrams, or None on EINTR/socket close."""
        import errno as _errno

        MSG_WAITFORONE = 0x10000
        n = self._libc.recvmmsg(self.sock.fileno(), self._hdrs, self.vlen,
                                MSG_WAITFORONE, None)
        if n <= 0:
            err = ctypes.get_errno()
            if err in (_errno.EBADF, _errno.ENOTSOCK, _errno.EINVAL):
                # socket closed under us: surface instead of hot-spinning
                raise OSError(err, "socket closed")
            return None
        lens = np.fromiter((self._hdrs[i].msg_len for i in range(n)),
                           dtype=np.int64, count=n)
        return self._buf, self._starts[:n], lens
