"""Size/time-based rotating file writer.

Parity model: /root/reference/src/flowgger/utils/rotating_file.rs:13-372.

- size mode (``max_time == 0 and max_size > 0``): when the next write
  would exceed ``max_size``, shift ``base.(n)`` → ``base.(n+1)`` for the
  newest ``max_files`` slots (the extension *replaces* the basename's,
  Rust ``set_extension``) and reopen the base file;
- time mode (``max_time > 0``): writes go to a timestamped file
  ``{stem}-{time_format}.{ext}``; rotation when the deadline passes or
  the size cap is hit, each rotation opening a freshly stamped file;
  ``max_files`` is *not* enforced in this mode (reference behavior);
- append-mode opens, size primed from existing file length.

``now_fn`` is injectable for tests — the reference uses a test-only
``now_time_mock`` field (rotating_file.rs:24-26).
"""

from __future__ import annotations

import os
import sys
import time as _time
from pathlib import Path
from typing import Callable, Optional

from .timeparse import format_time_description


class RotatingFile:
    def __init__(self, basepath: str, max_size: int, max_time: int,
                 max_files: int, time_format: str,
                 now_fn: Callable[[], float] = _time.time):
        self.basename = Path(basepath)
        self.max_size = max_size
        self.max_time = max_time
        self.max_files = max_files
        self.time_format = time_format
        self.now_fn = now_fn
        self.current_file = None
        self.current_size = 0
        self.next_rotation_time: Optional[float] = None

    # -- mode predicates (rotating_file.rs:176-188) ------------------------
    def is_enabled(self) -> bool:
        return self.is_time_triggered() or self.is_size_triggered()

    def is_time_triggered(self) -> bool:
        return self.max_time > 0

    def is_size_triggered(self) -> bool:
        return self.max_time == 0 and self.max_size > 0

    # ----------------------------------------------------------------------
    def _build_timestamped_filename(self) -> Path:
        now = self.now_fn()
        self.next_rotation_time = now + self.max_time * 60
        dt_str = format_time_description(self.time_format, now)
        stem = self.basename.stem
        ext = self.basename.suffix[1:] if self.basename.suffix else ""
        return self.basename.with_name(f"{stem}-{dt_str}.{ext}")

    def open(self):
        path = (self._build_timestamped_filename()
                if self.is_time_triggered() else self.basename)
        # buffering=0: the reference writes straight to the fd (Rust File
        # has no userspace buffer); buffering is opt-in via BufferedWriter.
        self.current_file = open(path, "ab", buffering=0)
        self.current_size = os.fstat(self.current_file.fileno()).st_size

    @staticmethod
    def open_file(path: str):
        return open(path, "ab", buffering=0)

    def _build_file_path(self, file_num: int) -> Path:
        if file_num < 0:
            return self.basename
        return self.basename.with_suffix(f".{file_num}")

    def _rotate_size(self):
        print(f"File {self.basename} reached size limit {self.max_size}, rotating",
              file=sys.stderr)
        if self.current_file is not None:
            self.current_file.close()
            self.current_file = None
        dest = self._build_file_path(self.max_files - 1)
        for file_num in range(self.max_files - 1, -1, -1):
            src = self._build_file_path(file_num - 1)
            try:
                os.rename(src, dest)
            except OSError:  # flowcheck: disable=FC04 -- gaps in the rotation chain are expected (missing older files)
                pass
            dest = src
        self.open()
        self.current_size = 0

    def _rotate_time(self):
        print(
            f"File {self.basename} reached time/size limit "
            f"{self.max_time}min/{self.max_size}bytes, rotating",
            file=sys.stderr,
        )
        if self.current_file is not None:
            self.current_file.close()
            self.current_file = None
        self.open()
        self.current_size = 0

    def _is_rotation_time_reached(self) -> bool:
        return (self.next_rotation_time is not None
                and self.next_rotation_time <= self.now_fn())

    def _is_rotation_size_reached(self, nbytes: int) -> bool:
        return self.max_size > 0 and self.current_size + nbytes > self.max_size

    def _check_rotation_trigger(self, nbytes: int):
        if self.is_time_triggered():
            if self._is_rotation_time_reached() or self._is_rotation_size_reached(nbytes):
                self._rotate_time()
        elif self.is_size_triggered() and self._is_rotation_size_reached(nbytes):
            self._rotate_size()

    # -- Write impl (rotating_file.rs:345-372) -----------------------------
    def write(self, buf: bytes) -> int:
        self._check_rotation_trigger(len(buf))
        self.current_size += len(buf)
        if self.current_file is not None:
            self.current_file.write(buf)
        return len(buf)

    def flush(self):
        if self.current_file is not None:
            self.current_file.flush()

    def close(self):
        if self.current_file is not None:
            self.current_file.close()
            self.current_file = None


class BufferedWriter:
    """Rust-style BufWriter: buffer up to ``capacity`` bytes; a write that
    doesn't fit flushes the buffer first; oversized writes go straight
    through (file_output.rs:172-177 pairs this with RotatingFile)."""

    def __init__(self, inner, capacity: int):
        self.inner = inner
        self.capacity = capacity
        self.buf = bytearray()

    def write(self, data: bytes) -> int:
        if len(self.buf) + len(data) > self.capacity:
            self.flush()
        if len(data) >= self.capacity:
            self.inner.write(data)
        else:
            self.buf.extend(data)
        return len(data)

    def flush(self):
        if self.buf:
            self.inner.write(bytes(self.buf))
            self.buf.clear()
        self.inner.flush()

    def close(self):
        self.flush()
        if hasattr(self.inner, "close"):
            self.inner.close()
