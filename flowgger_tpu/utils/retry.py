"""One retry/backoff policy for every reconnect loop in the pipeline.

Before this module the tree had three divergent hand-rolled recovery
loops: ``outputs/tls_output.py`` (randomized additive backoff with a
stability probe, reference parity with tls_output.rs:163-172),
``outputs/kafka_output.py`` (no retry at all — one error exits the
process), and ``inputs/redis_input.py`` (same exit-on-error contract).
``RetryPolicy`` expresses all three:

- mode ``"additive"`` — the reference's TLS recovery: the delay grows by
  ``uniform(0, delay)`` per failure up to ``max_ms`` and resets to
  ``init_ms`` once a connection has been stable for ``probe_ms``;
- mode ``"exponential"`` — classic exponential backoff with *full
  jitter* (AWS architecture-blog variant: ``sleep(uniform(0, min(cap,
  init * mult**attempt)))``), the default for everything new;
- an optional ``deadline_ms`` / ``max_attempts`` bound after which
  ``backoff()`` reports exhaustion so callers can fall back to their
  legacy die/degrade contract;
- a metrics hook: every backoff bumps a named counter in
  ``utils.metrics`` so recovery churn is observable.

The policy object is intentionally *stateful* (one per supervised
loop/thread; it is not shared) and deterministic under injected ``rng``
and ``sleep`` for tests.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple

from .metrics import registry as _metrics

DEFAULT_INIT_MS = 100
DEFAULT_MAX_MS = 10_000
DEFAULT_MULTIPLIER = 2.0


class RetryExhausted(Exception):
    """Raised by ``run()`` when the policy's attempt/deadline budget is
    spent; carries the last underlying error as ``__cause__``."""


class RetryPolicy:
    def __init__(
        self,
        init_ms: float = DEFAULT_INIT_MS,
        max_ms: float = DEFAULT_MAX_MS,
        mode: str = "exponential",
        multiplier: float = DEFAULT_MULTIPLIER,
        probe_ms: Optional[float] = None,
        deadline_ms: Optional[float] = None,
        max_attempts: Optional[int] = None,
        metric: Optional[str] = None,
        sleep: Callable[[float], None] = time.sleep,
        rng: Callable[[float, float], float] = random.uniform,
        clock: Callable[[], float] = time.monotonic,
    ):
        if mode not in ("exponential", "additive"):
            raise ValueError(f"unknown retry mode: {mode}")
        if max_ms < init_ms:
            raise ValueError("max_ms cannot be less than init_ms")
        self.init_ms = float(init_ms)
        self.max_ms = float(max_ms)
        self.mode = mode
        self.multiplier = multiplier
        self.probe_ms = probe_ms
        self.deadline_ms = deadline_ms
        self.max_attempts = max_attempts
        self.metric = metric
        self._sleep = sleep
        self._rng = rng
        self._clock = clock
        self.reset()

    # -- state -------------------------------------------------------------
    def reset(self) -> None:
        """Back to a fresh policy: next backoff starts at ``init_ms``."""
        self.attempts = 0
        self._delay_ms = self.init_ms
        self._started = self._clock()
        self._attempt_started = self._started

    def mark(self) -> None:
        """Note the start of a connection attempt / success window (the
        additive mode's stability probe measures from here)."""
        self._attempt_started = self._clock()

    def note_success(self) -> None:
        """An attempt fully succeeded: reset the growth state while
        keeping the deadline anchored (a long-lived supervised loop calls
        this instead of ``reset()`` so ``attempts`` totals stay
        meaningful for metrics)."""
        self._delay_ms = self.init_ms
        self._started = self._clock()
        self.attempts = 0

    def note_run(self, started: float) -> None:
        """Supervision loops: a target/connection that stayed up longer
        than the max backoff window counts as having recovered — it
        earns a fresh retry budget, so a daemon that crashes once a day
        never exhausts ``max_attempts``."""
        if (self._clock() - started) * 1000.0 > self.max_ms:
            self.note_success()

    def exhausted(self) -> bool:
        if self.max_attempts is not None and self.attempts >= self.max_attempts:
            return True
        if self.deadline_ms is not None:
            return (self._clock() - self._started) * 1000.0 >= self.deadline_ms
        return False

    # -- delays ------------------------------------------------------------
    def next_delay_ms(self) -> float:
        """Advance the failure state and return the next delay in ms
        (without sleeping)."""
        if self.mode == "additive":
            # tls_output.rs:163-172: reset after a stable probe window,
            # otherwise additive randomized growth capped at max
            elapsed_ms = (self._clock() - self._attempt_started) * 1000.0
            if self.probe_ms is not None and elapsed_ms > self.probe_ms:
                self._delay_ms = self.init_ms
            elif self._delay_ms < self.max_ms:
                self._delay_ms += self._rng(0.0, self._delay_ms)
            self.attempts += 1
            return float(round(self._delay_ms))
        base = min(self.max_ms, self.init_ms * (self.multiplier ** self.attempts))
        self.attempts += 1
        return self._rng(0.0, base)  # full jitter

    def backoff(self) -> Optional[float]:
        """Sleep for the next delay and return it (ms); ``None`` when the
        policy is exhausted (caller should give up / degrade)."""
        if self.exhausted():
            return None
        delay_ms = self.next_delay_ms()
        if self.metric:
            _metrics.inc(self.metric)
        self._sleep(delay_ms / 1000.0)
        return delay_ms

    # -- convenience wrapper -----------------------------------------------
    def run(self, fn: Callable, retry_on: Tuple[type, ...] = (Exception,),
            on_error: Optional[Callable[[BaseException], None]] = None):
        """Call ``fn()`` until it returns, backing off between failures;
        raises ``RetryExhausted`` (chaining the last error) when the
        attempt/deadline budget runs out."""
        while True:
            try:
                return fn()
            except retry_on as e:  # noqa: PERF203 - retry loop by design
                if on_error is not None:
                    on_error(e)
                if self.backoff() is None:
                    raise RetryExhausted(str(e)) from e


def retry_config_kwargs(config, prefix: str, init_ms: float = DEFAULT_INIT_MS,
                        max_ms: float = DEFAULT_MAX_MS,
                        max_attempts: Optional[int] = None) -> dict:
    """RetryPolicy constructor kwargs from ``{prefix}_retry_*`` config
    keys (``init`` / ``max`` / ``attempts`` in the TOML, e.g.
    ``output.kafka_retry_init = 250``).  Components that build one
    policy per worker thread keep this dict and construct from it."""
    kw = dict(
        init_ms=config.lookup_int(
            f"{prefix}_retry_init",
            f"{prefix}_retry_init must be an integer (ms)", int(init_ms)),
        max_ms=config.lookup_int(
            f"{prefix}_retry_max",
            f"{prefix}_retry_max must be an integer (ms)", int(max_ms)),
        max_attempts=config.lookup_int(
            f"{prefix}_retry_attempts",
            f"{prefix}_retry_attempts must be an integer", max_attempts))
    if kw["max_ms"] < kw["init_ms"]:
        from ..config import ConfigError

        # boot-time rejection: RetryPolicy's ValueError inside a worker
        # thread would otherwise become a supervised crash loop
        raise ConfigError(
            f"{prefix}_retry_max cannot be less than {prefix}_retry_init")
    return kw


def policy_from_config(config, prefix: str, **defaults) -> RetryPolicy:
    """One RetryPolicy straight from ``{prefix}_retry_*`` config keys;
    extra ``defaults`` (mode, metric, ...) pass through."""
    kw = retry_config_kwargs(
        config, prefix,
        init_ms=defaults.pop("init_ms", DEFAULT_INIT_MS),
        max_ms=defaults.pop("max_ms", DEFAULT_MAX_MS),
        max_attempts=defaults.pop("max_attempts", None))
    kw.update(defaults)  # mode, metric, probe_ms, ... pass through
    return RetryPolicy(**kw)
