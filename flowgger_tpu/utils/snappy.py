"""Snappy block-format codec (raw, un-framed) — the compression the
Kafka record-batch v2 format names attributes=2.

Native path: fg_snappy_compress/decompress in native/flowgger_host.cpp
(greedy 64KB-block hash matching, the standard algorithm).  Pure-Python
fallback: compression emits all-literal blocks (valid snappy per the
format spec — every decoder accepts it — at ratio 1.0) and the
decompressor handles every element type, so the codec is functional
with no toolchain at all.  The reference gets snappy from the kafka
crate (kafka_output.rs:169-196); this is the from-scratch equivalent.
"""

from __future__ import annotations

import ctypes

import numpy as np

from .. import native as _native


class SnappyError(Exception):
    pass


def _varint(v: int) -> bytes:
    out = bytearray()
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def compress(data: bytes) -> bytes:
    lib = _native._load()
    if lib is not None and hasattr(lib, "fg_snappy_compress"):
        src = np.frombuffer(data, dtype=np.uint8)
        cap = int(lib.fg_snappy_max_compressed(len(data)))
        dst = np.empty(cap, dtype=np.uint8)
        n = lib.fg_snappy_compress(
            src.ctypes.data if len(data) else None, len(data),
            dst.ctypes.data)
        return dst[:n].tobytes()
    # literal-only fallback: preamble + one literal element per 2^24-1
    out = bytearray(_varint(len(data)))
    pos = 0
    while pos < len(data):
        chunk = data[pos:pos + (1 << 24) - 1]
        n = len(chunk) - 1
        if n < 60:
            out.append(n << 2)
        elif n < 256:
            out += bytes((60 << 2, n))
        elif n < 65536:
            out += bytes((61 << 2, n & 0xFF, n >> 8))
        else:
            out += bytes((62 << 2, n & 0xFF, (n >> 8) & 0xFF, n >> 16))
        out += chunk
        pos += len(chunk)
    return bytes(out)


def _read_varint(data: bytes, pos: int):
    v = 0
    shift = 0
    while pos < len(data):
        b = data[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        if not (b & 0x80):
            return v, pos
        shift += 7
        if shift > 35:
            break
    raise SnappyError("bad varint preamble")


def decompress(data: bytes) -> bytes:
    ulen, pos = _read_varint(data, 0)
    lib = _native._load()
    if lib is not None and hasattr(lib, "fg_snappy_decompress"):
        src = np.frombuffer(data, dtype=np.uint8)
        dst = np.empty(max(ulen, 1), dtype=np.uint8)
        n = lib.fg_snappy_decompress(
            src.ctypes.data if len(data) else None, len(data),
            dst.ctypes.data, ulen)
        if n < 0:
            raise SnappyError("malformed snappy block")
        return dst[:n].tobytes()
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:
            ln = (tag >> 2) + 1
            if ln > 60:
                nb = ln - 60
                if pos + nb > n:
                    raise SnappyError("truncated literal length")
                ln = int.from_bytes(data[pos:pos + nb], "little") + 1
                pos += nb
            if pos + ln > n:
                raise SnappyError("truncated literal")
            out += data[pos:pos + ln]
            pos += ln
            continue
        if kind == 1:
            if pos >= n:
                raise SnappyError("truncated copy")
            ln = ((tag >> 2) & 7) + 4
            off = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:
            if pos + 2 > n:
                raise SnappyError("truncated copy")
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[pos:pos + 2], "little")
            pos += 2
        else:
            if pos + 4 > n:
                raise SnappyError("truncated copy")
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[pos:pos + 4], "little")
            pos += 4
        if off == 0 or off > len(out):
            raise SnappyError("bad copy offset")
        for _ in range(ln):  # overlapping copies are byte-serial
            out.append(out[-off])
    if len(out) != ulen:
        raise SnappyError("length mismatch")
    return bytes(out)
