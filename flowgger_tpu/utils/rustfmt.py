"""Rust-compatible number formatting.

The reference emits floats through two distinct Rust paths and the output
bytes differ, so we model both:

- ``display_f64`` — Rust ``f64::to_string()`` / ``{}`` Display (used by the
  RFC5424 structured-data renderer, record.rs:55-62, and the LTSV encoder,
  ltsv_encoder.rs:84-88): shortest round-trip decimal, *never* scientific
  notation, integral values lose the ``.0``.
- ``json_f64`` — serde_json float serialization (gelf_encoder.rs:113): the
  shortest round-trip form, keeping ``.0`` on integral values and using
  ``e``-notation without a ``+`` sign for extreme magnitudes.
"""

from __future__ import annotations

from decimal import Decimal


def display_f64(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if v == float("inf"):
        return "inf"
    if v == float("-inf"):
        return "-inf"
    r = repr(float(v))
    if "e" in r or "E" in r:
        # Expand scientific notation to plain decimal, as Rust Display does.
        d = Decimal(r)
        r = format(d, "f")
    if r.endswith(".0"):
        r = r[:-2]
    # Python prints -0.0; Rust Display prints "-0".
    return r


def json_f64(v: float) -> str:
    if v != v or v in (float("inf"), float("-inf")):
        # serde_json emits null for non-finite floats.
        return "null"
    r = repr(float(v))
    if "e" in r:
        # Python: 1e+20 / 1e-07 ; dtoa (serde_json): 1e20 / 1e-7
        mant, exp = r.split("e")
        sign = "-" if exp.startswith("-") else ""
        exp = exp.lstrip("+-").lstrip("0") or "0"
        r = f"{mant}e{sign}{exp}"
    return r


def display_i64(v: int) -> str:
    return str(int(v))
