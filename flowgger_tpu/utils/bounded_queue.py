"""Bounded pipeline queue with an overflow policy.

The reference's ``sync_channel`` blocks producers when the queue is full
(backpressure all the way to the socket).  That stays the default, but a
collector in front of a slow sink sometimes prefers shedding load to
stalling ingest, so the queue grows a policy:

    [input]
    queue_policy = "block"        # reference parity (default)
                 | "drop_newest"  # full queue: discard the incoming item
                 | "drop_oldest"  # full queue: discard the oldest item

Every shed message bumps the ``queue_dropped`` counter.  The SHUTDOWN
sentinel (``None``) is exempt: it always uses a blocking put and is
never dropped, so graceful drain survives any policy.

The ``queue_pressure`` fault-injection site makes a put behave as if the
queue were full (deterministically, see ``utils.faultinject``), so the
drop paths are testable without actually wedging a sink.
"""

from __future__ import annotations

import queue

from . import faultinject
from .metrics import registry as _metrics

POLICIES = ("block", "drop_newest", "drop_oldest")


class PolicyQueue(queue.Queue):
    def __init__(self, maxsize: int = 0, policy: str = "block"):
        if policy not in POLICIES:
            raise ValueError(f"unknown queue policy: {policy}")
        super().__init__(maxsize)
        self.policy = policy

    def put(self, item, block: bool = True, timeout=None):
        if item is None or self.policy == "block":
            # sentinel delivery and reference-parity backpressure
            if item is not None and faultinject.enabled():
                # under block policy the pressure site only counts
                faultinject.fire("queue_pressure")
            return super().put(item, block, timeout)
        pressured = faultinject.enabled() and faultinject.fire("queue_pressure")
        while True:
            try:
                if pressured:
                    raise queue.Full
                return super().put(item, block=False)
            except queue.Full:
                if self.policy == "drop_newest":
                    _metrics.inc("queue_dropped")
                    return
                # drop_oldest: make room, then retry the put
                try:
                    old = super().get(block=False)
                # flowcheck: disable=FC04 -- not an error: a consumer raced us, so room exists and the put retries
                except queue.Empty:
                    pressured = False
                    continue
                if old is None:
                    # never shed the shutdown sentinel: put it back and
                    # drop the incoming item instead (task_done balances
                    # the re-put so unfinished-task accounting holds)
                    super().put(old)
                    self.task_done()
                    _metrics.inc("queue_dropped")
                    return
                self.task_done()
                _metrics.inc("queue_dropped")
                pressured = False
