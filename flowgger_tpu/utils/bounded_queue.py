"""Bounded pipeline queue with an overflow policy.

The reference's ``sync_channel`` blocks producers when the queue is full
(backpressure all the way to the socket).  That stays the default, but a
collector in front of a slow sink sometimes prefers shedding load to
stalling ingest, so the queue grows a policy:

    [input]
    queue_policy = "block"        # reference parity (default)
                 | "drop_newest"  # full queue: discard the incoming item
                 | "drop_oldest"  # full queue: discard the oldest item

Every shed message bumps the ``queue_dropped`` counter plus the
per-cause ``queue_dropped_{policy}`` label, so a graph can tell which
policy (and, on the tenancy fair queue, which tenant) paid.  Once the
pipeline enters its drain phase (``mark_draining``, called at SIGTERM/
EOF before the final flush), sheds additionally count
``queue_shed_during_drain`` — a drain test can then distinguish shed
lines from delivered lines instead of inferring loss from a short
output file.

The SHUTDOWN sentinel (``None``) is exempt: it always uses a blocking
put and is never dropped, so graceful drain survives any policy.

The ``queue_pressure`` fault-injection site makes a put behave as if the
queue were full (deterministically, see ``utils.faultinject``), so the
drop paths are testable without actually wedging a sink.

Multi-tenant pipelines (a configured ``[tenants]`` table) swap this
class for ``tenancy.fairqueue.WeightedFairQueue`` — per-tenant FIFO
lanes, weighted-fair dequeue, noisiest-tenant-first shedding — with the
same queue surface and the same sentinel/drain exemptions.
"""

from __future__ import annotations

import queue
import time
from collections import deque

from . import faultinject
from .metrics import registry as _metrics

POLICIES = ("block", "drop_newest", "drop_oldest")

# one queue_wait_seconds histogram sample per this many dequeued items:
# the sojourn clock pairs ride the queue's own mutex (``_put``/``_get``
# hooks), but the histogram has its own lock — sampling keeps the
# per-record fast path at a deque append instead of a second lock
QUEUE_WAIT_SAMPLE = 16


class PolicyQueue(queue.Queue):
    def __init__(self, maxsize: int = 0, policy: str = "block"):
        if policy not in POLICIES:
            raise ValueError(f"unknown queue policy: {policy}")
        super().__init__(maxsize)
        self.policy = policy
        self.draining = False
        # enqueue-time stamps parallel to the FIFO (SHUTDOWN exempt on
        # both sides, so alignment survives the sentinel): the
        # queue_wait_seconds histogram is sampled at dequeue
        self._wait_ts: deque = deque()
        self._wait_n = 0

    # queue.Queue calls these under its own mutex
    def _put(self, item) -> None:
        super()._put(item)
        if item is not None:
            self._wait_ts.append(time.perf_counter())

    def _get(self):
        item = super()._get()
        if item is not None and self._wait_ts:
            ts = self._wait_ts.popleft()
            self._wait_n += 1
            if self._wait_n % QUEUE_WAIT_SAMPLE == 0:
                _metrics.observe("queue_wait_seconds",
                                 time.perf_counter() - ts)
        return item

    def mark_draining(self) -> None:
        """Pipeline drain entered: subsequent sheds also count
        ``queue_shed_during_drain`` (see module docstring)."""
        self.draining = True

    def fill_fraction(self) -> float:
        """Queue occupancy in [0, 1] — the durability tier's watermark
        signal (durability/manager.py should_spill).  Unbounded queues
        report 0.0: no backpressure means nothing to spill for."""
        return self.qsize() / self.maxsize if self.maxsize > 0 else 0.0

    def _count_drop(self) -> None:
        from ..obs import events as _events

        _metrics.inc("queue_dropped")
        _metrics.inc(f"queue_dropped_{self.policy}")
        if self.draining:
            _metrics.inc("queue_shed_during_drain")
        _events.emit("queue", "queue_drop", detail=self.policy,
                     cost=1, cost_unit="items")

    def put(self, item, block: bool = True, timeout=None):
        if item is None or self.policy == "block":
            # sentinel delivery and reference-parity backpressure
            if item is not None and faultinject.enabled():
                # under block policy the pressure site only counts
                faultinject.fire("queue_pressure")
            return super().put(item, block, timeout)
        pressured = faultinject.enabled() and faultinject.fire("queue_pressure")
        while True:
            try:
                if pressured:
                    raise queue.Full
                return super().put(item, block=False)
            except queue.Full:
                if self.policy == "drop_newest":
                    self._count_drop()
                    return
                # drop_oldest: make room, then retry the put
                try:
                    old = super().get(block=False)
                # flowcheck: disable=FC04 -- not an error: a consumer raced us, so room exists and the put retries
                except queue.Empty:
                    pressured = False
                    continue
                if old is None:
                    # never shed the shutdown sentinel: put it back and
                    # drop the incoming item instead (task_done balances
                    # the re-put so unfinished-task accounting holds)
                    super().put(old)
                    self.task_done()
                    self._count_drop()
                    return
                self.task_done()
                self._count_drop()
                pressured = False
