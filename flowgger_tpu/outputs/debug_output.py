"""Debug output: print each framed message to stdout.

Parity model: /root/reference/src/flowgger/output/debug_output.rs:17-36
(lossy UTF-8, no added newline beyond the merger's framing, flush per
message).
"""

from __future__ import annotations

import sys

from . import Output, SHUTDOWN, ack_item, stream_bytes


class DebugOutput(Output):
    def __init__(self, config=None):
        pass

    def start(self, arx, merger):
        def run():
            while True:
                item = arx.get()
                if item is SHUTDOWN:
                    arx.task_done()
                    return
                data, _ = stream_bytes(item, merger)
                sys.stdout.write(data.decode("utf-8", errors="replace"))
                sys.stdout.flush()
                ack_item(item)
                arx.task_done()

        return self.spawn(run, "debug-output")
