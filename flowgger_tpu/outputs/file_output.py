"""File output with optional buffering and size/time rotation.

Parity model: /root/reference/src/flowgger/output/file_output.rs:50-218.
Config keys: output.file_path (required), file_buffer_size (0 = off),
file_rotation_size (0 = off), file_rotation_time (minutes, 0 = off),
file_rotation_maxfiles (default 50), file_rotation_timeformat
(default ``[year][month][day]T[hour][minute][second]Z``).
"""

from __future__ import annotations

import sys

from . import Output, SHUTDOWN, ack_item, stream_bytes
from ..block import EncodedBlock
from ..utils import faultinject as _faults
from ..utils.metrics import registry as _metrics
from ..config import Config, ConfigError
from ..encoders import validate_time_format_input
from ..utils.rotating_file import BufferedWriter, RotatingFile

FILE_DEFAULT_BUFFER_SIZE = 0
FILE_DEFAULT_TIME_FORMAT = "[year][month][day]T[hour][minute][second]Z"
FILE_DEFAULT_ROTATION_SIZE = 0
FILE_DEFAULT_ROTATION_TIME = 0
FILE_DEFAULT_ROTATION_MAXFILES = 50


class FileOutput(Output):
    def __init__(self, config: Config):
        path = config.lookup("output.file_path")
        if path is None:
            raise ConfigError("output.file_path is missing")
        if not isinstance(path, str):
            raise ConfigError("output.file_path must be a string")
        self.path = path
        self.buffer_size = config.lookup_int(
            "output.file_buffer_size",
            "output.file_buffer_size should be an integer",
            FILE_DEFAULT_BUFFER_SIZE,
        )
        self.rotation_size = config.lookup_int(
            "output.file_rotation_size",
            "output.file_rotation_size should be an integer",
            FILE_DEFAULT_ROTATION_SIZE,
        )
        self.rotation_time = config.lookup_int(
            "output.file_rotation_time",
            "output.file_rotation_time should be an integer",
            FILE_DEFAULT_ROTATION_TIME,
        )
        self.rotation_maxfiles = config.lookup_int(
            "output.file_rotation_maxfiles",
            "output.file_rotation_maxfiles should be an integer",
            FILE_DEFAULT_ROTATION_MAXFILES,
        )
        time_format = config.lookup_str(
            "output.file_rotation_timeformat",
            "output.file_rotation_timeformat should be a string",
            FILE_DEFAULT_TIME_FORMAT,
        )
        self.time_format = validate_time_format_input(
            "file_rotation_timeformat", time_format, FILE_DEFAULT_TIME_FORMAT
        )

    def open_writer(self):
        rotating = RotatingFile(
            self.path, self.rotation_size, self.rotation_time,
            self.rotation_maxfiles, self.time_format,
        )
        if rotating.is_enabled():
            try:
                rotating.open()
                writer = rotating
            except OSError as e:
                print(f"Unable to open rotating file {self.path}: {e}", file=sys.stderr)
                return None
        else:
            try:
                writer = RotatingFile.open_file(self.path)
            except OSError as e:
                print(f"Unable to open file {self.path}: {e}", file=sys.stderr)
                return None
        if self.buffer_size > 0:
            writer = BufferedWriter(writer, self.buffer_size)
        return writer

    def start(self, arx, merger):
        writer = self.open_writer()
        if writer is None:
            raise RuntimeError(f"Cannot open file to {self.path}")

        rotating = self.rotation_size > 0 or self.rotation_time > 0
        # boxes, not closure variables: a supervised restart re-enters
        # run() and must (a) swap in a fresh writer when the old fd went
        # bad, and (b) deliver the retained item whose write failed —
        # retention beats a queue requeue (no drop, no reorder, no
        # blocking put from the sole consumer)
        wbox = [writer]
        carry = [None]

        def run():
            if wbox[0] is None:
                wbox[0] = self.open_writer()
                if wbox[0] is None:
                    # supervisor backoff handles the retry pacing
                    raise RuntimeError(f"Cannot reopen file {self.path}")
            while True:
                if carry[0] is not None:
                    item, from_queue = carry[0], False
                else:
                    item, from_queue = arx.get(), True
                if item is SHUTDOWN:
                    if hasattr(wbox[0], "flush"):
                        wbox[0].flush()
                    arx.task_done()
                    return
                written = 0
                try:
                    if _faults.enabled():
                        _faults.maybe_raise("sink_write", OSError)
                    if isinstance(item, EncodedBlock) and rotating:
                        # preserve the reference's per-message rotation
                        # trigger granularity (rotating_file.rs:346-363)
                        for framed in item.iter_framed():
                            wbox[0].write(framed)
                            written += 1
                        _metrics.inc("output_written", len(item))
                    else:
                        data, count = stream_bytes(item, merger)
                        wbox[0].write(data)
                        _metrics.inc("output_written", count)
                    # durability ack: fires only once the bytes cleared
                    # any BufferedWriter layer — an ack on merely-
                    # buffered data would advance the replay cursor
                    # past bytes a crash can still lose
                    if (getattr(item, "ack_cb", None) is not None
                            and self.buffer_size > 0
                            and hasattr(wbox[0], "flush")):
                        wbox[0].flush()
                    ack_item(item)
                except OSError:
                    _metrics.inc("output_errors")
                    if from_queue:
                        arx.task_done()
                    if (isinstance(item, EncodedBlock) and written
                            and self.buffer_size == 0):
                        # unbuffered writer: a successful write() call
                        # reached the fd, so retain only the unwritten
                        # tail — already-written frames must not
                        # duplicate on redelivery.  With a BufferedWriter
                        # a write() may only have buffered (a flush-time
                        # failure would lose trimmed frames), so the
                        # whole block is retained instead: at-least-once.
                        _metrics.inc("output_written", written)
                        # the durability ack (if any) rides the trimmed
                        # block: it fires only once the TAIL lands too
                        item = EncodedBlock(
                            item.data, item.bounds[written:],
                            None if item.prefix_lens is None
                            else item.prefix_lens[written:],
                            item.suffix_len, ack_cb=item.ack_cb)
                    carry[0] = item
                    # the fd may be what broke: reopen on restart
                    try:
                        if hasattr(wbox[0], "close"):
                            wbox[0].close()
                    except OSError:  # flowcheck: disable=FC04 -- fd already failed; the write error re-raises below
                        pass
                    wbox[0] = None
                    raise
                carry[0] = None
                if from_queue:
                    arx.task_done()

        return self.spawn(run, "file-output")
