"""File output with optional buffering and size/time rotation.

Parity model: /root/reference/src/flowgger/output/file_output.rs:50-218.
Config keys: output.file_path (required), file_buffer_size (0 = off),
file_rotation_size (0 = off), file_rotation_time (minutes, 0 = off),
file_rotation_maxfiles (default 50), file_rotation_timeformat
(default ``[year][month][day]T[hour][minute][second]Z``).
"""

from __future__ import annotations

import sys

from . import Output, SHUTDOWN, spawn_worker, stream_bytes
from ..block import EncodedBlock
from ..utils.metrics import registry as _metrics
from ..config import Config, ConfigError
from ..encoders import validate_time_format_input
from ..utils.rotating_file import BufferedWriter, RotatingFile

FILE_DEFAULT_BUFFER_SIZE = 0
FILE_DEFAULT_TIME_FORMAT = "[year][month][day]T[hour][minute][second]Z"
FILE_DEFAULT_ROTATION_SIZE = 0
FILE_DEFAULT_ROTATION_TIME = 0
FILE_DEFAULT_ROTATION_MAXFILES = 50


class FileOutput(Output):
    def __init__(self, config: Config):
        path = config.lookup("output.file_path")
        if path is None:
            raise ConfigError("output.file_path is missing")
        if not isinstance(path, str):
            raise ConfigError("output.file_path must be a string")
        self.path = path
        self.buffer_size = config.lookup_int(
            "output.file_buffer_size",
            "output.file_buffer_size should be an integer",
            FILE_DEFAULT_BUFFER_SIZE,
        )
        self.rotation_size = config.lookup_int(
            "output.file_rotation_size",
            "output.file_rotation_size should be an integer",
            FILE_DEFAULT_ROTATION_SIZE,
        )
        self.rotation_time = config.lookup_int(
            "output.file_rotation_time",
            "output.file_rotation_time should be an integer",
            FILE_DEFAULT_ROTATION_TIME,
        )
        self.rotation_maxfiles = config.lookup_int(
            "output.file_rotation_maxfiles",
            "output.file_rotation_maxfiles should be an integer",
            FILE_DEFAULT_ROTATION_MAXFILES,
        )
        time_format = config.lookup_str(
            "output.file_rotation_timeformat",
            "output.file_rotation_timeformat should be a string",
            FILE_DEFAULT_TIME_FORMAT,
        )
        self.time_format = validate_time_format_input(
            "file_rotation_timeformat", time_format, FILE_DEFAULT_TIME_FORMAT
        )

    def open_writer(self):
        rotating = RotatingFile(
            self.path, self.rotation_size, self.rotation_time,
            self.rotation_maxfiles, self.time_format,
        )
        if rotating.is_enabled():
            try:
                rotating.open()
                writer = rotating
            except OSError as e:
                print(f"Unable to open rotating file {self.path}: {e}", file=sys.stderr)
                return None
        else:
            try:
                writer = RotatingFile.open_file(self.path)
            except OSError as e:
                print(f"Unable to open file {self.path}: {e}", file=sys.stderr)
                return None
        if self.buffer_size > 0:
            writer = BufferedWriter(writer, self.buffer_size)
        return writer

    def start(self, arx, merger):
        writer = self.open_writer()
        if writer is None:
            raise RuntimeError(f"Cannot open file to {self.path}")

        rotating = self.rotation_size > 0 or self.rotation_time > 0

        def run():
            while True:
                item = arx.get()
                if item is SHUTDOWN:
                    if hasattr(writer, "flush"):
                        writer.flush()
                    arx.task_done()
                    return
                if isinstance(item, EncodedBlock) and rotating:
                    # preserve the reference's per-message rotation
                    # trigger granularity (rotating_file.rs:346-363)
                    for framed in item.iter_framed():
                        writer.write(framed)
                    _metrics.inc("output_written", len(item))
                else:
                    data, count = stream_bytes(item, merger)
                    writer.write(data)
                    _metrics.inc("output_written", count)
                arx.task_done()

        return spawn_worker(run, "file-output")
