"""Kafka producer output with message coalescing.

Parity model: /root/reference/src/flowgger/output/kafka_output.rs:13-212.
Implemented in the outputs milestone; see repo task list.
"""

from __future__ import annotations

from . import Output


class KafkaOutput(Output):  # pragma: no cover - placeholder, full impl pending
    def __init__(self, config):
        raise NotImplementedError("KafkaOutput: implementation lands with the outputs milestone")

    def start(self, arx, merger):
        raise NotImplementedError
