"""Kafka producer output with message coalescing.

Parity model: /root/reference/src/flowgger/output/kafka_output.rs:13-212.
``output.kafka_brokers`` (required list), ``kafka_topic`` (required),
``kafka_acks`` -1/0/1, ``kafka_timeout`` ms, ``kafka_threads``,
``kafka_coalesce`` (buffer N messages then send_all), ``kafka_compression``
none/gzip/snappy (snappy via the from-scratch codec in utils/snappy.py;
requires a broker speaking record batches v2, negotiated automatically).
An unresponsive broker terminates the process (exit 1), matching the
reference's supervisor-restart contract; output framing is ignored with
a warning.  Transport: utils/kafka_wire.py, a from-scratch minimal
protocol client.
"""

from __future__ import annotations

import sys

from . import Output, SHUTDOWN, ack_item
from ..block import EncodedBlock
from ..config import Config, ConfigError
from ..utils.kafka_wire import KafkaError, KafkaProducer
from ..utils.retry import RetryExhausted, RetryPolicy, retry_config_kwargs

KAFKA_DEFAULT_ACKS = 0
KAFKA_DEFAULT_COALESCE = 1
KAFKA_DEFAULT_COMPRESSION = "none"
KAFKA_DEFAULT_THREADS = 1
KAFKA_DEFAULT_TIMEOUT = 60_000
KAFKA_DEFAULT_RETRY_INIT = 250
KAFKA_DEFAULT_RETRY_MAX = 10_000
KAFKA_DEFAULT_RETRY_ATTEMPTS = 3


class KafkaOutput(Output):
    def __init__(self, config: Config):
        self.acks = config.lookup_int(
            "output.kafka_acks", "output.kafka_acks must be a 16-bit integer",
            KAFKA_DEFAULT_ACKS)
        if self.acks not in (-1, 0, 1):
            raise ConfigError("Unsupported value for kafka_acks")
        brokers = config.lookup("output.kafka_brokers")
        if brokers is None:
            raise ConfigError("output.kafka_brokers is required")
        if not isinstance(brokers, list) or not all(isinstance(b, str) for b in brokers):
            raise ConfigError("output.kafka_brokers must be a list of strings")
        self.brokers = brokers
        topic = config.lookup("output.kafka_topic")
        if topic is None or not isinstance(topic, str):
            raise ConfigError("output.kafka_topic must be a string")
        self.topic = topic
        self.timeout_ms = config.lookup_int(
            "output.kafka_timeout", "output.kafka_timeout must be a 64-bit integer",
            KAFKA_DEFAULT_TIMEOUT)
        self.threads = config.lookup_int(
            "output.kafka_threads", "output.kafka_threads must be a 32-bit integer",
            KAFKA_DEFAULT_THREADS)
        self.coalesce = config.lookup_int(
            "output.kafka_coalesce", "output.kafka_coalesce must be a size integer",
            KAFKA_DEFAULT_COALESCE)
        compression = config.lookup_str(
            "output.kafka_compression",
            # sic: the reference's panic message has this typo
            # (kafka_output.rs:169 "output.kafka_compresion must be a string")
            "output.kafka_compresion must be a string",
            KAFKA_DEFAULT_COMPRESSION).lower()
        if compression not in ("none", "gzip", "snappy"):
            raise ConfigError("Unsupported compression method")
        self.compression = compression
        # retry-before-dying: the reference exits the process on the
        # first unresponsive broker; here each connect/send gets
        # output.kafka_retry_attempts tries with jittered exponential
        # backoff first, and only exhaustion keeps the exit contract
        self._retry_kw = retry_config_kwargs(
            config, "output.kafka",
            init_ms=KAFKA_DEFAULT_RETRY_INIT,
            max_ms=KAFKA_DEFAULT_RETRY_MAX,
            max_attempts=KAFKA_DEFAULT_RETRY_ATTEMPTS)
        self.exit_on_failure = True  # tests disable to keep pytest alive

    def _send_retrying(self, policy, producer, batch) -> None:
        """send_all with backoff; raises RetryExhausted when the broker
        stays unresponsive through the whole retry budget."""
        def send():
            producer.send_all(self.topic, batch)

        policy.run(send, retry_on=(KafkaError,),
                   on_error=lambda e: print(
                       f"Kafka send failed, retrying: [{e}]",
                       file=sys.stderr))
        policy.note_success()

    def _worker(self, arx, merger):
        policy = RetryPolicy(metric="sink_reconnects", **self._retry_kw)

        def connect():
            producer = KafkaProducer(self.brokers, self.acks, self.timeout_ms,
                                     self.compression)
            producer.refresh_metadata(self.topic)
            return producer

        try:
            producer = policy.run(
                connect, retry_on=(KafkaError, OSError),
                on_error=lambda e: print(
                    f"Unable to connect to Kafka, retrying: [{e}]",
                    file=sys.stderr))
        except RetryExhausted as e:
            print(f"Unable to connect to Kafka: [{e}]")
            return self._die()
        policy.note_success()
        queue_buf = []
        # durability acks ride the coalescing buffer in parallel: they
        # fire only after the send_all carrying their messages came
        # back clean through the whole retry ladder (RetryPolicy) —
        # Kafka-level acks= semantics are the producer's as configured
        ack_buf = []
        while True:
            item = arx.get()
            if item is SHUTDOWN:
                try:
                    self._send_retrying(policy, producer, queue_buf)
                except RetryExhausted as e:
                    print(f"Kafka not responsive: [{e}]")
                    arx.task_done()
                    return self._die()
                for acked in ack_buf:
                    ack_item(acked)
                arx.task_done()
                return None
            if isinstance(item, EncodedBlock):
                queue_buf.extend(item.iter_unframed())
            else:
                queue_buf.append(item)
            if getattr(item, "ack_cb", None) is not None:
                ack_buf.append(item)
            if len(queue_buf) >= max(1, self.coalesce):
                try:
                    self._send_retrying(policy, producer, queue_buf)
                except RetryExhausted as e:
                    print(f"Kafka not responsive: [{e}]")
                    arx.task_done()
                    return self._die()
                queue_buf = []
                for acked in ack_buf:
                    ack_item(acked)
                ack_buf = []
            arx.task_done()

    def _die(self):
        if self.exit_on_failure:
            import os

            os._exit(1)

    def start(self, arx, merger):
        if merger is not None:
            print("Output framing is ignored with the Kafka output", file=sys.stderr)
        return [self.spawn(lambda: self._worker(arx, merger), "kafka-output")
                for _ in range(self.threads)]
