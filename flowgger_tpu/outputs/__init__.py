"""Outputs (sinks): consumer threads draining the bounded queue.

Parity model: /root/reference/src/flowgger/output/ — trait
``Output { start(arx, merger) }`` (output/mod.rs:21-30): ``start`` spawns
worker thread(s) competing on the shared receiver and returns immediately.
Here the queue is a ``queue.Queue`` (already thread-safe, so no explicit
``Arc<Mutex<...>>`` wrapper is needed); a ``None`` item is the shutdown
sentinel used by tests and graceful stops.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..block import EncodedBlock
from ..mergers import Merger

SHUTDOWN = None


def stream_bytes(item, merger: Optional[Merger]):
    """(wire bytes, message count) for byte-stream sinks.  EncodedBlock
    items are pre-framed by the producer with the pipeline's merger, so
    they are written wholesale; plain items get framed here, matching
    the reference's consumer loop (file_output.rs:203-216)."""
    if isinstance(item, EncodedBlock):
        return item.data, len(item)
    return (merger.frame(item) if merger is not None else item), 1


class Output:
    # set by Pipeline.start_output: sink workers then spawn supervised
    # (crash → restart with backoff, thread_crashes/thread_restarts
    # metrics) instead of dying silently
    supervisor = None

    def start(self, arx, merger: Optional[Merger]):
        raise NotImplementedError

    def spawn(self, target, name: str) -> threading.Thread:
        return spawn_worker(target, name, self.supervisor)


def spawn_worker(target, name: str, supervisor=None) -> threading.Thread:
    if supervisor is not None:
        return supervisor.spawn(target, name)
    t = threading.Thread(target=target, name=name, daemon=True)
    t.start()
    return t


def ack_item(item) -> None:
    """Fire a delivered item's durability ack, if it carries one.

    Sinks call this at their own delivery point — FileOutput after a
    flushed write, TLS after sendall, Kafka after an acknowledged
    send_all — so the WAL replay cursor (durability/manager.py)
    advances only on real sink acknowledgment.  The ``sink_ack_loss``
    fault site suppresses the callback (the ack "never arrives"),
    which is exactly a stuck-replay drill: the record stays unacked,
    ``replay_cursor_lag`` pins, and the stall watchdog journals it.
    A failing callback is contained and counted — an ack bug must
    never take down a sink worker."""
    cb = getattr(item, "ack_cb", None)
    if cb is None:
        return
    from ..utils import faultinject as _faults

    if _faults.enabled() and _faults.fire("sink_ack_loss"):
        return
    from ..utils.metrics import registry as _metrics

    try:
        cb()
    except Exception as e:  # noqa: BLE001 - ack is advisory for the sink
        _metrics.inc("sink_ack_errors")
        import sys

        print(f"sink ack callback failed ({type(e).__name__}: {e})",
              file=sys.stderr)
    else:
        _metrics.inc("sink_acks")


from .debug_output import DebugOutput  # noqa: E402
from .file_output import FileOutput  # noqa: E402
from .tls_output import TlsOutput  # noqa: E402
from .kafka_output import KafkaOutput  # noqa: E402

__all__ = [
    "Output",
    "DebugOutput",
    "FileOutput",
    "TlsOutput",
    "KafkaOutput",
    "spawn_worker",
    "SHUTDOWN",
]
