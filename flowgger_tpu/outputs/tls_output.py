"""TLS output: forward framed messages to a downstream syslog/TLS
cluster with failover and backoff.

Parity model: /root/reference/src/flowgger/output/tls_output.rs:21-361.
Implemented in the outputs milestone; see repo task list.
"""

from __future__ import annotations

from . import Output


class TlsOutput(Output):  # pragma: no cover - placeholder, full impl pending
    def __init__(self, config):
        raise NotImplementedError("TlsOutput: implementation lands with the outputs milestone")

    def start(self, arx, merger):
        raise NotImplementedError
