"""TLS output: forward framed messages to a downstream syslog/TLS
cluster with failover and randomized backoff.

Parity model: /root/reference/src/flowgger/output/tls_output.rs:21-361.

- ``output.connect`` is a list of ``host:port`` endpoints, shuffled at
  startup; workers advance round-robin through the shared list and
  reshuffle each time a cycle completes (tls_output.rs:131-140);
- per-message flush unless ``output.tls_async`` (tls_output.rs:119-122);
- reconnect uses randomized additive backoff: delay grows by
  ``uniform(0, delay)`` up to ``tls_recovery_delay_max`` ms, resetting
  to ``tls_recovery_delay_init`` after ``tls_recovery_probe_time`` ms of
  connection stability (tls_output.rs:163-172);
- client-side TLS config mirrors the input side, plus optional client
  cert/key.
"""

from __future__ import annotations

import random
import socket
import ssl
import sys
import threading

from . import Output, SHUTDOWN, ack_item, stream_bytes
from ..config import Config, ConfigError
from ..utils import faultinject as _faults
from ..utils.metrics import registry as _metrics
from ..utils.retry import RetryPolicy

DEFAULT_RECOVERY_DELAY_INIT = 1
DEFAULT_RECOVERY_DELAY_MAX = 10_000
DEFAULT_RECOVERY_PROBE_TIME = 30_000
DEFAULT_ASYNC = False
DEFAULT_TIMEOUT = 3600
DEFAULT_THREADS = 1

# carry-slot stand-in for a consumed SHUTDOWN sentinel (which is None,
# the slot's empty value): a failed final flush must not lose shutdown
_CARRY_SHUTDOWN = object()


class _Cluster:
    def __init__(self, connect):
        self.connect = list(connect)
        random.shuffle(self.connect)
        self.idx = 0
        self.lock = threading.Lock()

    def next_endpoint(self) -> str:
        with self.lock:
            self.idx += 1
            if self.idx >= len(self.connect):
                random.shuffle(self.connect)
                self.idx = 0
            return self.connect[self.idx]


class TlsOutput(Output):
    def __init__(self, config: Config):
        self.threads = config.lookup_int(
            "output.tls_threads", "output.tls_threads must be a 32-bit integer",
            DEFAULT_THREADS)
        connect = config.lookup("output.connect")
        if connect is None:
            raise ConfigError("output.connect is required")
        if not isinstance(connect, list) or not all(isinstance(x, str) for x in connect):
            raise ConfigError("output.connect must be a list of strings")
        self.cluster = _Cluster(connect)
        cert = config.lookup_str(
            "output.tls_cert", "output.tls_cert must be a path to a .pem file")
        key = config.lookup_str(
            "output.tls_key", "output.tls_key must be a path to a .pem file")
        ciphers = config.lookup_str(
            "output.tls_ciphers", "output.tls_ciphers must be a string with a cipher suite")
        verify_peer = config.lookup_bool(
            "output.tls_verify_peer", "output.tls_verify_peer must be a boolean", False)
        ca_file = config.lookup_str(
            "output.tls_ca_file", "output.tls_ca_file must be a path to a file")
        self.timeout = config.lookup_int(
            "output.timeout", "output.timeout must be an integer", DEFAULT_TIMEOUT)
        self.async_ = config.lookup_bool(
            "output.tls_async", "output.tls_async must be a boolean", DEFAULT_ASYNC)
        self.recovery_delay_init = config.lookup_int(
            "output.tls_recovery_delay_init",
            "output.tls_recovery_delay_init must be an integer",
            DEFAULT_RECOVERY_DELAY_INIT)
        self.recovery_delay_max = config.lookup_int(
            "output.tls_recovery_delay_max",
            "output.tls_recovery_delay_max must be an integer",
            DEFAULT_RECOVERY_DELAY_MAX)
        self.recovery_probe_time = config.lookup_int(
            "output.tls_recovery_probe_time",
            "output.tls_recovery_probe_time must be an integer",
            DEFAULT_RECOVERY_PROBE_TIME)
        if self.recovery_delay_max < self.recovery_delay_init:
            raise ConfigError(
                "output.tls_recovery_delay_max cannot be less than "
                "output.tls_recovery_delay_init")

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        if verify_peer:
            # reference SslConnector::connect(hostname, ...) verifies the
            # peer against system CAs and the hostname (tls_output.rs:323)
            ctx.check_hostname = True
            ctx.verify_mode = ssl.CERT_REQUIRED
            if ca_file is not None:
                try:
                    ctx.load_verify_locations(cafile=ca_file)
                except (OSError, ssl.SSLError):
                    raise ConfigError("Unable to read the trusted CA file")
            else:
                ctx.load_default_certs()
        else:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        if cert is not None:
            try:
                ctx.load_cert_chain(certfile=cert, keyfile=key if key else cert)
            except (OSError, ssl.SSLError):
                raise ConfigError("Unable to read the TLS certificate")
        if ciphers is not None:
            try:
                ctx.set_ciphers(ciphers)
            except ssl.SSLError:
                raise ConfigError("Unsupported cipher suite")
        self.ctx = ctx

    # -- worker ------------------------------------------------------------
    def _handle_connection(self, arx, merger, endpoint: str, carry: list):
        """``carry`` is this worker's one-item retention slot: a message
        whose write failed rides there (never back through the queue —
        no drop, no reorder, no blocking put from the sole consumer) and
        is delivered first on the next connection."""
        host, _, port = endpoint.rpartition(":")
        if not host or not port.isdigit():
            # malformed endpoint: treated as a failed connection so the
            # worker rotates to the next cluster member instead of dying
            raise ConnectionRefusedError(f"Invalid connection string: {endpoint}")
        sock = socket.create_connection((host, int(port)), timeout=self.timeout)
        print(f"Connected to {endpoint}", file=sys.stderr)
        try:
            tls = self.ctx.wrap_socket(sock, server_hostname=host)
        except (ssl.SSLError, OSError):
            sock.close()
            raise ConnectionAbortedError("SSL handshake aborted by the server")
        print(f"Completed SSL handshake with {endpoint}", file=sys.stderr)
        # tls_async buffers like the reference's BufWriter (8KB) instead
        # of flushing per message (tls_output.rs:98,119-122)
        buf = bytearray()
        try:
            while True:
                if carry[0] is not None:
                    item = (SHUTDOWN if carry[0] is _CARRY_SHUTDOWN
                            else carry[0])
                    from_queue = False
                else:
                    item, from_queue = arx.get(), True
                if item is SHUTDOWN:
                    if buf:
                        try:
                            tls.sendall(bytes(buf))
                        except OSError:
                            # shutdown must survive the reconnect: carry
                            # it (the async-buffered bytes are lost with
                            # the connection, as in the reference)
                            carry[0] = _CARRY_SHUTDOWN
                            if from_queue:
                                arx.task_done()
                            raise
                    carry[0] = None
                    if from_queue:
                        arx.task_done()
                    return True
                data, _ = stream_bytes(item, merger)
                try:
                    if _faults.enabled():
                        _faults.maybe_raise("sink_write", BrokenPipeError)
                    if self.async_:
                        buf.extend(data)
                        if getattr(item, "ack_cb", None) is not None:
                            # a durability-acked item forces the async
                            # buffer out now: acking bytes that are
                            # still host-buffered would advance the
                            # replay cursor past a loss window
                            tls.sendall(bytes(buf))
                            buf.clear()
                        elif len(buf) >= 8192:
                            tls.sendall(bytes(buf))
                            buf.clear()
                    else:
                        tls.sendall(data)
                except OSError:
                    # connection died with the message in hand: retain it
                    # for redelivery on the next connection
                    carry[0] = item
                    if from_queue:
                        arx.task_done()
                    raise
                ack_item(item)
                carry[0] = None
                if from_queue:
                    arx.task_done()
        finally:
            try:
                tls.close()
            except OSError:  # flowcheck: disable=FC04 -- fd already dead; close is best-effort
                pass

    def _worker(self, arx, merger):
        # the reference's randomized additive backoff with a stability
        # probe (tls_output.rs:163-172), expressed as the shared policy;
        # every backoff bumps sink_reconnects
        policy = RetryPolicy(
            init_ms=self.recovery_delay_init, max_ms=self.recovery_delay_max,
            mode="additive", probe_ms=self.recovery_probe_time,
            metric="sink_reconnects")
        carry = [None]  # one-item retention slot (see _handle_connection)
        prev_endpoint = None
        while True:
            policy.mark()
            endpoint = self.cluster.next_endpoint()
            if prev_endpoint is not None and endpoint != prev_endpoint:
                # an actual rotation to another cluster member — a
                # same-endpoint reconnect is only counted by
                # sink_reconnects
                _metrics.inc("sink_failovers")
            prev_endpoint = endpoint
            try:
                if self._handle_connection(arx, merger, endpoint, carry):
                    return  # graceful shutdown
            except ConnectionRefusedError:
                print(f"Connection to {endpoint} refused", file=sys.stderr)
            except (ConnectionAbortedError, ConnectionResetError):
                print(f"Connection to {endpoint} aborted by the server",
                      file=sys.stderr)
            except OSError as e:
                print(f"Error while communicating with {endpoint} - {e}",
                      file=sys.stderr)
            policy.backoff()  # unlimited policy: never exhausts
            print("Attempting to reconnect", file=sys.stderr)

    def start(self, arx, merger):
        return [self.spawn(lambda: self._worker(arx, merger), "tls-output")
                for _ in range(self.threads)]
