"""Normalized log record — the contract between decoders and encoders.

Parity model: /root/reference/src/flowgger/record.rs:4-91 (Record,
StructuredData, SDValue enum, RFC5424 Display impl, facility/severity
constants).  This is a fresh design for a columnar/batched pipeline: the
per-record classes here are the *scalar* views; the TPU path works on
`flowgger_tpu.tpu.columnar.ColumnarBatch` and materializes these lazily.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Union

from .utils.rustfmt import display_f64

# record.rs:84-91
FACILITY_MAX = 0xFF >> 3
FACILITY_MISSING = 0xFF
SEVERITY_MAX = (1 << 3) - 1
SEVERITY_MISSING = 0xFF


class SDValue:
    """Typed structured-data value (record.rs:4-11).

    Values are tagged rather than relying on Python's dynamic types because
    the distinction between I64/U64/F64 must survive round-trips (a GELF
    `9001` is U64, `-3` is I64, `1.5` is F64) and `bool` vs int must not
    collapse.
    """

    __slots__ = ("kind", "value")

    STRING = "string"
    BOOL = "bool"
    F64 = "f64"
    I64 = "i64"
    U64 = "u64"
    NULL = "null"

    def __init__(self, kind: str, value):
        self.kind = kind
        self.value = value

    # -- constructors ------------------------------------------------------
    @classmethod
    def string(cls, v: str) -> "SDValue":
        return cls(cls.STRING, v)

    @classmethod
    def bool_(cls, v: bool) -> "SDValue":
        return cls(cls.BOOL, bool(v))

    @classmethod
    def f64(cls, v: float) -> "SDValue":
        return cls(cls.F64, float(v))

    @classmethod
    def i64(cls, v: int) -> "SDValue":
        return cls(cls.I64, int(v))

    @classmethod
    def u64(cls, v: int) -> "SDValue":
        return cls(cls.U64, int(v))

    @classmethod
    def null(cls) -> "SDValue":
        return cls(cls.NULL, None)

    # ----------------------------------------------------------------------
    def display(self) -> str:
        """Value as rendered inside RFC5424 structured data (record.rs:55-62)."""
        if self.kind == self.STRING:
            return self.value
        if self.kind == self.BOOL:
            return "true" if self.value else "false"
        if self.kind == self.F64:
            return display_f64(self.value)
        if self.kind in (self.I64, self.U64):
            return str(self.value)
        return ""

    def __eq__(self, other):
        return (
            isinstance(other, SDValue)
            and self.kind == other.kind
            and self.value == other.value
        )

    def __hash__(self):
        return hash((self.kind, self.value))

    def __repr__(self):
        return f"SDValue({self.kind}, {self.value!r})"


@dataclass
class StructuredData:
    """One RFC5424 `[sd_id k="v" ...]` element (record.rs:23-38)."""

    sd_id: Optional[str] = None
    pairs: List[Tuple[str, SDValue]] = field(default_factory=list)

    def to_string(self) -> str:
        """RFC5424 rendering; strips one leading '_' from pair names and
        renders Null values as a bare name (record.rs:42-68)."""
        out = ["["]
        if self.sd_id is not None:
            out.append(self.sd_id)
        for name, value in self.pairs:
            name = name[1:] if name.startswith("_") else name
            if value.kind == SDValue.NULL:
                out.append(f" {name}")
            else:
                out.append(f' {name}="{value.display()}"')
        out.append("]")
        return "".join(out)

    __str__ = to_string


@dataclass
class Record:
    """Normalized record passed decoder → encoder (record.rs:70-82)."""

    ts: float = 0.0
    hostname: str = ""
    facility: Optional[int] = None
    severity: Optional[int] = None
    appname: Optional[str] = None
    procid: Optional[str] = None
    msgid: Optional[str] = None
    msg: Optional[str] = None
    full_msg: Optional[str] = None
    sd: Optional[List[StructuredData]] = None
