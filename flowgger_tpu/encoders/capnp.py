"""Cap'n Proto encoder.

Parity model: /root/reference/src/flowgger/encoder/capnp_encoder.rs:36-109
over the wire format in flowgger_tpu/capnp_wire.py.  Missing
facility/severity encode as 0xff; only the first StructuredData element is
representable (schema limitation, capnp_encoder.rs:78-80);
``[output.capnp_extra]`` static string pairs land in the ``extra`` list.
"""

from __future__ import annotations

from . import Encoder
from .. import capnp_wire
from ..config import Config, ConfigError
from ..record import Record


class CapnpEncoder(Encoder):
    def __init__(self, config: Config):
        extra_tbl = config.lookup_table(
            "output.capnp_extra", "output.capnp_extra must be a list of key/value pairs"
        )
        self.extra = []
        if extra_tbl is not None:
            for k, v in extra_tbl.items():
                if not isinstance(v, str):
                    raise ConfigError("output.capnp_extra values must be strings")
                self.extra.append((k, v))

    def encode(self, record: Record) -> bytes:
        return capnp_wire.encode_record(record, self.extra)
