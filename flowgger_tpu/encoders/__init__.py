"""Encoders: Record → output bytes.

Parity model: /root/reference/src/flowgger/encoder/ — trait
``Encoder { encode(record: Record) -> Result<Vec<u8>> }``
(encoder/mod.rs:54-56).  Encode errors raise ``EncodeError``; the pipeline
drops the message and keeps going, like the reference.
"""

from __future__ import annotations

from ..config import Config
from ..record import Record
from ..utils.timeparse import format_time_description

# encoder/mod.rs:31
SYSLOG_PREPEND_DEFAULT_TIME_FORMAT = "[year][month][day]T[hour][minute][second]Z"


class EncodeError(Exception):
    pass


class Encoder:
    def encode(self, record: Record) -> bytes:
        raise NotImplementedError


def validate_time_format_input(name: str, time_format: str, default: str) -> str:
    """Warn-and-default for legacy chrono-style ``%`` formats
    (mod.rs:372-393); escaped ``\\%`` passes through as a literal ``%``."""
    import sys

    if time_format.count("%") != time_format.count("\\%"):
        print(
            f"WARNING: Wrong {name} value received: {time_format}.\n"
            'From version "0.3.0" forward the time format needs to be compliant with:\n'
            "https://docs.rs/time/0.3.7/time/format_description/index.html \n"
            f"Will use the default one: {default}. "
            "If you want to use %, you need to escape it (\\\\%)\n",
            file=sys.stderr,
        )
        return default
    return time_format.replace("\\%", "%")


def config_get_prepend_ts(config: Config):
    """output.syslog_prepend_timestamp handling (encoder/mod.rs:58-81)."""
    fmt = config.lookup_str(
        "output.syslog_prepend_timestamp",
        "output.syslog_prepend_timestamp should be a string",
    )
    if fmt is None:
        return None
    return validate_time_format_input(
        "syslog_prepend_timestamp", fmt, SYSLOG_PREPEND_DEFAULT_TIME_FORMAT
    )


def build_prepend_ts(fmt: str) -> str:
    """Render the prepend header for *now* (encoder/mod.rs:83-94)."""
    try:
        return format_time_description(fmt)
    except ValueError:
        raise EncodeError("Failed to format date")


from .gelf import GelfEncoder  # noqa: E402
from .ltsv import LTSVEncoder  # noqa: E402
from .rfc5424 import RFC5424Encoder  # noqa: E402
from .rfc3164 import RFC3164Encoder  # noqa: E402
from .passthrough import PassthroughEncoder  # noqa: E402
from .capnp import CapnpEncoder  # noqa: E402

__all__ = [
    "Encoder",
    "EncodeError",
    "GelfEncoder",
    "LTSVEncoder",
    "RFC5424Encoder",
    "RFC3164Encoder",
    "PassthroughEncoder",
    "CapnpEncoder",
    "config_get_prepend_ts",
    "build_prepend_ts",
    "validate_time_format_input",
    "SYSLOG_PREPEND_DEFAULT_TIME_FORMAT",
]
