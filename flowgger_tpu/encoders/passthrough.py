"""Passthrough encoder: emit record.full_msg verbatim, with the optional
prepend-timestamp header.

Parity model: /root/reference/src/flowgger/encoder/passthrough_encoder.rs:22-46.
"""

from __future__ import annotations

from . import Encoder, EncodeError, build_prepend_ts, config_get_prepend_ts
from ..config import Config
from ..record import Record


class PassthroughEncoder(Encoder):
    def __init__(self, config: Config):
        self.header_time_format = config_get_prepend_ts(config)

    def encode(self, record: Record) -> bytes:
        if record.full_msg is None:
            raise EncodeError("Cannot output empty raw message")
        out = []
        if self.header_time_format is not None:
            out.append(build_prepend_ts(self.header_time_format))
        out.append(record.full_msg)
        return "".join(out).encode("utf-8")
