"""RFC3164 (legacy syslog) encoder.

Parity model: /root/reference/src/flowgger/encoder/rfc3164_encoder.rs:28-97.
``[prepend-ts][<pri>]Mon  d hh:mm:ss hostname appname[procid]: msgid sd msg``
— pri only when both facility and severity are present; timestamp from
the integer part of record.ts; structured data appended even though it is
not part of RFC3164.
"""

from __future__ import annotations

from . import Encoder, EncodeError, build_prepend_ts, config_get_prepend_ts
from ..config import Config
from ..record import Record
from ..utils.timeparse import format_rfc3164_header_ts


class RFC3164Encoder(Encoder):
    def __init__(self, config: Config):
        self.header_time_format = config_get_prepend_ts(config)

    def encode(self, record: Record) -> bytes:
        out = []
        if self.header_time_format is not None:
            out.append(build_prepend_ts(self.header_time_format))
        if record.facility is not None and record.severity is not None:
            npri = ((record.facility << 3) & 0xF8) + (record.severity & 0x7)
            out.append(f"<{npri}>")
        try:
            out.append(format_rfc3164_header_ts(record.ts))
        except (ValueError, OverflowError):
            raise EncodeError("Failed to parse unix timestamp in RFC3164 encoder")
        out.append(record.hostname)
        out.append(" ")
        if record.appname is not None:
            out.append(record.appname)
        if record.procid is not None:
            out.append(f"[{record.procid}]:")
            out.append(" ")
        if record.msgid is not None:
            out.append(record.msgid)
            out.append(" ")
        if record.sd is not None:
            for sd in record.sd:
                out.append(sd.to_string())
            out.append(" ")
        if record.msg is not None:
            out.append(record.msg)
        return "".join(out).encode("utf-8")
