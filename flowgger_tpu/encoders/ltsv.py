"""LTSV encoder.

Parity model: /root/reference/src/flowgger/encoder/ltsv_encoder.rs:65-125.
Field order: SD pairs (leading ``_`` stripped), ``[output.ltsv_extra]``
pairs, then host, time, message?, full_message?, level?, facility?,
appname?, procid?, msgid?.  Keys escape ``\\n``/``\\t`` → space and
``:`` → ``_``; values escape ``\\n``/``\\t`` → space.  Null SD values
render as an empty string; floats use Rust Display form.
"""

from __future__ import annotations

from . import Encoder
from ..config import Config, ConfigError
from ..record import Record, SDValue
from ..utils.rustfmt import display_f64


class _LTSVString:
    def __init__(self):
        self.parts = []

    def insert(self, key: str, value: str):
        if "\n" in key or "\t" in key or ":" in key:
            key = key.replace("\n", " ").replace("\t", " ").replace(":", "_")
        if "\n" in value or "\t" in value:
            value = value.replace("\t", " ").replace("\n", " ")
        self.parts.append(f"{key}:{value}")

    def finalize(self) -> str:
        return "\t".join(self.parts)


def _sd_value_str(value: SDValue) -> str:
    if value.kind == SDValue.NULL:
        return ""
    if value.kind == SDValue.BOOL:
        return "true" if value.value else "false"
    if value.kind == SDValue.F64:
        return display_f64(value.value)
    return str(value.value)


class LTSVEncoder(Encoder):
    def __init__(self, config: Config):
        extra_tbl = config.lookup_table(
            "output.ltsv_extra", "output.ltsv_extra must be a list of key/value pairs"
        )
        self.extra = []
        if extra_tbl is not None:
            for k, v in extra_tbl.items():
                if not isinstance(v, str):
                    raise ConfigError("output.ltsv_extra values must be strings")
                self.extra.append((k, v))

    def encode(self, record: Record) -> bytes:
        res = _LTSVString()
        if record.sd is not None:
            for sd in record.sd:
                for name, value in sd.pairs:
                    name = name[1:] if name.startswith("_") else name
                    res.insert(name, _sd_value_str(value))
        for name, value in self.extra:
            name = name[1:] if name.startswith("_") else name
            res.insert(name, value)
        res.insert("host", record.hostname)
        res.insert("time", display_f64(record.ts))
        if record.msg is not None:
            res.insert("message", record.msg)
        if record.full_msg is not None:
            res.insert("full_message", record.full_msg)
        if record.severity is not None:
            res.insert("level", str(record.severity))
        if record.facility is not None:
            res.insert("facility", str(record.facility))
        if record.appname is not None:
            res.insert("appname", record.appname)
        if record.procid is not None:
            res.insert("procid", record.procid)
        if record.msgid is not None:
            res.insert("msgid", record.msgid)
        return res.finalize().encode("utf-8")
