"""RFC5424 encoder.

Parity model: /root/reference/src/flowgger/encoder/rfc5424_encoder.rs:28-93.
``<pri>1 ts host appname? procid|- msgid|- sd|- msg?`` — pri defaults to
``<13>`` when facility or severity is missing; the timestamp is truncated
to milliseconds and rendered RFC3339 with trimmed subseconds; note the
reference omits appname *and its trailing space* entirely when absent.
"""

from __future__ import annotations

from . import Encoder, EncodeError
from ..record import Record
from ..utils.timeparse import unix_to_rfc3339_ms

DEFAULT_PRIORITY = "<13>"
DEFAULT_SYSLOG_VERSION = "1"


class RFC5424Encoder(Encoder):
    def __init__(self, config=None):
        pass

    def encode(self, record: Record) -> bytes:
        out = []
        if record.facility is not None and record.severity is not None:
            npri = ((record.facility << 3) & 0xF8) + (record.severity & 0x7)
            out.append(f"<{npri}>")
        else:
            out.append(DEFAULT_PRIORITY)
        out.append(DEFAULT_SYSLOG_VERSION)
        out.append(" ")
        try:
            out.append(unix_to_rfc3339_ms(record.ts))
        except (ValueError, OverflowError):
            raise EncodeError("Failed to parse date")
        out.append(" ")
        out.append(record.hostname)
        out.append(" ")
        if record.appname is not None:
            out.append(record.appname)
            out.append(" ")
        out.append(record.procid if record.procid is not None else "-")
        out.append(" ")
        out.append(record.msgid if record.msgid is not None else "-")
        out.append(" ")
        if record.sd is not None:
            for sd in record.sd:
                out.append(sd.to_string())
            out.append(" ")
        else:
            out.append("- ")
        if record.msg is not None:
            out.append(record.msg)
        return "".join(out).encode("utf-8")
