"""GELF 1.1 JSON encoder.

Parity model: /root/reference/src/flowgger/encoder/gelf_encoder.rs:51-116.
Output is a single JSON object with *sorted* keys (serde_json 0.8's
ObjectBuilder is a BTreeMap) and no whitespace.  Fixed keys: version,
host (``unknown`` when empty), short_message (``-`` when absent),
timestamp; optional level/full_message/application_name/process_id; every
SD pair flattens to a top-level field (later SD elements overwrite
earlier on key collision); ``sd_id`` records the (last) element id;
``[output.gelf_extra]`` static pairs overwrite everything.
"""

from __future__ import annotations

from typing import Dict

from . import Encoder, EncodeError
from ..config import Config, ConfigError
from ..record import Record, SDValue
from ..utils.rustfmt import json_f64

# C-accelerated escape: quotes+escapes exactly like serde_json (",\\,
# \b \f \n \r \t short forms, \u00xx for other controls, non-ASCII raw)
from json.encoder import encode_basestring as _quote


def _json_value(v) -> str:
    if isinstance(v, str):
        return _quote(v)
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        return json_f64(v)
    if isinstance(v, int):
        return str(v)
    raise EncodeError("Unable to serialize to JSON")


def serialize_sorted_json(obj: Dict[str, object]) -> bytes:
    """serde_json-compatible compact serialization with BTreeMap key order."""
    items = ",".join(
        f"{_quote(k)}:{_json_value(v)}" for k, v in sorted(obj.items())
    )
    return ("{" + items + "}").encode("utf-8")


class GelfEncoder(Encoder):
    def __init__(self, config: Config):
        extra_tbl = config.lookup_table(
            "output.gelf_extra", "output.gelf_extra must be a list of key/value pairs"
        )
        self.extra = []
        if extra_tbl is not None:
            for k, v in extra_tbl.items():
                if not isinstance(v, str):
                    raise ConfigError("output.gelf_extra values must be strings")
                self.extra.append((k, v))

    def encode(self, record: Record) -> bytes:
        obj: Dict[str, object] = {
            "version": "1.1",
            "host": record.hostname if record.hostname else "unknown",
            "short_message": record.msg if record.msg is not None else "-",
            "timestamp": record.ts,
        }
        if record.severity is not None:
            obj["level"] = int(record.severity)
        if record.full_msg is not None:
            obj["full_message"] = record.full_msg
        if record.appname is not None:
            obj["application_name"] = record.appname
        if record.procid is not None:
            obj["process_id"] = record.procid
        if record.sd is not None:
            for sd in record.sd:
                if sd.sd_id is not None:
                    obj["sd_id"] = sd.sd_id
                for name, value in sd.pairs:
                    if value.kind == SDValue.F64:
                        obj[name] = float(value.value)
                    elif value.kind == SDValue.BOOL:
                        obj[name] = bool(value.value)
                    elif value.kind == SDValue.NULL:
                        obj[name] = None
                    elif value.kind == SDValue.STRING:
                        obj[name] = str(value.value)
                    else:
                        obj[name] = int(value.value)
        for name, value in self.extra:
            obj[name] = value
        return serialize_sorted_json(obj)
