"""Device-side LTSV→GELF encode: final framed bytes assembled on device
for untyped LTSV rows, compacted and fetched output-sized
(device_common machinery — same contract as device_gelf/device_rfc3164).

Layout mirrors the host tier (encode_ltsv_gelf_block.py) byte-for-byte::

    {"_<key>":"V"..., "full_message":L, "host":H|unknown, ["level":N,]
     "short_message":"M"|"-", "timestamp":T, "version":"1.1"}

Pair selection rides the decode kernel's part/special channels over the
small static part axis: a part is a pair iff its index is none of the
(last-occurrence) special positions, and rows with REPEATED special
names fall back — detected elementwise with the same ``name:``-pattern
planes the decoder uses — so last-occurrence equals name-match on every
row the tier accepts, exactly like the host tier's repeated-special
fallback (encode_ltsv_gelf_block.py special_name handling).

Device tier restrictions (everything else splices through the host
span tier / scalar oracle): rfc3339 or unsigned unix-literal
timestamps (the kernel's split-integer parse covers <= 16 digits
within 2**53 exactly; signed or longer stamps need per-value host
parses), ≤6 pairs, 8-byte sort prefixes with the ambiguity/duplicate
fallback of the rfc5424 device sorter, no typed ``ltsv_schema`` (gated
at the route), ASCII rows within the JSON-escape budget.
"""


from __future__ import annotations

# byte-identity contract (flowcheck FC03): the scalar counterpart
# this route must stay byte-identical to, and the differential
# test that enforces it
SCALAR_ORACLE = "flowgger_tpu.encoders.gelf:GelfEncoder"
DIFF_TEST = "tests/test_device_ltsv.py::test_device_ltsv_matches_scalar_and_engages"

from functools import partial

import jax
import jax.numpy as jnp

from .device_common import (
    E_CAP,
    TS_W,
    _out_width,
    assemble_rows,
    escape_stage,
    fetch_encode_driver,
    sort_pairs_by_key8,
)
from .encode_ltsv_gelf_block import (
    _C_DASH,
    _C_FULL,
    _C_HOST,
    _C_LEVEL,
    _C_P0,
    _C_P1,
    _C_P2,
    _C_SEVD,
    _C_SHORT,
    _C_SHORT_LVL,
    _C_TAIL,
    _C_TS,
    _C_UNKNOWN,
)
from .ltsv import _match_at
from .rfc5424 import _cumsum, best_scan_impl

_I32 = jnp.int32

FALLBACK_FRAC = 0.05
DECLINE_LIMIT = 3
COOLDOWN = 16
MAX_DEV_PAIRS = 6
# escalation width when the 6-pair tier declines a batch (encode-side
# analog of the decode rescue): Batcher-16 sort network, 16-pair
# segment table; parts beyond the decode's P=24 axis still fall back
WIDE_DEV_PAIRS = 16

_PARTS = {
    "open": b"{",
    "p0": _C_P0,
    "p1": _C_P1,
    "p2": _C_P2,
    "full": _C_FULL,
    "host": _C_HOST,
    "level": _C_LEVEL,
    "short_l": _C_SHORT_LVL,
    "short": _C_SHORT,
    "ts": _C_TS,
    "tail": _C_TAIL,
    "unknown": _C_UNKNOWN,
    "dash": _C_DASH,
    "sevd": _C_SEVD,
}


def _bank(suffix: bytes, extras=()):
    """Constant bank; extras fold in via the host tier's
    gelf_extra_consts_ltsv so the two tiers can never diverge."""
    parts = dict(_PARTS)
    parts["hl"] = b""
    parts["l2a"] = b""
    parts["l2b"] = b""
    if extras:
        from .encode_ltsv_gelf_block import gelf_extra_consts_ltsv

        econsts = gelf_extra_consts_ltsv(list(extras))
        assert econsts is not None  # route_ok pre-checked
        (parts["open"], parts["full"], parts["host"], parts["hl"],
         parts["l2a"], parts["l2b"], parts["ts"],
         parts["tail"]) = econsts
    from .device_common import build_bank

    bank, offs = build_bank(parts, suffix)
    return bank, offs, parts


def elide_spec(suffix: bytes, extras=()):
    """(head, ts-label, tail) constants the elided kernel skips and the
    host splice restores — single source shared with the fused route."""
    _, _, parts = _bank(suffix, extras)
    return (parts["open"], parts["ts"], parts["tail"] + suffix)


@partial(jax.jit, static_argnames=("suffix", "impl", "assemble",
                                   "extras", "max_pairs", "elide"))
def _encode_kernel(batch, lens, dec, ts_text, ts_len, *, suffix: bytes,
                   impl: str, assemble: bool = True, extras=(),
                   max_pairs: int = MAX_DEV_PAIRS, elide: bool = False):
    N, L = batch.shape
    bank, off, parts = _bank(suffix, extras)
    OW = _out_width(L, L + E_CAP + len(bank) + TS_W)
    iota = jax.lax.broadcasted_iota(_I32, (N, L), 1)
    bb = batch.astype(_I32)

    es = escape_stage(batch, lens, iota,
                      lambda x: _cumsum(x, impl), assemble)
    dmap = es["dmap"]
    lens32 = lens.astype(_I32)
    valid = iota < lens32[:, None]
    row_e = lens32 + es["ne_total"]

    # ---- repeated special names (elementwise planes) --------------------
    prev_tab = jnp.pad((batch == 9) & valid, ((0, 0), (1, 0)))[:, :L]
    pstart = valid & ((iota == 0) | prev_tab)
    rep_special = jnp.zeros((N,), dtype=bool)
    for word in (b"time:", b"host:", b"message:", b"level:"):
        m = _match_at(batch, word, valid) & pstart
        rep_special |= jnp.sum(m.astype(_I32), axis=1) > 1

    # ---- pair selection over the static part axis -----------------------
    n_parts = dec["n_parts"].astype(_I32)
    P = dec["part_start"].shape[1]
    # *_pos channels are BYTE positions of the (last) special key start
    # (-1 when absent); a part is special iff its start equals one
    specials = [dec[k].astype(_I32) for k in ("time_pos", "host_pos",
                                              "msg_pos", "level_pos")]
    pair_ord_cols = []
    run = jnp.zeros((N,), dtype=_I32)
    is_pair_cols = []
    colonless = jnp.zeros((N,), dtype=bool)
    for j in range(P):
        in_row = j < n_parts
        ps_j = dec["part_start"][:, j].astype(_I32)
        is_spec = jnp.zeros((N,), dtype=bool)
        for sp in specials:
            is_spec |= (sp >= 0) & (ps_j == sp)
        isp = in_row & ~is_spec
        colonless |= in_row & (dec["colon_pos"][:, j].astype(_I32) < 0)
        run = run + isp.astype(_I32)
        is_pair_cols.append(isp)
        pair_ord_cols.append(run)
    pair_count = run

    # per-pair channel select (static P x MAX_DEV_PAIRS where-chains)
    def sel(chan_key, plus=0):
        outs = []
        ch = dec[chan_key].astype(_I32)
        for p in range(max_pairs):
            acc = jnp.zeros((N,), dtype=_I32)
            for j in range(P):
                acc = jnp.where(is_pair_cols[j]
                                & (pair_ord_cols[j] == p + 1),
                                ch[:, j] + plus, acc)
            outs.append(acc)
        return outs

    ns_r = sel("part_start")
    ne_r = sel("colon_pos")            # name end = ':' position
    vs_r = sel("colon_pos", plus=1)
    ve_r = sel("part_end")

    # ---- 8-byte sort keys + shared network ------------------------------
    cols = {"_pair_count": pair_count,
            "ns_raw": list(ns_r), "ne_raw": list(ne_r),
            "ns": [dmap(x) for x in ns_r],
            "ne": [dmap(x) for x in ne_r],
            "vs": [dmap(x) for x in vs_r],
            "ve": [dmap(x) for x in ve_r]}
    ambig = sort_pairs_by_key8(bb, iota, cols, max_pairs)

    # ---- fixed-field spans ----------------------------------------------
    host_s = dmap(dec["host_start"])
    host_e = dmap(dec["host_end"])
    msg_s = dmap(dec["msg_start"])
    msg_e = dmap(dec["msg_end"])
    has_msg = dec["msg_pos"].astype(_I32) >= 0
    level = dec["level_val"].astype(_I32)
    has_level = level >= 0

    # ---- segment table (mirrors the host tier's 1 + 5p + 13 layout) -----
    EW = L + E_CAP
    cbase = EW
    tbase = EW + len(bank)
    zero = jnp.zeros((N,), dtype=_I32)
    # elide=True: the row-constant head/ts-label/tail segments stay off
    # the device row; the host splice restores them post-fetch
    # (device_common.splice_elided_rows)
    segs = [] if elide else [(zero + (cbase + off["open"]),
                              zero + len(parts["open"]))]
    for p in range(max_pairs):
        pv = p < pair_count
        segs.append((zero + (cbase + off["p0"]),
                     jnp.where(pv, 2, 0)))
        segs.append((cols["ns"][p],
                     jnp.where(pv, cols["ne"][p] - cols["ns"][p], 0)))
        segs.append((zero + (cbase + off["p1"]),
                     jnp.where(pv, 3, 0)))
        segs.append((cols["vs"][p],
                     jnp.where(pv, cols["ve"][p] - cols["vs"][p], 0)))
        segs.append((zero + (cbase + off["p2"]),
                     jnp.where(pv, 2, 0)))
    host_empty = host_e <= host_s
    qsrc = cbase + off["p1"] + 2   # a '"' byte inside the '":"' const
    segs += [
        (zero + (cbase + off["full"]), zero + len(parts["full"])),
        (zero, row_e),
        (zero + (cbase + off["host"]), zero + len(parts["host"])),
        (jnp.where(host_empty, cbase + off["unknown"], host_s),
         jnp.where(host_empty, len(_C_UNKNOWN), host_e - host_s)),
        (zero + (cbase + off["hl"]), zero + len(parts["hl"])),
        (zero + (cbase + off["level"]),
         jnp.where(has_level, len(_C_LEVEL), 0)),
        (cbase + off["sevd"] + jnp.maximum(level, 0),
         jnp.where(has_level, 1, 0)),
        # extras between level and short: after-number when a level is
        # present, string-close otherwise (same pairing as short below)
        (jnp.where(has_level, cbase + off["l2a"], cbase + off["l2b"]),
         jnp.where(has_level, len(parts["l2a"]), len(parts["l2b"]))),
        (jnp.where(has_level, cbase + off["short_l"],
                   cbase + off["short"]),
         jnp.where(has_level, len(_C_SHORT_LVL), len(_C_SHORT))),
        (jnp.where(has_msg, qsrc, cbase + off["dash"]),
         jnp.where(has_msg, 1, len(_C_DASH))),
        (msg_s, jnp.where(has_msg, msg_e - msg_s, 0)),
        (zero + qsrc, jnp.where(has_msg, 1, 0)),
    ]
    if not elide:
        segs.append((zero + (cbase + off["ts"]),
                     zero + len(parts["ts"])))
    segs.append((zero + tbase, ts_len.astype(_I32)))
    if not elide:
        segs.append((zero + (cbase + off["tail"]),
                     zero + len(parts["tail"]) + len(suffix)))

    out_len = segs[0][1]
    for _, ln in segs[1:]:
        out_len = out_len + ln

    # timestamps: rfc3339 rides the computed-channel path; unix-literal
    # floats ride the split-integer parse when unsigned and within f64's
    # exact-integer range (<= 16 digits, value < 2**53 — the host
    # combine is then the correctly rounded strtod value); anything
    # else (signed, 17+ digits) falls back to the host tier
    kind = dec["ts_kind"].astype(_I32)
    meta = dec["ts_meta"].astype(_I32)
    ts_hi = dec["ts_hi"].astype(_I32)
    ts_lo = dec["ts_lo"].astype(_I32)
    ndig = (meta >> 8) & 255
    signed = ((meta >> 16) & 1) == 1
    f16_ok = (ts_hi < 9007199) | ((ts_hi == 9007199)
                                  & (ts_lo <= 254740992))
    float_dev = ((kind == 1) & ~signed
                 & ((ndig <= 15) | ((ndig == 16) & f16_ok)))
    tier = (dec["ok"].astype(bool)
            & ~dec["has_high"].astype(bool)
            & ~jnp.any(es["bad_ctl"], axis=1)
            & (es["ne_total"] <= E_CAP)
            & ((kind == 0) | float_dev)
            & (dec["host_pos"].astype(_I32) >= 0)
            & ~colonless
            & ~rep_special
            & (pair_count <= max_pairs)
            & ~ambig
            & (out_len <= OW))
    if not assemble:
        return tier
    acc, out_len2 = assemble_rows(segs, es["esc_row"], bank, ts_text,
                                  N, OW)
    return acc, out_len2, tier


def route_ok(encoder, merger, decoder=None) -> bool:
    """GELF output over line/nul/syslen framing, untyped decode only
    (``ltsv_schema`` rows carry per-value canonicality screens that are
    host work); gelf_extra rides as constant segments when this
    layout's keys place statically (gelf_extra_consts_ltsv)."""
    from .device_common import gelf_route_ok
    from .encode_ltsv_gelf_block import gelf_extra_consts_ltsv

    if decoder is not None and getattr(decoder, "schema", None):
        return False
    return gelf_route_ok(
        encoder, merger,
        lambda e: gelf_extra_consts_ltsv(e) is not None)


TS_KEYS = ("days", "sod", "off", "nanos", "ts_kind",
           "ts_hi", "ts_lo", "ts_meta")


def ts_vals_ltsv(small, okh):
    """rfc3339 rows combine days/sod/off/nanos; float-span rows
    combine the kernel's exact split-integer parse (vectorized —
    no per-row Python).  Shared by the split and fused ltsv tiers."""
    import numpy as np

    from .materialize import compute_ts

    kind = small["ts_kind"]
    rfc = okh & (kind == 0)
    masked = {k: np.where(rfc, small[k], 0)
              for k in ("days", "sod", "off", "nanos")}
    vals = compute_ts(masked)
    fv = ((small["ts_hi"].astype(np.float64) * 1e9
           + small["ts_lo"].astype(np.float64))
          / np.power(10.0, (small["ts_meta"] & 255).astype(np.int64)))
    return np.where(okh & (kind == 1), fv, vals)


def fetch_encode(handle, packed, encoder, merger, route_state=None,
                 decoder=None):
    """Device ltsv→GELF encode for a submitted ltsv decode handle;
    returns (BlockResult | None, fetch_seconds)."""
    from .block_common import merger_suffix
    from .materialize_ltsv import _scalar_ltsv

    out, batch_dev, lens_dev = handle
    suffix, syslen = merger_suffix(merger)
    impl = best_scan_impl()
    extras = tuple((k, v) for k, v in getattr(encoder, "extra", ()))
    # constant elision, extended from the rfc5424→GELF leg: head /
    # ts-label / tail never cross PCIe, the splice restores them
    espec = elide_spec(suffix, extras)

    def kernel(ts_text, ts_len, assemble):
        return _encode_kernel(batch_dev, lens_dev, dict(out), ts_text,
                              ts_len, suffix=suffix, impl=impl,
                              assemble=assemble, extras=extras,
                              elide=True)

    # zero-JIT boot: consult the AOT artifact store before compiling
    from .aot import encode_wrap

    kernel = encode_wrap("device_ltsv", kernel, batch_dev, lens_dev,
                         dict(out), suffix, impl, extras)

    def wide():
        """16-pair escalation kernel (lazy: compiled only when a batch
        declines at the 6-pair width)."""
        def kernel_w(ts_text, ts_len, assemble):
            return _encode_kernel(batch_dev, lens_dev, dict(out), ts_text,
                                  ts_len, suffix=suffix, impl=impl,
                                  assemble=assemble, extras=extras,
                                  max_pairs=WIDE_DEV_PAIRS, elide=True)
        return out, kernel_w

    def scalar_fn(line):
        return _scalar_ltsv(decoder, line)

    return fetch_encode_driver(
        kernel, out, batch_dev, lens_dev, packed, encoder, merger,
        route_state, suffix, syslen, scalar_fn=scalar_fn,
        fallback_frac=FALLBACK_FRAC, decline_limit=DECLINE_LIMIT,
        cooldown=COOLDOWN, ts_keys=TS_KEYS,
        ts_vals_fn=ts_vals_ltsv, wide=wide, elide=espec)
