"""Columnar RFC5424→RFC5424 re-encoding: span tables → one framed
output buffer per batch (rfc5424_encoder.rs:28-93 semantics).

For kernel-ok ASCII rows without escaped SD values, every output piece
is either a raw chunk span (host/app/proc/msgid, SD ids/names/values —
the reference re-emits decoded values verbatim, record.rs:55-62), a
constant, PRI digits, or a deduplicated millisecond-truncated RFC3339
timestamp; the whole batch gathers in one ``concat_segments`` call.
Multi-block structured data nests pairs inside their block's brackets
via ``pair_sd`` attribution.  Rows outside the tier take the scalar
oracle through block_common.finish_block.
"""


from __future__ import annotations

# byte-identity contract (flowcheck FC03): the scalar counterpart
# this route must stay byte-identical to, and the differential
# test that enforces it
SCALAR_ORACLE = "flowgger_tpu.encoders.rfc5424:RFC5424Encoder"
DIFF_TEST = "tests/test_encode_gelf_block.py::test_rfc5424_block_route_matches_scalar"

from typing import Dict, Optional

import numpy as np

from ..mergers import Merger
from ..utils.timeparse import unix_to_rfc3339_ms
from .assemble import (
    build_source,
    concat_segments,
    decimal_segments,
    exclusive_cumsum,
)
from .block_common import (
    BlockResult,
    apply_syslen_prefix,
    finish_block,
    merger_suffix,
    syslen_prefix_lens_from_framed,
    ts_scratch,
)


def _native_rows(chunk_bytes, starts64, out, n, ridx, suffix, syslen):
    """Assemble tier rows through the native fg_r5 row writer; None when
    the library lacks the symbols."""
    from .. import native

    if not native.r5_rows_available():
        return None
    R = ridx.size
    scratch, ts_off, ts_len = ts_scratch(out, n, ridx,
                                         unix_to_rfc3339_ms)
    meta = np.empty((R, 16), dtype=np.int32)
    meta[:, 0] = starts64[ridx]
    fac = np.asarray(out["facility"])[:n][ridx].astype(np.int64)
    sev = np.asarray(out["severity"])[:n][ridx].astype(np.int64)
    meta[:, 1] = (fac << 3) + sev
    for k, key in enumerate(("host_start", "host_end", "app_start",
                             "app_end", "proc_start", "proc_end",
                             "msgid_start", "msgid_end",
                             "msg_trim_start", "trim_end")):
        meta[:, 2 + k] = np.asarray(out[key])[:n][ridx]
    sdc = np.asarray(out["sd_count"])[:n][ridx]
    meta[:, 12] = sdc
    meta[:, 13] = np.asarray(out["pair_count"])[:n][ridx]
    meta[:, 14] = ts_off
    meta[:, 15] = ts_len
    return native.r5_rows_native(
        chunk_bytes, meta,
        np.asarray(out["sid_start"])[:n][ridx],
        np.asarray(out["sid_end"])[:n][ridx],
        np.asarray(out["name_start"])[:n][ridx],
        np.asarray(out["name_end"])[:n][ridx],
        np.asarray(out["val_start"])[:n][ridx],
        np.asarray(out["val_end"])[:n][ridx],
        np.asarray(out["pair_sd"])[:n][ridx],
        scratch, suffix, syslen)


def encode_rfc5424_rfc5424_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
) -> Optional[BlockResult]:
    spec = merger_suffix(merger)
    if spec is None:
        return None
    suffix, syslen = spec

    n = int(n_real)
    starts64 = np.asarray(starts[:n], dtype=np.int64)
    lens64 = np.asarray(orig_lens[:n], dtype=np.int64)
    ok = np.asarray(out["ok"][:n], dtype=bool)
    has_high = np.asarray(out["has_high"][:n], dtype=bool)
    val_has_esc = np.asarray(out["val_has_esc"][:n], dtype=bool)
    cand = ok & (lens64 <= max_len) & ~has_high
    if val_has_esc.shape[1]:
        cand &= ~val_has_esc.any(axis=1)

    ridx = np.flatnonzero(cand)
    R = ridx.size
    final_buf = b""
    row_off = np.zeros(1, dtype=np.int64)
    prefix_lens_tier: Optional[np.ndarray] = None

    if R:
        res = _native_rows(chunk_bytes, starts64, out, n, ridx, suffix,
                           syslen)
        if res is not None:
            buf, row_off = res
            tier_lens = np.diff(row_off)
            if syslen:
                prefix_lens_tier = syslen_prefix_lens_from_framed(tier_lens)
            final_buf = buf.tobytes()
            return finish_block(chunk_bytes, starts64, lens64, n, cand,
                                ridx, final_buf, row_off,
                                prefix_lens_tier, suffix, syslen, merger,
                                encoder)

    if R:
        chunk_arr = np.frombuffer(chunk_bytes, dtype=np.uint8)
        st = starts64[ridx]

        def span(skey, ekey):
            a = st + np.asarray(out[skey])[:n][ridx]
            return a, st + np.asarray(out[ekey])[:n][ridx] - a

        host_s, host_l = span("host_start", "host_end")
        app_s, app_l = span("app_start", "app_end")
        proc_s, proc_l = span("proc_start", "proc_end")
        msgid_s, msgid_l = span("msgid_start", "msgid_end")
        msg_s = st + np.asarray(out["msg_trim_start"])[:n][ridx]
        msg_l = st + np.asarray(out["trim_end"])[:n][ridx] - msg_s

        fac = np.asarray(out["facility"])[:n][ridx].astype(np.int64)
        sev = np.asarray(out["severity"])[:n][ridx].astype(np.int64)
        pri = (fac << 3) + sev
        sdc = np.asarray(out["sd_count"])[:n][ridx].astype(np.int64)
        pc = np.asarray(out["pair_count"])[:n][ridx].astype(np.int64)
        nsd = sdc > 0

        scratch, ts_off, ts_len = ts_scratch(out, n, ridx,
                                             unix_to_rfc3339_ms)
        consts, offs = build_source(
            b"<", b">1 ", b" ", b'="', b'"', b"[", b"]", b"-",
            b"0123456789 ", suffix, scratch)
        (o_lt, o_gt1, o_sp, o_eqq, o_q, o_lb, o_rb, o_dash,
         o_dec, o_sfx, o_ts) = offs
        cbase = int(chunk_arr.size)
        src = np.concatenate([chunk_arr, consts])

        # segment plan per row:
        #   head (15): '<' d d d '>1 ' ts ' ' host ' ' app ' ' proc ' '
        #              msgid ' '
        #   sd: per block '[' sid ... ']' (3 + 5*pairs segs); dash rows 1
        #   tail (3): ' ' msg framing-suffix
        HEAD = 15
        sd_segs = np.where(nsd, 3 * sdc + 5 * pc, 1)
        segc = HEAD + sd_segs + 3
        rstart = exclusive_cumsum(segc)[:-1]
        S = int(segc.sum())
        seg_src = np.zeros(S, dtype=np.int64)
        seg_len = np.zeros(S, dtype=np.int64)

        hd = rstart[:, None] + np.arange(HEAD, dtype=np.int64)[None, :]
        hsrc = np.empty((R, HEAD), dtype=np.int64)
        hlen = np.empty((R, HEAD), dtype=np.int64)
        dsrc, dlen = decimal_segments(pri, cbase + o_dec, width=3)
        cols = (
            (cbase + o_lt, 1),
            (dsrc[0::3], dlen[0::3]),
            (dsrc[1::3], dlen[1::3]),
            (dsrc[2::3], dlen[2::3]),
            (cbase + o_gt1, 3),
            (cbase + o_ts + ts_off, ts_len),
            (cbase + o_sp, 1),
            (host_s, host_l),
            (cbase + o_sp, 1),
            (app_s, app_l),
            (cbase + o_sp, 1),
            (proc_s, proc_l),
            (cbase + o_sp, 1),
            (msgid_s, msgid_l),
            (cbase + o_sp, 1),
        )
        for k, (s, ln) in enumerate(cols):
            hsrc[:, k] = s
            hlen[:, k] = ln
        seg_src[hd] = hsrc
        seg_len[hd] = hlen

        # dash rows
        dmask = ~nsd
        if dmask.any():
            dpos = rstart[dmask] + HEAD
            seg_src[dpos] = cbase + o_dash
            seg_len[dpos] = 1

        # blocks + pairs
        max_sd = np.asarray(out["sid_start"]).shape[1]
        P = np.asarray(out["name_start"]).shape[1]
        if nsd.any():
            pair_sd = np.asarray(out["pair_sd"])[:n][ridx]       # [R, P]
            jmask = np.arange(P)[None, :] < pc[:, None]
            # pairs with pair_sd < k, per row/block -> block seg offsets
            pb_rb = ((pair_sd[:, None, :] < np.arange(max_sd)[None, :, None])
                     & jmask[:, None, :]).sum(axis=2)            # [R, max_sd]
            p_in = ((pair_sd[:, None, :] == np.arange(max_sd)[None, :, None])
                    & jmask[:, None, :]).sum(axis=2)
            kmask = np.arange(max_sd)[None, :] < sdc[:, None]
            bstart = (rstart[:, None] + HEAD + 3 * np.arange(max_sd)[None, :]
                      + 5 * pb_rb)                               # [R, max_sd]
            sid_s = st[:, None] + np.asarray(out["sid_start"])[:n][ridx]
            sid_e = st[:, None] + np.asarray(out["sid_end"])[:n][ridx]
            km = kmask & nsd[:, None]
            seg_src[bstart[km]] = cbase + o_lb
            seg_len[bstart[km]] = 1
            seg_src[bstart[km] + 1] = sid_s[km]
            seg_len[bstart[km] + 1] = (sid_e - sid_s)[km]
            rb_pos = bstart + 2 + 5 * p_in
            seg_src[rb_pos[km]] = cbase + o_rb
            seg_len[rb_pos[km]] = 1

            # pair segments: ' ' name '="' value '"'; within-block
            # ordinal = j - pairs_before_block(row, block_of_j)
            rows2 = np.repeat(np.arange(R), pc)
            jop = np.arange(int(pc.sum())) - np.repeat(
                exclusive_cumsum(pc)[:-1], pc)
            b_of = pair_sd[rows2, jop]
            w_of = jop - pb_rb[rows2, b_of]
            p0 = bstart[rows2, b_of] + 2 + 5 * w_of
            ns = st[rows2] + np.asarray(out["name_start"])[:n][ridx][rows2, jop]
            ne = st[rows2] + np.asarray(out["name_end"])[:n][ridx][rows2, jop]
            vs = st[rows2] + np.asarray(out["val_start"])[:n][ridx][rows2, jop]
            ve = st[rows2] + np.asarray(out["val_end"])[:n][ridx][rows2, jop]
            seg_src[p0] = cbase + o_sp
            seg_len[p0] = 1
            seg_src[p0 + 1] = ns
            seg_len[p0 + 1] = ne - ns
            seg_src[p0 + 2] = cbase + o_eqq
            seg_len[p0 + 2] = 2
            seg_src[p0 + 3] = vs
            seg_len[p0 + 3] = ve - vs
            seg_src[p0 + 4] = cbase + o_q
            seg_len[p0 + 4] = 1

        # tail: ' ' + msg + framing suffix
        t0 = rstart + HEAD + sd_segs
        seg_src[t0] = cbase + o_sp
        seg_len[t0] = 1
        seg_src[t0 + 1] = msg_s
        seg_len[t0 + 1] = msg_l
        seg_src[t0 + 2] = cbase + o_sfx
        seg_len[t0 + 2] = len(suffix)

        dst0 = exclusive_cumsum(seg_len)
        body = concat_segments(src, seg_src, seg_len, dst0)
        row_off = np.concatenate([dst0[rstart], dst0[-1:]])
        tier_lens = np.diff(row_off)
        if syslen:
            final_buf, row_off, prefix_lens_tier = apply_syslen_prefix(
                body, row_off, tier_lens)
        else:
            final_buf = body.tobytes()

    return finish_block(chunk_bytes, starts64, lens64, n, cand, ridx,
                        final_buf, row_off, prefix_lens_tier, suffix,
                        syslen, merger, encoder)



def encode_rfc3164_rfc5424_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
) -> Optional[BlockResult]:
    """rfc3164→RFC5424 relay upgrade (rfc5424_encoder.rs:28-93 over the
    legacy Record shape): PRI digits when the line carried one (else
    the encoder's <13> default), re-formatted ms-truncated RFC3339
    stamp, host + message tail spans, and the constant "- - -"
    proc/msgid/sd slots (appname is absent, so its slot is skipped —
    exactly the scalar encoder's gating)."""
    from .encode_ltsv_block import _ltsv_core
    from .materialize_rfc3164 import _scalar_3164

    spec = merger_suffix(merger)
    if spec is None:
        return None
    suffix, syslen = spec

    n = int(n_real)
    starts64 = np.asarray(starts[:n], dtype=np.int64)
    lens64 = np.asarray(orig_lens[:n], dtype=np.int64)
    ok = np.asarray(out["ok"][:n], dtype=bool)
    has_high = np.asarray(out["has_high"][:n], dtype=bool)
    cand = ok & (lens64 <= max_len) & ~has_high
    ridx = np.flatnonzero(cand)
    R = ridx.size
    if not R:
        return finish_block(chunk_bytes, starts64, lens64, n, cand, ridx,
                            b"", np.zeros(1, dtype=np.int64), None,
                            suffix, syslen, merger, encoder,
                            scalar_fn=_scalar_3164)
    st = starts64[ridx]
    host_a = st + np.asarray(out["host_start"])[:n][ridx].astype(np.int64)
    host_l = (np.asarray(out["host_end"])[:n][ridx].astype(np.int64)
              - np.asarray(out["host_start"])[:n][ridx].astype(np.int64))
    msg_a = st + np.asarray(out["msg_start"])[:n][ridx].astype(np.int64)
    msg_l = np.maximum(st + lens64[ridx] - msg_a, 0)
    has_pri = np.asarray(out["has_pri"][:n], dtype=bool)[ridx]
    fac = np.asarray(out["facility"])[:n][ridx].astype(np.int64)
    sev = np.asarray(out["severity"])[:n][ridx].astype(np.int64)
    pri = (fac << 3) + sev

    scratch, ts_off, ts_len = ts_scratch(out, n, ridx,
                                         unix_to_rfc3339_ms)
    chunk_arr = np.frombuffer(chunk_bytes, dtype=np.uint8)
    consts, offs = build_source(
        b"<", b">1 ", b"<13>1 ", b" ", b" - - - ", b"0123456789",
        suffix, scratch)
    (o_lt, o_gt1, o_dflt, o_sp, o_tail, o_dec, o_sfx, o_ts) = offs
    cbase = int(chunk_arr.size)
    src = np.concatenate([chunk_arr, consts])

    pri_d = decimal_segments(pri, cbase + o_dec, width=3)
    pc = np.zeros(R, dtype=np.int64)
    cols = (
        (np.where(has_pri, cbase + o_lt, 0), np.where(has_pri, 1, 0)),
        (pri_d[0][0::3], np.where(has_pri, pri_d[1][0::3], 0)),
        (pri_d[0][1::3], np.where(has_pri, pri_d[1][1::3], 0)),
        (pri_d[0][2::3], np.where(has_pri, pri_d[1][2::3], 0)),
        (np.where(has_pri, cbase + o_gt1, cbase + o_dflt),
         np.where(has_pri, len(b">1 "), len(b"<13>1 "))),
        (cbase + o_ts + ts_off, ts_len),
        (cbase + o_sp, 1),
        (host_a, host_l),
        (cbase + o_tail, len(b" - - - ")),
        (msg_a, msg_l),
        (cbase + o_sfx, len(suffix)),
    )
    return _ltsv_core(chunk_bytes, starts64, lens64, n, cand, ridx,
                      src, cbase, pc, None, 0, 0,
                      cols, (), suffix, syslen, merger, encoder,
                      scalar_fn=_scalar_3164)


def _rfc5424_sd_assemble(chunk_bytes, chunk_arr, src, offs, starts64,
                         lens64, n, cand, ridx, pc, ts_off, ts_len,
                         host_a, host_l, msg_a, msg_l, has_msg, pairs,
                         suffix, syslen, merger, encoder, scalar_fn):
    """Shared RFC5424 row assembly for the Record-shaped routes
    (gelf→RFC5424, ltsv→RFC5424): constant <13> PRI head, rfc3339-ms
    stamp, host, " - - " proc/msgid slots, one SD block (or "- "),
    optional message, framing suffix.

    ``offs`` is the build_source offset tuple for the consts
    ``("<13>1 ", " ", " - - ", "[", "] ", "- ", ' ', '="', '"',
    suffix, scratch)``; ``pairs`` is None or ``(rr [T] compacted row
    ids ASCENDING, ns, nlen, eqlen, vsrc, vlen, qlen)`` — the three
    length columns let callers gate null values (bare names)."""
    (o_pri, o_sp, o_tail3, o_open, o_close, o_dash2, o_psp, o_eq,
     o_q, o_sfx, o_ts) = offs
    cbase = int(chunk_arr.size)
    R = ridx.size
    has_sd = pc > 0

    HEAD = 6
    TAIL = 3
    segc = HEAD + 5 * pc + TAIL
    rstart = exclusive_cumsum(segc)[:-1]
    S = int(segc.sum())
    seg_src = np.zeros(S, dtype=np.int64)
    seg_len = np.zeros(S, dtype=np.int64)

    head = (
        (np.full(R, cbase + o_pri), np.full(R, 6)),   # "<13>1 "
        (cbase + o_ts + ts_off, ts_len),
        (np.full(R, cbase + o_sp), np.full(R, 1)),
        (host_a, host_l),
        (np.full(R, cbase + o_tail3), np.full(R, 5)),  # " - - "
        (np.full(R, cbase + o_open), np.where(has_sd, 1, 0)),
    )
    for k, (sv, lv) in enumerate(head):
        seg_src[rstart + k] = sv
        seg_len[rstart + k] = lv

    if pairs is not None and pairs[0].size:
        rr, ns, nlen, eqlen, vsrc, vlen, qlen = pairs
        new_row = np.ones(rr.size, dtype=bool)
        new_row[1:] = rr[1:] != rr[:-1]
        run_starts = np.flatnonzero(new_row)
        within = (np.arange(rr.size)
                  - np.repeat(run_starts,
                              np.diff(np.append(run_starts, rr.size))))
        p0 = rstart[rr] + HEAD + 5 * within
        seg_src[p0] = cbase + o_psp
        seg_len[p0] = 1
        seg_src[p0 + 1] = ns
        seg_len[p0 + 1] = nlen
        seg_src[p0 + 2] = cbase + o_eq
        seg_len[p0 + 2] = eqlen
        seg_src[p0 + 3] = vsrc
        seg_len[p0 + 3] = vlen
        seg_src[p0 + 4] = cbase + o_q
        seg_len[p0 + 4] = qlen

    fd = (rstart + HEAD + 5 * pc)[:, None] + np.arange(
        TAIL, dtype=np.int64)[None, :]
    tail_cols = (
        (np.where(has_sd, cbase + o_close, cbase + o_dash2),
         np.full(R, 2)),
        (msg_a, np.where(has_msg, msg_l, 0)),
        (np.full(R, cbase + o_sfx), np.full(R, len(suffix))),
    )
    fsrc = np.empty((R, TAIL), dtype=np.int64)
    flen = np.empty((R, TAIL), dtype=np.int64)
    for k, (sv, lv) in enumerate(tail_cols):
        fsrc[:, k] = sv
        flen[:, k] = lv
    seg_src[fd] = fsrc
    seg_len[fd] = flen

    dst0 = exclusive_cumsum(seg_len)
    body = concat_segments(src, seg_src, seg_len, dst0)
    row_off = np.concatenate([dst0[rstart], dst0[-1:]])
    prefix_lens_tier = None
    if syslen:
        final_buf, row_off, prefix_lens_tier = apply_syslen_prefix(
            body, row_off, np.diff(row_off))
    else:
        final_buf = body.tobytes()
    return finish_block(chunk_bytes, starts64, lens64, n, cand, ridx,
                        final_buf, row_off, prefix_lens_tier, suffix,
                        syslen, merger, encoder, scalar_fn=scalar_fn)


def encode_gelf_rfc5424_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
) -> Optional[BlockResult]:
    """gelf→RFC5424 (rfc5424_encoder.rs:28-93 over the GELF Record
    shape): facility is always absent so PRI is the constant <13>
    default; the stamp re-formats ms-truncated rfc3339 from the parsed
    value; appname's slot is skipped, procid/msgid render "-", and the
    typed pairs rebuild one SD block in sorted-ORIGINAL-key Record
    order — ``[ name="value" ...]`` with nulls as bare names, bools as
    constants, clean strings/canonical ints verbatim (record.rs:42-68
    does not escape values, and the escape-free tier's strings cannot
    contain a quote)."""
    from .block_common import gelf_sorted_pairs
    from .encode_gelf_gelf_block import _NAME_CAP, gelf_screen
    from .gelf import VT_FALSE, VT_NULL, VT_NUMBER, VT_STRING, VT_TRUE
    from .materialize_gelf import _scalar_gelf

    spec = merger_suffix(merger)
    if spec is None:
        return None
    suffix, syslen = spec

    s = gelf_screen(chunk_bytes, starts, orig_lens, out, n_real, max_len)
    n, starts64, lens64, cand = (s["n"], s["starts64"], s["lens64"],
                                 s["cand"])
    chunk_arr = s["chunk_arr"]
    is_pair = s["is_pair"] & cand[:, None]

    rop_s, ns_s, ne_s, pv_t, pv_a, pv_b = gelf_sorted_pairs(
        chunk_arr, starts64, cand, is_pair, s["kabs"], s["key_e"],
        s["vabs_a"], s["vabs_b"], s["val_t"], s["byte_at"], _NAME_CAP)

    ridx = np.flatnonzero(cand)
    R = ridx.size
    if not R:
        return finish_block(chunk_bytes, starts64, lens64, n, cand, ridx,
                            b"", np.zeros(1, dtype=np.int64), None,
                            suffix, syslen, merger, encoder,
                            scalar_fn=_scalar_gelf)

    # timestamps: per-unique span parse + rfc3339-ms format, one pass
    from .block_common import span_f64_scratch

    scratch, ts_off, ts_len = span_f64_scratch(
        chunk_bytes, s["tsa_all"][ridx], s["tsb_all"][ridx],
        unix_to_rfc3339_ms)

    host_a0, host_b0 = s["vspan_at"](s["host_f"])
    host_a, host_l = host_a0[ridx], (host_b0 - host_a0)[ridx]
    msg_a0, msg_b0 = s["vspan_at"](s["short_f"])
    msg_a, msg_l = msg_a0[ridx], (msg_b0 - msg_a0)[ridx]
    has_msg = s["has_short"][ridx]

    consts, offs = build_source(
        b"<13>1 ", b" ", b" - - ", b"[", b"] ", b"- ", b' ', b'="',
        b'"', suffix, scratch, b"true", b"false")
    o_true, o_false = offs[11], offs[12]
    chunk_src = np.concatenate([chunk_arr, consts])
    cbase = int(chunk_arr.size)

    # pc in ORIGINAL row space, selected down to the candidate rows
    pc = (np.bincount(rop_s, minlength=n)[ridx].astype(np.int64)
          if rop_s.size else np.zeros(R, dtype=np.int64))

    pairs = None
    if rop_s.size:
        tpos = np.cumsum(cand) - 1
        rr = tpos[rop_s]
        is_null = pv_t == VT_NULL
        is_txt = (pv_t == VT_STRING) | (pv_t == VT_NUMBER)
        vsrc = np.where(is_txt, pv_a,
                        np.where(pv_t == VT_TRUE, cbase + o_true,
                                 np.where(pv_t == VT_FALSE,
                                          cbase + o_false, 0)))
        vlen = np.where(is_txt, pv_b - pv_a,
                        np.where(pv_t == VT_TRUE, 4,
                                 np.where(pv_t == VT_FALSE, 5, 0)))
        pairs = (rr, ns_s, ne_s - ns_s,
                 np.where(is_null, 0, 2),
                 vsrc, np.where(is_null, 0, vlen),
                 np.where(is_null, 0, 1))

    return _rfc5424_sd_assemble(
        chunk_bytes, chunk_arr, chunk_src, offs[:11], starts64, lens64,
        n, cand, ridx, pc, ts_off, ts_len, host_a, host_l, msg_a, msg_l,
        has_msg, pairs, suffix, syslen, merger, encoder, _scalar_gelf)


def encode_ltsv_rfc5424_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
    decoder=None,
) -> Optional[BlockResult]:
    """ltsv→RFC5424: facility is always absent so PRI is the constant
    <13> default; stamps re-format ms-truncated rfc3339 (rfc3339 rows
    from the calendar channels, unix literals from the split-integer
    parse); pairs rebuild one SD block in PART order (the Record keeps
    insertion order; record.rs:42-68 renders values unescaped, so raw
    spans are exact).  Typed ``ltsv_schema`` keeps the Record path."""
    from .block_common import (
        ltsv_special_screen,
        ltsv_ts_vals,
        vals_scratch,
    )
    from .materialize_ltsv import _scalar_ltsv

    spec = merger_suffix(merger)
    if spec is None:
        return None
    if decoder is not None and getattr(decoder, "schema", None):
        return None
    suffix, syslen = spec

    def scalar_fn(line):
        return _scalar_ltsv(decoder, line)

    n = int(n_real)
    starts64 = np.asarray(starts[:n], dtype=np.int64)
    lens64 = np.asarray(orig_lens[:n], dtype=np.int64)
    ok = np.asarray(out["ok"][:n], dtype=bool)
    has_high = np.asarray(out["has_high"][:n], dtype=bool)
    n_parts = np.asarray(out["n_parts"])[:n].astype(np.int64)
    part_start = np.asarray(out["part_start"])[:n]
    part_end = np.asarray(out["part_end"])[:n]
    colon_pos = np.asarray(out["colon_pos"])[:n]
    host_pos = np.asarray(out["host_pos"])[:n]

    P = part_start.shape[1]
    jmask = np.arange(P)[None, :] < n_parts[:, None]
    cand = ok & (lens64 <= max_len) & ~has_high & (host_pos >= 0)
    cand &= ~(jmask & (colon_pos < 0)).any(axis=1)
    chunk_arr = np.frombuffer(chunk_bytes, dtype=np.uint8)
    nlen = np.where(jmask, colon_pos - part_start, 0)
    special_name, uniq_ok = ltsv_special_screen(
        chunk_arr, starts64, part_start, nlen, jmask)
    cand &= uniq_ok

    ridx = np.flatnonzero(cand)
    R = ridx.size
    if not R:
        return finish_block(chunk_bytes, starts64, lens64, n, cand, ridx,
                            b"", np.zeros(1, dtype=np.int64), None,
                            suffix, syslen, merger, encoder,
                            scalar_fn=scalar_fn)
    st = starts64[ridx]

    ts_vals = ltsv_ts_vals(out, n, ridx, chunk_bytes, starts64)
    scratch, ts_off, ts_len = vals_scratch(ts_vals, unix_to_rfc3339_ms)

    host_a = st + np.asarray(out["host_start"])[:n][ridx].astype(np.int64)
    host_l = (np.asarray(out["host_end"])[:n][ridx].astype(np.int64)
              - np.asarray(out["host_start"])[:n][ridx].astype(np.int64))
    msg_a = st + np.asarray(out["msg_start"])[:n][ridx].astype(np.int64)
    msg_l = (np.asarray(out["msg_end"])[:n][ridx].astype(np.int64)
             - np.asarray(out["msg_start"])[:n][ridx].astype(np.int64))
    has_msg = np.asarray(out["msg_pos"])[:n][ridx].astype(np.int64) >= 0

    consts, offs = build_source(
        b"<13>1 ", b" ", b" - - ", b"[", b"] ", b"- ", b' ', b'="',
        b'"', suffix, scratch)
    chunk_src = np.concatenate([chunk_arr, consts])

    # pairs in PART order: non-special parts, raw name/value spans
    is_pair = jmask[ridx] & ~special_name[ridx]
    pc = is_pair.sum(axis=1).astype(np.int64)

    pairs = None
    if int(pc.sum()):
        rr2, cc = np.nonzero(is_pair)
        rop = rr2.astype(np.int64)
        ns = st[rop] + part_start[ridx][rr2, cc].astype(np.int64)
        ne = st[rop] + colon_pos[ridx][rr2, cc].astype(np.int64)
        ve = st[rop] + part_end[ridx][rr2, cc].astype(np.int64)
        T = rop.size
        pairs = (rop, ns, ne - ns, np.full(T, 2), ne + 1, ve - ne - 1,
                 np.full(T, 1))

    return _rfc5424_sd_assemble(
        chunk_bytes, chunk_arr, chunk_src, offs, starts64, lens64, n,
        cand, ridx, pc, ts_off, ts_len, host_a, host_l, msg_a, msg_l,
        has_msg, pairs, suffix, syslen, merger, encoder, scalar_fn)
