"""Zero-JIT boot: the versioned AOT kernel-artifact pipeline.

Every fresh process used to pay first-compile JIT for every (format,
encoder, bucket) it touched — on constrained hosts the device-encode
compiles never finish at all, and even the healthy compiles put minutes
between process start and the first emitted batch.  This module makes
startup a *load*, not a compile (the simdjson lesson, arxiv 1902.08318:
these decoders are fixed programs — precompile them, don't re-derive
them per process):

- **build** (``python -m flowgger_tpu.tpu.aot build --out DIR``): runs
  on any host, no accelerator needed.  Enumerates the live route
  matrix — the four block decoders, the four split device-encode
  kernels, and the four fused decode→encode programs
  (tpu/fused_routes.py) — across the configured shape-bucket grid
  (pack.shape_bucket_grid) and serializes each via ``jax.export``
  cross-platform lowering (TPU artifacts serialize from a CPU-only
  box).  A manifest records KERNEL_ABI, the jax version, platform,
  bucket grid, route name, the demand/elide static args, and a content
  hash per blob.  ``--warm`` additionally executes each CPU-platform
  program once with the persistent XLA compile cache pointed inside
  the artifact dir (``<out>/xla-cache``), so the *executable* ships
  alongside the portable StableHLO.

- **load** (``input.tpu_aot_dir``): BatchHandler installs the store
  before any kernel dispatch.  Decode submits, the fused-route tier,
  and the split device-encode kernels all consult the store first —
  a hit calls the deserialized exported program (``jax.jit(exp.call)``)
  instead of tracing + compiling; any mismatch (wrong KERNEL_ABI, jax
  version, bucket grid, platform, a corrupted blob, a missing route)
  declines to the existing JIT + watchdog + persistent-cache ladder
  with a counted reject reason.  ``aot_hits``/``aot_misses``/
  ``aot_rejects[_reason]`` counters let a production boot assert zero
  fresh compiles (``compile_cache_misses == 0`` with ``aot_hits > 0``).

The PR 5 persistent compile cache becomes the *fallback*, not the
plan: when the artifact dir carries a warmed ``xla-cache`` and no
explicit ``input.tpu_compile_cache_dir`` is configured, the loader
points JAX's cache there automatically, so even the one residual
compile per exported program (StableHLO → executable) is a cache hit.

Byte identity is unchanged at every rung: an AOT-loaded program IS the
jit program (same trace, same statics), and every decline lands on the
tiers whose identity the existing differential tests seal.
"""

from __future__ import annotations

# byte-identity contract (flowcheck FC03): AOT-loaded programs must be
# byte-identical to the JIT-booted pipeline (itself sealed against the
# scalar oracle); the differential tests run the same corpus through an
# artifact-booted handler and a plain one across line/nul/syslen
SCALAR_ORACLE = "flowgger_tpu.encoders.gelf:GelfEncoder"
DIFF_TEST = (
    "tests/test_aot.py::test_aot_boot_byte_identity_and_hits",
    "tests/test_aot.py::test_aot_rejects_decline_to_jit_byte_identical",
)

import hashlib
import json
import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

MANIFEST_NAME = "manifest.json"
AOT_FORMAT = 1
XLA_CACHE_SUBDIR = "xla-cache"

DECODE_FORMATS = ("rfc5424", "rfc3164", "ltsv", "gelf", "jsonl", "dns")
ENCODE_MODULES = ("device_gelf", "device_rfc3164", "device_ltsv",
                  "device_gelf_gelf", "device_rfc5424_out",
                  "device_rfc5424_out_3164", "device_ltsv_out",
                  "device_capnp")
FUSED_ROUTES = ("rfc5424_gelf", "rfc3164_gelf", "ltsv_gelf", "gelf_gelf",
                "rfc5424_rfc5424", "rfc3164_rfc5424", "rfc5424_ltsv",
                "rfc5424_capnp")
# framing name -> block merger suffix; syslen shares "line"'s b"\n"
# (block_common.merger_suffix: the syslen prefix is a host-side splice)
FRAMINGS = {"line": b"\n", "nul": b"\x00"}
FAMILIES = ("decode", "fused", "encode", "framing", "pallas")
# device-resident framing (tpu/framing.py): stage-A span kernels per
# input framing plus the shared stage-B gather
FRAMING_KINDS = ("line", "nul", "syslen")
# the byte-bucket each row bucket's framing artifact assumes (~128 B
# average records); other region sizes decline to the JIT ladder
FRAMING_AVG_BYTES = 128

# the active store is module state with the same contract as
# pack._SHAPE_BUCKETS: only an explicit config key (input.tpu_aot_dir /
# input.tpu_aot = "off") touches it, so a default-configured handler
# never silently drops another handler's artifacts
_active_lock = threading.Lock()
_active_store: List[Optional["AotStore"]] = [None]
# artifact root whose in-dir xla-cache setup_aot auto-pointed JAX's
# persistent cache at (None = setup_aot never touched the cache) — a
# later rejection of that same store must un-point it, or the JIT
# fallback ladder writes wrong-shape executables into the shipped
# artifact directory
_auto_cache_root: List[Optional[str]] = [None]
# the persistent-cache config enable_compile_cache displaced when
# setup_aot auto-pointed the cache (e.g. an operator's stock
# JAX_COMPILATION_CACHE_DIR): un-pointing must RESTORE it, not just
# clear the cache dir
_displaced_cache: List[Optional[Dict]] = [None]
# roots whose load already failed this process: Pipeline and
# BatchHandler both wire setup_aot on a normal boot, and re-loading a
# known-bad dir would count (and log) every boot-level rejection twice
_failed_roots: set = set()

_ABSENT = object()


def _snapshot_cache_config() -> Dict:
    """The current values of the persistent-cache knobs
    enable_compile_cache overwrites (``device_common.CACHE_KNOBS`` is
    the single source; absent knobs skipped — names vary across jax
    versions)."""
    import jax

    from .device_common import CACHE_KNOBS

    return {k: v for k in CACHE_KNOBS
            if (v := getattr(jax.config, k, _ABSENT)) is not _ABSENT}


def _restore_cache_config(snapshot: Optional[Dict]) -> None:
    """Put back a ``_snapshot_cache_config`` snapshot (no snapshot =
    just clear the cache dir) and reset jax's latched cache state —
    the one restore dance shared by ``_unpoint_auto_cache`` and
    ``warm_artifacts``."""
    import jax

    for k, v in (snapshot
                 or {"jax_compilation_cache_dir": None}).items():
        try:
            jax.config.update(k, v)
        except Exception:  # noqa: BLE001 - knob names vary across jax versions
            pass
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # noqa: BLE001 - private API; harmless if gone
        pass


def _metrics():
    from ..utils.metrics import registry

    return registry


def _scan_impl_for(platform: str) -> str:
    """THE platform->scan-impl mapping: plain cumsum on cpu, MXU
    tri-matmul elsewhere.  Single-sourced here — the builder stamps it
    into every fused/encode artifact key from the platform string
    (never the build host), and ``rfc5424.best_scan_impl`` delegates
    here at runtime, so the two sides cannot drift into a silent
    all-miss boot."""
    return "lax" if platform == "cpu" else "mm"


# ---------------------------------------------------------------------------
# canonical lookup keys: family + platform + static args + flattened
# input shapes/dtypes.  The builder and the loader both derive the key
# from the SAME helpers below, so a drift in either is a test failure,
# not a silent all-miss boot.

def _canon_static(v):
    if isinstance(v, bytes):
        return {"__bytes__": v.hex()}
    if isinstance(v, frozenset):
        return sorted(v)
    if isinstance(v, (tuple, list)):
        return [_canon_static(x) for x in v]
    if isinstance(v, dict):
        return {k: _canon_static(v[k]) for k in sorted(v)}
    return v


def canon_statics(statics: Dict) -> Dict:
    return {k: _canon_static(statics[k]) for k in sorted(statics)}


def args_spec(args) -> List:
    """Flattened (dtype, shape) list of an argument pytree — accepts
    arrays and ShapeDtypeStructs alike (dict leaves flatten in sorted
    key order on both sides)."""
    import jax

    return [[str(x.dtype), list(x.shape)]
            for x in jax.tree_util.tree_leaves(args)]


def entry_key(family: str, platform: str, statics: Dict,
              spec: List) -> str:
    blob = json.dumps({"family": family, "platform": platform,
                       "statics": canon_statics(statics), "spec": spec},
                      sort_keys=True, separators=(",", ":"))
    digest = hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]
    return f"{family.replace('/', '_')}--{platform}--{digest}"


# ---------------------------------------------------------------------------
# per-family static-arg recipes: ONE definition each, imported by the
# builder (export time) and by the call sites in rfc5424/rfc3164/ltsv/
# gelf/device_*/fused_routes (lookup time)

def decode_statics(fmt: str) -> Dict:
    if fmt == "rfc5424":
        from .rfc5424 import DEFAULT_MAX_SD

        return {"max_sd": DEFAULT_MAX_SD, "extract_impl": "sum"}
    if fmt == "ltsv":
        from .ltsv import DEFAULT_MAX_PARTS

        return {"max_parts": DEFAULT_MAX_PARTS}
    if fmt == "gelf":
        from .gelf import DEFAULT_MAX_FIELDS

        return {"max_fields": DEFAULT_MAX_FIELDS}
    if fmt == "jsonl":
        from .jsonl import DEFAULT_MAX_FIELDS

        return {"max_fields": DEFAULT_MAX_FIELDS}
    # rfc3164 (the year is a traced input, not a static) and dns (the
    # fixed grammar has no static knobs)
    return {}


def fused_statics(route_name: str, suffix: bytes, impl: str,
                  extras: Tuple) -> Dict:
    from .fused_routes import DEMAND

    statics = {"suffix": suffix, "impl": impl, "extras": extras,
               "demand": DEMAND[route_name], "elide": True}
    if route_name in ("rfc5424_gelf", "rfc5424_rfc5424", "rfc5424_ltsv",
                      "rfc5424_capnp"):
        from .pallas_kernels import fused_leg_mode
        from .rfc5424 import DEFAULT_MAX_SD

        statics["max_sd"] = DEFAULT_MAX_SD
        # the rfc5424 decode leg traces differently per pallas mode —
        # part of the artifact key so a loaded program always matches
        # what the live closure would trace ("compiled" or "off";
        # interpret never reaches a fused program)
        statics["pallas"] = fused_leg_mode()
    return statics


def framing_statics(kind: str, ncap: int, region_bytes: int) -> Dict:
    """Static-arg recipe for one framing stage-A kernel (kind in
    FRAMING_KINDS) or the stage-B gather (kind="gather", where ``ncap``
    carries max_len).  ONE definition shared by the builder and
    ``framing_call``'s call sites in tpu/framing.py."""
    if kind == "line":
        return {"sep": 10, "strip_cr": True, "ncap": ncap}
    if kind == "nul":
        return {"sep": 0, "strip_cr": False, "ncap": ncap}
    if kind == "syslen":
        from .framing import syslen_hops

        return {"ncap": ncap, "max_hops": syslen_hops(region_bytes)}
    if kind == "gather":
        return {"max_len": ncap}
    raise ValueError(f"unknown framing kind {kind!r}")


def pallas_statics(kind: str, ncap: int, region_bytes: int) -> Dict:
    """Static-arg recipe for one Pallas kernel entry (kind in
    FRAMING_KINDS, ``gather`` — where ``ncap`` carries max_len — or
    ``decode_rfc5424``/``decode_jsonl``).  ONE definition shared by the
    builder and the probe sites (tpu/framing.py,
    pallas_kernels.decode_tier); the ``interpret`` flag is appended
    per-platform — cpu artifacts embed the interpreter path, Mosaic
    only lowers on accelerators."""
    if kind == "line":
        return {"sep": 10, "strip_cr": True, "ncap": ncap}
    if kind == "nul":
        return {"sep": 0, "strip_cr": False, "ncap": ncap}
    if kind == "syslen":
        return {"ncap": ncap}
    if kind == "gather":
        return {"max_len": ncap}
    if kind == "decode_rfc5424":
        from .rfc5424 import DEFAULT_MAX_SD

        return {"max_sd": DEFAULT_MAX_SD}
    if kind == "decode_jsonl":
        return {}
    raise ValueError(f"unknown pallas kind {kind!r}")


def encode_statics(module: str, suffix: bytes, impl: str,
                   extras: Tuple) -> Dict:
    if module == "device_gelf_gelf":
        return {"suffix": suffix, "elide": True}
    if module in ("device_rfc5424_out", "device_rfc5424_out_3164"):
        # the PR 19 output-leg kernels have no impl/extras statics; the
        # rfc5424 leg carries max_sd, the shared-core rfc3164 leg not
        statics = {"suffix": suffix, "elide": True}
        if module == "device_rfc5424_out":
            from .rfc5424 import DEFAULT_MAX_SD

            statics["max_sd"] = DEFAULT_MAX_SD
        return statics
    if module in ("device_ltsv_out", "device_capnp"):
        return {"suffix": suffix, "extras": extras, "elide": True}
    statics = {"suffix": suffix, "impl": impl, "extras": extras,
               "elide": True}
    if module == "device_gelf":
        from .rfc5424 import DEFAULT_MAX_SD

        statics["max_sd"] = DEFAULT_MAX_SD
    return statics


# ---------------------------------------------------------------------------
# loader / store

class AotStore:
    """A loaded artifact dir: validated manifest + lazily deserialized
    exported programs, each wrapped in ``jax.jit(exp.call)`` (the exact
    calling convention the builder's ``--warm`` used, so the warmed
    persistent-cache entries match)."""

    def __init__(self, root: str, manifest: Dict):
        self.root = root
        self.manifest = manifest
        self.entries: Dict[str, Dict] = manifest["entries"]
        self._calls: Dict[str, object] = {}
        self._bad: set = set()
        self._warned: set = set()
        self._lock = threading.Lock()

    @property
    def xla_cache_dir(self) -> str:
        return os.path.join(self.root, XLA_CACHE_SUBDIR)

    def has_warm_cache(self) -> bool:
        """True when a skip-free ``--warm`` pass populated the
        kabi-versioned xla-cache for THIS kernel ABI *and THIS host's
        platform* (the per-platform marker file) — a tpu-platform build
        warmed on a cpu box creates no ``warmed-tpu`` marker, so a tpu
        fleet host must not skip prewarm against executables that were
        never compiled."""
        return os.path.isfile(_warm_marker_path(self.root,
                                                self._platform()))

    @staticmethod
    def _platform() -> str:
        import jax

        return jax.default_backend()

    # -- load-time validation ---------------------------------------------
    @classmethod
    def load(cls, root: str, expect_grid=None,
             expect_max_len: Optional[int] = None) -> Optional["AotStore"]:
        """Load + strictly validate an artifact dir; None (with a
        counted ``aot_rejects_<reason>``) sends the boot down the JIT +
        persistent-cache ladder instead."""
        reg = _metrics()

        def reject(reason: str, msg: str) -> None:
            from ..obs import events as _events

            reg.inc("aot_rejects")
            reg.inc(f"aot_rejects_{reason}")
            _events.emit(
                "aot", "aot_reject", detail=f"{reason}: {msg}",
                route=root,
                msg=f"aot: rejecting artifact dir {root} ({msg}); "
                    "kernels use the JIT + persistent-cache ladder")

        try:
            with open(os.path.join(root, MANIFEST_NAME), "rb") as f:
                manifest = json.load(f)
        except Exception as e:  # noqa: BLE001 - any unreadable manifest declines
            reject("corrupt", f"unreadable manifest: {type(e).__name__}: {e}")
            return None
        if manifest.get("aot_format") != AOT_FORMAT:
            reject("manifest_format",
                   f"manifest format {manifest.get('aot_format')!r} != "
                   f"{AOT_FORMAT}")
            return None
        from .device_common import KERNEL_ABI

        if manifest.get("kernel_abi") != KERNEL_ABI:
            reject("kernel_abi",
                   f"artifact KERNEL_ABI {manifest.get('kernel_abi')!r} != "
                   f"running {KERNEL_ABI}")
            return None
        import jax

        if manifest.get("jax_version") != jax.__version__:
            reject("jax_version",
                   f"artifact jax {manifest.get('jax_version')!r} != "
                   f"running {jax.__version__}")
            return None
        platform = cls._platform()
        if platform not in manifest.get("platforms", []):
            reject("platform",
                   f"no artifacts for runtime platform '{platform}' "
                   f"(built: {manifest.get('platforms')})")
            return None
        shape_msg = cls._shape_mismatch(manifest, expect_grid,
                                        expect_max_len)
        if shape_msg:
            reject("bucket_grid", shape_msg)
            return None
        if not isinstance(manifest.get("entries"), dict):
            # a parseable-but-truncated manifest must decline like any
            # other mismatch, not KeyError out of the boot
            reject("corrupt", "manifest has no entries table")
            return None
        store = cls(root, manifest)
        n_here = sum(1 for e in store.entries.values()
                     if isinstance(e, dict)
                     and e.get("platform") == platform)
        print(f"aot: loaded {n_here} artifacts for platform "
              f"'{platform}' from {root} "
              f"(grid {manifest.get('rows_grid')}, "
              f"kabi {manifest.get('kernel_abi')})", file=sys.stderr)
        return store

    @staticmethod
    def _shape_mismatch(manifest: Dict, expect_grid,
                        expect_max_len: Optional[int]) -> Optional[str]:
        if (expect_max_len is not None
                and manifest.get("max_len") != expect_max_len):
            return (f"artifact max_len {manifest.get('max_len')} != "
                    f"configured {expect_max_len}")
        if expect_grid is not None:
            built = set(manifest.get("rows_grid", ()))
            missing = sorted(set(int(g) for g in expect_grid) - built)
            if missing:
                return (f"configured row buckets {missing} not in the "
                        f"artifact grid {sorted(built)}")
        return None

    def revalidate(self, expect_grid=None,
                   expect_max_len: Optional[int] = None) -> bool:
        """Re-check an already-loaded store against shape expectations
        learned after load (BatchHandler's max_len + bucket grid);
        False = reject (counted) and the caller deactivates it."""
        msg = self._shape_mismatch(self.manifest, expect_grid,
                                   expect_max_len)
        if msg is None:
            return True
        reg = _metrics()
        from ..obs import events as _events

        reg.inc("aot_rejects")
        reg.inc("aot_rejects_bucket_grid")
        _events.emit(
            "aot", "aot_reject", detail=f"bucket_grid: {msg}",
            route=self.root,
            msg=f"aot: rejecting artifact dir {self.root} ({msg}); "
                "kernels use the JIT + persistent-cache ladder")
        return False

    # -- lookup ------------------------------------------------------------
    def covers(self, family: str, statics: Dict, spec: List) -> bool:
        key = entry_key(family, self._platform(), statics, spec)
        return key in self.entries and key not in self._bad

    def find(self, family: str, statics: Dict, args):
        """The exported program's callable, or None (counted as a miss;
        a missing entry additionally counts the ``missing_route``
        reject reason the loader tests pin — once per key, while
        ``aot_misses`` counts every missed call)."""
        reg = _metrics()
        key = entry_key(family, self._platform(), statics,
                        args_spec(args))
        entry = self.entries.get(key)
        if entry is None:
            reg.inc("aot_misses")
            with self._lock:
                first = key not in self._warned
                self._warned.add(key)
            if first:
                reg.inc("aot_rejects")
                reg.inc("aot_rejects_missing_route")
            return None
        if key in self._bad:
            reg.inc("aot_misses")
            return None
        call = self._get_call(key, entry)
        if call is None:
            reg.inc("aot_misses")
        return call

    def _get_call(self, key: str, entry: Dict):
        with self._lock:
            call = self._calls.get(key)
        if call is not None:
            return call
        try:
            path = os.path.join(self.root, entry["file"])
            with open(path, "rb") as f:
                blob = f.read()
            if hashlib.sha256(blob).hexdigest() != entry["sha256"]:
                raise ValueError("content hash mismatch")
            import jax
            from jax import export as jexport

            call = jax.jit(jexport.deserialize(blob).call)
        except Exception as e:  # noqa: BLE001 - a bad blob must decline, not crash
            self.reject_entry(key, "corrupt",
                              f"{type(e).__name__}: {e}")
            return None
        with self._lock:
            self._calls[key] = call
        return call

    def reject_entry(self, key: str, reason: str, detail: str) -> None:
        reg = _metrics()
        with self._lock:
            self._bad.add(key)
            first = key not in self._warned
            self._warned.add(key)
        from ..obs import events as _events

        reg.inc("aot_rejects")
        reg.inc(f"aot_rejects_{reason}")
        _events.emit(
            "aot", "aot_reject", detail=f"{reason}: {detail}", route=key,
            msg=(f"aot: artifact [{key}] rejected ({reason}: {detail}); "
                 "that kernel uses the JIT ladder") if first else None)


def active_store() -> Optional[AotStore]:
    with _active_lock:
        return _active_store[0]


def activate_store(store: Optional[AotStore]) -> None:
    """Install (or clear, with None) the process-wide store — exposed
    for tests; production goes through setup_aot."""
    with _active_lock:
        _active_store[0] = store


def setup_aot(config, max_len: Optional[int] = None,
              grid=None) -> Optional[AotStore]:
    """Wire ``input.tpu_aot_dir`` / ``input.tpu_aot``.  No key = no-op
    (an already-active store from another handler stays).  ``require``
    turns a failed load into a startup ConfigError instead of a silent
    JIT boot — the production assert for artifact fleets.

    Called twice on a normal boot — Pipeline (before any device op,
    shape expectations unknown) and BatchHandler (max_len + bucket grid
    known): the second call revalidates the already-active store's
    manifest against the shape expectations without re-reading blobs.

    When the store loads and no explicit ``input.tpu_compile_cache_dir``
    is configured, JAX's persistent cache is pointed at the artifact
    dir's own ``xla-cache`` — the builder's ``--warm`` populated it, so
    even the residual StableHLO→executable compile of each exported
    program is a cache hit and the PR 5 cache becomes the fallback
    tier, not the plan."""
    mode = config.lookup_str(
        "input.tpu_aot",
        "input.tpu_aot must be a string (auto, require or off)", "auto")
    if mode not in ("auto", "require", "off"):
        from ..config import ConfigError

        raise ConfigError("input.tpu_aot must be auto, require or off")
    aot_dir = config.lookup_str(
        "input.tpu_aot_dir",
        "input.tpu_aot_dir must be a string (artifact directory)", None)
    if mode == "off":
        if aot_dir:
            activate_store(None)
            # clearing the store must also restore stock persistent
            # caching if an earlier wiring pass auto-pointed JAX's
            # cache inside an artifact dir — the JIT ladder this
            # config now runs on must not write executables into a
            # shipped artifact set
            with _active_lock:
                pointed = _auto_cache_root[0]
            if pointed is not None:
                _unpoint_auto_cache(pointed)
        return None
    if not aot_dir:
        if mode == "require":
            from ..config import ConfigError

            raise ConfigError(
                'input.tpu_aot = "require" needs input.tpu_aot_dir')
        return None
    root = os.path.expanduser(aot_dir)
    store = active_store()
    with _active_lock:
        already_failed = root in _failed_roots
    if store is not None and store.root == root:
        # second wiring pass (BatchHandler): revalidate the manifest
        # against the now-known shape expectations only
        if not store.revalidate(expect_grid=grid,
                                expect_max_len=max_len):
            activate_store(None)
            _unpoint_auto_cache(root)
            store = None
    elif already_failed:
        # this dir's rejection was already counted + logged by the
        # earlier wiring pass (Pipeline); don't double-count the boot
        store = None
    else:
        store = AotStore.load(root, expect_grid=grid,
                              expect_max_len=max_len)
        if store is not None:
            activate_store(store)
        else:
            # a failed load of a NEW root must not clobber another
            # handler's working store (module invariant above); this
            # handler simply boots on the JIT ladder
            with _active_lock:
                _failed_roots.add(root)
    if store is None:
        if mode == "require":
            from ..config import ConfigError

            raise ConfigError(
                f"input.tpu_aot = \"require\" but the artifact dir "
                f"{aot_dir} failed validation (see stderr)")
        return None
    explicit_cache = config.lookup_str(
        "input.tpu_compile_cache_dir",
        "input.tpu_compile_cache_dir must be a string (directory)", None)
    if not explicit_cache and store.has_warm_cache():
        # only a dir the builder actually warmed (kabi subdir present)
        # is worth pointing the persistent cache at; artifact dirs can
        # live on read-only mounts, so a failed install (EROFS, perms)
        # declines to stock cache behavior instead of crashing the boot
        from .device_common import enable_compile_cache

        displaced = _snapshot_cache_config()
        try:
            enable_compile_cache(store.xla_cache_dir)
        except OSError as e:
            print(f"aot: cannot use the artifact xla-cache at "
                  f"{store.xla_cache_dir} ({type(e).__name__}: {e}); "
                  "persistent caching keeps the stock configuration",
                  file=sys.stderr)
        else:
            with _active_lock:
                if _auto_cache_root[0] is None:
                    # first point: remember what we displaced (a
                    # re-point keeps the ORIGINAL stock config)
                    _displaced_cache[0] = displaced
                _auto_cache_root[0] = root
    return store


def _unpoint_auto_cache(root: str) -> None:
    """Restore the persistent-cache config setup_aot displaced when it
    pointed JAX's cache inside ``root``'s artifact dir (no-op
    otherwise) — an operator's stock cache (e.g. the plain
    JAX_COMPILATION_CACHE_DIR env var) comes back, it is not just
    switched off."""
    with _active_lock:
        if _auto_cache_root[0] != root:
            return
        _auto_cache_root[0] = None
        displaced = _displaced_cache[0]
        _displaced_cache[0] = None
    _restore_cache_config(displaced)


# ---------------------------------------------------------------------------
# call-site helpers (the loader half of each family recipe)

def decode_call(fmt: str, args, statics: Optional[Dict] = None
                ) -> Optional[Dict]:
    """AOT decode for one packed batch: the exported program's channel
    dict, or None → the caller runs its decode_*_jit as before.  Called
    from the decode submit fns (rfc5424/rfc3164/ltsv/gelf).  ``statics``
    is the caller's actual static-arg dict — when it differs from the
    canonical build recipe (a non-default max_sd, a forced impl) the
    configuration is not AOT-addressable and this returns None without
    touching the counters."""
    store = active_store()
    if store is None:
        return None
    recipe = decode_statics(fmt)
    if statics is not None and dict(statics) != recipe:
        return None
    call = store.find(f"decode_{fmt}", recipe, args)
    if call is None:
        return None
    try:
        out = call(*args)
    except Exception as e:  # noqa: BLE001 - decline to JIT, never lose the batch
        key = entry_key(f"decode_{fmt}", store._platform(),
                        decode_statics(fmt), args_spec(args))
        store.reject_entry(key, "call_error", f"{type(e).__name__}: {e}")
        return None
    _metrics().inc("aot_hits")
    return out


def framing_call(kind: str, args, statics: Dict):
    """AOT lookup for one framing kernel call (stage-A spans for a
    framing in FRAMING_KINDS, or kind="gather" for stage B): the
    exported program's output, or None → the caller runs its jit under
    the framing watchdog slot as before.  Same decline contract as
    decode_call: a call error rejects the entry and falls back, never
    losing the region."""
    store = active_store()
    if store is None:
        return None
    call = store.find(f"framing_{kind}", dict(statics), args)
    if call is None:
        return None
    try:
        out = call(*args)
    except Exception as e:  # noqa: BLE001 - decline to JIT, never lose the region
        key = entry_key(f"framing_{kind}", store._platform(),
                        dict(statics), args_spec(args))
        store.reject_entry(key, "call_error", f"{type(e).__name__}: {e}")
        return None
    _metrics().inc("aot_hits")
    return out


def pallas_call(kind: str, args, statics: Dict):
    """AOT lookup for one Pallas kernel call (stage-A spans, stage-B
    gather, or a decode pass): the exported program's output, or None →
    the caller jits the live kernel under its watchdog slot.  The
    runtime's interpret flag joins the lookup key, so a cpu(interpret)
    artifact never answers a compiled-mode probe — same decline
    contract as framing_call."""
    store = active_store()
    if store is None:
        return None
    from .pallas_kernels import interpret_mode

    full = {**statics, "interpret": interpret_mode()}
    call = store.find(f"pallas_{kind}", full, args)
    if call is None:
        return None
    try:
        out = call(*args)
    except Exception as e:  # noqa: BLE001 - decline to the live kernel, never lose data
        key = entry_key(f"pallas_{kind}", store._platform(), full,
                        args_spec(args))
        store.reject_entry(key, "call_error", f"{type(e).__name__}: {e}")
        return None
    _metrics().inc("aot_hits")
    return out


def wrap_kernel(family: str, kernel, args, statics: Dict):
    """Wrap a device-encode/fused kernel closure (``kernel(ts_text,
    ts_len, assemble)``) so each call consults the store first and
    declines to the jit closure on any miss/reject.  The wrapped call
    still runs under the driver's compile watchdog, so a cold
    xla-cache (exported program not yet compiled on this machine)
    degrades exactly like a cold jit compile."""
    store = active_store()
    if store is None:
        return kernel

    def wrapped(ts_text, ts_len, assemble):
        full = {**statics, "assemble": bool(assemble)}
        call_args = (*args, ts_text, ts_len)
        call = store.find(family, full, call_args)
        if call is not None:
            try:
                out = call(*call_args)
            except Exception as e:  # noqa: BLE001 - decline to JIT, never lose the batch
                key = entry_key(family, store._platform(), full,
                                args_spec(call_args))
                store.reject_entry(key, "call_error",
                                   f"{type(e).__name__}: {e}")
            else:
                _metrics().inc("aot_hits")
                return out
        return kernel(ts_text, ts_len, assemble)

    return wrapped


def encode_wrap(module: str, kernel, batch_dev, lens_dev, dec,
                suffix: bytes, impl: str, extras, max_sd=None):
    """Wrap a split device-encode kernel closure with the AOT lookup
    when this config is AOT-addressable — the statics must equal the
    canonical build recipe (``encode_statics``); a non-default
    ``max_sd`` is not addressable and keeps the plain jit closure
    (never touching the counters)."""
    store = active_store()
    if store is None:
        return kernel
    recipe = encode_statics(module, suffix, impl, extras)
    if max_sd is not None and recipe.get("max_sd") != max_sd:
        return kernel
    return wrap_kernel(module, kernel, (batch_dev, lens_dev, dec),
                       recipe)


def fused_wrap(route_name: str, kernel, args, suffix: bytes, impl: str,
               extras, max_sd=None):
    """Wrap a fused decode→encode kernel closure (``args`` = the
    committed device inputs, ``(b, ln)`` or ``(b, ln, year)`` for
    rfc3164) with the AOT lookup; same addressability contract as
    ``encode_wrap``."""
    store = active_store()
    if store is None:
        return kernel
    recipe = fused_statics(route_name, suffix, impl, extras)
    if max_sd is not None and recipe.get("max_sd") != max_sd:
        return kernel
    return wrap_kernel(f"fused_{route_name}", kernel, args, recipe)


def _shape_spec(rows: int, max_len: int, fmt: Optional[str] = None,
                ts_w: Optional[int] = None, dec_spec=None) -> List:
    """args_spec for a family at one bucket shape without building
    arrays (prewarm coverage checks)."""
    spec = [["uint8", [rows, max_len]], ["int32", [rows]]]
    if fmt == "rfc3164":
        spec.append(["int32", []])
    if dec_spec is not None:
        spec.extend(dec_spec)
    if ts_w is not None:
        spec.extend([["uint8", [rows, ts_w]], ["int32", [rows]]])
    return spec


def prewarm_covered(fmt: str, rows: int, max_len: int, encoder=None,
                    merger=None, fused_route=None,
                    ltsv_decoder=None) -> bool:
    """True when every program prewarm would compile for this (fmt,
    rows) bucket is already AOT-loaded — decode always, plus the fused
    probe/assemble pair when a fused route is engaged, plus the split
    device-encode pair when the split device tier applies.  Partial
    coverage returns False: the prewarm pass still runs (its decode
    submit hits the store anyway) so the uncovered programs warm.  An
    un-warmed store (built without ``--warm``) also returns False —
    loaded-but-cold exported programs still pay StableHLO→executable
    on first call, and the prewarm pass pays it in the background
    instead of the first real batch."""
    store = active_store()
    if (store is None or fmt not in DECODE_FORMATS
            or not store.has_warm_cache()):
        return False
    from .device_common import TS_W

    if not store.covers(f"decode_{fmt}", decode_statics(fmt),
                        _shape_spec(rows, max_len, fmt)):
        return False
    if encoder is None or merger is None:
        return True
    from .block_common import merger_suffix

    ms = merger_suffix(merger)
    if ms is None:
        return True
    suffix, _syslen = ms
    from .rfc5424 import best_scan_impl

    impl = best_scan_impl()
    extras = tuple((k, v) for k, v in getattr(encoder, "extra", ()))
    if fused_route is not None:
        statics = fused_statics(fused_route.name, suffix, impl, extras)
        for assemble, ts_w in ((False, 0), (True, TS_W)):
            if not store.covers(
                    f"fused_{fused_route.name}",
                    {**statics, "assemble": assemble},
                    _shape_spec(rows, max_len, fmt, ts_w=ts_w)):
                return False
        # prewarm warms the split pair too (the fused tier's decline
        # fallback), so coverage must include it — fall through
    for module in _ENCODE_MODULES_FOR_FMT.get(fmt, ()):
        # jsonl/dns have no entries (host block path is the only tier);
        # per-encoder route gates mean at most one module engages
        if not _split_route_ok(module, encoder, merger, ltsv_decoder):
            continue
        statics = encode_statics(module, suffix, impl, extras)
        dec_spec = _dec_spec_for(module, rows, max_len)
        for assemble, ts_w in ((False, 0), (True, TS_W)):
            if not store.covers(module,
                                {**statics, "assemble": assemble},
                                _shape_spec(rows, max_len, ts_w=ts_w,
                                            dec_spec=dec_spec)):
                return False
        break
    return True


# split device-encode legs per input format: the →GELF module first
# (the original tier), then the PR 19 output legs; batch.py engages at
# most one per batch (the route gates key on concrete encoder type)
_ENCODE_MODULES_FOR_FMT = {
    "rfc5424": ("device_gelf", "device_rfc5424_out", "device_ltsv_out",
                "device_capnp"),
    "rfc3164": ("device_rfc3164", "device_rfc5424_out_3164"),
    "ltsv": ("device_ltsv",),
    "gelf": ("device_gelf_gelf",),
}
_MODULE_FMT = {m: f for f, ms in _ENCODE_MODULES_FOR_FMT.items()
               for m in ms}
# AOT module name -> python module (the rfc3164→rfc5424 leg shares the
# SD-assembly core module under a distinct artifact family)
_MODULE_IMPORT = {"device_rfc5424_out_3164": "device_rfc5424_out"}


def _split_route_ok(module: str, encoder, merger,
                    ltsv_decoder=None) -> bool:
    import importlib

    mod = importlib.import_module(
        "." + _MODULE_IMPORT.get(module, module), __package__)
    if module == "device_ltsv":
        # the real dispatch gate sees the decoder: a schema'd LTSV
        # route is host work, so demanding split-encode coverage for
        # it would keep prewarm busy on a fully-covered boot
        return mod.route_ok(encoder, merger, ltsv_decoder)
    return mod.route_ok(encoder, merger)


def _dec_spec_for(module: str, rows: int, max_len: int) -> List:
    """Flattened decode-channel spec feeding one split encode kernel —
    via jax.eval_shape over the same decode jit the runtime handle
    carries (no compile, no arrays)."""
    import jax
    import jax.numpy as jnp

    b = jax.ShapeDtypeStruct((rows, max_len), jnp.uint8)
    ln = jax.ShapeDtypeStruct((rows,), jnp.int32)
    fmt = _MODULE_FMT[module]
    if fmt == "rfc3164":
        yr = jax.ShapeDtypeStruct((), jnp.int32)
        dec = jax.eval_shape(_decode_fn(fmt), b, ln, yr)
    else:
        dec = jax.eval_shape(_decode_fn(fmt), b, ln)
    return args_spec(dec)


# ---------------------------------------------------------------------------
# builder

def _decode_fn(fmt: str):
    statics = decode_statics(fmt)
    if fmt == "rfc5424":
        from .rfc5424 import decode_rfc5424_jit

        return lambda b, ln: decode_rfc5424_jit(b, ln, **statics)
    if fmt == "rfc3164":
        from .rfc3164 import decode_rfc3164_jit

        return lambda b, ln, yr: decode_rfc3164_jit(b, ln, yr)
    if fmt == "ltsv":
        from .ltsv import decode_ltsv_jit

        return lambda b, ln: decode_ltsv_jit(b, ln, **statics)
    if fmt == "jsonl":
        from .jsonl import decode_jsonl_jit

        return lambda b, ln: decode_jsonl_jit(b, ln, **statics)
    if fmt == "dns":
        from .dns import decode_dns_jit

        return lambda b, ln: decode_dns_jit(b, ln)
    from .gelf import decode_gelf_jit

    return lambda b, ln: decode_gelf_jit(b, ln, **statics)


def _fused_fn(route_name: str, statics: Dict):
    from . import fused_routes as _fr

    demand = statics["demand"]
    suffix, impl, extras = (statics["suffix"], statics["impl"],
                            statics["extras"])
    assemble = statics["assemble"]
    if route_name == "rfc5424_gelf":
        max_sd = statics["max_sd"]
        pallas = statics.get("pallas", "off")

        return lambda b, ln, ts, tl: _fr._fused_rfc5424_gelf(
            b, ln, ts, tl, max_sd=max_sd, suffix=suffix, impl=impl,
            assemble=assemble, extras=extras, demand=demand,
            pallas=pallas)
    if route_name == "rfc3164_gelf":
        return lambda b, ln, yr, ts, tl: _fr._fused_rfc3164_gelf(
            b, ln, yr, ts, tl, suffix=suffix, impl=impl,
            assemble=assemble, extras=extras, demand=demand)
    if route_name == "ltsv_gelf":
        return lambda b, ln, ts, tl: _fr._fused_ltsv_gelf(
            b, ln, ts, tl, suffix=suffix, impl=impl,
            assemble=assemble, extras=extras, demand=demand)
    if route_name == "rfc5424_rfc5424":
        max_sd = statics["max_sd"]
        pallas = statics.get("pallas", "off")

        return lambda b, ln, ts, tl: _fr._fused_rfc5424_rfc5424(
            b, ln, ts, tl, max_sd=max_sd, suffix=suffix,
            assemble=assemble, demand=demand, pallas=pallas)
    if route_name == "rfc3164_rfc5424":
        return lambda b, ln, yr, ts, tl: _fr._fused_rfc3164_rfc5424(
            b, ln, yr, ts, tl, suffix=suffix, assemble=assemble,
            demand=demand)
    if route_name == "rfc5424_ltsv":
        max_sd = statics["max_sd"]
        pallas = statics.get("pallas", "off")

        return lambda b, ln, ts, tl: _fr._fused_rfc5424_ltsv(
            b, ln, ts, tl, max_sd=max_sd, suffix=suffix,
            extras=extras, assemble=assemble, demand=demand,
            pallas=pallas)
    if route_name == "rfc5424_capnp":
        max_sd = statics["max_sd"]
        pallas = statics.get("pallas", "off")

        return lambda b, ln, ts, tl: _fr._fused_rfc5424_capnp(
            b, ln, ts, tl, max_sd=max_sd, suffix=suffix,
            extras=extras, assemble=assemble, demand=demand,
            pallas=pallas)
    return lambda b, ln, ts, tl: _fr._fused_gelf_gelf(
        b, ln, ts, tl, suffix=statics["suffix"],
        assemble=assemble, demand=demand)


def _encode_fn(module: str, statics: Dict):
    import importlib

    mod = importlib.import_module(
        "." + _MODULE_IMPORT.get(module, module), __package__)
    kernel = (mod._encode_kernel_3164
              if module == "device_rfc5424_out_3164"
              else mod._encode_kernel)
    kw = {k: v for k, v in statics.items() if k != "demand"}
    return lambda b, ln, dec, ts, tl: kernel(
        b, ln, dec, ts, tl, **kw)


def _framing_fn(kind: str, statics: Dict):
    """Builder-side callable for one framing kernel (the loader half is
    ``framing_call``)."""
    from . import framing as _framing

    if kind == "gather":
        return lambda region, starts, lens: _framing.frame_gather_jit(
            region, starts, lens, **statics)
    if kind == "syslen":
        return lambda region, rlen: _framing.frame_syslen_spans_jit(
            region, rlen, **statics)
    return lambda region, rlen: _framing.frame_sep_spans_jit(
        region, rlen, **statics)


def _pallas_fn(kind: str, statics: Dict):
    """Builder-side callable for one Pallas kernel entry (the loader
    half is ``pallas_call``; ``statics`` includes the per-platform
    ``interpret`` flag)."""
    from . import pallas_kernels as _pk

    if kind == "gather":
        return lambda region, starts, lens: _pk.frame_gather_pallas(
            region, starts, lens, **statics)
    if kind == "syslen":
        return lambda region, rlen: _pk.frame_syslen_spans_pallas(
            region, rlen, **statics)
    if kind == "decode_rfc5424":
        def _dec(b, ln):
            from .rfc5424 import decode_rfc5424_pallas

            return decode_rfc5424_pallas(b, ln, **statics)

        return _dec
    if kind == "decode_jsonl":
        return lambda b, ln: _pk.decode_jsonl_pallas(b, ln, **statics)
    return lambda region, rlen: _pk.frame_sep_spans_pallas(
        region, rlen, **statics)


def _export_one(fn, example_args, platform: str):
    import jax
    from jax import export as jexport

    return jexport.export(jax.jit(fn), platforms=[platform])(*example_args)


def build_artifacts(out_dir: str, platforms=("cpu",),
                    families=FAMILIES, formats=DECODE_FORMATS,
                    framings=("line", "nul"), rows_grid=None,
                    n_buckets: int = 4, batch_size: int = 16384,
                    max_len: int = 512, extras=(), warm: bool = False,
                    warm_timeout_s: float = 900.0,
                    quiet: bool = False) -> Dict:
    """Export the route matrix into ``out_dir`` and write/merge the
    manifest.  Re-invoking with more platforms/families merges into an
    existing manifest when the KERNEL_ABI and jax version match (so cpu
    and tpu sets can build in separate passes); anything else is an
    error — mixed-ABI artifact dirs must not exist."""
    import jax
    import jax.numpy as jnp

    from . import pack as _pack
    from .device_common import KERNEL_ABI, TS_W

    bad = sorted(set(formats) - set(DECODE_FORMATS))
    if bad:
        raise ValueError(f"unknown format(s) {bad} "
                         f"(expected {sorted(DECODE_FORMATS)})")
    bad = sorted(set(families) - set(FAMILIES))
    if bad:
        raise ValueError(f"unknown family(ies) {bad} "
                         f"(expected {sorted(FAMILIES)})")
    if rows_grid is None:
        rows_grid = _pack.shape_bucket_grid(n_buckets, batch_size)
    rows_grid = tuple(sorted({int(r) for r in rows_grid}))
    extras = tuple(tuple(kv) for kv in extras)
    os.makedirs(out_dir, exist_ok=True)
    manifest_path = os.path.join(out_dir, MANIFEST_NAME)
    if os.path.exists(manifest_path):
        with open(manifest_path, "rb") as f:
            manifest = json.load(f)
        if (manifest.get("kernel_abi") != KERNEL_ABI
                or manifest.get("jax_version") != jax.__version__
                or manifest.get("aot_format") != AOT_FORMAT):
            raise RuntimeError(
                f"{manifest_path} was built for kabi="
                f"{manifest.get('kernel_abi')} jax="
                f"{manifest.get('jax_version')}; rebuild into a fresh "
                "directory instead of mixing ABIs")
        if (manifest.get("max_len") != max_len
                or tuple(manifest.get("rows_grid", ())) != rows_grid):
            raise RuntimeError(
                f"{manifest_path} covers max_len="
                f"{manifest.get('max_len')} grid="
                f"{manifest.get('rows_grid')}; pass the same shape "
                "arguments when merging")
    else:
        manifest = {"aot_format": AOT_FORMAT, "kernel_abi": KERNEL_ABI,
                    "jax_version": jax.__version__, "platforms": [],
                    "rows_grid": list(rows_grid), "max_len": max_len,
                    "batch_size": batch_size, "entries": {}}

    suffixes = {}
    for fr in framings:
        if fr not in FRAMINGS:
            raise ValueError(f"unknown framing {fr!r} "
                             f"(expected {sorted(FRAMINGS)})")
        suffixes[FRAMINGS[fr]] = fr
    built = []

    def note(msg):
        if not quiet:
            print(f"aot build: {msg}", file=sys.stderr)

    def add_entry(family, platform, rows, route, fn, example_args,
                  statics):
        spec = args_spec(example_args)
        key = entry_key(family, platform, statics, spec)
        if key in manifest["entries"]:
            note(f"skip {key} (already built)")
            return
        exp = _export_one(fn, example_args, platform)
        blob = exp.serialize()
        fname = key + ".jaxexport"
        with open(os.path.join(out_dir, fname), "wb") as f:
            f.write(blob)
        manifest["entries"][key] = {
            "family": family, "platform": platform, "rows": rows,
            "max_len": max_len, "route": route,
            "statics": canon_statics(statics), "spec": spec,
            "file": fname, "bytes": len(blob),
            "sha256": hashlib.sha256(blob).hexdigest(),
        }
        built.append(key)
        note(f"exported {key} ({len(blob)} bytes)")

    for platform in platforms:
        impl = _scan_impl_for(platform)
        for rows in rows_grid:
            b = jax.ShapeDtypeStruct((rows, max_len), jnp.uint8)
            ln = jax.ShapeDtypeStruct((rows,), jnp.int32)
            yr = jax.ShapeDtypeStruct((), jnp.int32)
            probe_ts = jax.ShapeDtypeStruct((rows, 0), jnp.uint8)
            full_ts = jax.ShapeDtypeStruct((rows, TS_W), jnp.uint8)
            tl = jax.ShapeDtypeStruct((rows,), jnp.int32)
            if "decode" in families:
                for fmt in formats:
                    args = (b, ln, yr) if fmt == "rfc3164" else (b, ln)
                    add_entry(f"decode_{fmt}", platform, rows, fmt,
                              _decode_fn(fmt), args, decode_statics(fmt))
            if "fused" in families:
                for route_name in FUSED_ROUTES:
                    if route_name.split("_", 1)[0] not in formats:
                        continue
                    for suffix in suffixes:
                        for assemble, ts in ((False, probe_ts),
                                             (True, full_ts)):
                            statics = {
                                **fused_statics(route_name, suffix,
                                                impl, extras),
                                "assemble": assemble}
                            args = ((b, ln, yr, ts, tl)
                                    if route_name in ("rfc3164_gelf",
                                                      "rfc3164_rfc5424")
                                    else (b, ln, ts, tl))
                            add_entry(f"fused_{route_name}", platform,
                                      rows, route_name,
                                      _fused_fn(route_name, statics),
                                      args, statics)
            if "framing" in families:
                # device-resident framing: one stage-A span kernel per
                # framing kind + the shared stage-B gather, at this row
                # bucket's assumed byte bucket (~FRAMING_AVG_BYTES per
                # record; other region sizes hit the JIT ladder).  The
                # kernels are small (cumsum/scatter/gather planes), so
                # the full enumeration stays cheap to export.
                from .framing import region_bucket

                rb = region_bucket(rows * FRAMING_AVG_BYTES)
                reg = jax.ShapeDtypeStruct((rb,), jnp.uint8)
                rl = jax.ShapeDtypeStruct((), jnp.int32)
                for kind in FRAMING_KINDS:
                    fst = framing_statics(kind, rows, rb)
                    add_entry(f"framing_{kind}", platform, rows, kind,
                              _framing_fn(kind, fst), (reg, rl), fst)
                gst = framing_statics("gather", max_len, rb)
                sl = jax.ShapeDtypeStruct((rows,), jnp.int32)
                add_entry("framing_gather", platform, rows, "gather",
                          _framing_fn("gather", gst), (reg, sl, sl),
                          gst)
            if "pallas" in families:
                # Pallas structural kernels (PR 20): stage-A spans +
                # stage-B gather + the single-VMEM decode passes.  cpu
                # artifacts embed interpret mode (Mosaic only lowers on
                # accelerators); regions past PALLAS_MAX_REGION get no
                # artifact — the runtime tier disengages there anyway.
                from . import pallas_kernels as _pk
                from .framing import region_bucket

                interp = platform == "cpu"
                rb = region_bucket(rows * FRAMING_AVG_BYTES)
                if rb <= _pk.PALLAS_MAX_REGION:
                    reg = jax.ShapeDtypeStruct((rb,), jnp.uint8)
                    rl = jax.ShapeDtypeStruct((), jnp.int32)
                    for kind in FRAMING_KINDS:
                        pst = {**pallas_statics(kind, rows, rb),
                               "interpret": interp}
                        add_entry(f"pallas_{kind}", platform, rows,
                                  kind, _pallas_fn(kind, pst),
                                  (reg, rl), pst)
                    sl = jax.ShapeDtypeStruct((rows,), jnp.int32)
                    gst = {**pallas_statics("gather", max_len, rb),
                           "interpret": interp}
                    add_entry("pallas_gather", platform, rows,
                              "gather", _pallas_fn("gather", gst),
                              (reg, sl, sl), gst)
                for fmt in ("rfc5424", "jsonl"):
                    if fmt not in formats:
                        continue
                    pst = {**pallas_statics(f"decode_{fmt}", rows, 0),
                           "interpret": interp}
                    add_entry(f"pallas_decode_{fmt}", platform, rows,
                              fmt, _pallas_fn(f"decode_{fmt}", pst),
                              (b, ln), pst)
            if "encode" in families:
                for fmt in formats:
                    # jsonl/dns: no device-encode kernel (empty tuple);
                    # the decode channels are shared by every split
                    # module of this input format
                    dec = None
                    for module in _ENCODE_MODULES_FOR_FMT.get(fmt, ()):
                        for suffix in suffixes:
                            for assemble, ts in ((False, probe_ts),
                                                 (True, full_ts)):
                                if dec is None:
                                    if fmt == "rfc3164":
                                        dec = jax.eval_shape(
                                            _decode_fn(fmt), b, ln, yr)
                                    else:
                                        dec = jax.eval_shape(
                                            _decode_fn(fmt), b, ln)
                                statics = {
                                    **encode_statics(module, suffix,
                                                     impl, extras),
                                    "assemble": assemble}
                                add_entry(module, platform, rows, fmt,
                                          _encode_fn(module, statics),
                                          (b, ln, dec, ts, tl),
                                          statics)
        if platform not in manifest["platforms"]:
            manifest["platforms"].append(platform)

    manifest["platforms"].sort()
    with open(manifest_path, "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    note(f"manifest: {len(manifest['entries'])} entries "
         f"({len(built)} new) -> {manifest_path}")
    if warm:
        # warm EVERY entry, not just this invocation's new ones — a
        # merge into a previously-unwarmed dir must not write a warm
        # marker over cold entries (already-warm ones are cache hits)
        warm_artifacts(out_dir, quiet=quiet, timeout_s=warm_timeout_s)
    elif built:
        # new entries with no warm pass: an existing marker for their
        # platform now overclaims — revoke it so has_warm_cache()
        # cannot suppress prewarm over never-executed programs
        for p in sorted({manifest["entries"][k]["platform"]
                         for k in built}):
            mk = _warm_marker_path(out_dir, p)
            if os.path.exists(mk):
                os.unlink(mk)
                note(f"revoked warm marker for '{p}' (new entries "
                     "are unwarmed; re-run with --warm)")
    return manifest


def _warm_marker_path(out_dir: str, platform: str) -> str:
    """The per-platform warm marker: written only by a skip-free warm
    pass, read by ``AotStore.has_warm_cache`` on the serving host."""
    from .device_common import KERNEL_ABI

    return os.path.join(out_dir, XLA_CACHE_SUBDIR,
                        f"kabi-{KERNEL_ABI}", f"warmed-{platform}")


def warm_artifacts(out_dir: str, keys=None, quiet: bool = False,
                   timeout_s: float = 900.0) -> int:
    """Execute each runnable exported program once with the persistent
    XLA cache pointed at ``<out>/xla-cache`` — after this, a fleet boot
    against the artifact dir performs zero fresh compiles (StableHLO →
    executable is a cache hit).  Only entries for THIS host's platform
    can run (tpu artifacts warm on the first tpu boot instead — no
    runnable entry means no cache is created and no warm marker
    written, so ``has_warm_cache`` stays False on the fleet).  Each
    warm runs under ``timeout_s`` — a wedged XLA compile (this repo's
    documented failure mode) skips that entry with a note instead of
    hanging the build CLI.  The per-platform warm marker is revoked at
    the start of every pass and re-written only by a skip-free pass
    over EVERY entry of this platform (a ``keys=`` subset or an
    errored/killed pass leaves warmth unclaimed).  Returns the number
    of programs warmed."""
    import numpy as np

    import jax
    from jax import export as jexport

    from .device_common import enable_compile_cache

    with open(os.path.join(out_dir, MANIFEST_NAME), "rb") as f:
        manifest = json.load(f)
    platform = jax.default_backend()
    platform_keys = [key
                     for key, entry in sorted(manifest["entries"].items())
                     if entry["platform"] == platform]
    runnable = [(key, manifest["entries"][key]) for key in platform_keys
                if keys is None or key in keys]
    if not runnable:
        if not quiet:
            print(f"aot warm: no runnable entries for platform "
                  f"'{platform}' (cross-platform artifacts warm on "
                  "their own fleet's first boot)", file=sys.stderr)
        return 0
    # warmth is uncertain from here until the pass proves otherwise —
    # an error/kill mid-pass must not leave a stale marker claiming
    # the cache covers entries that never executed
    marker = _warm_marker_path(out_dir, platform)
    if os.path.exists(marker):
        os.unlink(marker)
    # the warm loop must point the process-global persistent cache at
    # the artifact dir — and must put it back: an in-process caller
    # (library use, build-then-serve) would otherwise keep writing
    # every later compile into the shipped artifact set with zeroed
    # persist thresholds (the exact hazard _unpoint_auto_cache guards
    # on the load side)
    old_cache = _snapshot_cache_config()
    enable_compile_cache(os.path.join(out_dir, XLA_CACHE_SUBDIR))
    warmed, skipped = 0, 0
    try:
        for key, entry in runnable:
            with open(os.path.join(out_dir, entry["file"]), "rb") as f:
                exp = jexport.deserialize(f.read())
            leaves = [np.zeros(a.shape, a.dtype) for a in exp.in_avals]
            args, kwargs = jax.tree_util.tree_unflatten(exp.in_tree,
                                                        leaves)
            if not quiet:
                # named BEFORE the call so a wedged compile identifies
                # its entry even if the operator has to kill the build
                print(f"aot warm: {key} ...", file=sys.stderr)
            box: List = [None]

            def _run(exp=exp, args=args, kwargs=kwargs, box=box):
                try:
                    jax.block_until_ready(
                        jax.jit(exp.call)(*args, **kwargs))
                except BaseException as e:  # noqa: BLE001 - ferried to the caller
                    box[0] = e

            t = threading.Thread(target=_run, daemon=True,
                                 name=f"aot-warm:{key}")
            t.start()
            t.join(timeout_s)
            if t.is_alive():
                skipped += 1
                print(f"aot warm: {key} still compiling after "
                      f"{timeout_s:.0f}s; skipping (the fleet pays "
                      "this compile at first boot — prewarm stays on)",
                      file=sys.stderr)
                continue
            if box[0] is not None:
                raise box[0]
            warmed += 1
    finally:
        _restore_cache_config(old_cache)
    if skipped == 0 and len(runnable) == len(platform_keys):
        # only a skip-free pass over EVERY entry of this platform may
        # claim warmth — a keys= subset leaves the rest cold, and
        # has_warm_cache() suppressing prewarm over cold fused/encode
        # programs is exactly the first-batch stall this guards
        with open(marker, "w", encoding="utf-8") as f:
            f.write(f"{warmed}\n")
    return warmed


def validate_artifacts(out_dir: str, quiet: bool = False) -> Dict:
    """Deserialize + hash-verify EVERY entry of EVERY platform (the
    build-only acceptance for platforms this host cannot execute, e.g.
    tpu artifacts exported from a cpu box).  Raises on any failure;
    returns a per-platform/per-family summary."""
    from jax import export as jexport

    with open(os.path.join(out_dir, MANIFEST_NAME), "rb") as f:
        manifest = json.load(f)
    if manifest.get("aot_format") != AOT_FORMAT:
        raise RuntimeError(f"manifest format {manifest.get('aot_format')!r}"
                           f" != {AOT_FORMAT}")
    for field in ("kernel_abi", "jax_version", "rows_grid", "max_len",
                  "platforms", "entries"):
        if field not in manifest:
            raise RuntimeError(f"manifest missing field {field!r}")
    summary: Dict[str, int] = {}
    for key, entry in sorted(manifest["entries"].items()):
        path = os.path.join(out_dir, entry["file"])
        with open(path, "rb") as f:
            blob = f.read()
        if hashlib.sha256(blob).hexdigest() != entry["sha256"]:
            raise RuntimeError(f"{key}: content hash mismatch")
        exp = jexport.deserialize(blob)
        if entry["platform"] not in exp.platforms:
            raise RuntimeError(
                f"{key}: manifest platform {entry['platform']!r} not in "
                f"exported platforms {exp.platforms}")
        nspec = len(entry["spec"])
        if len(exp.in_avals) != nspec:
            raise RuntimeError(
                f"{key}: {len(exp.in_avals)} exported inputs != "
                f"{nspec} in the manifest spec")
        label = f"{entry['platform']}/{entry['family']}"
        summary[label] = summary.get(label, 0) + 1
    if not quiet:
        print(f"aot validate: {len(manifest['entries'])} entries OK "
              f"({json.dumps(summary, sort_keys=True)})", file=sys.stderr)
    return summary


# ---------------------------------------------------------------------------
# legacy single-kernel Pallas relay flow (tools/pallas_aot.py now
# delegates here; the artifact and verbs are unchanged)

_PALLAS_ART = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))),
    "tools", "pallas_rfc5424_tpu.jaxexport")
_PALLAS_SHAPE = (4096, 256, 2, 6)  # N, L, MAX_SD, MAX_PAIRS


def pallas_export(art: str = _PALLAS_ART) -> str:
    import functools

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax import export as jexport

    from . import rfc5424 as R

    n, length, max_sd, max_pairs = _PALLAS_SHAPE
    fn = functools.partial(R.decode_rfc5424_pallas, max_sd=max_sd,
                           max_pairs=max_pairs)
    b = jnp.zeros((n, length), jnp.uint8)
    ln = jnp.zeros((n,), jnp.int32)
    blob = jexport.export(jax.jit(fn), platforms=["tpu"])(b, ln).serialize()
    with open(art, "wb") as f:
        f.write(blob)
    print(f"exported {len(blob)} bytes -> {art}")
    return art


def pallas_run(art: str = _PALLAS_ART) -> int:
    import numpy as np

    import jax

    cache = os.environ.get("FLOWGGER_JAX_CACHE",
                           os.path.expanduser("~/.cache/flowgger_jax"))
    os.makedirs(cache, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache)
    print("devices:", jax.devices())
    import jax.numpy as jnp
    from jax import export as jexport

    from . import rfc5424 as R

    n, length, max_sd, max_pairs = _PALLAS_SHAPE
    with open(art, "rb") as f:
        exp = jexport.deserialize(f.read())
    lines = [
        b'<13>1 2023-09-20T12:35:45.123Z host app 123 MSGID '
        b'[ex@32473 k="v" a="b"] hello world',
        b'<34>1 2003-10-11T22:14:15.003Z mymachine.example.com su - '
        b'ID47 - su root failed',
    ] * (n // 2)
    batch = np.zeros((n, length), np.uint8)
    lens = np.zeros((n,), np.int32)
    for i, s in enumerate(lines[:n]):
        batch[i, :len(s)] = np.frombuffer(s, np.uint8)
        lens[i] = len(s)
    # the rewritten kernel returns the decode channel dict (the old
    # _PALLAS_SHAPE-era artifact was a flat tuple); exp.call restores
    # the output pytree, so compare per key
    out = exp.call(jnp.asarray(batch), jnp.asarray(lens))
    ref = R.decode_rfc5424_jit(jnp.asarray(batch), jnp.asarray(lens),
                               max_sd=max_sd, max_pairs=max_pairs)
    keys = list(R._KEYS_1D) + list(R._KEYS_SD) + list(R._KEYS_PAIR)
    bad = 0
    for k in keys:
        r = np.asarray(ref[k]).astype(np.int64)
        o2 = np.asarray(out[k]).astype(np.int64)
        if o2.ndim == 2 and o2.shape[1] == 1:
            o2 = o2[:, 0]
        if not (o2 == r.reshape(o2.shape)).all():
            bad += 1
            print(f"MISMATCH {k}")
    print("PALLAS AOT DIFFERENTIAL:", "FAIL" if bad else "OK",
          f"({len(keys)} channels)")
    return 1 if bad else 0


# ---------------------------------------------------------------------------
# CLI

def _csv(s: str) -> Tuple[str, ...]:
    return tuple(x.strip() for x in s.split(",") if x.strip())


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m flowgger_tpu.tpu.aot",
        description="AOT kernel artifact pipeline (zero-JIT boot)")
    sub = ap.add_subparsers(dest="verb", required=True)

    b = sub.add_parser("build", help="export the route matrix")
    b.add_argument("--out", required=True)
    b.add_argument("--platforms", default="cpu", type=_csv)
    b.add_argument("--families", default=",".join(FAMILIES), type=_csv)
    b.add_argument("--formats", default=",".join(DECODE_FORMATS),
                   type=_csv)
    b.add_argument("--framings", default="line,nul", type=_csv)
    b.add_argument("--rows", default=None,
                   help="explicit row buckets, e.g. 256,2048 "
                        "(default: --buckets geometric grid)")
    b.add_argument("--buckets", type=int, default=4,
                   help="bucket count for pack.shape_bucket_grid")
    b.add_argument("--batch-size", type=int, default=16384)
    b.add_argument("--max-len", type=int, default=512)
    b.add_argument("--warm", action="store_true",
                   help="execute each runnable program once with the "
                        "XLA cache at <out>/xla-cache")
    b.add_argument("--warm-timeout-s", type=float, default=900.0,
                   help="per-program warm budget; a wedged XLA compile "
                        "skips the entry (and revokes the warm marker) "
                        "instead of hanging the build")

    v = sub.add_parser("validate",
                       help="deserialize + hash-verify every entry")
    v.add_argument("dir")

    p = sub.add_parser("pallas",
                       help="legacy single-kernel Pallas relay flow")
    p.add_argument("mode", choices=("export", "run"))

    args = ap.parse_args(argv)
    if args.verb == "build":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        rows = (tuple(int(r) for r in _csv(args.rows))
                if args.rows else None)
        build_artifacts(args.out, platforms=args.platforms,
                        families=args.families, formats=args.formats,
                        framings=args.framings, rows_grid=rows,
                        n_buckets=args.buckets,
                        batch_size=args.batch_size,
                        max_len=args.max_len, warm=args.warm,
                        warm_timeout_s=args.warm_timeout_s)
        return 0
    if args.verb == "validate":
        validate_artifacts(args.dir)
        return 0
    if args.mode == "export":
        pallas_export()
        return 0
    return pallas_run()


if __name__ == "__main__":
    sys.exit(main())
