"""Device-resident framing: ParPaRaw-style delimiter-parallel record
splitting over raw transport regions (arxiv 1905.13415).

Every device route used to start *after* the host did the slow part:
per-connection splitter threads found record boundaries byte-by-byte
and ``pack.py`` copied each line into the padded arena before a kernel
ever saw data — and the overlap-executor measurements showed those host
stages dominating wall time.  ParPaRaw's observation is that framing
itself is massively parallel: delimiter detection over a raw buffer is
a byte-classification plane plus a prefix sum, exactly the machinery
``tpu/jsonidx.py`` already runs *inside* the decode kernels (simdjson
stage 1, arxiv 1902.08318).  This module lifts it in front of them:

- **stage A (spans)** — ``frame_sep_spans_jit`` (line/nul framing):
  delimiter cumsum over the region + packed-ordinal scatter extraction
  of each record's end; CR strip is an elementwise lookback.
  ``frame_syslen_spans_jit`` (RFC5425 octet counting): the digit-prefix
  *value* at every position comes from a right-to-left weighted suffix
  sum (exact in wrapping int32 arithmetic — each frame's window sum is
  < 1e9, so the mod-2^32 difference of two wrapped cumsum samples is
  the true value), and the data-dependent frame *chain* from offset 0
  resolves with pointer doubling (log2(B) scatter/gather hops) — the
  parallel-scan shape ParPaRaw uses for its escape/quote automata.
- **stage B (pack)** — ``frame_gather_jit``: one [rows, max_len]
  gather from the device-resident region replaces the host arena
  memcpy; the batch never exists host-side.  Only the span *metadata*
  (two i32 vectors, 8 bytes/row) crosses D2H — the block encoders
  splice oversized/fallback rows from the raw region bytes the host
  already owns, exactly like the decode fallback path.

The host-side contract is byte identity with the host splitters
(``pack.split_chunk`` for line/nul, ``splitters._scan_syslen_region``
for syslen): same records, same order, across arbitrary chunk
boundaries.  Anything the kernels cannot express exactly (a syslen
length prefix over 9 digits, span-count overflow) declines the whole
region to the host path — never a divergent answer.

Decline ladder: the first compile per (bytes, rows) shape runs under
the production watchdog (slot ``framing/<framing>``); a timeout or any
device error falls back to the host splitter for that flush (the raw
bytes are still on the host, so no record is ever lost), feeding the
breaker like a decode failure.  ``FramingEconomics`` mirrors
RouteEconomics for the framing-vs-host-pack arm: the device tier
probes first, a slow-measuring one buys host-pack comparison batches,
and the loser re-probes periodically.
"""

from __future__ import annotations

import functools
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import events as _events
from ..utils.metrics import registry as _metrics

SCALAR_ORACLE = "flowgger_tpu.tpu.pack:split_chunk"
DIFF_TEST = (
    "tests/test_framing.py::test_frame_sep_spans_match_host_split",
    "tests/test_framing.py::test_frame_syslen_spans_match_host_scan",
    "tests/test_framing.py::test_raw_ingest_byte_identity_all_framings",
)

_I32 = jnp.int32
# numpy scalar, NOT jnp.int32(...): materializing a device scalar at
# import time costs a jit(convert_element_type) compile in every fresh
# process — the one fresh compile that broke the zero-JIT artifact
# boot's compile_cache_misses == 0 gate (inside traced code a numpy
# int32 scalar folds in identically)
_BIG = np.int32(1 << 30)

# region byte floor (mirrors pack._MIN_BYTES) and the syslen digit-run
# cap the exact-int32 value parse supports; longer prefixes decline the
# region to the host scan, which owns the > 2^31-1 error semantics
MIN_REGION_BYTES = 1 << 14
MAX_PREFIX_DIGITS = 9

# decline hysteresis (same shape as the fused tier's): this many
# watchdog declines in a row put the framing tier on a cooldown of
# host-framed flushes before the next probe
DECLINE_LIMIT = 3
COOLDOWN = 32

_POW10 = tuple(10 ** i for i in range(MAX_PREFIX_DIGITS))


class FramingDeclined(Exception):
    """The device framing tier declined this region (compile watchdog,
    span overflow, or an inexpressible syslen prefix); the caller must
    re-frame on the host path — same bytes, no records lost."""


def region_bucket(nbytes: int) -> int:
    """Padded device size for a raw region: next power of two with a
    floor, so steady-state traffic hits a handful of compiled shapes
    (the same amortization argument as pack's row bucketing)."""
    b = MIN_REGION_BYTES
    while b < nbytes:
        b <<= 1
    return b


def syslen_hops(nbytes: int) -> int:
    """Pointer-doubling iterations that cover every chain in a region
    of ``nbytes``: frame starts strictly increase, so ceil(log2(B+1))
    hops reach any frame head."""
    return max(1, int(nbytes + 1).bit_length())


# ---------------------------------------------------------------------------
# stage A: span kernels
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("sep", "strip_cr", "ncap"))
def frame_sep_spans_jit(region, rlen, sep: int = 10,
                        strip_cr: bool = True, ncap: int = 256):
    """Separator framing spans over ``region[:rlen]`` (u8 [B]).

    Returns starts/lens (orig, CR-stripped) [ncap], n, consumed (one
    past the last separator) and an overflow flag (n > ncap — the
    caller sized ncap from its exact host-side separator count, so
    overflow only means the caller must decline to the host path).
    """
    B = region.shape[0]
    idx = jnp.arange(B, dtype=_I32)
    valid = idx < rlen
    is_sep = (region == jnp.uint8(sep)) & valid
    ordc = jnp.cumsum(is_sep.astype(_I32))
    n = ordc[-1]
    # packed-ordinal extraction: the k-th separator's position scatters
    # into slot k (each ordinal hit exactly once; extras dump past ncap)
    slot = jnp.where(is_sep, jnp.minimum(ordc - 1, ncap), ncap)
    ends = jnp.zeros(ncap + 1, _I32).at[slot].add(
        jnp.where(is_sep, idx, 0))[:ncap]
    k = jnp.arange(ncap, dtype=_I32)
    live = k < n
    prev_end = jnp.concatenate([jnp.full((1,), -1, _I32), ends[:-1]])
    starts = jnp.where(live, prev_end + 1, 0)
    lens = ends - starts
    if strip_cr:
        before = region[jnp.clip(ends - 1, 0, B - 1)]
        has_cr = live & (lens > 0) & (before == jnp.uint8(13))
        lens = lens - has_cr.astype(_I32)
    lens = jnp.where(live, lens, 0)
    consumed = jnp.where(
        n > 0, ends[jnp.clip(n - 1, 0, ncap - 1)] + 1, 0)
    return {"starts": starts, "lens": lens, "n": n,
            "consumed": consumed, "overflow": n > ncap}


@functools.partial(jax.jit, static_argnames=("ncap", "max_hops"))
def frame_syslen_spans_jit(region, rlen, ncap: int = 256,
                           max_hops: int = 15):
    """RFC5425 octet-count framing spans over ``region[:rlen]``.

    Mirrors ``splitters._scan_syslen_region``: frames are
    ``<decimal> <body>`` back to back from offset 0; the scan stops at
    the first incomplete frame (consumed = its start) and ``err`` is
    set when the stop position holds a malformed prefix (a space is
    reachable but the bytes before it are not all digits, or the
    prefix is empty).  ``decline`` flags a reachable prefix longer
    than MAX_PREFIX_DIGITS digits (or span overflow): the value could
    exceed what the int32 parse expresses, so the caller re-frames the
    region on the host, which owns those exact error semantics.
    """
    B = region.shape[0]
    idx = jnp.arange(B, dtype=_I32)
    valid = idx < rlen
    bi = region.astype(_I32)
    is_digit = (bi >= 48) & (bi <= 57) & valid
    is_space = (bi == 32) & valid
    # next space / next non-digit at-or-after each position (reverse
    # cummin lookaheads; positions at/past rlen act as non-digits)
    sp = jax.lax.cummin(jnp.where(is_space, idx, _BIG), axis=0,
                        reverse=True)
    nd = jax.lax.cummin(
        jnp.where(is_digit, _BIG, jnp.minimum(idx, rlen)), axis=0,
        reverse=True)
    has_space = sp < rlen
    prefix_ok = has_space & (nd == sp) & (sp > idx)
    run = jnp.where(prefix_ok, sp - idx, 0)
    too_long = prefix_ok & (run > MAX_PREFIX_DIGITS)
    # digit-prefix value at every position: weight each digit by
    # 10^(distance to its run's space), then difference a right-to-left
    # cumsum.  The full-buffer cumsum may wrap int32, but each frame's
    # window sum is < 1e9, so the wrapped difference is exact.
    exp = jnp.clip(sp - 1 - idx, 0, MAX_PREFIX_DIGITS - 1)
    pow10 = jnp.asarray(_POW10, dtype=_I32)
    w = jnp.where(is_digit & has_space, (bi - 48) * pow10[exp], 0)
    suf = jnp.cumsum(w[::-1])[::-1]
    suf_ext = jnp.concatenate([suf, jnp.zeros(1, _I32)])
    val = suf - suf_ext[jnp.clip(sp, 0, B)]
    body = sp + 1
    nxt = body + val
    frame_ok = prefix_ok & ~too_long & (nxt <= rlen)
    # the frame chain from offset 0, resolved by pointer doubling:
    # jump[p] = next frame start (sentinel B when p heads no complete
    # frame); each hop both propagates the reached set one jump and
    # doubles the jump table, so max_hops = ceil(log2(B+1)) suffices
    jump = jnp.concatenate(
        [jnp.where(frame_ok, jnp.clip(nxt, 0, B), B),
         jnp.full((1,), B, _I32)])
    reach = jnp.zeros(B + 1, bool).at[0].set(True)
    j = jump
    for _ in range(max_hops):
        reach = reach.at[jnp.where(reach, j, B)].max(reach)
        j = j[j]
    heads = reach[:B] & frame_ok
    ordc = jnp.cumsum(heads.astype(_I32))
    n = ordc[-1]
    slot = jnp.where(heads, jnp.minimum(ordc - 1, ncap), ncap)
    starts = jnp.zeros(ncap + 1, _I32).at[slot].add(
        jnp.where(heads, body, 0))[:ncap]
    lens = jnp.zeros(ncap + 1, _I32).at[slot].add(
        jnp.where(heads, val, 0))[:ncap]
    consumed = jnp.max(jnp.where(heads, jnp.clip(nxt, 0, B), 0))
    # error analysis at the chain stop, mirroring the host scan: a
    # reachable space with a non-digit (or empty) prefix before it
    stop = jnp.clip(consumed, 0, B - 1)
    sp_stop = sp[stop]
    nd_stop = nd[stop]
    bad_prefix = (sp_stop < rlen) & ((nd_stop != sp_stop)
                                     | (sp_stop == consumed))
    err = (consumed < rlen) & bad_prefix
    decline = jnp.any(reach[:B] & too_long) | (n > ncap)
    return {"starts": starts, "lens": lens, "n": n,
            "consumed": consumed, "err": err, "decline": decline}


# ---------------------------------------------------------------------------
# stage B: device pack (gather)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("max_len",))
def frame_gather_jit(region, starts, lens, max_len: int = 512):
    """Gather the framed records into a dense [rows, max_len] batch on
    device (the arena copy the host pack used to do), with lens clipped
    to max_len — oversized rows splice later from the host region bytes
    exactly like the decode fallback path."""
    col = jnp.arange(max_len, dtype=_I32)[None, :]
    lens_c = jnp.minimum(lens.astype(_I32), max_len)
    idx = starts.astype(_I32)[:, None] + col
    gathered = region[jnp.clip(idx, 0, region.shape[0] - 1)]
    batch = jnp.where(col < lens_c[:, None], gathered,
                      jnp.uint8(0)).astype(jnp.uint8)
    return batch, lens_c


# ---------------------------------------------------------------------------
# host wrapper: region bytes -> packed tuple
# ---------------------------------------------------------------------------

def _device_put2(arr, device):
    return jax.device_put(arr, device) if device is not None \
        else jnp.asarray(arr)


def _watchdogged(slot: str, fn):
    from .device_common import guarded_compile_call

    return guarded_compile_call(slot, fn)


def _aot_spans(framing: str, statics: dict, args):
    from . import aot

    return aot.framing_call(framing, args, statics)


# per-process decline hysteresis for the Pallas framing tier (one
# namespace per framing kind, separate from the jnp tier's budgets)
_PALLAS_STATE: dict = {}


def _pallas_spans_probe(framing: str, region_dev, rlen, B: int,
                        ncap: int, statics: dict, dev_label: str):
    """Try the single-VMEM Pallas spans kernel; None = declined or
    disengaged (the caller falls to the jnp scatter ladder).  Declines
    ride the framing cooldown ladder under their own namespace."""
    from . import aot as _aot
    from . import pallas_kernels as _pallas

    if not _pallas.framing_engaged(B):
        return None
    pstate = cooldown_state(_PALLAS_STATE, f"pallas:{framing}")
    if in_cooldown(pstate):
        return None
    interp = _pallas.interpret_mode()
    p_statics = _aot.pallas_statics(framing, ncap, B)
    if framing == "syslen":
        pfn = lambda: _pallas.frame_syslen_spans_pallas(  # noqa: E731
            region_dev, rlen, interpret=interp, **p_statics)
    else:
        pfn = lambda: _pallas.frame_sep_spans_pallas(  # noqa: E731
            region_dev, rlen, interpret=interp, **p_statics)

    def stage_a_pallas():
        out = _aot.pallas_call(framing, (region_dev, rlen), p_statics)
        if out is not None:
            return out
        return pfn()

    try:
        out = _watchdogged(
            f"pallas/{framing}:{B}x{ncap}:{dev_label}", stage_a_pallas)
    except Exception as e:  # noqa: BLE001 - decline to the jnp tier, never lose data
        note_decline(pstate)
        _metrics.inc("pallas_declines")
        _events.emit("framing", "pallas_decline", route=framing,
                     detail=f"{type(e).__name__}: {e}",
                     cost=B, cost_unit="region_bytes")
        return None
    note_success(pstate)
    return out


def _pallas_gather_probe(region_dev, starts_dev, lens_dev, B: int,
                         rows: int, max_len: int, dev_label: str):
    """Stage-B analogue of :func:`_pallas_spans_probe`."""
    from . import aot as _aot
    from . import pallas_kernels as _pallas

    if not _pallas.framing_engaged(B):
        return None
    pstate = cooldown_state(_PALLAS_STATE, "pallas:gather")
    if in_cooldown(pstate):
        return None
    interp = _pallas.interpret_mode()
    p_statics = _aot.pallas_statics("gather", max_len, B)

    def stage_b_pallas():
        res = _aot.pallas_call(
            "gather", (region_dev, starts_dev, lens_dev), p_statics)
        if res is not None:
            return res
        return _pallas.frame_gather_pallas(
            region_dev, starts_dev, lens_dev, interpret=interp,
            **p_statics)

    try:
        out = _watchdogged(
            f"pallas/gather:{B}x{rows}x{max_len}:{dev_label}",
            stage_b_pallas)
    except Exception as e:  # noqa: BLE001 - decline to the jnp tier, never lose data
        note_decline(pstate)
        _metrics.inc("pallas_declines")
        _events.emit("framing", "pallas_decline", route="gather",
                     detail=f"{type(e).__name__}: {e}",
                     cost=B, cost_unit="region_bytes")
        return None
    note_success(pstate)
    return out


def _aot_gather(statics: dict, args):
    from . import aot

    return aot.framing_call("gather", args, statics)


def device_frame_region(region: bytes, framing: str, max_len: int,
                        n_records: Optional[int] = None, device=None):
    """Frame one raw region on device and return
    ``(packed, consumed, err)`` with the exact ``pack_*_2d`` packed
    contract — (batch, clipped_lens, chunk, starts, orig_lens, n_real)
    — where batch/clipped_lens are *device-resident* arrays ready to
    chain straight into ``block_submit`` (and the fused programs) with
    no host arena copy.

    ``framing`` is ``line`` / ``nul`` / ``syslen``.  For line/nul the
    caller passes a region ending at its final separator plus the exact
    separator count ``n_records`` (one memchr-speed ``bytes.count``);
    for syslen the kernel itself finds ``consumed`` and ``err``.
    Raises FramingDeclined (compile watchdog, span overflow, or an
    inexpressible syslen prefix) — the caller re-frames on the host.
    Any other exception is a device failure for the breaker.
    """
    from . import pack as _pack
    from .device_common import CompileTimeout

    nbytes = len(region)
    B = region_bucket(nbytes)
    buf = np.zeros(B, dtype=np.uint8)
    if nbytes:
        buf[:nbytes] = np.frombuffer(region, dtype=np.uint8)
    region_dev = _device_put2(buf, device)
    rlen = _device_put2(np.int32(nbytes), device)
    try:
        dev_label = ",".join(sorted(str(d) for d in region_dev.devices()))
    except Exception:  # noqa: BLE001 - older arrays lack .devices()
        dev_label = "default"

    from . import aot as _aot

    # for syslen the space count bounds the span-array width (frames <=
    # spaces: each frame's own delimiter is one); line/nul pass the
    # exact separator count.  Statics come from the ONE recipe the AOT
    # builder also uses (aot.framing_statics), so a loaded artifact and
    # this jit can never drift apart.
    ncap = _pack.bucket_rows(max(n_records or 1, 1))
    statics = _aot.framing_statics(framing, ncap, B)
    if framing == "syslen":
        kfn = lambda: frame_syslen_spans_jit(  # noqa: E731
            region_dev, rlen, **statics)
    else:
        kfn = lambda: frame_sep_spans_jit(  # noqa: E731
            region_dev, rlen, **statics)

    def stage_a():
        out = _aot_spans(framing, statics, (region_dev, rlen))
        if out is not None:
            return out
        return kfn()

    # Pallas tier first: the single-VMEM spans kernel collapses the
    # pointer-doubling scatter ladder to one region read; a decline
    # (lowering failure, watchdog) rides its own cooldown ladder and
    # falls straight to the jnp tier below — same bytes, same output.
    out = _pallas_spans_probe(framing, region_dev, rlen, B, ncap,
                              statics, dev_label)
    slot = f"framing/{framing}:{B}x{ncap}:{dev_label}"
    try:
        if out is None:
            out = _watchdogged(slot, stage_a)
    except CompileTimeout:
        _metrics.inc("framing_declines")
        _events.emit("framing", "framing_decline", route=framing,
                     detail="compile watchdog")
        raise FramingDeclined("compile watchdog") from None
    spans = jax.device_get(out)
    n = int(spans["n"])
    consumed = int(spans["consumed"])
    err = bool(spans.get("err", False))
    if bool(spans.get("overflow", False)) or bool(spans.get("decline",
                                                            False)):
        _metrics.inc("framing_declines")
        _events.emit("framing", "framing_decline", route=framing,
                     detail="span overflow or oversized prefix",
                     cost=nbytes, cost_unit="region_bytes")
        raise FramingDeclined("span overflow or oversized prefix")
    # span metadata is the only D2H on this path: 2 x i32 per slot
    _metrics.inc("framing_span_fetch_bytes", 8 * ncap + 16)

    rows = _pack.bucket_rows(max(n, 1))
    starts_np = np.zeros(rows, dtype=np.int32)
    orig_lens = np.asarray(spans["lens"][:n], dtype=np.int32)
    starts_np[:n] = spans["starts"][:n]
    _pack._note_shape(rows, max_len)

    if rows == ncap and framing != "syslen":
        starts_dev, lens_dev = out["starts"], out["lens"]
    else:
        lens_p = np.zeros(rows, dtype=np.int32)
        lens_p[:n] = orig_lens
        starts_dev = _device_put2(starts_np, device)
        lens_dev = _device_put2(lens_p, device)

    g_statics = _aot.framing_statics("gather", max_len, B)

    def stage_b():
        res = _aot_gather(g_statics, (region_dev, starts_dev, lens_dev))
        if res is not None:
            return res
        return frame_gather_jit(region_dev, starts_dev, lens_dev,
                                max_len=max_len)

    gather_out = _pallas_gather_probe(region_dev, starts_dev, lens_dev,
                                      B, rows, max_len, dev_label)
    gslot = f"framing/gather:{B}x{rows}x{max_len}:{dev_label}"
    try:
        if gather_out is not None:
            batch_dev, lens_c_dev = gather_out
        else:
            batch_dev, lens_c_dev = _watchdogged(gslot, stage_b)
    except CompileTimeout:
        _metrics.inc("framing_declines")
        _events.emit("framing", "framing_decline", route=framing,
                     detail="compile watchdog (gather)")
        raise FramingDeclined("compile watchdog (gather)") from None
    _metrics.inc("framing_rows", n)
    if gather_out is not None:
        # rows that went through the Pallas tier end to end (spans may
        # have too, but the gather is the [rows, max_len] pass that
        # defines the tier's throughput accounting)
        _metrics.inc("pallas_rows", n)
    packed = (batch_dev, lens_c_dev, region, starts_np, orig_lens, n)
    return packed, consumed, err


# ---------------------------------------------------------------------------
# framing-vs-host-pack economics
# ---------------------------------------------------------------------------

class FramingEconomics:
    """Measured seconds/row of the device framing stage vs the host
    split+pack it replaces; ``allow_framing()`` routes each flush to
    the cheaper one with periodic loser re-probes — the RouteEconomics
    pattern applied to the framing arm (on a real accelerator the
    device tier wins and nothing changes; on a CPU backend the native
    memcpy pack usually wins and the tier self-disables, visibly)."""

    MARGIN = 1.5
    ALPHA = 0.4
    OK_SPR = 1e-6  # ~1M rows/s framing needs no host comparison

    def __init__(self, enabled: bool = True, probe_every: int = 256):
        self.enabled = enabled
        self.probe_every = max(2, int(probe_every))
        self._lock = threading.Lock()
        self._spr = {"framing": None, "hostpack": None}
        self._batches = 0
        # journal bookkeeping: device framing is the probe-first
        # default, so the first measured re-route to the host pack (and
        # every flip back) is one economics_switch event
        self._winner = "framing"

    def allow_framing(self) -> bool:
        if not self.enabled:
            return True
        with self._lock:
            dev, host = self._spr["framing"], self._spr["hostpack"]
            self._batches += 1
            if dev is None:
                return True          # no framing sample yet: probe it
            if host is None:
                # healthy device framing never pays the host pack; a
                # slow-measuring one buys one comparison flush
                return dev <= self.OK_SPR
            probe = self._batches % self.probe_every == 0
            if dev > host * self.MARGIN:
                return probe         # framing losing: re-probe on schedule
            if host > dev * self.MARGIN:
                return not probe     # host losing: re-sample on schedule
            return True              # within noise: prefer the device tier

    def observe(self, path: str, rows: int, seconds: float) -> None:
        if not self.enabled or rows <= 0 or path not in self._spr:
            return
        spr = seconds / rows
        flip = None
        with self._lock:
            prev = self._spr[path]
            self._spr[path] = spr if prev is None \
                else prev + self.ALPHA * (spr - prev)
            ewma = self._spr[path]
            dev, host = self._spr["framing"], self._spr["hostpack"]
            if dev is not None and host is not None:
                new = self._winner
                if dev > host * self.MARGIN:
                    new = "hostpack"
                elif host > dev * self.MARGIN:
                    new = "framing"
                if new != self._winner:
                    flip = (self._winner, new,
                            dev if new == "framing" else host,
                            host if new == "framing" else dev)
                    self._winner = new
        # exported unconditionally: when the tier self-disables on a
        # slow backend, these two gauges in /healthz are the operator's
        # signal for WHY device framing stopped engaging
        _metrics.set_gauge(f"framing_{path}_spr", ewma)
        if flip is not None:
            old, new, new_spr, old_spr = flip
            _events.emit(
                "economics", "economics_switch", route="framing",
                detail=f"{old} -> {new} "
                       f"({old}={old_spr:.3g} s/row, {new}={new_spr:.3g})",
                cost=new_spr, cost_unit="s_per_row",
                msg=f"framing economics: {old} -> {new} (measured "
                    f"{new_spr:.3g} s/row vs {old_spr:.3g})")

    def snapshot(self) -> dict:
        with self._lock:
            return {"framing_s_per_row": self._spr["framing"],
                    "hostpack_s_per_row": self._spr["hostpack"],
                    "batches": self._batches}

    @classmethod
    def from_config(cls, config) -> "FramingEconomics":
        enabled = config.lookup_bool(
            "input.tpu_encode_economics",
            "input.tpu_encode_economics must be a boolean", True)
        probe_every = config.lookup_int(
            "input.tpu_encode_probe_every",
            "input.tpu_encode_probe_every must be an integer (batches)",
            256)
        return cls(enabled=enabled, probe_every=probe_every)


def cooldown_state(route_state: dict, framing: str) -> dict:
    """Per-handler decline-hysteresis dict for one framing's device
    tier — its own namespace, so a framing decline never eats the
    decode/encode tiers' decline budgets (fused_routes precedent)."""
    return route_state.setdefault(f"framing:{framing}", {})


def note_decline(state: dict) -> None:
    """Count one watchdog decline; DECLINE_LIMIT in a row starts a
    COOLDOWN of host-framed flushes before the next probe."""
    state["declines"] = state.get("declines", 0) + 1
    if state["declines"] >= DECLINE_LIMIT:
        state["cooldown"] = COOLDOWN
        state["declines"] = 0


def in_cooldown(state: dict) -> bool:
    cd = state.get("cooldown", 0)
    if cd > 0:
        state["cooldown"] = cd - 1
        return True
    return False


def note_success(state: dict) -> None:
    state["declines"] = 0
