"""Shared machinery for columnar block encoders: framing specs, the
scalar-oracle fallback loop, and the splice that interleaves vectorized
tier runs with per-row fallback output in input order.

Every block encoder (GELF, passthrough, ...) produces a contiguous
``final_buf`` for its fast-tier rows plus ``row_off`` boundaries; this
module turns that into an EncodedBlock with the reference's observable
semantics — per-line errors in order (line_splitter.rs:37-54), framing
pre-applied with the pipeline's merger (merger/mod.rs:30-32).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..block import EncodedBlock
from ..encoders import EncodeError
from ..mergers import LineMerger, Merger, NulMerger, SyslenMerger
from .assemble import (
    build_source,
    concat_segments,
    exclusive_cumsum,
    syslen_prefix_segments,
)
from .materialize import _scalar_line, compute_ts


def vals_scratch(vals: np.ndarray, fmt_fn):
    """Deduplicated formatted values: repetitive streams share few
    distinct stamps, and ``fmt_fn`` (json_f64, display_f64,
    unix_to_rfc3339_ms...) is the only per-value Python.  Returns
    (scratch bytes, per-row offsets, per-row lengths)."""
    uniq, inv = np.unique(vals, return_inverse=True)
    strs = [fmt_fn(float(u)).encode("ascii") for u in uniq]
    scratch = b"".join(strs)
    ulen = np.fromiter((len(s) for s in strs), dtype=np.int64,
                       count=len(strs))
    uoff = exclusive_cumsum(ulen)[:-1]
    return scratch, uoff[inv], ulen[inv]


def ts_scratch(out, n: int, ridx: np.ndarray, fmt_fn):
    """vals_scratch over the calendar-channel timestamps."""
    ts = compute_ts({k: np.asarray(v)[:n][ridx]
                     for k, v in out.items()
                     if k in ("days", "sod", "off", "nanos")})
    return vals_scratch(ts, fmt_fn)


def ltsv_extra_blob(extra) -> bytes:
    """Pre-rendered ``ltsv_extra`` pairs, escaped once per config the
    way _LTSVString.insert does (strip leading '_', tab/newline→space,
    ':'→'_' in keys), each pair tab-terminated."""
    parts = []
    for k, v in extra:
        k = k[1:] if k.startswith("_") else k
        k = k.replace("\n", " ").replace("\t", " ").replace(":", "_")
        v = v.replace("\t", " ").replace("\n", " ")
        parts.append(f"{k}:{v}\t".encode("utf-8"))
    return b"".join(parts)


def ltsv_special_screen(chunk_arr: np.ndarray, starts64: np.ndarray,
                        part_start: np.ndarray, nlen: np.ndarray,
                        jmask: np.ndarray):
    """LTSV special-key routing shared by the GELF/capnp/LTSV blocks:
    specials match by NAME (the kernel's *_pos channels only catch the
    last occurrence, but the scalar decoder routes every occurrence of
    a repeated special), so the blocks screen by the first 8 key bytes.
    Returns (special_name [n, P] mask, uniq_ok [n] — False where a
    special name repeats and the row must take the oracle)."""
    n, P = part_start.shape
    key8 = (starts64[:, None, None] + part_start[:, :, None]
            + np.arange(8, dtype=np.int64)[None, None, :])
    km = chunk_arr[np.clip(key8, 0, max(chunk_arr.size - 1, 0))] \
        if chunk_arr.size else np.zeros((n, P, 8), dtype=np.uint8)
    special_name = np.zeros((n, P), dtype=bool)
    uniq_ok = np.ones(n, dtype=bool)
    for word in (b"time", b"host", b"message", b"level"):
        match = jmask & (nlen == len(word))
        for i, ch in enumerate(word[:8]):
            match &= km[:, :, i] == ch
        special_name |= match
        uniq_ok &= match.sum(axis=1) <= 1
    return special_name, uniq_ok


def span_f64_scratch(chunk_bytes: bytes, tsa, tsb, fmt_fn):
    """Dedup parse+format of per-row numeric SPANS in one dict pass
    keyed on the span bytes (repetitive streams share few distinct
    stamps; fmt_fn is the only per-unique Python).  Returns
    (scratch bytes, per-row offsets, per-row lengths)."""
    cache = {}
    pieces = []
    pos = 0
    R = len(tsa)
    off = np.empty(R, dtype=np.int64)
    ln = np.empty(R, dtype=np.int64)
    for i, (a, b) in enumerate(zip(tsa.tolist(), tsb.tolist())):
        key = chunk_bytes[a:b]
        hit = cache.get(key)
        if hit is None:
            txt = fmt_fn(float(key)).encode("ascii")
            hit = (pos, len(txt))
            cache[key] = hit
            pieces.append(txt)
            pos += len(txt)
        off[i] = hit[0]
        ln[i] = hit[1]
    return b"".join(pieces), off, ln


def span_f64_values(chunk_bytes: bytes, tsa, tsb) -> np.ndarray:
    """Dedup parse of per-row numeric spans to f64 values."""
    cache = {}
    out = np.empty(len(tsa), dtype=np.float64)
    for i, (a, b) in enumerate(zip(tsa.tolist(), tsb.tolist())):
        key = chunk_bytes[a:b]
        v = cache.get(key)
        if v is None:
            v = float(key)
            cache[key] = v
        out[i] = v
    return out


def gelf_sorted_pairs(chunk_arr, starts64, cand, is_pair, kabs, key_e,
                      vabs_a, vabs_b, val_t, byte_at, cap: int):
    """Flat pair table in sorted-ORIGINAL-key Record order for the
    GELF-input routes (materialize_gelf routes sorted(obj.keys())).
    Duplicate-key rows drop out of ``cand`` IN PLACE (dict last-wins
    semantics go to the oracle).  Returns (rop_s — ORIGINAL row ids —,
    ns_s stripped name starts so ``'_' + span`` is the final name,
    ne_s, pv_t, pv_a, pv_b)."""
    if not int(is_pair.sum()):
        z = np.zeros(0, dtype=np.int64)
        return z, z, z, z.copy(), z, z
    prow, pcol = np.nonzero(is_pair)
    rop = prow.astype(np.int64)
    ns_abs = kabs[prow, pcol]
    ne_abs = starts64[rop] + key_e[prow, pcol]
    order, dup_rows = sorted_pair_order(chunk_arr, rop, ns_abs, ne_abs,
                                        cap)
    if dup_rows.size:
        cand[dup_rows] = False
        order = order[cand[rop[order]]]
    rop_s = rop[order]
    has_us = byte_at(ns_abs[order]) == ord("_")
    return (rop_s, ns_abs[order] + has_us, ne_abs[order],
            val_t[prow, pcol][order], vabs_a[prow, pcol][order],
            vabs_b[prow, pcol][order])


def ltsv_ts_vals(out, n: int, ridx: np.ndarray, chunk_bytes: bytes,
                 starts64: np.ndarray) -> np.ndarray:
    """Per-row f64 timestamps for ltsv tier rows: rfc3339 rows combine
    the calendar channels; unix-literal rows combine the kernel's exact
    split-integer parse (ts_hi * 1e9 + ts_lo over 10**frac, correctly
    rounded within 2**53); signed or 17+-digit stamps take an exact
    per-row ``float(span)`` (ts_meta bit 16 is "has a sign CHARACTER",
    not "negative")."""
    kind = np.asarray(out["ts_kind"])[:n][ridx]
    ts = compute_ts({k: np.where(kind == 0, np.asarray(v)[:n][ridx], 0)
                     for k, v in out.items()
                     if k in ("days", "sod", "off", "nanos")})
    fl = np.flatnonzero(kind == 1)
    if fl.size:
        hi = np.asarray(out["ts_hi"])[:n][ridx][fl].astype(np.float64)
        lo = np.asarray(out["ts_lo"])[:n][ridx][fl].astype(np.float64)
        meta = np.asarray(out["ts_meta"])[:n][ridx][fl].astype(np.int64)
        frac = meta & 255
        ndig = (meta >> 8) & 255
        signed = ((meta >> 16) & 1) == 1
        fv = (hi * 1e9 + lo) / np.power(10.0, frac)
        wide = np.flatnonzero(
            signed | (ndig > 16)
            | ((ndig == 16)
               & ((hi > 9007199.0)
                  | ((hi == 9007199.0) & (lo > 254740992.0)))))
        if wide.size:
            st_fl = starts64[ridx][fl]
            tsa = (st_fl + np.asarray(out["ts_start"])[:n][ridx][fl]
                   ).astype(np.int64)
            tsb = (st_fl + np.asarray(out["ts_end"])[:n][ridx][fl]
                   ).astype(np.int64)
            for w in wide.tolist():
                fv[w] = float(chunk_bytes[tsa[w]:tsb[w]])
        ts[fl] = fv
    return ts


def sorted_pair_order(chunk_arr: np.ndarray, rop: np.ndarray,
                      ns_abs: np.ndarray, ne_abs: np.ndarray, cap: int):
    """Sort a flat pair table by (row, name bytes) and detect duplicate
    names within a row.

    Sort keys are the name bytes packed big-endian into uint64 words via
    a contiguous view, width adapting to the batch's longest name (the
    caller guarantees names <= ``cap`` bytes).  Returns (order indices,
    duplicate-row ids) — callers drop duplicate rows to the scalar
    oracle for dict last-wins semantics, or handle them natively."""
    max_name = int((ne_abs - ns_abs).max(initial=0))
    K = max(8, min(cap, -(-max_name // 8) * 8))
    gidx = (ns_abs[:, None]
            + np.arange(K, dtype=np.int64)[None, :]).astype(np.int32)
    nm = np.where(gidx < ne_abs[:, None].astype(np.int32),
                  chunk_arr[np.minimum(gidx, chunk_arr.size - 1)],
                  np.uint8(0))
    words = np.ascontiguousarray(nm).view(">u8")
    order = np.lexsort(tuple(words[:, w] for w in range(K // 8 - 1, -1, -1))
                       + (rop,))
    srop = rop[order]
    swords = words[order]
    dup = (srop[1:] == srop[:-1]) & (swords[1:] == swords[:-1]).all(axis=1)
    dup_rows = np.unique(srop[1:][dup]) if dup.any() else np.zeros(
        0, dtype=rop.dtype)
    return order, dup_rows


def syslen_prefix_lens_from_framed(framed_lens: np.ndarray) -> np.ndarray:
    """Per-row syslen prefix width recovered from framed lengths (the
    native row writers emit the prefix inline, so only the total framed
    length comes back): the unique d with
    decimal_digits(framed - d - 1) == d, plus one for the space."""
    from .assemble import _DEC_WIDTH

    plens = np.zeros(framed_lens.size, dtype=np.int64)
    pow10 = 10 ** np.arange(1, _DEC_WIDTH, dtype=np.int64)
    for d in range(1, _DEC_WIDTH + 1):
        body = framed_lens - d - 1
        ndig = 1 + (body[:, None] >= pow10[None, :]).sum(axis=1)
        plens = np.where((plens == 0) & (ndig == d), d + 1, plens)
    return plens


def apply_syslen_prefix(body: np.ndarray, row_off: np.ndarray,
                        tier_lens: np.ndarray):
    """Prepend the syslen length prefix per row via one more segment
    gather.  The rows in ``body`` must already carry their trailing
    newline (the framed length value counts payload + '\\n',
    syslen_merger.rs:14-31).  Returns (final_buf bytes, new row_off,
    prefix_lens)."""
    deco, _ = build_source(b"0123456789 ")
    src2 = np.concatenate([body, deco])
    psrc, plen, prefix_lens = syslen_prefix_segments(tier_lens,
                                                     int(body.size))
    seg_src = np.concatenate([psrc, row_off[:-1, None]], axis=1).ravel()
    seg_len = np.concatenate([plen, tier_lens[:, None]], axis=1).ravel()
    out = concat_segments(src2, seg_src, seg_len)
    return out.tobytes(), exclusive_cumsum(tier_lens + prefix_lens), prefix_lens


class BlockResult:
    """The block plus per-row errors, in input order.

    ``emit`` marks which input rows produced a message (the block's
    bounds align with ``emit``'s True positions) and ``error_rows``
    carries the input-row index of each error — both are what the
    auto-detect merger needs to interleave per-class blocks back into
    input order."""

    __slots__ = ("block", "errors", "fallback_rows", "emit", "error_rows")

    def __init__(self, block: EncodedBlock, errors: List[Tuple[str, str]],
                 fallback_rows: int, emit=None, error_rows=None):
        self.block = block
        self.errors = errors
        self.fallback_rows = fallback_rows
        self.emit = emit
        self.error_rows = error_rows


def extra_forms(k: str, v: str) -> Tuple[bytes, bytes, bytes]:
    """The three boundary renderings of one gelf_extra pair, shared by
    every layout's slot folder (encode_gelf_block / _rfc3164 / _ltsv):
    ``self`` (before a key: fully quoted + trailing comma),
    ``string-close`` (after an unclosed string value: leading ``",``
    closes it, own closing quote supplied by the next constant), and
    ``after-number`` (after a bare number or self-closed value:
    self-contained with a leading comma)."""
    from json.encoder import encode_basestring as _quote

    kq = _quote(k).encode("utf-8")
    vq = _quote(v).encode("utf-8")
    return (kq + b":" + vq + b",",
            b'",' + kq + b":" + vq[:-1],
            b"," + kq + b":" + vq)


def extra_tail(default: bytes, tv: bytes, vz: bytes) -> bytes:
    """Rebuild the ``,"version":"1.1"}`` tail with extras before/after
    the version key (tv: after-number form, vz: string-close form)."""
    if not (tv or vz):
        return default
    return tv + b',"version":"1.1' + vz + b'"}'


def merger_suffix(merger: Optional[Merger]) -> Optional[Tuple[bytes, bool]]:
    """(suffix bytes, needs syslen prefix) or None if the merger type is
    not block-encodable."""
    if merger is None:
        return b"", False
    t = type(merger)
    if t is LineMerger:
        return b"\n", False
    if t is NulMerger:
        return b"\0", False
    if t is SyslenMerger:
        return b"\n", True
    return None


def finish_block(
    chunk_bytes: bytes,
    starts64: np.ndarray,
    lens64: np.ndarray,
    n: int,
    cand: np.ndarray,
    ridx: np.ndarray,
    final_buf: bytes,
    row_off: np.ndarray,
    prefix_lens_tier: Optional[np.ndarray],
    suffix: bytes,
    syslen: bool,
    merger: Optional[Merger],
    encoder,
    scalar_fn=_scalar_line,
) -> BlockResult:
    """Fallback rows through the scalar oracle (``scalar_fn``, the
    rfc5424 one by default), splice in input order, compute message
    bounds; returns the BlockResult."""
    errors: List[Tuple[str, str]] = []
    row_bytes_len = np.zeros(n, dtype=np.int64)
    emit = np.zeros(n, dtype=bool)
    if ridx.size:
        row_bytes_len[ridx] = np.diff(row_off)
        emit[ridx] = True

    fb_idx = np.flatnonzero(~cand)
    fallback_payload: Dict[int, bytes] = {}
    fb_prefix: Dict[int, int] = {}
    fallback_rows = 0  # parity with the per-row path: utf8 errors excluded
    error_rows: List[int] = []
    for i in fb_idx.tolist():
        s = int(starts64[i])
        ln = int(lens64[i])
        raw = chunk_bytes[s:s + ln]
        try:
            line = raw.decode("utf-8")
        except UnicodeDecodeError:
            errors.append(("__utf8__", ""))
            error_rows.append(i)
            continue
        fallback_rows += 1
        res = scalar_fn(line)
        if res.record is None:
            errors.append((res.error, line))
            error_rows.append(i)
            continue
        try:
            payload = encoder.encode(res.record)
        except EncodeError as e:
            errors.append((str(e), line))
            error_rows.append(i)
            continue
        framed_b = merger.frame(payload) if merger is not None else payload
        fallback_payload[i] = framed_b
        fb_prefix[i] = len(framed_b) - len(payload) - len(suffix)
        row_bytes_len[i] = len(framed_b)
        emit[i] = True

    # splice tier runs and fallback rows in input order: fb_idx is
    # exactly the non-tier rows, so every gap between consecutive
    # fallback rows is a contiguous run of tier rows whose bytes are
    # already contiguous in final_buf — one slice per run.
    if fb_idx.size:
        pieces: List[bytes] = []
        tpos = np.cumsum(cand) - 1  # tier ordinal per row
        prev = 0
        for i in fb_idx.tolist():
            if i > prev:
                pieces.append(
                    final_buf[int(row_off[tpos[prev]]):
                              int(row_off[tpos[i - 1] + 1])])
            fp = fallback_payload.get(i)
            if fp is not None:
                pieces.append(fp)
            prev = i + 1
        if prev < n:
            pieces.append(final_buf[int(row_off[tpos[prev]]):])
        data = b"".join(pieces)
    else:
        data = final_buf

    bounds = exclusive_cumsum(row_bytes_len[emit])
    prefix_lens = None
    if syslen:
        prefix_lens = np.zeros(n, dtype=np.int64)
        if prefix_lens_tier is not None:
            prefix_lens[ridx] = prefix_lens_tier
        for i, v in fb_prefix.items():
            prefix_lens[i] = v
        prefix_lens = prefix_lens[emit]

    block = EncodedBlock(data, bounds, prefix_lens, len(suffix))
    return BlockResult(block, errors, fallback_rows, emit=emit,
                       error_rows=error_rows)
