"""Shared machinery for columnar block encoders: framing specs, the
scalar-oracle fallback loop, and the splice that interleaves vectorized
tier runs with per-row fallback output in input order.

Every block encoder (GELF, passthrough, ...) produces a contiguous
``final_buf`` for its fast-tier rows plus ``row_off`` boundaries; this
module turns that into an EncodedBlock with the reference's observable
semantics — per-line errors in order (line_splitter.rs:37-54), framing
pre-applied with the pipeline's merger (merger/mod.rs:30-32).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..block import EncodedBlock
from ..encoders import EncodeError
from ..mergers import LineMerger, Merger, NulMerger, SyslenMerger
from .assemble import exclusive_cumsum
from .materialize import _scalar_line


class BlockResult:
    """The block plus per-row errors, in input order."""

    __slots__ = ("block", "errors", "fallback_rows")

    def __init__(self, block: EncodedBlock, errors: List[Tuple[str, str]],
                 fallback_rows: int):
        self.block = block
        self.errors = errors
        self.fallback_rows = fallback_rows


def merger_suffix(merger: Optional[Merger]) -> Optional[Tuple[bytes, bool]]:
    """(suffix bytes, needs syslen prefix) or None if the merger type is
    not block-encodable."""
    if merger is None:
        return b"", False
    t = type(merger)
    if t is LineMerger:
        return b"\n", False
    if t is NulMerger:
        return b"\0", False
    if t is SyslenMerger:
        return b"\n", True
    return None


def finish_block(
    chunk_bytes: bytes,
    starts64: np.ndarray,
    lens64: np.ndarray,
    n: int,
    cand: np.ndarray,
    ridx: np.ndarray,
    final_buf: bytes,
    row_off: np.ndarray,
    prefix_lens_tier: Optional[np.ndarray],
    suffix: bytes,
    syslen: bool,
    merger: Optional[Merger],
    encoder,
) -> BlockResult:
    """Fallback rows through the scalar oracle, splice in input order,
    compute message bounds; returns the BlockResult."""
    errors: List[Tuple[str, str]] = []
    row_bytes_len = np.zeros(n, dtype=np.int64)
    emit = np.zeros(n, dtype=bool)
    if ridx.size:
        row_bytes_len[ridx] = np.diff(row_off)
        emit[ridx] = True

    fb_idx = np.flatnonzero(~cand)
    fallback_payload: Dict[int, bytes] = {}
    fb_prefix: Dict[int, int] = {}
    fallback_rows = 0  # parity with the per-row path: utf8 errors excluded
    for i in fb_idx.tolist():
        s = int(starts64[i])
        ln = int(lens64[i])
        raw = chunk_bytes[s:s + ln]
        try:
            line = raw.decode("utf-8")
        except UnicodeDecodeError:
            errors.append(("__utf8__", ""))
            continue
        fallback_rows += 1
        res = _scalar_line(line)
        if res.record is None:
            errors.append((res.error, line))
            continue
        try:
            payload = encoder.encode(res.record)
        except EncodeError as e:
            errors.append((str(e), line))
            continue
        framed_b = merger.frame(payload) if merger is not None else payload
        fallback_payload[i] = framed_b
        fb_prefix[i] = len(framed_b) - len(payload) - len(suffix)
        row_bytes_len[i] = len(framed_b)
        emit[i] = True

    # splice tier runs and fallback rows in input order: fb_idx is
    # exactly the non-tier rows, so every gap between consecutive
    # fallback rows is a contiguous run of tier rows whose bytes are
    # already contiguous in final_buf — one slice per run.
    if fb_idx.size:
        pieces: List[bytes] = []
        tpos = np.cumsum(cand) - 1  # tier ordinal per row
        prev = 0
        for i in fb_idx.tolist():
            if i > prev:
                pieces.append(
                    final_buf[int(row_off[tpos[prev]]):
                              int(row_off[tpos[i - 1] + 1])])
            fp = fallback_payload.get(i)
            if fp is not None:
                pieces.append(fp)
            prev = i + 1
        if prev < n:
            pieces.append(final_buf[int(row_off[tpos[prev]]):])
        data = b"".join(pieces)
    else:
        data = final_buf

    bounds = exclusive_cumsum(row_bytes_len[emit])
    prefix_lens = None
    if syslen:
        prefix_lens = np.zeros(n, dtype=np.int64)
        if prefix_lens_tier is not None:
            prefix_lens[ridx] = prefix_lens_tier
        for i, v in fb_prefix.items():
            prefix_lens[i] = v
        prefix_lens = prefix_lens[emit]

    block = EncodedBlock(data, bounds, prefix_lens, len(suffix))
    return BlockResult(block, errors, fallback_rows)
