"""Batched TPU decode tier.

The reference parses each log line with branch-heavy per-line scalar code
(decoder/rfc5424_decoder.rs hot loop, splitter/line_splitter.rs:44-54).
This tier replaces that with columnar, fixed-shape decoding: N lines are
packed into a ``[N, L]`` uint8 tensor and parsed entirely with
data-parallel primitives (cumulative sums for field segmentation,
backslash-run parity + prefix-XOR for quote semantics, ``top_k`` for
k-th-delimiter extraction) that XLA maps onto the TPU's vector units —
no sequential NFA, no data-dependent control flow.

Correctness contract: rows the kernel marks ``ok`` decode *identically*
to the scalar oracle (differential-tested); anything structurally
unusual sets a per-row fallback flag and is re-decoded by the scalar
path, so the pipeline's observable behavior — including per-line error
messages — is byte-identical with the reference's semantics.
"""

import os


def apply_platform_env() -> None:
    """Re-assert the user's ``JAX_PLATFORMS`` choice on the live config.

    Some site installs (the axon TPU relay plugin) override the platform
    list from ``sitecustomize`` at interpreter start, clobbering the
    environment variable the operator set.  Called before the first
    kernel dispatch so ``JAX_PLATFORMS=cpu python -m flowgger_tpu ...``
    behaves as written even under such plugins."""
    want = os.environ.get("JAX_PLATFORMS", "")
    if not want:
        return
    import jax

    try:
        if jax.config.jax_platforms != want:
            jax.config.update("jax_platforms", want)
    except Exception:  # noqa: BLE001 - platform pinning is best-effort
        pass
