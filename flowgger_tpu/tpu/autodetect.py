"""Mixed-format auto-detect dispatch (BASELINE.json config #5).

``input.format = "auto_tpu"`` accepts a stream mixing RFC5424, RFC3164,
LTSV, and GELF records.  Each batch is partitioned by a cheap first-bytes
signature and every class is decoded by its columnar kernel (RFC3164
rows go through the tpu/rfc3164.py standard-layout fast path, with the
lenient cases falling back to the scalar decoder per row); results
reassemble in input order, so downstream ordering matches a
single-format run.

Signature rules (on the first bytes only):
- ``{``                      → GELF JSON
- ``<digits>1␣`` (opt. BOM)  → RFC5424 (version tag after the PRI)
- ``<``            otherwise → RFC3164
- TAB and ``:``  in the line → LTSV
- anything else              → RFC3164 (the lenient legacy decoder —
  also the reference's catch-all behavior class)

``input.auto_extra_formats`` (a list; default empty, so existing auto
streams classify exactly as before) opts extra legs in:
- ``"jsonl"`` re-routes the ``{`` signature to the generic JSON-lines
  leg (tpu/jsonl.py) instead of GELF — the two dialects share the
  byte signature, so the key picks which decoder owns it;
- ``"dns"`` adds, ahead of the LTSV rule, exactly-five-tabs lines
  whose first field is a unix timestamp (``digits[.digits]``) — the
  dnstap-TSV signature (tpu/dns.py).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..config import Config, ConfigError
from ..decoders.ltsv import LTSVDecoder
from .materialize import LineResult

F_RFC5424, F_RFC3164, F_LTSV, F_GELF, F_JSONL, F_DNS = 0, 1, 2, 3, 4, 5

_EXTRA_FORMATS = ("jsonl", "dns")


def auto_extra_formats(config: Config) -> Tuple[str, ...]:
    """The validated ``input.auto_extra_formats`` list (empty tuple =
    the classic four-class table)."""
    v = config.lookup("input.auto_extra_formats")
    if v is None:
        return ()
    if (not isinstance(v, list)
            or any(not isinstance(x, str) for x in v)):
        raise ConfigError(
            "input.auto_extra_formats must be a list of strings")
    bad = sorted(set(v) - set(_EXTRA_FORMATS))
    if bad:
        raise ConfigError(
            f"input.auto_extra_formats: unknown format(s) {bad} "
            f"(expected a subset of {list(_EXTRA_FORMATS)})")
    return tuple(x for x in _EXTRA_FORMATS if x in v)


def _dns_signature(b: bytes) -> bool:
    """Exactly five tabs and a ``digits[.digits]`` first field — the
    dnstap-TSV shape (decoders/dns.py grammar)."""
    if b.count(b"\t") != 5:
        return False
    head = b.split(b"\t", 1)[0]
    if not head:
        return False
    whole, dot, frac = head.partition(b".")
    if not whole.isdigit():
        return False
    return not dot or frac.isdigit()


def classify(raw: bytes, extras: Tuple[str, ...] = ()) -> int:
    b = raw
    if b.startswith(b"\xef\xbb\xbf"):
        b = b[3:]
    if b.startswith(b"{"):
        return F_JSONL if "jsonl" in extras else F_GELF
    if b.startswith(b"<"):
        gt = b.find(b">", 1, 6)
        if gt > 1 and b[gt + 1:gt + 3] == b"1 " and b[1:gt].isdigit():
            return F_RFC5424
        return F_RFC3164
    # the dns signature checks the RAW bytes (no BOM strip): a BOM'd
    # first field is not a clean unix timestamp — DNSDecoder would
    # reject it anyway — and the vectorized overlay (_extras_adjust)
    # reads the packed rows unstripped, so the two classifiers must
    # agree byte-for-byte on such rows
    if "dns" in extras and _dns_signature(raw):
        return F_DNS
    if b"\t" in b and b":" in b:
        return F_LTSV
    return F_RFC3164


def classify_device(batch, lens):
    """The ``classify`` decision table as a device kernel: ~2 fused
    passes over the packed [N, L] batch (vs ~6 numpy passes host-side).
    Returns an int8 class-code vector.  Rows are classified on their
    clipped bytes — callers re-classify clip-overflow rows from the raw
    chunk exactly like the host path."""
    import jax
    import jax.numpy as jnp

    from .rfc5424 import _shift_left

    N, L = batch.shape
    lens = lens.astype(jnp.int32)
    iota = jax.lax.broadcasted_iota(jnp.int32, (N, L), 1)
    valid = iota < lens[:, None]
    bb = jnp.where(valid, batch, jnp.uint8(0))
    bom = ((lens >= 3) & (bb[:, 0] == 0xEF) & (bb[:, 1] == 0xBB)
           & (bb[:, 2] == 0xBF))
    G = jnp.where(bom[:, None], _shift_left(bb, 3, 0), bb)

    g0 = G[:, 0]
    is_gelf = g0 == ord("{")
    is_lt = g0 == ord("<")
    gt = jnp.zeros_like(lens)
    for j in (2, 3, 4, 5):
        gt = jnp.where((gt == 0) & (G[:, j] == ord(">")), j, gt)
    digits_ok = jnp.ones_like(is_lt)
    for j in (1, 2, 3, 4):
        within = j < gt
        dig = (G[:, j] >= 48) & (G[:, j] <= 57)
        digits_ok &= ~within | dig
    v1 = jnp.zeros_like(g0)
    v2 = jnp.zeros_like(g0)
    for j in (2, 3, 4, 5):
        sel = gt == j
        v1 = jnp.where(sel, G[:, j + 1], v1)
        v2 = jnp.where(sel, G[:, j + 2] if j + 2 < L else 0, v2)
    is5424 = (is_lt & (gt >= 2) & digits_ok
              & (v1 == ord("1")) & (v2 == 32))
    has_tab = jnp.any((bb == 9), axis=1)
    has_col = jnp.any((bb == 58), axis=1)

    cls = jnp.full((N,), F_RFC3164, jnp.int8)
    cls = jnp.where(has_tab & has_col, jnp.int8(F_LTSV), cls)
    cls = jnp.where(is_lt, jnp.int8(F_RFC3164), cls)
    cls = jnp.where(is5424, jnp.int8(F_RFC5424), cls)
    cls = jnp.where(is_gelf, jnp.int8(F_GELF), cls)
    return cls


_CLASSIFY_JIT = None


def _classify_device_jit(batch, lens):
    global _CLASSIFY_JIT
    if _CLASSIFY_JIT is None:
        import jax

        _CLASSIFY_JIT = jax.jit(classify_device)
    return _CLASSIFY_JIT(batch, lens)


def _extras_adjust(cls, batch, lens, n, extras) -> None:
    """Overlay the opt-in extra legs onto a base four-class vector, in
    the same precedence order as ``classify``: the ``{`` signature
    re-labels to jsonl, and the dns TSV signature (checked before the
    LTSV rule, i.e. it may override an LTSV/RFC3164 base class but
    never a ``{``/``<`` one) re-labels to dns.  Vectorized numpy over
    the packed rows; clip-overflow rows are re-classified from their
    raw bytes by the caller either way."""
    import numpy as np

    if "jsonl" in extras:
        cls[cls == F_GELF] = F_JSONL
    if "dns" in extras:
        b = batch[:n]
        L = b.shape[1]
        valid = np.arange(L)[None, :] < np.asarray(lens)[:n, None]
        is_tab = (b == 9) & valid
        five = is_tab.sum(axis=1) == 5
        ft = np.where(is_tab, np.arange(L)[None, :], L).min(axis=1)
        in_head = (np.arange(L)[None, :] < ft[:, None]) & valid
        is_digit = (b >= 48) & (b <= 57)
        is_dot = b == ord(".")
        junk = np.any(in_head & ~is_digit & ~is_dot, axis=1)
        dots = (in_head & is_dot).sum(axis=1)
        dot_edge = np.any(in_head & is_dot
                          & ((np.arange(L)[None, :] == 0)
                             | (np.arange(L)[None, :]
                                == (ft - 1)[:, None])), axis=1)
        dns = five & (ft >= 1) & ~junk & (dots <= 1) & ~dot_edge
        # a '{'/'<' first byte took its own branch before the dns rule
        dns &= (cls == F_LTSV) | (cls == F_RFC3164)
        dns &= (b[:, 0] != ord("<")) & (b[:, 0] != ord("{"))
        cls[dns] = F_DNS


def classify_packed(packed, sharded=None, extras=()) -> "np.ndarray":
    """First-bytes classification of the packed batch — the same
    decision table as ``classify`` with no per-line Python: the device
    kernel above for real batches, numpy host fallback for tiny or
    pathological geometries.  Rows longer than max_len are
    re-classified from their raw bytes (their tab/colon signature may
    lie beyond the clip).  ``sharded`` (a ShardedDecode built for
    "classify") spreads the kernel over the device mesh.  ``extras``
    (input.auto_extra_formats) overlays the opt-in jsonl/dns legs on
    the vectorized paths."""
    import numpy as np

    batch, lens, chunk, starts, orig_lens, n = packed
    L = batch.shape[1]
    if n == 0:
        return np.zeros(0, dtype=np.int8)
    if L >= 19 and n >= 512:
        import jax.numpy as jnp

        if sharded is not None:
            cls = np.asarray(
                sharded.fn(*sharded.put(batch[:n], lens[:n])))[:n].copy()
        else:
            cls = np.asarray(_classify_device_jit(
                jnp.asarray(batch[:n]), jnp.asarray(lens[:n]))).copy()
        if extras:
            _extras_adjust(cls, batch, lens, n, extras)
        over = np.flatnonzero(np.asarray(orig_lens)[:n] > L)
        for i in over.tolist():
            s = int(np.asarray(starts)[i])
            ln = int(np.asarray(orig_lens)[i])
            cls[i] = classify(chunk[s:s + ln], extras)
        return cls
    if L < 19:
        # pathological max_len: classify from the unclipped chunk bytes
        st = np.asarray(starts)
        ol = np.asarray(orig_lens)
        return np.fromiter(
            (classify(chunk[int(st[i]):int(st[i]) + int(ol[i])], extras)
             for i in range(n)),
            dtype=np.int8, count=n)

    head = batch[:n, :19]
    bom = ((head[:, 0] == 0xEF) & (head[:, 1] == 0xBB)
           & (head[:, 2] == 0xBF))
    G = np.where(bom[:, None], batch[:n, 3:19], head[:, :16])

    b0 = G[:, 0]
    is_gelf = b0 == ord("{")
    is_lt = b0 == ord("<")
    # first '>' at offset 2..5 (classify: find('>', 1, 6) with gt > 1)
    gt = np.zeros(n, dtype=np.int64)
    for j in (2, 3, 4, 5):
        gt = np.where((gt == 0) & (G[:, j] == ord(">")), j, gt)
    digits_ok = np.ones(n, dtype=bool)
    for j in (1, 2, 3, 4):
        within = j < gt
        dig = (G[:, j] >= 48) & (G[:, j] <= 57)
        digits_ok &= ~within | dig
    rows = np.arange(n)
    v1 = G[rows, gt + 1]
    v2 = G[rows, gt + 2]
    is5424 = is_lt & (gt >= 2) & digits_ok & (v1 == ord("1")) & (v2 == 32)
    has_tab = (batch[:n] == 9).any(axis=1)
    has_col = (batch[:n] == 58).any(axis=1)

    cls = np.full(n, F_RFC3164, dtype=np.int8)
    cls[has_tab & has_col] = F_LTSV
    cls[is_lt] = F_RFC3164
    cls[is5424] = F_RFC5424
    cls[is_gelf] = F_GELF
    if extras:
        _extras_adjust(cls, batch, lens, n, extras)

    over = np.flatnonzero(np.asarray(orig_lens)[:n] > L)
    for i in over.tolist():
        s = int(np.asarray(starts)[i])
        ln = int(np.asarray(orig_lens)[i])
        cls[i] = classify(chunk[s:s + ln], extras)
    return cls


def _class_table(extras: Tuple[str, ...]):
    table = [(F_RFC5424, "rfc5424"), (F_RFC3164, "rfc3164"),
             (F_LTSV, "ltsv"), (F_GELF, "gelf")]
    if "jsonl" in extras:
        table.append((F_JSONL, "jsonl"))
    if "dns" in extras:
        table.append((F_DNS, "dns"))
    return table


def decode_auto_packed(packed, max_len: int,
                       ltsv_decoder: Optional[LTSVDecoder] = None,
                       extras: Tuple[str, ...] = ()
                       ) -> List[LineResult]:
    """Partition a packed batch by vectorized class signature, run each
    class's columnar kernel on a row subset, and reassemble results in
    input order (BASELINE config #5, zero per-line Python pre-kernel)."""
    import numpy as np

    from . import pack as packmod
    from .batch import _decode_packed

    if ltsv_decoder is None:
        ltsv_decoder = LTSVDecoder(Config.from_string(""))
    n = packed[5]
    classes = classify_packed(packed, extras=extras)
    results: List[LineResult] = [None] * n  # type: ignore
    for cls, fmt in _class_table(extras):
        idx = np.flatnonzero(classes == cls)
        if not idx.size:
            continue
        sub = packmod.subset_packed(packed, idx)
        res = _decode_packed(fmt, sub,
                             ltsv_decoder if fmt == "ltsv" else None)
        for i, r in zip(idx.tolist(), res):
            results[i] = r
    return results


def decode_auto_batch(lines: List[bytes], max_len: int,
                      ltsv_decoder: Optional[LTSVDecoder] = None,
                      extras: Tuple[str, ...] = ()
                      ) -> List[LineResult]:
    """List-of-lines entry: pack once, then the packed auto route."""
    from . import pack as packmod

    return decode_auto_packed(packmod.pack_lines_2d(lines, max_len),
                              max_len, ltsv_decoder, extras)


def encode_auto_gelf_blocks(packed, encoder, merger, ltsv_decoder=None,
                            route_state=None, sharded_for=None,
                            extras=()):
    """Block-encode a mixed batch: classify, submit every class's kernel
    (device work for independent classes overlaps via JAX async
    dispatch), run each class's columnar encode route — GELF, capnp,
    LTSV, or RFC5424, all four classes support each (round 5) — on its
    row subset, and merge the per-class buffers back into input order
    with one segment gather.  Returns a BlockResult or None when any
    leg is inapplicable (typed ltsv_schema, gelf_extra, unsupported
    merger) — the caller then uses the Record path."""
    import numpy as np

    from ..block import EncodedBlock
    from ..encoders.gelf import GelfEncoder
    from .assemble import concat_segments, exclusive_cumsum
    from .block_common import BlockResult, merger_suffix
    from . import pack as packmod
    from .batch import block_fetch_encode, block_submit

    if ltsv_decoder is None:
        ltsv_decoder = LTSVDecoder(Config.from_string(""))
    spec = merger_suffix(merger)
    if spec is None:
        return None
    # gelf_extra needs static placement the gelf leg cannot provide;
    # capnp_extra / ltsv_extra render inside their legs
    if type(encoder) is GelfEncoder and encoder.extra:
        return None
    if ltsv_decoder.schema:
        return None
    if extras:
        # the jsonl/dns legs block-encode GELF and LTSV only; other
        # encoders keep the Record path for the whole mixed batch
        from ..encoders.ltsv import LTSVEncoder

        if type(encoder) not in (GelfEncoder, LTSVEncoder):
            return None
    suffix, syslen = spec

    n = packed[5]
    classes = classify_packed(
        packed, sharded_for("classify") if sharded_for else None,
        extras=extras)
    submitted = []
    for cls, fmt in _class_table(extras):
        idx = np.flatnonzero(classes == cls)
        if not idx.size:
            continue
        sub = packmod.subset_packed(packed, idx)
        submitted.append((idx, fmt, sub, block_submit(
            fmt, sub, sharded_for(fmt) if sharded_for else None)))
    legs = []
    for idx, fmt, sub, handle in submitted:
        res, _fetch_s, _declined_s = block_fetch_encode(
            fmt, handle, sub, encoder, merger, ltsv_decoder, route_state)
        if res is None:
            return None
        legs.append((idx, res))

    emit = np.zeros(n, dtype=bool)
    row_len = np.zeros(n, dtype=np.int64)
    row_src = np.zeros(n, dtype=np.int64)   # leg ordinal
    row_boff = np.zeros(n, dtype=np.int64)  # offset inside leg buffer
    row_pfx = np.zeros(n, dtype=np.int64)
    buffers = []
    errors = []
    error_rows = []
    fallback_rows = 0
    for li, (idx, res) in enumerate(legs):
        b = res.block
        erows = idx[np.flatnonzero(res.emit)]
        lens_c = np.diff(b.bounds)
        emit[erows] = True
        row_len[erows] = lens_c
        row_src[erows] = li
        row_boff[erows] = b.bounds[:-1]
        if b.prefix_lens is not None:
            row_pfx[erows] = b.prefix_lens
        buffers.append(np.frombuffer(b.data, dtype=np.uint8))
        for (err, line), r in zip(res.errors, res.error_rows):
            errors.append((err, line))
            error_rows.append(int(idx[r]))
        fallback_rows += res.fallback_rows

    bases = exclusive_cumsum(np.array([b.size for b in buffers],
                                      dtype=np.int64))[:-1] \
        if buffers else np.zeros(0, dtype=np.int64)
    src = np.concatenate(buffers) if buffers else np.zeros(0, dtype=np.uint8)
    rows = np.flatnonzero(emit)
    seg_src = bases[row_src[rows]] + row_boff[rows] if rows.size else \
        np.zeros(0, dtype=np.int64)
    seg_len = row_len[rows]
    data = concat_segments(src, seg_src, seg_len).tobytes() if rows.size \
        else b""
    bounds = exclusive_cumsum(seg_len)
    prefix_lens = row_pfx[rows] if syslen else None

    # errors in input order (the per-leg lists are subset-ordered)
    if errors:
        order = np.argsort(np.array(error_rows, dtype=np.int64),
                           kind="stable")
        errors = [errors[i] for i in order.tolist()]

    block = EncodedBlock(data, bounds, prefix_lens, len(suffix))
    return BlockResult(block, errors, fallback_rows, emit=emit,
                       error_rows=sorted(error_rows))
