"""Mixed-format auto-detect dispatch (BASELINE.json config #5).

``input.format = "auto_tpu"`` accepts a stream mixing RFC5424, RFC3164,
LTSV, and GELF records.  Each batch is partitioned by a cheap first-bytes
signature and every class is decoded by its columnar kernel (RFC3164
rows go through the tpu/rfc3164.py standard-layout fast path, with the
lenient cases falling back to the scalar decoder per row); results
reassemble in input order, so downstream ordering matches a
single-format run.

Signature rules (on the first bytes only):
- ``{``                      → GELF JSON
- ``<digits>1␣`` (opt. BOM)  → RFC5424 (version tag after the PRI)
- ``<``            otherwise → RFC3164
- TAB and ``:``  in the line → LTSV
- anything else              → RFC3164 (the lenient legacy decoder —
  also the reference's catch-all behavior class)
"""

from __future__ import annotations

from typing import List, Optional

from ..config import Config
from ..decoders.ltsv import LTSVDecoder
from .materialize import LineResult

F_RFC5424, F_RFC3164, F_LTSV, F_GELF = 0, 1, 2, 3


def classify(raw: bytes) -> int:
    b = raw
    if b.startswith(b"\xef\xbb\xbf"):
        b = b[3:]
    if b.startswith(b"{"):
        return F_GELF
    if b.startswith(b"<"):
        gt = b.find(b">", 1, 6)
        if gt > 1 and b[gt + 1:gt + 3] == b"1 " and b[1:gt].isdigit():
            return F_RFC5424
        return F_RFC3164
    if b"\t" in b and b":" in b:
        return F_LTSV
    return F_RFC3164


def decode_auto_batch(lines: List[bytes], max_len: int,
                      ltsv_decoder: Optional[LTSVDecoder] = None
                      ) -> List[LineResult]:
    from .batch import _decode_gelf_batch, _decode_ltsv_batch, _decode_rfc5424_batch

    if ltsv_decoder is None:
        ltsv_decoder = LTSVDecoder(Config.from_string(""))
    classes = [classify(ln) for ln in lines]
    buckets: List[List[int]] = [[], [], [], []]
    for i, c in enumerate(classes):
        buckets[c].append(i)

    results: List[LineResult] = [None] * len(lines)  # type: ignore

    if buckets[F_RFC5424]:
        sub = [lines[i] for i in buckets[F_RFC5424]]
        for i, res in zip(buckets[F_RFC5424], _decode_rfc5424_batch(sub, max_len)):
            results[i] = res
    if buckets[F_LTSV]:
        sub = [lines[i] for i in buckets[F_LTSV]]
        for i, res in zip(buckets[F_LTSV],
                          _decode_ltsv_batch(sub, max_len, ltsv_decoder)):
            results[i] = res
    if buckets[F_GELF]:
        sub = [lines[i] for i in buckets[F_GELF]]
        for i, res in zip(buckets[F_GELF], _decode_gelf_batch(sub, max_len)):
            results[i] = res
    if buckets[F_RFC3164]:
        from .batch import _decode_rfc3164_batch

        sub = [lines[i] for i in buckets[F_RFC3164]]
        for i, res in zip(buckets[F_RFC3164],
                          _decode_rfc3164_batch(sub, max_len)):
            results[i] = res
    return results
