"""Columnar RFC5424→GELF encoding: span tables → one framed output
buffer per batch, with no per-row Python on the fast tier.

Replaces the per-row dict/join fast path (encode_gelf.py, ~69K rows/s/
core) for the flagship route.  Two engines produce identical bytes:

- **native** (preferred): ``fg_gelf_lens``/``fg_gelf_write`` in
  native/flowgger_host.cpp assemble each kernel-ok row's GELF JSON
  directly from the chunk in two threaded passes (measure, prefix-sum,
  write), including per-row SD-name sorting with dict last-wins
  semantics and JSON escaping.
- **numpy fallback**: the row layout is flattened into (source offset,
  length) segments over a JSON-escaped chunk view, a constant bank and
  a timestamp scratch, then gathered in one ``concat_segments`` call
  (tpu/assemble.py).  This tier additionally excludes rows with
  duplicate or >48-byte SD names (vectorized sort-key limits); those
  rows re-run the scalar oracle instead.

Rows outside the tier (kernel-flagged, oversized, non-ASCII, SD values
needing unescape) re-run the scalar oracle (decoder → GelfEncoder), so
observable bytes stay identical to the reference semantics
(gelf_encoder.rs:51-116) in every case; differential tests drive both
engines against the Record path.

Framing (merger/mod.rs:30-32) is pre-applied: line/nul suffixes ride
the tail constant and syslen's length prefix is rendered inline; the
result is an EncodedBlock the sinks write wholesale.
"""


from __future__ import annotations

# byte-identity contract (flowcheck FC03): the scalar counterpart
# this route must stay byte-identical to, and the differential
# test that enforces it
SCALAR_ORACLE = "flowgger_tpu.encoders.gelf:GelfEncoder"
DIFF_TEST = "tests/test_encode_gelf_block.py::test_block_matches_scalar_corpus"

from typing import Dict, Optional

import numpy as np

# serde_json-compatible string escaping (shared with encoders/gelf.py)
from json.encoder import encode_basestring as _quote

from ..mergers import Merger
from ..utils.rustfmt import json_f64
from .assemble import (
    build_source,
    concat_segments,
    escape_json,
    exclusive_cumsum,
)
from .block_common import (
    BlockResult,
    apply_syslen_prefix,
    finish_block,
    merger_suffix,
    sorted_pair_order,
    syslen_prefix_lens_from_framed,
    ts_scratch,
)

__all__ = ["encode_rfc5424_gelf_block", "BlockResult", "merger_suffix"]

_NAME_KEY_MAX = 48   # numpy tier: SD names longer than this fall back
_NATIVE_MAX_PAIRS = 64  # kMaxPairs in flowgger_host.cpp
# numpy tier row stride: the open-brace slot + the canonical tail
# columns (asserted against len(cols) below so the two can't desync)
_TAIL_COLS = 18
_ROW_STRIDE = 1 + _TAIL_COLS

# constant bank --------------------------------------------------------------
_C_OPEN = b"{"
_C_P0 = b'"_'
_C_P1 = b'":"'
_C_P2 = b'",'
_C_APP = b'"application_name":"'
_C_FULL = b'","full_message":"'
_C_HOST = b'","host":"'
_C_LEVEL = b'","level":'
_C_PROC = b',"process_id":"'
_C_SDID = b'","sd_id":"'
_C_SHORT = b'","short_message":"'
_C_TS = b'","timestamp":'
_C_TAIL = b',"version":"1.1"}'
_C_UNKNOWN = b"unknown"
_C_DASH = b"-"
_C_SEVD = b"01234567"

_FIXED_KEYS = ("application_name", "full_message", "host", "level",
               "process_id", "sd_id", "short_message", "timestamp",
               "version")


def gelf_extra_slots(extra):
    """Render ``[output.gelf_extra]`` pairs into the static insertion
    slots of the rfc5424 GELF layout (serde_json BTreeMap order means a
    non-``_`` key's position among the fixed keys is config-static, so
    each extra is a constant byte run folded into the neighbouring
    segment constant).  Slot text forms: ``self`` (before a key, fully
    quoted + trailing comma), ``string-close`` (after a string value:
    leading ``",`` closes it, own closing quote supplied by the next
    constant), ``number`` (after a bare number: self-contained with a
    leading comma).  Returns the slot dict, or None when any key needs
    dynamic placement — a leading ``_`` interleaves with SD pairs, and
    a fixed-key name overwrites a computed field (gelf_encoder.rs
    extras overwrite everything) — those configs take the Record path.
    """
    from .block_common import extra_forms

    slots = {k: b"" for k in ("open", "app", "full", "host", "level",
                              "proc", "p6", "short", "ts", "tail_num",
                              "tail_ver")}
    for k, v in sorted(extra or ()):
        if k.startswith("_") or k in _FIXED_KEYS:
            return None
        sf, sc, nm = extra_forms(k, v)
        if k < "_":
            slots["open"] += sf
        elif k < "application_name":
            slots["app"] += sf
        elif k < "full_message":
            slots["full"] += sc
        elif k < "host":
            slots["host"] += sc
        elif k < "level":
            slots["level"] += sc
        elif k < "process_id":
            slots["proc"] += nm
        elif k < "sd_id":
            slots["p6"] += sc
        elif k < "short_message":
            slots["short"] += sc
        elif k < "timestamp":
            slots["ts"] += sc
        elif k < "version":
            slots["tail_num"] += nm
        else:
            slots["tail_ver"] += sc
    return slots


def gelf_extra_consts(extra):
    """(open, app, full, host, level, proc, p6, short, ts, tail) segment
    constants with the extras folded in; None when unsupported."""
    slots = gelf_extra_slots(extra)
    if slots is None:
        return None
    from .block_common import extra_tail

    tail = extra_tail(_C_TAIL, slots["tail_num"], slots["tail_ver"])
    return (_C_OPEN + slots["open"], slots["app"] + _C_APP,
            slots["full"] + _C_FULL, slots["host"] + _C_HOST,
            slots["level"] + _C_LEVEL, slots["proc"] + _C_PROC,
            slots["p6"], slots["short"] + _C_SHORT,
            slots["ts"] + _C_TS, tail)


def encode_rfc5424_gelf_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
) -> Optional[BlockResult]:
    """Returns None when this route can't apply (gelf_extra keys that
    need dynamic placement, or an unknown merger type) — the caller
    then uses the per-row path."""
    from .. import native

    spec = merger_suffix(merger)
    if spec is None:
        return None
    econsts = gelf_extra_consts(encoder.extra)
    if econsts is None:
        return None
    (c_open, c_app, c_full, c_host, c_level, c_proc, c_p6, c_short,
     c_ts, c_tail) = econsts
    suffix, syslen = spec

    n = int(n_real)
    starts64 = np.asarray(starts[:n], dtype=np.int64)
    lens64 = np.asarray(orig_lens[:n], dtype=np.int64)
    ok = np.asarray(out["ok"][:n], dtype=bool)
    has_high = np.asarray(out["has_high"][:n], dtype=bool)
    pair_count = np.asarray(out["pair_count"][:n])
    sd_count = np.asarray(out["sd_count"][:n])
    val_has_esc = np.asarray(out["val_has_esc"][:n], dtype=bool)
    name_start = np.asarray(out["name_start"])[:n]
    name_end = np.asarray(out["name_end"])[:n]

    cand = ok & (lens64 <= max_len) & ~has_high

    chunk_arr = np.frombuffer(chunk_bytes, dtype=np.uint8)
    # the native row assembler predates the extras slots: extras run on
    # the numpy segment engine (still columnar, still ~20x the Record
    # path)
    use_native = (native.gelf_rows_available()
                  and not encoder.extra
                  and name_start.shape[1] <= _NATIVE_MAX_PAIRS)
    if not use_native and val_has_esc.shape[1]:
        # the numpy engine emits value spans through the shared escaped
        # chunk view and cannot compose the SD unescape; the native row
        # assembler handles those values directly
        cand &= ~val_has_esc.any(axis=1)

    ns_s = ne_s = vs_s = ve_s = np.zeros(0, dtype=np.int64)
    if not use_native:
        # numpy tier limits: SD name length cap + no duplicate names
        jmask = np.arange(name_start.shape[1])[None, :] < pair_count[:, None]
        nlen = np.where(jmask, name_end - name_start, 0)
        cand &= nlen.max(axis=1, initial=0) <= _NAME_KEY_MAX

        # pair table sorted by (row, name bytes)
        pc = np.where(cand & (sd_count > 0),
                      pair_count.astype(np.int64), 0)
        T = int(pc.sum())
        if T:
            rop = np.repeat(np.arange(n, dtype=np.int64), pc)
            jop = np.arange(T, dtype=np.int64) - np.repeat(
                exclusive_cumsum(pc)[:-1], pc)
            ns_abs = starts64[rop] + name_start[rop, jop]
            ne_abs = starts64[rop] + name_end[rop, jop]
            vs_abs = starts64[rop] + np.asarray(out["val_start"])[:n][rop, jop]
            ve_abs = starts64[rop] + np.asarray(out["val_end"])[:n][rop, jop]
            order, dup_rows = sorted_pair_order(chunk_arr, rop, ns_abs,
                                                ne_abs, _NAME_KEY_MAX)
            if dup_rows.size:
                cand[dup_rows] = False
                order = order[cand[rop[order]]]
            ns_s, ne_s = ns_abs[order], ne_abs[order]
            vs_s, ve_s = vs_abs[order], ve_abs[order]

    ridx = np.flatnonzero(cand)
    R = ridx.size
    final_buf = b""
    row_off = np.zeros(1, dtype=np.int64)
    prefix_lens_tier: Optional[np.ndarray] = None

    if R and use_native:
        scratch, ts_off, ts_len = ts_scratch(out, n, ridx, json_f64)
        meta = np.empty((R, 17), dtype=np.int32)
        meta[:, 0] = starts64[ridx]
        for k, key in enumerate(("host_start", "host_end", "app_start",
                                 "app_end", "proc_start", "proc_end",
                                 "msg_trim_start", "trim_end", "full_start",
                                 "severity")):
            meta[:, 1 + k] = np.asarray(out[key])[:n][ridx]
        nsd = (np.asarray(sd_count)[ridx] > 0)
        meta[:, 11] = nsd
        last = np.maximum(np.asarray(sd_count)[ridx] - 1, 0)
        meta[:, 12] = np.asarray(out["sid_start"])[:n][ridx, last]
        meta[:, 13] = np.asarray(out["sid_end"])[:n][ridx, last]
        meta[:, 14] = ts_off
        meta[:, 15] = ts_len
        meta[:, 16] = np.asarray(pair_count)[ridx]
        pns = np.asarray(out["name_start"])[:n][ridx]
        pne = np.asarray(out["name_end"])[:n][ridx]
        pvs = np.asarray(out["val_start"])[:n][ridx]
        pve = np.asarray(out["val_end"])[:n][ridx]
        pesc = val_has_esc[ridx].astype(np.int32)
        res = native.gelf_rows_native(chunk_bytes, meta, pns, pne, pvs, pve,
                                      pesc, scratch, suffix, syslen)
        # gelf_rows_available() was checked above, so res cannot be None
        buf, row_off = res
        tier_lens = np.diff(row_off)
        if syslen:
            prefix_lens_tier = syslen_prefix_lens_from_framed(tier_lens)
        final_buf = buf.tobytes()

    if R and not use_native:
        emap = escape_json(chunk_arr)
        esc = emap.esc

        # per-row escaped spans ----------------------------------------
        def espan(skey, ekey):
            a = starts64[ridx] + np.asarray(out[skey])[:n][ridx]
            b = starts64[ridx] + np.asarray(out[ekey])[:n][ridx]
            ea = emap.map(a)
            return ea, emap.map(b) - ea

        app_src, app_len = espan("app_start", "app_end")
        host_src, host_len = espan("host_start", "host_end")
        proc_src, proc_len = espan("proc_start", "proc_end")
        full_src, full_len = espan("full_start", "trim_end")
        msg_src, msg_len = espan("msg_trim_start", "trim_end")

        nsd = np.asarray(sd_count)[ridx] > 0
        last = np.maximum(np.asarray(sd_count)[ridx] - 1, 0)
        sid_a = starts64[ridx] + np.asarray(out["sid_start"])[:n][ridx, last]
        sid_b = starts64[ridx] + np.asarray(out["sid_end"])[:n][ridx, last]
        sid_src = emap.map(sid_a)
        sid_len = emap.map(sid_b) - sid_src

        sev = np.asarray(out["severity"])[:n][ridx].astype(np.int64)

        scratch, ts_off, ts_len = ts_scratch(out, n, ridx, json_f64)
        const_bank, coffs = build_source(
            c_open, _C_P0, _C_P1, _C_P2, c_app, c_full, c_host,
            c_level, c_proc, _C_SDID, c_short, c_ts, c_tail + suffix,
            _C_UNKNOWN, _C_DASH, _C_SEVD, c_p6)
        (o_open, o_p0, o_p1, o_p2, o_app, o_full, o_host, o_level, o_proc,
         o_sdid, o_short, o_ts, o_tail, o_unknown, o_dash, o_sevd,
         o_p6) = coffs
        cbase = int(esc.size)
        tbase = cbase + int(const_bank.size)
        src = np.concatenate([
            esc, const_bank, np.frombuffer(scratch or b"\0", dtype=np.uint8),
        ])
        ts_src = tbase + ts_off
        # empty-field redirects
        host_src = np.where(host_len == 0, cbase + o_unknown, host_src)
        host_len = np.where(host_len == 0, len(_C_UNKNOWN), host_len)
        msg_src = np.where(msg_len == 0, cbase + o_dash, msg_src)
        msg_len = np.where(msg_len == 0, 1, msg_len)

        # ---- segment stream (column-wise construction) ---------------
        # every row gets 19 fixed segment slots (brace + 18 canonical
        # tail parts — incl. the extras slot between process_id and
        # sd_id — with the sd_id pair zero-length when absent) plus
        # 5 slots per SD pair, so destinations are pure index arithmetic
        # and each column is one R- or T-sized write — no S-sized masks.
        pc2 = np.where(cand & (np.asarray(sd_count) > 0),
                       np.asarray(pair_count).astype(np.int64), 0)
        p = pc2[ridx]
        T2 = ns_s.size
        pb = exclusive_cumsum(p)
        rstart = _ROW_STRIDE * np.arange(R, dtype=np.int64) + 5 * pb[:-1]
        S = _ROW_STRIDE * R + 5 * T2
        seg_src = np.empty(S, dtype=np.int64)
        seg_len = np.empty(S, dtype=np.int64)

        seg_src[rstart] = cbase + o_open
        seg_len[rstart] = len(c_open)

        if T2:
            name_src = emap.map(ns_s)
            name_len_e = emap.map(ne_s) - name_src
            val_src = emap.map(vs_s)
            val_len_e = emap.map(ve_s) - val_src
            tord = np.repeat(np.arange(R, dtype=np.int64), p)
            within = np.arange(T2, dtype=np.int64) - np.repeat(pb[:-1], p)
            pd0 = rstart[tord] + 1 + 5 * within
            pair_dest = pd0[:, None] + np.arange(5, dtype=np.int64)[None, :]
            pair_src2 = np.empty((T2, 5), dtype=np.int64)
            pair_len2 = np.empty((T2, 5), dtype=np.int64)
            pair_src2[:, 0] = cbase + o_p0
            pair_len2[:, 0] = 2
            pair_src2[:, 1] = name_src
            pair_len2[:, 1] = name_len_e
            pair_src2[:, 2] = cbase + o_p1
            pair_len2[:, 2] = 3
            pair_src2[:, 3] = val_src
            pair_len2[:, 3] = val_len_e
            pair_src2[:, 4] = cbase + o_p2
            pair_len2[:, 4] = 2
            seg_src[pair_dest] = pair_src2
            seg_len[pair_dest] = pair_len2

        cols = (
            (cbase + o_app, len(c_app)),
            (app_src, app_len),
            (cbase + o_full, len(c_full)),
            (full_src, full_len),
            (cbase + o_host, len(c_host)),
            (host_src, host_len),
            (cbase + o_level, len(c_level)),
            (cbase + o_sevd + sev, 1),
            (cbase + o_proc, len(c_proc)),
            (proc_src, proc_len),
            (cbase + o_p6, len(c_p6)),
            (cbase + o_sdid, np.where(nsd, len(_C_SDID), 0)),
            (sid_src, np.where(nsd, sid_len, 0)),
            (cbase + o_short, len(c_short)),
            (msg_src, msg_len),
            (cbase + o_ts, len(c_ts)),
            (ts_src, ts_len),
            (cbase + o_tail, len(c_tail) + len(suffix)),
        )
        assert len(cols) == _TAIL_COLS
        tail_dest = (rstart + 1 + 5 * p)[:, None] + np.arange(
            _TAIL_COLS, dtype=np.int64)[None, :]
        tsrc = np.empty((R, _TAIL_COLS), dtype=np.int64)
        tlen = np.empty((R, _TAIL_COLS), dtype=np.int64)
        for k, (s, ln) in enumerate(cols):
            tsrc[:, k] = s
            tlen[:, k] = ln
        seg_src[tail_dest] = tsrc
        seg_len[tail_dest] = tlen

        dst0 = exclusive_cumsum(seg_len)
        body = concat_segments(src, seg_src, seg_len, dst0)
        row_off = np.concatenate([dst0[rstart], dst0[-1:]])
        tier_lens = np.diff(row_off)

        if syslen:
            final_buf, row_off, prefix_lens_tier = apply_syslen_prefix(
                body, row_off, tier_lens)
        else:
            final_buf = body.tobytes()

    return finish_block(chunk_bytes, starts64, lens64, n, cand, ridx,
                        final_buf, row_off, prefix_lens_tier, suffix,
                        syslen, merger, encoder)
