"""Columnar JSON-lines block encoders: the structural-index span
tables (tpu/jsonl.py) become framed GELF or LTSV bytes per batch.

The decoder (decoders/jsonl.py) routes timestamp/host/message/level
into Record fields and everything else into ``_``-prefixed typed SD
pairs.  On the fast tier every output piece is a raw span or constant
(same discipline as encode_gelf_gelf_block):

- pair keys keep their bytes (conditional ``_`` prefix for GELF, one
  leading ``_`` stripped for LTSV), sorted by final/original name;
- clean strings and canonical integers re-emit verbatim;
  true/false/null are constants;
- ``timestamp`` is float-parsed and re-formatted per row (json_f64 /
  display_f64 through the dedup scratch); missing timestamps — the
  oracle stamps now() — take the oracle;
- host/message default to the encoders' "unknown" / "-" constants.

Everything else — nested-container values, escaped strings, floats,
huge ints, control bytes, duplicate names, non-ASCII — re-runs the
scalar oracle, keeping bytes identical to JSONLDecoder→encoder in
every case.
"""

from __future__ import annotations

# byte-identity contract (flowcheck FC03): the scalar counterpart
# these routes must stay byte-identical to, and the differential
# tests that enforce it
SCALAR_ORACLE = "flowgger_tpu.decoders.jsonl:JSONLDecoder"
DIFF_TEST = (
    "tests/test_tpu_jsonl.py::test_jsonl_gelf_block_matches_scalar",
    "tests/test_tpu_jsonl.py::test_jsonl_ltsv_block_matches_scalar",
)

from typing import Dict, Optional

import numpy as np

from ..mergers import Merger
from ..utils.rustfmt import json_f64
from .assemble import (
    build_source,
    concat_segments,
    count_in_spans,
    exclusive_cumsum,
)
from .block_common import (
    BlockResult,
    apply_syslen_prefix,
    finish_block,
    gelf_sorted_pairs,
    merger_suffix,
    sorted_pair_order,
)
from .jsonidx import VT_FALSE, VT_NULL, VT_NUMBER, VT_STRING, VT_TRUE
from .materialize_jsonl import _scalar_jsonl

_SPECIALS = (b"timestamp", b"host", b"message", b"level")
_NAME_CAP = 48
_TSW = 24   # timestamp spans longer than this take the oracle


def jsonl_screen(chunk_bytes, starts, orig_lens, out, n_real: int,
                 max_len: int):
    """Shared JSON-lines route screen (jsonl→GELF / jsonl→LTSV): row
    byte screens, special-key routing via packed 8-byte words,
    per-special validation, and the pair value classes every text
    re-emission route accepts (clean strings, bools, null, canonical
    ints ≤ 18 digits — container values go to the oracle)."""
    n = int(n_real)
    starts64 = np.asarray(starts[:n], dtype=np.int64)
    lens64 = np.asarray(orig_lens[:n], dtype=np.int64)
    ok = np.asarray(out["ok"][:n], dtype=bool)
    n_fields = np.asarray(out["n_fields"])[:n].astype(np.int64)
    key_s = np.asarray(out["key_start"])[:n]
    key_e = np.asarray(out["key_end"])[:n]
    val_s = np.asarray(out["val_start"])[:n]
    val_e = np.asarray(out["val_end"])[:n]
    val_t = np.asarray(out["val_type"])[:n]
    key_esc = np.asarray(out["key_esc"][:n], dtype=bool)
    val_esc = np.asarray(out["val_esc"][:n], dtype=bool)

    chunk_arr = np.frombuffer(chunk_bytes, dtype=np.uint8)
    _KEYW = 16
    chunk_pad = np.concatenate(
        [chunk_arr, np.zeros(max_len + _KEYW + 2, dtype=np.uint8)])
    F = key_s.shape[1]
    jmask = np.arange(F)[None, :] < n_fields[:, None]

    # row-level byte screen: non-ASCII (decode semantics) or any
    # control byte must be absent, one prefix-count pass
    bad_cum = np.cumsum((chunk_arr >= 128) | (chunk_arr < 0x20))
    row_end = starts64 + lens64
    cand = ok & (lens64 <= max_len)
    cand &= count_in_spans(bad_cum, starts64, row_end) == 0
    cand &= ~(jmask & key_esc).any(axis=1)

    kabs = starts64[:, None] + key_s
    klen = key_e - key_s
    k8i = (kabs[:, :, None].astype(np.int32)
           + np.arange(8, dtype=np.int32)[None, None, :])
    k8 = np.where(np.arange(8)[None, None, :] < klen[:, :, None],
                  chunk_pad[k8i], np.uint8(0))
    kwords = np.ascontiguousarray(k8).view(">u8")[:, :, 0]

    def name_is(word: bytes):
        prefix = word[:8] + b"\0" * (8 - min(len(word), 8))
        target = int.from_bytes(prefix, "big")
        m = jmask & (klen == len(word)) & (kwords == np.uint64(target))
        if len(word) > 8 and m.any():
            rr, ff = np.nonzero(m)
            tail_ok = np.ones(rr.size, dtype=bool)
            base = kabs[rr, ff]
            for i, ch in enumerate(word[8:], start=8):
                tail_ok &= chunk_pad[base + i] == ch
            m2 = np.zeros_like(m)
            m2[rr[tail_ok], ff[tail_ok]] = True
            return m2
        return m

    sp_masks = {w: name_is(w) for w in _SPECIALS}
    is_special = np.zeros((n, F), dtype=bool)
    for w, m in sp_masks.items():
        is_special |= m
        cand &= m.sum(axis=1) <= 1  # repeated special keys: oracle

    def field_of(m):
        return m.any(axis=1), m.argmax(axis=1)

    has_ts, ts_f = field_of(sp_masks[b"timestamp"])
    has_host, host_f = field_of(sp_masks[b"host"])
    has_msg, msg_f = field_of(sp_masks[b"message"])
    has_lvl, lvl_f = field_of(sp_masks[b"level"])

    rows = np.arange(n)

    def vt_at(f):
        return val_t[rows, f]

    def vspan_at(f):
        a = starts64 + val_s[rows, f]
        return a, starts64 + val_e[rows, f]

    def vesc_at(f):
        return val_esc[rows, f]

    def byte_at(pos):
        return chunk_pad[np.asarray(pos, dtype=np.int64)]

    nondig_cum = np.cumsum(~((chunk_arr >= ord("0"))
                             & (chunk_arr <= ord("9"))))
    dot_cum = np.cumsum(chunk_arr == ord("."))

    def canonical_number(a, b):
        r"""JSON number grammar ``-?(0|[1-9][0-9]*)(\.[0-9]+)?`` whose
        float() parse matches json.loads semantics (same rules as the
        GELF screen; -0 excluded)."""
        ln = b - a
        first = byte_at(a)
        neg = first == ord("-")
        da = a + neg
        dfirst = byte_at(da)
        last = byte_at(b - 1)
        dots = count_in_spans(dot_cum, a, b)
        nondig = count_in_spans(nondig_cum, a, b)
        okn = (ln > neg) & (nondig == neg.astype(np.int64) + dots)
        okn &= (dots <= 1) & (dfirst != ord(".")) & (last != ord("."))
        okn &= (dfirst != ord("0")) | (b - da == 1) | (byte_at(da + 1)
                                                       == ord("."))
        okn &= ~(neg & (dfirst == ord("0")) & (dots == 0))
        return okn

    # timestamp: required for the tier (the oracle stamps now() when
    # absent — a per-row wall clock no batch constant can reproduce),
    # canonical number, bounded span
    tsa_all, tsb_all = vspan_at(ts_f)
    cand &= has_ts & (vt_at(ts_f) == VT_NUMBER)
    cand &= canonical_number(tsa_all, tsb_all)
    cand &= (tsb_all - tsa_all) <= _TSW
    # host/message: absent or clean strings
    cand &= ~has_host | ((vt_at(host_f) == VT_STRING) & ~vesc_at(host_f))
    cand &= ~has_msg | ((vt_at(msg_f) == VT_STRING) & ~vesc_at(msg_f))
    # level: absent or a bare digit 0-7
    lvl_a, lvl_b = vspan_at(lvl_f)
    lvl_byte = byte_at(lvl_a)
    lvl_ok = ((vt_at(lvl_f) == VT_NUMBER) & (lvl_b - lvl_a == 1)
              & (lvl_byte >= ord("0")) & (lvl_byte <= ord("7")))
    cand &= ~has_lvl | lvl_ok

    # pair fields: clean strings, bools, null, or canonical integers —
    # container values (VT_OBJECT/VT_ARRAY) re-serialize per row and
    # take the oracle
    is_pair = jmask & ~is_special
    vabs_a = starts64[:, None] + val_s
    vabs_b = starts64[:, None] + val_e
    vlen = val_e - val_s
    vfirst = byte_at(vabs_a)
    vsecond = byte_at(vabs_a + 1)
    dot_e_cum = np.cumsum((chunk_arr == ord(".")) | (chunk_arr == ord("e"))
                          | (chunk_arr == ord("E")))
    has_frac = count_in_spans(dot_e_cum, vabs_a, vabs_b) > 0
    neg = vfirst == ord("-")
    digits_len = vlen - neg
    int_ok = ((val_t == VT_NUMBER) & ~has_frac & (digits_len <= 18)
              & canonical_number(vabs_a, vabs_b)
              & ~((vfirst == ord("0")) & (vlen > 1))
              & ~(neg & (vsecond == ord("0"))))
    pair_ok = ((val_t == VT_STRING) & ~val_esc) | (val_t == VT_TRUE) \
        | (val_t == VT_FALSE) | (val_t == VT_NULL) | int_ok
    cand &= (~is_pair | pair_ok).all(axis=1)
    cand &= np.where(jmask, klen, 0).max(axis=1, initial=0) <= _NAME_CAP

    return dict(n=n, starts64=starts64, lens64=lens64, cand=cand,
                chunk_arr=chunk_arr, chunk_pad=chunk_pad, kabs=kabs,
                klen=klen, key_e=key_e, val_s=val_s, val_e=val_e,
                val_t=val_t, val_esc=val_esc, jmask=jmask,
                vabs_a=vabs_a, vabs_b=vabs_b,
                is_pair=is_pair, is_special=is_special,
                byte_at=byte_at, vt_at=vt_at, vspan_at=vspan_at,
                has_ts=has_ts, ts_f=ts_f, tsa_all=tsa_all,
                tsb_all=tsb_all,
                has_host=has_host, host_f=host_f,
                has_msg=has_msg, msg_f=msg_f,
                has_lvl=has_lvl, lvl_f=lvl_f)


def encode_jsonl_gelf_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
) -> Optional[BlockResult]:
    """jsonl→GELF: sorted-final-name object — pairs (all
    ``_``-prefixed, so they sort before every special), then
    host/level/short_message/timestamp/version."""
    spec = merger_suffix(merger)
    if spec is None or encoder.extra:
        return None
    suffix, syslen = spec

    s = jsonl_screen(chunk_bytes, starts, orig_lens, out, n_real,
                     max_len)
    (n, starts64, lens64, cand, chunk_arr, kabs, klen, key_e, val_s,
     val_e, val_t, jmask, is_pair, byte_at) = (
        s["n"], s["starts64"], s["lens64"], s["cand"], s["chunk_arr"],
        s["kabs"], s["klen"], s["key_e"], s["val_s"], s["val_e"],
        s["val_t"], s["jmask"], s["is_pair"], s["byte_at"])
    tsa_all, tsb_all = s["tsa_all"], s["tsb_all"]
    has_host, host_f = s["has_host"], s["host_f"]
    has_msg, msg_f = s["has_msg"], s["msg_f"]
    has_lvl, lvl_f = s["has_lvl"], s["lvl_f"]
    vabs_a, vabs_b = s["vabs_a"], s["vabs_b"]

    # ---- sorted pair table (by FINAL name: leading '_' skipped) ---------
    is_pair = is_pair & cand[:, None]
    pc = is_pair.sum(axis=1).astype(np.int64)
    T = int(pc.sum())
    if T:
        prow, pcol = np.nonzero(is_pair)
        rop = prow.astype(np.int64)
        ns_abs = kabs[prow, pcol]
        ne_abs = starts64[rop] + key_e[prow, pcol]
        has_us = byte_at(ns_abs) == ord("_")
        order, dup_rows = sorted_pair_order(
            chunk_arr, rop, ns_abs + has_us, ne_abs, _NAME_CAP)
        if dup_rows.size:
            cand[dup_rows] = False
            keep = cand[rop[order]]
            order = order[keep]
        rop_s = rop[order]
        ns_s, ne_s = ns_abs[order], ne_abs[order]
        us_s = has_us[order]
        pv_t = val_t[prow, pcol][order]
        pv_a = vabs_a[prow, pcol][order]
        pv_b = vabs_b[prow, pcol][order]
    else:
        rop_s = ns_s = ne_s = pv_a = pv_b = np.zeros(0, dtype=np.int64)
        us_s = np.zeros(0, dtype=bool)
        pv_t = np.zeros(0, dtype=np.int64)

    ridx = np.flatnonzero(cand)
    R = ridx.size
    final_buf = b""
    row_off = np.zeros(1, dtype=np.int64)
    prefix_lens_tier: Optional[np.ndarray] = None

    if R:
        from .block_common import span_f64_scratch

        scratch, ts_off, ts_len = span_f64_scratch(
            chunk_bytes, tsa_all[ridx], tsb_all[ridx], json_f64)

        consts, offs = build_source(
            b"{", b'"_', b'"', b'":', b'",', b"true", b"false", b"null",
            b'"host":"', b'"level":', b'"short_message":"',
            b'"timestamp":', b'"version":"1.1"}' + suffix,
            b"unknown", b"-", b",", scratch)
        (o_open, o_kpre, o_q, o_colon, o_qc, o_true, o_false, o_null,
         o_host, o_lvl, o_short, o_ts, o_tail, o_unknown, o_dash,
         o_comma, o_scratch) = offs
        cbase = int(chunk_arr.size)
        src = np.concatenate([chunk_arr, consts])

        # fixed tail is 13 segments; each pair is 7
        FIXED = 13
        p = pc[ridx]
        segc = 1 + 7 * p + FIXED
        rstart = exclusive_cumsum(segc)[:-1]
        S = int(segc.sum())
        seg_src = np.zeros(S, dtype=np.int64)
        seg_len = np.zeros(S, dtype=np.int64)
        seg_src[rstart] = cbase + o_open
        seg_len[rstart] = 1

        if T:
            tpos = np.cumsum(cand) - 1
            tord = tpos[rop_s]
            within = np.zeros(rop_s.size, dtype=np.int64)
            if rop_s.size:
                new_row = np.ones(rop_s.size, dtype=bool)
                new_row[1:] = rop_s[1:] != rop_s[:-1]
                run_starts = np.flatnonzero(new_row)
                within = (np.arange(rop_s.size)
                          - np.repeat(run_starts,
                                      np.diff(np.append(run_starts,
                                                        rop_s.size))))
            p0 = rstart[tord] + 1 + 7 * within
            is_str = pv_t == VT_STRING
            seg_src[p0] = np.where(us_s, cbase + o_q, cbase + o_kpre)
            seg_len[p0] = np.where(us_s, 1, 2)
            seg_src[p0 + 1] = ns_s
            seg_len[p0 + 1] = ne_s - ns_s
            seg_src[p0 + 2] = cbase + o_colon
            seg_len[p0 + 2] = 2
            seg_src[p0 + 3] = cbase + o_q
            seg_len[p0 + 3] = np.where(is_str, 1, 0)
            vsrc = np.where(
                is_str | (pv_t == VT_NUMBER), pv_a,
                np.where(pv_t == VT_TRUE, cbase + o_true,
                         np.where(pv_t == VT_FALSE, cbase + o_false,
                                  cbase + o_null)))
            vln = np.where(
                is_str | (pv_t == VT_NUMBER), pv_b - pv_a,
                np.where(pv_t == VT_TRUE, 4,
                         np.where(pv_t == VT_FALSE, 5, 4)))
            seg_src[p0 + 4] = vsrc
            seg_len[p0 + 4] = vln
            seg_src[p0 + 5] = cbase + o_q
            seg_len[p0 + 5] = np.where(is_str, 1, 0)
            seg_src[p0 + 6] = cbase + o_comma
            seg_len[p0 + 6] = 1

        hf = has_host[ridx]
        hfi = host_f[ridx]
        mf = has_msg[ridx]
        mfi = msg_f[ridx]
        lf = has_lvl[ridx]
        lfi = lvl_f[ridx]
        ri = ridx

        def span_sel(fi):
            a = starts64[ri] + val_s[ri, fi]
            b = starts64[ri] + val_e[ri, fi]
            return a, b - a

        host_a, host_l = span_sel(hfi)
        msg_a, msg_l = span_sel(mfi)
        lvl_src = starts64[ri] + val_s[ri, lfi]

        # absent OR empty host renders "unknown" (GelfEncoder falsy
        # check); absent message renders "-", empty stays empty
        host_eff_l = np.where(hf, host_l, 0)
        host_src = np.where(host_eff_l == 0, cbase + o_unknown, host_a)
        host_len = np.where(host_eff_l == 0, len(b"unknown"), host_eff_l)
        msg_src = np.where(mf, msg_a, cbase + o_dash)
        msg_len = np.where(mf, msg_l, 1)

        fd = (rstart + 1 + 7 * p)[:, None] + np.arange(
            FIXED, dtype=np.int64)[None, :]
        fsrc = np.empty((R, FIXED), dtype=np.int64)
        flen = np.empty((R, FIXED), dtype=np.int64)
        cols = (
            (cbase + o_host, len(b'"host":"')),
            (host_src, host_len),
            (cbase + o_qc, 2),
            (cbase + o_lvl, np.where(lf, len(b'"level":'), 0)),
            (lvl_src, np.where(lf, 1, 0)),
            (cbase + o_comma, np.where(lf, 1, 0)),
            (cbase + o_short, len(b'"short_message":"')),
            (msg_src, msg_len),
            (cbase + o_qc, 2),
            (cbase + o_ts, len(b'"timestamp":')),
            (cbase + o_scratch + ts_off, ts_len),
            (cbase + o_comma, 1),
            (cbase + o_tail, len(b'"version":"1.1"}') + len(suffix)),
        )
        for k, (s_, ln) in enumerate(cols):
            fsrc[:, k] = s_
            flen[:, k] = ln
        seg_src[fd] = fsrc
        seg_len[fd] = flen

        dst0 = exclusive_cumsum(seg_len)
        body = concat_segments(src, seg_src, seg_len, dst0)
        row_off = np.concatenate([dst0[rstart], dst0[-1:]])
        tier_lens = np.diff(row_off)
        if syslen:
            final_buf, row_off, prefix_lens_tier = apply_syslen_prefix(
                body, row_off, tier_lens)
        else:
            final_buf = body.tobytes()

    return finish_block(chunk_bytes, starts64, lens64, n, cand, ridx,
                        final_buf, row_off, prefix_lens_tier, suffix,
                        syslen, merger, encoder, scalar_fn=_scalar_jsonl)


def encode_jsonl_ltsv_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
) -> Optional[BlockResult]:
    """jsonl→LTSV: pairs in the Record's construction order — sorted
    by ORIGINAL key with the leading ``_`` stripped back off — then
    ltsv_extra, host, time, message?, level?.  Names containing ':'
    (LTSV key escape) take the oracle."""
    from ..utils.rustfmt import display_f64
    from .block_common import ltsv_extra_blob, span_f64_scratch
    from .encode_ltsv_block import _ltsv_core

    spec = merger_suffix(merger)
    if spec is None:
        return None
    suffix, syslen = spec

    s = jsonl_screen(chunk_bytes, starts, orig_lens, out, n_real,
                     max_len)
    n, starts64, lens64, cand = (s["n"], s["starts64"], s["lens64"],
                                 s["cand"])
    chunk_arr, kabs, key_e = s["chunk_arr"], s["kabs"], s["key_e"]
    byte_at, vspan_at = s["byte_at"], s["vspan_at"]
    is_pair = s["is_pair"] & cand[:, None]
    vabs_a, vabs_b = s["vabs_a"], s["vabs_b"]
    val_t = s["val_t"]

    # keys needing the LTSV ':'→'_' escape: count per name span
    if is_pair.any():
        col_cum = np.cumsum(chunk_arr == ord(":"))
        ne_all = starts64[:, None] + key_e
        ncols = np.where(is_pair,
                         count_in_spans(col_cum, kabs, ne_all), 0)
        cand &= ncols.sum(axis=1) == 0
        is_pair = is_pair & cand[:, None]

    # pair table in ORIGINAL-key sorted order (shared helper; drops
    # duplicate-key rows from cand, returns '_'-stripped name starts)
    rop_s, ns_s, ne_s, pv_t, pv_a, pv_b = gelf_sorted_pairs(
        chunk_arr, starts64, cand, is_pair, kabs, key_e, vabs_a, vabs_b,
        val_t, byte_at, _NAME_CAP)

    ridx = np.flatnonzero(cand)
    R = ridx.size
    if not R:
        return finish_block(chunk_bytes, starts64, lens64, n, cand,
                            ridx, b"", np.zeros(1, dtype=np.int64),
                            None, suffix, syslen, merger, encoder,
                            scalar_fn=_scalar_jsonl)

    scratch, ts_off, ts_len = span_f64_scratch(
        chunk_bytes, s["tsa_all"][ridx], s["tsb_all"][ridx], display_f64)

    extra_blob = ltsv_extra_blob(encoder.extra)
    consts, offs = build_source(
        b":", b"\t", b"host:", b"\ttime:", b"\tmessage:", b"\tlevel:",
        b"true", b"false", suffix, extra_blob, scratch)
    (o_col, o_tab, o_host, o_time, o_msg, o_lvl, o_true, o_false,
     o_sfx, o_extra, o_ts) = offs
    cbase = int(chunk_arr.size)
    src = np.concatenate([chunk_arr, consts])

    if rop_s.size:
        is_txt = (pv_t == VT_STRING) | (pv_t == VT_NUMBER)
        vs_r = np.where(is_txt, pv_a,
                        np.where(pv_t == VT_TRUE, cbase + o_true,
                                 np.where(pv_t == VT_FALSE,
                                          cbase + o_false, 0)))
        vln = np.where(is_txt, pv_b - pv_a,
                       np.where(pv_t == VT_TRUE, 4,
                                np.where(pv_t == VT_FALSE, 5, 0)))
        pair_flat = (ns_s, ne_s, vs_r, vs_r + vln)
        pc = np.bincount(rop_s, minlength=n)[ridx].astype(np.int64)
    else:
        pair_flat = None
        pc = np.zeros(R, dtype=np.int64)

    host_a, host_b = vspan_at(s["host_f"])
    host_a, host_l = host_a[ridx], (host_b - host_a)[ridx]
    has_host = s["has_host"][ridx]
    msg_a, msg_b = vspan_at(s["msg_f"])
    msg_a, msg_l = msg_a[ridx], (msg_b - msg_a)[ridx]
    has_msg = s["has_msg"][ridx]
    lv_a, _lv_b = vspan_at(s["lvl_f"])
    lv_a = lv_a[ridx]
    has_lvl = s["has_lvl"][ridx]

    cols = (
        (cbase + o_extra, len(extra_blob)),
        (cbase + o_host, len(b"host:")),
        (host_a, np.where(has_host, host_l, 0)),
        (cbase + o_time, len(b"\ttime:")),
        (cbase + o_ts + ts_off, ts_len),
        (np.where(has_msg, cbase + o_msg, 0),
         np.where(has_msg, len(b"\tmessage:"), 0)),
        (msg_a, np.where(has_msg, msg_l, 0)),
        (np.where(has_lvl, cbase + o_lvl, 0),
         np.where(has_lvl, len(b"\tlevel:"), 0)),
        (lv_a, np.where(has_lvl, 1, 0)),
        (cbase + o_sfx, len(suffix)),
    )
    return _ltsv_core(chunk_bytes, starts64, lens64, n, cand, ridx,
                      src, cbase, pc, pair_flat, o_col, o_tab,
                      cols, (), suffix, syslen, merger, encoder,
                      scalar_fn=_scalar_jsonl)
