"""Materialize columnar LTSV decode output into Records.

Schema typing (ltsv_decoder.rs:23-84 semantics) runs here via the scalar
decoder's ``_typed_pair`` — the kernel hands over spans; this stage
builds Python values, routes the special keys, and preserves the scalar
path's side effects (the "Missing value for name" stdout notices, error
precedence)."""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from ..decoders import DecodeError
from ..decoders.ltsv import LTSVDecoder
from ..record import Record, StructuredData
from .materialize import LineResult, compute_ts

_SPECIAL = ("time", "host", "message", "level")


def materialize_ltsv(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    decoder: LTSVDecoder,
) -> List[LineResult]:
    ts_rfc = compute_ts(out).tolist()
    out = {k: np.asarray(v).tolist() for k, v in out.items()}
    ok = out["ok"]
    results: List[LineResult] = []
    for n in range(n_real):
        s = int(starts[n])
        ln = int(orig_lens[n])
        raw = chunk_bytes[s:s + ln]
        try:
            line = raw.decode("utf-8")
        except UnicodeDecodeError:
            results.append(LineResult(None, "__utf8__", ""))
            continue
        if not ok[n] or ln > max_len:
            from ..utils.metrics import registry as _m; _m.inc("fallback_rows")
            results.append(_scalar_ltsv(decoder, line))
            continue
        byte_ok = len(line) == ln
        results.append(_from_spans(line, raw, byte_ok, n, out, ts_rfc, decoder))
    return results


def _scalar_ltsv(decoder: LTSVDecoder, line: str) -> LineResult:
    try:
        return LineResult(decoder.decode(line), None, line)
    except DecodeError as e:
        return LineResult(None, str(e), line)


def _from_spans(line: str, raw: bytes, byte_ok: bool, n: int,
                o: Dict[str, np.ndarray], ts_rfc: np.ndarray,
                decoder: LTSVDecoder) -> LineResult:
    def take(a: int, b: int) -> str:
        if a < 0 or b < a:
            return ""
        if byte_ok:
            return line[a:b]
        return raw[a:b].decode("utf-8")

    # timestamp
    if int(o["ts_kind"][n]) == 0:
        ts = float(ts_rfc[n])
    else:
        ts = float(take(int(o["ts_start"][n]), int(o["ts_end"][n])))

    hostname = take(int(o["host_start"][n]), int(o["host_end"][n])) \
        if int(o["host_pos"][n]) >= 0 else None
    msg = take(int(o["msg_start"][n]), int(o["msg_end"][n])) \
        if int(o["msg_pos"][n]) >= 0 else None
    level = int(o["level_val"][n])
    severity = level if level >= 0 else None

    sd = StructuredData(None)
    try:
        for k in range(int(o["n_parts"][n])):
            ps, pe = int(o["part_start"][n][k]), int(o["part_end"][n][k])
            cp = int(o["colon_pos"][n][k])
            if cp < 0 or cp >= pe:
                name = take(ps, pe)
                print(f"Missing value for name '{name}'")
                continue
            key = take(ps, cp)
            if key in _SPECIAL:
                continue  # routed by the kernel
            value = take(cp + 1, pe)
            sd.pairs.append(decoder._typed_pair(key, value))
    except DecodeError as e:
        return LineResult(None, str(e), line)

    record = Record(
        ts=ts,
        hostname=hostname,
        severity=severity,
        msg=msg,
        full_msg=line,
        sd=[sd] if sd.pairs else None,
    )
    return LineResult(record, None, line)
