"""Device-decode circuit breaker: keep the stream flowing when the
accelerator path degrades.

The batched decode path has a validated scalar fallback (the oracle
decoders produce byte-identical output at lower throughput — the same
property simdjson relies on to treat its fast path as optional).  This
breaker makes the switch automatic and observable:

- ``CLOSED``    — device path in use (normal);
- ``OPEN``      — tripped: every batch decodes through the scalar
  oracle; after ``cooldown_ms`` the next batch probes the device again;
- ``HALF_OPEN`` — one probe batch in flight on the device; success
  closes the breaker, failure re-opens it and restarts the cooldown.

Trips on either of two signals:

- ``failures`` consecutive device/XLA exceptions (each failed batch is
  re-decoded by the oracle in place, so no lines are lost);
- a sustained kernel-fallback ratio: when the last ``window`` batches
  pushed more than ``fallback_ratio`` of their rows through the per-row
  oracle anyway, the device round-trip is pure overhead and the breaker
  trips proactively.

State is exported as the ``device_breaker_state`` gauge (0 closed,
1 open, 2 half-open) plus ``breaker_trips`` / ``breaker_recoveries``
counters, and every transition is logged to stderr.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from typing import Optional

from ..utils.metrics import registry as _metrics

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
_STATE_GAUGE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}

DEFAULT_FAILURES = 3
DEFAULT_COOLDOWN_MS = 5_000
DEFAULT_WINDOW = 8
DEFAULT_FALLBACK_RATIO = 0.95


class DecodeBreaker:
    def __init__(self, failures: int = DEFAULT_FAILURES,
                 cooldown_ms: int = DEFAULT_COOLDOWN_MS,
                 window: int = DEFAULT_WINDOW,
                 fallback_ratio: Optional[float] = DEFAULT_FALLBACK_RATIO,
                 clock=time.monotonic):
        self.failures = max(1, failures)
        self.cooldown_ms = cooldown_ms
        self.window = max(1, window)
        self.fallback_ratio = fallback_ratio
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._ratios: "deque[float]" = deque(maxlen=self.window)
        self._trip_reason: Optional[str] = None  # "errors" | "ratio"
        self._probe_ratio: Optional[float] = None
        self.transitions: list = []  # (monotonic, from, to) history
        # journal events staged under the lock, emitted after release:
        # the journal may write a disk sink, and every thread asking
        # allow() would convoy behind it exactly while the device is
        # degrading (the fairqueue _event_buf pattern)
        self._event_buf: list = []
        # init without clobbering: another handler's breaker may already
        # be publishing a non-closed state on the shared gauge
        _metrics.init_gauge("device_breaker_state", 0)

    @classmethod
    def from_config(cls, config) -> Optional["DecodeBreaker"]:
        """``input.tpu_breaker_*`` keys; returns None (no breaker, legacy
        fail-fast behavior) when ``input.tpu_breaker = false``."""
        enabled = config.lookup_bool(
            "input.tpu_breaker", "input.tpu_breaker must be a boolean", True)
        if not enabled:
            return None
        failures = config.lookup_int(
            "input.tpu_breaker_failures",
            "input.tpu_breaker_failures must be an integer",
            DEFAULT_FAILURES)
        cooldown = config.lookup_int(
            "input.tpu_breaker_cooldown_ms",
            "input.tpu_breaker_cooldown_ms must be an integer (ms)",
            DEFAULT_COOLDOWN_MS)
        window = config.lookup_int(
            "input.tpu_breaker_window",
            "input.tpu_breaker_window must be an integer (batches)",
            DEFAULT_WINDOW)
        ratio = config.lookup_float(
            "input.tpu_breaker_fallback_ratio",
            "input.tpu_breaker_fallback_ratio must be a number in (0, 1]",
            DEFAULT_FALLBACK_RATIO)
        if ratio is not None and not 0.0 < ratio <= 1.0:
            from ..config import ConfigError

            raise ConfigError(
                "input.tpu_breaker_fallback_ratio must be a number in (0, 1]")
        return cls(failures=failures, cooldown_ms=cooldown, window=window,
                   fallback_ratio=ratio)

    # -- state machine -----------------------------------------------------
    def _transition(self, new: str, count_trip: bool = True) -> None:
        """Runs under ``self._lock``; journal events are staged into
        ``_event_buf`` and emitted by ``_drain_events`` after the caller
        releases the lock."""
        old, self._state = self._state, new
        self.transitions.append((self._clock(), old, new))
        _metrics.set_gauge("device_breaker_state", _STATE_GAUGE[new])
        msg = f"device-decode breaker: {old} -> {new}"
        if new == OPEN and count_trip:
            # re-opens after an uncured probe are the SAME logical trip:
            # breaker_trips counts trip events, not cooldown cycles —
            # and exactly one journal event per trip, same contract
            _metrics.inc("breaker_trips")
            self._event_buf.append(("breaker_trip", dict(
                detail=self._trip_reason or "errors",
                cost=self.cooldown_ms / 1000.0,
                cost_unit="cooldown_s", msg=msg)))
        elif new == CLOSED and old != CLOSED:
            _metrics.inc("breaker_recoveries")
            self._event_buf.append(("breaker_recover", dict(msg=msg)))
        else:
            print(msg, file=sys.stderr)
        if new == OPEN:
            self._opened_at = self._clock()

    def _drain_events(self) -> None:
        """Emit staged transition events outside the lock."""
        if not self._event_buf:
            return
        with self._lock:
            staged, self._event_buf = self._event_buf, []
        from ..obs import events as _events

        for reason, kwargs in staged:
            _events.emit("breaker", reason, **kwargs)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May this batch take the device path?  In OPEN state, the first
        call after the cooldown becomes the half-open probe; everything
        else stays on the oracle."""
        with self._lock:
            out = self._allow_locked()
        self._drain_events()
        return out

    def _allow_locked(self) -> bool:
        if self._state == CLOSED:
            return True
        if self._state == OPEN:
            elapsed_ms = (self._clock() - self._opened_at) * 1000.0
            if elapsed_ms >= self.cooldown_ms:
                self._transition(HALF_OPEN)
                return True  # this batch is the probe
            return False
        return False  # HALF_OPEN: probe already in flight

    def record_success(self) -> None:
        with self._lock:
            self._record_success_locked()
        self._drain_events()

    def _record_success_locked(self) -> None:
        self._consecutive = 0
        if self._state == HALF_OPEN:
            if (self._trip_reason == "ratio"
                    and self.fallback_ratio is not None
                    and self._probe_ratio is not None
                    and self._probe_ratio > self.fallback_ratio):
                # the device is healthy but the stream still pushes
                # nearly every row through the oracle: a "success"
                # doesn't cure a ratio trip — stay open (one probe
                # per cooldown, not an open/close flap every window)
                self._probe_ratio = None
                self._transition(OPEN, count_trip=False)
                return
            self._ratios.clear()
            self._trip_reason = None
            self._probe_ratio = None
            self._transition(CLOSED)

    def record_failure(self, error: BaseException) -> None:
        _metrics.inc("device_decode_errors")
        with self._lock:
            self._record_failure_locked(error)
        self._drain_events()

    def _record_failure_locked(self, error: BaseException) -> None:
        if self._state == HALF_OPEN:
            # failed probe: back to cooldown (same logical trip)
            self._transition(OPEN, count_trip=False)
            return
        self._consecutive += 1
        if self._state == CLOSED and self._consecutive >= self.failures:
            print(
                f"device-decode breaker tripping after "
                f"{self._consecutive} consecutive device errors "
                f"(last: {error})", file=sys.stderr)
            self._trip_reason = "errors"
            self._transition(OPEN)

    def observe_batch(self, n_rows: int, fallback_rows: int) -> None:
        """Feed one successful device batch's oracle-fallback share; a
        full window above the threshold trips the breaker (the device
        round-trip is not earning its keep)."""
        if self.fallback_ratio is None or n_rows <= 0:
            return
        with self._lock:
            self._observe_batch_locked(n_rows, fallback_rows)
        self._drain_events()

    def _observe_batch_locked(self, n_rows: int, fallback_rows: int) -> None:
        if self._state == HALF_OPEN:
            # the probe batch's own ratio: record_success consults it
            # to decide whether a ratio trip is actually cured
            self._probe_ratio = fallback_rows / n_rows
            return
        if self._state != CLOSED:
            return
        self._ratios.append(fallback_rows / n_rows)
        if (len(self._ratios) == self.window
                and min(self._ratios) > self.fallback_ratio):
            print(
                f"device-decode breaker tripping: fallback ratio > "
                f"{self.fallback_ratio} over the last {self.window} "
                f"batches", file=sys.stderr)
            self._ratios.clear()
            self._trip_reason = "ratio"
            self._transition(OPEN)
