r"""Shared JSON structural index (simdjson stage 1, arxiv 1902.08318).

ONE implementation of the batched flat-JSON tokenizer both JSON paths
ride — ``tpu/gelf.py`` (GELF's flat-JSON screen) and ``tpu/jsonl.py``
(generic JSON-lines) — so the quote-parity string masking, the
bit-packed backslash ladder, and the packed-ordinal span extractors are
single-sourced and the two decoders cannot drift.

Stage-1 plan (all branchless, no gathers — see tpu/gelf.py's module
docstring for the scan-free design history):

- byte classification: whitespace / quote / backslash / structural
  planes straight off the [N, L] batch;
- quote parity classifies in/out-of-string (escaped quotes via the
  shared bit-packed backslash ladder, ``rfc5424._esc_parity``);
- bounded-window lookarounds (one packed reduce-window each way)
  answer "previous/next significant byte" for token-role assignment;
- key/value spans extract via packed-ordinal matmul sums keyed on the
  key-open ordinal plane (``rfc5424.extract_by_ord``).

``nested`` extends the index with a **structural-character depth
channel** (cumsum of opens minus closes outside strings): top-level
container values (``"k": {...}`` / ``"k": [...]``) become spans of
class VT_OBJECT / VT_ARRAY whose extents pair the depth-1→2 open with
the matching 2→1 close by key ordinal — contents nest arbitrarily up
to ``nested`` levels; deeper rows flag to the scalar oracle.  With
``nested=0`` (the GELF screen) any bracket outside a string
disqualifies the row, preserving the flat-only contract byte for byte.

Anything structurally surprising (stray tokens, >1 value per key,
window overflow, unbalanced anything) flags the row ``ok=False`` so the
caller's scalar oracle keeps observable output byte-identical.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from .rfc5424 import (
    _bitpack32,
    _esc_parity,
    _row_all,
    _row_any,
    _row_max,
    _row_min,
    _row_sum,
    _scan_ordinals,
    _slot_geometry,
    _shift_left,
    _shift_right,
    extract_by_ord,
    extract_counts_by_ord,
)

WS_WINDOW = 8
_I32 = jnp.int32

# value token classes.  VT_OBJECT/VT_ARRAY only appear with nested > 0.
VT_STRING, VT_NUMBER, VT_TRUE, VT_FALSE, VT_NULL = 0, 1, 2, 3, 4
VT_OBJECT, VT_ARRAY = 5, 6


# ---------------------------------------------------------------------------
# compiled-NFA string machine (the Pallas stage-1 classifier's core)
#
# The string/escape automaton as an explicit DFA over byte classes,
# resolved in parallel by composing packed transition *functions* with
# a log-shift ladder — the classic parallel-automaton scan (ParPaRaw's
# quote/escape machinery, arxiv 1905.13415, and simdjson's stage-1
# classification recast as one scan).  Four states track (in-string,
# backslash-run parity):
#
#   0 = outside string, even bs-run   2 = inside string, even bs-run
#   1 = outside string, odd  bs-run   3 = inside string, odd  bs-run
#
# Each byte class maps to a state->state function packed 2 bits per
# state into one i32 (NFA_TABLE below — the "transition table": tiny
# scalar constants that live in SMEM / fold into the kernel as
# immediates).  Composition of two packed functions is branchless
# elementwise shift arithmetic, so an inclusive prefix composition is
# log2(L) compose steps — one automaton scan replaces the separate
# quote-parity cumsum + backslash XOR ladder of the parity path, and
# every op lowers under Mosaic (no gather, no scan primitive).
#
# Escape semantics mirror ``rfc5424._esc_parity`` exactly: a quote is
# escaped iff the backslash run ending just before it has odd length
# (tracked by the parity bit even *outside* strings, so junk like a
# lone ``\"`` at top level classifies identically to the parity path).

_S = 4                      # automaton states
_SB = 2                     # bits per state in a packed function


def _nfa_pack(dsts):
    """Pack a state->state map (tuple of _S destinations) into an i32."""
    word = 0
    for s, d in enumerate(dsts):
        word |= d << (_SB * s)
    return word


# byte class -> packed transition function
NFA_OTHER = _nfa_pack((0, 0, 2, 2))    # bs-run parity resets
NFA_QUOTE = _nfa_pack((2, 0, 0, 2))    # real toggles; escaped stays
NFA_BS = _nfa_pack((1, 0, 3, 2))       # parity toggles
NFA_IDENT = _nfa_pack((0, 1, 2, 3))    # ladder fill / start-of-row
NFA_TABLE = (NFA_OTHER, NFA_QUOTE, NFA_BS)


def _nfa_compose(g, f):
    """h = g∘f over packed transition functions (elementwise, variable
    shifts only — Mosaic-lowerable)."""
    h = jnp.zeros_like(f)
    for s in range(_S):
        fs = (f >> (_SB * s)) & (_S - 1)
        h = h | (((g >> (_SB * fs)) & (_S - 1)) << (_SB * s))
    return h


def _nfa_string_machine(quote, is_bs):
    """Resolve the string/escape automaton over [N, L] quote/backslash
    planes.  Returns ``(outside, escaped)``: the *exclusive* state at
    each position (the state in which its byte is consumed) projected
    to the outside-string and odd-backslash-parity predicates — exactly
    the planes the parity path derives from ``_esc_parity`` + the
    real-quote cumsum, computed here by one transition-function scan."""
    L = quote.shape[1]
    f = jnp.where(quote, NFA_QUOTE,
                  jnp.where(is_bs, NFA_BS, NFA_OTHER)).astype(_I32)
    k = 1
    while k < L:
        f = _nfa_compose(f, _shift_right(f, k, NFA_IDENT))
        k <<= 1
    st = _shift_right(f, 1, NFA_IDENT) & (_S - 1)  # state from start 0
    outside = st < 2
    escaped = (st & 1) == 1
    return outside, escaped


def _esc_cap_plane(is_bs):
    """Positions whose preceding backslash run reached ESC_RUN_CAP —
    the same cap plane ``_esc_parity(impl='manual')`` derives, computed
    standalone for the NFA path (whose escape parity is exact at any
    run length; the cap keeps row-flagging identical to the parity
    path, so both tiers send the same rows to the scalar oracle)."""
    from .rfc5424 import ESC_RUN_CAP

    a_k = _shift_right(is_bs, 1, False)
    for k in range(2, ESC_RUN_CAP + 1):
        a_k = a_k & _shift_right(is_bs, k, False)
    return a_k


# ---------------------------------------------------------------------------
# bounded-window lookarounds: reduce_window on the XLA paths, a
# (W-1)-step shift ladder under ``manual`` (Mosaic has no reduce_window)

def _window_max_before(v, W, fill, manual):
    """max of v over the W positions ending at each position."""
    if not manual:
        return jax.lax.reduce_window(v, fill, jax.lax.max, (1, W), (1, 1),
                                     ((0, 0), (W - 1, 0)))
    m = v
    for k in range(1, W):
        m = jnp.maximum(m, _shift_right(v, k, fill))
    return m


def _window_min_after(v, W, fill, manual):
    """min of v over the W positions starting at each position."""
    if not manual:
        return jax.lax.reduce_window(v, fill, jax.lax.min, (1, W), (1, 1),
                                     ((0, 0), (0, W - 1)))
    m = v
    for k in range(1, W):
        m = jnp.minimum(m, _shift_left(v, k, fill))
    return m


def _window_sum_before(v, W, manual):
    """sum of v over the W positions ending at each position."""
    if not manual:
        return jax.lax.reduce_window(v, jnp.int32(0), jax.lax.add,
                                     (1, W), (1, 1), ((0, 0), (W - 1, 0)))
    s = v
    for k in range(1, W):
        s = s + _shift_right(v, k, 0)
    return s


def structural_index(batch: jnp.ndarray, lens: jnp.ndarray,
                     max_fields: int, scan_impl: str, extract_impl: str,
                     nested: int = 0, string_impl: str = "parity"
                     ) -> Dict[str, jnp.ndarray]:
    """Tokenize a packed [N, L] batch of one-JSON-object lines into
    per-key span channels (see module docstring).  Returns the channel
    dict shared by the GELF and JSON-lines decoders.

    ``string_impl`` picks the in/out-of-string classifier: ``"parity"``
    (quote-parity cumsum + the bit-packed backslash XOR ladder — the
    XLA paths) or ``"nfa"`` (one compiled-NFA transition-function scan,
    the Pallas stage-1 path; identical planes on every row the parity
    ladder classifies exactly, and identical row *flagging* everywhere
    via the shared ESC_RUN_CAP plane)."""
    N, L = batch.shape
    manual = scan_impl == "manual"
    lens = lens.astype(_I32)
    iota = jax.lax.broadcasted_iota(_I32, (N, L), 1)
    valid = iota < lens[:, None]
    # uint8 byte plane (see rfc5424.py): widen inside consumer fusions
    bb = jnp.where(valid, batch, jnp.asarray(0, batch.dtype))

    is_ws = ((bb == 32) | (bb == 9) | (bb == 10) | (bb == 13)) & valid
    nonws = valid & ~is_ws

    # ---- escaped quotes & string parity ---------------------------------
    is_bs = (bb == 92) & valid
    quote = (bb == ord('"')) & valid
    if string_impl == "nfa":
        outside, escaped = _nfa_string_machine(quote, is_bs)
        real_q = quote & ~escaped
        cap_viol = _row_any(_esc_cap_plane(is_bs) & quote, manual)
    else:
        escaped, cap_plane, cap_words = _esc_parity(is_bs, scan_impl)
        real_q = quote & ~escaped
        if cap_plane is not None:
            cap_viol = _row_any(cap_plane & quote, manual)
        else:
            cap_viol = jnp.any((cap_words & _bitpack32(quote)) != 0,
                               axis=1)
        (q_incl,) = _scan_ordinals([real_q], scan_impl)
        q_excl = q_incl - real_q.astype(q_incl.dtype)
        outside = (q_excl & 1) == 0
    open_q = real_q & outside
    close_q = real_q & ~outside
    inside_str = (~outside) & valid
    ok = ~cap_viol

    # ---- bounded-window lookarounds -------------------------------------
    # ptb/ntb: byte of the nearest non-ws position within WS_WINDOW
    # before/after each position (0 when none in window).  Rows with a
    # longer outside-string whitespace run fall back, so "not found in
    # window" can never silently mean "found nothing relevant".  One
    # packed (position << 8 | byte) reduce-window pass each way.
    bi32 = bb.astype(_I32)
    pv = jnp.where(nonws, (iota << 8) | bi32, -1)
    rw_p = _window_max_before(pv, WS_WINDOW, jnp.int32(-1), manual)
    ptb_w = _shift_right(rw_p, 1, -1)
    ptb = jnp.where(ptb_w >= 0, ptb_w & 255, 0)
    _BIG = jnp.int32(1 << 30)
    nv = jnp.where(nonws, (iota << 8) | bi32, _BIG)
    rw_n = _window_min_after(nv, WS_WINDOW, _BIG, manual)
    ntb_w = _shift_left(rw_n, 1, _BIG)
    ntb = jnp.where(ntb_w < _BIG, ntb_w & 255, 0)

    # ws run > WS_WINDOW outside strings: a windowed count hitting W+1
    # (edge padding contributes 0, so short runs at the line start can
    # never flag, matching the shifted-AND ladder's False fill)
    run = is_ws & outside
    rw_run = _window_sum_before(run.astype(_I32), WS_WINDOW + 1, manual)
    # every row-disqualifying plane ORs into one mask reduced by a
    # single any at the end
    viol = rw_run == WS_WINDOW + 1

    # ---- structure: braces, brackets, depth -----------------------------
    lb = (bb == ord("{")) & outside
    rb = (bb == ord("}")) & outside
    lsb = (bb == ord("[")) & outside
    rsb = (bb == ord("]")) & outside
    if nested:
        open_br = lb | lsb
        close_br = rb | rsb
        cum_open, cum_close = _scan_ordinals([open_br, close_br],
                                             scan_impl)
        # inclusive depth: an open counts at its own position, a close
        # uncounts at its own — so the top-level '{' sits at depth 1,
        # a nested open at >= 2, a top-level-value close back at 1,
        # and the final '}' at 0
        depth = cum_open.astype(_I32) - cum_close.astype(_I32)
        viol |= (depth < 0) & valid
        max_depth = _row_max(jnp.where(valid, depth, 0), manual)
        ok &= max_depth <= 1 + nested
        top = depth == 1
        # exactly one depth-1 '{' (the object) and one depth-0 '}'
        # (its close); '['/']' may only appear inside a value
        lb_top = lb & top
        rb_end = rb & (depth == 0)
        viol |= lsb & top
        # ends of top-level container values; like a string value
        # close, the next significant byte must be ',' or '}'
        nested_close = close_br & top & ~rb_end
        viol |= nested_close & (ntb != ord(",")) & (ntb != ord("}"))
        # a depth-1→2 open is only legal in value position
        cont_start = open_br & (depth == 2)
        is_cont_val = cont_start & (ptb == ord(":"))
        viol |= cont_start & ~is_cont_val
    else:
        depth = None
        top = outside
        lb_top, rb_end = lb, rb
        viol |= (lsb | rsb)
        nested_close = jnp.zeros_like(lb)
        is_cont_val = jnp.zeros_like(lb)
    # first/last non-ws position with an is-it-the-brace tag packed into
    # the reduction word: first significant byte must be the object
    # open, last must be its close
    wf = _row_min(jnp.where(nonws, 2 * iota + (~lb).astype(_I32),
                            2 * L + 2), manual)
    first_is_lb = (wf & 1) == 0
    first_nonws = wf >> 1
    wl = _row_max(jnp.where(nonws, 2 * iota + rb.astype(_I32), -1),
                  manual)
    last_is_rb = (wl & 1) == 1
    last_nonws = wl >> 1
    ok &= first_is_lb & last_is_rb & (first_nonws < last_nonws)

    # ---- token roles (elementwise, top level only) ----------------------
    # an open quote sits at an outside-string (even-parity) position;
    # a CLOSE quote is inside its own string by parity, so its
    # top-levelness comes from the depth channel alone (depth never
    # changes inside a string — brackets there are parity-masked out)
    if nested:
        top_open_q = open_q & top
        top_close_q = close_q & (depth == 1)
    else:
        top_open_q = open_q
        top_close_q = close_q
    if nested:
        # quotes inside nested containers (depth >= 2) carry no
        # top-level role; an outside-string quote at depth <= 0 sits
        # before the object open / after its close — structurally junk
        viol |= open_q & ~top & (depth < 2)
    is_key_open = top_open_q & ((ptb == ord("{")) | (ptb == ord(",")))
    is_val_open = top_open_q & (ptb == ord(":"))
    viol |= top_open_q & ~is_key_open & ~is_val_open
    is_key_close = top_close_q & (ntb == ord(":"))
    is_val_close = top_close_q & ~is_key_close
    # a value close must be followed by ',' or '}'
    viol |= is_val_close & (ntb != ord(",")) & (ntb != ord("}"))

    colon_out = (bb == ord(":")) & top & valid
    comma_out = (bb == ord(",")) & top & valid
    # every comma introduces another key (next non-ws is a quote)
    viol |= comma_out & (ntb != ord('"'))

    key_ord, kc_ord = _scan_ordinals(
        [is_key_open, is_key_close], scan_impl)
    # row counts ride packed sums, as many per-count fields per i32
    # word as L allows; the ordinal-plane maxes equal plain mask counts
    # because the ordinals are inclusive cumsums
    cbits, per, cmask = _slot_geometry(L)

    def packed_counts(masks):
        outs = []
        for base in range(0, len(masks), per):
            grp = masks[base:base + per]
            acc = grp[0].astype(_I32)
            for s, m in enumerate(grp[1:], 1):
                acc = acc + (m.astype(_I32) << (cbits * s))
            word = _row_sum(acc, manual)
            for s in range(len(grp)):
                outs.append((word >> (cbits * s)) & cmask)
        return outs

    count_masks = [real_q, lb_top, rb_end, is_key_open, is_key_close,
                   colon_out, comma_out]
    if nested:
        count_masks += [lb | lsb, rb | rsb]
        (n_quotes, lbc, rbc, n_keys, n_kc, n_colons, n_commas,
         n_open, n_close) = packed_counts(count_masks)
        ok &= n_open == n_close  # balanced brackets
    else:
        n_quotes, lbc, rbc, n_keys, n_kc, n_colons, n_commas = \
            packed_counts(count_masks)
    ok &= (n_quotes & 1) == 0  # every string closed
    ok &= (lbc == 1) & (rbc == 1)
    ok &= n_kc == n_keys
    ok &= n_keys <= max_fields
    ok &= n_colons == n_keys
    ok &= n_commas == jnp.maximum(n_keys - 1, 0)

    # ---- literal/number runs --------------------------------------------
    structural = (colon_out | comma_out | lb | rb | real_q)
    if nested:
        structural = structural | lsb | rsb
        is_lit = nonws & outside & top & ~structural
    else:
        is_lit = nonws & outside & ~structural
    lit_start = is_lit & ~_shift_right(is_lit, 1, False)
    lit_end_m = is_lit & ~_shift_left(is_lit, 1, False)
    # nothing significant may precede the first key
    viol |= is_lit & (key_ord == 0)
    # backslashes are only legal inside strings; a bs "outside" (per
    # possibly-garbled parity) sends the row to the oracle, which also
    # shields the parity math itself from junk input
    viol |= is_bs & outside
    ok &= ~_row_any(viol, manual)

    # number/literal value start: a literal-run start whose previous
    # non-ws byte is ':'
    is_lit_val = lit_start & (ptb == ord(":"))
    is_val_start = is_val_open | is_lit_val | is_cont_val
    # literal tokens match against a packed next-4-bytes word; high
    # input bytes overflow into the sign bit deterministically and can
    # never collide with the ASCII token constants
    w2 = (bi32 << 8) | _shift_left(bi32, 1, 0)
    w4 = (w2 << 16) | _shift_left(w2, 2, 0)
    true_at = w4 == int.from_bytes(b"true", "big")
    null_at = w4 == int.from_bytes(b"null", "big")
    false_at = (w4 == int.from_bytes(b"fals", "big")) & \
        (_shift_left(bi32, 4, 0) == ord("e"))
    is_num0 = ((bb >= 48) & (bb <= 57)) | (bb == ord("-"))
    vclass = jnp.where(
        is_val_open, 1 + VT_STRING,
        jnp.where(true_at, 1 + VT_TRUE,
                  jnp.where(false_at, 1 + VT_FALSE,
                            jnp.where(null_at, 1 + VT_NULL,
                                      jnp.where(is_num0, 1 + VT_NUMBER,
                                                0)))))
    if nested:
        vclass = jnp.where(
            is_cont_val,
            jnp.where(bb == ord("{"), 1 + VT_OBJECT, 1 + VT_ARRAY),
            vclass)

    # ---- per-key extraction (packed-sum words) --------------------------
    F = max_fields
    key_open_pos = extract_by_ord(is_key_open, key_ord, iota, F, L,
                                  extract_impl, manual=manual)
    key_close_pos = extract_by_ord(is_key_close, kc_ord, iota, F, L,
                                   extract_impl, manual=manual)
    # value position and class share one extraction word per slot: the
    # class rides bits above the position field (fill L keeps the class
    # field 0; classes span 1..7, exactly the 3-bit field)
    pbits = max(10, int(L + 1).bit_length())
    vs_packed = extract_by_ord(is_val_start, key_ord,
                               iota | (vclass << pbits), F, L,
                               extract_impl, slot_bits=pbits + 3, manual=manual)
    val_start_pos = vs_packed & ((1 << pbits) - 1)
    val_class1 = vs_packed >> pbits
    val_close_pos = extract_by_ord(is_val_close, key_ord, iota, F, L,
                                   extract_impl, manual=manual)
    lit_end_pos = extract_by_ord(lit_end_m, key_ord, iota, F, L,
                                 extract_impl, manual=manual)
    # exactly one value token per key: a string close, a literal run,
    # or (nested mode) a container open.  Key ordinals are constant
    # across a container's interior — quotes/commas/colons there sit at
    # depth >= 2 and never open a new top-level key — so the close
    # extraction below keys on the same ordinal as its open.
    val_token_m = is_val_close | lit_start
    if nested:
        val_token_m = val_token_m | is_cont_val
    val_tokens = extract_counts_by_ord(val_token_m, key_ord, F,
                                       extract_impl, manual=manual)
    esc_count = extract_counts_by_ord(is_bs & inside_str, key_ord, F,
                                      extract_impl, manual=manual)

    field_valid = (jnp.arange(F, dtype=_I32)[None, :] < n_keys[:, None])
    ok &= _row_all(jnp.where(field_valid, val_tokens == 1,
                             val_tokens == 0), manual)
    ok &= _row_all(jnp.where(field_valid, val_class1 >= 1, True), manual)
    val_type = jnp.where(field_valid, val_class1 - 1, -1)

    # per-key ordering sanity: open < close < value start
    ok &= _row_all(jnp.where(field_valid,
                             (key_open_pos < key_close_pos)
                             & (key_close_pos < val_start_pos), True),
                   manual)
    # extraction-collision guard: multiple val-starts per key would
    # corrupt the packed sums — val_tokens==1 bounds val_close/lit
    # runs/container opens, and >1 val_start implies >1 of those (the
    # former is bounded; a second val_open implies a second ':' which
    # the colon count bounds)

    # string values: close quote; containers: matching close bracket;
    # literals: last run byte + 1
    is_string = val_type == VT_STRING
    if nested:
        cont_close_pos = extract_by_ord(nested_close, key_ord, iota, F,
                                        L, extract_impl, manual=manual)
        is_cont = (val_type == VT_OBJECT) | (val_type == VT_ARRAY)
        val_end = jnp.where(
            is_string, val_close_pos,
            jnp.where(is_cont, cont_close_pos + 1, lit_end_pos + 1))
        ok &= _row_all(jnp.where(field_valid & is_cont,
                                 cont_close_pos > val_start_pos, True),
                       manual)
    else:
        val_end = jnp.where(is_string, val_close_pos, lit_end_pos + 1)
    val_end = jnp.minimum(val_end, lens[:, None])
    # literal token length must match exactly (rejects "truex")
    lit_len = jnp.where(val_type == VT_TRUE, 4,
                        jnp.where(val_type == VT_FALSE, 5,
                                  jnp.where(val_type == VT_NULL, 4, -1)))
    ok &= _row_all(jnp.where(field_valid & (lit_len > 0),
                             val_end - val_start_pos == lit_len, True),
                   manual)
    # string values must close after they open
    ok &= _row_all(jnp.where(field_valid & is_string,
                             val_close_pos > val_start_pos, True), manual)

    esc_flag = (esc_count > 0) & field_valid

    return {
        "ok": ok,
        # n_fields stays un-zeroed on not-ok rows so the fetch-side
        # rescue can screen precisely; every consumer gates on ok
        # before reading it
        "n_fields": n_keys,
        "key_start": key_open_pos + 1, "key_end": key_close_pos,
        "val_start": jnp.where(is_string, val_start_pos + 1,
                               val_start_pos),
        "val_end": val_end,
        "val_type": val_type,
        "key_esc": esc_flag, "val_esc": esc_flag & is_string,
    }
