"""Columnar LTSV→GELF encoding: the LTSV kernel's part/special-key span
tables become framed GELF bytes per batch.

An untyped LTSV record (materialize_ltsv.py, no ``ltsv_schema``/
``ltsv_suffixes`` configured) maps to the sorted-key GELF object::

    {"_<key>":V..., "full_message":L, "host":H, ["level":N,]
     "short_message":M|-, "timestamp":T, "version":"1.1"}

Pair keys are emitted sorted (the shared uint64-word lexsort), values
JSON-escaped via the sparse EscapeMap.  Typed ``ltsv_schema`` keys stay
on the fast tier when their rendered bytes equal the raw span (bool
``true``/``false`` literals, canonical u64/i64 integers, f64 values that
roundtrip through json_f64 — emitted bare); non-canonical numbers,
configured name suffixes,
duplicate keys, colon-less parts (the scalar path prints a "Missing
value" notice), and non-ASCII bytes re-run the scalar oracle, keeping
bytes identical to decoder→GelfEncoder.
"""


from __future__ import annotations

# byte-identity contract (flowcheck FC03): the scalar counterpart
# this route must stay byte-identical to, and the differential
# test that enforces it
SCALAR_ORACLE = "flowgger_tpu.encoders.gelf:GelfEncoder"
DIFF_TEST = "tests/test_encode_gelf_block.py::test_ltsv_gelf_block_route_matches_scalar"

from typing import Dict, Optional

import numpy as np

from ..mergers import Merger
from ..utils.rustfmt import json_f64
from .assemble import (
    build_source,
    concat_segments,
    count_in_spans,
    escape_json,
    exclusive_cumsum,
)
from .block_common import (
    BlockResult,
    apply_syslen_prefix,
    finish_block,
    merger_suffix,
    sorted_pair_order,
    ts_scratch,
)
from .materialize_ltsv import _scalar_ltsv

_C_P0 = b'"_'
_C_P1 = b'":"'
_C_P2 = b'",'
_C_FULL = b'"full_message":"'
_C_HOST = b'","host":"'
_C_LEVEL = b'","level":'
_C_SHORT_LVL = b',"short_message":'    # after the bare level number
_C_SHORT = b'","short_message":'      # closing the host string
_C_TS = b',"timestamp":'
_C_TAIL = b',"version":"1.1"}'
_C_UNKNOWN = b"unknown"
_C_DASH = b'"-"'
_C_SEVD = b"01234567"
_NAME_CAP = 48

_FIXED_LTSV = ("full_message", "host", "level", "short_message",
               "timestamp", "version")


def gelf_extra_consts_ltsv(extra):
    """Fold ``[output.gelf_extra]`` pairs into this layout's constants
    (static BTreeMap placement, same idea as the rfc5424/rfc3164
    renderers).  Slot chain: pre-pairs (k < "_"), post-pairs
    ("_" < k < full_message), then the gated-level chain shared with
    the rfc3164 layout — except the short value here closes its own
    quote, so the short→timestamp slot is after-number form.  Returns
    (open, full_c, host_c, hl, l2_pri, l2_nopri, ts_c, tail_c) or None
    when a key needs dynamic placement (leading '_' interleaves with
    the pair keys; fixed keys overwrite)."""
    from .block_common import extra_forms, extra_tail

    pre = post = fh = hl = b""
    l2a = l2b = b""
    st = tv = vz = b""
    for k, v in sorted(extra or ()):
        if k.startswith("_") or k in _FIXED_LTSV:
            return None
        sf, sc, nm = extra_forms(k, v)
        if k < "_":
            pre += sf
        elif k < "full_message":
            post += sf
        elif k < "host":
            fh += sc
        elif k < "level":
            hl += sc
        elif k < "short_message":
            l2a += nm
            l2b += sc
        elif k < "timestamp":
            st += nm                           # short value self-closes
        elif k < "version":
            tv += nm
        else:
            vz += sc
    return (b"{" + pre, post + _C_FULL, fh + _C_HOST, hl, l2a, l2b,
            st + _C_TS, extra_tail(_C_TAIL, tv, vz))


def encode_ltsv_gelf_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
    decoder,
) -> Optional[BlockResult]:
    spec = merger_suffix(merger)
    if spec is None:
        return None
    econsts = gelf_extra_consts_ltsv(encoder.extra)
    if econsts is None:
        return None
    (c_open, c_full, c_host, c_hl, c_l2a, c_l2b, c_ts, c_tail) = econsts
    schema = decoder.schema or {}
    if schema:
        # typed keys are supported on the fast tier when rendered bytes
        # equal the raw span (canonical integers, the exact true/false
        # literals, json_f64-roundtripping floats); any configured name
        # suffix and big schemas take the Record path
        if len(schema) > 8:
            return None
        if any(decoder.suffixes.get(t) is not None
               for t in set(schema.values())):
            return None

    n = int(n_real)
    starts64 = np.asarray(starts[:n], dtype=np.int64)
    lens64 = np.asarray(orig_lens[:n], dtype=np.int64)
    suffix, syslen = spec
    ok = np.asarray(out["ok"][:n], dtype=bool)
    has_high = np.asarray(out["has_high"][:n], dtype=bool)
    n_parts = np.asarray(out["n_parts"])[:n].astype(np.int64)
    part_start = np.asarray(out["part_start"])[:n]
    part_end = np.asarray(out["part_end"])[:n]
    colon_pos = np.asarray(out["colon_pos"])[:n]
    host_pos = np.asarray(out["host_pos"])[:n]
    ts_kind = np.asarray(out["ts_kind"])[:n]

    P = part_start.shape[1]
    jmask = np.arange(P)[None, :] < n_parts[:, None]
    cand = ok & (lens64 <= max_len) & ~has_high & (host_pos >= 0)
    # colon-less parts trigger the scalar path's stdout notice
    cand &= ~(jmask & (colon_pos < 0)).any(axis=1)
    # pair-name length cap for the sort-key matrix; special keys are
    # excluded from pairs but bound the same way for simplicity
    nlen = np.where(jmask, colon_pos - part_start, 0)
    cand &= nlen.max(axis=1, initial=0) <= _NAME_CAP

    chunk_arr = np.frombuffer(chunk_bytes, dtype=np.uint8)

    # pair table: parts whose key NAME is not one of the special keys
    # (shared screen, block_common.ltsv_special_screen — the kernel's
    # special positions only catch the LAST occurrence; rows with
    # repeated special names drop to the oracle for exact parity)
    from .block_common import ltsv_special_screen

    special_name, uniq_ok = ltsv_special_screen(
        chunk_arr, starts64, part_start, nlen, jmask)
    cand &= uniq_ok
    is_pair = jmask & ~special_name & cand[:, None]

    pc = is_pair.sum(axis=1).astype(np.int64)
    T = int(pc.sum())
    if T:
        rows_all, cols_all = np.nonzero(is_pair)
        rop = rows_all.astype(np.int64)
        ns_abs = starts64[rop] + part_start[rows_all, cols_all]
        ne_abs = starts64[rop] + colon_pos[rows_all, cols_all]
        vs_abs = ne_abs + 1
        ve_abs = starts64[rop] + part_end[rows_all, cols_all]
        # typed-schema pair classification: 0 string, 1 bare literal
        # (bool true/false, canonical int, or canonical f64 — rendered
        # bytes equal the span), 2 needs-oracle (non-canonical)
        ptype = np.zeros(T, dtype=np.int8)
        if schema:
            # zero-padded view for fixed-width gathers past span ends
            # (kernel fill values are bounded by the row-relative
            # max_len); only the typed classification needs it
            chunk_pad = np.concatenate(
                [chunk_arr, np.zeros(max_len + 16, dtype=np.uint8)])
            nlen_p = ne_abs - ns_abs
            vlen_p = ve_abs - vs_abs
            vfirst = chunk_pad[vs_abs]
            vsecond = chunk_pad[np.minimum(vs_abs + 1, vs_abs + vlen_p - 1
                                           + (vlen_p == 0))]

            def name_match(word: bytes):
                m = nlen_p == len(word)
                if not m.any():
                    return m
                rr = np.flatnonzero(m)
                okb = np.ones(rr.size, dtype=bool)
                base = ns_abs[rr]
                for i, ch in enumerate(word):
                    okb &= chunk_pad[base + i] == ch
                out_m = np.zeros(T, dtype=bool)
                out_m[rr[okb]] = True
                return out_m

            def literal_match(word: bytes):
                m = vlen_p == len(word)
                if not m.any():
                    return m
                rr = np.flatnonzero(m)
                okb = np.ones(rr.size, dtype=bool)
                base = vs_abs[rr]
                for i, ch in enumerate(word):
                    okb &= chunk_pad[base + i] == ch
                out_m = np.zeros(T, dtype=bool)
                out_m[rr[okb]] = True
                return out_m

            # canonical integer spans: optional single '-', digits only,
            # no leading zero (except exactly "0"), no '+', not "-0..."
            dig_cum = np.cumsum(~((chunk_arr >= ord("0"))
                                  & (chunk_arr <= ord("9"))))
            neg = vfirst == ord("-")
            nondig = count_in_spans(dig_cum, vs_abs, ve_abs)
            dlen = vlen_p - neg
            int_canon = ((dlen >= 1) & (dlen <= 18)
                         & (nondig == neg.astype(np.int64))
                         & ~((vfirst == ord("0")) & (vlen_p > 1))
                         & ~(neg & (vsecond == ord("0"))))
            for key, sdtype in schema.items():
                m = name_match(key.encode("utf-8"))
                if not m.any():
                    continue
                if sdtype == "string":
                    continue
                if sdtype == "bool":
                    okv = literal_match(b"true") | literal_match(b"false")
                    ptype = np.where(m, np.where(okv, 1, 2), ptype)
                elif sdtype == "u64":
                    okv = int_canon & ~neg
                    ptype = np.where(m, np.where(okv, 1, 2), ptype)
                elif sdtype == "i64":
                    ptype = np.where(m, np.where(int_canon, 1, 2), ptype)
                elif sdtype == "f64":
                    # canonical f64 spans: the raw bytes equal the
                    # encoder's shortest-roundtrip rendering (json_f64)
                    # of the parsed value, so bare emission is
                    # byte-identical to the oracle.  Padded zeros,
                    # rewritten exponents, inf/nan ("null"), and
                    # Python-only forms ("1_0") all fail the roundtrip
                    # and drop that row to the oracle.  Checked per
                    # distinct value (typed fields repeat heavily).
                    okv = np.zeros(T, dtype=bool)
                    seen: dict = {}
                    for t in np.flatnonzero(m).tolist():
                        v = chunk_bytes[vs_abs[t]:ve_abs[t]]
                        ok = seen.get(v)
                        if ok is None:
                            try:
                                ok = (json_f64(float(v)).encode("ascii")
                                      == v)
                            except (ValueError, UnicodeDecodeError):
                                ok = False
                            seen[v] = ok
                        okv[t] = ok
                    ptype = np.where(m, np.where(okv, 1, 2), ptype)
                else:  # unknown type: oracle
                    ptype = np.where(m, 2, ptype)
            bad = ptype == 2
            if bad.any():
                cand[np.unique(rop[bad])] = False

        order, dup_rows = sorted_pair_order(chunk_arr, rop, ns_abs,
                                            ne_abs, _NAME_CAP)
        if dup_rows.size:
            cand[dup_rows] = False
        keep = cand[rop[order]]
        order = order[keep]
        ns_s, ne_s = ns_abs[order], ne_abs[order]
        vs_s, ve_s = vs_abs[order], ve_abs[order]
        rop_s = rop[order]
        bare_s = (ptype == 1)[order] if schema else             np.zeros(rop_s.size, dtype=bool)
    else:
        ns_s = ne_s = vs_s = ve_s = rop_s = np.zeros(0, dtype=np.int64)
        bare_s = np.zeros(0, dtype=bool)

    ridx = np.flatnonzero(cand)
    R = ridx.size
    final_buf = b""
    row_off = np.zeros(1, dtype=np.int64)
    prefix_lens_tier: Optional[np.ndarray] = None

    if R:
        emap = escape_json(chunk_arr)
        st = starts64[ridx]

        def espan(a_abs, b_abs):
            ea = emap.map(a_abs)
            return ea, emap.map(b_abs) - ea

        full_src, full_len = espan(st, st + lens64[ridx])
        host_a = st + np.asarray(out["host_start"])[:n][ridx]
        host_b = st + np.asarray(out["host_end"])[:n][ridx]
        host_src, host_len = espan(host_a, host_b)
        has_msg = np.asarray(out["msg_pos"])[:n][ridx] >= 0
        msg_a = st + np.asarray(out["msg_start"])[:n][ridx]
        msg_b = st + np.asarray(out["msg_end"])[:n][ridx]
        msg_src, msg_len = espan(msg_a, msg_b)
        level = np.asarray(out["level_val"])[:n][ridx].astype(np.int64)
        has_level = level >= 0

        # timestamps: rfc3339-kind rows share the deduplicated computed
        # scratch; unix-literal rows format float(span) individually
        # (per-row Python, like the f64 canonicality screen above)
        kind = ts_kind[ridx]
        scratch0, ts_off0, ts_len0 = ts_scratch(out, n, ridx, json_f64)
        lit_rows = np.flatnonzero(kind != 0)
        lit_strs = []
        if lit_rows.size:
            tsa = st[lit_rows] + np.asarray(out["ts_start"])[:n][ridx][lit_rows]
            tsb = st[lit_rows] + np.asarray(out["ts_end"])[:n][ridx][lit_rows]
            lit_strs = [
                json_f64(float(chunk_bytes[a:b])).encode("ascii")
                for a, b in zip(tsa.tolist(), tsb.tolist())
            ]
        lit_blob = b"".join(lit_strs)
        lit_len = np.fromiter((len(s) for s in lit_strs), dtype=np.int64,
                              count=len(lit_strs))
        lit_off = exclusive_cumsum(lit_len)[:-1] if lit_strs else \
            np.zeros(0, dtype=np.int64)
        ts_off = ts_off0.copy()
        ts_len = ts_len0.copy()
        ts_off[lit_rows] = len(scratch0) + lit_off
        ts_len[lit_rows] = lit_len
        scratch = scratch0 + lit_blob

        consts, offs = build_source(
            c_open, _C_P0, _C_P1, _C_P2, c_full, c_host, _C_LEVEL,
            _C_SHORT_LVL, _C_SHORT, c_ts, c_tail + suffix,
            _C_UNKNOWN, _C_DASH, _C_SEVD, c_hl, c_l2a, c_l2b, scratch)
        (o_open, o_p0, o_p1, o_p2, o_full, o_host, o_level, o_short_l,
         o_short, o_ts, o_tail, o_unknown, o_dash, o_sevd,
         o_hl, o_l2a, o_l2b, o_scratch) = offs
        cbase = int(emap.esc.size)
        src = np.concatenate([emap.esc, consts])

        host_src = np.where(host_len == 0, cbase + o_unknown, host_src)
        host_len = np.where(host_len == 0, len(_C_UNKNOWN), host_len)

        # short_message value is `"msg"` (quoted, escaped) or `"-"`;
        # emitted as [quote][msg][quote] with const redirects when absent
        p = pc[ridx]
        FIXED = 15  # incl. the two extras slot columns (empty w/o extras)
        segc = 1 + 5 * p + FIXED
        rstart = exclusive_cumsum(segc)[:-1]
        S = int(segc.sum())
        seg_src = np.zeros(S, dtype=np.int64)
        seg_len = np.zeros(S, dtype=np.int64)
        seg_src[rstart] = cbase + o_open
        seg_len[rstart] = len(c_open)

        if T:
            # map sorted pairs to their (possibly shrunk) rows
            tpos = np.cumsum(cand) - 1
            tord = tpos[rop_s]
            within = np.zeros(rop_s.size, dtype=np.int64)
            if rop_s.size:
                # consecutive runs per row in sorted order
                new_row = np.ones(rop_s.size, dtype=bool)
                new_row[1:] = rop_s[1:] != rop_s[:-1]
                run_starts = np.flatnonzero(new_row)
                within = (np.arange(rop_s.size)
                          - np.repeat(run_starts,
                                      np.diff(np.append(run_starts,
                                                        rop_s.size))))
            name_src = emap.map(ns_s)
            name_len = emap.map(ne_s) - name_src
            val_src = emap.map(vs_s)
            val_len = emap.map(ve_s) - val_src
            p0 = rstart[tord] + 1 + 5 * within
            seg_src[p0] = cbase + o_p0
            seg_len[p0] = 2
            seg_src[p0 + 1] = name_src
            seg_len[p0 + 1] = name_len
            # typed bare literals (bool/int) drop the value quotes:
            # '":' is a prefix of the '":"' const and ',' a suffix of
            # the '",' const, so both variants index the same bank
            seg_src[p0 + 2] = cbase + o_p1
            seg_len[p0 + 2] = np.where(bare_s, 2, 3)
            seg_src[p0 + 3] = val_src
            seg_len[p0 + 3] = val_len
            seg_src[p0 + 4] = cbase + o_p2 + bare_s
            seg_len[p0 + 4] = np.where(bare_s, 1, 2)

        fd = (rstart + 1 + 5 * p)[:, None] + np.arange(
            FIXED, dtype=np.int64)[None, :]
        fsrc = np.empty((R, FIXED), dtype=np.int64)
        flen = np.empty((R, FIXED), dtype=np.int64)
        qsrc = cbase + o_p1 + 2  # a '"' byte inside the const bank
        cols = (
            (cbase + o_full, len(c_full)),
            (full_src, full_len),
            (cbase + o_host, len(c_host)),
            (host_src, host_len),
            (cbase + o_hl, len(c_hl)),
            (cbase + o_level, np.where(has_level, len(_C_LEVEL), 0)),
            (cbase + o_sevd + np.maximum(level, 0),
             np.where(has_level, 1, 0)),
            (np.where(has_level, cbase + o_l2a, cbase + o_l2b),
             np.where(has_level, len(c_l2a), len(c_l2b))),
            (np.where(has_level, cbase + o_short_l, cbase + o_short),
             np.where(has_level, len(_C_SHORT_LVL), len(_C_SHORT))),
            (np.where(has_msg, qsrc, cbase + o_dash),
             np.where(has_msg, 1, len(_C_DASH))),
            (msg_src, np.where(has_msg, msg_len, 0)),
            (qsrc, np.where(has_msg, 1, 0)),
            (cbase + o_ts, len(c_ts)),
            (cbase + o_scratch + ts_off, ts_len),
            (cbase + o_tail, len(c_tail) + len(suffix)),
        )
        for k, (s_, ln) in enumerate(cols):
            fsrc[:, k] = s_
            flen[:, k] = ln
        seg_src[fd] = fsrc
        seg_len[fd] = flen

        dst0 = exclusive_cumsum(seg_len)
        body = concat_segments(src, seg_src, seg_len, dst0)
        row_off = np.concatenate([dst0[rstart], dst0[-1:]])
        tier_lens = np.diff(row_off)
        if syslen:
            final_buf, row_off, prefix_lens_tier = apply_syslen_prefix(
                body, row_off, tier_lens)
        else:
            final_buf = body.tobytes()

    def scalar_fn(line):
        return _scalar_ltsv(decoder, line)

    return finish_block(chunk_bytes, starts64, lens64, n, cand, ridx,
                        final_buf, row_off, prefix_lens_tier, suffix,
                        syslen, merger, encoder, scalar_fn=scalar_fn)
