"""Columnar →Cap'n Proto encoding: span tables become framed capnp
messages without per-row Python, for the rfc5424, rfc3164, and ltsv
decoders (the reference's capnp encoder is decoder-agnostic,
capnp_encoder.rs:36-109, and kafka+capnp is its default pipeline,
mod.rs:104 — every kernel format reaching it columnar means a stock
config never silently drops to the ~30x Record path).

The wire layout (capnp_wire.py, byte-identical with the reference's
golden bytes) is a bump-allocated single segment whose piece order is
fixed:

    framing | root ptr | root struct (2 data + 9 ptr words) |
    hostname, [appname], [procid], [msgid], [msg], full_msg, [sd_id]
    texts | [pairs tag word + 4-word elements | per-pair "_"+name and
    value texts] | [constant capnp_extra blob]

Every pointer is a self-relative word — pure arithmetic over the
per-row word layout, computed as int64 numpy vectors and viewed as
little-endian bytes.  Text bytes come out of the input chunk with one
``concat_segments`` gather (NUL padding from a zero bank), exactly like
the JSON block encoders.  ``capnp_extra`` is allocated last by the
reference encoder, so its bytes are row-invariant: one constant blob
plus a computed pointer word.

Format tiers (everything else splices through the scalar oracle →
CapnpEncoder, byte-identical in every case — differential-tested in
tests/test_encode_capnp_block.py):

- rfc5424: kernel-ok rows without value escapes (``\\"``-unescaping is
  host work) and within ``max_len``;
- rfc3164: kernel-ok ASCII rows (no SD, no optional fields beyond the
  PRI-gated facility/severity);
- ltsv: untyped rows (a configured ``ltsv_schema`` types pair values —
  route-gated to the Record path), no repeated/colonless specials;
  rfc3339 stamps combine from the kernel's calendar channels and
  unix-literal stamps from its exact split-integer parse, with a
  per-row ``float(span)`` for the rare 17+-digit stamp.
"""


from __future__ import annotations

# byte-identity contract (flowcheck FC03): the scalar counterpart
# this route must stay byte-identical to, and the differential
# test that enforces it
SCALAR_ORACLE = "flowgger_tpu.encoders.capnp:CapnpEncoder"
DIFF_TEST = "tests/test_encode_capnp_block.py::test_capnp_block_matches_scalar"

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..capnp_wire import (
    FACILITY_MISSING,
    PAIR_DATA_WORDS,
    PAIR_PTR_WORDS,
    RECORD_DATA_WORDS,
    RECORD_PTR_WORDS,
    SEVERITY_MISSING,
    WORD,
)
from ..mergers import Merger
from .assemble import build_source, concat_segments, exclusive_cumsum
from .block_common import apply_syslen_prefix, finish_block, merger_suffix
from .materialize import compute_ts

_PAIR_WORDS = PAIR_DATA_WORDS + PAIR_PTR_WORDS   # 4
_ROOT_WORDS = RECORD_DATA_WORDS + RECORD_PTR_WORDS  # 11
_HDR_BYTES = 8 + 8 + _ROOT_WORDS * WORD  # framing + root ptr + root struct
# pointer slots (word offsets inside the 9-slot pointer section)
_P_HOSTNAME, _P_APPNAME, _P_PROCID, _P_MSGID = 0, 1, 2, 3
_P_MSG, _P_FULL_MSG, _P_SD_ID, _P_PAIRS, _P_EXTRA = 4, 5, 6, 7, 8


def _text_words(lens: np.ndarray) -> np.ndarray:
    """Words a text of ``lens`` bytes occupies (NUL-terminated)."""
    return (lens + 1 + WORD - 1) // WORD


def _list_ptr_words(ptr_word: np.ndarray, target_word: np.ndarray,
                    count: np.ndarray, elem_size: int = 2) -> np.ndarray:
    off = target_word - ptr_word - 1
    lower = ((off << 2) | 1).astype(np.int64) & 0xFFFFFFFF
    upper = np.asarray((elem_size & 7) | ((count & 0x1FFFFFFF) << 3),
                       dtype=np.int64)
    return lower | (upper << 32)


def _extra_blob(extra: List[Tuple[str, str]]) -> bytes:
    """The row-invariant ``capnp_extra`` list bytes: tag word, 4-word
    elements, then per-pair key/value texts — all pointers relative
    within the blob (word 0 = the tag word)."""
    if not extra:
        return b""
    k = len(extra)
    words: List[int] = []
    tag = ((k << 2) & 0xFFFFFFFF) | (
        (PAIR_DATA_WORDS | (PAIR_PTR_WORDS << 16)) << 32)
    words.append(tag)
    elems_start = 1
    texts: List[bytes] = []
    text_word = elems_start + k * _PAIR_WORDS
    ptr_vals = {}
    for i, (name, value) in enumerate(extra):
        for j, s in enumerate((name.encode("utf-8"), value.encode("utf-8"))):
            data = s + b"\x00"
            nw = (len(data) + WORD - 1) // WORD
            ptr_word = elems_start + i * _PAIR_WORDS + PAIR_DATA_WORDS + j
            off = text_word - ptr_word - 1
            ptr_vals[ptr_word] = (((off << 2) | 1) & 0xFFFFFFFF) | (
                (2 | (len(data) << 3)) << 32)
            texts.append(data + b"\x00" * (nw * WORD - len(data)))
            text_word += nw
    for i in range(k):
        base = elems_start + i * _PAIR_WORDS
        words.extend([0, 0])  # data words: string discriminant (0)
        words.append(ptr_vals[base + PAIR_DATA_WORDS])
        words.append(ptr_vals[base + PAIR_DATA_WORDS + 1])
    blob = b"".join(int(w).to_bytes(8, "little", signed=False)
                    for w in words) + b"".join(texts)
    return blob


def _capnp_assemble(chunk_bytes, starts64, lens64, n, cand, ridx,
                    texts, sid, pairs, ts, fac, sev, encoder, merger,
                    suffix, syslen, scalar_fn=None, typed=None):
    """Shared layout + assembly for every format wrapper, over
    ridx-selected [R] arrays.

    ``texts``: the six plain text slots in allocation order —
    hostname/appname/procid/msgid/msg/full_msg — each ``(a, blen,
    gate)`` with gate None = present on every row (an all-False gate =
    the format never sets the field, matching the scalar encoder's
    skipped set_text → NULL pointer).  ``sid``: ``(a, blen, gate)`` or
    None.  ``pairs``: ``(name_a, name_l, val_a, val_l, pvalid,
    has_sd)`` [R, P] / [R] or None — pair names emit with the ``"_"``
    prefix; values are string-discriminant texts unless ``typed``
    overrides.  ``typed``: optional (d0, d1, val_is_text) [R, P] int64
    / int64 / bool — data word 0 (discriminant | bool bit 16), data
    word 1 (f64/i64/u64 bit pattern), and whether the value carries a
    text (strings only).  ``ts``/``fac``/``sev``: [R] float64 / uint8
    values (missing already mapped to the *_MISSING sentinels)."""
    R = ridx.size
    final_buf = b""
    row_off = np.zeros(1, dtype=np.int64)
    prefix_lens_tier: Optional[np.ndarray] = None

    if R:
        # ---- word layout ------------------------------------------------
        def gated(blen, gate):
            return blen if gate is None else np.where(gate, blen, 0)

        tw = []
        for a, blen, gate in texts:
            present = (np.ones(R, dtype=bool) if gate is None
                       else np.asarray(gate, dtype=bool))
            tw.append(np.where(present, _text_words(blen), 0))
        if sid is not None:
            sid_a, sid_l, has_sd_sid = sid
            si_w = np.where(has_sd_sid, _text_words(sid_l), 0)
        else:
            sid_a = sid_l = np.zeros(R, dtype=np.int64)
            has_sd_sid = np.zeros(R, dtype=bool)
            si_w = np.zeros(R, dtype=np.int64)
        if pairs is not None:
            name_a, name_l, val_a, val_l, pvalid, has_sd = pairs
            P = name_a.shape[1]
            name_l = np.where(pvalid, name_l, 0)
            val_l = np.where(pvalid, val_l, 0)
            if typed is not None:
                d0_t, d1_t, val_is_text = typed
                val_l = np.where(val_is_text, val_l, 0)
            else:
                val_is_text = np.ones_like(pvalid)
            k0 = pvalid.sum(axis=1).astype(np.int64)
            key_w = np.where(pvalid, _text_words(name_l + 1), 0)  # "_"+name
            valw = np.where(pvalid & val_is_text, _text_words(val_l), 0)
            pairs_w = np.where(has_sd, 1 + k0 * _PAIR_WORDS
                               + key_w.sum(axis=1) + valw.sum(axis=1), 0)
        else:
            P = 0
            has_sd = np.zeros(R, dtype=bool)
            k0 = np.zeros(R, dtype=np.int64)
            pairs_w = np.zeros(R, dtype=np.int64)
        extra = getattr(encoder, "extra", [])
        blob = _extra_blob(extra)
        blob_w = len(blob) // WORD

        w_at = [np.full(R, 1 + _ROOT_WORDS, dtype=np.int64)]
        for w in tw:
            w_at.append(w_at[-1] + w)
        w_sid = w_at[-1]
        w_pairs = w_sid + si_w            # tag word position
        w_extra = w_pairs + pairs_w
        nwords = w_extra + blob_w

        # ---- binary scratch: framing + root ptr + root struct -----------
        hdr = np.zeros((R, _HDR_BYTES), dtype=np.uint8)
        hdr[:, 4:8] = nwords.astype("<u4").view(np.uint8).reshape(R, 4)
        root_ptr = (RECORD_DATA_WORDS | (RECORD_PTR_WORDS << 16)) << 32
        hdr[:, 8:16] = np.frombuffer(
            int(root_ptr).to_bytes(8, "little"), dtype=np.uint8)
        hdr[:, 16:24] = np.asarray(ts, dtype=np.float64).astype(
            "<f8").view(np.uint8).reshape(R, 8)
        hdr[:, 24] = np.asarray(fac).astype(np.uint8)
        hdr[:, 25] = np.asarray(sev).astype(np.uint8)

        ptrs = np.zeros((R, RECORD_PTR_WORDS), dtype=np.int64)
        pw0 = 1 + RECORD_DATA_WORDS  # word index of pointer slot 0

        def text_ptr(slot, target_w, blen, gate=None):
            v = _list_ptr_words(np.full(R, pw0 + slot, dtype=np.int64),
                                target_w, blen + 1)
            ptrs[:, slot] = v if gate is None else np.where(gate, v, 0)

        for slot, ((a, blen, gate), w0) in enumerate(zip(texts, w_at)):
            text_ptr(slot, w0, blen, gate)
        text_ptr(_P_SD_ID, w_sid, sid_l, has_sd_sid)
        if pairs is not None:
            ptrs[:, _P_PAIRS] = np.where(
                has_sd,
                _list_ptr_words(np.full(R, pw0 + _P_PAIRS, dtype=np.int64),
                                w_pairs, k0 * _PAIR_WORDS, elem_size=7), 0)
        if blob_w:
            ptrs[:, _P_EXTRA] = _list_ptr_words(
                np.full(R, pw0 + _P_EXTRA, dtype=np.int64), w_extra,
                len(extra) * _PAIR_WORDS, elem_size=7)
        hdr[:, 32:] = ptrs.astype("<i8").view(np.uint8).reshape(R, 72)

        # ---- pairs scratch: tag word + 4-word elements -------------------
        if pairs is not None:
            pair_bytes = WORD * (1 + P * _PAIR_WORDS)
            pscratch = np.zeros((R, pair_bytes), dtype=np.uint8)
            tag = ((k0 << 2) & 0xFFFFFFFF) | np.int64(
                (PAIR_DATA_WORDS | (PAIR_PTR_WORDS << 16)) << 32)
            pscratch[:, 0:8] = np.where(has_sd, tag, 0).astype(
                "<i8").view(np.uint8).reshape(R, 8)
            # per-pair text word positions: keys/values alloc in pair order
            kv_w = np.zeros((R, P, 2), dtype=np.int64)
            cursor = w_pairs + 1 + k0 * _PAIR_WORDS
            for p in range(P):
                kv_w[:, p, 0] = cursor
                cursor = cursor + key_w[:, p]
                kv_w[:, p, 1] = cursor
                cursor = cursor + valw[:, p]
            ewords = np.zeros((R, P, _PAIR_WORDS), dtype=np.int64)
            if typed is not None:
                ewords[:, :, 0] = np.where(pvalid, d0_t, 0)
                ewords[:, :, 1] = np.where(pvalid, d1_t, 0)
            for p in range(P):
                base = w_pairs + 1 + p * _PAIR_WORDS
                ewords[:, p, 2] = np.where(
                    pvalid[:, p],
                    _list_ptr_words(base + PAIR_DATA_WORDS, kv_w[:, p, 0],
                                    name_l[:, p] + 2), 0)
                ewords[:, p, 3] = np.where(
                    pvalid[:, p] & val_is_text[:, p],
                    _list_ptr_words(base + PAIR_DATA_WORDS + 1,
                                    kv_w[:, p, 1], val_l[:, p] + 1), 0)
            pscratch[:, 8:] = ewords.astype("<i8").view(np.uint8).reshape(
                R, P * _PAIR_WORDS * WORD)

        # ---- segment table ----------------------------------------------
        chunk_arr = np.frombuffer(chunk_bytes, dtype=np.uint8)
        consts, offs = build_source(b"\x00" * (WORD * 2), b"_", blob,
                                    suffix, hdr.tobytes(),
                                    pscratch.tobytes() if pairs is not None
                                    else b"")
        o_zero, o_us, o_blob, o_suffix, o_hdr, o_pscratch = offs
        cbase = int(chunk_arr.size)
        src = np.concatenate([chunk_arr, consts])

        def pad_for(blen, words, gate=None):
            ln = words * WORD - blen
            if gate is not None:
                ln = np.where(gate, ln, 0)
            return ln

        cols: List[Tuple[np.ndarray, np.ndarray]] = []

        def add(srcv, lenv):
            cols.append((np.broadcast_to(srcv, (R,)).astype(np.int64),
                         np.broadcast_to(lenv, (R,)).astype(np.int64)))

        add(cbase + o_hdr + np.arange(R) * _HDR_BYTES,
            np.full(R, _HDR_BYTES))
        for (a, blen, gate), w in zip(texts, tw):
            gl = gated(blen, gate)
            add(a, gl)
            add(cbase + o_zero, pad_for(gl, w, gate))
        add(sid_a, gated(sid_l, has_sd_sid))
        add(cbase + o_zero, pad_for(gated(sid_l, has_sd_sid), si_w,
                                    has_sd_sid))
        if pairs is not None:
            # pairs: tag+elements scratch, then "_name\0pad value\0pad"
            add(cbase + o_pscratch + np.arange(R) * pair_bytes,
                np.where(has_sd, 8 + k0 * _PAIR_WORDS * WORD, 0))
            for p in range(P):
                pv = pvalid[:, p]
                add(cbase + o_us, np.where(pv, 1, 0))
                add(name_a[:, p], name_l[:, p])
                add(cbase + o_zero,
                    pad_for(name_l[:, p] + 1, key_w[:, p], pv))
                add(val_a[:, p], val_l[:, p])
                add(cbase + o_zero, pad_for(val_l[:, p], valw[:, p], pv))
        add(cbase + o_blob, np.full(R, len(blob)))
        add(cbase + o_suffix, np.full(R, len(suffix)))

        nseg = len(cols)
        seg_src = np.empty((R, nseg), dtype=np.int64)
        seg_len = np.empty((R, nseg), dtype=np.int64)
        for k, (s, ln) in enumerate(cols):
            seg_src[:, k] = s
            seg_len[:, k] = ln
        dst0 = exclusive_cumsum(seg_len.ravel())
        body = concat_segments(src, seg_src.ravel(), seg_len.ravel(), dst0)
        row_off = dst0[::nseg]
        tier_lens = np.diff(row_off)
        if syslen:
            final_buf, row_off, prefix_lens_tier = apply_syslen_prefix(
                body, row_off, tier_lens)
        else:
            final_buf = body.tobytes()

    kw = {} if scalar_fn is None else {"scalar_fn": scalar_fn}
    return finish_block(chunk_bytes, starts64, lens64, n, cand, ridx,
                        final_buf, row_off, prefix_lens_tier, suffix,
                        syslen, merger, encoder, **kw)


def encode_rfc5424_capnp_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
):
    spec = merger_suffix(merger)
    if spec is None:
        return None
    suffix, syslen = spec

    n = int(n_real)
    starts64 = np.asarray(starts[:n], dtype=np.int64)
    lens64 = np.asarray(orig_lens[:n], dtype=np.int64)
    ok = np.asarray(out["ok"][:n], dtype=bool)
    has_high = np.asarray(out["has_high"][:n], dtype=bool)
    val_esc = np.asarray(out["val_has_esc"][:n], dtype=bool)
    pair_count = np.asarray(out["pair_count"][:n], dtype=np.int64)
    esc_any = (val_esc[:, :]
               & (np.arange(val_esc.shape[1])[None, :] < pair_count[:, None])
               ).any(axis=1)
    cand = ok & (lens64 <= max_len) & ~has_high & ~esc_any

    ridx = np.flatnonzero(cand)
    if not ridx.size:
        return _capnp_assemble(chunk_bytes, starts64, lens64, n, cand,
                               ridx, [], None, None, None, None, None,
                               encoder, merger, suffix, syslen)
    st = starts64[ridx]

    def span(a_key, b_key):
        a = np.asarray(out[a_key])[:n][ridx].astype(np.int64)
        b = np.asarray(out[b_key])[:n][ridx].astype(np.int64)
        return st + a, np.maximum(b - a, 0)

    host_a, host_l = span("host_start", "host_end")
    app_a, app_l = span("app_start", "app_end")
    proc_a, proc_l = span("proc_start", "proc_end")
    msgid_a, msgid_l = span("msgid_start", "msgid_end")
    # msg: [msg_trim_start, trim_end) — None (no text) when empty
    msg_a = st + np.asarray(out["msg_trim_start"])[:n][ridx].astype(np.int64)
    trim_e = st + np.asarray(out["trim_end"])[:n][ridx].astype(np.int64)
    msg_l = np.maximum(trim_e - msg_a, 0)
    has_msg = msg_l > 0
    full_a = st + np.asarray(out["full_start"])[:n][ridx].astype(np.int64)
    full_l = np.maximum(trim_e - full_a, 0)
    sd_count = np.asarray(out["sd_count"])[:n][ridx].astype(np.int64)
    has_sd = sd_count > 0
    sid_a = st + np.asarray(out["sid_start"])[:n][ridx, 0].astype(np.int64)
    sid_l = np.maximum(
        np.asarray(out["sid_end"])[:n][ridx, 0].astype(np.int64)
        - np.asarray(out["sid_start"])[:n][ridx, 0].astype(np.int64), 0)
    pc = pair_count[ridx]
    P = np.asarray(out["name_start"]).shape[1]
    pair_sd = np.asarray(out["pair_sd"])[:n][ridx].astype(np.int64)
    name_a = st[:, None] + np.asarray(out["name_start"])[:n][ridx].astype(np.int64)
    name_l = (np.asarray(out["name_end"])[:n][ridx].astype(np.int64)
              - np.asarray(out["name_start"])[:n][ridx].astype(np.int64))
    val_a = st[:, None] + np.asarray(out["val_start"])[:n][ridx].astype(np.int64)
    val_l = (np.asarray(out["val_end"])[:n][ridx].astype(np.int64)
             - np.asarray(out["val_start"])[:n][ridx].astype(np.int64))
    # capnp carries only sd[0] (capnp_encoder.rs:78-80): gate pairs
    # on block 0 membership
    pvalid = (np.arange(P)[None, :] < pc[:, None]) & (pair_sd == 0)

    ts = compute_ts({k: np.asarray(v)[:n][ridx]
                     for k, v in out.items()
                     if k in ("days", "sod", "off", "nanos")})
    fac = np.asarray(out["facility"])[:n][ridx].astype(np.uint8)
    sev = np.asarray(out["severity"])[:n][ridx].astype(np.uint8)

    texts = [
        (host_a, host_l, None),
        (app_a, app_l, None),
        (proc_a, proc_l, None),
        (msgid_a, msgid_l, None),
        (msg_a, msg_l, has_msg),
        (full_a, full_l, None),
    ]
    return _capnp_assemble(
        chunk_bytes, starts64, lens64, n, cand, ridx, texts,
        (sid_a, sid_l, has_sd),
        (name_a, name_l, val_a, val_l, pvalid, has_sd),
        ts, fac, sev, encoder, merger, suffix, syslen)


def encode_rfc3164_capnp_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
):
    """rfc3164 Record → capnp: hostname + msg (tail) + full line, PRI-
    gated facility/severity, no appname/procid/msgid/sd
    (materialize_rfc3164.py's Record shape)."""
    from .materialize_rfc3164 import _scalar_3164

    spec = merger_suffix(merger)
    if spec is None:
        return None
    suffix, syslen = spec

    n = int(n_real)
    starts64 = np.asarray(starts[:n], dtype=np.int64)
    lens64 = np.asarray(orig_lens[:n], dtype=np.int64)
    ok = np.asarray(out["ok"][:n], dtype=bool)
    has_high = np.asarray(out["has_high"][:n], dtype=bool)
    cand = ok & (lens64 <= max_len) & ~has_high
    ridx = np.flatnonzero(cand)
    st = starts64[ridx]

    def sp(a_key, b_key):
        a = np.asarray(out[a_key])[:n][ridx].astype(np.int64)
        b = np.asarray(out[b_key])[:n][ridx].astype(np.int64)
        return st + a, np.maximum(b - a, 0)

    host_a, host_l = sp("host_start", "host_end")
    msg_a = st + np.asarray(out["msg_start"])[:n][ridx].astype(np.int64)
    msg_l = np.maximum(st + lens64[ridx] - msg_a, 0)
    R = ridx.size
    zero = np.zeros(R, dtype=np.int64)
    absent = np.zeros(R, dtype=bool)
    has_pri = np.asarray(out["has_pri"][:n], dtype=bool)[ridx]
    fac = np.where(has_pri,
                   np.asarray(out["facility"])[:n][ridx], FACILITY_MISSING)
    sev = np.where(has_pri,
                   np.asarray(out["severity"])[:n][ridx], SEVERITY_MISSING)
    ts = compute_ts({k: np.asarray(v)[:n][ridx]
                     for k, v in out.items()
                     if k in ("days", "sod", "off", "nanos")})

    texts = [
        (host_a, host_l, None),
        (zero, zero, absent),          # appname
        (zero, zero, absent),          # procid
        (zero, zero, absent),          # msgid
        (msg_a, msg_l, None),          # msg = line[msg_start:], may be ""
        (st, lens64[ridx], None),      # full_msg = whole line
    ]
    return _capnp_assemble(
        chunk_bytes, starts64, lens64, n, cand, ridx, texts, None, None,
        ts, fac, sev, encoder, merger, suffix, syslen,
        scalar_fn=_scalar_3164)


def encode_ltsv_capnp_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
    decoder=None,
):
    """ltsv Record → capnp: hostname, optional message, full line,
    severity from ``level``, untyped pairs in part order (a configured
    ``ltsv_schema`` types values — those rows keep the Record path,
    gated here like the GELF block's typed screens)."""
    from .materialize_ltsv import _scalar_ltsv

    spec = merger_suffix(merger)
    if spec is None:
        return None
    if decoder is not None and getattr(decoder, "schema", None):
        return None
    suffix, syslen = spec

    def scalar_fn(line):
        return _scalar_ltsv(decoder, line)

    n = int(n_real)
    starts64 = np.asarray(starts[:n], dtype=np.int64)
    lens64 = np.asarray(orig_lens[:n], dtype=np.int64)
    ok = np.asarray(out["ok"][:n], dtype=bool)
    has_high = np.asarray(out["has_high"][:n], dtype=bool)
    n_parts = np.asarray(out["n_parts"])[:n].astype(np.int64)
    part_start = np.asarray(out["part_start"])[:n]
    part_end = np.asarray(out["part_end"])[:n]
    colon_pos = np.asarray(out["colon_pos"])[:n]
    host_pos = np.asarray(out["host_pos"])[:n]
    ts_kind = np.asarray(out["ts_kind"])[:n]

    P = part_start.shape[1]
    jmask = np.arange(P)[None, :] < n_parts[:, None]
    cand = ok & (lens64 <= max_len) & ~has_high & (host_pos >= 0)
    # colon-less parts trigger the scalar path's stdout notice
    cand &= ~(jmask & (colon_pos < 0)).any(axis=1)

    chunk_arr = np.frombuffer(chunk_bytes, dtype=np.uint8)
    # specials route by NAME (every occurrence), repeated names drop to
    # the oracle — shared screen (block_common.ltsv_special_screen)
    from .block_common import ltsv_special_screen

    nlen = np.where(jmask, colon_pos - part_start, 0)
    special_name, uniq_ok = ltsv_special_screen(
        chunk_arr, starts64, part_start, nlen, jmask)
    cand &= uniq_ok

    ridx = np.flatnonzero(cand)
    st = starts64[ridx]

    def sp(a_key, b_key):
        a = np.asarray(out[a_key])[:n][ridx].astype(np.int64)
        b = np.asarray(out[b_key])[:n][ridx].astype(np.int64)
        return st + a, np.maximum(b - a, 0)

    host_a, host_l = sp("host_start", "host_end")
    msg_a, msg_l = sp("msg_start", "msg_end")
    has_msg = np.asarray(out["msg_pos"])[:n][ridx].astype(np.int64) >= 0
    level = np.asarray(out["level_val"])[:n][ridx].astype(np.int64)
    R = ridx.size
    zero = np.zeros(R, dtype=np.int64)
    absent = np.zeros(R, dtype=bool)
    fac = np.full(R, FACILITY_MISSING, dtype=np.int64)
    sev = np.where(level >= 0, level, SEVERITY_MISSING)

    # timestamps: rfc3339 / split-integer / per-row-exact, shared with
    # the LTSV self-encode block (block_common.ltsv_ts_vals)
    from .block_common import ltsv_ts_vals

    ts = ltsv_ts_vals(out, n, ridx, chunk_bytes, starts64)

    # pairs: non-special parts in part order, "_"-prefixed string values
    is_pair = jmask[ridx] & ~special_name[ridx]
    name_a = st[:, None] + part_start[ridx].astype(np.int64)
    name_l2 = (colon_pos[ridx].astype(np.int64)
               - part_start[ridx].astype(np.int64))
    val_a = st[:, None] + colon_pos[ridx].astype(np.int64) + 1
    val_l = (part_end[ridx].astype(np.int64)
             - colon_pos[ridx].astype(np.int64) - 1)
    # compact pairs left so pvalid is a prefix mask (the layout cursor
    # walks pair slots in order; gaps would still work but waste slots)
    order = np.argsort(~is_pair, axis=1, kind="stable")
    rr = np.arange(R)[:, None]
    pvalid = np.take_along_axis(is_pair, order, axis=1)
    name_a = name_a[rr, order]
    name_l2 = name_l2[rr, order]
    val_a = val_a[rr, order]
    val_l = val_l[rr, order]
    has_sd = pvalid.any(axis=1)

    texts = [
        (host_a, host_l, None),
        (zero, zero, absent),          # appname
        (zero, zero, absent),          # procid
        (zero, zero, absent),          # msgid
        (msg_a, msg_l, has_msg),
        (st, lens64[ridx], None),      # full_msg = whole line
    ]
    return _capnp_assemble(
        chunk_bytes, starts64, lens64, n, cand, ridx, texts,
        (zero, zero, np.zeros(R, dtype=bool)),   # sd_id is None for ltsv
        (name_a, name_l2, val_a, val_l, pvalid, has_sd),
        ts, fac, sev, encoder, merger, suffix, syslen,
        scalar_fn=scalar_fn)


def encode_gelf_capnp_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
):
    """gelf→capnp: the JSON tokenizer's spans through the decoder-
    agnostic capnp encoder.  Pairs carry their TYPED discriminants —
    strings as texts, bools/null as data bits, canonical ints (≤ 18
    digits) parsed vectorially into i64/u64 words; float pair values
    (a per-value parse+bit pattern) take the oracle.  Pair order is the
    Record's: sorted ORIGINAL keys, duplicates → oracle."""
    from .encode_gelf_gelf_block import _NAME_CAP, gelf_screen
    from .gelf import VT_FALSE, VT_NULL, VT_NUMBER, VT_STRING, VT_TRUE
    from .materialize_gelf import _scalar_gelf

    spec = merger_suffix(merger)
    if spec is None:
        return None
    suffix, syslen = spec

    s = gelf_screen(chunk_bytes, starts, orig_lens, out, n_real, max_len)
    n, starts64, lens64, cand = (s["n"], s["starts64"], s["lens64"],
                                 s["cand"])
    chunk_arr, chunk_pad = s["chunk_arr"], s["chunk_pad"]
    kabs, key_e = s["kabs"], s["key_e"]
    byte_at, vspan_at = s["byte_at"], s["vspan_at"]
    is_pair = s["is_pair"] & cand[:, None]
    vabs_a, vabs_b = s["vabs_a"], s["vabs_b"]
    val_t = s["val_t"]

    # ---- pair table in ORIGINAL-key sorted order (shared helper;
    # drops duplicate-key rows from cand) --------------------------------
    from .block_common import gelf_sorted_pairs

    rop_s, ns_s, ne_s, pv_t, pv_a, pv_b = gelf_sorted_pairs(
        chunk_arr, starts64, cand, is_pair, kabs, key_e, vabs_a, vabs_b,
        val_t, byte_at, _NAME_CAP)

    ridx = np.flatnonzero(cand)
    R = ridx.size
    if not R:
        return _capnp_assemble(chunk_bytes, starts64, lens64, n, cand,
                               ridx, [], None, None, None, None, None,
                               encoder, merger, suffix, syslen,
                               scalar_fn=_scalar_gelf)

    # timestamps: per-unique float of the span (dedup dict)
    from .block_common import span_f64_values

    ts = span_f64_values(chunk_bytes, s["tsa_all"][ridx],
                         s["tsb_all"][ridx])

    lv_a, _ = vspan_at(s["lvl_f"])
    sev = np.where(s["has_lvl"],
                   chunk_pad[np.asarray(lv_a, dtype=np.int64)] - ord("0"),
                   SEVERITY_MISSING)[ridx]
    fac = np.full(R, FACILITY_MISSING, dtype=np.int64)

    # ---- pair slots: [R, P] matrices in sorted order + typed words ------
    if rop_s.size:
        # rr maps each pair to its COMPACTED candidate row (slot matrix
        # space); pc counts in that same space — a fallback row BEFORE
        # a candidate row must not shift either
        tpos = np.cumsum(cand) - 1
        rr = tpos[rop_s]
        pc = np.bincount(rr, minlength=R).astype(np.int64)
        P = max(1, int(pc.max(initial=0)))
        within = np.zeros(rop_s.size, dtype=np.int64)
        if rop_s.size:
            new_row = np.ones(rop_s.size, dtype=bool)
            new_row[1:] = rop_s[1:] != rop_s[:-1]
            run_starts = np.flatnonzero(new_row)
            within = (np.arange(rop_s.size)
                      - np.repeat(run_starts,
                                  np.diff(np.append(run_starts,
                                                    rop_s.size))))
        name_a = np.zeros((R, P), dtype=np.int64)
        name_l = np.zeros((R, P), dtype=np.int64)
        val_a = np.zeros((R, P), dtype=np.int64)
        val_l = np.zeros((R, P), dtype=np.int64)
        pvalid = np.zeros((R, P), dtype=bool)
        d0 = np.zeros((R, P), dtype=np.int64)
        d1 = np.zeros((R, P), dtype=np.int64)
        vtext = np.zeros((R, P), dtype=bool)
        # vectorized canonical-int parse: <= 19-byte window incl sign
        is_num = pv_t == VT_NUMBER
        neg = chunk_pad[pv_a] == ord("-")
        wnd = (pv_a[:, None]
               + np.arange(19, dtype=np.int64)[None, :])
        wb = chunk_pad[wnd]
        wlen = pv_b - pv_a
        in_w = (np.arange(19)[None, :] >= neg[:, None].astype(np.int64)) \
            & (np.arange(19)[None, :] < wlen[:, None])
        digs = np.where(in_w, wb - ord("0"), 0).astype(np.int64)
        # right-align place values: digit at window index i has place
        # (wlen - 1 - i)
        place = wlen[:, None] - 1 - np.arange(19)[None, :]
        mag = (digs * np.where(in_w, 10 ** np.clip(place, 0, 18), 0)
               ).sum(axis=1)
        ival = np.where(neg, -mag, mag)
        disc = np.where(pv_t == VT_STRING, 0,
                        np.where(pv_t == VT_TRUE, 1 | (1 << 16),
                                 np.where(pv_t == VT_FALSE, 1,
                                          np.where(pv_t == VT_NULL, 5,
                                                   np.where(neg, 3, 4)))))
        slot = (rr, within)
        name_a[slot] = ns_s
        name_l[slot] = ne_s - ns_s
        val_a[slot] = pv_a
        val_l[slot] = pv_b - pv_a
        pvalid[slot] = True
        d0[slot] = disc
        d1[slot] = np.where(is_num, ival, 0)
        vtext[slot] = pv_t == VT_STRING
        has_sd = pc > 0
        pairs = (name_a, name_l, val_a, val_l, pvalid, has_sd)
        typed = (d0, d1, vtext)
    else:
        pairs = None
        typed = None

    zero = np.zeros(R, dtype=np.int64)
    absent = np.zeros(R, dtype=bool)
    host_a0, host_b0 = vspan_at(s["host_f"])
    msg_a0, msg_b0 = vspan_at(s["short_f"])
    full_a0, full_b0 = vspan_at(s["full_f"])
    texts = [
        (host_a0[ridx], (host_b0 - host_a0)[ridx], None),
        (zero, zero, absent),          # appname
        (zero, zero, absent),          # procid
        (zero, zero, absent),          # msgid
        (msg_a0[ridx], (msg_b0 - msg_a0)[ridx], s["has_short"][ridx]),
        (full_a0[ridx], (full_b0 - full_a0)[ridx], s["has_full"][ridx]),
    ]
    return _capnp_assemble(
        chunk_bytes, starts64, lens64, n, cand, ridx, texts,
        (zero, zero, np.zeros(R, dtype=bool)),   # sd_id is None for gelf
        pairs, ts, fac, sev, encoder, merger, suffix, syslen,
        scalar_fn=_scalar_gelf, typed=typed)
