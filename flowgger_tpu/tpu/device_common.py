"""Shared machinery for device-side encode kernels (device_gelf,
device_rfc3164, ...): gather-free JSON escaping, per-row segment
assembly, on-device row compaction, and the host fetch driver with
tier gating, decline hysteresis, and output-sized D2H.

Every format-specific module contributes only (a) a jitted kernel
``kernel(ts_text, ts_len, assemble) -> tier | (acc, out_len, tier)``
built from these primitives plus its own segment table, and (b) a
``route_ok`` predicate; the fetch flow (phase-1 tier probe, timestamp
text upload, compaction, syslen prefixing, fallback splicing) is one
implementation here.

The reference fuses decode→encode per line in its hot loop
(line_splitter.rs:44-54 → encoder/mod.rs:54-56); this is the batched
TPU shape of that fusion, for every format pair that rides it.
"""

from __future__ import annotations

import os
import sys
import threading
from functools import lru_cache, partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .assemble import exclusive_cumsum
from .materialize import compute_ts

_I32 = jnp.int32
_U8 = jnp.uint8

# -- compile watchdog --------------------------------------------------------
# The device-encode kernels are large; on some hosts/backends their XLA
# compile can take minutes (observed: effectively unbounded on old CPU
# containers).  The fast path is optional — a compile must never stall
# the stream — so the first call of each kernel phase runs under a
# wall-clock deadline: on timeout the compile keeps warming the jit
# cache in a daemon thread while every batch meanwhile declines to the
# host block-encode path (same bytes), and once the background compile
# lands the device tier engages normally.
COMPILE_TIMEOUT_ENV = "FLOWGGER_COMPILE_TIMEOUT_MS"
COMPILE_TIMEOUT_MS_DEFAULT = 15_000

_compile_slots: Dict[str, threading.Event] = {}
_compile_ready = set()  # names that have completed once: call inline
_compile_lock = threading.Lock()
_compile_warned = set()
# cumulative decline count, independent of the (resettable) metrics
# registry — tests/conftest.py reads it to turn a watchdog-declined
# differential test into an informative xfail
_decline_total = 0
# single-flight: at most ONE background kernel compile at a time.  The
# big device-encode compiles are multi-GB XLA jobs; running several
# concurrently (plus the foreground's own jit work) has crashed the
# process on constrained hosts.  Queued compiles wait here — their
# guarded callers decline instantly in the meantime.
_compile_sema = threading.Semaphore(1)
# slot name currently holding _compile_sema ("name" key present iff a
# compile is in flight).  A fresh guarded call observing an in-flight
# compile declines immediately instead of waiting out a deadline its
# own queued compile can never meet (the foreground used to stall a
# full FLOWGGER_COMPILE_TIMEOUT_MS per fresh kernel+shape behind one
# wedged compile).  A box rather than a bare global so each worker
# thread clears exactly the instance it marked — tests that swap in an
# isolated semaphore swap this box alongside it, and an in-flight
# worker from before the swap can neither corrupt the new box nor
# leave a stale name in the restored one.
_compile_active_box: Dict[str, str] = {}


class CompileTimeout(Exception):
    """A device-encode kernel is still compiling; decline this batch."""


def _compile_deadline_s() -> float:
    try:
        ms = int(os.environ.get(COMPILE_TIMEOUT_ENV,
                                COMPILE_TIMEOUT_MS_DEFAULT))
    except ValueError:
        ms = COMPILE_TIMEOUT_MS_DEFAULT
    return ms / 1000.0


def compile_decline_count() -> int:
    """Process-cumulative watchdog declines (never reset — unlike the
    metrics registry counter of the same event)."""
    return _decline_total


_decline_count_lock = threading.Lock()


def _count_decline() -> None:
    global _decline_total
    from ..utils.metrics import registry as _reg

    _reg.inc("device_encode_compile_declines")
    with _decline_count_lock:
        _decline_total += 1


def guarded_compile_call(name: str, fn, *args, timeout_s=None):
    """Run a (potentially compiling) jit call with a deadline.

    Raises CompileTimeout when the call exceeds the deadline — the call
    finishes in a background daemon thread so the jit cache still warms
    — or instantly while that background run is still going.  A value
    of ``FLOWGGER_COMPILE_TIMEOUT_MS=0`` disables the watchdog.
    ``timeout_s`` overrides the deadline for this call (the fused-route
    tier runs its first-compile waits under a tighter budget)."""
    timeout = _compile_deadline_s() if timeout_s is None else timeout_s
    if timeout <= 0:
        return fn(*args)
    done = threading.Event()
    # pair the semaphore with its active-slot box at call time, so the
    # worker marks/clears the same instances the busy check reads even
    # if a test swaps the module globals mid-flight
    sema, active = _compile_sema, _compile_active_box
    declined = False
    with _compile_lock:
        if name in _compile_ready:
            # jit cache warm for this name+shape: call inline (also the
            # landing path for background compiles — the worker marks
            # readiness itself, so a landed kernel never re-queues
            # behind another kernel's compile on the semaphore)
            _compile_slots.pop(name, None)
            ready = True
        else:
            ready = False
            pending = _compile_slots.get(name)
            if pending is not None and not pending.is_set():
                # journal + raise AFTER the lock: the journal may write
                # a disk sink, and every caller probing the slot table
                # would serialize behind it
                declined = True
            else:
                # claim the slot inside this same critical section so
                # two threads can never spawn duplicate compiles of one
                # kernel (a finished-but-errored slot is replaced)
                _compile_slots[name] = done
                busy = active.get("name")
    if declined:
        _count_decline()
        from ..obs import events as _events

        _events.emit("compile", "watchdog_decline", detail=name)
        raise CompileTimeout(name)
    if ready:
        return fn(*args)
    box: dict = {}

    def run():
        try:
            with sema:
                with _compile_lock:
                    active["name"] = name
                try:
                    box["result"] = fn(*args)
                finally:
                    with _compile_lock:
                        active.pop("name", None)
        except BaseException as e:  # noqa: BLE001 - ferried to the caller
            box["error"] = e
        else:
            with _compile_lock:
                _compile_ready.add(name)
        finally:
            done.set()

    # flowcheck: disable=FC10 -- the compile worker must outlive its (watchdog-declined) caller so the compile lands for the next call; the done event + single-flight semaphore own its lifecycle, and joining it is exactly the stall the watchdog exists to prevent
    threading.Thread(target=run, daemon=True,
                     name=f"xla-compile:{name}").start()
    if busy is not None:
        # another kernel's compile holds the single-flight semaphore
        # RIGHT NOW, so this one cannot even start XLA work before the
        # deadline — waiting it out is provably futile.  Decline
        # immediately (the queued thread still warms the cache once the
        # semaphore frees); the batch takes the host path meanwhile.
        # On healthy hosts the semaphore is almost always free, so this
        # path only engages while a compile is genuinely in flight.
        _count_decline()
        from ..obs import events as _events

        msg = None
        if name not in _compile_warned:
            _compile_warned.add(name)
            msg = (f"device-encode kernel [{name}] queued behind the "
                   f"in-flight [{busy}] compile; using the host encode "
                   "path until it lands")
        _events.emit("compile", "busy_decline", detail=name, msg=msg)
        raise CompileTimeout(name)
    if not done.wait(timeout):
        _count_decline()
        from ..obs import events as _events

        msg = None
        if name not in _compile_warned:
            _compile_warned.add(name)
            msg = (f"device-encode kernel [{name}] still compiling "
                   f"after {timeout:.0f}s; using the host encode path "
                   "until it lands")
        _events.emit("compile", "watchdog_decline", detail=name,
                     cost=timeout, cost_unit="deadline_s", msg=msg)
        raise CompileTimeout(name)
    with _compile_lock:
        _compile_slots.pop(name, None)
        if "error" not in box:
            _compile_ready.add(name)
    if "error" in box:
        raise box["error"]
    return box["result"]

# -- persistent compile cache + prewarm --------------------------------------
# A fresh (rows, max_len) shape costs a full XLA compile — >60s for the
# encode kernels on constrained hosts, which the watchdog converts into
# host-path declines: the device tier spends its first minutes per shape
# losing the route-economics race it should win.  Two fixes compose:
# the persistent compilation cache (``input.tpu_compile_cache_dir``)
# makes every compile a once-per-machine cost, and the background
# prewarm compiles the configured format's kernels for the shape-bucket
# grid at startup so the first real batch hits a warm jit cache.  Cache
# traffic is observable as ``compile_cache_hits``/``compile_cache_
# misses`` counters (a second cold process of the same config should
# report zero misses for the prewarmed kernels).

_cache_state_lock = threading.Lock()
_cache_dir_installed = None
_cache_listener_installed = False

# Kernel ABI revision folded into the persistent-cache directory layout.
# JAX's cache key covers the traced computation, NOT our kernel-level
# contracts: a signature/layout change (the PR 4 ``_encode_kernel``
# elide rework silently invalidated every cached encode entry) leaves
# stale entries of the OLD kernels poisoning the dir forever and makes
# "second cold process compiles nothing" silently false after an
# upgrade.  Bump this whenever a kernel signature, segment layout, or
# channel contract changes; old revisions keep their own subdirectory
# and die with ordinary cache cleanup.
KERNEL_ABI = 9


def _install_cache_listener() -> None:
    """Bridge JAX's compilation-cache monitoring events into the metrics
    registry (idempotent; the listener registry is process-global)."""
    global _cache_listener_installed
    with _cache_state_lock:
        if _cache_listener_installed:
            return
        _cache_listener_installed = True
    from jax import monitoring as _monitoring

    from ..utils.metrics import registry as _reg

    def _on_event(event, **_kw):
        # event names are stable-ish across jax versions; match the leaf
        if event.endswith("/cache_hits"):
            _reg.inc("compile_cache_hits")
        elif event.endswith("/cache_misses"):
            _reg.inc("compile_cache_misses")

    _monitoring.register_event_listener(_on_event)


# every persistent-cache knob enable_compile_cache mutates, paired
# with the value it sets — the ONE place both the enable loop and the
# snapshot/restore sites (tpu/aot.py, the test fixtures, via
# CACHE_KNOBS) derive from, so a knob added here is set AND restored
CACHE_KNOB_SETTINGS = (
    ("jax_persistent_cache_min_compile_time_secs", 0.0),
    ("jax_persistent_cache_min_entry_size_bytes", 0),
)
CACHE_KNOBS = (("jax_compilation_cache_dir",)
               + tuple(k for k, _ in CACHE_KNOB_SETTINGS))


def enable_compile_cache(cache_dir: str) -> str:
    """Point JAX's persistent compilation cache at ``cache_dir`` and
    start counting hits/misses.  Thresholds are dropped to zero so even
    the small decode kernels persist — on hosts where the big encode
    compiles never finish inside the watchdog, the cheap kernels are
    exactly the ones worth never recompiling.

    The configured directory is versioned by ``KERNEL_ABI``
    (``<dir>/kabi-<N>``): entries compiled against an older kernel ABI
    can neither be loaded by mistake nor mask a needed recompile."""
    cache_dir = os.path.join(os.path.expanduser(cache_dir),
                             f"kabi-{KERNEL_ABI}")
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    for knob, val in CACHE_KNOB_SETTINGS:
        try:
            jax.config.update(knob, val)
        except Exception:  # noqa: BLE001 - knob names vary across jax versions
            pass
    try:
        # jax latches the use-the-cache decision at the first compile;
        # a process that already compiled something (tests, a handler
        # built before the config was read) must reset that memo or the
        # new cache dir is silently ignored
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # noqa: BLE001 - private API; harmless if gone
        pass
    _install_cache_listener()
    with _cache_state_lock:
        global _cache_dir_installed
        _cache_dir_installed = cache_dir
    return cache_dir


def setup_compile_cache(config):
    """Wire ``input.tpu_compile_cache_dir`` (no key = no cache, the
    stock JAX behavior).  Returns the directory when installed."""
    cache_dir = config.lookup_str(
        "input.tpu_compile_cache_dir",
        "input.tpu_compile_cache_dir must be a string (directory)", None)
    if not cache_dir:
        return None
    return enable_compile_cache(cache_dir)


def _zero_packed(rows: int, max_len: int):
    """A zero-row packed tuple of device shape [rows, max_len] — the
    cheapest input that still compiles every kernel phase (n_real = 0:
    nothing is emitted, fetched bodies are empty)."""
    return (np.zeros((rows, max_len), dtype=np.uint8),
            np.zeros(rows, dtype=np.int32), b"",
            np.zeros(rows, dtype=np.int32),
            np.zeros(0, dtype=np.int32), 0)


def prewarm_kernels(fmt: str, max_len: int, row_buckets, encoder=None,
                    merger=None, ltsv_decoder=None, supervisor=None,
                    devices=None, fused_route=None):
    """Background-compile ``fmt``'s decode kernel — and, when the
    device-encode route applies (encoder+merger given), its encode
    phases — for every shape in ``row_buckets``.

    Runs on one daemon thread (spawned through the pipeline Supervisor
    when given, so a crash restarts with backoff instead of silently
    losing the warmup).  The cheap decode compiles run directly on this
    thread — the prewarm worker IS the off-stream background the
    watchdog would otherwise provide, and queueing them on the
    watchdog's single-flight semaphore would starve them forever behind
    a stuck encode compile.  The huge device-encode compiles keep their
    existing ``FLOWGGER_COMPILE_TIMEOUT_MS`` watchdog + single-flight
    path inside ``fetch_encode_driver`` (a timeout there declines
    cleanly while the compile keeps warming).  ``devices`` (lane
    dispatch) warms one executable per lane device — jit caches key on
    placement, so a default-device warmup would leave lanes 1..N cold.
    With a persistent cache installed every landed compile also becomes
    a once-per-machine cost.  Returns the thread."""
    buckets = [int(b) for b in row_buckets]
    devs = list(devices) if devices else [None]

    def run():
        from ..utils.metrics import registry as _reg
        from .aot import prewarm_covered
        from .batch import block_fetch_encode, block_submit

        for rows in buckets:
            # zero-JIT boot: a bucket whose every program is already
            # AOT-loaded needs no background compile — the store's
            # exported programs replace trace+compile at dispatch.  On
            # a fully artifact-booted process the prewarm thread is
            # idle (one log line per skipped route)
            if prewarm_covered(fmt, rows, max_len, encoder=encoder,
                               merger=merger, fused_route=fused_route,
                               ltsv_decoder=ltsv_decoder):
                _reg.inc("prewarm_aot_skips")
                print(f"kernel prewarm: {fmt}@{rows}x{max_len} "
                      "AOT-loaded; skipping background compile",
                      file=sys.stderr)
                continue
            for di, dev in enumerate(devs):
                packed = _zero_packed(rows, max_len)
                name = f"prewarm:{fmt}:{rows}x{max_len}:d{di}"
                try:
                    # the jit *call* compiles synchronously, right here
                    # on the prewarm thread
                    handle = block_submit(fmt, packed, None, dev)
                    if encoder is not None and merger is not None:
                        # device-encode probe/assemble compiles are
                        # guarded inside fetch_encode_driver; a timeout
                        # there simply declines to the host block path
                        # while the compile keeps warming in background
                        block_fetch_encode(fmt, handle, packed, encoder,
                                           merger, ltsv_decoder,
                                           route_state={})
                        if fused_route is not None:
                            # warm the fused single-program route too —
                            # same guarded/decline semantics
                            from . import fused_routes as _fr

                            fh = _fr.submit(fused_route, packed, dev)
                            _fr.fetch_encode(fh, packed, encoder,
                                             merger, ltsv_decoder,
                                             route_state={})
                    _reg.inc("prewarmed_shapes")
                except CompileTimeout:
                    continue  # still compiling in the watchdog's worker
                except Exception as e:  # noqa: BLE001 - warmup must never kill ingest
                    print(f"kernel prewarm [{name}] failed: "
                          f"{type(e).__name__}: {e}", file=sys.stderr)

    if supervisor is not None:
        return supervisor.spawn(run, "tpu-prewarm", exhausted="return")
    t = threading.Thread(target=run, daemon=True, name="tpu-prewarm")
    t.start()
    return t


TS_W = 32          # timestamp text slot width (longest json_f64 ≈ 25)
E_CAP = 56         # max JSON escapes per row on the device tier

# group granularity (bytes) of on-device compaction: 8 keeps the mean
# per-row padding at ~G/2 = 4 bytes (it was 16 at the old G=32 — most
# of the gap between fetched and emitted bytes/row) for ~2 extra barrel
# stages, each a fused elementwise pass
COMPACT_G = 8
# skip compaction when padded size is within this factor of the real
# output (the extra device passes would not pay for the smaller fetch)
COMPACT_MIN_SAVING = 1.15


def _shr2d(arr, k):
    """Shift rows right by static k (drop tail, zero-fill head)."""
    if k == 0:
        return arr
    return jnp.pad(arr[:, :-k], ((0, 0), (k, 0)))


def _monotone_expand(vals, shifts, w_out, nbits):
    """Place vals[i,j] at column j + shifts[i,j]; shifts nondecreasing
    along each row, < 2**nbits. Vacated slots become 0 (vals must be 0
    where nothing is emitted). MSB-first barrel: collision-free because
    intermediate positions j + (s>>k<<k) stay strictly increasing."""
    x = jnp.pad(vals, ((0, 0), (0, w_out - vals.shape[1])))
    s = jnp.pad(shifts, ((0, 0), (0, w_out - shifts.shape[1])))
    for k in range(nbits - 1, -1, -1):
        d = 1 << k
        mv = s >= d
        xm = jnp.where(mv, x, 0)
        sm = jnp.where(mv, s - d, 0)
        x = jnp.where(mv, 0, x) | _shr2d(xm, d)
        s = jnp.where(mv, 0, s) + _shr2d(sm, d)
    return x


def _rot_rows(x, r, w: int):
    """Cyclic right-rotate each row of [N, w] by per-row r (w pow2)."""
    for k in range(w.bit_length() - 1):
        d = 1 << k
        bit = ((r >> k) & 1) == 1
        rolled = jnp.concatenate([x[:, -d:], x[:, :-d]], axis=1)
        x = jnp.where(bit[:, None], rolled, x)
    return x


def _out_width(L: int, src_width: int = 0) -> int:
    """Static output width: a power of two covering the concatenated
    source row (``src_width`` = escaped line + constant bank + ts text,
    which the rotate-assembly requires to fit) and typical GELF output
    for lines of width L."""
    w = 512
    while w < 2 * L or w < src_width:
        w *= 2
    return w


def escape_stage(batch, lens, iota, cumsum_fn, assemble: bool):
    """JSON-escape classification + (when assembling) the escaped row.

    Returns a dict with: ``esc_row`` ([N, L+E_CAP] u8 escaped bytes, or
    None when not assembling), ``esc_i`` (int [N, L] escape indicator),
    ``ne_total`` ([N] escapes per row), ``bad_ctl`` ([N, L] control
    bytes needing 6-byte \\u00XX escapes — off-tier), and ``dmap(a)``
    mapping raw offsets to escaped offsets."""
    bb = batch.astype(_I32)
    valid = iota < lens.astype(_I32)[:, None]
    two_ctl = ((bb == 8) | (bb == 9) | (bb == 10) | (bb == 12) | (bb == 13))
    esc = ((bb == 34) | (bb == 92) | two_ctl) & valid
    bad_ctl = (bb < 32) & ~two_ctl & valid
    esc_i = esc.astype(_I32)
    ne_incl = cumsum_fn(esc_i)
    ne_excl = ne_incl - esc_i
    ne_total = ne_incl[:, -1]

    esc_row = None
    if assemble:
        mapped = jnp.where(bb == 8, ord("b"),
                 jnp.where(bb == 9, ord("t"),
                 jnp.where(bb == 10, ord("n"),
                 jnp.where(bb == 12, ord("f"),
                 jnp.where(bb == 13, ord("r"), bb)))))
        mapped = jnp.where(valid, mapped, 0).astype(_I32)
        nbits = E_CAP.bit_length()
        EW = batch.shape[1] + E_CAP
        s_main = jnp.minimum(ne_excl + esc_i, E_CAP)
        s_pref = jnp.minimum(ne_excl, E_CAP)
        main = _monotone_expand(mapped, s_main, EW, nbits)
        pref = _monotone_expand(jnp.where(esc, ord("\\"), 0).astype(_I32),
                                s_pref, EW, nbits)
        esc_row = (main | pref).astype(_U8)

    def dmap(a):
        a = a.astype(_I32)
        ne_at = jnp.sum(esc_i * (iota < a[:, None]), axis=1)
        return a + ne_at

    return {"esc_row": esc_row, "esc_i": esc_i, "ne_total": ne_total,
            "bad_ctl": bad_ctl, "dmap": dmap, "valid": valid}


def assemble_rows(segs, esc_row, bank: bytes, ts_text, N: int, OW: int):
    """OR-accumulate the per-row segment table into the [N, OW] output.

    ``segs`` is a list of ``(src0 [N], seglen [N])`` in destination
    order; sources index the concatenated row ``escaped line ∥ constant
    bank ∥ timestamp text``.  Returns (acc, out_len).  The scan body
    compiles once (vs once per segment) while each step stays a handful
    of fused [N, OW] elementwise passes."""
    seg_src = jnp.stack([s for s, _ in segs])
    seg_len = jnp.stack([ln for _, ln in segs])
    seg_dst = jnp.cumsum(seg_len, axis=0) - seg_len
    out_len = seg_dst[-1] + seg_len[-1]

    const_row = jnp.asarray(np.frombuffer(bank, dtype=np.uint8))
    CB = len(bank)
    src2 = jnp.concatenate([
        esc_row,
        jnp.broadcast_to(const_row[None, :], (N, CB)),
        ts_text.astype(_U8),
    ], axis=1)
    if src2.shape[1] > OW:
        raise ValueError(f"source row {src2.shape[1]} exceeds OW {OW}")
    src2 = jnp.pad(src2, ((0, 0), (0, OW - src2.shape[1])))
    iow = jax.lax.broadcasted_iota(_I32, (N, OW), 1)

    def step(a, xs):
        src0, seglen, dst0 = xs
        m = (iow >= src0[:, None]) & (iow < (src0 + seglen)[:, None])
        contrib = jnp.where(m, src2, jnp.uint8(0))
        return a | _rot_rows(contrib, (dst0 - src0) % OW, OW), None

    acc, _ = jax.lax.scan(step, jnp.zeros((N, OW), dtype=_U8),
                          (seg_src, seg_len, seg_dst))
    return acc, out_len


@partial(jax.jit, static_argnames=("G",))
def _compact_kernel(acc, out_len, tier, *, G: int = COMPACT_G):
    """Row compaction on device: pack the tier rows' output bytes into a
    contiguous group-aligned buffer so the host fetches ~sum(out_len)
    bytes instead of the padded ``[N, OW]`` matrix.

    Rows are already left-aligned, so compaction is a pure left-shift of
    whole G-byte groups: row i's ``ceil(len/G)`` leading groups move to
    group offset ``base[i] = sum_j<i ceil(len_j/G)``.  The per-group
    shift ``i*(OW/G) - base[i]`` is row-constant and nondecreasing, and
    destinations are strictly increasing, so an LSB-first barrel shifter
    is collision-free: after applying bits 0..k, two valid groups a < b
    satisfy ``p_b - p_a = (b-a) - ((s_b&m)-(s_a&m)) >= (b-a)-(s_b-s_a)
    >= 1`` (low-bit differences never exceed the full difference when
    the high bits are monotone).  Non-tier and padding groups are zeroed
    and stay put (shift 0); moving groups OR over them harmlessly.

    Returns the flat byte buffer; the host slices the first
    ``sum(ceil(gated_len/G))*G`` bytes (it recomputes base from the
    fetched lengths with the same integer math)."""
    N, OW = acc.shape
    assert OW % G == 0
    ngr = OW // G
    gated = jnp.where(tier, out_len, 0)
    used = (gated + (G - 1)) // G                          # [N]
    base = jnp.cumsum(used) - used                         # exclusive
    gi = jax.lax.broadcasted_iota(_I32, (N, ngr), 1)
    row = jax.lax.broadcasted_iota(_I32, (N, ngr), 0)
    valid = gi < used[:, None]
    shift = jnp.where(valid, row * ngr - base[:, None], 0).reshape(-1)
    x = jnp.where(valid.reshape(-1)[:, None], acc.reshape(N * ngr, G),
                  jnp.uint8(0))
    s = shift
    T = N * ngr
    for k in range(max(T - 1, 1).bit_length()):
        d = 1 << k
        if d >= T:
            break
        mv = ((s >> k) & 1) == 1
        xm = jnp.where(mv[:, None], x, jnp.uint8(0))
        sm = jnp.where(mv, s - d, 0)
        x = jnp.where(mv[:, None], jnp.uint8(0), x)
        s = jnp.where(mv, 0, s)
        x = x | jnp.concatenate(
            [xm[d:], jnp.zeros((d, G), jnp.uint8)], axis=0)
        s = s + jnp.concatenate(
            [sm[d:], jnp.zeros((d,), s.dtype)], axis=0)
    return x.reshape(-1)


def splice_rows(body: np.ndarray, row_off: np.ndarray,
                ins_src: np.ndarray, ins_at: np.ndarray,
                ins_a: np.ndarray, ins_l: np.ndarray):
    """Generic per-row insertion splice for constant/computed elision.

    Every row gets K insertions: insertion k of row r takes
    ``ins_l[r, k]`` bytes from ``ins_src`` at offset ``ins_a[r, k]`` and
    lands at body-relative offset ``ins_at[r, k]`` (offsets ascending
    per row, measured in the elided body's coordinates).  One segment
    gather (2K+1 segments/row, native concat when available) rebuilds
    the full rows.  ``splice_elided_rows`` is the fixed
    head/ts-label/tail specialization; the →RFC5424/→LTSV/→capnp routes
    use this one because their elided constants sit at row-dependent
    offsets (mid-row gaps, per-row PRI digits, computed capnp headers).
    Returns (full body, full row_off)."""
    from .assemble import concat_segments, exclusive_cumsum

    R = row_off.size - 1
    K = ins_at.shape[1]
    lens = np.diff(row_off).astype(np.int64)
    B = int(np.asarray(body).size)
    src = np.concatenate([np.asarray(body, dtype=np.uint8),
                          np.asarray(ins_src, dtype=np.uint8)])
    seg_src = np.empty((R, 2 * K + 1), dtype=np.int64)
    seg_len = np.empty((R, 2 * K + 1), dtype=np.int64)
    r0 = row_off[:-1].astype(np.int64)
    prev = np.zeros(R, dtype=np.int64)
    for k in range(K):
        at = np.minimum(np.asarray(ins_at[:, k], dtype=np.int64), lens)
        seg_src[:, 2 * k] = r0 + prev
        seg_len[:, 2 * k] = np.maximum(at - prev, 0)
        seg_src[:, 2 * k + 1] = B + np.asarray(ins_a[:, k], dtype=np.int64)
        seg_len[:, 2 * k + 1] = np.asarray(ins_l[:, k], dtype=np.int64)
        prev = np.maximum(at, prev)
    seg_src[:, 2 * K] = r0 + prev
    seg_len[:, 2 * K] = lens - prev
    out = concat_segments(src, seg_src.ravel(), seg_len.ravel())
    new_lens = lens + np.asarray(ins_l, dtype=np.int64).sum(axis=1)
    return out, exclusive_cumsum(new_lens)


def splice_elided_rows(body: np.ndarray, row_off: np.ndarray,
                       ts_lens: np.ndarray, head: bytes, ts_label: bytes,
                       tail: bytes):
    """Rebuild full output rows from constant-elided device rows.

    Output compaction 2.0: the head constant, the timestamp-label
    constant, and the tail constant (+ framing suffix) are identical for
    every row and at host-computable positions — the head leads, the
    timestamp text is the row's final ``ts_lens[i]`` bytes, the tail
    trails — so the kernel skips assembling them and the D2H transfer
    ships only the variable bytes.  This splice restores the exact
    host-tier bytes with one segment gather (5 segments/row, native
    concat when available).  Returns (full body, full row_off)."""
    from .assemble import concat_segments, exclusive_cumsum

    R = row_off.size - 1
    lens = np.diff(row_off).astype(np.int64)
    deco = np.frombuffer(head + ts_label + tail, dtype=np.uint8)
    src = np.concatenate([np.asarray(body, dtype=np.uint8), deco])
    B = int(np.asarray(body).size)
    h, lb, tl = len(head), len(ts_label), len(tail)
    ts = np.asarray(ts_lens, dtype=np.int64)
    pre = lens - ts  # variable bytes before the timestamp text
    seg_src = np.stack([
        np.full(R, B, dtype=np.int64),
        row_off[:-1].astype(np.int64),
        np.full(R, B + h, dtype=np.int64),
        row_off[:-1].astype(np.int64) + pre,
        np.full(R, B + h + lb, dtype=np.int64),
    ], axis=1).ravel()
    seg_len = np.stack([
        np.full(R, h, dtype=np.int64), pre,
        np.full(R, lb, dtype=np.int64), ts,
        np.full(R, tl, dtype=np.int64),
    ], axis=1).ravel()
    out = concat_segments(src, seg_src, seg_len)
    return out, exclusive_cumsum(lens + h + lb + tl)


def ts_text_block(small: Dict[str, np.ndarray], ts_vals_fn=None,
                  render=None):
    """Format per-row timestamp digits host-side.  The native threaded
    formatter (fg_format_f64_json: to_chars shortest round-trip,
    json_f64 notation — differentially fuzzed in
    tests/test_native_and_chunks.py) handles near-unique real-stream
    stamps at full rate; without the library, fall back to dedup +
    per-unique json_f64 (only fast for repetitive streams).

    ``ts_vals_fn(small, ok_mask) -> float64 array`` overrides the
    default days/sod/off/nanos combine for formats whose device tier
    carries other timestamp channels (ltsv float spans).

    ``render(val) -> bytes`` overrides the json_f64 notation for
    output formats whose timestamp text is not serde_json's — the
    →RFC5424 routes' rfc3339-ms form, the →LTSV routes' Rust Display
    form, the →capnp route's raw little-endian f64 words — via the
    dedup path (those routes' stamps are either repetitive or cheap)."""
    from .. import native
    from ..utils.rustfmt import json_f64

    okh = small["ok"].astype(bool)
    if ts_vals_fn is not None:
        ts_vals = ts_vals_fn(small, okh)
    else:
        masked = {k: np.where(okh, small[k], 0)
                  for k in ("days", "sod", "off", "nanos")}
        ts_vals = compute_ts(masked)
    if render is None:
        res = native.format_f64_json_native(ts_vals, TS_W)
        if res is not None:
            return res

        def render(val):
            return json_f64(float(val)).encode("ascii")
    uniq, inv = np.unique(ts_vals, return_inverse=True)
    txt = np.zeros((uniq.size, TS_W), dtype=np.uint8)
    ulen = np.zeros(uniq.size, dtype=np.int32)
    for u, val in enumerate(uniq):
        s = render(float(val))[:TS_W]
        txt[u, :len(s)] = np.frombuffer(s, dtype=np.uint8)
        ulen[u] = len(s)
    return txt[inv], ulen[inv]


def build_bank(parts: Dict[str, bytes], suffix: bytes):
    """Concatenate a device encoder's segment constants into one bank
    (the framing suffix rides the tail constant); returns
    (bank_bytes, {name: offset})."""
    offs, bank = {}, b""
    for k, v in parts.items():
        if k == "tail":
            v = v + suffix
        offs[k] = len(bank)
        bank += v
    return bank, offs


_AMBIG_LEN = 8     # name-key bytes captured for sorting
_BIG = 0x7FFFFFFF  # sort key for absent pairs (names are ASCII < 0x7f)

# optimal 12-comparator sorting network for 6 elements
_NET6 = ((0, 5), (1, 3), (2, 4), (1, 2), (3, 4), (0, 3), (2, 5),
         (0, 1), (2, 3), (4, 5), (1, 2), (3, 4))


@lru_cache(maxsize=None)
def _sort_network(n: int):
    """Comparator list sorting ``n`` elements: the hand-tuned
    12-comparator network for the common 6-pair tier, Batcher
    odd-even mergesort for any other width (63 comparators at n=16 —
    the wide tier that keeps 7..16-pair streams on-device)."""
    if n == 6:
        return _NET6
    pairs = []
    p = 1
    while p < n:
        k = p
        while k >= 1:
            j = k % p
            while j <= n - 1 - k:
                for i in range(0, min(k, n - j - k)):
                    if (i + j) // (p * 2) == (i + j + k) // (p * 2):
                        pairs.append((i + j, i + j + k))
                j += 2 * k
            k //= 2
        p *= 2
    return tuple(pairs)


def sort_pairs_by_key8(bb, iota, cols, max_pairs: int, slot_valid=None):
    """Sort per-pair span columns by their names' first 8 bytes
    (serde_json BTreeMap order) with a 12-comparator network, and flag
    rows whose order the 8-byte prefix cannot decide.

    ``cols`` must carry lists keyed ``ns``/``ne`` (raw name spans used
    for the keys) plus any payload lists to ride the swaps; this adds
    ``hi``/``lo``/``nlen`` key lists, sorts everything in place, and
    returns the ambig mask: equal 8-byte prefixes are orderable only
    when exactly one name is ≤8 bytes (a strict prefix of the other) —
    equal-length or both-longer pairs (including duplicates, dict
    last-wins semantics) fall back to the host tiers.

    Slots are normally pre-compacted (valid pairs first, ``_pair_count``
    gating); ``slot_valid`` (per-slot [N] bool list) instead marks valid
    slots in place — invalid ones key to _BIG and the sort itself
    compacts them to the tail, saving callers the O(F^2) where-chain
    compaction (device_gelf_gelf feeds raw field order this way)."""
    import jax.numpy as jnp

    N = bb.shape[0]
    pair_count = cols.pop("_pair_count")
    cols["hi"], cols["lo"], cols["nlen"] = [], [], []
    for p in range(max_pairs):
        ns_r = cols["ns_raw"][p]
        ne_r = cols["ne_raw"][p]
        pv = (p < pair_count) if slot_valid is None else slot_valid[p]
        r = iota - ns_r[:, None]
        in_name = (r >= 0) & (iota < ne_r[:, None])
        z = jnp.where(in_name, bb, 0)
        hi = jnp.sum(z * ((r == 0) * (1 << 24) + (r == 1) * (1 << 16)
                          + (r == 2) * (1 << 8) + (r == 3)), axis=1)
        lo = jnp.sum(z * ((r == 4) * (1 << 24) + (r == 5) * (1 << 16)
                          + (r == 6) * (1 << 8) + (r == 7)), axis=1)
        cols["hi"].append(jnp.where(pv, hi, _BIG))
        cols["lo"].append(jnp.where(pv, lo, _BIG))
        cols["nlen"].append(jnp.where(pv, ne_r - ns_r, _BIG))

    payload = [k for k in cols if k not in ("hi", "lo", "nlen")]
    for i, j in _sort_network(max_pairs):
        ah, bh = cols["hi"][i], cols["hi"][j]
        al, bl = cols["lo"][i], cols["lo"][j]
        an, bn = cols["nlen"][i], cols["nlen"][j]
        swap = (bh < ah) | ((bh == ah) & ((bl < al)
                            | ((bl == al) & (bn < an))))
        for key in ("hi", "lo", "nlen", *payload):
            a, b = cols[key][i], cols[key][j]
            cols[key][i] = jnp.where(swap, b, a)
            cols[key][j] = jnp.where(swap, a, b)

    ambig = jnp.zeros((N,), dtype=bool)
    for p in range(max_pairs - 1):
        keq = ((cols["hi"][p] == cols["hi"][p + 1])
               & (cols["lo"][p] == cols["lo"][p + 1])
               & (cols["hi"][p] != _BIG))
        la, lb = cols["nlen"][p], cols["nlen"][p + 1]
        ambig |= keq & ((la == lb) | ((la > _AMBIG_LEN)
                                      & (lb > _AMBIG_LEN)))
    return ambig


def gelf_route_ok(encoder, merger, extras_placeable) -> bool:
    """Shared applicability predicate for the device GELF-encode routes:
    GELF output over line/nul/syslen framing, with the kill switch and
    merger allowlist in ONE place; ``extras_placeable(extra) -> bool``
    is the per-layout static-placement check."""
    import os

    from ..encoders.gelf import GelfEncoder
    from ..mergers import LineMerger, NulMerger, SyslenMerger

    if os.environ.get("FLOWGGER_DEVICE_ENCODE", "1") == "0":
        return False
    if type(encoder) is not GelfEncoder:
        return False
    if encoder.extra and not extras_placeable(encoder.extra):
        return False
    return merger is None or type(merger) in (LineMerger, NulMerger,
                                              SyslenMerger)


def encode_route_ok(encoder, merger, enc_cls) -> bool:
    """Applicability predicate shared by the non-GELF device encode
    routes (→RFC5424 / →LTSV / →capnp): exact encoder type over
    line/nul/syslen framing, honoring the same kill switch as the GELF
    legs.  Their extras are always statically placeable (LTSV/capnp
    extras render to one constant blob, RFC5424 has none), so unlike
    ``gelf_route_ok`` there is no placement check."""
    import os

    from ..mergers import LineMerger, NulMerger, SyslenMerger

    if os.environ.get("FLOWGGER_DEVICE_ENCODE", "1") == "0":
        return False
    if type(encoder) is not enc_cls:
        return False
    return merger is None or type(merger) in (LineMerger, NulMerger,
                                              SyslenMerger)


def fetch_encode_driver(kernel, out, batch_dev, lens_dev, packed, encoder,
                        merger, route_state, suffix: bytes, syslen: bool,
                        scalar_fn, fallback_frac: float,
                        decline_limit: int, cooldown: int,
                        ts_keys=("days", "sod", "off", "nanos"),
                        ts_vals_fn=None, ts_render=None, wide=None,
                        elide=None, kname_prefix=None,
                        compile_timeout_s=None, route_label=None,
                        small_fetch_fn=None, fused_counters=True):
    """Shared fetch flow for every device-encode format:

    1. phase-1 tier probe (``kernel(..., assemble=False)`` — XLA
       dead-code-eliminates the assembly) with a pessimistic TS_W
       timestamp width, so persistently declining streams never pay the
       assembly or the host timestamp formatting;
    2. decline hysteresis via ``route_state`` (caller-owned dict);
    3. timestamp text upload (native formatter), full kernel;
    4. on-device row compaction when it saves >15% of the fetch, with
       row lengths fetched as u16 and the uncompacted fallback trimmed
       on device to the batch's real row count and max row length;
    5. constant elision (``elide=(head, ts_label, tail)``): the kernel
       skipped those row-constant segments, the splice restores them
       host-side, and the D2H ships only variable bytes — the step that
       brings fetched bytes/row at or under emitted bytes/row;
    6. syslen prefixing (host splice over the output-sized body);
    7. fallback splicing through ``finish_block``.

    ``kname_prefix`` overrides the compile-watchdog slot namespace (the
    fused-route closures all live in one module — without it two routes
    at the same shape would share a slot and mask each other's pending
    compiles); ``compile_timeout_s`` overrides the watchdog deadline for
    every guarded call in this flow; ``route_label`` exports per-route
    ``fetch_bytes_per_row_{label}`` / ``emit_bytes_per_row_{label}``
    gauges, plus the ``fused_rows`` counters unless
    ``fused_counters=False`` (split-tier callers share a logical
    route's gauges without claiming its rows as fused).

    Returns (BlockResult | None, fetch_seconds); None = caller should
    use the span-fetch host path."""
    import time as _time

    from ..utils.metrics import registry as _metrics
    from .block_common import apply_syslen_prefix, finish_block

    batch, lens, chunk, starts, orig_lens, n_real = packed
    n = int(n_real)
    N = batch_dev.shape[0]

    if route_state is not None and route_state.get("cooldown", 0) > 0:
        route_state["cooldown"] -= 1
        return None, 0.0

    t_fetch = 0.0
    fetched = [0]

    def _fetch(arr):
        nonlocal t_fetch
        t0 = _time.perf_counter()
        h = np.asarray(arr)
        t_fetch += _time.perf_counter() - t0
        fetched[0] += h.nbytes
        return h

    empty_ts = jnp.zeros((N, 0), dtype=jnp.uint8)
    full_ts_len = jnp.full((N,), TS_W, dtype=jnp.int32)

    def probe(k):
        """Phase-1 tier probe.  A kernel may return a dict — ``tier``
        plus extra device channels (e.g. gelf→GELF's timestamp parse,
        which only exists encode-side); the extras merge into ``out``
        so the ts fetch below sees them like decode outputs."""
        t1 = k(empty_ts, full_ts_len, False)
        if isinstance(t1, dict):
            extra = {k2: v for k2, v in t1.items() if k2 != "tier"}
            return t1["tier"], extra
        return t1, None

    # compile-watchdog slot names: stable per kernel module + shape +
    # device (closures are rebuilt per batch; the jit cache underneath
    # is not; lane dispatch compiles one executable per device, so each
    # lane's compile needs its own watchdog slot)
    try:
        _dev = ",".join(sorted(str(d) for d in batch_dev.devices()))
    except Exception:  # noqa: BLE001 - tracers/older arrays have no .devices()
        _dev = "default"
    kname = (f"{kname_prefix or getattr(kernel, '__module__', 'device')}:"
             f"{tuple(batch_dev.shape)}:{_dev}")

    def _guarded(slot, fn, *args):
        return guarded_compile_call(slot, fn, *args,
                                    timeout_s=compile_timeout_s)

    def _declined_compile():
        if route_state is not None:
            route_state["cooldown"] = cooldown
        return None, t_fetch

    wide_adopted = False
    try:
        tier1, extra1 = _guarded(f"{kname}:probe", probe, kernel)
    except CompileTimeout:
        return _declined_compile()
    if extra1:
        out = {**out, **extra1}
    tier1_np = _fetch(tier1)[:n]

    starts64 = np.asarray(starts[:n], dtype=np.int64)
    lens64 = np.asarray(orig_lens[:n], dtype=np.int64)
    max_len = batch.shape[1]
    cand1 = tier1_np & (lens64 <= max_len)

    # pair-budget escalation: when the base-width tier declines (e.g. a
    # 7+-pair stream) and the format has a wide kernel (the encode-side
    # analog of decode's 16-pair rescue), probe it before giving the
    # batch to the host path; wide batches pay the bigger sort network
    # and segment table only when the base width actually failed.  A
    # failed wide probe sets its own cooldown so streams declining for
    # non-pair reasons (escapes, bad stamps) don't pay a futile second
    # decode + probe every batch.
    if (n and wide is not None
            and (1.0 - cand1.mean()) > fallback_frac):
        wide_cd = 0 if route_state is None else \
            route_state.get("wide_cooldown", 0)
        if wide_cd > 0:
            route_state["wide_cooldown"] = wide_cd - 1
        else:
            out_w, kernel_w = wide()
            try:
                tier1w, extraw = _guarded(
                    f"{kname}:probe-wide", probe, kernel_w)
            except CompileTimeout:
                tier1w = None
            if tier1w is None:
                if route_state is not None:
                    route_state["wide_cooldown"] = cooldown
            else:
                cand1w = _fetch(tier1w)[:n] & (lens64 <= max_len)
                if (1.0 - cand1w.mean()) <= fallback_frac:
                    _metrics.inc("device_encode_wide_batches")
                    kernel, out, cand1 = kernel_w, out_w, cand1w
                    wide_adopted = True
                    if extraw:
                        out = {**out, **extraw}
                elif route_state is not None:
                    route_state["wide_cooldown"] = cooldown

    if n and (1.0 - cand1.mean()) > fallback_frac:
        _metrics.inc("device_encode_declined")
        _metrics.inc("device_encode_fetch_bytes", fetched[0])
        if route_state is not None:
            route_state["declines"] = route_state.get("declines", 0) + 1
            if route_state["declines"] >= decline_limit:
                route_state["cooldown"] = cooldown
                route_state["declines"] = 0
        return None, t_fetch
    if route_state is not None:
        route_state["declines"] = 0

    if small_fetch_fn is not None:
        # route-provided small-channel fetch (fused ltsv): narrowed
        # dtypes and kind-conditional channel skips keep the fixed
        # per-row D2H overhead under the elided-constant savings
        small = small_fetch_fn(out, _fetch)
    else:
        small = {k: _fetch(out[k]) for k in ("ok",) + tuple(ts_keys)}
    # only phase-1 candidates get host timestamp formatting (ADVICE r4):
    # tier-rejected rows (e.g. LTSV float-stamp rows) may hold garbage
    # days/sod and their text is discarded anyway.  Phase-2 acceptance
    # is intersected with cand1 below so a non-candidate can never ride
    # the device tier with the placeholder text.
    cand1_full = np.zeros(small["ok"].shape[0], dtype=bool)
    cand1_full[:n] = cand1
    small["ok"] = small["ok"].astype(bool) & cand1_full
    ts_text, ts_len = ts_text_block(small, ts_vals_fn, render=ts_render)
    # wide kernels get their own watchdog slot: the narrow assemble
    # being warm says nothing about the (bigger) wide compile
    asm_slot = f"{kname}:assemble-wide" if wide_adopted else \
        f"{kname}:assemble"
    try:
        acc, out_len, tier = _guarded(
            asm_slot, kernel, jnp.asarray(ts_text),
            jnp.asarray(ts_len), True)
    except CompileTimeout:
        return _declined_compile()

    # full-N fetches (tiny): the host must recompute the compaction
    # layout with the exact integer math the device used, including any
    # dp-padding rows beyond n.  Lengths ride D2H as u16 (they are
    # bounded by OW) — half the width of the old i32 fetch.
    N_acc, OW = acc.shape
    tier_full = _fetch(tier)
    len_full = _fetch(out_len.astype(jnp.uint16) if OW <= 0xFFFF
                      else out_len).astype(np.int64)
    tier_np = tier_full[:n]
    len_np = len_full[:n]

    # the real (shorter) timestamp text can only widen the tier vs the
    # pessimistic phase-1 gate, but rows outside cand1 carry placeholder
    # ts text (masked above), so the decision set is the intersection
    cand = tier_np & cand1
    ridx = np.flatnonzero(cand)

    G = COMPACT_G
    gated = np.where(tier_full, len_full, 0)
    total_bytes = int(gated.sum())
    flat = None
    if (total_bytes and ridx.size
            and N_acc * OW > total_bytes * COMPACT_MIN_SAVING):
        # device-side row compaction: D2H ≈ sum(out_len), G-aligned
        try:
            flat = _guarded(
                f"{kname}:compact-wide" if wide_adopted
                else f"{kname}:compact", _compact_kernel, acc, out_len, tier)
        except CompileTimeout:
            flat = None  # trimmed-width fetch below until the compile lands
    if flat is not None:
        used = (gated + (G - 1)) // G
        base = np.cumsum(used) - used
        total_groups = int(used.sum())
        comp = _fetch(flat[: total_groups * G]).reshape(-1, G)
        u = used[ridx]
        ucum = np.cumsum(u) - u
        pos = np.arange(int(u.sum()), dtype=np.int64) - np.repeat(ucum, u)
        gidx = np.repeat(base[ridx], u) + pos
        gv = np.minimum(G, np.repeat(len_np[ridx], u) - pos * G)
        grp = comp[gidx]
        body = grp[np.arange(G)[None, :] < gv[:, None]]
        row_off = exclusive_cumsum(len_np[ridx])
        _metrics.inc("fetch_bytes_saved",
                     max(0, N_acc * OW - total_groups * G))
    elif ridx.size:
        # compaction skipped (or its compile pending): still trim the
        # fetched matrix on device to the real row count and the batch's
        # max gated row length instead of shipping the padded [N, OW].
        # maxw quantizes up to 128 so the slice program count stays
        # bounded, and the slice itself runs under the compile watchdog
        # (a data-dependent shape is a fresh XLA program; on a hung
        # remote compile the plain full-matrix transfer below cannot
        # stall — it is a pure copy of an existing buffer)
        maxw = min(OW, -(-max(int(gated[:n].max()), 1) // 128) * 128)
        try:
            trimmed = _guarded(
                f"{kname}:trim:{maxw}", lambda: acc[:n, :maxw])
        except CompileTimeout:
            trimmed = None
        if trimmed is not None:
            out_np = _fetch(trimmed)
            _metrics.inc("fetch_bytes_saved",
                         max(0, N_acc * OW - n * maxw))
        else:
            out_np = _fetch(acc)[:n]
        rows = out_np[ridx]
        m = np.arange(rows.shape[1])[None, :] < len_np[ridx, None]
        body = rows[m]
        row_off = exclusive_cumsum(len_np[ridx])
    else:
        body = np.zeros(0, dtype=np.uint8)
        row_off = np.zeros(1, dtype=np.int64)

    if elide is not None and ridx.size:
        # restore the head / timestamp-label / tail constants the kernel
        # left out of the transfer (byte-identical by construction); a
        # callable elide owns the whole splice — the →RFC5424/→LTSV/
        # →capnp routes' elided segments carry row-dependent bytes (PRI
        # digits, computed capnp headers) or sit at mid-row offsets
        if callable(elide):
            body, row_off = elide(
                body, row_off, small, np.asarray(ts_text),
                np.asarray(ts_len, dtype=np.int64), ridx)
        else:
            body, row_off = splice_elided_rows(
                body, row_off, np.asarray(ts_len, dtype=np.int64)[ridx],
                *elide)

    prefix_lens_tier = None
    if syslen and ridx.size:
        final_buf, row_off, prefix_lens_tier = apply_syslen_prefix(
            body, row_off, np.diff(row_off))
    else:
        final_buf = body.tobytes()

    _metrics.inc("device_encode_rows", int(ridx.size))
    _metrics.inc("device_encode_scalar_rows", int(n - ridx.size))
    _metrics.inc("device_encode_fetch_bytes", fetched[0])
    _metrics.inc("device_encode_out_bytes", len(final_buf))
    if route_label is not None:
        if fused_counters:
            _metrics.inc("fused_rows", int(ridx.size))
            _metrics.inc(f"fused_rows_{route_label}", int(ridx.size))
        if ridx.size:
            # ONE denominator for both gauges (tier rows): dividing
            # fetch by all n rows diluted it whenever fallback rows
            # existed, reporting fetch<emit even when per-tier-row
            # fetch exceeded emit.  Tier-row fetch is the conservative
            # reading — the batch-wide small fetches are all charged to
            # the tier rows.
            _metrics.set_gauge(f"fetch_bytes_per_row_{route_label}",
                               round(fetched[0] / int(ridx.size), 1))
            # tier-row emitted width (splice constants included), the
            # number the fetch gauge must stay under
            _metrics.set_gauge(
                f"emit_bytes_per_row_{route_label}",
                round(float(row_off[-1]) / int(ridx.size), 1))
    res = finish_block(chunk, starts64, lens64, n, cand, ridx, final_buf,
                       row_off, prefix_lens_tier, suffix, syslen, merger,
                       encoder, scalar_fn=scalar_fn)
    return res, t_fetch
