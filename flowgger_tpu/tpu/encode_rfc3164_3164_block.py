"""Columnar RFC3164→RFC3164 re-encode: the legacy-syslog fast path's
span tables become framed legacy-syslog bytes again (the reference's
syslog→syslog relay mode, rfc3164_encoder.rs:28-97).

An rfc3164 fast-path record carries hostname/msg spans, optional PRI
and an integer-second timestamp, so each row is nine fixed segments::

    [ "<" npri-digits ">" ] TS_header hostname " " msg

with npri re-rendered from facility<<3|severity (the decoder may have
normalized leading zeros, so the digits cannot be a span) and the
header timestamp (``Mon  d hh:mm:ss ``) deduplicated host-side
(second granularity makes real streams highly repetitive).  The
``syslog_prepend_timestamp`` option emits wall-clock-at-encode-time
text, which is inherently per-call — those configs keep the Record
path.  Rows outside the tier re-run the scalar oracle, byte-identical
in every case."""


from __future__ import annotations

# byte-identity contract (flowcheck FC03): the scalar counterpart
# this route must stay byte-identical to, and the differential
# test that enforces it
SCALAR_ORACLE = "flowgger_tpu.encoders.rfc3164:RFC3164Encoder"
DIFF_TEST = "tests/test_device_rfc3164.py::test_3164_self_encode_block_matches_scalar"

from typing import Dict, Optional

import numpy as np

from ..mergers import Merger
from ..utils.timeparse import format_rfc3164_header_ts
from .assemble import (
    build_source,
    concat_segments,
    decimal_segments,
    exclusive_cumsum,
)
from .block_common import (
    apply_syslen_prefix,
    finish_block,
    merger_suffix,
    ts_scratch,
)
from .materialize_rfc3164 import _scalar_3164

_SEGS = 10  # < d d d > ts host " " msg suffix


def encode_rfc3164_3164_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
):
    spec = merger_suffix(merger)
    if spec is None or encoder.header_time_format is not None:
        return None
    suffix, syslen = spec

    n = int(n_real)
    starts64 = np.asarray(starts[:n], dtype=np.int64)
    lens64 = np.asarray(orig_lens[:n], dtype=np.int64)
    ok = np.asarray(out["ok"][:n], dtype=bool)
    has_high = np.asarray(out["has_high"][:n], dtype=bool)
    cand = ok & (lens64 <= max_len) & ~has_high

    ridx = np.flatnonzero(cand)
    R = ridx.size
    final_buf = b""
    row_off = np.zeros(1, dtype=np.int64)
    prefix_lens_tier = None

    if R:
        st = starts64[ridx]
        host_a = st + np.asarray(out["host_start"])[:n][ridx].astype(np.int64)
        host_b = st + np.asarray(out["host_end"])[:n][ridx].astype(np.int64)
        msg_a = st + np.asarray(out["msg_start"])[:n][ridx].astype(np.int64)
        row_end = st + lens64[ridx]
        has_pri = np.asarray(out["has_pri"][:n], dtype=bool)[ridx]
        npri = (((np.asarray(out["facility"])[:n][ridx].astype(np.int64)
                  << 3) & 0xF8)
                + (np.asarray(out["severity"])[:n][ridx].astype(np.int64)
                   & 0x7))

        scratch, ts_off, ts_len = ts_scratch(out, n, ridx,
                                             format_rfc3164_header_ts)
        consts, offs = build_source(b"<", b">", b" ", b"0123456789",
                                    suffix, scratch)
        o_lt, o_gt, o_sp, o_dig, o_suffix, o_scratch = offs
        chunk_arr = np.frombuffer(chunk_bytes, dtype=np.uint8)
        cbase = int(chunk_arr.size)
        src = np.concatenate([chunk_arr, consts])

        dsrc, dlen = decimal_segments(npri, cbase + o_dig, width=3)
        dsrc = dsrc.reshape(R, 3)
        dlen = dlen.reshape(R, 3) * has_pri[:, None]

        seg_src = np.empty((R, _SEGS), dtype=np.int64)
        seg_len = np.empty((R, _SEGS), dtype=np.int64)
        cols = (
            (cbase + o_lt, np.where(has_pri, 1, 0)),
            (dsrc[:, 0], dlen[:, 0]),
            (dsrc[:, 1], dlen[:, 1]),
            (dsrc[:, 2], dlen[:, 2]),
            (cbase + o_gt, np.where(has_pri, 1, 0)),
            (cbase + o_scratch + ts_off, ts_len),
            (host_a, np.maximum(host_b - host_a, 0)),
            (cbase + o_sp, 1),
            (msg_a, np.maximum(row_end - msg_a, 0)),
            (cbase + o_suffix, len(suffix)),
        )
        for k, (s, ln) in enumerate(cols):
            seg_src[:, k] = s
            seg_len[:, k] = ln

        flat_src = seg_src.ravel()
        flat_len = seg_len.ravel()
        dst0 = exclusive_cumsum(flat_len)
        body = concat_segments(src, flat_src, flat_len, dst0)
        row_off = dst0[::_SEGS]
        tier_lens = np.diff(row_off)
        if syslen:
            final_buf, row_off, prefix_lens_tier = apply_syslen_prefix(
                body, row_off, tier_lens)
        else:
            final_buf = body.tobytes()

    return finish_block(chunk_bytes, starts64, lens64, n, cand, ridx,
                        final_buf, row_off, prefix_lens_tier, suffix,
                        syslen, merger, encoder, scalar_fn=_scalar_3164)
