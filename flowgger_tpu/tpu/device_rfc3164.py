"""Device-side RFC3164→GELF encode: final framed bytes assembled on
device for the legacy-syslog fast path, compacted and fetched
output-sized (device_common machinery — same contract as device_gelf).

The rfc3164 fast-path record carries no SD, no appname/procid/msgid, an
unstripped message, and the whole line as full_message
(rfc3164_decoder.rs:31-122 lenient grammar; materialize_rfc3164.py), so
the sorted-key GELF object is eleven segments per row::

    {"full_message":F,"host":H,["level":N,]"short_message":M,
     "timestamp":T,"version":"1.1"}

with the level pair gated per row on has_pri — exactly the layout of
the host tier (encode_rfc3164_gelf_block.py), whose byte constants this
kernel shares so fallback splices can never diverge.
"""


from __future__ import annotations

# byte-identity contract (flowcheck FC03): the scalar counterpart
# this route must stay byte-identical to, and the differential
# test that enforces it
SCALAR_ORACLE = "flowgger_tpu.encoders.gelf:GelfEncoder"
DIFF_TEST = "tests/test_device_rfc3164.py::test_device_3164_matches_scalar_and_engages"

from functools import partial

import jax
import jax.numpy as jnp

from .device_common import (
    E_CAP,
    TS_W,
    _out_width,
    assemble_rows,
    escape_stage,
    fetch_encode_driver,
)
from .encode_rfc3164_gelf_block import (
    _C_HOST,
    _C_LEVEL,
    _C_OPEN,
    _C_SEVD,
    _C_SHORT_NOPRI,
    _C_SHORT_PRI,
    _C_TAIL,
    _C_TS,
)
from .rfc5424 import _cumsum, best_scan_impl

_I32 = jnp.int32

FALLBACK_FRAC = 0.05
DECLINE_LIMIT = 3
COOLDOWN = 16

_PARTS = {
    "open": _C_OPEN,
    "host": _C_HOST,
    "level": _C_LEVEL,
    "short_p": _C_SHORT_PRI,
    "short_n": _C_SHORT_NOPRI,
    "ts": _C_TS,
    "tail": _C_TAIL,
    "sevd": _C_SEVD,
}


def _bank(suffix: bytes, extras=()):
    """Constant bank; extras fold in via the host tier's
    gelf_extra_consts_3164 so the two tiers can never diverge."""
    parts = dict(_PARTS)
    parts["hl"] = b""
    parts["l2a"] = b""
    parts["l2b"] = b""
    if extras:
        from .encode_rfc3164_gelf_block import gelf_extra_consts_3164

        econsts = gelf_extra_consts_3164(list(extras))
        assert econsts is not None  # route_ok pre-checked
        (parts["open"], parts["host"], parts["hl"], parts["l2a"],
         parts["l2b"], parts["short_p"], parts["short_n"], parts["ts"],
         parts["tail"]) = econsts
    from .device_common import build_bank

    bank, offs = build_bank(parts, suffix)
    return bank, offs, parts


def elide_spec(suffix: bytes, extras=()):
    """(head, ts-label, tail) constants the elided kernel skips and the
    host splice restores — single source shared with the fused route."""
    _, _, parts = _bank(suffix, extras)
    return (parts["open"], parts["ts"], parts["tail"] + suffix)


@partial(jax.jit, static_argnames=("suffix", "impl", "assemble",
                                   "extras", "elide"))
def _encode_kernel(batch, lens, dec, ts_text, ts_len, *, suffix: bytes,
                   impl: str, assemble: bool = True, extras=(),
                   elide: bool = False):
    N, L = batch.shape
    bank, off, parts = _bank(suffix, extras)
    OW = _out_width(L, L + E_CAP + len(bank) + TS_W)
    iota = jax.lax.broadcasted_iota(_I32, (N, L), 1)

    es = escape_stage(batch, lens, iota,
                      lambda x: _cumsum(x, impl), assemble)
    dmap = es["dmap"]

    lens32 = lens.astype(_I32)
    host_s, host_e = dmap(dec["host_start"]), dmap(dec["host_end"])
    msg_s = dmap(dec["msg_start"])
    row_e = lens32 + es["ne_total"]     # dmap(lens) without the reduction
    has_pri = dec["has_pri"].astype(bool)

    EW = L + E_CAP
    cbase = EW
    tbase = EW + len(bank)
    zero = jnp.zeros((N,), dtype=_I32)
    # constant-elision mode (elide=True) skips the row-constant head,
    # timestamp-label, and tail segments: the host splice restores them
    # after an output-sized variable-bytes-only D2H fetch
    # (device_common.splice_elided_rows — same contract as device_gelf)
    segs = [] if elide else [
        (zero + (cbase + off["open"]), zero + len(parts["open"])),
    ]
    segs += [
        (zero, row_e),                                   # full_message
        (zero + (cbase + off["host"]), zero + len(parts["host"])),
        (host_s, jnp.maximum(host_e - host_s, 0)),
        (zero + (cbase + off["hl"]), zero + len(parts["hl"])),
        (zero + (cbase + off["level"]),
         jnp.where(has_pri, len(parts["level"]), 0)),
        (cbase + off["sevd"] + dec["severity"].astype(_I32),
         jnp.where(has_pri, 1, 0)),
        # extras between level and short: after-number variant when PRI
        # present, string-close variant otherwise (same selection as the
        # short constant below)
        (jnp.where(has_pri, cbase + off["l2a"], cbase + off["l2b"]),
         jnp.where(has_pri, len(parts["l2a"]), len(parts["l2b"]))),
        (jnp.where(has_pri, cbase + off["short_p"],
                   cbase + off["short_n"]),
         jnp.where(has_pri, len(parts["short_p"]),
                   len(parts["short_n"]))),
        (msg_s, jnp.maximum(row_e - msg_s, 0)),          # short_message
    ]
    if not elide:
        segs.append((zero + (cbase + off["ts"]), zero + len(parts["ts"])))
    segs.append((zero + tbase, ts_len.astype(_I32)))
    if not elide:
        segs.append((zero + (cbase + off["tail"]),
                     zero + len(parts["tail"]) + len(suffix)))

    out_len = segs[0][1]
    for _, ln in segs[1:]:
        out_len = out_len + ln

    tier = (dec["ok"].astype(bool)
            & ~dec["has_high"].astype(bool)
            & ~jnp.any(es["bad_ctl"], axis=1)
            & (es["ne_total"] <= E_CAP)
            & (out_len <= OW))
    if not assemble:
        return tier
    acc, out_len2 = assemble_rows(segs, es["esc_row"], bank, ts_text,
                                  N, OW)
    return acc, out_len2, tier


def route_ok(encoder, merger) -> bool:
    """GELF output over line/nul/syslen framing; gelf_extra rides as
    constant segments when this layout can place the keys statically
    (gelf_extra_consts_3164 — note the rfc3164 fixed-key set differs
    from the rfc5424 one, so placeability differs too)."""
    from .device_common import gelf_route_ok
    from .encode_rfc3164_gelf_block import gelf_extra_consts_3164

    return gelf_route_ok(
        encoder, merger,
        lambda e: gelf_extra_consts_3164(e) is not None)


def fetch_encode(handle, packed, encoder, merger, route_state=None):
    """Device rfc3164→GELF encode for a submitted rfc3164 decode handle
    (out dict, batch_dev, lens_dev); returns (BlockResult | None,
    fetch_seconds) with None = use the host span path."""
    from .block_common import merger_suffix
    from .materialize_rfc3164 import _scalar_3164

    out, batch_dev, lens_dev = handle
    suffix, syslen = merger_suffix(merger)
    impl = best_scan_impl()
    extras = tuple((k, v) for k, v in getattr(encoder, "extra", ()))
    # constant elision (PR 4's rfc5424→GELF win, extended here): the
    # head, timestamp-label, and tail constants never cross PCIe — the
    # splice restores the exact host-tier bytes (same _bank both sides)
    espec = elide_spec(suffix, extras)

    def kernel(ts_text, ts_len, assemble):
        return _encode_kernel(batch_dev, lens_dev, dict(out), ts_text,
                              ts_len, suffix=suffix, impl=impl,
                              assemble=assemble, extras=extras,
                              elide=True)

    # zero-JIT boot: consult the AOT artifact store before compiling
    from .aot import encode_wrap

    kernel = encode_wrap("device_rfc3164", kernel, batch_dev, lens_dev,
                         dict(out), suffix, impl, extras)

    return fetch_encode_driver(
        kernel, out, batch_dev, lens_dev, packed, encoder, merger,
        route_state, suffix, syslen, scalar_fn=_scalar_3164,
        fallback_frac=FALLBACK_FRAC, decline_limit=DECLINE_LIMIT,
        cooldown=COOLDOWN, elide=espec)
