r"""Columnar DNS query-log decoder (dnstap-style TSV).

Scalar spec: flowgger_tpu/decoders/dns.py.  The grammar is fixed —
exactly six tab-separated fields, ``ts client qname qtype rcode
latency_us`` — so the whole decode is the fixed-grammar columnar plan
of arxiv 2411.12035 (and this repo's ltsv kernel): one tab-ordinal
cumsum segments the line, five packed-sum extractions recover the tab
positions, and every field becomes a span plus an elementwise
validation mask.  No lookarounds, no parity — this is the cheapest
kernel in the tree.

- ``ts`` validates as ``digits[.digits]`` on-device; the exact f64
  value materializes host-side (``float(span)``, dedup-cached);
- ``latency_us`` validates as 1..19 plain digits (19 digits always fit
  u64; longer-but-still-u64 values are oracle work);
- ``client``/``qname`` must be non-empty; ``qtype``/``rcode`` are free
  spans.

Rows failing any check — wrong field count, junk timestamp, oversized
latency — flag ``ok=False`` and re-run the scalar oracle, keeping
observable output byte-identical in every case.
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp

from .rfc5424 import (
    _scan_ordinals,
    best_extract_impl,
    best_scan_impl,
    extract_by_ord,
)

N_FIELDS = 6
MAX_LAT_DIGITS = 19  # 19 decimal digits always fit u64
_I32 = jnp.int32


def decode_dns(batch: jnp.ndarray, lens: jnp.ndarray,
               scan_impl: str = None,
               extract_impl: str = None) -> Dict[str, jnp.ndarray]:
    if scan_impl is None:
        scan_impl = best_scan_impl()
    if extract_impl is None:
        extract_impl = best_extract_impl()
    N, L = batch.shape
    lens = lens.astype(_I32)
    iota = jax.lax.broadcasted_iota(_I32, (N, L), 1)
    valid = iota < lens[:, None]
    bb = jnp.where(valid, batch, jnp.uint8(0))
    is_digit = (bb >= 48) & (bb <= 57)
    is_dot = bb == ord(".")

    is_tab = (bb == 9) & valid
    (tab_ord,) = _scan_ordinals([is_tab], scan_impl)
    n_tabs = jnp.max(jnp.where(is_tab, tab_ord, 0), axis=1).astype(_I32)
    ok = n_tabs == N_FIELDS - 1

    # the five separator positions; rows with a different tab count are
    # already off the tier, so fill values never reach a consumer
    tab_pos = extract_by_ord(is_tab, tab_ord, iota, N_FIELDS - 1, L,
                             extract_impl)
    tab_pos = jnp.minimum(tab_pos, lens[:, None])
    t0, t1, t2, t3, t4 = (tab_pos[:, k] for k in range(N_FIELDS - 1))

    # ---- ts: digits[.digits] in [0, t0) ---------------------------------
    in_ts = (iota < t0[:, None]) & valid
    dot_bad = is_dot & ((iota == 0) | (iota == (t0 - 1)[:, None]))
    ts_viol = in_ts & ((~is_digit & ~is_dot) | dot_bad)
    n_dots = jnp.sum((in_ts & is_dot).astype(_I32), axis=1)
    ts_ok = ~jnp.any(ts_viol, axis=1) & (n_dots <= 1) & (t0 >= 1)

    # ---- latency: 1..19 plain digits in [t4+1, len) ----------------------
    lat_start = t4 + 1
    in_lat = (iota >= lat_start[:, None]) & valid
    lat_len = lens - lat_start
    lat_ok = (~jnp.any(in_lat & ~is_digit, axis=1)
              & (lat_len >= 1) & (lat_len <= MAX_LAT_DIGITS))

    client_start, client_end = t0 + 1, t1
    qname_start, qname_end = t1 + 1, t2
    qtype_start, qtype_end = t2 + 1, t3
    rcode_start, rcode_end = t3 + 1, t4
    ok &= ts_ok & lat_ok
    ok &= (client_end > client_start) & (qname_end > qname_start)

    return {
        "ok": ok,
        "has_high": jnp.any((bb >= 128) & valid, axis=1),
        "ts_start": jnp.zeros_like(lens), "ts_end": t0,
        "client_start": client_start, "client_end": client_end,
        "qname_start": qname_start, "qname_end": qname_end,
        "qtype_start": qtype_start, "qtype_end": qtype_end,
        "rcode_start": rcode_start, "rcode_end": rcode_end,
        "lat_start": lat_start, "lat_end": lens,
    }


@functools.partial(jax.jit, static_argnames=("demand",))
def decode_dns_jit(batch, lens, demand=None):
    """``demand`` (static frozenset): keep only the channels the
    consumer reads so XLA dead-code-eliminates the rest."""
    out = decode_dns(batch, lens)
    if demand is not None:
        out = {k: v for k, v in out.items() if k in demand}
    return out


def decode_dns_submit(batch, lens, sharded=None):
    """Asynchronous dispatch (pair with decode_dns_fetch) — the dns leg
    of the block pipeline's double buffering."""
    import jax.numpy as jnp

    if sharded is not None:
        b, ln = sharded.put(batch, lens)
        return sharded.fn(b, ln), b, ln
    from .aot import decode_call

    b, ln = jnp.asarray(batch), jnp.asarray(lens)
    # zero-JIT boot: a loaded AOT artifact replaces the trace+compile
    out = decode_call("dns", (b, ln))
    if out is None:
        out = decode_dns_jit(b, ln)
    return out, b, ln


def decode_dns_fetch(handle):
    import numpy as np

    return {k: np.asarray(v) for k, v in handle[0].items()}
