"""Columnar RFC5424→passthrough encoding: each kernel-ok row's output
*is* a slice of the input (BOM-stripped, whitespace-rtrimmed full
message, passthrough_encoder.rs:22-46), so the whole batch's framed
bytes are one segment gather — no escaping, no scratch.

Per row: [syslen prefix digits +] ``chunk[full_start : trim_end]``
[+ suffix].  Rows outside the tier (kernel-flagged, oversized,
non-ASCII) take the scalar oracle via block_common.finish_block.
"""


from __future__ import annotations

# byte-identity contract (flowcheck FC03): the scalar counterpart
# this route must stay byte-identical to, and the differential
# test that enforces it
SCALAR_ORACLE = "flowgger_tpu.encoders.passthrough:PassthroughEncoder"
DIFF_TEST = "tests/test_encode_gelf_block.py::test_passthrough_block_matches_scalar"

from typing import Dict, Optional

import numpy as np

from ..mergers import Merger
from .assemble import (
    build_source,
    concat_segments,
    exclusive_cumsum,
    syslen_prefix_segments,
)
from .block_common import BlockResult, finish_block, merger_suffix


def encode_rfc5424_passthrough_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
) -> Optional[BlockResult]:
    """Returns None when the route can't apply (prepend-timestamp
    configured or an unknown merger type)."""
    if merger_suffix(merger) is None or encoder.header_time_format is not None:
        return None
    n = int(n_real)
    starts64 = np.asarray(starts[:n], dtype=np.int64)
    lens64 = np.asarray(orig_lens[:n], dtype=np.int64)

    def spans(ridx):
        a = starts64[ridx] + np.asarray(out["full_start"])[:n][ridx]
        return a, (starts64[ridx]
                   + np.asarray(out["trim_end"])[:n][ridx] - a)

    from .materialize import _scalar_line

    return _passthrough_block(chunk_bytes, starts64, lens64, out,
                              n, max_len, encoder, merger, spans,
                              _scalar_line)


def encode_rfc3164_passthrough_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
) -> Optional[BlockResult]:
    """rfc3164 variant: full_msg is the whole line, untrimmed
    (materialize_rfc3164.py Record construction)."""
    if merger_suffix(merger) is None or encoder.header_time_format is not None:
        return None
    n = int(n_real)
    starts64 = np.asarray(starts[:n], dtype=np.int64)
    lens64 = np.asarray(orig_lens[:n], dtype=np.int64)

    def spans(ridx):
        return starts64[ridx], lens64[ridx]

    from .materialize_rfc3164 import _scalar_3164

    return _passthrough_block(chunk_bytes, starts64, lens64, out,
                              n, max_len, encoder, merger, spans,
                              _scalar_3164)


def _passthrough_block(chunk_bytes, starts64, lens64, out, n, max_len,
                       encoder, merger, spans_fn, scalar_fn
                       ) -> Optional[BlockResult]:
    suffix, syslen = merger_suffix(merger)  # caller pre-checked
    ok = np.asarray(out["ok"][:n], dtype=bool)
    has_high = np.asarray(out["has_high"][:n], dtype=bool)
    cand = ok & (lens64 <= max_len) & ~has_high

    ridx = np.flatnonzero(cand)
    R = ridx.size
    final_buf = b""
    row_off = np.zeros(1, dtype=np.int64)
    prefix_lens_tier: Optional[np.ndarray] = None

    if R:
        chunk_arr = np.frombuffer(chunk_bytes, dtype=np.uint8)
        span_src, span_len = spans_fn(ridx)
        deco, offs = build_source(b"0123456789 ", suffix)
        src = np.concatenate([chunk_arr, deco])
        dbase = chunk_arr.size
        sfx_off = dbase + offs[1]

        if syslen:
            # framed value = body length + 1 for the trailing newline
            # (syslen_merger.rs:14-31); suffix IS that newline here
            body = span_len + len(suffix)
            psrc, plen, prefix_lens_tier = syslen_prefix_segments(
                body, dbase)
            seg_src = np.concatenate(
                [psrc, span_src[:, None],
                 np.full((R, 1), sfx_off, dtype=np.int64)], axis=1).ravel()
            seg_len = np.concatenate(
                [plen, span_len[:, None],
                 np.full((R, 1), len(suffix), dtype=np.int64)],
                axis=1).ravel()
            row_lens = span_len + len(suffix) + prefix_lens_tier
        else:
            nseg = 2
            seg_src = np.empty(R * nseg, dtype=np.int64)
            seg_len = np.empty(R * nseg, dtype=np.int64)
            seg_src[0::nseg] = span_src
            seg_len[0::nseg] = span_len
            seg_src[1::nseg] = sfx_off
            seg_len[1::nseg] = len(suffix)
            row_lens = span_len + len(suffix)

        final_buf = concat_segments(src, seg_src, seg_len).tobytes()
        row_off = exclusive_cumsum(row_lens)

    return finish_block(chunk_bytes, starts64, lens64, n, cand, ridx,
                        final_buf, row_off, prefix_lens_tier, suffix,
                        syslen, merger, encoder, scalar_fn=scalar_fn)
