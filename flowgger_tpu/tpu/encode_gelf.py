"""Columnar-to-GELF encode: span tables → output bytes with no Record
objects on the fast path.

The measured host bottleneck of the batched pipeline is Python object
construction (Record/SDValue materialization ≈ 23µs/row, then the
per-record encoder walks those objects again).  For the flagship
``rfc5424_tpu → gelf`` route this module serializes each kernel-ok row
*directly from the RFC5424 span tables* — a small dict of pre-formatted
JSON fragments (C-accelerated string escaping), sorted keys, one join —
and only falls back to materialize+GelfEncoder for flagged rows.

Output bytes are identical to GelfEncoder over the materialized Record
(differential-tested in tests/test_encode_gelf_fast.py): same sorted-key
order, same escaping, same last-wins collision semantics via the dict.
"""

from __future__ import annotations

from json.encoder import encode_basestring as _quote
from typing import Dict, List

import numpy as np

from ..encoders import EncodeError
from ..encoders.gelf import GelfEncoder
from ..utils.rustfmt import json_f64
from ..decoders.rfc5424 import _unescape_sd_value
from .materialize import LineResult, _scalar_line, compute_ts

class EncodedResult:
    """Encoded bytes or a per-line error (same contract as LineResult)."""

    __slots__ = ("encoded", "error", "line")

    def __init__(self, encoded, error, line):
        self.encoded = encoded
        self.error = error
        self.line = line


def encode_rfc5424_gelf(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder: GelfEncoder,
) -> List[EncodedResult]:
    ts_arr = compute_ts(out).tolist()
    o = {k: np.asarray(v).tolist() for k, v in out.items()}
    ok = o["ok"]
    extra = encoder.extra
    results: List[EncodedResult] = []
    starts_l = starts.tolist() if hasattr(starts, "tolist") else starts
    lens_l = orig_lens.tolist() if hasattr(orig_lens, "tolist") else orig_lens

    sd_count = o["sd_count"]
    pair_count = o["pair_count"]
    sid_start, sid_end = o["sid_start"], o["sid_end"]
    name_start, name_end = o["name_start"], o["name_end"]
    val_start, val_end = o["val_start"], o["val_end"]
    val_has_esc = o["val_has_esc"]
    host_s, host_e = o["host_start"], o["host_end"]
    app_s, app_e = o["app_start"], o["app_end"]
    proc_s, proc_e = o["proc_start"], o["proc_end"]
    msg_s = o["msg_start"]
    full_s = o["full_start"]
    sev = o["severity"]

    for n in range(n_real):
        s = starts_l[n]
        ln = lens_l[n]
        raw = chunk_bytes[s:s + ln]
        try:
            line = raw.decode("utf-8")
        except UnicodeDecodeError:
            results.append(EncodedResult(None, "__utf8__", ""))
            continue
        if not ok[n] or ln > max_len or len(line) != ln:
            # flagged, oversized, or multi-byte rows: Record path
            from ..utils.metrics import registry as _m

            _m.inc("fallback_rows")
            res = _scalar_line(line)
            if res.record is None:
                results.append(EncodedResult(None, res.error, line))
                continue
            try:
                results.append(EncodedResult(encoder.encode(res.record), None, line))
            except EncodeError as e:
                results.append(EncodedResult(None, str(e), line))
            continue

        # fixed fields (gelf_encoder.rs field mapping); msgid is decoded
        # but GELF has no field for it
        host = line[host_s[n]:host_e[n]]
        msg = line[msg_s[n]:].strip()
        nsd = sd_count[n]
        if not extra:
            # common case: fixed keys are emitted in their known sorted
            # order; SD keys all start with '_' (sorts before them) and
            # never collide with fixed names
            parts = []
            if nsd:
                sd_frags: Dict[str, str] = {}
                for j in range(pair_count[n]):
                    value = line[val_start[n][j]:val_end[n][j]]
                    if val_has_esc[n][j]:
                        value = _unescape_sd_value(value)
                    # SD names exclude '"' and '\' by grammar: no escaping
                    sd_frags["_" + line[name_start[n][j]:name_end[n][j]]] = value
                for name in sorted(sd_frags):
                    parts.append('"%s":%s' % (name, _quote(sd_frags[name])))
            parts.append('"application_name":' + _quote(line[app_s[n]:app_e[n]]))
            parts.append('"full_message":' + _quote(line[full_s[n]:].rstrip()))
            parts.append('"host":' + (_quote(host) if host else '"unknown"'))
            parts.append('"level":%d' % sev[n])
            parts.append('"process_id":' + _quote(line[proc_s[n]:proc_e[n]]))
            if nsd:
                parts.append('"sd_id":' + _quote(
                    line[sid_start[n][nsd - 1]:sid_end[n][nsd - 1]]))
            parts.append('"short_message":' + (_quote(msg) if msg else '"-"'))
            parts.append('"timestamp":' + json_f64(ts_arr[n]))
            parts.append('"version":"1.1"')
            results.append(EncodedResult(
                ("{" + ",".join(parts) + "}").encode("utf-8"), None, line))
            continue
        frags: Dict[str, str] = {"version": '"1.1"'}
        frags["host"] = _quote(host) if host else '"unknown"'
        frags["short_message"] = _quote(msg) if msg else '"-"'
        frags["timestamp"] = json_f64(ts_arr[n])
        frags["level"] = str(sev[n])
        frags["full_message"] = _quote(line[full_s[n]:].rstrip())
        frags["application_name"] = _quote(line[app_s[n]:app_e[n]])
        frags["process_id"] = _quote(line[proc_s[n]:proc_e[n]])
        if nsd:
            frags["sd_id"] = _quote(line[sid_start[n][nsd - 1]:sid_end[n][nsd - 1]])
            for j in range(pair_count[n]):
                value = line[val_start[n][j]:val_end[n][j]]
                if val_has_esc[n][j]:
                    value = _unescape_sd_value(value)
                frags["_" + line[name_start[n][j]:name_end[n][j]]] = _quote(value)
        for k, v in extra:
            frags[k] = _quote(v)
        body = ",".join(f"{_quote(k)}:{frags[k]}" for k in sorted(frags))
        results.append(EncodedResult(("{" + body + "}").encode("utf-8"), None, line))
    return results
